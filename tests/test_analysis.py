"""The static-analysis gate (oni_ml_tpu/analysis): engine mechanics
(suppressions, baseline, parse errors), fixture-backed positives and
negatives for every rule in the catalog, the graftlint CLI (--json
golden, rule selection, --update-schema round trip), and the self-run
that holds the LIVE repo clean against the committed baseline.

Everything runs on synthetic trees under tmp_path except the self-run
tests — no jax, no numpy, no device.
"""

import json
import os

import pytest

from oni_ml_tpu.analysis import run_analysis
from oni_ml_tpu.analysis import cli as lint_cli
from oni_ml_tpu.analysis import schema as journal_schema
from oni_ml_tpu.analysis.engine import ParsedModule, parse_modules
from oni_ml_tpu.analysis.rules import (
    HarvestCoverageRule,
    HiddenHostSyncRule,
    JournalDocsRule,
    JournalSchemaRule,
    LockDisciplineRule,
    MonotonicClockRule,
    QuantileRule,
    RetraceHazardRule,
    TunedConstantRule,
    default_rules,
)


def make_tree(tmp_path, files: dict) -> str:
    """Materialize {relpath: source} as a scannable fixture root."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return str(tmp_path)


def run_on(tmp_path, files, rules=None, baseline=()):
    return run_analysis(root=make_tree(tmp_path, files), rules=rules,
                        baseline=list(baseline))


def rule_lines(report, rule_id):
    return [(f.path, f.line) for f in report.findings
            if f.rule == rule_id]


# ---------------------------------------------------------------------------
# engine: suppressions, baseline, parse errors
# ---------------------------------------------------------------------------


def test_suppression_trailing_and_own_line(tmp_path):
    src = (
        "import time\n"
        "a = time.time()  # lint: ok(monotonic-clock, epoch stamp)\n"
        "# lint: ok(monotonic-clock, stamp on the next line)\n"
        "b = time.time()\n"
        "c = time.time()\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/x.py": src},
               rules=[MonotonicClockRule()])
    assert r.suppressed == 2
    assert rule_lines(r, "monotonic-clock") == [("oni_ml_tpu/x.py", 5)]


def test_wildcard_suppression_and_wrong_rule_id(tmp_path):
    src = (
        "import time\n"
        "a = time.time()  # lint: ok(*, anything goes here)\n"
        "b = time.time()  # lint: ok(quantile, wrong rule id)\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/x.py": src},
               rules=[MonotonicClockRule()])
    # `*` silences any rule on its line; a suppression naming a
    # DIFFERENT rule does not.
    assert r.suppressed == 1
    assert rule_lines(r, "monotonic-clock") == [("oni_ml_tpu/x.py", 3)]


def test_suppression_marker_in_string_literal_is_inert(tmp_path):
    # Only real COMMENT tokens suppress: the marker inside a string
    # (a hint message, a doc example) must not mask findings on its
    # line or the next.
    src = (
        "import time\n"
        'msg = "see # lint: ok(monotonic-clock, example)"; '
        "t0 = time.time()\n"
        'hint = "# lint: ok(monotonic-clock, own-line-looking string)"\n'
        "t1 = time.time()\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/x.py": src},
               rules=[MonotonicClockRule()])
    assert r.suppressed == 0
    assert rule_lines(r, "monotonic-clock") == \
        [("oni_ml_tpu/x.py", 2), ("oni_ml_tpu/x.py", 4)]


def test_reasonless_suppression_is_itself_a_finding(tmp_path):
    src = (
        "import time\n"
        "a = time.time()  # lint: ok(monotonic-clock)\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/x.py": src},
               rules=[MonotonicClockRule()])
    rules_hit = {f.rule for f in r.findings}
    # The reasonless suppression does NOT suppress, and is reported.
    assert rules_hit == {"monotonic-clock", "suppression-format"}


def test_baseline_absorbs_counted_findings_and_flags_stale(tmp_path):
    src = "import time\na = time.time()\nb = time.time()\n"
    baseline = [
        {"rule": "monotonic-clock", "path": "oni_ml_tpu/x.py", "count": 1},
        {"rule": "monotonic-clock", "path": "oni_ml_tpu/gone.py",
         "count": 3},
    ]
    r = run_on(tmp_path, {"oni_ml_tpu/x.py": src},
               rules=[MonotonicClockRule()], baseline=baseline)
    assert r.baselined == 1
    # One of the two real findings survives the count=1 budget...
    assert len(rule_lines(r, "monotonic-clock")) == 1
    # ...and the entry matching nothing is reported stale.
    assert rule_lines(r, "stale-baseline") == [("oni_ml_tpu/gone.py", 0)]


def test_unparseable_file_fails_the_report(tmp_path):
    r = run_on(tmp_path, {"oni_ml_tpu/bad.py": "def broken(:\n"},
               rules=[MonotonicClockRule()])
    assert not r.ok
    assert r.parse_errors and r.parse_errors[0][0] == "oni_ml_tpu/bad.py"


def test_null_byte_source_is_a_parse_error_not_a_crash(tmp_path):
    # ast.parse raises ValueError (not SyntaxError) on null bytes — a
    # corrupted file must surface as a parse error, not a traceback
    # out of the gate.
    r = run_on(tmp_path, {"oni_ml_tpu/__init__.py": "",
                          "oni_ml_tpu/bad.py": "x = 1\x00\n"},
               rules=[MonotonicClockRule()])
    assert not r.ok
    assert any(p == "oni_ml_tpu/bad.py" for p, _ in r.parse_errors)


def test_empty_scan_root_fails_the_report(tmp_path):
    # A gate that scans nothing must not report clean — a wrong --root
    # or cwd would otherwise pass CI while linting zero files.
    r = run_analysis(root=str(tmp_path / "nonexistent"))
    assert not r.ok
    assert r.files_scanned == 0
    assert any("no oni_ml_tpu/ package files" in msg
               for _, msg in r.parse_errors)


def test_scan_covers_tools_and_bench(tmp_path):
    files = {
        "tools/t.py": "import time\nx = time.time()\n",
        "bench.py": "import time\ny = time.time()\n",
        "oni_ml_tpu/__init__.py": "",
    }
    r = run_on(tmp_path, files, rules=[MonotonicClockRule()])
    assert {p for p, _ in rule_lines(r, "monotonic-clock")} == \
        {"tools/t.py", "bench.py"}


# ---------------------------------------------------------------------------
# migrated grep-lints, now AST-accurate
# ---------------------------------------------------------------------------


def test_monotonic_clock_ignores_docstring_mentions(tmp_path):
    src = '"""Calls time.time() in prose only."""\nX = "time.time()"\n'
    r = run_on(tmp_path, {"oni_ml_tpu/x.py": src},
               rules=[MonotonicClockRule()])
    assert r.ok  # the grep version flagged both of these


def test_tuned_constant_literal_placement(tmp_path):
    files = {
        "oni_ml_tpu/config.py": "device_chunk = 65536\n",
        "oni_ml_tpu/plans/seeds.py": "DEFAULT_CHUNK = 65536\n",
        "oni_ml_tpu/consumer.py": (
            "device_chunk = 65536\n"
            "max_batch: int = 256\n"
            "pre_workers = compute()\n"      # non-literal: fine
            "unrelated = 3\n"
        ),
    }
    r = run_on(tmp_path, files, rules=[TunedConstantRule()])
    assert rule_lines(r, "tuned-constant") == [
        ("oni_ml_tpu/consumer.py", 1), ("oni_ml_tpu/consumer.py", 2),
    ]


def test_quantile_math_only_in_telemetry(tmp_path):
    files = {
        "oni_ml_tpu/telemetry/spans.py": "q = np.percentile(a, 99)\n",
        "oni_ml_tpu/scoring/x.py": "q = np.percentile(a, 99)\n",
        "tools/probe.py": "q = np.quantile(a, 0.5)\n",
    }
    r = run_on(tmp_path, files, rules=[QuantileRule()])
    assert {p for p, _ in rule_lines(r, "quantile")} == \
        {"oni_ml_tpu/scoring/x.py", "tools/probe.py"}


REGISTRY_SRC = (
    "HARVEST_COVERAGE = {\n"
    '    "models/covered.py": "harvested at dispatch",\n'
    '    "models/ghost.py": "stale: file was deleted",\n'
    '    "models/nojit.py": "stale: jit site was removed",\n'
    "}\n"
)
JIT_SRC = "import jax\nf = jax.jit(lambda x: x)\n"


def test_harvest_coverage_drift_both_ways(tmp_path):
    files = {
        "oni_ml_tpu/telemetry/roofline.py": REGISTRY_SRC,
        "oni_ml_tpu/models/covered.py": JIT_SRC,
        "oni_ml_tpu/models/uncovered.py": JIT_SRC,
        "oni_ml_tpu/models/nojit.py": "x = 1\n",
        # A docstring mention must NOT count as an entry point (the
        # false positive the grep-lint had).
        "oni_ml_tpu/models/prose.py": '"""uses jax.jit inside."""\n',
    }
    r = run_on(tmp_path, files, rules=[HarvestCoverageRule()])
    got = rule_lines(r, "harvest-coverage")
    # The unregistered jit file fails; the two stale registry entries
    # fail; covered.py and prose.py are fine.
    assert ("oni_ml_tpu/models/uncovered.py", 2) in got
    assert ("oni_ml_tpu/telemetry/roofline.py", 3) in got   # ghost
    assert ("oni_ml_tpu/telemetry/roofline.py", 4) in got   # nojit
    assert len(got) == 3


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------


def test_retrace_hazard_decorator_forms(tmp_path):
    src = (
        "import jax\n"
        "from functools import partial\n"
        "\n"
        "@jax.jit\n"
        "def bad(x, flag):\n"
        "    if flag:\n"
        "        return x\n"
        "    return -x\n"
        "\n"
        '@partial(jax.jit, static_argnames=("flag",))\n'
        "def good(x, flag):\n"
        "    if flag:\n"
        "        return x\n"
        "    return -x\n"
        "\n"
        "@partial(jax.jit, static_argnums=(1,))\n"
        "def good2(x, n):\n"
        "    while n > 0:\n"
        "        n = n - 1\n"
        "    return x\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/m.py": src},
               rules=[RetraceHazardRule()])
    assert rule_lines(r, "retrace-hazard") == [("oni_ml_tpu/m.py", 6)]


def test_retrace_hazard_call_form_and_range(tmp_path):
    src = (
        "import jax\n"
        "def f(x, n):\n"
        "    for _ in range(n):\n"
        "        x = x + 1\n"
        "    return x\n"
        "jf_bad = jax.jit(f)\n"
        'jf_good = jax.jit(f, static_argnames=("n",))\n'
    )
    r = run_on(tmp_path, {"oni_ml_tpu/m.py": src},
               rules=[RetraceHazardRule()])
    # Flagged once for the un-static wrapping only (sites dedup per
    # (target, statics); the explicitly-static one is clean).
    assert rule_lines(r, "retrace-hazard") == [("oni_ml_tpu/m.py", 3)]


def test_retrace_hazard_static_site_does_not_shadow_bare_site(tmp_path):
    # Order-reversed twin of the test above: a properly-static jit
    # site FIRST must not absorb the bare jax.jit(f) after it.
    src = (
        "import jax\n"
        "def f(x, n):\n"
        "    for _ in range(n):\n"
        "        x = x + 1\n"
        "    return x\n"
        'jf_good = jax.jit(f, static_argnames=("n",))\n'
        "jf_bad = jax.jit(f)\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/m.py": src},
               rules=[RetraceHazardRule()])
    assert rule_lines(r, "retrace-hazard") == [("oni_ml_tpu/m.py", 3)]


def test_retrace_hazard_method_does_not_shadow_module_def(tmp_path):
    # jax.jit(step) jits the MODULE function; a same-named class
    # method with hazardous control flow must not be what gets
    # analyzed (was a false positive).
    src = (
        "import jax\n"
        "def step(x, n):\n"
        "    return x + 1\n"
        "class Driver:\n"
        "    def step(self, x, n):\n"
        "        if n > 2:\n"
        "            return x\n"
        "        return -x\n"
        "jf = jax.jit(step)\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/m.py": src},
               rules=[RetraceHazardRule()])
    assert r.ok


def test_retrace_hazard_nested_callable_param_is_own_binding(tmp_path):
    # A nested lambda/def parameter shadowing a jit parameter is the
    # nested callable's OWN (host-side) binding — not the traced
    # argument (was a false positive).
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, n):\n"
        "    g = lambda n: 1 if n else 0\n"
        "    def h(n):\n"
        "        while n:\n"
        "            n = n - 1\n"
        "        return n\n"
        "    return x + g(0) + h(0)\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/m.py": src},
               rules=[RetraceHazardRule()])
    assert r.ok


def test_retrace_hazard_trace_stable_tests_ignored(tmp_path):
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, y):\n"
        "    if x.shape[0] == 1:\n"       # shape: trace-stable
        "        return x\n"
        "    if isinstance(y, tuple):\n"  # behind a call: out of scope
        "        return x\n"
        "    return x + 1\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/m.py": src},
               rules=[RetraceHazardRule()])
    assert r.ok


def test_retrace_hazard_partial_bound_args_are_static(tmp_path):
    src = (
        "import jax\n"
        "from functools import partial\n"
        "def f(n, x):\n"
        "    if n > 2:\n"
        "        return x\n"
        "    return -x\n"
        "jf = jax.jit(partial(f, 4))\n"   # n positionally bound: static
    )
    r = run_on(tmp_path, {"oni_ml_tpu/m.py": src},
               rules=[RetraceHazardRule()])
    assert r.ok


# ---------------------------------------------------------------------------
# hidden-host-sync
# ---------------------------------------------------------------------------

HOT = "oni_ml_tpu/models/fused.py"


def test_hidden_host_sync_in_hot_loop(tmp_path):
    src = (
        "import numpy as np\n"
        "def drive(xs):\n"
        "    out = []\n"
        "    for x in xs:\n"
        "        out.append(float(x))\n"
        "    return out\n"
    )
    r = run_on(tmp_path, {HOT: src}, rules=[HiddenHostSyncRule()])
    assert rule_lines(r, "hidden-host-sync") == [(HOT, 5)]


def test_hidden_host_sync_span_wrapped_is_deliberate(tmp_path):
    src = (
        "def drive(xs):\n"
        "    total = 0.0\n"
        "    for x in xs:\n"
        '        with maybe_span("em.host_sync"):\n'
        "            total += float(x)\n"
        "    return total\n"
    )
    r = run_on(tmp_path, {HOT: src}, rules=[HiddenHostSyncRule()])
    assert r.ok


def test_hidden_host_sync_scope(tmp_path):
    src = (
        "def f(x, xs):\n"
        "    a = float(x)\n"              # not in a loop: fine
        "    return a\n"
    )
    cold = (
        "def f(xs):\n"
        "    return [float(x) for x in xs]\n"
    )
    r = run_on(tmp_path, {
        HOT: src,
        "oni_ml_tpu/io/cold.py": cold,    # not a hot module: fine
    }, rules=[HiddenHostSyncRule()])
    assert r.ok


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


def test_lock_discipline_mixed_guarding(tmp_path):
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "    def sneak(self):\n"
        "        self._n = 0\n"           # guarded elsewhere: race
    )
    r = run_on(tmp_path, {"oni_ml_tpu/c.py": src},
               rules=[LockDisciplineRule()])
    assert rule_lines(r, "lock-discipline") == [("oni_ml_tpu/c.py", 10)]


def test_lock_discipline_locked_helper_and_init_exempt(tmp_path):
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"           # __init__: pre-publication
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self._bump_locked()\n"
        "    def _bump_locked(self):\n"
        "        self._n += 1\n"          # name marks it lock-held
        "    def reset(self):\n"
        "        with self._lock:\n"
        "            self._do_reset()\n"
        "    def _do_reset(self):\n"
        '        """Caller holds self._lock."""\n'
        "        self._n = 0\n"           # docstring marks it
    )
    r = run_on(tmp_path, {"oni_ml_tpu/c.py": src},
               rules=[LockDisciplineRule()])
    assert r.ok


def test_lock_discipline_threaded_unguarded_counter(tmp_path):
    # The BatchScorer shape this rule caught live: a Condition guards
    # the queue, a worker thread bumps counters with no lock, and other
    # methods read them.
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "        self._seq = 0\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "    def _run(self):\n"
        "        self._seq += 1\n"        # cross-thread, no guard
        "    def seq(self):\n"
        "        return self._seq\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/s.py": src},
               rules=[LockDisciplineRule()])
    assert rule_lines(r, "lock-discipline") == [("oni_ml_tpu/s.py", 8)]


def test_lock_discipline_single_threaded_class_quiet(tmp_path):
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def bump(self):\n"
        "        self._n += 1\n"          # never guarded anywhere,
        "    def read(self):\n"           # no threads started: quiet
        "        return self._n\n"
    )
    r = run_on(tmp_path, {"oni_ml_tpu/c.py": src},
               rules=[LockDisciplineRule()])
    assert r.ok


# ---------------------------------------------------------------------------
# journal-schema / journal-docs
# ---------------------------------------------------------------------------

EMITTER = (
    "def emit(j, info):\n"
    '    j.append({"kind": "em_ll", "iter": 1, "ll": 0.5})\n'
    '    j.annotation("probe", ok=True)\n'
)


def _schema_of(tmp_path, files):
    root = make_tree(tmp_path, files)
    modules, errors = parse_modules(root)
    assert not errors
    return journal_schema.extract_schema(modules)


def test_schema_extraction_dict_and_annotation(tmp_path):
    ext = _schema_of(tmp_path, {"oni_ml_tpu/e.py": EMITTER})
    assert ext == {
        "em_ll": {"fields": ["iter", "ll"], "open": False},
        "probe": {"fields": ["ok"], "open": False},
    }


def test_schema_extraction_follows_local_record_growth(tmp_path):
    src = (
        "def emit(j, info):\n"
        '    rec = {"kind": "stage", "stage": "pre"}\n'
        '    rec["wall_s"] = 1.0\n'
        "    rec.update(info)\n"
        "    j.append(rec)\n"
    )
    ext = _schema_of(tmp_path, {"oni_ml_tpu/e.py": src})
    assert ext["stage"] == {"fields": ["stage", "wall_s"], "open": True}


def test_journal_schema_drift_directions(tmp_path):
    committed = {
        "em_ll": {"fields": ["conv", "iter", "ll"], "open": False},
        "probe": {"fields": ["ok"], "open": False},
        "retired": {"fields": [], "open": False},
    }
    r = run_on(tmp_path, {"oni_ml_tpu/e.py": EMITTER},
               rules=[JournalSchemaRule(schema=committed)])
    msgs = " | ".join(f.message for f in r.findings)
    # Dropped field (conv), and a kind no longer emitted (retired).
    assert "dropped field(s) ['conv']" in msgs
    assert "'retired' is no longer emitted" in msgs
    assert len(r.findings) == 2


def test_journal_schema_new_kind_and_new_field_fail(tmp_path):
    committed = {"em_ll": {"fields": ["iter"], "open": False}}
    r = run_on(tmp_path, {"oni_ml_tpu/e.py": EMITTER},
               rules=[JournalSchemaRule(schema=committed)])
    msgs = " | ".join(f.message for f in r.findings)
    assert "new record kind 'probe'" in msgs
    assert "gained undeclared field(s) ['ll']" in msgs


def test_journal_schema_missing_committed_file(tmp_path):
    r = run_on(tmp_path, {"oni_ml_tpu/e.py": EMITTER},
               rules=[JournalSchemaRule()])
    assert [f.rule for f in r.findings] == ["journal-schema"]
    assert "missing or empty" in r.findings[0].message


def test_journal_docs_requires_backticked_kind(tmp_path):
    files = {
        "oni_ml_tpu/e.py": EMITTER,
        "docs/observability.md": "| `em_ll` | iteration likelihood |\n",
    }
    r = run_on(tmp_path, files, rules=[JournalDocsRule()])
    assert [f.rule for f in r.findings] == ["journal-docs"]
    assert "'probe'" in r.findings[0].message
    # And with both kinds documented: clean.
    files["docs/observability.md"] += "| `probe` | liveness marker |\n"
    r2 = run_on(tmp_path / "b", files, rules=[JournalDocsRule()])
    assert r2.ok


# ---------------------------------------------------------------------------
# the graftlint CLI
# ---------------------------------------------------------------------------

DIRTY = {"oni_ml_tpu/x.py": "import time\nt0 = time.time()\n"}


def test_cli_json_golden(tmp_path, capsys):
    root = make_tree(tmp_path, DIRTY)
    rc = lint_cli.main(["--root", root, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["ok"] is False
    assert out["files_scanned"] == 1
    assert out["counts"] == {"monotonic-clock": 1}
    assert out["findings"] == [{
        "rule": "monotonic-clock",
        "path": "oni_ml_tpu/x.py",
        "line": 2,
        "message": ("bare time.time() — wall clocks step under NTP; "
                    "time intervals with a monotonic clock"),
        "hint": ("use time.monotonic_ns()/time.perf_counter() for "
                 "intervals; a true wall-clock timestamp gets "
                 "`# lint: ok(monotonic-clock, <why>)`"),
    }]


def test_cli_human_output_and_exit_codes(tmp_path, capsys):
    root = make_tree(tmp_path, DIRTY)
    assert lint_cli.main(["--root", root]) == 1
    out = capsys.readouterr().out
    assert "oni_ml_tpu/x.py:2: [monotonic-clock]" in out
    assert "(fix:" in out
    clean = make_tree(tmp_path / "clean",
                      {"oni_ml_tpu/y.py": "x = 1\n"})
    assert lint_cli.main(["--root", clean]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_rule_selection(tmp_path, capsys):
    root = make_tree(tmp_path, DIRTY)
    # Selecting a rule that cannot fire here: clean.
    assert lint_cli.main(["--root", root, "--rule", "quantile"]) == 0
    capsys.readouterr()
    with pytest.raises(SystemExit):
        lint_cli.main(["--root", root, "--rule", "not-a-rule"])


def test_cli_list_rules_names_whole_catalog(capsys):
    assert lint_cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in default_rules():
        assert rule.id in out


def test_cli_update_schema_round_trip(tmp_path, capsys):
    """--update-schema writes the contract INTO the scanned root; the
    very next lint run is clean, and deleting a field from the emit
    site afterwards fails the journal-schema rule — the acceptance
    drill for schema drift."""
    files = dict(DIRTY)
    files["oni_ml_tpu/e.py"] = EMITTER
    files["docs/observability.md"] = "`em_ll` and `probe`\n"
    root = make_tree(tmp_path, files)
    assert lint_cli.main(["--root", root, "--update-schema"]) == 0
    schema_path = os.path.join(
        root, "oni_ml_tpu/analysis/schema/journal_schema.json")
    assert os.path.exists(schema_path)
    capsys.readouterr()
    assert lint_cli.main(
        ["--root", root, "--rule", "journal-schema"]) == 0
    capsys.readouterr()
    # Drop a field from the call site: drift must fail the run.
    (tmp_path / "oni_ml_tpu/e.py").write_text(
        'def emit(j, info):\n    j.append({"kind": "em_ll", "iter": 1})\n'
        '    j.annotation("probe", ok=True)\n'
    )
    assert lint_cli.main(
        ["--root", root, "--rule", "journal-schema"]) == 1
    assert "dropped field(s) ['ll']" in capsys.readouterr().out


def test_ml_ops_lint_routes_to_graftlint(capsys):
    from oni_ml_tpu.runner.ml_ops import main as ml_ops_main

    assert ml_ops_main(["lint", "--list-rules"]) == 0
    assert "retrace-hazard" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# the live repo
# ---------------------------------------------------------------------------


def test_live_repo_is_clean_against_committed_baseline():
    """The self-run gate: every rule over the real tree (package,
    tools/, bench.py), inline suppressions honored, committed baseline
    applied.  A new finding — retrace hazard, unlocked write, schema or
    coverage drift, a stale baseline entry — fails HERE."""
    report = run_analysis()
    assert report.ok, "\n" + "\n".join(
        f.format() for f in report.findings
    ) + "\n".join(f"{p}: {m}" for p, m in report.parse_errors)


def test_committed_schema_matches_extraction_exactly():
    """`journal_schema.json` must be regeneration-stable: if extraction
    and the committed file ever disagree the journal-schema rule fails
    the self-run above; this pins the sharper property that the file
    was written BY the extractor (field lists sorted, open flags
    bool)."""
    from oni_ml_tpu.analysis.engine import repo_root

    modules, errors = parse_modules(repo_root())
    assert not errors
    extracted = journal_schema.extract_schema(modules)
    committed = journal_schema.load_schema()
    assert extracted == committed


def test_live_baseline_is_empty():
    """The acceptance bar was an empty baseline (every true positive
    fixed or suppressed-with-reason at adoption).  If a future change
    NEEDS a baseline entry this test forces that to be a deliberate,
    reviewed edit here."""
    from oni_ml_tpu.analysis.engine import load_baseline

    assert load_baseline() == []
