"""lda-c-compatible CLI: settings.txt parsing + the reference argument
vector producing the final.* / likelihood.dat contract."""

import numpy as np
import pytest

from oni_ml_tpu.io import Corpus, formats
from oni_ml_tpu.runner import lda_cli

import reference_lda as ref
from test_lda import corpus_from_docs


def test_read_settings(tmp_path):
    p = tmp_path / "settings.txt"
    p.write_text(
        "var max iter 30\n"
        "var convergence 1e-7\n"
        "em max iter 12\n"
        "em convergence 1e-5\n"
        "alpha estimate\n"
    )
    s = lda_cli.read_settings(str(p))
    assert s == {
        "var_max_iters": 30,
        "var_tol": 1e-7,
        "em_max_iters": 12,
        "em_tol": 1e-5,
        "estimate_alpha": True,
    }


def test_read_settings_alpha_fixed_and_unknown_keys(tmp_path):
    p = tmp_path / "settings.txt"
    p.write_text("alpha fixed\nsome future knob 3\nem max iter 5\n")
    s = lda_cli.read_settings(str(p))
    assert s == {"estimate_alpha": False, "em_max_iters": 5}


def test_read_settings_unbounded_var_iter_sentinel(tmp_path):
    # lda-c's inf-settings.txt uses -1 for "iterate until converged".
    p = tmp_path / "settings.txt"
    p.write_text("var max iter -1\n")
    s = lda_cli.read_settings(str(p))
    assert s["var_max_iters"] >= 10_000


def test_mesh_from_spec():
    from oni_ml_tpu.parallel import mesh_from_spec

    mesh, vocab_sharded = mesh_from_spec("4,2")
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    assert vocab_sharded
    mesh, vocab_sharded = mesh_from_spec("8,1")
    assert not vocab_sharded
    for bad in ("8", "a,b", "1,2,3"):
        with pytest.raises(ValueError, match="DATA,MODEL"):
            mesh_from_spec(bad)


def test_cli_reference_argv_end_to_end(tmp_path):
    docs, _ = ref.make_synthetic_corpus(
        num_docs=20, num_terms=25, num_topics=3, seed=1
    )
    corpus = corpus_from_docs(docs, 25)
    day = tmp_path / "day"
    day.mkdir()
    corpus.save(str(day))
    settings = tmp_path / "settings.txt"
    settings.write_text(
        "var max iter 20\nvar convergence 1e-6\n"
        "em max iter 8\nem convergence 0\nalpha estimate\n"
    )

    rc = lda_cli.main([
        "est", "2.5", "4", str(settings), "20",
        str(day / "model.dat"), "random", str(day),
    ])
    assert rc == 0

    beta = formats.read_beta(str(day / "final.beta"))
    gamma = formats.read_gamma(str(day / "final.gamma"))
    assert beta.shape == (4, 25)
    assert gamma.shape == (corpus.num_docs, 4)
    np.testing.assert_allclose(np.exp(beta).sum(-1), np.ones(4), rtol=1e-4)
    lls = [
        float(line.split("\t")[0])
        for line in (day / "likelihood.dat").read_text().splitlines()
    ]
    assert len(lls) == 8
    assert lls[-1] > lls[0]  # training improved the likelihood


def test_cli_rejects_bad_argv(capsys):
    assert lda_cli.main(["est", "2.5"]) == 2
    assert lda_cli.main([
        "est", "2.5", "4", "s.txt", "20", "m.dat", "seeded", "out",
    ]) == 2


def test_help_flag_exits_zero(capsys):
    from oni_ml_tpu.runner.lda_cli import main as lda_main
    from oni_ml_tpu.features.qtiles import main as qtiles_main

    assert lda_main(["--help"]) == 0
    assert "usage" in capsys.readouterr().out
    assert qtiles_main(["-h"]) == 0
    assert "usage" in capsys.readouterr().out
    # empty argv stays the error path
    assert lda_main([]) == 2
    assert qtiles_main([]) == 2
