"""tools/config3_30day.py — the config-3 evidence tool — at tiny scale.

The r05 realistic-cardinality capture
(docs/bench_captures/r05_config3_realistic.json: 524,937 docs / 50,169
vocab / K=50 to convergence) was produced by this tool; these tests
keep its mechanics honest without the 16 GB / hour-scale run:

- the power-law IP population actually scales document cardinality
  with the configured populations (VERDICT r4 item 3's core point —
  the reference maps every active IP to a document,
  flow_pre_lda.scala:366-380);
- the --train stage records convergence and writes likelihood.dat
  beside --out;
- the emitted JSON record carries the contract fields downstream
  readers (captures README, evidence index) cite.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "config3_30day.py")


@pytest.fixture(scope="module")
def tool_record(tmp_path_factory):
    """One tiny end-to-end run shared by the assertions below."""
    out = tmp_path_factory.mktemp("config3") / "rec.json"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never touch the chip
    proc = subprocess.run(
        [sys.executable, TOOL,
         "--events-per-day", "6000", "--days", "2",
         "--n-src", "3000", "--n-dst", "1500",
         "--ip-zipf-a", "1.2", "--n-svc-ports", "8",
         "--train", "--num-topics", "4", "--em-max-iters", "30",
         "--batch-size", "256",
         "--out", str(out)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(out) as f:
        rec = json.load(f)
    return rec, out


def test_record_contract_fields(tool_record):
    rec, _ = tool_record
    for field in ("gen_wall_s", "raw_gb", "pre_wall_s", "events",
                  "word_count_rows", "corpus_wall_s", "num_docs",
                  "vocab_size", "num_tokens", "train_wall_s",
                  "em_iters", "final_likelihood", "likelihood_rows",
                  "peak_rss_gb", "ip_zipf_a", "n_svc_ports"):
        assert field in rec, field
    # The pre stage drops one line as the header (reference
    # removeHeader parity, flow_pre_lda.scala:22-26) — the r05 capture
    # shows the same 149,999,999-of-150M shape.
    assert rec["days"] == 2 and 11_999 <= rec["events"] <= 12_000
    # Two documents per event (src and dst perspectives) bound docs by
    # events*2 and by the address population.
    assert 0 < rec["num_docs"] <= min(24_000, 3000 + 1500)


def test_power_law_population_scales_cardinality(tool_record):
    """12k events over a 4.5k-IP rank^-1.2 population must surface a
    large share of that population as documents — the fixed 6k-host
    round-4 pool gave 6,000 docs at 150M events, which is the failure
    mode this tool's realistic mode exists to rule out."""
    rec, _ = tool_record
    # With a=1.2 over 3k src ranks, 12k draws cover most of the head
    # and a meaningful tail: expect >1/3 of the population seen.
    assert rec["num_docs"] > 1500, rec["num_docs"]
    # The widened service mix yields a multi-hundred-word vocabulary
    # even at this scale (6 fixed services gave ~100).
    assert rec["vocab_size"] > 200, rec["vocab_size"]


def test_train_stage_converges_and_keeps_likelihood(tool_record):
    rec, out = tool_record
    assert rec["num_topics"] == 4
    assert 1 <= rec["em_iters"] <= 30
    assert rec["likelihood_rows"] == rec["em_iters"]
    ll_copy = str(out)[:-5] + "_likelihood.dat"
    assert os.path.exists(ll_copy)
    with open(ll_copy) as f:
        lls = [float(line.split()[0]) for line in f if line.strip()]
    assert len(lls) == rec["em_iters"]
    # Monotone non-decreasing likelihood (EM invariant).
    assert all(b >= a - 1e-6 * abs(a) for a, b in zip(lls, lls[1:]))
    assert rec["final_likelihood"] == pytest.approx(lls[-1], rel=1e-6)
