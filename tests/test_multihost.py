"""Real 2-process jax.distributed training over the host-local-shards +
explicit-allreduce architecture (parallel/allreduce.py, ROADMAP item 1).

Launches two OS processes that form a CPU jax.distributed cluster; each
trains its document shards HOST-LOCALLY (its own 2 virtual devices) and
the sufficient statistics cross processes through the KV-ring allreduce
— the old global-mesh SPMD program is gone (the CPU runtime cannot
execute cross-process XLA collectives at all, which is why this whole
suite used to error).  Contracts checked: bitwise rank parity,
agreement with plain single-process training, BYTE-identical
coordinator artifacts between a 1-process and a 2-process run (the
shard plan and reduction tree derive from the corpus, not the rank
count), the sparse engine surviving distribution, and structured
failure propagation ("failed on another rank", the BackendLost/rc=3
machinery) instead of hangs or raw XLA tracebacks.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)

# The failure-injection tests rely on bounded collective waits; the
# worker fixtures inherit it too so a wedged run fails the suite fast
# instead of eating the launcher timeout.
_WAIT_TIMEOUT_S = "90"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env():
    env = {
        k: v
        for k, v in os.environ.items()
        # The workers configure their own backend; scrub the suite's
        # single-process CPU/8-device env, any TPU pool hook, and the
        # E-step engine override (it would pin every run's engine and
        # hollow out the sparse-vs-dense cross checks).
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS",
                     "ONI_ML_TPU_ESTEP")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ONI_ML_TPU_ALLREDUCE_TIMEOUT_S"] = _WAIT_TIMEOUT_S
    return env


def _launch_workers(outdir, nprocs: int, timeout: float = 420.0):
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(TESTS_DIR, "multihost_worker.py"),
             str(port), str(pid), str(nprocs), str(outdir)],
            env=_worker_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for pid in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    for pid, out in enumerate(outs):
        assert f"WORKER_OK {pid}" in out
    return outdir


@pytest.fixture(scope="module")
def worker_runs(tmp_path_factory):
    """The 2-process cluster run."""
    return _launch_workers(tmp_path_factory.mktemp("mh2"), nprocs=2)


@pytest.fixture(scope="module")
def worker_runs_single(tmp_path_factory):
    """The SAME worker script at 1 process — the byte-identity
    baseline: same corpus-derived shard plan, same per-shard programs,
    same reduction tree, local transport."""
    return _launch_workers(tmp_path_factory.mktemp("mh1"), nprocs=1)


def test_ranks_agree_and_match_single_process(worker_runs):
    r0 = np.load(worker_runs / "proc0.npz")
    r1 = np.load(worker_runs / "proc1.npz")
    # The reduced stats are identical bytes on every rank (fixed
    # pairwise tree over gathered partials), so the whole derived model
    # must be too — parity is asserted in-loop by the trainer and
    # re-checked here on the persisted results.
    np.testing.assert_array_equal(r0["log_beta"], r1["log_beta"])
    np.testing.assert_array_equal(r0["gamma"], r1["gamma"])
    np.testing.assert_array_equal(r0["lls"], r1["lls"])
    assert r0["alpha"] == r1["alpha"]

    # And the distributed run must agree with plain (non-distributed)
    # single-process training on the same corpus/config: the explicit
    # allreduce sums the identical per-doc suff-stats, so only
    # reduction-order/batching noise remains.
    sys.path.insert(0, TESTS_DIR)
    import reference_lda as ref
    from test_lda import corpus_from_docs

    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.models import train_corpus

    docs, _ = ref.make_synthetic_corpus(
        num_docs=80, num_terms=25, num_topics=3, seed=21
    )
    res = train_corpus(
        corpus_from_docs(docs, 25),
        LDAConfig(num_topics=3, em_max_iters=6, em_tol=0.0, batch_size=32,
                  min_bucket_len=64, seed=4, fused_em_chunk=4),
        distributed=False,
    )
    np.testing.assert_allclose(res.log_beta, r0["log_beta"], atol=5e-4)
    np.testing.assert_allclose(
        np.asarray([ll for ll, _ in res.likelihoods]), r0["lls"], rtol=1e-5
    )


def test_sparse_engine_two_rank_parity(worker_runs):
    """The PR 9 sparse engine under distribution: per-shard bucketed
    layouts, per-bucket segment-sums folded into the local partials,
    [V, K] factor allreduced.  Ranks agree bit-for-bit, and the
    trajectory matches the dense-family distributed run on the same
    corpus/config within engine tolerance (the engines share
    semantics; the sparse kernel runs interpret-mode on CPU)."""
    r0 = np.load(worker_runs / "proc0.npz")
    r1 = np.load(worker_runs / "proc1.npz")
    np.testing.assert_array_equal(r0["sp_log_beta"], r1["sp_log_beta"])
    np.testing.assert_array_equal(r0["sp_gamma"], r1["sp_gamma"])
    np.testing.assert_array_equal(r0["sp_lls"], r1["sp_lls"])
    # vs the dense-family run (run 1 warm-starts while run 2 is
    # fresh-start — compare against a fresh-start dense single-process
    # run instead).
    sys.path.insert(0, TESTS_DIR)
    import reference_lda as ref
    from test_lda import corpus_from_docs

    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.models import train_corpus

    docs, _ = ref.make_synthetic_corpus(
        num_docs=80, num_terms=25, num_topics=3, seed=21
    )
    dense = train_corpus(
        corpus_from_docs(docs, 25),
        LDAConfig(num_topics=3, em_max_iters=6, em_tol=0.0, batch_size=32,
                  min_bucket_len=64, seed=4),
        distributed=False,
    )
    np.testing.assert_allclose(
        np.asarray([ll for ll, _ in dense.likelihoods]), r0["sp_lls"],
        rtol=1e-4,
    )
    np.testing.assert_allclose(
        np.exp(r0["sp_log_beta"]), np.exp(dense.log_beta),
        rtol=5e-3, atol=5e-3,
    )


def test_artifacts_byte_identical_across_rank_counts(
    worker_runs, worker_runs_single
):
    """THE distribution-correctness contract: a 2-rank run's
    coordinator-written artifacts are byte-identical to a 1-rank run's.
    The shard plan (and therefore every per-shard compiled program and
    the fixed pairwise reduction tree) derives from the corpus, not the
    process count — distribution changes WHERE shards run, never the
    arithmetic."""
    for day in ("day", "day_sparse"):
        for fn in ("final.beta", "final.gamma", "final.other",
                   "likelihood.dat"):
            two = (worker_runs / day / fn).read_bytes()
            one = (worker_runs_single / day / fn).read_bytes()
            assert two == one, f"{day}/{fn} differs across rank counts"


def test_streaming_checkpoint_survives_multihost(worker_runs):
    """Distributed streaming trainer: micro-batches row-split across
    ranks, lambda blended from the reduced stats identically on every
    rank; the coordinator owns a loadable stream checkpoint."""
    from oni_ml_tpu.models.online_lda import load_stream_checkpoint

    r0 = np.load(worker_runs / "proc0.npz")
    r1 = np.load(worker_runs / "proc1.npz")
    np.testing.assert_array_equal(r0["stream_lam"], r1["stream_lam"])
    assert r0["stream_steps"] == r1["stream_steps"] > 0
    z = load_stream_checkpoint(str(worker_runs / "day" / "stream.npz"))
    assert z["step"] == int(r0["stream_steps"])
    np.testing.assert_allclose(z["lam"], r0["stream_lam"], rtol=1e-6)


def test_pipeline_multihost_single_writer(worker_runs):
    """run_pipeline across both ranks: stage decisions broadcast over
    the KV store, every stage output written exactly once by the
    coordinator, full day completes (pre/corpus/lda/score all
    recorded), every rank joins stage_lda."""
    import json

    r0 = np.load(worker_runs / "proc0.npz")
    r1 = np.load(worker_runs / "proc1.npz")
    assert r0["pipeline_stages"] == 4          # coordinator ran all stages
    day = worker_runs / "20260101"
    for fn in ("word_counts.dat", "model.dat", "final.beta",
               "doc_results.csv", "word_results.csv", "flow_results.csv",
               "metrics.json"):
        assert (day / fn).exists(), fn
    metrics = json.loads((day / "metrics.json").read_text())
    stages = [m["stage"] for m in metrics
              if m.get("stage") in ("pre", "corpus", "lda", "score")]
    assert stages == ["pre", "corpus", "lda", "score"]
    score = [m for m in metrics if m.get("stage") == "score"][0]
    assert score["scored_events"] == 200
    # The lda stage record carries the shard-plan/allreduce provenance.
    lda = [m for m in metrics if m.get("stage") == "lda"][0]
    assert lda["plans"]["em_shards"]["value"] >= 2
    assert lda["plans"]["allreduce"]["transport"] == "kvring"
    assert lda["plans"]["allreduce"]["bytes_out"] > 0
    assert r1["pipeline_stages"] >= 1          # rank 1 joined stage_lda


def test_coordinator_owns_shared_files(worker_runs):
    day = worker_runs / "day"
    # Coordinator wrote the full reference output set...
    for fn in ("final.beta", "final.gamma", "final.other", "likelihood.dat"):
        assert (day / fn).exists(), fn
    # ...exactly once: likelihood.dat has one line per EM iteration (6),
    # which a second appender would have doubled.
    lines = (day / "likelihood.dat").read_text().strip().split("\n")
    assert len(lines) == 6, lines
    # The completed run cleaned its checkpoint (coordinator-gated).
    assert not (day / "checkpoint.npz").exists()


def test_shard_plan_journaled(worker_runs):
    """The day dir's run journal carries the {"kind": "shard_plan"}
    record (and allreduce records) for post-hoc reconstruction."""
    import json

    jpath = worker_runs / "20260101" / "run_journal.jsonl"
    kinds = [json.loads(line).get("kind")
             for line in jpath.read_text().splitlines() if line.strip()]
    assert "shard_plan" in kinds
    assert "allreduce" in kinds


_ABORT_WORKER = r"""
import os, sys
port, pid = sys.argv[1], int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
from oni_ml_tpu.parallel import initialize_distributed
initialize_distributed(f"localhost:{port}", 2, pid)
from oni_ml_tpu.config import LDAConfig, PipelineConfig, ScoringConfig
from oni_ml_tpu.runner.ml_ops import run_pipeline
cfg = PipelineConfig(
    data_dir=sys.argv[3], flow_path="/nonexistent/flow.csv",
    lda=LDAConfig(num_topics=3), scoring=ScoringConfig(threshold=0.5),
)
run_pipeline(cfg, "20260102", "flow")
"""


_RANK1_FAIL_WORKER = r"""
import os, sys
port, pid = sys.argv[1], int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
from oni_ml_tpu.parallel import initialize_distributed
initialize_distributed(f"localhost:{port}", 2, pid)
import numpy as np
from oni_ml_tpu.config import LDAConfig, PipelineConfig, ScoringConfig
from oni_ml_tpu.runner import ml_ops

flow = os.path.join(sys.argv[3], "flow.csv")
if pid == 0:
    rows = ["hdr"] + [
        ",".join(["0"]*4 + ["1","2","3","0",f"10.0.0.{i%5}",f"10.0.1.{i%3}",
                  "443","2000","0","0","0","0","5","100"] + ["0"]*9)
        for i in range(64)
    ]
    with open(flow, "w") as f:
        f.write("\n".join(rows) + "\n")
if pid == 1:
    # Fail BEFORE any collective inside the lda stage — the class of
    # failure only the failure-key relay / outcome barrier can surface
    # on the peer.
    def boom(ctx):
        raise OSError("rank1 cannot read shared model.dat")
    ml_ops._STAGE_FNS[ml_ops.Stage.LDA] = boom
cfg = PipelineConfig(
    data_dir=sys.argv[3], flow_path=flow,
    lda=LDAConfig(num_topics=3, em_max_iters=3, batch_size=32,
                  min_bucket_len=64),
    scoring=ScoringConfig(threshold=0.5),
)
ml_ops.run_pipeline(cfg, "20260103", "flow")
"""


def _run_pair(script, tmp_path, timeout=180):
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(port), str(pid),
             str(tmp_path)],
            env=_worker_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)  # hang == the bug
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_noncoordinator_precollective_failure_fails_all_ranks(tmp_path):
    """Rank 1 raising inside stage_lda before its collectives must fail
    the whole job, not hang it: the failing rank posts the failure key
    and enters the outcome barrier; the survivor sees the False flag
    (or the key itself from inside a collective wait) and aborts with
    the structured peer-failure error.  Both ranks must terminate
    nonzero within the timeout."""
    procs, outs = _run_pair(_RANK1_FAIL_WORKER, tmp_path)
    assert procs[0].returncode != 0, outs[0][-2000:]
    assert procs[1].returncode != 0, outs[1][-2000:]
    assert "failed on another rank" in outs[0]


def test_coordinator_stage_failure_fails_all_ranks(tmp_path):
    """A stage exception on the coordinator (bad flow_path) must
    surface on every rank as the structured "failed on another rank"
    peer-failure (a BackendLost subclass — ml_ops exits rc=3 with the
    structured payload) — not leave non-coordinators blocked in the
    next decision broadcast, and not a raw XLA traceback."""
    procs, outs = _run_pair(_ABORT_WORKER, tmp_path)
    assert procs[0].returncode != 0, outs[0][-2000:]
    assert procs[1].returncode != 0, outs[1][-2000:]
    assert "failed on another rank" in outs[1]
    assert "XlaRuntimeError" not in outs[1]
