"""Real 2-process jax.distributed training (VERDICT r1 item 4).

Launches two OS processes that form a CPU jax.distributed cluster and
train over a 4-device mesh spanning both, then checks the multi-host
contracts: identical results on every rank, agreement with a
single-process run on the same corpus/config, and coordinator-only
ownership of the shared day directory's files.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def worker_runs(tmp_path_factory):
    outdir = tmp_path_factory.mktemp("mh")
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        # The workers configure their own backend; scrub the suite's
        # single-process CPU/8-device env, any TPU pool hook, and the
        # E-step engine override (it silently maps dense_em="on" to
        # "off", which would hollow out the dense cross-host test).
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS",
                     "ONI_ML_TPU_ESTEP")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(TESTS_DIR, "multihost_worker.py"),
             str(port), str(pid), "2", str(outdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    assert "WORKER_OK 0" in outs[0] and "WORKER_OK 1" in outs[1]
    return outdir


def test_ranks_agree_and_match_single_process(worker_runs):
    r0 = np.load(worker_runs / "proc0.npz")
    r1 = np.load(worker_runs / "proc1.npz")
    # to_host gathers collectively, so every rank must hold the same
    # global result.
    np.testing.assert_array_equal(r0["log_beta"], r1["log_beta"])
    np.testing.assert_array_equal(r0["gamma"], r1["gamma"])
    np.testing.assert_array_equal(r0["lls"], r1["lls"])
    assert r0["alpha"] == r1["alpha"]

    # And the 2-process 4-device mesh must agree with plain
    # single-process training (the same seed/config; collectives psum
    # the identical suff-stats, so only reduction-order noise remains).
    sys.path.insert(0, TESTS_DIR)
    import reference_lda as ref
    from test_lda import corpus_from_docs

    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.models import train_corpus

    docs, _ = ref.make_synthetic_corpus(
        num_docs=80, num_terms=25, num_topics=3, seed=21
    )
    res = train_corpus(
        corpus_from_docs(docs, 25),
        LDAConfig(num_topics=3, em_max_iters=6, em_tol=0.0, batch_size=32,
                  min_bucket_len=64, seed=4, fused_em_chunk=4),
    )
    np.testing.assert_allclose(res.log_beta, r0["log_beta"], atol=5e-4)
    np.testing.assert_allclose(
        np.asarray([ll for ll, _ in res.likelihoods]), r0["lls"], rtol=1e-5
    )


def test_vocab_sharded_dense_crosses_hosts(worker_runs):
    """The vocab-sharded dense plan on a (2, 2) mesh spanning both
    processes: ranks agree bit-for-bit, and the trajectory matches the
    sparse data-parallel run on the same corpus/config (the engines
    share semantics, so only reduction-order noise remains)."""
    r0 = np.load(worker_runs / "proc0.npz")
    r1 = np.load(worker_runs / "proc1.npz")
    np.testing.assert_array_equal(r0["vs_log_beta"], r1["vs_log_beta"])
    np.testing.assert_array_equal(r0["vs_lls"], r1["vs_lls"])
    np.testing.assert_allclose(r0["vs_lls"], r0["lls"], rtol=1e-4)
    np.testing.assert_allclose(
        np.exp(r0["vs_log_beta"]), np.exp(r0["log_beta"]),
        rtol=5e-3, atol=5e-3,
    )


def test_streaming_checkpoint_survives_multihost(worker_runs):
    """Online trainer on the 2-process mesh: the collective-before-gate
    checkpoint ordering must not deadlock, ranks must agree on lambda,
    and the coordinator must have written a loadable stream checkpoint."""
    from oni_ml_tpu.models.online_lda import load_stream_checkpoint

    r0 = np.load(worker_runs / "proc0.npz")
    r1 = np.load(worker_runs / "proc1.npz")
    np.testing.assert_array_equal(r0["stream_lam"], r1["stream_lam"])
    assert r0["stream_steps"] == r1["stream_steps"] > 0
    z = load_stream_checkpoint(str(worker_runs / "day" / "stream.npz"))
    assert z["step"] == int(r0["stream_steps"])
    np.testing.assert_allclose(z["lam"], r0["stream_lam"], rtol=1e-6)


def test_pipeline_multihost_single_writer(worker_runs):
    """run_pipeline across both ranks: stage decisions broadcast, every
    stage output written exactly once by the coordinator, full day
    completes (pre/corpus/lda/score all recorded)."""
    import json

    r0 = np.load(worker_runs / "proc0.npz")
    r1 = np.load(worker_runs / "proc1.npz")
    assert r0["pipeline_stages"] == 4          # coordinator ran all stages
    day = worker_runs / "20260101"
    for fn in ("word_counts.dat", "model.dat", "final.beta",
               "doc_results.csv", "word_results.csv", "flow_results.csv",
               "metrics.json"):
        assert (day / fn).exists(), fn
    metrics = json.loads((day / "metrics.json").read_text())
    assert [m["stage"] for m in metrics] == ["pre", "corpus", "lda", "score"]
    assert metrics[-1]["scored_events"] == 200
    assert r1["pipeline_stages"] >= 1          # rank 1 joined stage_lda


_ABORT_WORKER = r"""
import os, sys
port, pid = sys.argv[1], int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
from oni_ml_tpu.parallel import initialize_distributed, make_mesh
initialize_distributed(f"localhost:{port}", 2, pid)
from oni_ml_tpu.config import LDAConfig, PipelineConfig, ScoringConfig
from oni_ml_tpu.runner.ml_ops import run_pipeline
cfg = PipelineConfig(
    data_dir=sys.argv[3], flow_path="/nonexistent/flow.csv",
    lda=LDAConfig(num_topics=3), scoring=ScoringConfig(threshold=0.5),
)
run_pipeline(cfg, "20260102", "flow", mesh=make_mesh(data=4, model=1))
"""


_RANK1_FAIL_WORKER = r"""
import os, sys
port, pid = sys.argv[1], int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
from oni_ml_tpu.parallel import initialize_distributed, make_mesh
initialize_distributed(f"localhost:{port}", 2, pid)
import numpy as np
from oni_ml_tpu.config import LDAConfig, PipelineConfig, ScoringConfig
from oni_ml_tpu.runner import ml_ops

flow = os.path.join(sys.argv[3], "flow.csv")
if pid == 0:
    rows = ["hdr"] + [
        ",".join(["0"]*4 + ["1","2","3","0",f"10.0.0.{i%5}",f"10.0.1.{i%3}",
                  "443","2000","0","0","0","0","5","100"] + ["0"]*9)
        for i in range(64)
    ]
    with open(flow, "w") as f:
        f.write("\n".join(rows) + "\n")
if pid == 1:
    # Fail BEFORE any collective inside the lda stage — the class of
    # failure a one-to-all outcome broadcast cannot relay.
    def boom(ctx):
        raise OSError("rank1 cannot read shared model.dat")
    ml_ops._STAGE_FNS[ml_ops.Stage.LDA] = boom
cfg = PipelineConfig(
    data_dir=sys.argv[3], flow_path=flow,
    lda=LDAConfig(num_topics=3, em_max_iters=3, batch_size=32,
                  min_bucket_len=64),
    scoring=ScoringConfig(threshold=0.5),
)
ml_ops.run_pipeline(cfg, "20260103", "flow", mesh=make_mesh(data=4, model=1))
"""


def _run_pair(script, tmp_path, timeout=180):
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS",
                     "ONI_ML_TPU_ESTEP")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(port), str(pid),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)  # hang == the bug
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_noncoordinator_precollective_failure_fails_all_ranks(tmp_path):
    """Rank 1 raising inside stage_lda before its collectives must fail
    the whole job, not hang it.  Two mechanisms cover this: the
    all-gathered outcome flags relay the failure when the survivor has
    reached the barrier, and the jax.distributed coordination-service
    heartbeat errors a survivor stuck inside the stage's collectives
    once the failed rank's process exits.  Either way both ranks must
    terminate nonzero within the timeout."""
    procs, outs = _run_pair(_RANK1_FAIL_WORKER, tmp_path)
    # The survivor's collective errors via the runtime (heartbeat /
    # mismatch detection) and the failed rank can die on a C++-level
    # abort before Python prints — the contract is termination, not a
    # specific message.
    assert procs[0].returncode != 0, outs[0][-2000:]
    assert procs[1].returncode != 0, outs[1][-2000:]


def test_coordinator_stage_failure_fails_all_ranks(tmp_path):
    """A stage exception on the coordinator (bad flow_path) must
    propagate to every rank through the outcome barrier — not leave
    non-coordinators blocked in the next decision broadcast."""
    procs, outs = _run_pair(_ABORT_WORKER, tmp_path)
    assert procs[0].returncode != 0, outs[0][-2000:]
    assert procs[1].returncode != 0, outs[1][-2000:]
    assert "failed on another rank" in outs[1]


def test_coordinator_owns_shared_files(worker_runs):
    day = worker_runs / "day"
    # Coordinator wrote the full reference output set...
    for fn in ("final.beta", "final.gamma", "final.other", "likelihood.dat"):
        assert (day / fn).exists(), fn
    # ...exactly once: likelihood.dat has one line per EM iteration (6),
    # which a second appender would have doubled.
    lines = (day / "likelihood.dat").read_text().strip().split("\n")
    assert len(lines) == 6, lines
    # The completed run cleaned its checkpoint (coordinator-gated).
    assert not (day / "checkpoint.npz").exists()
