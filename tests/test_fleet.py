"""Multi-tenant serving fleet (oni_ml_tpu/serving/fleet.py +
tenants.py): manifest/spec validation, FleetRegistry stacked snapshots
and per-tenant hot-swap, cross-tenant packed-dispatch score parity
(bit-identical to single-tenant scoring), admission backpressure and
rejection, the hot-swap isolation stress the acceptance criteria name,
per-tenant metrics on the live /metrics endpoint, the fleet dry-run
CLI, the load_gen fleet SLO harness, and bench_diff's
latency-direction-aware serving keys.  All CPU, no markers — this file
is the tier-1 fleet smoke.
"""

import json
import os
import pickle
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from oni_ml_tpu import plans
from oni_ml_tpu.config import ServingConfig
from oni_ml_tpu.plans import KNOBS, PlanStore, use_store
from oni_ml_tpu.runner.serve import _synthetic_day
from oni_ml_tpu.scoring import ScoringModel
from oni_ml_tpu.serving import (
    AdmissionRejected,
    DnsEventFeaturizer,
    FleetRegistry,
    FleetScorer,
    FlowEventFeaturizer,
    MetricsEmitter,
    RefreshLoop,
    TenantSpec,
    demux_scores,
    event_documents,
    load_manifest,
    parse_manifest,
    score_features,
)
from oni_ml_tpu.telemetry.spans import Recorder

from test_features import flow_row


@pytest.fixture(scope="module")
def days():
    """Three distinct synthetic DNS days (distinct seeds -> distinct
    models; same K -> one pack group) shared by the fleet tests."""
    return {f"t{i}": _synthetic_day(seed=42 + i) for i in range(3)}


def _perturbed(model: ScoringModel, seed: int = 7) -> ScoringModel:
    rng = np.random.default_rng(seed)
    theta = model.theta * rng.uniform(0.5, 1.5, model.theta.shape)
    theta[:-1] /= theta[:-1].sum(1, keepdims=True)
    p = model.p * rng.uniform(0.5, 1.5, model.p.shape)
    p[:-1] /= p[:-1].sum(0, keepdims=True)
    return ScoringModel(
        ip_index=model.ip_index, theta=theta,
        word_index=model.word_index, p=p,
    )


def _fleet(days, tenants=("t0", "t1"), **cfg_kw):
    """FleetRegistry + FleetScorer over `tenants`, host-pinned."""
    fleet = FleetRegistry()
    featurizers = {}
    for t in tenants:
        rows, model, cuts = days[t]
        fleet.add_tenant(TenantSpec(tenant=t, dsource="dns"))
        fleet.publish(t, model, source=f"day-{t}")
        featurizers[t] = DnsEventFeaturizer(cuts)
    cfg = ServingConfig(device_score_min=None, **cfg_kw)
    metrics = MetricsEmitter(to_stdout=False)
    scorer = FleetScorer(fleet, featurizers, cfg, metrics=metrics)
    return fleet, featurizers, metrics, scorer


# ---------------------------------------------------------------------------
# specs + manifest
# ---------------------------------------------------------------------------


def test_tenant_spec_validation():
    TenantSpec(tenant="ok_id_1", dsource="dns")        # valid
    with pytest.raises(ValueError, match="tenant id"):
        TenantSpec(tenant="bad.dots", dsource="dns")
    with pytest.raises(ValueError, match="tenant id"):
        # '-' rewrites to '_' in OpenMetrics names: "acme-eu" and
        # "acme_eu" would merge onto one exposition series.
        TenantSpec(tenant="acme-eu", dsource="dns")
    with pytest.raises(ValueError, match="tenant id"):
        TenantSpec(tenant="", dsource="dns")
    with pytest.raises(ValueError, match="dsource"):
        TenantSpec(tenant="a", dsource="http")
    with pytest.raises(ValueError, match="admission"):
        TenantSpec(tenant="a", dsource="dns", admission="drop")
    with pytest.raises(ValueError, match="queue_max"):
        TenantSpec(tenant="a", dsource="dns", queue_max=-1)
    with pytest.raises(ValueError, match="weight"):
        TenantSpec(tenant="a", dsource="dns", weight=0.0)


def test_manifest_roundtrip_and_rejections(tmp_path):
    path = str(tmp_path / "fleet.json")
    with open(path, "w") as f:
        json.dump({"tenants": [
            {"tenant": "alpha", "day_dir": "/d/a", "dsource": "flow",
             "weight": 2.0},
            {"tenant": "beta", "day_dir": "/d/b", "dsource": "dns",
             "queue_max": 64, "admission": "reject"},
        ]}, f)
    specs = load_manifest(path)
    assert [s.tenant for s in specs] == ["alpha", "beta"]
    assert specs[0].weight == 2.0
    assert specs[1].admission == "reject"
    with pytest.raises(ValueError, match="duplicate"):
        parse_manifest({"tenants": [
            {"tenant": "a", "dsource": "dns"},
            {"tenant": "a", "dsource": "dns"},
        ]})
    with pytest.raises(ValueError, match="unknown keys"):
        parse_manifest({"tenants": [{"tenant": "a", "dsourc": "dns"}]})
    with pytest.raises(ValueError, match="zero tenants"):
        parse_manifest({"tenants": []})
    with pytest.raises(ValueError, match="'tenants' list"):
        parse_manifest(["a"])


# ---------------------------------------------------------------------------
# FleetRegistry: per-tenant hot-swap + stacked snapshots
# ---------------------------------------------------------------------------


def test_registry_per_tenant_versions_monotonic(days):
    fleet = FleetRegistry()
    for t in ("t0", "t1"):
        fleet.add_tenant(TenantSpec(tenant=t, dsource="dns"))
    _, m0, _ = days["t0"]
    _, m1, _ = days["t1"]
    fleet.publish("t0", m0, "a")
    fleet.publish("t1", m1, "b")
    fleet.publish("t0", _perturbed(m0), "a2")
    # t0's swap bumped ONLY t0.
    assert fleet.version("t0") == 2
    assert fleet.version("t1") == 1
    assert fleet.previous("t0").version == 1
    # The retired snapshot stays pinned (registry.py semantics).
    assert fleet.previous("t0").model is m0


def test_stacked_snapshot_offsets_and_contents(days):
    fleet = FleetRegistry()
    models = {}
    for t in ("t0", "t1", "t2"):
        _, m, _ = days[t]
        models[t] = m
        fleet.add_tenant(TenantSpec(tenant=t, dsource="dns"))
        fleet.publish(t, m, t)
    stack = fleet.stack_for("t0")
    assert stack.tenants == ("t0", "t1", "t2")
    # Each tenant's slice (INCLUDING its fallback row) is its model.
    for t in stack.tenants:
        m = models[t]
        i0 = stack.ip_base[t]
        w0 = stack.word_base[t]
        np.testing.assert_array_equal(
            stack.model.theta[i0:i0 + m.theta.shape[0]], m.theta)
        np.testing.assert_array_equal(
            stack.model.p[w0:w0 + m.p.shape[0]], m.p)
    assert stack.model.theta.shape[0] == sum(
        m.theta.shape[0] for m in models.values())


def test_stack_double_buffered_install(days):
    fleet = FleetRegistry()
    for t in ("t0", "t1"):
        _, m, _ = days[t]
        fleet.add_tenant(TenantSpec(tenant=t, dsource="dns"))
        fleet.publish(t, m, t)
    before = fleet.stack_for("t0")
    theta_before = before.model.theta.copy()
    fleet.publish("t0", _perturbed(days["t0"][1]), "swap")
    after = fleet.stack_for("t0")
    # Fresh instance installed; the old one an in-flight flush holds is
    # untouched — and t1's slice is bit-identical across the swap.
    assert after is not before
    assert after.stack_version > before.stack_version
    np.testing.assert_array_equal(before.model.theta, theta_before)
    t1 = days["t1"][1]
    w0 = after.word_base["t1"]
    np.testing.assert_array_equal(
        after.model.p[w0:w0 + t1.p.shape[0]], t1.p)
    assert after.version_of("t0") == 2
    assert after.version_of("t1") == 1


def test_registry_errors(days):
    fleet = FleetRegistry()
    fleet.add_tenant(TenantSpec(tenant="t0", dsource="dns"))
    with pytest.raises(ValueError, match="already added"):
        fleet.add_tenant(TenantSpec(tenant="t0", dsource="dns"))
    with pytest.raises(KeyError, match="unknown tenant"):
        fleet.active("nope")
    with pytest.raises(RuntimeError, match="no model published"):
        fleet.active("t0")
    with pytest.raises(RuntimeError, match="no published model"):
        fleet.tenant_k("t0")


def test_fleet_publish_journal_and_counter(days, tmp_path):
    from oni_ml_tpu.telemetry import Journal

    jpath = str(tmp_path / "fleet.jsonl")
    journal = Journal(jpath)
    rec = Recorder()
    fleet = FleetRegistry(journal=journal, recorder=rec)
    fleet.add_tenant(TenantSpec(tenant="t0", dsource="dns"))
    _, m, _ = days["t0"]
    fleet.publish("t0", m, "day-one")
    fleet.publish("t0", _perturbed(m), "refresh")
    journal.close()
    records = [r for r in Journal.replay(jpath)
               if r["kind"] == "fleet_publish"]
    assert [r["version"] for r in records] == [1, 2]
    assert records[0]["tenant"] == "t0"
    assert records[0]["source"] == "day-one"
    assert records[0]["k"] == m.num_topics
    assert rec.counters["serve.t0.publishes"].value == 2


def test_refresh_loop_over_fleet_view(days):
    """serving/refresh.py works unchanged against a per-tenant view:
    its publish routes through the fleet (version bump + stack
    rebuild)."""
    fleet = FleetRegistry()
    rows, model, cuts = days["t0"]
    fleet.add_tenant(TenantSpec(tenant="t0", dsource="dns"))
    fleet.publish("t0", model, "day")
    view = fleet.view("t0")
    from oni_ml_tpu.config import OnlineLDAConfig

    loop = RefreshLoop(view, OnlineLDAConfig(
        num_topics=model.num_topics), every=1)
    fz = DnsEventFeaturizer(cuts)
    feats = fz([fz.validate(r) for r in rows[:16]])
    ips, words = event_documents(feats, "dns")
    new = loop.observe(fleet.active("t0"), ips, words)
    assert new is not None and new.version == 2
    assert fleet.version("t0") == 2
    assert fleet.stack_for("t0").version_of("t0") == 2


def test_mixed_k_tenants_get_separate_stacks(days):
    """Tenants whose K diverges form separate pack groups (per-tenant
    segment dispatch) — heterogeneous fleets degrade to more
    dispatches, never to wrong scores."""
    rows0, m0, cuts0 = days["t0"]
    rows1, m1, cuts1 = days["t1"]
    # Rebuild t1's model with K+1 topics over the same populations.
    rng = np.random.default_rng(0)
    ips = sorted(m1.ip_index, key=m1.ip_index.get)
    vocab = sorted(m1.word_index, key=m1.word_index.get)
    k2 = m1.num_topics + 1
    m1b = ScoringModel.from_results(
        ips, rng.dirichlet(np.ones(k2), size=len(ips)),
        vocab, rng.dirichlet(np.ones(len(vocab)), size=k2).T,
        fallback=0.1,
    )
    fleet = FleetRegistry()
    for t, m in (("t0", m0), ("t1", m1b)):
        fleet.add_tenant(TenantSpec(tenant=t, dsource="dns"))
        fleet.publish(t, m, t)
    assert fleet.stack_for("t0").tenants == ("t0",)
    assert fleet.stack_for("t1").tenants == ("t1",)
    metrics = MetricsEmitter(to_stdout=False)
    scorer = FleetScorer(
        fleet, {"t0": DnsEventFeaturizer(cuts0),
                "t1": DnsEventFeaturizer(cuts1)},
        ServingConfig(device_score_min=None), metrics=metrics,
    )
    try:
        futs0 = [scorer.submit("t0", r) for r in rows0[:8]]
        futs1 = [scorer.submit("t1", r) for r in rows1[:8]]
        scorer.flush()
        s0 = np.array([f.result(30.0)[0] for f in futs0])
        [f.result(30.0) for f in futs1]
    finally:
        scorer.close()
    agg = [r for r in metrics.records
           if "segments" in r and "tenant" not in r]
    # Both tenants flushed together but dispatched as two segments.
    assert any(r["segments"] == 2 and r["tenants"] == 2 for r in agg)
    fz0 = DnsEventFeaturizer(cuts0)
    feats0 = fz0([fz0.validate(r) for r in rows0[:8]])
    np.testing.assert_array_equal(
        s0, score_features(m0, feats0, "dns", device_min=None))


# ---------------------------------------------------------------------------
# packed scoring parity
# ---------------------------------------------------------------------------


def test_packed_scores_bit_identical_to_single_tenant(days):
    """The tentpole invariant: a cross-tenant packed flush produces
    BIT-IDENTICAL scores to scoring each tenant alone — packing changes
    which dispatch a row rides, never its arithmetic."""
    fleet, featurizers, metrics, scorer = _fleet(
        days, tenants=("t0", "t1", "t2"))
    futs = {t: [] for t in ("t0", "t1", "t2")}
    try:
        for i in range(64):
            for t in futs:
                futs[t].append(scorer.submit(t, days[t][0][i]))
        scorer.flush()
        got = {t: np.array([f.result(30.0)[0] for f in fs])
               for t, fs in futs.items()}
    finally:
        scorer.close()
    for t, fs in futs.items():
        fz = featurizers[t]
        feats = fz([fz.validate(days[t][0][i]) for i in range(64)])
        expected = score_features(days[t][1], feats, "dns",
                                  device_min=None)
        np.testing.assert_array_equal(got[t], expected)
    # And the packed flushes really did span tenants.
    agg = [r for r in metrics.records if "tenant" not in r
           and isinstance(r.get("tenants"), int)]
    assert any(r["tenants"] == 3 and r["segments"] == 1 for r in agg)


def test_flow_tenant_pairs_min_combined(days):
    """A flow tenant's packed pairs (two per event, src then dst)
    demux back through the min-combine — parity with the single-model
    flow scorer."""
    lines = ["header"] + [
        flow_row(sip=f"10.0.0.{i % 5}", dip=f"10.0.1.{i % 7}",
                 ipkt=str(5 + i), ibyt=str(500 + 13 * i))
        for i in range(24)
    ]
    from oni_ml_tpu.features.flow import featurize_flow

    day_feats = featurize_flow(lines)
    ips = sorted({day_feats.sip(i) for i in range(day_feats.num_events)}
                 | {day_feats.dip(i)
                    for i in range(day_feats.num_events)})
    vocab = sorted(set(day_feats.src_word) | set(day_feats.dest_word))
    rng = np.random.default_rng(3)
    k = 5
    flow_model = ScoringModel.from_results(
        ips, rng.dirichlet(np.ones(k), size=len(ips)),
        vocab, rng.dirichlet(np.ones(len(vocab)), size=k).T,
        fallback=0.05,
    )
    cuts = (day_feats.time_cuts, day_feats.ibyt_cuts,
            day_feats.ipkt_cuts)
    rows_dns, dns_model, dns_cuts = days["t0"]
    fleet = FleetRegistry()
    fleet.add_tenant(TenantSpec(tenant="fl", dsource="flow"))
    fleet.add_tenant(TenantSpec(tenant="dn", dsource="dns"))
    fleet.publish("fl", flow_model, "flow-day")
    fleet.publish("dn", dns_model, "dns-day")
    featurizers = {"fl": FlowEventFeaturizer(cuts),
                   "dn": DnsEventFeaturizer(dns_cuts)}
    metrics = MetricsEmitter(to_stdout=False)
    scorer = FleetScorer(fleet, featurizers,
                         ServingConfig(device_score_min=None),
                         metrics=metrics)
    flow_lines = lines[1:17]
    try:
        f_futs = [scorer.submit("fl", ln) for ln in flow_lines]
        d_futs = [scorer.submit("dn", r) for r in rows_dns[:16]]
        scorer.flush()
        got_flow = np.array([f.result(30.0)[0] for f in f_futs])
        got_dns = np.array([f.result(30.0)[0] for f in d_futs])
    finally:
        scorer.close()
    ffz = featurizers["fl"]
    feats = ffz([ffz.validate(ln) for ln in flow_lines])
    np.testing.assert_array_equal(
        got_flow,
        score_features(flow_model, feats, "flow", device_min=None))
    dfz = featurizers["dn"]
    dfeats = dfz([dfz.validate(r) for r in rows_dns[:16]])
    np.testing.assert_array_equal(
        got_dns,
        score_features(dns_model, dfeats, "dns", device_min=None))
    # Same K -> the flow and dns tenants packed into ONE dispatch.
    agg = [r for r in metrics.records if "tenant" not in r
           and isinstance(r.get("tenants"), int)]
    assert any(r["tenants"] == 2 and r["segments"] == 1 for r in agg)


def test_scorer_label_tracks_actual_dispatch(days, monkeypatch):
    """The flush's `scorer` label (which gates the device roofline
    histogram) follows the per-group PACKED PAIR dispatch decision, not
    the flush's raw event count."""
    from oni_ml_tpu.serving import fleet as fleet_mod

    # Pretend the break-even is 40 pairs: a 32-pair dns flush is
    # host-labeled even if someone counted 2 tenants x 16 events
    # against a lower bound; monkeypatching only the fleet's reference
    # leaves the actual scoring dispatch untouched (host on CPU).
    monkeypatch.setattr(
        fleet_mod, "use_device_path", lambda n, dmin: n >= 40)
    fleet, _, metrics, scorer = _fleet(
        days, tenants=("t0", "t1"), fleet_max_batch=32,
        fleet_max_wait_ms=60_000.0)
    try:
        futs = [scorer.submit(t, days[t][0][i])
                for i in range(16) for t in ("t0", "t1")]
        [f.result(30.0) for f in futs]
    finally:
        scorer.close()
    agg = [r for r in metrics.records
           if "tenant" not in r and "segments" in r]
    assert agg and all(r["scorer"] == "host" for r in agg)
    assert all(r["segments_device"] == 0 for r in agg)
    # A 48-pair group (>= the fake break-even) labels device.
    monkeypatch.setattr(
        fleet_mod, "use_device_path", lambda n, dmin: n >= 20)
    fleet2, _, metrics2, scorer2 = _fleet(
        days, tenants=("t0", "t1"), fleet_max_batch=32,
        fleet_max_wait_ms=60_000.0)
    try:
        futs = [scorer2.submit(t, days[t][0][i])
                for i in range(16) for t in ("t0", "t1")]
        [f.result(30.0) for f in futs]
    finally:
        scorer2.close()
    agg2 = [r for r in metrics2.records
            if "tenant" not in r and "segments" in r]
    assert agg2 and all(r["scorer"] == "device" for r in agg2)
    assert all(r["segments_device"] == r["segments"] for r in agg2)


def test_demux_scores_helper():
    s = np.array([0.4, 0.9, 0.7, 0.2, 0.8, 0.1])
    np.testing.assert_array_equal(
        demux_scores(s, 2), np.array([0.2, 0.8, 0.1]))
    np.testing.assert_array_equal(demux_scores(s, 1), s)


def test_no_cross_tenant_score_leakage(days):
    """Two tenants submit THE SAME raw rows against different models:
    each tenant's scores must come from its own model slice."""
    rows, m0, cuts = days["t0"]
    m1 = _perturbed(m0, seed=11)
    fleet = FleetRegistry()
    for t, m in (("a", m0), ("b", m1)):
        fleet.add_tenant(TenantSpec(tenant=t, dsource="dns"))
        fleet.publish(t, m, t)
    fz = DnsEventFeaturizer(cuts)
    scorer = FleetScorer(fleet, {"a": fz, "b": fz},
                         ServingConfig(device_score_min=None))
    try:
        fa = [scorer.submit("a", r) for r in rows[:32]]
        fb = [scorer.submit("b", r) for r in rows[:32]]
        scorer.flush()
        sa = np.array([f.result(30.0)[0] for f in fa])
        sb = np.array([f.result(30.0)[0] for f in fb])
    finally:
        scorer.close()
    feats = fz([fz.validate(r) for r in rows[:32]])
    np.testing.assert_array_equal(
        sa, score_features(m0, feats, "dns", device_min=None))
    np.testing.assert_array_equal(
        sb, score_features(m1, feats, "dns", device_min=None))
    assert not np.array_equal(sa, sb)   # distinct models, distinct scores


# ---------------------------------------------------------------------------
# admission: backpressure + rejection
# ---------------------------------------------------------------------------


def test_admission_reject_sheds_load(days, tmp_path):
    from oni_ml_tpu.telemetry import Journal

    jpath = str(tmp_path / "admit.jsonl")
    journal = Journal(jpath)
    rows, model, cuts = days["t0"]
    fleet = FleetRegistry()
    fleet.add_tenant(TenantSpec(
        tenant="t0", dsource="dns", queue_max=4, admission="reject"))
    fleet.publish("t0", model, "day")
    metrics = MetricsEmitter(to_stdout=False)
    # A huge flush size + long wait keep the worker idle while the
    # queue fills.
    scorer = FleetScorer(
        fleet, {"t0": DnsEventFeaturizer(cuts)},
        ServingConfig(device_score_min=None,
                      fleet_max_batch=1 << 14,
                      fleet_max_wait_ms=60_000.0),
        metrics=metrics, journal=journal,
    )
    try:
        futs = [scorer.submit("t0", r) for r in rows[:4]]
        with pytest.raises(AdmissionRejected) as ei:
            scorer.submit("t0", rows[4])
        assert ei.value.tenant == "t0"
        assert (ei.value.depth, ei.value.capacity) == (4, 4)
        scorer.flush()
        [f.result(30.0) for f in futs]
        stats = {s["tenant"]: s for s in scorer.tenant_stats()}
        assert stats["t0"]["rejected"] == 1
        assert stats["t0"]["scored"] == 4
        assert metrics.recorder.counters[
            "serve.t0.admission_rejects"].value == 1
    finally:
        scorer.close()
        journal.close()
    recs = [r for r in Journal.replay(jpath)
            if r["kind"] == "admission_reject"]
    assert recs and recs[0]["tenant"] == "t0"
    assert recs[0]["capacity"] == 4


def test_admission_block_backpressures(days):
    """admission="block" (the default): a producer outrunning scoring
    throttles at its own tenant's bound, everything still streams
    through exactly once, and the stall is priced."""
    rows, model, cuts = days["t0"]
    fleet = FleetRegistry()
    fleet.add_tenant(TenantSpec(tenant="t0", dsource="dns",
                                queue_max=4))
    fleet.publish("t0", model, "day")
    metrics = MetricsEmitter(to_stdout=False)
    scorer = FleetScorer(
        fleet, {"t0": DnsEventFeaturizer(cuts)},
        ServingConfig(device_score_min=None, fleet_max_batch=2,
                      fleet_max_wait_ms=5.0),
        metrics=metrics,
    )
    try:
        futs = [scorer.submit("t0", r) for r in rows[:24]]
        results = [f.result(60.0) for f in futs]
        assert len(results) == 24
        assert scorer.events_scored == 24
    finally:
        scorer.close()


def test_unknown_tenant_and_malformed_event(days):
    _, _, _, scorer = _fleet(days)
    try:
        with pytest.raises(KeyError, match="unknown tenant"):
            scorer.submit("ghost", days["t0"][0][0])
        with pytest.raises(ValueError):
            scorer.submit("t0", "not,enough,columns")
    finally:
        scorer.close()


# ---------------------------------------------------------------------------
# hot-swap isolation (acceptance criterion)
# ---------------------------------------------------------------------------


def test_hot_swap_isolation_under_sustained_load(days):
    """Publish tenant A's model repeatedly while tenant B streams:
    B sees ZERO failed futures, B's served version never moves, B's
    scores stay bit-identical to its own model, and A's registry
    versions stay monotonic."""
    fleet, featurizers, metrics, scorer = _fleet(
        days, tenants=("t0", "t1"),
        fleet_max_batch=32, fleet_max_wait_ms=5.0)
    n_pub = 12
    stop = threading.Event()
    published = []

    def publisher():
        for i in range(n_pub):
            snap = fleet.publish(
                "t0", _perturbed(days["t0"][1], seed=100 + i),
                source=f"swap-{i}")
            published.append(snap.version)
            time.sleep(0.002)
        stop.set()

    pub = threading.Thread(target=publisher, daemon=True)
    futs_b, futs_a = [], []
    pub.start()
    rows0, rows1 = days["t0"][0], days["t1"][0]
    i = 0
    while not stop.is_set() or i < 64:
        futs_a.append(scorer.submit("t0", rows0[i % len(rows0)]))
        futs_b.append(scorer.submit("t1", rows1[i % len(rows1)]))
        i += 1
        time.sleep(0.0005)
    scorer.flush()
    pub.join(timeout=30.0)
    try:
        res_a = [f.result(30.0) for f in futs_a]
        res_b = [f.result(30.0) for f in futs_b]
    finally:
        scorer.close()
    # A's registry versions are strictly monotonic, and versions served
    # to A's futures never decrease in submit order.
    assert published == list(range(2, n_pub + 2))
    versions_a = [v for _, v in res_a]
    assert all(b >= a for a, b in zip(versions_a, versions_a[1:]))
    assert fleet.version("t0") == n_pub + 1
    # Isolation: every B future resolved, on version 1, bit-identical
    # to B's own model throughout the swap storm.
    assert len(res_b) == len(futs_b)
    assert {v for _, v in res_b} == {1}
    fz = featurizers["t1"]
    m1 = days["t1"][1]
    raws = [rows1[j % len(rows1)] for j in range(len(res_b))]
    feats = fz([fz.validate(r) for r in raws])
    np.testing.assert_array_equal(
        np.array([s for s, _ in res_b]),
        score_features(m1, feats, "dns", device_min=None))
    # No error records for tenant t1.
    assert not any(r.get("tenant") == "t1" and "error" in r
                   for r in metrics.records)


# ---------------------------------------------------------------------------
# metrics plane
# ---------------------------------------------------------------------------


def test_per_tenant_metric_namespaces(days):
    _, _, metrics, scorer = _fleet(days, tenants=("t0", "t1"))
    try:
        futs = [scorer.submit(t, days[t][0][i])
                for i in range(16) for t in ("t0", "t1")]
        scorer.flush()
        [f.result(30.0) for f in futs]
    finally:
        scorer.close()
    rec = metrics.recorder
    for t in ("t0", "t1"):
        assert rec.counters[f"serve.{t}.events"].value == 16
        assert rec.histograms[f"serve.{t}.latency_ms"].count >= 1
    # The aggregate namespace counts every event exactly once (the
    # per-tenant records must not double into it).
    assert rec.counters["serve.events"].value == 32


def test_metrics_endpoint_exposes_per_tenant_series(days):
    """Acceptance: per-tenant metrics visible on the live /metrics
    endpoint."""
    from oni_ml_tpu.telemetry import MetricsServer

    _, _, metrics, scorer = _fleet(days, tenants=("t0", "t1"))
    try:
        futs = [scorer.submit(t, days[t][0][i])
                for i in range(8) for t in ("t0", "t1")]
        scorer.flush()
        [f.result(30.0) for f in futs]
        server = MetricsServer(metrics.recorder, port=0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics",
                    timeout=10) as resp:
                text = resp.read().decode()
        finally:
            server.close()
    finally:
        scorer.close()
    assert "serve_t0_latency_ms" in text
    assert "serve_t1_latency_ms" in text
    assert "serve_t0_events_total" in text
    assert "serve_latency_ms" in text          # aggregate still there


# ---------------------------------------------------------------------------
# plans integration
# ---------------------------------------------------------------------------


def test_fleet_scorer_resolves_plan_knobs(days, tmp_path):
    st = PlanStore(str(tmp_path / "plans.jsonl"), seeds=False)
    fp = plans.fingerprint(KNOBS["fleet_max_batch"].scope)
    st.record("fleet_max_batch", fp, "*", 512, source="probe")
    with use_store(st):
        fleet, featurizers, _, scorer = _fleet(days, tenants=("t0",))
        try:
            assert scorer.max_batch == 512
            assert scorer.plan["max_batch"]["source"] == "plan"
            assert scorer.plan["max_wait_ms"]["source"] == "default"
        finally:
            scorer.close()
        # A plan flush size past the fleet's total admission capacity
        # would make the max_batch trigger unreachable — degrade to
        # the shipped default.
        st.record("fleet_max_batch", fp, "*", 1 << 20, source="probe")
        metrics = MetricsEmitter(to_stdout=False)
        scorer2 = FleetScorer(
            fleet, featurizers,
            ServingConfig(device_score_min=None), metrics=metrics)
        try:
            assert scorer2.max_batch == ServingConfig.fleet_max_batch
            assert scorer2.plan["max_batch"]["source"] == "default"
        finally:
            scorer2.close()


# ---------------------------------------------------------------------------
# CLI: fleet dry run + live manifest stream
# ---------------------------------------------------------------------------


def test_fleet_dry_run_cli(capsys):
    from oni_ml_tpu.runner import ml_ops

    assert ml_ops.main(
        ["serve", "--dry-run", "--fleet", "synthetic"]) == 0
    last = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(last)
    assert summary["serve_fleet_dry_run"] == "ok"
    assert summary["tenants"] == 2
    assert summary["packed_flushes"] >= 1
    assert summary["versions_served"]["t0"][-1] >= 2
    assert summary["versions_served"]["t1"] == [1]


def test_fleet_dry_run_cli_n_tenants(capsys):
    from oni_ml_tpu.runner import ml_ops

    assert ml_ops.main(
        ["serve", "--dry-run", "--fleet", "synthetic:3"]) == 0
    last = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(last)
    assert summary["serve_fleet_dry_run"] == "ok"
    assert summary["tenants"] == 3
    with pytest.raises(SystemExit):
        ml_ops.main(["serve", "--dry-run", "--fleet", "synthetic:1"])
    with pytest.raises(SystemExit, match="integer"):
        ml_ops.main(["serve", "--dry-run", "--fleet", "synthetic:four"])
    # A REAL manifest under --dry-run must not silently run the
    # synthetic path and report ok about a file it never opened.
    with pytest.raises(SystemExit, match="synthetic"):
        ml_ops.main(["serve", "--dry-run", "--fleet", "/tmp/m.json"])


def _write_day_dir(path, rows, model, dsource="dns"):
    """A minimal completed day directory: results CSVs + features.pkl
    (the three artifacts serve's fleet loader reads)."""
    from oni_ml_tpu.features.dns import featurize_dns
    from oni_ml_tpu.io import formats

    os.makedirs(path, exist_ok=True)
    ips = sorted(model.ip_index, key=model.ip_index.get)
    vocab = sorted(model.word_index, key=model.word_index.get)
    formats.write_doc_results(
        os.path.join(path, "doc_results.csv"), ips, model.theta[:-1])
    formats.write_word_results(
        os.path.join(path, "word_results.csv"), vocab,
        np.log(np.asarray(model.p[:-1], np.float64)).T)
    feats = featurize_dns(rows)
    with open(os.path.join(path, "features.pkl"), "wb") as f:
        pickle.dump(feats, f)


def test_fleet_live_stream_from_manifest(tmp_path, capsys):
    """`ml_ops serve --fleet manifest.json` end to end: two day
    directories, tenant-tagged input lines, per-tenant stream_end
    accounting, rc 0."""
    from oni_ml_tpu.runner import ml_ops

    manifest = {"tenants": []}
    input_lines = []
    for i, t in enumerate(("alpha", "beta")):
        rows, model, _ = _synthetic_day(seed=60 + i)
        day = str(tmp_path / t)
        _write_day_dir(day, rows, model)
        manifest["tenants"].append(
            {"tenant": t, "day_dir": day, "dsource": "dns"})
        input_lines += [f"{t}\t" + ",".join(r) for r in rows[:24]]
    mpath = str(tmp_path / "fleet.json")
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    ipath = str(tmp_path / "events.csv")
    with open(ipath, "w") as f:
        f.write("\n".join(input_lines) + "\n")
    rc = ml_ops.main([
        "serve", "--fleet", mpath, "--input", ipath, "--no-plans",
        "--no-compilation-cache", "--device-score-min", "0",
        "--max-batch", "12",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    end = next(json.loads(ln) for ln in out.splitlines()
               if '"stream_end"' in ln)
    assert end["submitted"] == 48
    assert end["events_scored"] == 48
    # --max-batch reaches the FLEET scorer (48 events / 12 per flush).
    assert end["batches"] >= 4
    plans_rec = next(json.loads(ln) for ln in out.splitlines()
                     if '"event": "plans"' in ln)
    assert plans_rec["knobs"]["max_batch"]["value"] == 12
    per_tenant = {s["tenant"]: s for s in end["tenant_stats"]}
    assert per_tenant["alpha"]["scored"] == 24
    assert per_tenant["beta"]["scored"] == 24
    assert end["final_versions"] == {"alpha": 1, "beta": 1}
    loaded = [json.loads(ln) for ln in out.splitlines()
              if '"model_loaded"' in ln]
    assert {r["tenant"] for r in loaded} == {"alpha", "beta"}
    # A stream whose EVERY line is rejected (untagged lines into a
    # multi-tenant fleet — a framing mismatch) must NOT exit 0.
    bad = str(tmp_path / "untagged.csv")
    with open(bad, "w") as f:
        f.write("\n".join(ln.split("\t", 1)[1]
                          for ln in input_lines[:8]) + "\n")
    rc = ml_ops.main([
        "serve", "--fleet", mpath, "--input", bad, "--no-plans",
        "--no-compilation-cache",
    ])
    capsys.readouterr()
    assert rc == 1


def test_fleet_live_stream_rejects_synthetic_outside_dry_run():
    from oni_ml_tpu.runner import ml_ops

    with pytest.raises(SystemExit, match="dry-run"):
        ml_ops.main(["serve", "--fleet", "synthetic"])


# ---------------------------------------------------------------------------
# load_gen fleet harness + bench_diff serving keys
# ---------------------------------------------------------------------------


def _tools():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = os.path.join(here, "tools")
    if p not in sys.path:
        sys.path.insert(0, p)


def test_parse_mix_and_fleet_mix():
    _tools()
    import load_gen

    assert load_gen.parse_mix("poisson:2,bursty:1") == [
        ("poisson", 2.0), ("bursty", 1.0)]
    assert load_gen.parse_mix("poisson") == [("poisson", 1.0)]
    with pytest.raises(ValueError, match="unknown pattern"):
        load_gen.parse_mix("uniform:1")
    with pytest.raises(ValueError, match="weight"):
        load_gen.parse_mix("poisson:0")
    mix = load_gen.fleet_mix(4, "poisson:3,bursty:1", 4000.0)
    assert [m["pattern"] for m in mix] == [
        "poisson", "bursty", "poisson", "bursty"]
    # Weights split the aggregate offered rate.
    assert sum(m["rate_eps"] for m in mix) == pytest.approx(4000.0)
    assert mix[0]["rate_eps"] == pytest.approx(4000.0 * 3 / 8)


def test_run_fleet_slo_small():
    _tools()
    import load_gen

    res = load_gen.run_fleet_slo(
        2, "poisson:1,bursty:1", n_events=64, rate_eps=5000.0,
        max_batch=32, max_wait_ms=5.0, device_score_min=None,
    )
    assert res["n_tenants"] == 2
    agg = res["aggregate"]
    assert agg["resolved"] == res["n_events"]
    assert agg["errors"] == 0
    assert agg["p99_ms"] is not None
    assert set(res["tenants"]) == {"t0", "t1"}
    for t, summary in res["tenants"].items():
        assert summary["resolved"] == summary["events"]
        assert summary["pattern"] in ("poisson", "bursty")
        assert summary["p50_ms"] is not None
    # The zero-retrace proof rides every payload (0 on a host-pinned
    # run by construction; the field is what the TPU bench gates on).
    assert res["plans"]["retraces_after_warmup"] == 0
    # Measured window only — the warmup burst is excluded.
    assert res["packed"]["events_scored"] == res["n_events"]


def test_bench_diff_serving_latency_directions(tmp_path):
    _tools()
    import bench_diff

    def fleet_payload(p99_t1, eps=4000):
        return {
            "metric": "serving", "value": eps, "unit": "events/sec",
            "secondary": {"serving_slo_fleet": {
                "value": eps, "unit": "events/sec",
                "aggregate": {"sustained_eps": eps, "p50_ms": 10,
                              "p99_ms": 20, "p999_ms": 25},
                "tenants": {
                    "t0": {"sustained_eps": eps / 2, "p99_ms": 20,
                           "p999_ms": 22},
                    "t1": {"sustained_eps": eps / 2, "p99_ms": p99_t1,
                           "p999_ms": 23},
                },
            }},
        }

    # A per-tenant p99 blowup is a REGRESSION (ms = lower-better)...
    rows = bench_diff.diff_payloads(
        fleet_payload(20), fleet_payload(40))
    reg = [r for r in rows if r["regression"]]
    assert [r["name"] for r in reg] == [
        "phase:serving_slo_fleet:tenant.t1.p99_ms"]
    # ...while a p99 IMPROVEMENT of the same magnitude is not.
    rows = bench_diff.diff_payloads(
        fleet_payload(40), fleet_payload(20))
    assert not [r for r in rows if r["regression"]]
    # sustained_eps keeps the higher-better direction.
    rows = bench_diff.diff_payloads(
        fleet_payload(20, eps=4000), fleet_payload(20, eps=2000))
    assert any(r["regression"]
               and r["name"].endswith("sustained_eps")
               for r in rows)
    # serving_slo (single-model) pattern groups compare too.
    old = {"secondary": {"serving_slo": {
        "value": 1, "unit": "events/sec",
        "poisson": {"sustained_eps": 1000, "p99_ms": 5,
                    "p999_ms": 9}}}}
    new = json.loads(json.dumps(old))
    new["secondary"]["serving_slo"]["poisson"]["p999_ms"] = 30
    rows = bench_diff.diff_payloads(old, new)
    assert any(r["regression"] and "p999" in r["name"] for r in rows)


def test_bench_serving_slo_fleet_smoke():
    """bench.py's fleet phase wrapper returns the acceptance payload
    shape: aggregate + >= 4 per-tenant summaries + the plans proof."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if here not in sys.path:
        sys.path.insert(0, here)
    import bench

    res = bench.bench_serving_slo_fleet(
        n_tenants=4, n_events=128, rate_eps=8000.0, max_batch=32,
        max_wait_ms=5.0, device_score_min=None)
    assert res["n_tenants"] == 4
    assert len(res["tenants"]) == 4
    assert res["aggregate"]["resolved"] == res["n_events"]
    assert res["plans"]["retraces_after_warmup"] == 0
