"""Cross-host serving wire (oni_ml_tpu/serving/wire.py + autoscale.py
+ the TCP promotion claims): columnar frame round-trips for every
typed encoding, the columnar<->pickle score parity pins across all
three registered sources, loud rejection of truncated / oversized /
version-drifted frames, the same-host shm ring's wraparound and
concurrent stress contracts, the autoscaler's hysteresis / cooldown /
reaction-clock control law on an injectable clock, and the
concurrent-router failover claim (exactly one winner, both routers'
futures resolve).  All CPU, no markers — the tier-1 cross-host smoke."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from oni_ml_tpu import sources
from oni_ml_tpu.config import ServingConfig
from oni_ml_tpu.parallel.membership import FileKVClient
from oni_ml_tpu.scoring import ScoringModel
from oni_ml_tpu.serving import (
    AutoScaler,
    FleetRouter,
    ReplicaServer,
    ShmRing,
    TenantSpec,
    decode_payload,
    encode_payload,
    score_features,
)
from oni_ml_tpu.serving import wire as wire_mod
from oni_ml_tpu.serving import wire_pickle


# ---------------------------------------------------------------------------
# columnar frame round-trips
# ---------------------------------------------------------------------------


def test_frame_roundtrip_typed_encodings():
    """Every typed encoding survives encode->decode: nd arrays
    (zero-copy, bit-identical), id lists, string tables, split raws,
    cuts tuples, and plain JSON scalars."""
    rng = np.random.default_rng(0)
    msg = {
        "op": "submit_many",
        "tenant": "t0",
        "ids": [7, 8, 9, 10],
        "raws": [["a", "bb", "ccc"], ["dd", ""], ["zzz"]],
        "arr": rng.standard_normal((3, 5)),
        "cuts": (np.arange(4.0), [0.5, 1.5], np.array([9.0])),
        "n": 42,
        "flag": True,
    }
    out = decode_payload(encode_payload(msg))
    assert out["op"] == "submit_many" and out["tenant"] == "t0"
    assert out["n"] == 42 and out["flag"] is True
    assert out["ids"] == msg["ids"]
    assert out["raws"] == [["a", "bb", "ccc"], ["dd", ""], ["zzz"]]
    np.testing.assert_array_equal(out["arr"], msg["arr"])
    assert len(out["cuts"]) == 3
    for got, want in zip(out["cuts"], msg["cuts"]):
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want, np.float64))


def test_frame_roundtrip_model_and_scores_bit_identical():
    """A ScoringModel column set and a coalesced score batch (floats
    + interleaved errors) round-trip bit-identically — float64 scores
    never reformat on the wire."""
    rng = np.random.default_rng(1)
    model = ScoringModel.from_results(
        ["10.0.0.1", "10.0.0.2"], rng.dirichlet(np.ones(3), size=2),
        ["w0", "w1", "w2", "w3"],
        rng.dirichlet(np.ones(4), size=3).T, fallback=0.05,
    )
    out = decode_payload(encode_payload({"op": "add", "model": model}))
    m = out["model"]
    np.testing.assert_array_equal(np.asarray(m.theta),
                                  np.asarray(model.theta))
    np.testing.assert_array_equal(np.asarray(m.p), np.asarray(model.p))
    assert m.ip_index == model.ip_index
    assert m.word_index == model.word_index

    batch = [
        {"id": 1, "score": float(np.nextafter(0.1, 1.0)), "version": 3},
        {"id": 2, "error": "boom"},
        {"id": 3, "score": -1.5e-300, "version": 1},
    ]
    got = decode_payload(encode_payload(batch))
    assert got[0] == batch[0]   # == on floats: bit-identical or bust
    assert got[1] == {"id": 2, "error": "boom"}
    assert got[2] == batch[2]


def test_pickle_fallback_gated_by_negotiated_codec():
    """A non-columnar frame decodes only on a link whose negotiation
    settled on the pickle fallback; on a columnar link it is rejected
    outright — the receiver never sniffs its way into the unpickler."""
    msg = {"op": "stats", "x": [1, 2, 3]}
    blob = wire_pickle.encode_payload(msg)
    assert bytes(blob[:4]) != wire_mod.MAGIC
    assert decode_payload(blob, codec="pickle") == msg
    with pytest.raises(ConnectionError, match="did not negotiate"):
        decode_payload(blob)


def test_fallback_unpickler_refuses_code_execution_gadgets():
    """Even a negotiated-fallback link never executes frame bytes:
    the allowlisted unpickler refuses globals outside the wire's
    legitimate vocabulary, so an os.system reduce gadget fails the
    decode instead of running."""
    import os
    import pickle as _pickle

    class Evil:
        def __reduce__(self):
            return (os.system, ("true",))

    blob = _pickle.dumps(Evil())
    with pytest.raises(ConnectionError, match="allowlist"):
        wire_pickle.decode_payload(blob)
    with pytest.raises(_pickle.UnpicklingError, match="allowlist"):
        wire_pickle.decode_opaque(blob)
    # The legitimate vocabulary still round-trips.
    arr = np.arange(4.0)
    np.testing.assert_array_equal(
        wire_pickle.decode_opaque(wire_pickle.encode_opaque(arr)), arr)


# ---------------------------------------------------------------------------
# malformed-frame rejection
# ---------------------------------------------------------------------------


def test_truncated_and_drifted_frames_rejected():
    frame = encode_payload({"op": "x", "arr": np.arange(6.0)})
    # Truncated anywhere: header, descriptors, meta, or column bytes.
    for cut in (2, wire_mod._HDR.size - 1, len(frame) - 3):
        with pytest.raises(ConnectionError):
            decode_payload(frame[:cut])
    # Trailing junk is length drift, not silently ignored padding.
    with pytest.raises(ConnectionError):
        decode_payload(frame + b"\0\0")


def test_version_mismatch_and_unknown_kind_rejected():
    frame = bytearray(encode_payload({"op": "x"}))
    vers = frame[:]
    vers[4] = wire_mod.WIRE_VERSION + 1   # !4sBBHI — byte 4 = version
    with pytest.raises(ConnectionError, match="version"):
        decode_payload(bytes(vers))
    kind = frame[:]
    kind[5] = 250                         # byte 5 = frame kind
    with pytest.raises(ConnectionError, match="kind"):
        decode_payload(bytes(kind))


def test_malformed_columnar_frames_fail_as_connection_error():
    """Hostile descriptors — meta referencing a missing column, a
    garbage dtype string — surface as the wire's uniform
    ConnectionError, never a TypeError/ValueError/KeyError that would
    escape a reader thread's ``except (ConnectionError, OSError)``."""
    missing = wire_mod._frame(wire_mod.KIND_MSG,
                              {"f": {}, "e": {"x": "nd"}}, [])
    with pytest.raises(ConnectionError):
        decode_payload(missing)
    good = bytearray(encode_payload({"op": "x", "arr": np.arange(4.0)}))
    i = good.index(b"<f8")
    good[i:i + 3] = b"zzz"       # np.dtype("zzz") raises TypeError
    with pytest.raises(ConnectionError):
        decode_payload(bytes(good))


def test_oversized_announcement_rejected_before_allocation():
    """A length prefix announcing more than MAX_FRAME_BYTES fails the
    read loudly instead of allocating by attacker."""
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack("!I", wire_mod.MAX_FRAME_BYTES + 1))
        with pytest.raises(ConnectionError, match="oversized"):
            wire_mod.recv_frame(b)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# columnar <-> pickle parity pins (dns / flow / proxy)
# ---------------------------------------------------------------------------


def _source_tenant(dsource: str, seed: int):
    """One tenant's day from the source registry: raw benign rows,
    the trained-yesterday model over the day's actual key/word
    populations, and the pinned cuts."""
    src = sources.get(dsource)
    rows = src.synth_benign(32, seed=seed)
    feats = src.featurize(rows)
    cuts = src.cuts_of(feats)
    keys, vocab = set(), set()
    for ks, words in src.event_pairs(feats):
        keys.update(ks)
        vocab.update(words)
    rng = np.random.default_rng(seed)
    k = 4
    model = ScoringModel.from_results(
        sorted(keys), rng.dirichlet(np.ones(k), size=len(keys)),
        sorted(vocab),
        rng.dirichlet(np.ones(len(vocab)), size=k).T, fallback=0.05,
    )
    return rows, model, cuts, feats


def _routed_scores(cfg: ServingConfig, tenants: dict) -> dict:
    replica = ReplicaServer("r0", cfg)
    router = FleetRouter(cfg)
    try:
        router.connect_replica("r0", replica.host, replica.port)
        for name, (rows, model, cuts, _) in tenants.items():
            router.add_tenant(
                TenantSpec(tenant=name, dsource=name), cuts, model)
        router.start(warmup=False)
        futs = {name: router.submit_many(name, rows)
                for name, (rows, _, _, _) in tenants.items()}
        router.flush()
        return {name: np.array([f.result(timeout=30.0)[0]
                                for f in fs])
                for name, fs in futs.items()}
    finally:
        router.close()
        replica.stop()


def test_wire_parity_pin_all_sources_columnar_vs_pickle():
    """THE byte-parity pin (acceptance criteria): for every registered
    source, scores routed over the columnar wire are bit-identical to
    the same census over the negotiated pickle wire AND to the
    in-process oracle."""
    tenants = {name: _source_tenant(name, seed)
               for seed, name in enumerate(("dns", "flow", "proxy"))}
    base = dict(fleet_max_batch=64, fleet_max_wait_ms=5.0,
                device_score_min=None)
    columnar = _routed_scores(
        ServingConfig(wire_format="columnar", **base), tenants)
    fallback = _routed_scores(
        ServingConfig(wire_format="pickle", **base), tenants)
    for name, (_, model, _, feats) in tenants.items():
        oracle = score_features(model, feats, name, device_min=None)
        np.testing.assert_array_equal(columnar[name], oracle)
        np.testing.assert_array_equal(fallback[name], oracle)


# ---------------------------------------------------------------------------
# receive-side pickle gating (the replica's ports)
# ---------------------------------------------------------------------------


def test_unnegotiated_pickle_frame_drops_the_connection():
    """A peer that skips negotiation and throws a pickle frame at a
    columnar replica gets its connection dropped — the frame is never
    unpickled (default wire_accept_pickle=False)."""
    rep = ReplicaServer("r0", ServingConfig())
    try:
        s = socket.create_connection((rep.host, rep.port))
        try:
            payload = wire_pickle.encode_payload(
                {"op": "ping", "id": 1})
            s.sendall(struct.pack("!I", len(payload)) + payload)
            with pytest.raises(ConnectionError):
                wire_mod.recv_frame(s)    # replica hung up, no reply
        finally:
            s.close()
    finally:
        rep.stop()


def test_hello_pickle_only_offer_refused_unless_accepted():
    """The hello negotiation is the only gate into the fallback: a
    pickle-only offer is an error under the default config and only
    negotiates the fallback when wire_accept_pickle is on."""
    rep = ReplicaServer("r0", ServingConfig())
    try:
        s = socket.create_connection((rep.host, rep.port))
        try:
            wire_mod.send_frame(
                s, {"op": "hello", "id": 1, "wire": ["pickle"]})
            rsp = wire_mod.recv_frame(s)
            assert "wire_accept_pickle" in rsp["error"]
        finally:
            s.close()
    finally:
        rep.stop()
    rep = ReplicaServer("r1", ServingConfig(wire_accept_pickle=True))
    try:
        s = socket.create_connection((rep.host, rep.port))
        try:
            wire_mod.send_frame(
                s, {"op": "hello", "id": 1, "wire": ["pickle"]})
            rsp = wire_mod.recv_frame(s)
            assert rsp["wire"] == "pickle" and rsp["ok"]
        finally:
            s.close()
    finally:
        rep.stop()


def test_hello_rings_are_torn_down_with_their_connection():
    """Rings negotiated by a connection's hello die with the
    connection (and a repeat hello replaces, not accumulates) — a
    reconnecting or SIGKILL'd router leaks no shm segments or polling
    threads on the replica."""
    rep = ReplicaServer("r0", ServingConfig())
    try:
        hello = {"op": "hello", "wire": ["columnar"], "shm": True,
                 "host": socket.gethostname()}
        s = socket.create_connection((rep.host, rep.port))
        try:
            wire_mod.send_frame(s, {**hello, "id": 1})
            rsp = wire_mod.recv_frame(s)
            assert rsp["shm"] is not None
            assert len(rep._rings) == 2
            # Second hello on the same connection: replaced, not
            # appended.
            wire_mod.send_frame(s, {**hello, "id": 2})
            rsp2 = wire_mod.recv_frame(s)
            assert rsp2["shm"] is not None
            assert rsp2["shm"]["c2s"] != rsp["shm"]["c2s"]
            assert len(rep._rings) == 2
        finally:
            s.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and rep._rings:
            time.sleep(0.01)
        assert rep._rings == []
    finally:
        rep.stop()


# ---------------------------------------------------------------------------
# shm ring
# ---------------------------------------------------------------------------


def test_shm_ring_wraparound_orders_and_survives_reuse():
    """Sequence numbers run far past the two physical slabs: every
    frame arrives intact, in order, with sizes spanning empty to
    nearly slab-filling — slab reuse never overwrites an unread
    frame."""
    ring = ShmRing.create(slab_bytes=4096)
    peer = ShmRing.attach(ring.name, 4096)
    try:
        rng = np.random.default_rng(2)
        sizes = [int(s) for s in rng.integers(0, 4000, size=64)]
        payloads = [bytes(rng.integers(0, 256, size=s, dtype=np.uint8))
                    for s in sizes]
        got = []

        def consume():
            while len(got) < len(payloads):
                p = peer.pop(timeout_s=5.0)
                assert p is not None
                got.append(p)

        t = threading.Thread(target=consume)
        t.start()
        for p in payloads:
            assert ring.push(p, timeout_s=5.0)
        t.join(timeout=30.0)
        assert not t.is_alive()
        assert got == payloads
        with pytest.raises(ValueError, match="exceeds ring slab"):
            ring.push(b"x" * 4097)
    finally:
        peer.close()
        ring.close()


def test_shm_ring_concurrent_stress_columnar_frames():
    """Producer/consumer threads under real columnar frames: 300
    variable score batches cross the ring bit-identically while the
    producer backpressures on the two-slab window."""
    ring = ShmRing.create(slab_bytes=1 << 16)
    peer = ShmRing.attach(ring.name, 1 << 16)
    sent = []
    rng = np.random.default_rng(3)
    for i in range(300):
        n = int(rng.integers(1, 64))
        sent.append([{"id": 1000 * i + j,
                      "score": float(rng.standard_normal()),
                      "version": i} for j in range(n)])
    got = []
    try:
        def consume():
            while len(got) < len(sent):
                p = peer.pop(timeout_s=10.0)
                assert p is not None
                got.append(decode_payload(p))

        t = threading.Thread(target=consume)
        t.start()
        for batch in sent:
            assert ring.push(encode_payload(batch), timeout_s=10.0)
        t.join(timeout=60.0)
        assert not t.is_alive()
        assert got == sent
    finally:
        peer.close()
        ring.close()


def test_shm_ring_stuck_seqlock_times_out_and_closes():
    """A peer SIGKILL'd between its seqlock guard writes leaves the
    guard odd forever: the survivor's read bounds out, marks the ring
    closed, and degrades instead of busy-looping at 100% CPU."""
    ring = ShmRing.create(slab_bytes=1024)
    peer = ShmRing.attach(ring.name, 1024)
    try:
        peer._SEQLOCK_STUCK_S = 0.2
        # Producer dies mid-_locked_write: pseq stays odd.
        ring._write_u64(wire_mod._OFF_PSEQ, 1)
        t0 = time.monotonic()
        assert peer.pop(timeout_s=30.0) is None
        assert time.monotonic() - t0 < 5.0
        assert peer.closed
        # Both ends now see the ring dead and return immediately.
        assert peer.pop(timeout_s=1.0) is None
        assert not ring.push(b"x", timeout_s=0.2)
    finally:
        peer.close()
        ring.close()


def test_shm_ring_close_unblocks_peer():
    ring = ShmRing.create(slab_bytes=1024)
    peer = ShmRing.attach(ring.name, 1024)
    try:
        assert ring.push(b"last", timeout_s=1.0)
        ring.close()
        # Pending frames still drain after close...
        assert peer.pop(timeout_s=1.0) == b"last"
        # ...then the peer sees shutdown, not a hang.
        assert peer.pop(timeout_s=1.0) is None
        assert peer.closed
        assert not peer.push(b"x", timeout_s=0.2)
    finally:
        peer.close()
        ring.close()


# ---------------------------------------------------------------------------
# autoscaler control law (fake router, injectable clock)
# ---------------------------------------------------------------------------


class _FakeRouter:
    """stats()-shaped occupancy the tests steer directly."""

    def __init__(self, replicas, cap=8):
        self.replicas = list(replicas)
        self.cap = cap
        self.occupancy = 0
        self.joined = []
        self.drained = []

    def stats(self):
        return {
            "replicas": list(self.replicas),
            "max_inflight": self.cap,
            "edges": {r: {"inflight": self.occupancy
                          // max(1, len(self.replicas)),
                          "events": 0, "admission_stall_s": 0.0}
                      for r in self.replicas},
        }

    def join_replica(self, rid, host, port):
        self.replicas.append(rid)
        self.joined.append(rid)

    def drain_replica(self, rid):
        self.replicas.remove(rid)
        self.drained.append(rid)


def _scaler(router, **cfg_kw):
    cfg = ServingConfig(
        autoscale_interval_s=0.5, autoscale_halflife_s=1.0,
        autoscale_cooldown_s=5.0, autoscale_high=0.75,
        autoscale_low=0.25, autoscale_max_replicas=4, **cfg_kw)
    counter = {"n": 0}

    def spawn():
        counter["n"] += 1
        rid = f"as{counter['n']}"
        return rid, "127.0.0.1", 0

    return AutoScaler(router, spawn=spawn,
                      stop=lambda rid: None, config=cfg)


def test_autoscaler_hysteresis_ewma_and_reaction_clock():
    """The EWMA delays the decision past the first raw breach (no
    flap on one bursty sample) and reaction_s measures breach ->
    join, not zero."""
    router = _FakeRouter(["r0"], cap=8)
    sc = _scaler(router)
    # Seed the EWMA low: in-band, no action.
    router.occupancy = 4            # util 0.5 of 1x8
    assert sc.tick(now=0.0)["action"] == "hold"
    # Raw breach at t=1: EWMA (0.5 -> 0.625) still under 0.75.
    router.occupancy = 8            # util 1.0
    d = sc.tick(now=1.0)
    assert d["action"] == "hold" and d["util"] == 1.0
    # Breach persists: EWMA crosses, the join fires, and the
    # reaction clock started at the FIRST raw breach (t=1).
    d = sc.tick(now=2.0)
    assert d["action"] == "up" and router.joined == ["as1"]
    assert d["reaction_s"] == pytest.approx(1.0)


def test_autoscaler_cooldown_max_replicas_and_owned_drain():
    router = _FakeRouter(["r0"], cap=8)
    sc = _scaler(router)
    # Saturated on the very first sample: the seed EWMA IS the raw
    # sample, so the join fires without the smoothing delay.
    router.occupancy = 8
    assert sc.tick(now=0.0)["action"] == "up"
    # Cooldown: still saturated, but the controller only observes.
    d = sc.tick(now=1.0)
    assert (d["action"], d["reason"]) == ("hold", "cooldown")
    # After cooldown the grown fleet is saturated again -> up (the
    # EWMA restarted from None after the join, so it reseeds hot).
    router.occupancy = 16
    assert sc.tick(now=6.0)["action"] == "up"
    router.occupancy = 96
    assert sc.tick(now=12.0)["action"] == "up"
    # At max_replicas the controller reports the ceiling, not a spawn.
    d = sc.tick(now=18.0)
    assert d["action"] == "hold" and d["reason"] == "at max_replicas"
    assert router.replicas == ["r0", "as1", "as2", "as3"]
    # Drain: LIFO over OWNED replicas only, down to the floor — r0
    # (operator-connected) is never drained.
    router.occupancy = 0
    for i in range(40):
        sc.tick(now=20.0 + 6.0 * i)
        if not sc._owned:
            break
    assert router.drained == ["as3", "as2", "as1"]
    assert router.replicas == ["r0"]
    d = sc.tick(now=500.0)
    assert d["action"] == "hold"    # nothing owned, floor holds


def test_autoscaler_decisions_are_journaled():
    journal = []

    class _J:
        def append(self, rec):
            journal.append(rec)

    router = _FakeRouter(["r0"], cap=8)
    sc = AutoScaler(router, spawn=lambda: ("a", "h", 0),
                    stop=lambda rid: None,
                    config=ServingConfig(), journal=_J())
    router.occupancy = 0
    sc.tick(now=0.0)
    assert journal and journal[-1]["kind"] == "autoscale"
    assert {"action", "util", "util_ewma", "replicas",
            "occupancy"} <= set(journal[-1])


# ---------------------------------------------------------------------------
# concurrent-router failover claim
# ---------------------------------------------------------------------------


def test_claim_promotion_error_taxonomy():
    """Only a genuine ALREADY_EXISTS loses the promotion election; a
    KV transport failure mid-failover claims by default — duplicate
    backfills are router_version-idempotent on the replica, zero
    backfills silently lose the promoted tenants' data path."""
    from oni_ml_tpu.parallel.membership import MembershipClient

    class _DeadKV:
        def key_value_set(self, *a, **k):
            raise RuntimeError("connection refused")

    class _TakenKV:
        def key_value_set(self, *a, **k):
            raise RuntimeError("ALREADY_EXISTS: oni/fleet/promote/r0")

    assert MembershipClient(_DeadKV()).claim_promotion(
        "r0", "ra") is True
    assert MembershipClient(_TakenKV()).claim_promotion(
        "r0", "ra") is False


def test_concurrent_router_failover_single_claim_both_resolve(tmp_path):
    """Two routers over the same membership both see the replica die:
    exactly ONE wins the first-writer promotion claim (the other's
    failover record carries claimed=false), and BOTH routers' in-flight
    futures resolve bit-identically — no event is lost to losing the
    race."""
    cfg = ServingConfig(fleet_max_batch=32, fleet_max_wait_ms=5.0,
                        device_score_min=None)
    kv_dir = str(tmp_path / "kv")
    tenants = {f"t{i}": _source_tenant("dns", seed=10 + i)
               for i in range(4)}
    replicas = {f"r{i}": ReplicaServer(f"r{i}", cfg,
                                       kv=FileKVClient(kv_dir))
                for i in range(3)}
    journals = {"ra": [], "rb": []}

    class _J:
        def __init__(self, sink):
            self.sink = sink

        def append(self, rec):
            self.sink.append(rec)

    routers = {}
    try:
        for name in ("ra", "rb"):
            r = FleetRouter(cfg, kv=FileKVClient(kv_dir),
                            router_id=name,
                            journal=_J(journals[name]))
            for rid, rep in replicas.items():
                r.connect_replica(rid, rep.host, rep.port)
            for t, (rows, model, cuts, _) in tenants.items():
                r.add_tenant(TenantSpec(tenant=t, dsource="dns"),
                             cuts, model)
            r.start(warmup=False)
            routers[name] = r
        victim = routers["ra"].placement()["t0"].primary
        futs = {name: {t: r.submit_many(t, tenants[t][0])
                       for t in tenants}
                for name, r in routers.items()}
        replicas[victim].kill()
        for r in routers.values():
            r.flush()
        time.sleep(0.1)
        for r in routers.values():
            r.flush()
        for name, r in routers.items():
            for t, fs in futs[name].items():
                got = np.array([f.result(timeout=30.0)[0]
                                for f in fs])
                _, model, _, feats = tenants[t]
                np.testing.assert_array_equal(
                    got,
                    score_features(model, feats, "dns",
                                   device_min=None))
        # Exactly one claim winner across the two routers.
        deadline = time.monotonic() + 15.0
        claims = []
        while time.monotonic() < deadline:
            claims = [rec["claimed"]
                      for recs in journals.values() for rec in recs
                      if rec.get("kind") == "failover"
                      and "event" not in rec]
            if len(claims) >= 2:
                break
            time.sleep(0.05)
        assert len(claims) == 2
        assert sorted(claims) == [False, True]
    finally:
        for r in routers.values():
            r.close()
        for rep in replicas.values():
            rep.stop()
