"""The composed standing service (PR 20): train/serve co-scheduling,
multi-tenant fleet composition with per-tenant freshness ledgers, the
drain/publish-race convergence on the router, and chaos during a
refresh.

The contracts pinned hardest:

* co-scheduler priority — a waiting serve slot blocks the NEXT train
  chunk (never the current one), a train fit in flight delays a serve
  slot by at most one chunk wall, and the starvation cap bounds how
  long a saturated serve side can lock training out;
* per-tenant event-time freshness — a tenant replaying YESTERDAY's
  events next to a tenant replaying today's must get freshness
  numbers off its OWN clock, not the fleet's newest slice;
* drain/publish race — a publish whose fan-out raced a re-placement
  converges: every live route/shadow holder ends on the latest
  version, verified end-to-end by the version a scored future reports;
* chaos during refresh — killing the primary mid-refresh drops zero
  score futures and the in-flight fit's publish lands exactly once
  through the promoted shadow (journal kinds: failover, cosched,
  freshness).
"""

import dataclasses
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from oni_ml_tpu.config import (  # noqa: E402
    ContinuousConfig,
    PipelineConfig,
    ServingConfig,
)
from oni_ml_tpu.runner.continuous import (  # noqa: E402
    FleetContinuousService,
    IngestSlice,
    interleave_streams,
    paced_tagged,
)
from oni_ml_tpu.serving import (  # noqa: E402
    CoScheduler,
    FleetRouter,
    ReplicaServer,
    TenantSpec,
)


# ---------------------------------------------------------------------------
# CoScheduler
# ---------------------------------------------------------------------------


def test_cosched_refresh_active_brackets_fit():
    cs = CoScheduler()
    assert not cs.refresh_active
    with cs.train_fit("t"):
        assert cs.refresh_active
    assert not cs.refresh_active
    s = cs.summary()
    assert s["train_chunks"] == 0 and s["serve_slots"] == 0


def test_cosched_waiting_serve_blocks_next_chunk():
    """A serve slot HELD keeps the next train chunk out (and counts
    one contended yield); the chunk proceeds once the slot clears."""
    cs = CoScheduler()
    entered = threading.Event()

    def one_chunk():
        with cs.train_fit("t"):
            with cs.train_chunk():
                entered.set()

    th = threading.Thread(target=one_chunk)
    with cs.serve_slot():
        th.start()
        assert not entered.wait(0.25), "chunk ran under a live slot"
    assert entered.wait(5.0), "chunk never ran after slot release"
    th.join(timeout=5.0)
    s = cs.summary()
    assert s["yields"] == 1
    assert s["yield_wait_s"] > 0


def test_cosched_serve_waits_at_most_one_chunk():
    """A serve slot arriving mid-chunk waits for THAT chunk only: the
    train side parks before its next chunk while the slot runs."""
    cs = CoScheduler()
    stop = threading.Event()
    in_chunk = threading.Event()

    def train():
        with cs.train_fit("t"):
            while not stop.is_set():
                with cs.train_chunk():
                    in_chunk.set()
                    time.sleep(0.05)

    th = threading.Thread(target=train)
    th.start()
    try:
        for _ in range(3):
            assert in_chunk.wait(5.0)
            in_chunk.clear()
            with cs.serve_slot():
                # Slot held: no chunk is active underneath us.
                assert not cs._train_active
    finally:
        stop.set()
        th.join(timeout=5.0)
    s = cs.summary()
    assert s["serve_slots"] == 3
    assert s["preempts"] >= 1          # at least one contended wait
    assert s["preempt_wait_p99_s"] is None or \
        s["preempt_wait_p99_s"] < 5.0


def test_cosched_starvation_cap_bounds_train_wait():
    """A serve side that never drains cannot lock training out past
    the starvation deadline."""
    cs = CoScheduler(starvation_s=0.2)
    holding = threading.Event()
    release = threading.Event()

    def serve_hold():
        with cs.serve_slot():
            holding.set()
            release.wait(5.0)

    th = threading.Thread(target=serve_hold)
    th.start()
    assert holding.wait(5.0)
    t0 = time.perf_counter()
    with cs.train_fit("t"):
        with cs.train_chunk():
            waited = time.perf_counter() - t0
    release.set()
    th.join(timeout=5.0)
    assert 0.15 <= waited < 2.0, waited


def test_cosched_journals_fit_rollup():
    from oni_ml_tpu.telemetry import Recorder

    records = []

    class _J:
        def append(self, rec, sync=False):
            records.append(rec)

    cs = CoScheduler(recorder=Recorder(), journal=_J())
    with cs.train_fit("acme"):
        with cs.train_chunk():
            pass
    fits = [r for r in records
            if r.get("kind") == "cosched" and r.get("event") == "fit"]
    assert len(fits) == 1
    assert fits[0]["tenant"] == "acme"
    assert fits[0]["chunks"] == 1


# ---------------------------------------------------------------------------
# fleet composition helpers
# ---------------------------------------------------------------------------


def _flow_line(rng, sip, dip, dport, h=None):
    h = int(rng.integers(0, 24)) if h is None else h
    return (
        "2016-01-22 00:00:00,2016,1,22,"
        f"{h},{int(rng.integers(0, 60))},{int(rng.integers(0, 60))},0.0,"
        f"{sip},{dip},{int(rng.integers(1024, 60000))},{dport},TCP,,0,0,"
        f"{int(rng.integers(1, 100))},{int(rng.integers(40, 100000))},"
        "0,0,0,0,0,0,0,0,0"
    )


def _slice(rng, idx, n=120, t_base=0.0):
    ports = (80, 443, 22, 53)
    lines = [
        _flow_line(rng, f"10.0.0.{int(rng.integers(0, 24))}",
                   f"10.1.0.{int(rng.integers(0, 12))}",
                   ports[int(rng.integers(0, len(ports)))])
        for _ in range(n)
    ]
    return IngestSlice(lines=lines, t0=t_base + idx * 600.0,
                       t1=t_base + (idx + 1) * 600.0, index=idx)


def _fleet_config(tmp_path):
    config = PipelineConfig(
        data_dir=str(tmp_path),
        continuous=ContinuousConfig(
            window_s=1800.0, refresh_every_s=1200.0,
            min_refresh_docs=8, drift_tol_nats=0.8,
            drift_min_history=2, vocab_floor=512, batch_size=64,
            holdout_frac=0.3,
        ),
    )
    return dataclasses.replace(
        config,
        lda=dataclasses.replace(config.lda, num_topics=4,
                                em_max_iters=20),
        serving=ServingConfig(fleet_max_batch=32, fleet_max_wait_ms=5.0,
                              device_score_min=None),
    )


def _journal_kinds(path):
    kinds = set()
    with open(path) as f:
        for ln in f:
            try:
                kinds.add(json.loads(ln).get("kind"))
            except json.JSONDecodeError:
                pass
    return kinds


# ---------------------------------------------------------------------------
# per-tenant freshness (satellite: the ledger is per tenant, not global)
# ---------------------------------------------------------------------------


def test_fleet_per_tenant_event_freshness(tmp_path):
    """A tenant replaying YESTERDAY (event clock 24h behind) next to a
    tenant replaying today must get event-time freshness off its OWN
    slice clock: a global clock would charge the lagging tenant ~24h
    of staleness at every publish."""
    config = _fleet_config(tmp_path)
    fleet = FleetContinuousService(
        config, {"today": "flow", "yday": "flow"},
        out_dir=str(tmp_path / "fleet"), coscheduler=True,
        warmup_refreshes=2,
    )
    rng = np.random.default_rng(3)
    lag = 86400.0
    try:
        for idx in range(6):
            fleet.ingest("today", _slice(rng, idx))
            fleet.ingest("yday", _slice(rng, idx, t_base=-lag))
    finally:
        payload = fleet.close()
    t_today = payload["tenants"]["today"]
    t_yday = payload["tenants"]["yday"]
    assert t_today["freshness_samples"] > 0
    assert t_yday["freshness_samples"] > 0
    # Per-tenant clock: the lagging tenant's event freshness is the
    # cadence lag + refresh wall (minutes), nowhere near 24h.
    assert t_yday["freshness_event_p99_min"] < lag / 60.0 / 4
    assert t_yday["freshness_event_p99_min"] < 120.0
    # The shared journal's freshness records are tenant-keyed.
    jpath = os.path.join(str(tmp_path / "fleet"), "run_journal.jsonl")
    tenants_seen = set()
    with open(jpath) as f:
        for ln in f:
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "freshness":
                tenants_seen.add(rec.get("tenant"))
    assert tenants_seen == {"today", "yday"}
    # Both tenants trained and published through the shared cosched.
    assert payload["cosched"]["train_chunks"] > 0
    assert payload["publishes"] >= 2
    assert payload["refresh_errors"] == 0


# ---------------------------------------------------------------------------
# drain/publish race (satellite: fan-out vs live membership)
# ---------------------------------------------------------------------------


def _dns_fleet(n_replicas=3, journal=None):
    from oni_ml_tpu.runner.serve import _synthetic_day

    cfg = ServingConfig(fleet_max_batch=32, fleet_max_wait_ms=5.0,
                        device_score_min=None)
    replicas = {
        f"r{i}": ReplicaServer(f"r{i}", cfg) for i in range(n_replicas)
    }
    router = FleetRouter(cfg, journal=journal)
    for rid, rep in replicas.items():
        router.connect_replica(rid, rep.host, rep.port)
    rows, model, cuts = _synthetic_day(n_events=48, seed=11)
    router.add_tenant(TenantSpec(tenant="t0", dsource="dns"), cuts,
                      model)
    router.start(warmup=False)
    return replicas, router, rows, model


def test_publish_converges_stale_fanout_target(tmp_path):
    """The drain/publish race, distilled: a route/shadow holder whose
    push was lost (simulated by erasing its ledger entry) must be
    re-pushed by the convergence pass — verified end-to-end by the
    version a scored future reports."""
    replicas, router, rows, model = _dns_fleet()
    try:
        v = router.publish("t0", model, source="test")
        primary = router.placement()["t0"].primary
        # Simulate the lost push the race produces: the ledger says
        # the primary never got v (concurrent drain re-routed the
        # fan-out past it).
        with router._cond:
            router._hosted[primary].pop("t0", None)
        router._converge_publish("t0", v)
        with router._cond:
            assert router._hosted[primary].get("t0") == v
        fut = router.submit("t0", rows[0])
        router.flush()
        _, got_version = fut.result(timeout=60.0)
        assert got_version == v
    finally:
        router.close()
        for rep in replicas.values():
            rep.stop()


def test_publish_during_drain_converges(tmp_path):
    """Publish fanning out WHILE the primary drains: whatever
    interleaving the scheduler picks, every live route/shadow holder
    ends on the latest version and a scored future serves it."""
    replicas, router, rows, model = _dns_fleet()
    try:
        primary = router.placement()["t0"].primary
        versions = []

        def do_publish():
            versions.append(router.publish("t0", model, source="race"))

        th = threading.Thread(target=do_publish)
        th.start()
        drained = router.drain_replica(primary)
        th.join(timeout=60.0)
        assert not th.is_alive()
        assert drained["moved"] >= 1
        v = versions[0]
        place = router.placement()["t0"]
        with router._cond:
            for r in (place.primary, place.shadow):
                if r and r in router._links:
                    assert router._hosted[r].get("t0", 0) >= v, (
                        r, dict(router._hosted)
                    )
        fut = router.submit("t0", rows[1])
        router.flush()
        _, got_version = fut.result(timeout=60.0)
        assert got_version >= v
    finally:
        router.close()
        for rep in replicas.values():
            rep.stop()


# ---------------------------------------------------------------------------
# chaos during refresh (satellite: SIGKILL mid-chunk, zero drops)
# ---------------------------------------------------------------------------


def test_chaos_kill_primary_mid_refresh(tmp_path):
    """Kill a tenant's primary replica while its refresh fit is
    mid-chunk (ReplicaServer.kill: SIGKILL minus the process — the
    composed bench does the real-subprocess variant).  The contract:
    zero failed score futures (admission-journal replay through the
    promoted shadow), the in-flight fit's publish lands exactly once
    (versions stay monotone and consistent), and the journal pins the
    episode via its failover/cosched/freshness kinds."""
    from oni_ml_tpu.telemetry import Journal

    config = _fleet_config(tmp_path)
    scfg = config.serving
    replicas = {f"r{i}": ReplicaServer(f"r{i}", scfg) for i in range(3)}
    router_journal = Journal(str(tmp_path / "router_journal.jsonl"))
    router = FleetRouter(scfg, journal=router_journal)
    for rid, rep in replicas.items():
        router.connect_replica(rid, rep.host, rep.port)
    fleet = FleetContinuousService(
        config, {"acme": "flow", "globex": "flow"},
        out_dir=str(tmp_path / "fleet"), router=router,
        coscheduler=True, warmup_refreshes=2,
    )
    rng = np.random.default_rng(5)
    killed = None
    try:
        for idx in range(4):
            for t in ("acme", "globex"):
                fleet.ingest(t, _slice(rng, idx))
        deadline = time.time() + 120.0
        while not fleet.binding.ready("acme"):
            assert time.time() < deadline, "router never bootstrapped"
            time.sleep(0.1)
        idx = 4
        deadline = time.time() + 180.0
        while idx < 40 and time.time() < deadline:
            for t in ("acme", "globex"):
                fleet.ingest(t, _slice(rng, idx))
            if killed is None and fleet.cosched.refresh_active:
                # A refresh fit is mid-chunk RIGHT NOW: kill acme's
                # primary with scores and the fit both in flight.
                victim = router.placement()["acme"].primary
                replicas[victim].kill()
                killed = (victim, idx)
            if killed is not None and idx - killed[1] >= 4:
                break
            idx += 1
        assert killed is not None, "no refresh ever active during feed"
    finally:
        payload = fleet.close()
        router.close()
        for rep in replicas.values():
            rep.stop()
        router_journal.close()
    # Zero dropped score futures across the kill.
    assert payload["serving"]["failed_futures"] == 0, payload["serving"]
    assert payload["serving"]["events_scored"] > 0
    # The in-flight fit published exactly once: the binding's version
    # census matches the router's, monotone, no double-publish.
    for t in ("acme", "globex"):
        assert (payload["serving"]["versions"][t]
                == router._tenants[t]["version"])
    assert payload["refresh_errors"] == 0
    assert len(payload["router"]["failovers"]) >= 1
    # Journal pins: the episode is reconstructable from kinds.
    assert "failover" in _journal_kinds(
        str(tmp_path / "router_journal.jsonl"))
    fleet_kinds = _journal_kinds(
        os.path.join(str(tmp_path / "fleet"), "run_journal.jsonl"))
    assert {"cosched", "freshness"} <= fleet_kinds


# ---------------------------------------------------------------------------
# stream interleaving helpers
# ---------------------------------------------------------------------------


def test_interleave_streams_orders_by_event_time():
    a = [IngestSlice(lines=["x"], t0=0, t1=600, index=0),
         IngestSlice(lines=["x"], t0=1200, t1=1800, index=1)]
    b = [IngestSlice(lines=["y"], t0=600, t1=1200, index=0)]
    tagged = interleave_streams({"a": a, "b": b})
    assert [(t, sl.t1) for t, sl in tagged] == [
        ("a", 600), ("b", 1200), ("a", 1800)]


def test_paced_tagged_stamps_arrivals():
    a = [IngestSlice(lines=["x"], t0=0, t1=600, index=0)]
    b = [IngestSlice(lines=["y"], t0=600, t1=1200, index=0)]
    out = list(paced_tagged(interleave_streams({"a": a, "b": b}),
                            float("inf")))
    assert [t for t, _ in out] == ["a", "b"]
    assert all(sl.arrival_wall > 0 for _, sl in out)
