"""Smoke tests for the driver-facing bench entry points.

bench.py is the round gate the driver runs on real hardware; these
protect it from API drift (it imports deep into fused/dense/scoring).
On the CPU test backend the dense gate is off, so bench_em exercises
the sparse fused path; shapes are tiny to keep compiles cheap.
"""

import json

import numpy as np


def test_bench_em_sparse_smoke():
    import bench

    em = bench.bench_em(4, 128, 32, 16, chunk=2, rounds=1, var_max_iters=3)
    assert np.isfinite(em["docs_per_sec"]) and em["docs_per_sec"] > 0
    assert em["t_iter"] > 0
    assert em["use_dense"] is False  # CPU backend: dense gate needs TPU
    assert em["wmajor"] is False
    assert em["corpus_itemsize"] == 4  # sparse: no dense corpus stored
    assert 0 < em["mean_vi"] <= 3


def test_bench_em_compact_smoke():
    """phase_config4's engine path at toy scale: with the full-V dense
    gate off (CPU), compact=True must route through the compact-vocab
    dense engine (plan_compact + compact_stack_batches) and report its
    width/unique-word evidence fields."""
    import bench

    em = bench.bench_em(4, 4096, 32, 16, chunk=2, rounds=1,
                        var_max_iters=3, compact=True,
                        word_law="loguniform")
    assert np.isfinite(em["docs_per_sec"]) and em["docs_per_sec"] > 0
    assert em["use_dense"] is True           # compact engine IS dense
    assert em["engine_variant"] == "compact"
    # log-uniform draw over [1, 4096) from 32*16 tokens: far fewer
    # uniques than V, padded up to the compact width
    assert 0 < em["unique_words"] <= 32 * 16
    assert em["unique_words"] <= em["compact_width"] < 4096


def test_bench_flow_day_matches_schema(tmp_path):
    """The synthetic flow day must align with FLOW_COLUMNS — an earlier
    version carried an extra leading column, so the featurizer read
    sip='0.0' and a dip string as the port for EVERY row, collapsing
    the benched vocabulary to one port bucket."""
    import io

    import bench
    from oni_ml_tpu.features.flow import FLOW_COLUMNS, NUM_FLOW_COLUMNS
    from oni_ml_tpu.features.native_flow import featurize_flow_file

    buf = io.StringIO()
    bench._write_flow_day(buf, 500, n_src=50, n_dst=20)
    lines = buf.getvalue().strip().splitlines()
    assert len(lines) == 500
    cols = lines[0].split(",")
    assert len(cols) == NUM_FLOW_COLUMNS
    assert cols[FLOW_COLUMNS["sip"]].startswith("10.0.")
    assert cols[FLOW_COLUMNS["dip"]].startswith("10.1.")
    assert int(cols[FLOW_COLUMNS["dport"]]) in (80, 443, 22, 53, 8080, 25)
    assert 0 <= int(cols[FLOW_COLUMNS["hour"]]) < 24
    p = tmp_path / "day.csv"
    p.write_text(buf.getvalue())
    feats = featurize_flow_file(str(p))
    if not hasattr(feats, "ip_table"):     # pure-Python fallback (no g++)
        import pytest

        pytest.skip("native featurizer unavailable")
    ips = set(feats.ip_table)
    assert "0.0" not in ips
    # Both endpoints present as documents; multiple port buckets.
    assert any(ip.startswith("10.0.") for ip in ips)
    assert any(ip.startswith("10.1.") for ip in ips)
    words = {feats.word_table[w] for w in feats.sw_id[:feats.num_raw_events]}
    ports = {w.split("_")[0] for w in words}
    assert len(ports) > 1 and "111111.0" not in ports


def test_bench_flow_day_realistic_cardinality():
    """Power-law mode (config-3 at-spec tooling): IPs draw from a
    rank^-a population over a 3-octet address space (src 10.* / dst
    11.*, disjoint), service ports widen beyond the fixed 6-service
    mix — and the DEFAULT byte stream is untouched (the round-1..4
    phases must stay comparable)."""
    import io

    import bench

    buf = io.StringIO()
    bench._write_flow_day(buf, 20_000, n_src=300_000, n_dst=150_000,
                          seed=5, ip_zipf_a=1.2, n_svc_ports=48)
    lines = buf.getvalue().strip().splitlines()
    assert len(lines) == 20_000
    sips = {ln.split(",")[8] for ln in lines}
    dips = {ln.split(",")[9] for ln in lines}
    # Long tail realized: thousands of distinct hosts from 20k events.
    assert len(sips) > 3_000 and len(dips) > 1_500
    assert all(s.startswith("10.") for s in sips)
    assert all(d.startswith("11.") for d in dips)
    dports = {int(ln.split(",")[11]) for ln in lines}
    assert len(dports) >= 40 and max(dports) <= 1024
    # Hot-host skew: the most active host sees far more than uniform.
    from collections import Counter

    top = Counter(ln.split(",")[8] for ln in lines).most_common(1)[0][1]
    assert top > 20_000 // 48

    # Default mode: byte-wise identical schema/space as before.
    buf2 = io.StringIO()
    bench._write_flow_day(buf2, 1_000, seed=5)
    l2 = buf2.getvalue().strip().splitlines()
    assert {int(ln.split(",")[11]) for ln in l2} <= {80, 443, 22, 53,
                                                     8080, 25}
    assert all(ln.split(",")[8].startswith("10.0.") for ln in l2)
    assert all(ln.split(",")[9].startswith("10.1.") for ln in l2)

    # The service mix is FIXED across day seeds (real traffic keeps the
    # same services day over day): drawing it from the per-day rng gave
    # every day a fresh port subset, and a 30-day corpus realized ~770
    # distinct ports — a 16x vocabulary inflation artifact.
    bufA, bufB = io.StringIO(), io.StringIO()
    bench._write_flow_day(bufA, 500, seed=7, ip_zipf_a=1.2,
                          n_svc_ports=12)
    bench._write_flow_day(bufB, 500, seed=8, ip_zipf_a=1.2,
                          n_svc_ports=12)
    pa = {int(ln.split(",")[11])
          for ln in bufA.getvalue().strip().splitlines()}
    pb = {int(ln.split(",")[11])
          for ln in bufB.getvalue().strip().splitlines()}
    assert len(pa | pb) <= 12

    # Uniform mode with a >65536 population must use the wide encoding
    # too — the 2-octet form would silently emit non-IP strings like
    # 10.0.1367.44 (round-5 review finding).
    buf3 = io.StringIO()
    bench._write_flow_day(buf3, 2_000, n_src=200_000, n_dst=1_000,
                          seed=5)
    for ln in buf3.getvalue().strip().splitlines():
        for col in (8, 9):
            octets = ln.split(",")[col].split(".")
            assert len(octets) == 4
            assert all(0 <= int(o) <= 255 for o in octets)


def test_bench_em_stacked_batches_smoke():
    """n_batches stacks day-scale resident batches through the chunk
    runner's scan (tpu_probes batch_amort): docs/s must account for
    every stacked document and the run must stay finite.  n_batches=1
    keeps the legacy single-batch shape (drawn from the same rng
    stream) so prior-round phase numbers stay comparable."""
    import bench

    em1 = bench.bench_em(4, 256, 32, 16, chunk=2, rounds=1,
                         force_sparse=True)
    em3 = bench.bench_em(4, 256, 32, 16, chunk=2, rounds=1,
                         force_sparse=True, n_batches=3)
    for em in (em1, em3):
        assert np.isfinite(em["docs_per_sec"]) and em["docs_per_sec"] > 0
    # Same wall-clock basis: docs_per_sec = total docs / t_iter
    # (compare via the identical division — a multiply round-trip is
    # off by an ulp for ~1 in 7 timing values).
    assert em1["docs_per_sec"] == 32 / em1["t_iter"]
    assert em3["docs_per_sec"] == 96 / em3["t_iter"]


def test_bench_dns_scoring_smoke():
    import bench

    eps, p50 = bench.bench_dns_scoring(n_events=2000, reps=1)
    assert np.isfinite(eps) and eps > 0
    assert p50 > 0


def test_bench_pipeline_e2e_smoke():
    import bench

    total, stages, eps, pre, critical = bench.bench_pipeline_e2e(
        n_events=3000, n_src=50, n_dst=30, em_max_iters=3
    )
    assert total > 0 and eps > 0
    assert set(stages) == {"pre", "corpus", "lda", "score"}
    # The critical-path breakdown: per-stage walls (inline + that
    # stage's background tasks), the serial-equivalent sum, the
    # overlapped e2e wall, and the headline overlap_efficiency.
    assert set(critical["stage_wall_s"]) == {"pre", "corpus", "lda",
                                             "score"}
    assert critical["sum_of_stage_walls_s"] > 0
    assert critical["e2e_wall_s"] > 0
    assert critical["overlap_efficiency"] is not None
    assert "edges" in critical  # dataplane ran: per-edge stall stats
    # The pre record carries the parallel-featurization payload: the
    # resolved worker count, per-pass walls, the handoff mode, and (on
    # a multi-core host) the sequential comparison.
    assert pre["pre_workers"] >= 1
    assert pre["handoff"] == "direct"
    assert isinstance(pre["wall"], dict)
    if pre["pre_workers"] > 1:
        assert pre["pre_s_workers1"] > 0
    total, stages, eps, pre, _ = bench.bench_pipeline_e2e(
        n_events=2000, n_src=40, em_max_iters=3, dsource="dns",
        compare_pre_workers1=False,
    )
    assert total > 0 and eps > 0
    assert set(stages) == {"pre", "corpus", "lda", "score"}
    assert "pre_s_workers1" not in pre


def test_bench_flow_scoring_smoke():
    import bench

    eps, p50 = bench.bench_flow_scoring(n_events=2000, reps=1)
    assert np.isfinite(eps) and eps > 0
    assert p50 > 0


def test_em_utilization_fields():
    import bench

    util = bench.em_utilization(20, 8192, 4096, 5e-3)
    assert set(util) == {
        "achieved_tflops", "mxu_pct", "hbm_gbps", "hbm_pct"
    }
    assert all(v > 0 for v in util.values())


def _patch_phases(bench, monkeypatch):
    # In-process phase execution: the monkeypatched bench_* stubs below
    # don't exist inside the production path's phase subprocesses.
    monkeypatch.setenv("BENCH_INPROC", "1")
    monkeypatch.setattr(
        bench, "bench_em",
        lambda *a, **k: {"docs_per_sec": 1000.0, "t_iter": 0.004,
                         "use_dense": False, "wmajor": False,
                         "corpus_itemsize": 4, "mean_vi": 5.0,
                         "chunk": k.get("chunk", 128),
                         "alpha_max_iters": 8},
    )
    monkeypatch.setattr(
        bench, "bench_dns_scoring", lambda *a, **k: (5000.0, 0.08)
    )
    monkeypatch.setattr(
        bench, "bench_flow_scoring", lambda *a, **k: (4000.0, 0.1)
    )
    monkeypatch.setattr(bench, "bench_online_svi", lambda *a, **k: 2000.0)
    monkeypatch.setattr(
        bench, "bench_pipeline_e2e",
        lambda *a, **k: (60.0, {"pre": 10.0, "lda": 40.0}, 80000.0,
                         {"pre_workers": 2, "wall": {}, "handoff": "direct",
                          "pre_s_workers1": 18.0},
                         {"stage_wall_s": {"pre": 10.0, "lda": 40.0},
                          "per_stage_wall_s": {"pre": 12.0, "lda": 40.0},
                          "background_wall_s": 2.0,
                          "sum_of_stage_walls_s": 52.0,
                          "e2e_wall_s": 60.0,
                          "overlap_efficiency": -0.1538, "edges": {}}),
    )
    monkeypatch.setattr(bench, "_backend_responsive", lambda *a, **k: True)
    monkeypatch.setattr(
        bench, "bench_convergence",
        lambda *a, **k: (1.5, 20, -1e5, "fused+sparse"),
    )
    monkeypatch.setattr(
        bench, "bench_scoring_e2e",
        lambda *a, **k: {"value": 90000.0, "unit": "events/sec",
                         "n_events": 400_000,
                         "host_events_per_sec": 500000.0,
                         "device_events_per_sec": 800000.0,
                         "chunk": 65536, "dispatch": {"dispatches": 7},
                         "projected_dispatches_400k": 7,
                         "calibration": {"break_even": 4096}},
    )
    monkeypatch.setattr(
        bench, "bench_serving_slo",
        lambda *a, **k: {
            "n_events": 4096, "offered_eps": 4000.0,
            "poisson": {"sustained_eps": 3900.0, "p50_ms": 6.0,
                        "p99_ms": 18.0, "p999_ms": 25.0},
            "bursty": {"sustained_eps": 3800.0, "p50_ms": 4.0,
                       "p99_ms": 30.0, "p999_ms": 55.0},
        },
    )
    monkeypatch.setattr(
        bench, "bench_serving_slo_replicated",
        lambda *a, **k: {
            "n_tenants": 256, "zipf_s": 1.1, "spawn": "process",
            "route_window": 64, "max_wait_ms": 20.0,
            "replica_counts": [1, 2, 4],
            "scaling": {
                "1": {"replicas": 1, "events": 3072, "wall_s": 1.1,
                      "sustained_eps": 2800.0, "errors": 0,
                      "retraces_in_window": 0},
                "2": {"replicas": 2, "events": 6144, "wall_s": 1.1,
                      "sustained_eps": 5500.0, "errors": 0,
                      "retraces_in_window": 0},
                "4": {"replicas": 4, "events": 12288, "wall_s": 1.15,
                      "sustained_eps": 10700.0, "errors": 0,
                      "retraces_in_window": 0},
            },
            "sustained_eps_by_count": {"1": 2800.0, "2": 5500.0,
                                       "4": 10700.0},
            "replica_scaling_efficiency": 0.98,
            "replica_scaling_efficiency_by_count": {
                "1": 1.0, "2": 0.98, "4": 0.95},
            "retraces_in_windows": 0,
            "chaos": {
                "replicas": 2, "killed": "r0", "offered_eps": 1500.0,
                "events": 4096, "victim_tenants": 128,
                "errors_surviving": 0, "errors_victim_tenants": 0,
                "p50_ms": 93.0, "p99_ms": 215.0, "p999_ms": 253.0,
                "failover_window_events": 88,
                "failover_p999_ms": 202.0,
                "time_to_recovery_s": 0.18,
                "survivor_bit_identical": True,
                "retraces_after_recovery": 0,
                "failover_record": {"promoted": 128, "resent": 64,
                                    "recovery_s": 0.035},
            },
            "failover_p999_ms": 202.0,
            "time_to_recovery_s": 0.18,
        },
    )
    monkeypatch.setattr(
        bench, "bench_serving_crosshost",
        lambda *a, **k: {
            "fanin": {
                "router_counts": [1, 2], "n_replicas": 1,
                "aggregate_eps_by_routers": {"1": 358.5, "2": 682.2},
                "router_scaling_efficiency": 0.95,
                "fanin_exceeds_single_router": True,
                "wire_bytes_per_event": 134.0, "errors": 0,
                "chaos": {"survivor_errors": 0, "redriven_events": 64,
                          "survivor_bit_identical": True},
            },
            "autoscale": {
                "errors": 0, "wire_bytes_per_event": 131.0,
                "scale_up_reaction_s": 0.4, "max_replicas_reached": 3,
            },
            "sustained_eps": 682.2,
            "router_scaling_efficiency": 0.95,
            "fanin_exceeds_single_router": True,
            "wire_bytes_per_event": 134.0,
            "scale_up_reaction_s": 0.4,
            "max_replicas_reached": 3, "errors": 0,
        },
    )
    monkeypatch.setattr(
        bench, "bench_streaming_freshness",
        lambda *a, **k: {
            "dsource": "flow", "tenant": "stream", "slices": 96,
            "events": 40_000, "refreshes": 47, "publishes": 47,
            "vetoes": 0, "freshness_p50_s": 0.4,
            "freshness_p99_s": 2.4, "freshness_event_p50_min": 14.4,
            "freshness_event_p99_min": 29.0, "freshness_samples": 95,
            "warm": {"fits": 46, "mean_wall_s": 0.06,
                     "mean_em_iters": 5.4},
            "fresh": {"fits": 1, "mean_wall_s": 1.2,
                      "mean_em_iters": 74.0},
            "fresh_control": {"warm_start_speedup": 4.3,
                              "held_out_ll_delta": -0.36},
            "warm_start_speedup": 4.3, "held_out_ll": -6.08,
            "held_out_ll_delta": -0.36, "retraces_after_warmup": 0,
            "replay_speed": 1440.0,
        },
    )
    monkeypatch.setattr(
        bench, "bench_continuous_replicated",
        lambda *a, **k: {
            "replicas": 2, "replay_speed": 1440.0, "n_events": 12_000,
            "events_scored": 9970, "failed_futures": 0, "failovers": 1,
            "killed_replica": "r1",
            "freshness_p50_s": 1.3, "freshness_p99_s": 8.8,
            "freshness_event_p50_min": 0.04,
            "freshness_event_p99_min": 82.6,
            "p99_idle_ms": 92.8, "p99_during_refresh_ms": 106.6,
            "refresh_over_idle_ratio": 1.15,
            "p99_idle_uncoscheduled_ms": 62.3,
            "p99_during_refresh_uncoscheduled_ms": 63.0,
            "yield_wait_p99_ms": 7.1, "preempt_wait_p99_ms": None,
            "train_chunks": 2031, "yields": 9, "preempts": 0,
            "refreshes": 63, "publishes": 45,
            "coalesced_refreshes": 31, "refresh_errors": 0,
            "retraces_after_warmup": 0, "sustained_eps": 198.0,
            "replay_wall_s": 60.6,
        },
    )
    monkeypatch.setattr(
        bench, "bench_detection_quality",
        lambda *a, **k: {
            src: {"recall_at_k": 1.0, "precision_at_k": 1.0,
                  "score_separation": 2.5, "k": 24, "attacks": 24,
                  "per_scenario": {}, "events": 8024, "vocab": 900,
                  "docs": 48, "wall_s": 3.1}
            for src in ("flow", "dns", "proxy")
        },
    )
    monkeypatch.setattr(
        bench, "bench_distributed_em",
        lambda *a, **k: {
            "nprocs": 2, "docs": 2048, "em_iters": 6, "em_shards": 8,
            "transport": "kvring", "docs_per_sec": 60000.0,
            "per_host_estep_wall_s": 0.2, "single_proc_wall_s": 0.35,
            "single_proc_docs_per_sec": 35000.0,
            "scaling_efficiency": 0.875,
            "allreduce_bytes_per_iter": 230000.0,
            "allreduce_wall_s_per_iter": 0.004,
            "allreduce_ops": 7, "rank_ll_spread": 0.0,
        },
    )
    monkeypatch.setattr(
        bench, "bench_serving_slo_fleet",
        lambda *a, **k: {
            "n_tenants": 4, "mix": "poisson:1,bursty:1",
            "n_events": 4096, "offered_eps": 4000.0,
            "aggregate": {"sustained_eps": 3700.0, "p50_ms": 7.0,
                          "p99_ms": 21.0, "p999_ms": 40.0,
                          "resolved": 4096, "errors": 0},
            "tenants": {
                f"t{i}": {"pattern": "poisson" if i % 2 == 0
                          else "bursty",
                          "sustained_eps": 925.0, "p50_ms": 7.0,
                          "p99_ms": 22.0, "p999_ms": 41.0}
                for i in range(4)
            },
            "plans": {"retraces_after_warmup": 0},
        },
    )
    monkeypatch.setattr(
        bench, "bench_serving_slo_fleet_paged",
        lambda *a, **k: {
            "n_tenants": 256, "zipf_s": 1.1, "mix": "poisson:1,bursty:1",
            "n_events": 6144, "offered_eps": 6000.0,
            "aggregate": {"sustained_eps": 2200.0, "p50_ms": 48.0,
                          "p99_ms": 1100.0, "p999_ms": 1200.0,
                          "resolved": 6144, "errors": 0},
            "tenants": {"t0": {"pattern": "poisson",
                               "sustained_eps": 1200.0, "p50_ms": 7.0,
                               "p99_ms": 50.0, "p999_ms": 60.0}},
            "tenants_truncated": True,
            "residency": {"policy": "lru", "hot_capacity": 32,
                          "warm_capacity": 64,
                          "tiers": {"hot": 32, "warm": 64, "cold": 160},
                          "promotions": 350, "evictions": 320,
                          "cold_loads": 250, "spills": 400,
                          "failures": 0, "promotion_stall_s": 200.0},
            "plans": {"retraces_after_warmup": 0},
        },
    )


def test_bench_em_engine_pinning_smoke():
    """bench_em's engine pin: "sparse" runs the fused sparse bucketed
    kernel, "dense" forces the dense kernel in interpret mode on CPU —
    the two sides of the dense_vs_sparse crossover measurement — and
    the payload names what ran plus the effective/dense-equivalent
    FLOP accounting."""
    import bench

    em_s = bench.bench_em(4, 256, 32, 16, chunk=2, rounds=1,
                          var_max_iters=3, engine="sparse",
                          precision="f32")
    assert em_s["estep_engine"] == "sparse"
    assert em_s["use_dense"] is False
    assert em_s["flops_effective_per_iter"] > 0
    # 256 pads to the 128-lane tile; L=16 -> 16x dense-equivalent waste.
    assert em_s["flops_dense_equiv_per_iter"] == \
        em_s["flops_effective_per_iter"] * (256 / 16)
    assert em_s["roofline"]["effective_flops"] > 0

    em_d = bench.bench_em(4, 256, 32, 16, chunk=2, rounds=1,
                          var_max_iters=3, engine="dense",
                          precision="f32")
    assert em_d["estep_engine"] == "dense"
    assert em_d["use_dense"] is True
    assert np.isfinite(em_d["docs_per_sec"])


def test_bench_dense_vs_sparse_records_crossover(monkeypatch, tmp_path):
    """The dense_vs_sparse section measures both engines, persists the
    winner to the plan cache, and the RESOLVED engine (what a fresh
    auto run would pick, source "plan") is never slower than the dense
    baseline — the crossover proving itself on CPU."""
    import bench
    from oni_ml_tpu.ops import sparse_estep

    monkeypatch.setenv("ONI_ML_TPU_PLAN_CACHE",
                       str(tmp_path / "plans.jsonl"))
    sparse_estep._CROSSOVER_CACHE.clear()
    dvs = bench.bench_dense_vs_sparse(4, 256, 32, 16, chunk=2, rounds=1,
                                      precision="f32")
    assert dvs["winner"] in ("dense", "sparse")
    assert dvs["resolved_engine"] == dvs["winner"]
    assert dvs["resolved_source"] == "plan"
    assert dvs[dvs["winner"]]["docs_per_sec"] >= \
        dvs["dense"]["docs_per_sec"]
    for engine in ("dense", "sparse"):
        assert dvs[engine]["roofline"]["wall_s"] > 0
    sparse_estep._CROSSOVER_CACHE.clear()


def test_bench_main_diff_gate(capsys, monkeypatch, tmp_path):
    """BENCH_DIFF_AGAINST wires tools/bench_diff into main() as an
    opt-in post-run gate: the comparison rides the final record and a
    regression flips the exit code to 1 for CI."""
    import bench

    _patch_phases(bench, monkeypatch)
    base = {"metric": "lda_em_throughput", "value": 10_000.0,
            "unit": "docs/sec"}
    path = tmp_path / "base.json"
    path.write_text(json.dumps(base))
    monkeypatch.setenv("BENCH_DIFF_AGAINST", str(path))
    assert bench.main() == 1          # stub headline 1000 << 10000
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rec["bench_diff"]["regressions"] == 1
    assert rec["bench_diff"]["against"] == str(path)

    # A compatible baseline exits 0 with the comparison still recorded.
    base["value"] = 999.0
    path.write_text(json.dumps(base))
    assert bench.main() == 0
    rec = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1]
    )
    assert rec["bench_diff"]["regressions"] == 0


def test_bench_main_last_line_is_complete_record(capsys, monkeypatch):
    """main() re-prints the growing record after each phase (so a
    mid-run wedge can't erase the headline); the driver parses the LAST
    line, which must be the complete record with every secondary."""
    import bench

    _patch_phases(bench, monkeypatch)
    assert bench.main() == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) >= 2           # headline first, grown records after
    first = json.loads(out[0])
    assert first["metric"] == "lda_em_throughput"
    assert "secondary" not in first    # printed before any secondary ran
    rec = json.loads(out[-1])
    assert {"metric", "value", "unit", "vs_baseline", "prev_round"} <= set(rec)
    assert rec["metric"] == "lda_em_throughput"
    assert set(rec["secondary"]) == {
        "mosaic_smoke",
        "lda_em_throughput_fresh_start",
        "lda_em_throughput_k50_v50k",
        "lda_em_throughput_config4_v512k",
        "lda_online_svi",
        "lda_em_convergence",
        "dns_scoring",
        "flow_scoring",
        "scoring_e2e",
        "serving_slo",
        "serving_slo_fleet",
        "serving_slo_fleet_paged",
        "featurize_device",
        "serving_slo_replicated",
        "serving_crosshost",
        "streaming_freshness",
        "continuous_replicated",
        "detection_quality",
        "distributed_em",
        "pipeline_e2e",
        "pipeline_e2e_dns",
    }
    # prev_round must carry the latest prior driver-captured headline
    # (BENCH_r01.json in-repo: 483336 docs/s).
    assert rec["prev_round"] and rec["prev_round"]["value"] > 0
    # Every phase — success or error stub — carries its wall-clock so
    # the record shows where a slow round-end run spent its time.
    assert rec["phase_wall_s"] >= 0
    assert all(
        v.get("phase_wall_s", -1) >= 0 for v in rec["secondary"].values()
    )


def test_bench_lint_preflight_aborts_on_findings(capsys, monkeypatch):
    """With BENCH_LINT on (conftest turns it off suite-wide), a failing
    lint report aborts main() with a structured failure payload before
    any phase runs — CI-rejected code never spends grant time."""
    import bench

    import oni_ml_tpu.analysis as analysis
    from oni_ml_tpu.analysis.engine import Finding, Report

    monkeypatch.setenv("BENCH_LINT", "1")
    report = Report(
        findings=[Finding("monotonic-clock", "oni_ml_tpu/x.py", 3,
                          "bare time.time()")],
        suppressed=0, baselined=0, files_scanned=1,
        parse_errors=[("oni_ml_tpu/bad.py", "SyntaxError: boom")],
    )
    monkeypatch.setattr(analysis, "run_analysis", lambda: report)
    _patch_phases(bench, monkeypatch)
    assert bench.main() == 1
    captured = capsys.readouterr()
    rec = json.loads(captured.out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert "lint preflight failed" in rec["error"]
    assert "1 parse error(s)" in rec["error"]
    assert rec["phases"] == {}          # aborted before any phase
    assert "[monotonic-clock]" in captured.err
    assert "parse error" in captured.err


def test_bench_main_headline_survives_secondary_failure(capsys, monkeypatch):
    """A crashing secondary must not lose the headline or the other
    secondaries — it is recorded as an error stub and main() stays 0."""
    import bench

    _patch_phases(bench, monkeypatch)
    monkeypatch.setattr(
        bench, "bench_online_svi",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    assert bench.main() == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    svi = rec["secondary"]["lda_online_svi"]
    assert svi["error"] == "boom" and svi["phase_wall_s"] >= 0
    assert rec["secondary"]["dns_scoring"]["value"] > 0


def test_bench_backend_dead_skips_device_phases_keeps_host_phases(
    capsys, monkeypatch
):
    """Once a device phase times out AND the backend re-probe fails
    twice, remaining DEVICE phases are skipped (not left to hang in
    backend init for their full timeouts) while host-only scoring
    phases still run."""
    import bench

    _patch_phases(bench, monkeypatch)
    monkeypatch.setattr(
        bench, "bench_convergence",
        lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("timeout after 300s (wedged device call?)")
        ),
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    # The initial responsiveness gate also uses _backend_responsive;
    # let the run start (first call True), then fail probes mid-run.
    gate = iter([True])
    monkeypatch.setattr(
        bench, "_backend_responsive",
        lambda *a, **k: next(gate, False),
    )
    assert bench.main() == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    sec = rec["secondary"]
    assert "timeout" in sec["lda_em_convergence"]["error"]
    # Host-only phases after the wedge still produced numbers.
    assert sec["dns_scoring"]["value"] > 0
    assert sec["flow_scoring"]["value"] > 0
    # Device phases after the wedge were skipped, not timed out.
    for name in ("lda_em_throughput_k50_v50k",
                 "lda_em_throughput_config4_v512k",
                 "pipeline_e2e", "pipeline_e2e_dns", "lda_online_svi"):
        assert sec[name] == {"error": "skipped: backend wedged earlier in run",
                             "phase_wall_s": 0.0}
    # The phase before the wedge ran normally.
    assert sec["lda_em_throughput_fresh_start"]["value"] > 0


def test_bench_phase_subprocess_unknown_phase_reports_error():
    """The production per-phase isolation path: a phase subprocess that
    exits non-zero (here: unknown phase name, rc=2) must come back as a
    (None, error) pair, not an exception or a bogus payload."""
    import bench

    payload, err = bench._run_phase_subprocess("no_such_phase", 60.0)
    assert payload is None
    assert "rc=2" in err and "no_such_phase" in err


def test_bench_online_svi_smoke():
    import bench

    dps = bench.bench_online_svi(k=4, v=256, b=64, l=16, steps=4, chunk=2)
    assert np.isfinite(dps) and dps > 0


def test_bench_main_emits_structured_failure_when_backend_wedged(
    capsys, monkeypatch
):
    """Rounds 2 and 3 both ended parsed=null because a dead backend
    produced NO stdout.  The wedged path must now exit 1 with a final
    parseable line: value=null, an error string, and provenance-marked
    last_good evidence — never a fake measurement."""
    import bench

    monkeypatch.setattr(bench, "_backend_responsive", lambda *a, **k: False)
    # The real host phases take minutes; what the test pins is that
    # their results ride the failure record as fresh measurements.
    monkeypatch.setattr(
        bench, "_run_host_only_phases",
        lambda inproc: {"dns_scoring": {"value": 12345.6,
                                        "unit": "events/sec"}},
    )
    assert bench.main() == 1
    last = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(last)
    assert rec["metric"] == "lda_em_throughput"
    assert rec["value"] is None
    assert "backend unavailable" in rec["error"]
    lg = rec["last_good"]
    assert lg is not None and lg["value"] > 0 and "provenance" in lg
    # The two evidence grades must ride SEPARATE fields (round-4
    # review finding: last_good prefers the richer in-session capture,
    # so a skimming consumer read 1.31M as the best *driver* number).
    ldv = rec["last_driver_verified"]
    assert ldv is not None and ldv["value"] > 0
    assert "driver-captured" in ldv["provenance"]
    # A dead-backend round still carries THIS round's host-only phase
    # measurements (r05: the scoring stages need no chip).
    assert rec["host_only_phases"]["dns_scoring"]["value"] == 12345.6


def test_bench_headline_unrecoverable_still_carries_host_phases(
    capsys, monkeypatch
):
    """Backend passes the gate but dies during the headline: the
    failure record must still carry fresh host-only phase results
    (review finding on the r05 gate-path feature — the loss recurs on
    this exit path otherwise)."""
    import bench

    monkeypatch.setattr(bench, "_backend_responsive",
                        lambda *a, **k: True)
    monkeypatch.setattr(bench, "_run_phase",
                        lambda n, f, t, i: (None, "rc=1: dead", 1.0))
    monkeypatch.setattr(
        bench, "_run_host_only_phases",
        lambda inproc: {"flow_scoring": {"value": 7.0}},
    )
    assert bench.main() == 1
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert "headline unrecoverable" in rec["error"]
    assert rec["host_only_phases"]["flow_scoring"]["value"] == 7.0


def test_bench_run_host_only_phases_selects_host_phases(monkeypatch):
    """_run_host_only_phases runs exactly the touches_device=False
    phases and keeps per-phase failures recoverable."""
    import bench

    ran = []

    def fake_run_phase(name, fn, timeout, inproc):
        ran.append(name)
        if name == "flow_scoring":
            return None, "rc=1: boom", 1.0
        return {"value": 1.0}, None, 1.0

    monkeypatch.setattr(bench, "_run_phase", fake_run_phase)
    out = bench._run_host_only_phases(False)
    expected = [n for n, _, _, dev in bench.PHASES if not dev]
    assert ran == expected and set(out) == set(expected)
    assert out["dns_scoring"] == {"value": 1.0}
    assert out["flow_scoring"]["error"] == "rc=1: boom"


def test_bench_gate_schedule_bounded(monkeypatch):
    """The initial probe gate must fit under BENCH_GATE_S (round 3's
    ~40-min gentle window outran the driver's timeout: rc=124, no
    record).  Worst case = every probe times out + every backoff —
    including budgets below one PROBE_S (the probe clamps down)."""
    import bench

    for budget in (60.0, 120.0, 600.0, 1800.0):
        probes, backoffs = bench._gate_schedule(budget)
        assert sum(probes) + sum(backoffs) <= budget
        assert len(probes) >= 1
    # default comes from the module constant and is driver-safe
    monkeypatch.delenv("BENCH_GATE_S", raising=False)
    probes, backoffs = bench._gate_schedule()
    assert sum(probes) + sum(backoffs) <= 600.0


def test_bench_convergence_smoke():
    import bench

    s, iters, ll, engine = bench.bench_convergence(
        k=4, v=128, b=32, l=16, em_tol=1e-3, max_iters=24, chunk=8
    )
    assert s > 0 and 0 < iters <= 24 and np.isfinite(ll)
    # CPU-pinned test env: dense is TPU-gated, so the engine label must
    # report what actually ran (review finding: it was hardcoded once).
    assert engine == "fused+sparse"


def test_bench_sigterm_salvages_parseable_record(tmp_path):
    """An outer driver timeout SIGTERMs the orchestrator (rc=124 runs).
    The handler must leave the same parseable last line the watchdog
    guarantees — here, a structured failure (the backend gate is still
    probing when the TERM lands), never empty stdout."""
    import os
    import signal
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # An unusable platform makes every probe fail; a huge gate keeps
    # main() inside _backend_responsive when the TERM arrives.
    env["JAX_PLATFORMS"] = "tpu"
    env["BENCH_GATE_S"] = "600"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "bench.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=repo,
    )
    # Wait for the readiness marker (not a fixed sleep: the import
    # chain can exceed any guess on a loaded machine, and a TERM
    # before the handler is installed dies with default semantics).
    # select() keeps the deadline real — and it must poll the RAW pipe
    # fd with os.read, never the TextIOWrapper: a buffered readline()
    # can hold a complete line Python-side while select() reports the
    # fd not-ready (round-4 advisor finding).
    import select

    deadline = time.time() + 120
    ready = False
    fd = proc.stderr.fileno()
    tail = b""
    while time.time() < deadline and not ready:
        r, _, _ = select.select([fd], [], [], 1.0)
        if not r:
            if proc.poll() is not None:
                break  # died with no further output
            continue
        chunk = os.read(fd, 4096)
        if not chunk:
            break  # EOF: bench died before the marker
        tail = (tail + chunk)[-256:]
        ready = b"salvage handler installed" in tail
    if not ready:
        proc.kill()
        proc.communicate()
        raise AssertionError("bench.py never printed the readiness marker")
    time.sleep(1.0)  # let it enter the probe gate
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=60)
    assert proc.returncode == 1
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert "terminated by supervising process" in rec["error"]
    lg = rec["last_good"]
    # last_good rides whatever evidence files the checkout carries;
    # assert on it only when present (it is, in this repo).
    assert lg is None or lg["value"] > 0
    # The salvage path shares _failure_payload, so the driver-verified
    # grade must ride here too.
    ldv = rec["last_driver_verified"]
    assert ldv is None or "driver-captured" in ldv["provenance"]


def test_bench_em_reports_effective_alpha_max_iters():
    """The payload's alpha_max_iters is threaded from the chunk runner
    make_chunk_runner actually built (via _setup_em's info), so a
    monkeypatched maker that overrides the cap — tools/tpu_probes.py's
    alpha_ab newton100 — reports its real setting instead of re-reading
    bench.ALPHA_MAX_ITERS."""
    import bench
    from oni_ml_tpu.models import fused

    em = bench.bench_em(4, 128, 32, 16, chunk=2, rounds=1, var_max_iters=3)
    assert em["alpha_max_iters"] == bench.ALPHA_MAX_ITERS

    orig = fused.make_chunk_runner

    def newton100(**kw):
        kw["alpha_max_iters"] = 100
        return orig(**kw)

    fused.make_chunk_runner = newton100
    try:
        em = bench.bench_em(4, 128, 32, 16, chunk=2, rounds=1,
                            var_max_iters=3)
    finally:
        fused.make_chunk_runner = orig
    assert em["alpha_max_iters"] == 100


def test_bench_dead_backend_payload_carries_completed_phases(
    capsys, monkeypatch
):
    """The r05 acceptance contract: an injected dead backend must emit
    a payload containing every phase that completed (the host-only
    scoring phases ran fresh) plus an explicit backend_lost annotation
    — never a value=null total loss."""
    import bench

    _patch_phases(bench, monkeypatch)
    # Gate fails: the backend never answers.
    monkeypatch.setattr(bench, "_backend_responsive", lambda *a, **k: False)
    assert bench.main() == 1
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert rec["backend_lost"] is True
    # The completed-phase ledger rides the failure payload: the
    # host-only phases ran (stubbed) and their numbers survive.
    phases = rec["phases"]
    assert phases["dns_scoring"]["value"] > 0
    assert phases["flow_scoring"]["value"] > 0
    # host_only_phases marks the same measurements as host-context.
    assert rec["host_only_phases"]["dns_scoring"]["value"] > 0


def test_bench_journal_records_phase_outcomes(capsys, monkeypatch, tmp_path):
    """BENCH_JOURNAL=path: every phase outcome lands in a crash-safe
    telemetry journal (run_start ... phase* ... run_end ok)."""
    import bench
    from oni_ml_tpu.telemetry import Journal

    jpath = str(tmp_path / "bench.jsonl")
    monkeypatch.setenv("BENCH_JOURNAL", jpath)
    _patch_phases(bench, monkeypatch)
    assert bench.main() == 0
    capsys.readouterr()
    records = Journal.replay(jpath)
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "run_start"
    assert kinds[-1] == "run_end" and records[-1]["ok"]
    phases = {r["name"]: r for r in records if r["kind"] == "phase"}
    assert set(phases) == {n for n, _, _, _ in bench.PHASES}
    assert all(p["ok"] for p in phases.values())
    assert phases["headline"]["payload"]["value"] > 0


def test_bench_journal_failure_path_marks_backend_lost(
    capsys, monkeypatch, tmp_path
):
    import bench
    from oni_ml_tpu.telemetry import Journal

    jpath = str(tmp_path / "bench.jsonl")
    monkeypatch.setenv("BENCH_JOURNAL", jpath)
    monkeypatch.setattr(bench, "_backend_responsive", lambda *a, **k: False)
    monkeypatch.setattr(
        bench, "_run_host_only_phases",
        lambda inproc: {"dns_scoring": {"value": 1.0}},
    )
    assert bench.main() == 1
    capsys.readouterr()
    records = Journal.replay(jpath)
    kinds = [r["kind"] for r in records]
    assert "backend_lost" in kinds
    ends = [r for r in records if r["kind"] == "run_end"]
    assert len(ends) == 1 and not ends[0]["ok"]


def test_bench_midrun_backend_death_annotates_record(capsys, monkeypatch):
    """A grant that dies AFTER the headline keeps its real value but
    the final record carries the backend_lost annotation naming the
    wedged phase."""
    import bench

    _patch_phases(bench, monkeypatch)
    monkeypatch.setattr(
        bench, "bench_convergence",
        lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("timeout after 300s (wedged device call?)")
        ),
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    gate = iter([True])
    monkeypatch.setattr(
        bench, "_backend_responsive",
        lambda *a, **k: next(gate, False),
    )
    assert bench.main() == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] > 0                      # headline survived
    assert "lda_em_convergence" in rec["backend_lost"]


def test_bench_diff_regression_gate(tmp_path):
    """tools/bench_diff.py: the documented post-bench step — compares
    headline / phases / utilization / overlap_efficiency between two
    payloads and exits 1 on regression beyond thresholds."""
    import io
    import os
    import sys
    from contextlib import redirect_stdout

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import bench_diff

    def payload(value, e2e_seconds, eff, mxu):
        return {
            "metric": "lda_em_throughput", "value": value,
            "unit": "docs/sec",
            "utilization": {"mxu_pct": mxu, "hbm_pct": 3.1},
            "secondary": {
                "pipeline_e2e": {"value": e2e_seconds, "unit": "seconds",
                                 "overlap_efficiency": eff},
                "dns_scoring": {"value": 150000.0, "unit": "events/sec"},
            },
        }

    old_p = tmp_path / "old.json"
    new_p = tmp_path / "new.json"
    # The driver wrapper form ({"parsed": ...}) must unwrap.
    old_p.write_text(json.dumps(
        {"n": 5, "rc": 0, "parsed": payload(1e6, 100.0, 0.10, 10.5)}
    ))

    # 1) Improvement everywhere: exit 0.
    new_p.write_text(json.dumps(payload(1.2e6, 90.0, 0.15, 11.0)))
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert bench_diff.main([str(old_p), str(new_p)]) == 0
    assert "no regressions" in buf.getvalue()

    # 2) Throughput collapse: exit 1, headline row flagged.
    new_p.write_text(json.dumps(payload(0.5e6, 100.0, 0.10, 10.5)))
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert bench_diff.main([str(old_p), str(new_p)]) == 1
    assert "REGRESSION" in buf.getvalue()

    # 3) Seconds are lower-better: slower e2e beyond threshold fails.
    new_p.write_text(json.dumps(payload(1e6, 150.0, 0.10, 10.5)))
    assert bench_diff.main(
        [str(old_p), str(new_p), "--json"]) == 1

    # 4) overlap_efficiency drop beyond --efficiency-drop fails even
    # with every wall within tolerance.
    new_p.write_text(json.dumps(payload(1e6, 101.0, 0.01, 10.5)))
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert bench_diff.main([str(old_p), str(new_p)]) == 1
    assert "overlap_efficiency" in buf.getvalue()
    # ...but within tolerance passes.
    new_p.write_text(json.dumps(payload(1e6, 101.0, 0.08, 10.5)))
    assert bench_diff.main([str(old_p), str(new_p)]) == 0

    # 5) Utilization absolute-point drop fails.
    new_p.write_text(json.dumps(payload(1e6, 100.0, 0.10, 7.0)))
    assert bench_diff.main([str(old_p), str(new_p)]) == 1

    # 6) --json emits structured rows.
    new_p.write_text(json.dumps(payload(1.1e6, 95.0, 0.12, 10.6)))
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert bench_diff.main([str(old_p), str(new_p), "--json"]) == 0
    out = json.loads(buf.getvalue())
    assert out["regressions"] == 0
    names = {r["name"] for r in out["rows"]}
    assert "headline:lda_em_throughput" in names
    assert "phase:pipeline_e2e" in names
    assert "overlap_efficiency:pipeline_e2e" in names
    assert "utilization:mxu_pct" in names

    # 7) Unusable input: exit 2.
    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2]")
    assert bench_diff.main([str(bad), str(new_p)]) == 2


def test_bench_distributed_em_smoke():
    """The REAL distributed_em phase machinery at toy scale: spawns the
    1-process baseline and a 2-rank CPU cluster (fresh worker
    processes, KV-ring transport) and reports the acceptance payload —
    allreduce bytes/wall per iteration, scaling efficiency, and zero
    rank-ELBO spread (parity)."""
    import bench

    res = bench.bench_distributed_em(nprocs=2, docs=192, em_iters=2)
    assert res["nprocs"] == 2
    assert res["transport"] == "kvring"
    assert res["em_iters"] == 2
    assert res["docs_per_sec"] > 0
    assert res["single_proc_docs_per_sec"] > 0
    assert res["scaling_efficiency"] > 0
    assert res["allreduce_bytes_per_iter"] > 0
    assert res["allreduce_wall_s_per_iter"] > 0
    # em_iters reduces + the gamma merge ride the same collective.
    assert res["allreduce_ops"] == res["em_iters"] + 1
    assert res["rank_ll_spread"] == 0.0
    # The bf16 wire-compression leg: the bulk suff-stats payload
    # halves, the gamma merge + control plane stay exact, so the
    # whole-fit ratio sits between 0.4 and ~0.95; the compressed wire
    # may not silently change the f32 default leg.
    assert res["allreduce_precision"] == "f32"
    bf16 = res["allreduce_bf16"]
    assert 0.3 < bf16["bytes_ratio"] < 0.98
    assert bf16["bytes_per_iter"] < res["allreduce_bytes_per_iter"]
    # bf16-tolerance, not bit-equal: a few percent of the ELBO
    # magnitude at toy scale, never garbage.
    assert bf16["ll_drift_rel"] < 0.05


def test_bench_diff_distributed_em_directions(tmp_path):
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools",
    ))
    import bench_diff

    def payload(eff, ar_wall):
        return {
            "metric": "lda_em_throughput", "value": 1000.0,
            "unit": "docs/sec",
            "secondary": {"distributed_em": {
                "value": 2000.0, "unit": "docs/sec",
                "scaling_efficiency": eff,
                "allreduce_wall_s_per_iter": ar_wall,
                "allreduce_bytes_per_iter": 219000.0,
            }},
        }

    # Efficiency DROP is a regression (fraction = higher-better)...
    rows = bench_diff.diff_payloads(payload(0.8, 0.01), payload(0.5, 0.01))
    reg = [r["name"] for r in rows if r["regression"]]
    assert reg == ["phase:distributed_em.scaling_efficiency"]
    # ...allreduce-wall GROWTH is a regression (s = lower-better)...
    rows = bench_diff.diff_payloads(payload(0.8, 0.01), payload(0.8, 0.02))
    reg = [r["name"] for r in rows if r["regression"]]
    assert reg == ["phase:distributed_em.allreduce_wall_s_per_iter"]
    # ...and the same moves in the GOOD direction gate nothing.
    rows = bench_diff.diff_payloads(payload(0.5, 0.02), payload(0.8, 0.01))
    assert not [r for r in rows if r["regression"]]
    # A headline-level distributed_em capture compares directly too.
    old = {"value": 2000.0, "unit": "docs/sec",
           "scaling_efficiency": 0.8, "allreduce_wall_s_per_iter": 0.01}
    new = dict(old, scaling_efficiency=0.4)
    rows = bench_diff.diff_payloads(old, new)
    assert any(r["regression"]
               and r["name"] == "headline.scaling_efficiency"
               for r in rows)
