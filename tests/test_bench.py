"""Smoke tests for the driver-facing bench entry points.

bench.py is the round gate the driver runs on real hardware; these
protect it from API drift (it imports deep into fused/dense/scoring).
On the CPU test backend the dense gate is off, so bench_em exercises
the sparse fused path; shapes are tiny to keep compiles cheap.
"""

import json

import numpy as np


def test_bench_em_sparse_smoke():
    import bench

    dps, t_iter, used_dense, used_wmajor = bench.bench_em(
        4, 128, 32, 16, chunk=2, rounds=1, var_max_iters=3
    )
    assert np.isfinite(dps) and dps > 0
    assert t_iter > 0
    assert used_dense is False  # CPU backend: dense gate requires TPU
    assert used_wmajor is False


def test_bench_dns_scoring_smoke():
    import bench

    eps, p50 = bench.bench_dns_scoring(n_events=2000, reps=1)
    assert np.isfinite(eps) and eps > 0
    assert p50 > 0


def test_em_utilization_fields():
    import bench

    util = bench.em_utilization(20, 8192, 4096, 5e-3)
    assert set(util) == {
        "achieved_tflops", "mxu_pct", "hbm_gbps", "hbm_pct"
    }
    assert all(v > 0 for v in util.values())


def test_bench_main_emits_one_json_line(capsys, monkeypatch):
    """main() must print exactly one JSON object with the driver's
    required keys, whatever engine the backend picks."""
    import bench

    monkeypatch.setattr(
        bench, "bench_em",
        lambda *a, **k: (1000.0, 0.004, False, False),
    )
    monkeypatch.setattr(
        bench, "bench_dns_scoring", lambda *a, **k: (5000.0, 0.08)
    )
    monkeypatch.setattr(bench, "bench_online_svi", lambda *a, **k: 2000.0)
    monkeypatch.setattr(bench, "_backend_responsive", lambda *a, **k: True)
    monkeypatch.setattr(
        bench, "bench_convergence", lambda *a, **k: (1.5, 20, -1e5)
    )
    assert bench.main() == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    rec = json.loads(out[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    assert rec["metric"] == "lda_em_throughput"


def test_bench_online_svi_smoke():
    import bench

    dps = bench.bench_online_svi(k=4, v=256, b=64, l=16, steps=4, warm=2)
    assert np.isfinite(dps) and dps > 0


def test_bench_main_aborts_cleanly_when_backend_wedged(capsys, monkeypatch):
    import bench

    monkeypatch.setattr(bench, "_backend_responsive", lambda *a, **k: False)
    assert bench.main() == 1
    assert capsys.readouterr().out.strip() == ""  # no fake JSON line


def test_bench_convergence_smoke():
    import bench

    s, iters, ll = bench.bench_convergence(
        k=4, v=128, b=32, l=16, em_tol=1e-3, max_iters=24, chunk=8
    )
    assert s > 0 and 0 < iters <= 24 and np.isfinite(ll)
