"""Device featurization plane (sources/device.py, ops/featurize_kernel
.py, the FleetScorer featurize engines): the golden-oracle parity
contract — device-built word rows byte-identical to the host
featurizers for every registered source, malformed and adversarial
rows included — plus the strict-parse gates, the sparse code table,
the fused single-dispatch path, and the serving wiring."""

import os

import numpy as np
import pytest

from oni_ml_tpu.config import ServingConfig
from oni_ml_tpu.scoring import ScoringModel
from oni_ml_tpu.serving import (
    FleetRegistry,
    FleetScorer,
    MetricsEmitter,
    TenantSpec,
)
from oni_ml_tpu.sources import get as get_source
from oni_ml_tpu.sources.device import (
    DeviceBatch,
    Unlowerable,
    _CodeTable,
    cached_featurizer,
    compile_featurizer,
    device_batch,
    resolve_engine,
)

SOURCES = ("flow", "dns", "proxy")

GARBAGE = ("", "junk", "nan", "NaN", "inf", "-inf", "-0.0", "0",
           "1e309", "999999", " 12 ", "0x10", "1_0", "7.5e-2", "-3.25",
           "0.30000000000000004", "  ", "None")


def _fuzz_cols(src: str) -> tuple:
    """Columns worth attacking per source: every value that feeds a
    number parse, a bin, a categorical table, or a document key."""
    return {
        "flow": (4, 5, 6, 8, 9, 10, 11, 16, 17),
        "dns": (1, 2, 3, 4, 6, 7),
        "proxy": (1, 2, 3, 4, 5, 6, 7),
    }[src]


def _day(src: str, n: int = 300, seed: int = 1):
    """(spec, cuts, model, train_rows): a clean synthetic day through
    the source's own synth generator, host-featurized for cuts and a
    vocabulary, with a random (but deterministic) model over it."""
    spec = get_source(src)
    lines = spec.synth_benign(n, seed)
    cuts = spec.derive_cuts(lines)
    feats = spec.featurize(lines, precomputed_cuts=cuts)
    ips, words = spec.event_documents(feats)
    ip_index = {v: i for i, v in enumerate(sorted(set(ips)))}
    word_index = {v: i for i, v in enumerate(sorted(set(words)))}
    rng = np.random.default_rng(seed)
    k = 4
    theta = rng.uniform(0.1, 1.0, (len(ip_index) + 1, k))
    theta[:-1] /= theta[:-1].sum(1, keepdims=True)
    p = rng.uniform(0.1, 1.0, (len(word_index) + 1, k))
    p[:-1] /= p[:-1].sum(0, keepdims=True)
    model = ScoringModel(ip_index=ip_index, theta=theta,
                         word_index=word_index, p=p)
    rows = [ln.strip().split(",") for ln in lines]
    return spec, cuts, model, rows


def _fuzzed_rows(src: str, rows, seed: int = 7, frac: float = 0.6):
    """Serve-time adversarial rows: random garbage cells (unparsable
    numbers, -0.0/nan, unseen categorical values, separator-laden
    strings) injected into otherwise valid rows — column counts stay
    valid, values do not."""
    rng = np.random.default_rng(seed)
    cols = _fuzz_cols(src)
    extra = GARBAGE + ("a_b", "x_y_z", "evil_METHOD", "q.a_b.example")
    out = []
    for r in rows:
        r = list(r)
        if rng.random() < frac:
            for _ in range(int(rng.integers(1, 4))):
                c = int(rng.choice(cols))
                r[c] = str(rng.choice(extra))
        out.append(r)
    return out


def _host_pairs(spec, model, feats):
    pairs = spec.event_pairs(feats)
    ip = np.concatenate([model.ip_rows(k) for k, _ in pairs])
    w = np.concatenate([model.word_rows(ws) for _, ws in pairs])
    return ip.astype(np.int32), w.astype(np.int32)


def _serve_feats(spec, cuts, rows):
    """Host-featurize pre-split serve rows through the serving
    featurizer (the oracle the device path must match byte for byte)."""
    fz = spec.event_featurizer(cuts)
    if spec.name == "dns":
        return fz(rows), fz
    return fz([",".join(r) for r in rows]), fz


# ---------------------------------------------------------------------------
# parity: device word/ip rows byte-identical to the host oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("src", SOURCES)
def test_parity_clean_day(src):
    spec, cuts, model, rows = _day(src)
    dev, info = compile_featurizer(spec, cuts, model)
    assert dev is not None, info["reason"]
    assert info["lowered"] and info["kind"] == "featurize_compile"
    feats, _ = _serve_feats(spec, cuts, rows)
    ip_h, w_h = _host_pairs(spec, model, feats)
    assert np.array_equal(dev.word_rows_local(rows), w_h)
    batch = DeviceBatch(dev, lambda raws: feats, rows, rows)
    ip_d, w_d, mult = batch.pair_rows()
    assert np.array_equal(ip_d, ip_h)
    assert np.array_equal(w_d, w_h)
    assert mult == spec.pairs_per_event


@pytest.mark.parametrize("src", SOURCES)
@pytest.mark.parametrize("seed", [7, 23])
def test_parity_adversarial_fuzz(src, seed):
    """Randomized garbage cells: every row still featurizes on both
    paths (host featurizers never raise on bad VALUES, only on bad
    column counts) and word rows stay byte-identical — unseen
    categorical values and unparsable numbers land on the same rows,
    usually the fallback."""
    spec, cuts, model, rows = _day(src)
    dev, info = compile_featurizer(spec, cuts, model)
    assert dev is not None, info["reason"]
    bad = _fuzzed_rows(src, rows, seed=seed)
    feats, _ = _serve_feats(spec, cuts, bad)
    assert feats.num_raw_events == len(bad)
    ip_h, w_h = _host_pairs(spec, model, feats)
    assert np.array_equal(dev.word_rows_local(bad), w_h)
    batch = DeviceBatch(dev, lambda raws: feats, bad, bad)
    ip_d, w_d, _ = batch.pair_rows()
    assert np.array_equal(ip_d, ip_h)
    assert np.array_equal(w_d, w_h)


def test_parity_flow_signed_zero_and_nan_ports():
    """The -0.0/0.0 bit-pattern case: str(-0.0) != str(0.0), so the
    port intern pass must unique on float BITS — and NaN ports must
    follow Python min/max propagation, not numpy's."""
    spec, cuts, model, rows = _day("flow")
    dev, info = compile_featurizer(spec, cuts, model)
    assert dev is not None, info["reason"]
    probes = []
    for sport, dport in [("-0.0", "0.0"), ("0.0", "-0.0"),
                         ("nan", "80"), ("80", "nan"), ("nan", "nan"),
                         ("-0.0", "-0.0"), ("0", "0")]:
        r = list(rows[0])
        r[10], r[11] = sport, dport
        probes.append(r)
    feats, _ = _serve_feats(spec, cuts, probes)
    _, w_h = _host_pairs(spec, model, feats)
    assert np.array_equal(dev.word_rows_local(probes), w_h)


@pytest.mark.parametrize("src", SOURCES)
def test_malformed_row_shedding_parity(src):
    """Admission (admit/validate) rejects exactly the rows the host
    featurizer would shed: wrong column counts.  Bad VALUES pass
    admission on both paths."""
    spec, cuts, model, rows = _day(src)
    fz = spec.event_featurizer(cuts)
    good = ",".join(rows[0])
    assert fz.admit(good)[1] == rows[0]
    for bad in (good + ",extra", "only,three,cols", ""):
        with pytest.raises(ValueError):
            fz.admit(bad)
        with pytest.raises(ValueError):
            fz.validate(bad)
        host = spec.featurize([bad], precomputed_cuts=cuts)
        assert host.num_raw_events == 0


# ---------------------------------------------------------------------------
# strict-parse gates + the sparse table
# ---------------------------------------------------------------------------


def test_dns_separator_vocab_gates():
    """A vocabulary word the grammar cannot represent (a qtype/rcode
    with an embedded separator makes >8 segments) gates the WHOLE
    model: the host could produce it, so lowering would be unsound."""
    spec, cuts, model, _ = _day("dns")
    wi = dict(model.word_index)
    wi["0_4_1_0_5_1_1_0_1"] = len(wi)   # 9 segments
    p = np.vstack([model.p[:-1], model.p[-2:]])
    bad = ScoringModel(ip_index=model.ip_index, theta=model.theta,
                       word_index=wi, p=p)
    dev, info = compile_featurizer(spec, cuts, bad)
    assert dev is None
    assert not info["lowered"]
    assert "separator" in info["reason"]


def test_proxy_template_gate():
    """A vocabulary word that fails the template grammar's fullmatch
    gates the model (producible-value ambiguity)."""
    spec, cuts, model, _ = _day("proxy")
    wi = dict(model.word_index)
    wi["GET_junk"] = len(wi)
    p = np.vstack([model.p[:-1], model.p[-2:]])
    bad = ScoringModel(ip_index=model.ip_index, theta=model.theta,
                       word_index=wi, p=p)
    dev, info = compile_featurizer(spec, cuts, bad)
    assert dev is None and not info["lowered"]


def test_flow_unparseable_vocab_word_skips_not_gates():
    """Flow word segments are str(float) renderings — a vocabulary word
    that does not parse is host-UNPRODUCIBLE, so it is skipped (an
    unreachable entry), never a gate."""
    spec, cuts, model, rows = _day("flow")
    wi = dict(model.word_index)
    wi["not_a_flow_word"] = len(wi)
    p = np.vstack([model.p[:-1], model.p[-2:]])
    odd = ScoringModel(ip_index=model.ip_index, theta=model.theta,
                       word_index=wi, p=p)
    dev, info = compile_featurizer(spec, cuts, odd)
    assert dev is not None, info["reason"]
    feats, _ = _serve_feats(spec, cuts, rows)
    _, w_h = _host_pairs(spec, odd, feats)
    assert np.array_equal(dev.word_rows_local(rows), w_h)


def test_sparse_code_table_mode_and_parity():
    """Past _MAX_CODE_SPACE the table switches to the sorted-code
    binary probe — same lookups, bounded memory.  The synthetic serve
    day's DNS vocabulary (qtypes x rcodes x five bin fields ~ 5M
    codes for ~100 words) exercises it end to end."""
    from oni_ml_tpu.runner.serve import _synthetic_day

    lines, model, cuts = _synthetic_day(seed=42)
    spec = get_source("dns")
    dev, info = compile_featurizer(spec, tuple(cuts), model)
    assert dev is not None, info["reason"]
    assert info["mode"] == "sparse"
    assert info["code_space"] > info["lut"]
    rows = [ln.strip().split(",") if isinstance(ln, str) else list(ln)
            for ln in lines]
    fz = spec.event_featurizer(tuple(cuts))
    feats = fz(rows)
    _, w_h = _host_pairs(spec, model, feats)
    assert np.array_equal(dev.word_rows_local(rows), w_h)


def test_code_table_shapes():
    t = _CodeTable([(0, 1, 0), (1, 0, 1)], (2, 2), fallback_row=2)
    assert t.mode == "dense" and t.size == 8  # pow2(4 + 1)
    assert t.rows_of(np.array([1, 2, 3], np.int32)).tolist() == [0, 1, 2]
    big = _CodeTable([(0, 5, 0), (1, 7, 1)], (2, 1 << 30),
                     fallback_row=2)
    assert big.mode == "sparse"
    codes = big.mask_invalid(
        np.array([5, (1 << 30) + 7, 12], np.int64),
        np.array([False, False, True]),
    )
    assert big.rows_of(codes).tolist() == [0, 1, 2]
    with pytest.raises(Unlowerable):
        _CodeTable([], (1 << 31, 1 << 31, 1), fallback_row=0)


def test_emit_lines_proxy_fleet_framing():
    """load_gen --emit-lines --dsource proxy: every emitted line is
    tab-framed `<tenant>\\t<csv>` and the payload ADMITS through the
    proxy serving featurizer (column-count valid, no header row)."""
    import io
    import sys

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import load_gen

    spec = get_source("proxy")
    cuts = spec.derive_cuts(spec.synth_benign(40, 0))
    fz = spec.event_featurizer(cuts)
    out = io.StringIO()
    n = load_gen.emit_lines("poisson", 32, 1e9, out=out, tenants=2,
                            dsource="proxy")
    lines = out.getvalue().splitlines()
    assert n == 32 and len(lines) == 32
    for i, ln in enumerate(lines):
        tenant, _, payload = ln.partition("\t")
        assert tenant == f"t{i % 2}"
        _, row = fz.admit(payload)
        assert len(row) == spec.num_columns
    # Default source stays the serve harness's DNS day (8 columns).
    out = io.StringIO()
    load_gen.emit_lines("poisson", 4, 1e9, out=out)
    assert all(len(ln.split(",")) == 8
               for ln in out.getvalue().splitlines())


def test_derive_cuts_memoized(monkeypatch):
    """Registry specs are singletons; derive_cuts runs the ECDF
    featurize pass once per distinct slice and hands repeat callers
    the same cut tuple."""
    spec = get_source("proxy")
    spec.__dict__.pop("_derived_cuts", None)
    lines = spec.synth_benign(50, 3)
    calls = []
    orig = type(spec).featurize

    def counting(self, *a, **k):
        calls.append(1)
        return orig(self, *a, **k)

    monkeypatch.setattr(type(spec), "featurize", counting)
    c1 = spec.derive_cuts(lines)
    n = len(calls)
    assert n >= 1
    assert spec.derive_cuts(list(lines)) is c1
    assert len(calls) == n
    other = spec.derive_cuts(spec.synth_benign(50, 4))
    assert len(calls) > n and other is not c1


# ---------------------------------------------------------------------------
# compile cache + engine resolution
# ---------------------------------------------------------------------------


def test_cached_featurizer_compiles_once():
    spec, cuts, model, rows = _day("proxy", n=60)
    dev1, info1 = cached_featurizer(model, spec, cuts)
    assert dev1 is not None and info1 is not None
    dev2, info2 = cached_featurizer(model, spec, cuts)
    assert dev2 is dev1 and info2 is None   # info exactly once
    batch, fresh = device_batch(spec.event_featurizer(cuts), rows, rows,
                                model)
    assert isinstance(batch, DeviceBatch) and fresh is None


def test_shared_vocab_rebinds_compiled_table():
    # Same-day tenant fleets: distinct models over one vocabulary must
    # share ONE compiled table (a rebind, not a vocabulary re-parse) —
    # and one device transfer of its rows.
    spec, cuts, model, rows = _day("dns", n=80, seed=3)
    rng = np.random.default_rng(9)
    theta2 = rng.uniform(0.1, 1.0, model.theta.shape)
    theta2[:-1] /= theta2[:-1].sum(1, keepdims=True)
    p2 = rng.uniform(0.1, 1.0, model.p.shape)
    p2[:-1] /= p2[:-1].sum(0, keepdims=True)
    model2 = ScoringModel(ip_index=dict(model.ip_index), theta=theta2,
                          word_index=dict(model.word_index), p=p2)
    dev1, info1 = cached_featurizer(model, spec, cuts)
    dev2, info2 = cached_featurizer(model2, spec, cuts)
    assert info1 is not None and info1["shared"] is False
    assert info2 is not None and info2["shared"] is True
    assert dev2 is not dev1 and dev2.table is dev1.table
    assert dev2.model is model2
    np.testing.assert_array_equal(dev1.word_rows_local(rows),
                                  dev2.word_rows_local(rows))
    from oni_ml_tpu.ops import featurize_kernel as fk

    assert fk.device_lut(dev2) is fk.device_lut(dev1)
    # a vocabulary that differs in content does NOT share
    widx3 = dict(model.word_index)
    widx3["zz_phantom_word"] = len(widx3)
    p3 = rng.uniform(0.1, 1.0, (len(widx3) + 1, model.p.shape[1]))
    model3 = ScoringModel(ip_index=dict(model.ip_index),
                          theta=theta2, word_index=widx3, p=p3)
    dev3, info3 = cached_featurizer(model3, spec, cuts)
    assert info3 is not None and info3["shared"] is False
    assert dev3 is not None and dev3.table is not dev1.table


def test_resolve_engine_precedence(monkeypatch):
    monkeypatch.delenv("ONI_ML_TPU_FEATURIZE", raising=False)
    assert resolve_engine("auto") == ("device", "default")
    assert resolve_engine("host") == ("host", "config")
    assert resolve_engine("fused") == ("fused", "config")
    monkeypatch.setenv("ONI_ML_TPU_FEATURIZE", "host")
    assert resolve_engine("fused") == ("host", "env")


# ---------------------------------------------------------------------------
# fused kernel + fleet wiring
# ---------------------------------------------------------------------------


def _fleet(src: str, model, cuts, engine: str, journal):
    spec = get_source(src)
    fleet = FleetRegistry()
    fleet.add_tenant(TenantSpec(tenant="t0", dsource=src))
    fleet.publish("t0", model, source="day")
    cfg = ServingConfig(device_score_min=None, featurize_engine=engine)
    return FleetScorer(
        fleet, {"t0": spec.event_featurizer(cuts)}, cfg,
        metrics=MetricsEmitter(to_stdout=False), journal=journal,
    )


class _Journal(list):
    def append(self, record):  # noqa: A003 - journal protocol
        list.append(self, record)


@pytest.mark.parametrize("src", SOURCES)
def test_fleet_device_engine_scores_bitwise(src, monkeypatch):
    monkeypatch.delenv("ONI_ML_TPU_FEATURIZE", raising=False)
    spec, cuts, model, rows = _day(src, n=120)
    raws = rows if src == "dns" else [",".join(r) for r in rows]
    out = {}
    for engine in ("host", "device"):
        jn = _Journal()
        scorer = _fleet(src, model, cuts, engine, jn)
        futs = [scorer.submit("t0", r) for r in raws]
        scorer.flush()
        out[engine] = [f.result(timeout=30)[0] for f in futs]
        scorer.close()
        if engine == "device":
            comp = [r for r in jn if r.get("kind") == "featurize_compile"]
            assert len(comp) == 1 and comp[0]["lowered"]
            dem = [r for r in jn if r.get("kind") == "demux"]
            assert dem and dem[0]["featurize"] == "device"
            assert dem[0]["featurize_device_tenants"] == 1
    assert out["host"] == out["device"]


def test_fleet_fused_engine_close_and_single_dispatch(monkeypatch):
    monkeypatch.delenv("ONI_ML_TPU_FEATURIZE", raising=False)
    import oni_ml_tpu.ops.featurize_kernel as fk

    spec, cuts, model, rows = _day("dns", n=200)
    out = {}
    for engine in ("host", "fused"):
        scorer = _fleet("dns", model, cuts, engine, _Journal())
        futs = [scorer.submit("t0", r) for r in rows]
        scorer.flush()
        out[engine] = np.array(
            [f.result(timeout=30)[0] for f in futs]
        )
        scorer.close()
    assert "fused" in fk._FNS
    np.testing.assert_allclose(out["fused"], out["host"], rtol=1e-5)


def test_fused_scores_matches_device_rows_and_threshold():
    from oni_ml_tpu.scoring.pipeline import fused_featurize_scores
    from oni_ml_tpu.scoring.score import batched_scores

    spec, cuts, model, rows = _day("proxy", n=150)
    dev, info = compile_featurizer(spec, cuts, model)
    assert dev is not None, info["reason"]
    feats, _ = _serve_feats(spec, cuts, rows)
    batch = DeviceBatch(dev, lambda raws: feats, rows, rows)
    d, codes, ip = batch.fused_operands()
    ref = batched_scores(model, *(batch.pair_rows()[:2]), None)
    got = fused_featurize_scores(model, d, codes, ip, block=256)
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    thr = float(np.median(ref))
    scores, keep = fused_featurize_scores(model, d, codes, ip,
                                          block=256, threshold=thr)
    np.testing.assert_allclose(scores, got, rtol=0)
    assert np.array_equal(keep, scores < thr)


def test_fused_zero_post_warmup_retraces():
    """Same padded shape family across flushes -> the fused program
    compiles once; varying flush sizes under the featurize_block floor
    must not retrace."""
    import jax

    from oni_ml_tpu.scoring.pipeline import fused_featurize_scores

    spec, cuts, model, rows = _day("dns", n=250)
    dev, info = compile_featurizer(spec, cuts, model)
    assert dev is not None, info["reason"]
    feats, _ = _serve_feats(spec, cuts, rows)

    def fused_n(n, word_base=0):
        sub = rows[:n]
        f = spec.featurize(sub, precomputed_cuts=cuts)
        b = DeviceBatch(dev, lambda raws: f, sub, sub)
        d, codes, ip = b.fused_operands()
        return fused_featurize_scores(model, d, codes, ip,
                                      word_base=word_base, block=256)

    fused_n(200)   # warmup at the padded tier
    fn = jax.jit(lambda: 0)  # noqa: F841 - ensures jax importable
    import oni_ml_tpu.ops.featurize_kernel as fk

    fused = fk._FNS["fused"]
    if not hasattr(fused, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    warm = fused._cache_size()
    for n, wb in ((150, 0), (130, 3), (256, 7), (199, 0)):
        fused_n(n, word_base=wb)
    assert fused._cache_size() == warm


def test_pending_event_row_plumbing(monkeypatch):
    """submit() stores the admission-parsed row on the pending event;
    validate-only featurizers (no admit) still serve, host-featurized."""
    monkeypatch.delenv("ONI_ML_TPU_FEATURIZE", raising=False)
    spec, cuts, model, rows = _day("proxy", n=40)

    class ValidateOnly:
        dsource = "proxy"

        def __init__(self, inner):
            self._inner = inner

        def validate(self, line):
            return self._inner.validate(line)

        def __call__(self, lines):
            return self._inner(lines)

    fleet = FleetRegistry()
    fleet.add_tenant(TenantSpec(tenant="t0", dsource="proxy"))
    fleet.publish("t0", model, source="day")
    jn = _Journal()
    scorer = FleetScorer(
        fleet, {"t0": ValidateOnly(spec.event_featurizer(cuts))},
        ServingConfig(device_score_min=None, featurize_engine="device"),
        metrics=MetricsEmitter(to_stdout=False), journal=jn,
    )
    futs = [scorer.submit("t0", ",".join(r)) for r in rows]
    scorer.flush()
    scores = [f.result(timeout=30)[0] for f in futs]
    scorer.close()
    assert len(scores) == len(rows)
    dem = [r for r in jn if r.get("kind") == "demux"]
    assert dem[0]["featurize_device_tenants"] == 0   # no rows -> host


# ---------------------------------------------------------------------------
# golden day: the committed byte contract
# ---------------------------------------------------------------------------


GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden")


@pytest.mark.parametrize("src", ["flow", "dns"])
def test_golden_day_parity(src):
    """Device word rows byte-identical to the host featurizer on the
    committed golden day, against the committed pinned model."""
    import sys

    sys.path.insert(0, GOLDEN)
    from generate import (DNS_FALLBACK, FLOW_FALLBACK, load_dns_feats,
                          load_flow_feats)

    spec = get_source(src)
    if src == "flow":
        feats, fallback = load_flow_feats(), FLOW_FALLBACK
    else:
        feats, fallback = load_dns_feats(), DNS_FALLBACK
    model = ScoringModel.from_files(
        os.path.join(GOLDEN, "expected", src, "doc_results.csv"),
        os.path.join(GOLDEN, "expected", src, "word_results.csv"),
        fallback=fallback,
    )
    cuts = spec.cuts_of(feats)
    dev, info = compile_featurizer(spec, cuts, model)
    assert dev is not None, info["reason"]
    with open(os.path.join(GOLDEN, "inputs", f"{src}.csv")) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    rows = [ln.split(",") for ln in lines]
    ncol = spec.num_columns if src == "flow" else 8
    rows = [r for r in rows if len(r) == ncol][1:]   # drop header
    serve_feats, _ = _serve_feats(spec, cuts, rows)
    _, w_h = _host_pairs(spec, model, serve_feats)
    assert np.array_equal(dev.word_rows_local(rows), w_h)
