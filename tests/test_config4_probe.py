"""tools/config4_hbm_probe.py mechanics at small width.

The tool's value is the real-width record (V=512k — produced by the
tool run, kept under docs/bench_captures/); here we pin that the
compile-only pipeline works on the virtual mesh and that the
per-device buffer accounting matches the sharding arithmetic the
architecture doc argues from.
"""

import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)

import config4_hbm_probe


def test_probe_compiles_both_plans_and_accounts_buffers():
    v, b, k = 2048, 32, 4
    rec = config4_hbm_probe.probe(v=v, b=b, k=k, var_max_iters=3)
    vs = rec["plans"]["vocab_sharded_dense"]
    dp = rec["plans"]["data_parallel_dense"]
    # DP keeps the full [b, v] corpus shard (+ replicated beta) resident
    # per device; vocab sharding cuts the per-device argument bytes to
    # ~1/n_devices of that.  These are XLA buffer-assignment numbers,
    # not hand arithmetic.
    corpus_bytes = b * v * 4
    assert dp["argument_bytes"] >= corpus_bytes
    assert vs["argument_bytes"] < corpus_bytes / 4
    assert vs["argument_bytes"] < dp["argument_bytes"]
    for plan in (vs, dp):
        assert plan["peak_bytes"] > 0
        assert isinstance(plan["fits_hbm"], bool)
    # At toy width everything fits, so the claim gate must hold.
    assert rec["claim_verified"] is True
    assert rec["b_per_chip"] == b and rec["v"] == v
