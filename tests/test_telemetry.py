"""Telemetry flight recorder: journal write/replay, span tracing +
Chrome trace export, heartbeat loss detection, and the serving metrics
registry refactor.  (The grep-lints that lived here are AST rules in
oni_ml_tpu/analysis/ now — see tests/test_analysis.py.)

Everything here is the fast tier-1 smoke — no device, no subprocesses
(the SIGKILL crash-recovery path lives in tests/test_journal_crash.py).
"""

import json
import os
import threading

import pytest

from oni_ml_tpu.telemetry import (
    BackendLost,
    HeartbeatMonitor,
    Journal,
    Recorder,
    RunJournal,
    current_recorder,
    maybe_span,
    use_recorder,
)


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------


def test_journal_append_replay_roundtrip(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path, fsync_every=2) as j:
        for i in range(5):
            j.append({"kind": "x", "i": i})
    records = Journal.replay(path)
    assert [r["i"] for r in records] == list(range(5))
    # Every record is stamped with seq / wall t / monotonic ns.
    assert [r["seq"] for r in records] == list(range(5))
    assert all("t" in r and "mono_ns" in r for r in records)
    # mono_ns is non-decreasing (monotonic clock).
    ns = [r["mono_ns"] for r in records]
    assert ns == sorted(ns)


def test_journal_replay_tolerates_truncated_tail(tmp_path):
    """The hard-kill signature: a half-written final line must replay
    to every complete record, dropped-line count ZERO (clean
    truncation is expected, not damage)."""
    path = str(tmp_path / "j.jsonl")
    with Journal(path) as j:
        j.append({"kind": "a"})
        j.append({"kind": "b"})
    with open(path, "ab") as f:  # simulate a kill mid-append
        f.write(b'{"kind": "c", "truncat')
    records, dropped = Journal.replay_report(path)
    assert [r["kind"] for r in records] == ["a", "b"]
    assert dropped == 0


def test_journal_replay_counts_midfile_damage(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path) as j:
        j.append({"kind": "a"})
    with open(path, "ab") as f:
        f.write(b"NOT JSON AT ALL\n")
    with Journal(path) as j:
        j.append({"kind": "b"})
    records, dropped = Journal.replay_report(path)
    assert [r["kind"] for r in records] == ["a", "b"]
    assert dropped == 1


def test_journal_replay_missing_file_is_empty():
    assert Journal.replay("/nonexistent/never/j.jsonl") == []


def test_journal_append_is_one_line_per_record(tmp_path):
    """Atomic line writes: concurrent writers may interleave RECORDS
    but never bytes — every line parses alone."""
    path = str(tmp_path / "j.jsonl")
    j = Journal(path, fsync_every=0)

    def writer(tag):
        for i in range(50):
            j.append({"kind": "w", "tag": tag, "i": i})

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    j.close()
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln]
    assert len(lines) == 200
    for ln in lines:
        assert isinstance(json.loads(ln), dict)


def test_run_journal_completed_stages_and_force_boundary(tmp_path):
    path = str(tmp_path / "j.jsonl")
    rj = RunJournal(Journal(path))
    rj.run_start(force=False)
    rj.stage_begin("pre")
    rj.stage_end("pre", ok=True, wall_s=1.0)
    rj.stage_begin("corpus")
    rj.stage_end("corpus", ok=True)
    rj.stage_begin("lda")
    rj.stage_end("lda", ok=False, error="boom")  # failed: NOT complete
    rj.close()
    done = RunJournal.completed_stages(Journal.replay(path))
    assert done == {"pre", "corpus"}

    # A force run invalidates prior completions; its own completions
    # count again.
    rj = RunJournal(Journal(path))
    rj.run_start(force=True)
    rj.stage_end("pre", ok=True)
    rj.close()
    done = RunJournal.completed_stages(Journal.replay(path))
    assert done == {"pre"}


def test_run_journal_tolerates_none_journal():
    rj = RunJournal(None)
    rj.run_start()
    rj.stage_begin("pre")
    rj.stage_end("pre")
    rj.em_likelihood(1, -10.0, 0.5)
    rj.heartbeat(True)
    rj.backend_lost(reason="x")
    rj.close()  # no raise = pass


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_spans_nest_and_export_chrome_trace():
    rec = Recorder()
    with rec.span("outer", label="o"):
        with rec.span("inner"):
            pass
        rec.counter("things").add(3)
        rec.histogram("lat_s").observe(0.25)
    trace = rec.chrome_trace()
    # Chrome trace-event JSON object form: traceEvents list, every
    # event has name/ph/ts(+dur for X), numeric pid/tid — what
    # Perfetto / chrome://tracing validate on load.
    assert set(trace) >= {"traceEvents"}
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and len(evs) >= 3
    for e in evs:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] in ("X", "C", "i"):
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert {"outer", "inner"} <= set(xs)
    # inner nests inside outer on the timeline.
    assert xs["inner"]["ts"] >= xs["outer"]["ts"]
    assert (xs["inner"]["ts"] + xs["inner"]["dur"]
            <= xs["outer"]["ts"] + xs["outer"]["dur"] + 1e-3)
    assert xs["outer"]["args"]["label"] == "o"
    # depth tracked per thread: inner recorded at depth 1.
    inner_ev = next(e for e in rec.events if e["name"] == "inner")
    assert inner_ev["depth"] == 1
    # counters ride as "C" events and in the snapshot.
    assert any(e["ph"] == "C" and e["name"] == "things" for e in evs)
    snap = rec.snapshot()
    assert snap["counters"]["things"] == 3
    assert snap["histograms"]["lat_s"]["count"] == 1
    # the whole trace is json-serializable
    json.dumps(trace)


def test_span_error_annotated():
    rec = Recorder()
    with pytest.raises(ValueError):
        with rec.span("fails"):
            raise ValueError("nope")
    ev = next(e for e in rec.events if e["name"] == "fails")
    assert "ValueError" in ev["args"]["error"]


def test_maybe_span_is_noop_without_recorder():
    assert current_recorder() is None
    with maybe_span("nothing", a=1):
        pass  # no recorder: must not raise, must not record anywhere


def test_use_recorder_binds_and_restores():
    rec = Recorder()
    assert current_recorder() is None
    with use_recorder(rec):
        assert current_recorder() is rec
        with maybe_span("seen"):
            pass
    assert current_recorder() is None
    assert any(e["name"] == "seen" for e in rec.events)


def test_recorder_journals_spans(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    rec = Recorder(journal=j)
    with rec.span("stage.pre", fdate="20160122"):
        pass
    j.close()
    spans = [r for r in Journal.replay(path) if r.get("kind") == "span"]
    assert len(spans) == 1
    assert spans[0]["name"] == "stage.pre"
    assert spans[0]["dur_ns"] >= 0
    assert spans[0]["args"]["fdate"] == "20160122"


# ---------------------------------------------------------------------------
# heartbeat
# ---------------------------------------------------------------------------


def test_heartbeat_declares_lost_after_misses_and_check_raises(tmp_path):
    path = str(tmp_path / "j.jsonl")
    rj = RunJournal(Journal(path))
    hb = HeartbeatMonitor(
        interval_s=0.01, timeout_s=0.1, max_misses=2, journal=rj,
        probe=lambda t: None,          # backend never answers
        deep_probe=lambda t: None,     # subprocess probe agrees: dead
    )
    assert hb.beat_once() is False     # miss 1: not yet lost
    hb.check()
    assert hb.beat_once() is False     # miss 2: lost
    assert hb.lost.is_set()
    with pytest.raises(BackendLost):
        hb.check()
    rj.close()
    records = Journal.replay(path)
    kinds = [r["kind"] for r in records]
    assert kinds.count("heartbeat") == 2
    assert "backend_lost" in kinds
    lost = next(r for r in records if r["kind"] == "backend_lost")
    assert "subprocess probe" in lost["reason"]


def test_heartbeat_deep_probe_vetoes_loss():
    """An in-process wedge with a healthy grant (the subprocess probe
    answers) must NOT kill the run — misses reset."""
    hb = HeartbeatMonitor(
        interval_s=0.01, timeout_s=0.1, max_misses=1,
        probe=lambda t: None,
        deep_probe=lambda t: 4,        # fresh process sees 4 devices
    )
    assert hb.beat_once() is False
    assert not hb.lost.is_set()
    assert hb.misses == 0              # reset by the deep probe


def test_heartbeat_recovers_and_journals_latency(tmp_path):
    path = str(tmp_path / "j.jsonl")
    rj = RunJournal(Journal(path))
    answers = iter([None, 0.001, 0.002])
    hb = HeartbeatMonitor(
        interval_s=0.01, timeout_s=0.1, max_misses=3, journal=rj,
        probe=lambda t: next(answers), deep_probe=None,
    )
    assert hb.beat_once() is False
    assert hb.beat_once() is True      # recovered: misses reset
    assert hb.misses == 0
    assert hb.beat_once() is True
    hb.check()                         # never lost
    rj.close()
    beats = [r for r in Journal.replay(path) if r["kind"] == "heartbeat"]
    assert [b["ok"] for b in beats] == [False, True, True]
    assert beats[1]["latency_s"] > 0


def test_heartbeat_on_lost_callback_and_thread_lifecycle():
    fired = []
    hb = HeartbeatMonitor(
        interval_s=0.005, timeout_s=0.05, max_misses=1,
        probe=lambda t: None, deep_probe=None,
        on_lost=fired.append,
    )
    hb.start()
    hb.lost.wait(timeout=5.0)
    hb.stop()
    assert hb.lost.is_set()
    assert fired and "missed" in fired[0]


def test_heartbeat_pause_suspends_probing_and_resets_misses():
    """bench pauses the monitor around phase subprocesses: a paused
    loop must not probe (a busy healthy grant would miss), and resume
    forgets pre-pause misses."""
    calls = []

    def probe(t):
        calls.append(1)
        return None

    hb = HeartbeatMonitor(
        interval_s=0.01, timeout_s=0.05, max_misses=3,
        probe=probe, deep_probe=None,
    )
    assert hb.beat_once() is False and hb.misses == 1
    hb.pause()
    hb.start()
    import time as _time

    _time.sleep(0.1)              # several intervals while paused
    assert len(calls) == 1        # no probes fired under pause
    hb.resume()
    assert hb.misses == 0         # pause window says nothing
    hb.lost.wait(timeout=5.0)     # probing resumed: loss eventually
    hb.stop()
    assert hb.lost.is_set()


def test_heartbeat_real_device_probe_answers_on_cpu():
    """The production probe (tiny jitted add + transfer) against the
    test CPU backend: alive, with a measured latency."""
    from oni_ml_tpu.telemetry.heartbeat import device_add_probe

    lat = device_add_probe(timeout_s=60.0)
    assert lat is not None and lat > 0


# ---------------------------------------------------------------------------
# serving metrics on the shared registry
# ---------------------------------------------------------------------------


def test_metrics_emitter_feeds_shared_registry_and_journal(tmp_path):
    from oni_ml_tpu.serving import MetricsEmitter

    jpath = str(tmp_path / "serve.jsonl")
    j = Journal(jpath)
    rec = Recorder()
    m = MetricsEmitter(to_stdout=False, recorder=rec, journal=j)
    m.emit({"stage": "serve", "batch": 0, "events": 32, "flagged": 2,
            "latency_ms": 5.0, "score_ms": 1.25, "queue_depth": 3})
    m.emit({"stage": "serve", "batch": 1, "events": 16, "flagged": 0,
            "latency_ms": 7.0, "score_ms": 0.75, "queue_depth": 1})
    m.emit({"stage": "serve", "batch": 2, "events": 8, "error": "boom"})
    m.close()
    j.close()
    snap = m.snapshot()
    assert snap["counters"]["serve.emits"] == 3
    assert snap["counters"]["serve.events"] == 56
    assert snap["counters"]["serve.flagged"] == 2
    assert snap["counters"]["serve.errors"] == 1
    lat = snap["histograms"]["serve.latency_ms"]
    assert lat["count"] == 2 and lat["min"] == 5.0 and lat["max"] == 7.0
    # the deque record view is unchanged (test_serving.py's contract)
    assert len(m.records) == 3
    # every emit journaled as a serve record
    serves = [r for r in Journal.replay(jpath) if r["kind"] == "serve"]
    assert len(serves) == 3 and serves[0]["events"] == 32


def test_metrics_emitter_binds_ambient_recorder():
    from oni_ml_tpu.serving import MetricsEmitter

    rec = Recorder()
    with use_recorder(rec):
        m = MetricsEmitter(to_stdout=False)
    m.emit({"stage": "serve", "events": 4})
    assert rec.counters["serve.events"].value == 4


# ---------------------------------------------------------------------------
# trace_view tool
# ---------------------------------------------------------------------------


def test_trace_view_converts_journal_to_valid_chrome_trace(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools",
    ))
    import trace_view

    path = str(tmp_path / "run_journal.jsonl")
    rj = RunJournal(Journal(path))
    rj.run_start(fdate="20160122")
    rj.stage_begin("pre")
    rj.stage_end("pre", ok=True, wall_s=0.5, events=100)
    rj.stage_begin("lda")
    for i in range(3):
        rj.em_likelihood(i + 1, -100.0 + i, 0.1)
    rj.heartbeat(True, latency_s=0.001)
    rj.heartbeat(False, misses=1)
    rj.stage_end("lda", ok=True, wall_s=2.0)
    rj.stage_skipped("score", "outputs exist")
    rj.backend_lost(reason="test")
    rj.stage_begin("score")  # never ends: the killed run's last stage
    rj.close()

    records = trace_view.Journal.replay(path)
    trace = trace_view.journal_to_trace(records)
    evs = trace["traceEvents"]
    for e in evs:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] in ("X", "C", "i"):
            assert e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] >= 0
    names = [e["name"] for e in evs]
    assert "stage.pre" in names and "stage.lda" in names
    assert names.count("em likelihood") == 3
    assert "BACKEND LOST" in names
    assert "stage.score (unfinished)" in names
    json.dumps(trace)

    rows = trace_view.stage_summary(records)
    by = {r["stage"]: r for r in rows}
    assert by["lda"]["wall_s"] == 2.0 and by["lda"]["runs"] == 1
    assert by["score"]["skips"] == 1
    # CLI end-to-end: writes the trace file, prints the summary.
    out = str(tmp_path / "t.json")
    assert trace_view.main([path, "--out", out]) == 0
    with open(out) as f:
        assert "traceEvents" in json.load(f)


# The four grep-lints that lived here (monotonic-clock, tuned-constant,
# quantile, harvest-coverage) moved to the AST rule engine in
# oni_ml_tpu/analysis/ (same or stricter coverage, one suppression
# mechanism instead of per-lint allowlists).  tests/test_analysis.py
# enforces them now — including the live-repo clean run.
