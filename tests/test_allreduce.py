"""Unit suite for the distributed-EM collective layer: shard plans
(parallel/shard_plan.py), the deterministic tree reduction, and the
KV-ring transport (parallel/allreduce.py) driven in-process over a fake
coordination-client KV store — chunking, uneven rank counts, payload
asymmetry, failure relay, and timeouts, all without spawning a cluster
(tests/test_multihost.py owns the real 2-process paths).
"""

import pickle
import threading
import time

import numpy as np
import pytest

from oni_ml_tpu.parallel.allreduce import (
    Collective,
    PeerFailure,
    reduce_partials,
    tree_combine,
)
from oni_ml_tpu.parallel.shard_plan import (
    DEFAULT_EM_SHARDS,
    ShardPlan,
    plan_shards,
    resolve_em_shards,
)


# ---------------------------------------------------------------------------
# Shard plans
# ---------------------------------------------------------------------------


def test_plan_partitions_exactly():
    for d in (0, 1, 7, 80, 1001):
        for s in (1, 2, 8, 16):
            plan = plan_shards(d, 1, s)
            assert plan.num_shards == s
            assert plan.bounds[0][0] == 0
            assert plan.bounds[-1][1] == d
            for (a0, a1), (b0, b1) in zip(plan.bounds, plan.bounds[1:]):
                assert a1 == b0          # contiguous, ordered
            sizes = [e - st for st, e in plan.bounds]
            assert max(sizes) - min(sizes) <= 1
            assert sum(sizes) == d


def test_plan_bounds_invariant_to_rank_count():
    """THE byte-identity precondition: shard bounds depend on
    (num_docs, num_shards) only — owners change with the process
    count, the shards never do."""
    for p in (1, 2, 4, 8):
        assert plan_shards(1001, p, 8).bounds == plan_shards(1001, 1, 8).bounds


def test_plan_ownership_contiguous_and_balanced():
    plan = plan_shards(100, 3, 8)
    assert len(plan.owners) == 8
    assert sorted(set(plan.owners)) == [0, 1, 2]
    # Contiguous runs per rank, sizes differing by at most one.
    runs = [plan.owners.index(r) for r in (0, 1, 2)]
    assert runs == sorted(runs)
    counts = [plan.owners.count(r) for r in (0, 1, 2)]
    assert max(counts) - min(counts) <= 1
    assert not plan.aligned                 # 3 does not divide 8 evenly
    assert plan_shards(100, 2, 8).aligned
    assert plan_shards(100, 4, 8).aligned


def test_plan_validation():
    with pytest.raises(ValueError, match="cannot cover"):
        plan_shards(100, 4, 2)
    with pytest.raises(ValueError, match="at least one document shard"):
        resolve_em_shards(2, num_procs=4)
    assert resolve_em_shards(0, 1) == DEFAULT_EM_SHARDS
    assert resolve_em_shards(0, 16) == 16   # grown past the default
    assert resolve_em_shards(32, 2) == 32   # explicit wins


def test_plan_env_override(monkeypatch):
    monkeypatch.setenv("ONI_ML_TPU_EM_SHARDS", "4")
    assert resolve_em_shards(0, 2) == 4
    assert resolve_em_shards(16, 2) == 4    # env wins over config


def test_plan_record_roundtrip():
    plan = plan_shards(80, 2, 8)
    rec = plan.record(rank=1)
    assert rec["kind"] == "shard_plan"
    assert rec["owned_shards"] == [4, 5, 6, 7]
    assert rec["local_docs"] == 40
    assert rec["aligned"] is True
    assert len(rec["bounds"]) == 8


# ---------------------------------------------------------------------------
# Tree reduction
# ---------------------------------------------------------------------------


def test_tree_combine_alignment_invariance():
    """A contiguous aligned block's local combine is exactly the
    canonical subtree: combining per-rank roots equals combining all
    leaves at once, bit for bit — the cross-rank-count identity the
    artifacts contract rides on."""
    rng = np.random.default_rng(0)
    parts = [rng.standard_normal((33, 5)).astype(np.float32)
             for _ in range(8)]
    full = tree_combine(parts)
    for p in (1, 2, 4, 8):
        w = 8 // p
        roots = [tree_combine(parts[i * w:(i + 1) * w]) for i in range(p)]
        np.testing.assert_array_equal(tree_combine(roots), full)


def test_tree_combine_dicts_and_odd_tail():
    parts = [{"a": np.float32(i), "b": np.ones(2, np.float32) * i}
             for i in range(5)]
    out = tree_combine(parts)
    assert out["a"] == np.float32(10)
    np.testing.assert_array_equal(out["b"], np.ones(2, np.float32) * 10)
    with pytest.raises(ValueError):
        tree_combine([])


# ---------------------------------------------------------------------------
# KV-ring transport over a fake coordination client
# ---------------------------------------------------------------------------


class _MemKV:
    """In-process stand-in for the jaxlib DistributedRuntimeClient KV
    store: blocking gets with DEADLINE_EXCEEDED timeouts, write-once
    sets, deletes — enough to drive the ring across threads."""

    def __init__(self):
        self._d = {}
        self._cv = threading.Condition()

    def key_value_set(self, key, value, allow_overwrite=False):
        with self._cv:
            if key in self._d and not allow_overwrite:
                raise RuntimeError(f"ALREADY_EXISTS: {key}")
            self._d[key] = value
            self._cv.notify_all()

    def blocking_key_value_get(self, key, timeout_in_ms):
        deadline = time.monotonic() + timeout_in_ms / 1000.0
        with self._cv:
            while key not in self._d:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(f"DEADLINE_EXCEEDED: {key}")
                self._cv.wait(remaining)
            return self._d[key]

    def key_value_delete(self, key):
        with self._cv:
            self._d.pop(key, None)


def _ring(kv, nprocs, fn, timeout_s=20.0, max_chunk=64):
    """Run `fn(collective, rank)` on one thread per rank over a shared
    fake KV; returns per-rank results, re-raising the first error."""
    colls = [
        Collective(client=kv, rank=r, nprocs=nprocs, transport="kvring",
                   timeout_s=timeout_s, max_chunk_bytes=max_chunk)
        for r in range(nprocs)
    ]
    results: list = [None] * nprocs
    errors: list = [None] * nprocs

    def run(r):
        try:
            results[r] = fn(colls[r], r)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errors[r] = e

    threads = [threading.Thread(target=run, args=(r,))
               for r in range(nprocs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout_s + 10)
    for e in errors:
        if e is not None:
            raise e
    return results


@pytest.mark.parametrize("nprocs", [2, 3, 5])
def test_ring_allgather_objects(nprocs):
    kv = _MemKV()
    outs = _ring(kv, nprocs,
                 lambda c, r: c.allgather_obj({"rank": r}, "t"))
    for got in outs:
        assert got == [{"rank": r} for r in range(nprocs)]


def test_ring_allgather_arrays_chunked_uneven_payloads():
    """Chunking at a tiny bound plus per-rank payloads of DIFFERENT
    sizes (the unaligned reduce ships per-shard partials, so ranks
    legitimately send different byte counts)."""
    kv = _MemKV()

    def fn(c, r):
        named = {"x": np.arange((r + 1) * 40, dtype=np.float32)}
        return c.allgather_arrays(named, "arr")

    outs = _ring(kv, 3, fn, max_chunk=16)
    for got in outs:
        assert len(got) == 3
        for r in range(3):
            np.testing.assert_array_equal(
                got[r]["x"], np.arange((r + 1) * 40, dtype=np.float32)
            )
    # Single-reader ring keys were retired after the read; only the
    # (multi-reader) failure key namespace may remain.
    assert not kv._d, sorted(kv._d)


def test_ring_broadcast_and_barrier():
    kv = _MemKV()

    def fn(c, r):
        v = c.broadcast_obj({"model": "x"} if r == 0 else None, "bc")
        c.barrier("b")
        return v

    outs = _ring(kv, 3, fn)
    assert all(v == {"model": "x"} for v in outs)


def test_reduce_partials_aligned_matches_canonical_tree():
    """2 aligned ranks exchanging subtree roots reduce to the exact
    bytes a 1-rank reduction of the same shard partials produces."""
    rng = np.random.default_rng(1)
    parts = {s: {"ss": rng.standard_normal((17, 3)).astype(np.float32),
                 "ll": np.float32(rng.standard_normal())}
             for s in range(8)}
    want = tree_combine([parts[s] for s in range(8)])

    plan1 = plan_shards(100, 1, 8)
    coll1 = Collective(client=None, rank=0, nprocs=1, transport="local")
    got1 = reduce_partials(coll1, plan1, parts, "t")
    np.testing.assert_array_equal(got1["ss"], want["ss"])

    plan2 = plan_shards(100, 2, 8)
    kv = _MemKV()

    def fn(c, r):
        mine = {s: parts[s] for s in plan2.owned(r)}
        return reduce_partials(c, plan2, mine, "t")

    for got in _ring(kv, 2, fn):
        np.testing.assert_array_equal(got["ss"], want["ss"])
        np.testing.assert_array_equal(got["ll"], want["ll"])


def test_reduce_partials_unaligned_ships_per_shard():
    """3 ranks over 8 shards (unaligned): per-shard partials cross the
    ring and the canonical shard-order tree still applies — same bytes
    as the 1-rank reduction."""
    rng = np.random.default_rng(2)
    parts = {s: {"ss": rng.standard_normal((9, 2)).astype(np.float32)}
             for s in range(8)}
    want = tree_combine([parts[s] for s in range(8)])
    plan = plan_shards(100, 3, 8)
    assert not plan.aligned
    kv = _MemKV()

    def fn(c, r):
        mine = {s: parts[s] for s in plan.owned(r)}
        return reduce_partials(c, plan, mine, "t")

    for got in _ring(kv, 3, fn):
        np.testing.assert_array_equal(got["ss"], want["ss"])


def test_ring_wait_timeout_is_peer_failure():
    """A rank whose peer never shows terminates with the structured
    PeerFailure (bounded wait), not a hang."""
    kv = _MemKV()
    coll = Collective(client=kv, rank=0, nprocs=2, transport="kvring",
                      timeout_s=0.3)
    with pytest.raises(PeerFailure, match="timed out"):
        coll.allgather_obj(1, "never")


def test_fail_key_relays_structured_peer_failure():
    """A failing rank's posted failure surfaces on a BLOCKED peer
    within one poll slice as "failed on another rank" — the
    coordination-client health barrier of the mid-EM death contract."""
    kv = _MemKV()
    c0 = Collective(client=kv, rank=0, nprocs=2, transport="kvring",
                    timeout_s=30.0)
    c1 = Collective(client=kv, rank=1, nprocs=2, transport="kvring",
                    timeout_s=30.0)
    err: list = []

    def blocked():
        try:
            c0.allgather_obj(1, "x")
        except BaseException as e:  # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=blocked)
    t.start()
    time.sleep(0.1)
    c1.fail("boom in stage lda")
    t.join(30)
    assert err and isinstance(err[0], PeerFailure)
    assert "failed on another rank" in str(err[0])
    assert "boom in stage lda" in str(err[0])
    # ...and PeerFailure is BackendLost, so ml_ops exits rc=3.
    from oni_ml_tpu.telemetry import BackendLost

    assert isinstance(err[0], BackendLost)
    # A rank's own failure post never self-triggers.
    c1.check_peer_failure()


def test_failed_rank_drains_bounded(monkeypatch):
    """A rank that already posted its OWN failure must not wait the
    full collective timeout for barriers its (aborted) peers will
    never finish — its waits cap at the drain window and re-raise its
    own failure."""
    from oni_ml_tpu.parallel import allreduce as ar

    monkeypatch.setattr(ar, "FAIL_DRAIN_S", 0.2)
    kv = _MemKV()
    c = Collective(client=kv, rank=0, nprocs=3, transport="kvring",
                   timeout_s=60.0)
    c.fail("stage lda: boom")
    t0 = time.monotonic()
    with pytest.raises(PeerFailure, match="own failure.*boom"):
        c.allgather_obj(False, "outcome")
    assert time.monotonic() - t0 < 5.0


def test_fail_post_is_idempotent():
    kv = _MemKV()
    c = Collective(client=kv, rank=0, nprocs=2, transport="kvring")
    c.fail("first")
    c.fail("second")  # allow_overwrite — must not raise
    raw = kv.blocking_key_value_get("oni/ar/fail", 1)
    import base64

    rank, reason = pickle.loads(base64.b64decode(raw))
    assert rank == 0 and reason == "second"


def test_transport_selection_and_validation():
    c = Collective(client=None, rank=0, nprocs=1)
    assert c.transport == "local"
    assert c.allgather_arrays({"x": np.ones(3)}, "t") == [
        {"x": pytest.approx(np.ones(3))}
    ]
    assert c.broadcast_obj("v", "t") == "v"
    with pytest.raises(ValueError, match="unknown allreduce transport"):
        Collective(client=_MemKV(), rank=0, nprocs=2, transport="wat")


def test_psum_gather_single_process():
    """The ICI-path gather degenerates cleanly at one process (the
    shape every CPU test and the dryrun can execute; multi-host ICI
    numbers stay projections until a TPU grant)."""
    from oni_ml_tpu.parallel.allreduce import _psum_gather

    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = _psum_gather(x, 1)
    assert out.shape == (1, 3, 4)
    np.testing.assert_array_equal(out[0], x)
    # 8-byte dtypes transport bit-exact via the uint32 view (jax would
    # otherwise canonicalize f64 down to f32 with x64 off — the gamma
    # merge must not lose precision on the pod path).
    g = np.random.default_rng(3).standard_normal((5, 2))  # float64
    out64 = _psum_gather(g, 1)
    assert out64.dtype == np.float64
    np.testing.assert_array_equal(out64[0], g)


def test_allreduce_journal_record(monkeypatch):
    """Every data-plane op journals {"kind": "allreduce"} with bytes/
    rounds/wall through the active recorder, and the stats counters
    accumulate (what bench distributed_em and the lda stage record
    read)."""
    from oni_ml_tpu.telemetry.spans import Recorder, use_recorder

    class _Sink:
        def __init__(self):
            self.records = []

        def append(self, rec, sync=False):
            self.records.append(rec)

    sink = _Sink()
    rec = Recorder(journal=sink)
    kv = _MemKV()

    def fn(c, r):
        with use_recorder(rec):
            c.allgather_arrays({"x": np.ones(4, np.float32)}, "em1")
        return dict(c.stats)

    stats = _ring(kv, 2, fn)
    ars = [r for r in sink.records if r.get("kind") == "allreduce"]
    assert len(ars) == 2
    for a in ars:
        assert a["transport"] == "kvring"
        assert a["nprocs"] == 2
        assert a["rounds"] == 1
        assert a["bytes_out"] > 0 and a["bytes_in"] > 0
    for s in stats:
        assert s["ops"] == 1 and s["bytes_out"] > 0


# ---------------------------------------------------------------------------
# bf16-compressed payloads
# ---------------------------------------------------------------------------


def test_bf16_pack_roundtrip_tolerance():
    """Round-to-nearest-even bf16 pack: exact unpack back into f32
    with <= 2^-8 relative error on normal values, specials preserved."""
    from oni_ml_tpu.parallel.allreduce import _bf16_pack, _bf16_unpack

    rng = np.random.default_rng(0)
    x = (rng.standard_normal(4096).astype(np.float32)
         * np.float32(10.0) ** rng.integers(-6, 6, 4096))
    r = _bf16_unpack(_bf16_pack(x))
    np.testing.assert_allclose(r, x, rtol=2 ** -8, atol=0)
    specials = np.array([0.0, -0.0, np.inf, -np.inf], np.float32)
    np.testing.assert_array_equal(
        _bf16_unpack(_bf16_pack(specials)), specials)
    # NaN must survive the wire (a diverged rank's stats fail loudly,
    # never silently zero): every NaN bit pattern, incl. the
    # high-payload ones whose carry add would wrap to +/-0.0.
    nans = np.array([np.nan, -np.nan], np.float32)
    nans = np.concatenate([
        nans,
        np.array([0x7FFFFFFF, 0xFFFFFFFF, 0x7FC00001],
                 np.uint32).view(np.float32),
    ])
    assert np.isnan(_bf16_unpack(_bf16_pack(nans))).all()
    # f64 input packs through f32 (accumulation dtype is f32).
    assert _bf16_unpack(_bf16_pack(np.ones(3, np.float64))).dtype \
        == np.float32


def test_allgather_bf16_halves_bytes_rank_identical():
    """bf16 wire precision on the kvring: float payloads ship half the
    bytes, EVERY rank (sender included) unpacks the same bits, so the
    gathered arrays are rank-identical and within bf16 tolerance of
    the f32 wire; int arrays pass through untouched."""
    rng = np.random.default_rng(3)
    payloads = [
        {"ss": rng.standard_normal((128, 8)).astype(np.float32),
         "n": np.int64(7 + r)}
        for r in range(2)
    ]
    out = {}
    for precision in ("f32", "bf16"):
        kv = _MemKV()

        def fn(c, r, precision=precision):
            g = c.allgather_arrays(payloads[r], "t",
                                   precision=precision)
            return tree_combine(g), dict(c.stats)

        out[precision] = _ring(kv, 2, fn, max_chunk=1 << 20)
    (f32_a, s32), (f32_b, _) = out["f32"]
    (bf_a, s16), (bf_b, _) = out["bf16"]
    np.testing.assert_array_equal(f32_a["ss"], f32_b["ss"])
    np.testing.assert_array_equal(bf_a["ss"], bf_b["ss"])
    assert bf_a["n"] == f32_a["n"] == np.int64(7) + np.int64(8)
    np.testing.assert_allclose(bf_a["ss"], f32_a["ss"],
                               rtol=2 ** -7, atol=2 ** -6)
    assert not np.array_equal(bf_a["ss"], f32_a["ss"])
    assert s16["bytes_out"] < 0.62 * s32["bytes_out"]


def test_reduce_partials_bf16_parity_and_journal_precision():
    """The suff-stats reduce under precision="bf16": rank-identical
    reduced stats within tolerance of the f32 wire, and the
    {"kind": "allreduce"} record carries the APPLIED precision."""
    from oni_ml_tpu.telemetry.spans import Recorder, use_recorder

    class _Sink:
        def __init__(self):
            self.records = []

        def append(self, rec, sync=False):
            self.records.append(rec)

    rng = np.random.default_rng(5)
    parts = {s: {"ss": rng.standard_normal((17, 3)).astype(np.float32)}
             for s in range(8)}
    plan2 = plan_shards(100, 2, 8)

    def run(precision, sink):
        kv = _MemKV()
        rec = Recorder(journal=sink)

        def fn(c, r):
            mine = {s: parts[s] for s in plan2.owned(r)}
            with use_recorder(rec):
                return reduce_partials(c, plan2, mine, "t",
                                       precision=precision)

        return _ring(kv, 2, fn)

    sink32, sink16 = _Sink(), _Sink()
    got32 = run(None, sink32)
    got16 = run("bf16", sink16)
    np.testing.assert_array_equal(got16[0]["ss"], got16[1]["ss"])
    np.testing.assert_allclose(got16[0]["ss"], got32[0]["ss"],
                               rtol=2 ** -6, atol=2 ** -5)
    assert {r["precision"] for r in sink32.records
            if r.get("kind") == "allreduce"} == {"f32"}
    assert {r["precision"] for r in sink16.records
            if r.get("kind") == "allreduce"} == {"bf16"}


def test_collective_payload_precision_env_and_validation(monkeypatch):
    """ONI_ML_TPU_ALLREDUCE_PRECISION sets the collective default;
    junk values fail loudly at construction."""
    monkeypatch.setenv("ONI_ML_TPU_ALLREDUCE_PRECISION", "bf16")
    c = Collective(client=_MemKV(), rank=0, nprocs=1,
                   transport="local")
    assert c.payload_precision == "bf16"
    monkeypatch.delenv("ONI_ML_TPU_ALLREDUCE_PRECISION")
    c = Collective(client=_MemKV(), rank=0, nprocs=1,
                   transport="local")
    assert c.payload_precision == "f32"
    with pytest.raises(ValueError, match="payload_precision"):
        Collective(client=_MemKV(), rank=0, nprocs=1,
                   transport="local", payload_precision="f16")
