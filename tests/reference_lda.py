"""Slow, trusted NumPy implementation of variational-EM LDA used as the
test oracle for the JAX engine (SURVEY.md §4: "tests against ... a slow
trusted NumPy reference implementation on small corpora").

Implements the textbook Blei et al. (2003) coordinate ascent exactly as
reconstructed from the reference engine's contract (SURVEY.md §2.8/§3.3),
doc by doc, token by token — no batching, no padding, float64 throughout.
"""

from __future__ import annotations

import numpy as np
from scipy.special import digamma, gammaln


def e_step_doc(log_beta, alpha, words, counts, var_max_iters=20, var_tol=1e-6):
    """Per-document fixed point. Returns (gamma [K], phi [N, K], likelihood)."""
    K = log_beta.shape[0]
    from oni_ml_tpu.ops.stop import STALL_GATE

    n_total = counts.sum()
    gamma = np.full(K, alpha + n_total / K)
    beta_w = np.exp(log_beta[:, words])  # [K, N]
    prev_delta = np.inf
    for it in range(var_max_iters):
        e_lt = digamma(gamma) - digamma(gamma.sum())
        phi = beta_w.T * np.exp(e_lt)[None, :]  # [N, K]
        phi = phi / (phi.sum(-1, keepdims=True) + 1e-300)
        gamma_new = alpha + (phi * counts[:, None]).sum(0)
        # The engines' shared stop rule (oni_ml_tpu/ops/stop.py):
        # var_tol RELATIVE to the iteration-invariant per-doc mean gamma
        # (= alpha + N_d/K, since gamma sums to K*alpha + N_d exactly),
        # or gated stagnation (delta no longer shrinking once already
        # under STALL_GATE — the arithmetic's noise floor; in this
        # float64 oracle deltas decrease strictly until machine epsilon,
        # so it is mirrored for semantic alignment but effectively never
        # fires first).
        scale = alpha + n_total / K
        delta = np.abs(gamma_new - gamma).mean() / scale
        gamma = gamma_new
        if delta < var_tol:
            break
        if it > 0 and delta < STALL_GATE and delta >= prev_delta:
            break
        prev_delta = delta
    e_lt = digamma(gamma) - digamma(gamma.sum())
    phi = beta_w.T * np.exp(e_lt)[None, :]
    phinorm = phi.sum(-1)
    phi = phi / (phinorm[:, None] + 1e-300)
    # ELBO with beta as a point estimate (no beta-prior term), in the
    # collapsed form: sum_n c_n log(sum_k exp(E[log theta_k]) beta_kw)
    # + KL-ish gamma terms.
    ll = (
        (counts * np.log(phinorm + 1e-300)).sum()
        + gammaln(K * alpha)
        - K * gammaln(alpha)
        + ((alpha - gamma) * e_lt).sum()
        + gammaln(gamma).sum()
        - gammaln(gamma.sum())
    )
    return gamma, phi, ll


def em(docs, num_terms, num_topics, alpha=2.5, em_max_iters=50, em_tol=1e-4,
       var_max_iters=20, var_tol=1e-6, init_log_beta=None, estimate_alpha=False,
       seed=0):
    """docs: list of (words [N] int, counts [N] int). Returns dict with
    log_beta, gamma, likelihoods."""
    K, V = num_topics, num_terms
    rng = np.random.default_rng(seed)
    if init_log_beta is None:
        noise = rng.uniform(size=(K, V)) + 1.0 / V
        log_beta = np.log(noise / noise.sum(-1, keepdims=True))
    else:
        log_beta = np.array(init_log_beta, dtype=np.float64)

    D = len(docs)
    gamma_out = np.zeros((D, K))
    lls = []
    ll_prev = None
    for _ in range(em_max_iters):
        ss = np.zeros((K, V))
        total_ll = 0.0
        for d, (words, counts) in enumerate(docs):
            gamma, phi, ll = e_step_doc(
                log_beta, alpha, np.asarray(words), np.asarray(counts, np.float64),
                var_max_iters, var_tol,
            )
            gamma_out[d] = gamma
            total_ll += ll
            np.add.at(ss.T, words, phi * np.asarray(counts)[:, None])
        with np.errstate(divide="ignore"):
            log_beta = np.where(
                ss > 0, np.log(ss) - np.log(ss.sum(-1, keepdims=True)), -100.0
            )
        lls.append(total_ll)
        if ll_prev is not None and abs((ll_prev - total_ll) / ll_prev) < em_tol:
            break
        ll_prev = total_ll
    return {"log_beta": log_beta, "gamma": gamma_out, "likelihoods": lls,
            "alpha": alpha}


def make_synthetic_corpus(num_docs=60, num_terms=40, num_topics=3, seed=0,
                          doc_len_range=(5, 40)):
    """Generative LDA sample -> list of (words, counts) with every term id
    guaranteed in-range; returns (docs, true_beta)."""
    rng = np.random.default_rng(seed)
    beta = rng.dirichlet(np.full(num_terms, 0.1), size=num_topics)
    docs = []
    for _ in range(num_docs):
        theta = rng.dirichlet(np.full(num_topics, 0.5))
        n = rng.integers(*doc_len_range)
        z = rng.choice(num_topics, size=n, p=theta)
        w = np.array([rng.choice(num_terms, p=beta[zi]) for zi in z])
        uniq, cnt = np.unique(w, return_counts=True)
        docs.append((uniq.astype(np.int32), cnt.astype(np.int32)))
    return docs, beta


def docs_to_triples(docs, prefix="ip"):
    """-> (ip, word, count) triples for Corpus.from_word_counts."""
    out = []
    for d, (words, counts) in enumerate(docs):
        for w, c in zip(words, counts):
            out.append((f"{prefix}{d}", f"w{int(w)}", int(c)))
    return out
