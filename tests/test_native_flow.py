"""Native (C++) flow featurizer vs the pure-Python path.

The native path must be featurization-identical: same kept rows, same
numeric columns, same words, same first-seen-order word counts, same
scored output.  Skips when the native lib can't build (no g++).
"""

import pickle

import numpy as np
import pytest

from oni_ml_tpu.features import flow as pyflow
from oni_ml_tpu.features import native_flow

from test_features import flow_row

pytestmark = pytest.mark.skipif(
    not native_flow.available(), reason="native flow featurizer unavailable"
)


def make_day(tmp_path, n=500, seed=7, with_edge_rows=True):
    rng = np.random.default_rng(seed)
    lines = ["word,count,header"]
    for _ in range(n):
        lines.append(
            flow_row(
                hour=int(rng.integers(0, 24)),
                minute=int(rng.integers(0, 60)),
                second=int(rng.integers(0, 60)),
                sip=f"10.0.{rng.integers(0, 4)}.{rng.integers(1, 60)}",
                dip=f"172.16.{rng.integers(0, 4)}.{rng.integers(1, 60)}",
                col10=str(rng.choice([80, 443, 55000, 0, 1024, 1025])),
                col11=str(rng.choice([80, 6000, 70000, 0, 1024])),
                ipkt=str(rng.integers(1, 100)),
                ibyt=str(rng.integers(40, 10000)),
            )
        )
    if with_edge_rows:
        lines.insert(5, "word,count,header")        # duplicate header
        lines.insert(7, "short,row")                # wrong field count
        lines.append(",".join(["##"] * 27))         # NaN everything
        lines.append(flow_row(col10="0", col11="0"))      # both ports zero
        lines.append(flow_row(col10="80", col11="80"))    # equal ports
        lines.append(flow_row(col10="bogus", col11="80"))  # NaN port
        # Overflow/underflow numerals: Python float() saturates to
        # inf / 0.0; the native parser must match, not yield NaN.
        lines.append(flow_row(ibyt="1e999", ipkt="1e-999"))
        lines.append(flow_row(col10="1e999", col11="80"))
    path = tmp_path / "flow.csv"
    path.write_text("\n".join(lines) + "\n")
    return path, lines


def featurize_both(tmp_path, feedback_rows=(), **kw):
    path, lines = make_day(tmp_path, **kw)
    with open(path) as f:
        py = pyflow.featurize_flow(
            (line.rstrip("\n") for line in f), feedback_rows=feedback_rows
        )
    nat = native_flow.featurize_flow_file(
        str(path), feedback_rows=feedback_rows
    )
    assert isinstance(nat, native_flow.NativeFlowFeatures)
    return py, nat


def assert_parity(py, nat):
    assert nat.num_events == py.num_events
    assert nat.num_raw_events == py.num_raw_events
    np.testing.assert_array_equal(nat.time_cuts, py.time_cuts)
    np.testing.assert_array_equal(nat.ibyt_cuts, py.ibyt_cuts)
    np.testing.assert_array_equal(nat.ipkt_cuts, py.ipkt_cuts)
    np.testing.assert_array_equal(nat.num_time, py.num_time)
    np.testing.assert_array_equal(nat.time_bin, py.time_bin)
    np.testing.assert_array_equal(nat.ibyt_bin, py.ibyt_bin)
    np.testing.assert_array_equal(nat.ipkt_bin, py.ipkt_bin)
    assert nat.word_port == py.word_port
    assert nat.src_word == py.src_word
    assert nat.dest_word == py.dest_word
    assert nat.ip_pair == py.ip_pair
    assert nat.rows == py.rows
    assert nat.word_counts() == py.word_counts()
    for i in range(0, py.num_events, max(1, py.num_events // 7)):
        assert nat.featurized_row(i) == py.featurized_row(i)
        assert nat.sip(i) == py.sip(i)
        assert nat.dip(i) == py.dip(i)


def test_parity_random_day(tmp_path):
    py, nat = featurize_both(tmp_path)
    assert_parity(py, nat)


def test_parity_with_feedback(tmp_path):
    fb = [flow_row(sip="9.9.9.9", dip="8.8.8.8", col10="80", col11="55000")] * 7
    py, nat = featurize_both(tmp_path, feedback_rows=fb)
    assert_parity(py, nat)
    # Feedback rows train but are not scored.
    assert nat.num_events == nat.num_raw_events + 7


def test_parity_precomputed_cuts(tmp_path):
    path, _ = make_day(tmp_path)
    cuts = (
        np.linspace(0, 20, 10),
        np.linspace(0, 9000, 10),
        np.linspace(0, 80, 5),
    )
    with open(path) as f:
        py = pyflow.featurize_flow(
            (line.rstrip("\n") for line in f), precomputed_cuts=cuts
        )
    nat = native_flow.featurize_flow_file(str(path), precomputed_cuts=cuts)
    assert_parity(py, nat)


def test_parity_long_cut_lists(tmp_path):
    # >15 cuts used to alias the native word cache's packed key; 12-bit
    # fields must keep words exact for any realistic cut list.
    path, _ = make_day(tmp_path)
    cuts = (
        np.linspace(0, 23, 20),
        np.linspace(0, 9000, 20),
        np.linspace(0, 80, 17),
    )
    with open(path) as f:
        py = pyflow.featurize_flow(
            (line.rstrip("\n") for line in f), precomputed_cuts=cuts
        )
    nat = native_flow.featurize_flow_file(str(path), precomputed_cuts=cuts)
    assert_parity(py, nat)


def test_absurd_cut_lists_rejected(tmp_path):
    path, _ = make_day(tmp_path, n=10)
    cuts = (np.zeros(5000), np.zeros(10), np.zeros(5))
    with pytest.raises(ValueError, match="4095"):
        native_flow.featurize_flow_file(str(path), precomputed_cuts=cuts)


def test_directory_path_errors(tmp_path):
    # fread on a directory yields 0 bytes + error; must raise, not return
    # an empty day.
    with pytest.raises(OSError):
        native_flow.featurize_flow_file(str(tmp_path))


def test_pickle_roundtrip(tmp_path):
    _, nat = featurize_both(tmp_path, n=50)
    again = pickle.loads(pickle.dumps(nat))
    assert again.word_counts() == nat.word_counts()
    assert again.featurized_row(3) == nat.featurized_row(3)
    assert again.num_raw_events == nat.num_raw_events


def test_scoring_identical(tmp_path):
    from oni_ml_tpu.scoring import ScoringModel, score_flow

    py, nat = featurize_both(tmp_path)
    k = 4
    rng = np.random.default_rng(0)
    ips = sorted({ip for ip, _, _ in py.word_counts()})
    words = sorted({w for _, w, _ in py.word_counts()})
    model = ScoringModel.from_results(
        doc_names=ips,
        doc_topic=rng.dirichlet(np.ones(k), size=len(ips)),
        vocab=words,
        word_topic=rng.dirichlet(np.ones(k), size=len(words)),
        fallback=0.05,
    )
    rows_py, s_py = score_flow(py, model, threshold=1.1)
    rows_nat, s_nat = score_flow(nat, model, threshold=1.1)
    assert rows_py == rows_nat
    np.testing.assert_array_equal(s_py, s_nat)
