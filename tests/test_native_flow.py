"""Native (C++) flow featurizer vs the pure-Python path.

The native path must be featurization-identical: same kept rows, same
numeric columns, same words, same first-seen-order word counts, same
scored output.  Skips when the native lib can't build (no g++).
"""

import pickle

import numpy as np
import pytest

from oni_ml_tpu.features import flow as pyflow
from oni_ml_tpu.features import native_flow

from test_features import flow_row

pytestmark = pytest.mark.skipif(
    not native_flow.available(), reason="native flow featurizer unavailable"
)


def make_day(tmp_path, n=500, seed=7, with_edge_rows=True):
    rng = np.random.default_rng(seed)
    lines = ["word,count,header"]
    for _ in range(n):
        lines.append(
            flow_row(
                hour=int(rng.integers(0, 24)),
                minute=int(rng.integers(0, 60)),
                second=int(rng.integers(0, 60)),
                sip=f"10.0.{rng.integers(0, 4)}.{rng.integers(1, 60)}",
                dip=f"172.16.{rng.integers(0, 4)}.{rng.integers(1, 60)}",
                col10=str(rng.choice([80, 443, 55000, 0, 1024, 1025])),
                col11=str(rng.choice([80, 6000, 70000, 0, 1024])),
                ipkt=str(rng.integers(1, 100)),
                ibyt=str(rng.integers(40, 10000)),
            )
        )
    if with_edge_rows:
        lines.insert(5, "word,count,header")        # duplicate header
        lines.insert(7, "short,row")                # wrong field count
        lines.append(",".join(["##"] * 27))         # NaN everything
        lines.append(flow_row(col10="0", col11="0"))      # both ports zero
        lines.append(flow_row(col10="80", col11="80"))    # equal ports
        lines.append(flow_row(col10="bogus", col11="80"))  # NaN port
        # Overflow/underflow numerals: Python float() saturates to
        # inf / 0.0; the native parser must match, not yield NaN.
        lines.append(flow_row(ibyt="1e999", ipkt="1e-999"))
        lines.append(flow_row(col10="1e999", col11="80"))
    path = tmp_path / "flow.csv"
    path.write_text("\n".join(lines) + "\n")
    return path, lines


def featurize_both(tmp_path, feedback_rows=(), **kw):
    path, lines = make_day(tmp_path, **kw)
    with open(path) as f:
        py = pyflow.featurize_flow(
            (line.rstrip("\n") for line in f), feedback_rows=feedback_rows
        )
    nat = native_flow.featurize_flow_file(
        str(path), feedback_rows=feedback_rows
    )
    assert isinstance(nat, native_flow.NativeFlowFeatures)
    return py, nat


def assert_parity(py, nat):
    assert nat.num_events == py.num_events
    assert nat.num_raw_events == py.num_raw_events
    np.testing.assert_array_equal(nat.time_cuts, py.time_cuts)
    np.testing.assert_array_equal(nat.ibyt_cuts, py.ibyt_cuts)
    np.testing.assert_array_equal(nat.ipkt_cuts, py.ipkt_cuts)
    np.testing.assert_array_equal(nat.num_time, py.num_time)
    np.testing.assert_array_equal(nat.time_bin, py.time_bin)
    np.testing.assert_array_equal(nat.ibyt_bin, py.ibyt_bin)
    np.testing.assert_array_equal(nat.ipkt_bin, py.ipkt_bin)
    assert nat.word_port == py.word_port
    assert nat.src_word == py.src_word
    assert nat.dest_word == py.dest_word
    assert nat.ip_pair == py.ip_pair
    assert nat.rows == py.rows
    assert nat.word_counts() == py.word_counts()
    for i in range(0, py.num_events, max(1, py.num_events // 7)):
        assert nat.featurized_row(i) == py.featurized_row(i)
        assert nat.sip(i) == py.sip(i)
        assert nat.dip(i) == py.dip(i)


def test_parity_random_day(tmp_path):
    py, nat = featurize_both(tmp_path)
    assert_parity(py, nat)


def test_parity_with_feedback(tmp_path):
    fb = [flow_row(sip="9.9.9.9", dip="8.8.8.8", col10="80", col11="55000")] * 7
    py, nat = featurize_both(tmp_path, feedback_rows=fb)
    assert_parity(py, nat)
    # Feedback rows train but are not scored.
    assert nat.num_events == nat.num_raw_events + 7


def test_parity_precomputed_cuts(tmp_path):
    path, _ = make_day(tmp_path)
    cuts = (
        np.linspace(0, 20, 10),
        np.linspace(0, 9000, 10),
        np.linspace(0, 80, 5),
    )
    with open(path) as f:
        py = pyflow.featurize_flow(
            (line.rstrip("\n") for line in f), precomputed_cuts=cuts
        )
    nat = native_flow.featurize_flow_file(str(path), precomputed_cuts=cuts)
    assert_parity(py, nat)


def test_parity_long_cut_lists(tmp_path):
    # >15 cuts used to alias the native word cache's packed key; 12-bit
    # fields must keep words exact for any realistic cut list.
    path, _ = make_day(tmp_path)
    cuts = (
        np.linspace(0, 23, 20),
        np.linspace(0, 9000, 20),
        np.linspace(0, 80, 17),
    )
    with open(path) as f:
        py = pyflow.featurize_flow(
            (line.rstrip("\n") for line in f), precomputed_cuts=cuts
        )
    nat = native_flow.featurize_flow_file(str(path), precomputed_cuts=cuts)
    assert_parity(py, nat)


def test_absurd_cut_lists_rejected(tmp_path):
    path, _ = make_day(tmp_path, n=10)
    cuts = (np.zeros(5000), np.zeros(10), np.zeros(5))
    with pytest.raises(ValueError, match="4095"):
        native_flow.featurize_flow_file(str(path), precomputed_cuts=cuts)


def test_empty_directory_errors(tmp_path):
    # A directory input expands to its files; an EMPTY expansion must
    # raise, not return an empty day.
    with pytest.raises(OSError, match="no flow input files"):
        native_flow.featurize_flow_file(str(tmp_path))


def test_multi_file_ingest_matches_concatenated(tmp_path):
    """Comma list / glob / directory inputs featurize identically to
    the concatenated single file: one joint ECDF over the union, part
    headers dropped (the reference's removeHeader over an HDFS
    location, flow_pre_lda.scala:249)."""
    path, lines = make_day(tmp_path, n=400)
    # Split into three "part files", each carrying the same header line
    # (Spark part files all carry it; removeHeader drops the copies).
    header, rows = lines[0], lines[1:]
    parts_dir = tmp_path / "parts"
    parts_dir.mkdir()
    for i, chunk in enumerate((rows[:150], rows[150:300], rows[300:])):
        (parts_dir / f"part-{i:05d}.csv").write_text(
            "\n".join([header] + chunk) + "\n"
        )
    whole = native_flow.featurize_flow_file(str(path))
    for spec in (
        ",".join(
            str(parts_dir / f"part-{i:05d}.csv") for i in range(3)
        ),
        str(parts_dir / "part-*.csv"),
        str(parts_dir),
    ):
        multi = native_flow.featurize_flow_file(spec)
        assert_parity(multi, whole) if isinstance(
            multi, native_flow.NativeFlowFeatures
        ) else None
        assert multi.num_events == whole.num_events
        assert multi.word_counts() == whole.word_counts()
        assert multi.rows == whole.rows


def test_job_output_markers_skipped(tmp_path):
    """Spark hiddenFileFilter semantics: a real job-output dir carries
    _SUCCESS / .part-*.crc / _metadata markers alongside the part
    files — directory and glob expansion must skip '_'/'.'-prefixed
    names (they are checksums/flags, not data), while explicitly named
    files always pass."""
    path, lines = make_day(tmp_path, n=60)
    out_dir = tmp_path / "job_out"
    out_dir.mkdir()
    (out_dir / "part-00000.csv").write_text("\n".join(lines) + "\n")
    (out_dir / "_SUCCESS").write_text("")
    (out_dir / "_metadata").write_bytes(b"\x00\x01binary")
    (out_dir / ".part-00000.csv.crc").write_bytes(b"\x00crc")
    assert native_flow.expand_flow_paths(str(out_dir)) == [
        str(out_dir / "part-00000.csv")
    ]
    assert native_flow.expand_flow_paths(str(out_dir / "*")) == [
        str(out_dir / "part-00000.csv")
    ]
    # Explicit naming bypasses the filter.
    assert native_flow.expand_flow_paths(str(out_dir / "_SUCCESS")) == [
        str(out_dir / "_SUCCESS")
    ]
    # A glob matching day DIRECTORIES expands each like the directory
    # branch (multi-day spec: /data/flow/2016*) — never returns a
    # directory path for the reader to open().
    assert native_flow.expand_flow_paths(str(tmp_path / "job_*")) == [
        str(out_dir / "part-00000.csv")
    ]
    # Hidden DIRECTORIES matched by a glob are skipped too (_logs/,
    # mid-job _temporary/ attempt dirs).
    logs = out_dir / "_logs"
    logs.mkdir()
    (logs / "history.csv").write_text("not,flow,data\n")
    assert native_flow.expand_flow_paths(str(out_dir / "*")) == [
        str(out_dir / "part-00000.csv")
    ]
    # ...but a pattern whose basename itself starts with '_' is a
    # deliberate selection of hidden names and passes.
    assert native_flow.expand_flow_paths(str(out_dir / "_SUC*")) == [
        str(out_dir / "_SUCCESS")
    ]
    whole = native_flow.featurize_flow_file(str(path))
    multi = native_flow.featurize_flow_file(str(out_dir))
    assert multi.num_events == whole.num_events
    assert multi.word_counts() == whole.word_counts()


def test_multi_file_python_fallback_matches(tmp_path):
    """The pure-Python fallback chains files with the same header
    semantics as the native path."""
    from itertools import chain

    path, lines = make_day(tmp_path, n=120)
    header, rows = lines[0], lines[1:]
    p1, p2 = tmp_path / "a.csv", tmp_path / "b.csv"
    p1.write_text("\n".join([header] + rows[:60]) + "\n")
    p2.write_text("\n".join([header] + rows[60:]) + "\n")
    with open(path) as f:
        whole = pyflow.featurize_flow(line.rstrip("\n") for line in f)
    from oni_ml_tpu.features.lineio import iter_raw_lines

    multi = pyflow.featurize_flow(
        chain.from_iterable(iter_raw_lines(str(p)) for p in (p1, p2))
    )
    assert multi.num_events == whole.num_events
    assert multi.word_counts() == whole.word_counts()
    assert multi.rows == whole.rows


def test_pickle_roundtrip(tmp_path):
    _, nat = featurize_both(tmp_path, n=50)
    again = pickle.loads(pickle.dumps(nat))
    assert again.word_counts() == nat.word_counts()
    assert again.featurized_row(3) == nat.featurized_row(3)
    assert again.num_raw_events == nat.num_raw_events


def test_scoring_identical(tmp_path):
    from oni_ml_tpu.scoring import ScoringModel, score_flow

    py, nat = featurize_both(tmp_path)
    k = 4
    rng = np.random.default_rng(0)
    ips = sorted({ip for ip, _, _ in py.word_counts()})
    words = sorted({w for _, w, _ in py.word_counts()})
    model = ScoringModel.from_results(
        doc_names=ips,
        doc_topic=rng.dirichlet(np.ones(k), size=len(ips)),
        vocab=words,
        word_topic=rng.dirichlet(np.ones(k), size=len(words)),
        fallback=0.05,
    )
    rows_py, s_py = score_flow(py, model, threshold=1.1)
    rows_nat, s_nat = score_flow(nat, model, threshold=1.1)
    assert rows_py == rows_nat
    np.testing.assert_array_equal(s_py, s_nat)


def test_spill_parity_and_pickle_bound(tmp_path):
    """spill_path streams raw rows to disk at ingest: identical
    featurization/rows/scoring surface, and features.pkl stays small
    because it references the spill file instead of embedding the
    bytes (VERDICT r2 weak-item 2)."""
    from oni_ml_tpu.features.blob import MmapBlob

    path, _ = make_day(tmp_path)
    nat = native_flow.featurize_flow_file(str(path))
    spill = native_flow.featurize_flow_file(
        str(path), spill_path=str(tmp_path / "raw_lines.bin")
    )
    assert isinstance(spill.lines_blob, MmapBlob)
    assert len(spill.lines_blob) == len(nat.lines_blob)
    assert spill.rows == nat.rows
    for i in range(0, nat.num_events, max(1, nat.num_events // 7)):
        assert spill.featurized_row(i) == nat.featurized_row(i)
    assert spill.word_counts() == nat.word_counts()

    # The pickle must NOT embed the blob: bound it by the non-blob data.
    import pickle as pkl

    spill_pkl = pkl.dumps(spill)
    assert len(spill_pkl) < len(pkl.dumps(nat)) - len(nat.lines_blob) // 2
    again = pkl.loads(spill_pkl)
    assert again.rows == nat.rows

    # Post-hoc spill of an in-memory container reaches the same state.
    nat.spill_lines(str(tmp_path / "raw_lines2.bin"))
    assert isinstance(nat.lines_blob, MmapBlob)
    assert nat.rows == spill.rows
    nat.spill_lines(str(tmp_path / "raw_lines3.bin"))  # idempotent no-op
    assert nat.lines_blob.path == str(tmp_path / "raw_lines2.bin")


def test_spill_with_feedback_and_scoring(tmp_path):
    """Feedback rows ingested after mark_raw append to the spill file;
    native emit reads rows through the mmap and must produce the exact
    bytes of the in-memory path."""
    from oni_ml_tpu.scoring import ScoringModel, score_flow_csv

    fb = [flow_row(sip="9.9.9.9", dip="8.8.8.8", col10="80",
                   col11="55000")] * 5
    path, _ = make_day(tmp_path)
    nat = native_flow.featurize_flow_file(str(path), feedback_rows=fb)
    spill = native_flow.featurize_flow_file(
        str(path), feedback_rows=fb,
        spill_path=str(tmp_path / "raw_lines.bin"),
    )
    assert spill.num_events == nat.num_events
    assert spill.num_raw_events == nat.num_raw_events

    k = 4
    rng = np.random.default_rng(0)
    ips = sorted({ip for ip, _, _ in nat.word_counts()})
    words = sorted({w for _, w, _ in nat.word_counts()})
    model = ScoringModel.from_results(
        doc_names=ips,
        doc_topic=rng.dirichlet(np.ones(k), size=len(ips)),
        vocab=words,
        word_topic=rng.dirichlet(np.ones(k), size=len(words)),
        fallback=0.05,
    )
    blob_nat, s_nat = score_flow_csv(nat, model, threshold=1.1)
    blob_spill, s_spill = score_flow_csv(spill, model, threshold=1.1)
    assert blob_nat == blob_spill
    np.testing.assert_array_equal(s_nat, s_spill)


def test_spill_bounds_rss(tmp_path):
    """Featurizing a day with spill_path must keep the high-water RSS
    well below input size + numeric arrays: the raw bytes never live in
    RAM (VERDICT r2 'Done = a test featurizing a synthetic day with RSS
    bounded well below input size').  Measured in a subprocess so other
    tests' allocations can't mask the high-water mark; rows carry a fat
    pad column so the blob dominates the per-event arrays."""
    import subprocess
    import sys
    import textwrap

    pad = "x" * 400
    n = 60_000
    day = tmp_path / "fat_day.csv"
    with open(day, "w") as f:
        f.write("h1,h2,h3\n")
        for i in range(n):
            f.write(
                f"a,b,c,{i % 24},{i % 60},{i % 60},d,e,10.0.0.{i % 250},"
                f"10.1.0.{i % 250},1024,{80 + i % 3},TCP,{pad},0,0,"
                f"{1 + i % 90},{40 + i % 9000},0,0,0,0,0,0,0,x,y\n"
            )
    input_bytes = day.stat().st_size
    assert input_bytes > 25 * 1024**2

    # VmHWM (resets on exec) rather than ru_maxrss (which Linux
    # carries ACROSS execve — a child forked from the jax-loaded pytest
    # process inherits its ~170MB high-water mark).
    script = textwrap.dedent(
        """
        import sys
        from oni_ml_tpu.features import native_flow
        spill = len(sys.argv) > 2
        kw = {"spill_path": sys.argv[2]} if spill else {}
        feats = native_flow.featurize_flow_file(sys.argv[1], **kw)
        assert feats.num_events > 0
        hwm = [l for l in open("/proc/self/status") if l.startswith("VmHWM")]
        print(hwm[0].split()[1])
        """
    )

    import os

    env = dict(os.environ)
    # Strip the axon sitecustomize path: it imports jax at interpreter
    # start (~150MB RSS), swamping the measurement.
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if "axon" not in p
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def rss_kb(*args):
        out = subprocess.run(
            [sys.executable, "-c", script, *args],
            capture_output=True, text=True, check=True, env=env,
        )
        return int(out.stdout.strip())

    # Baseline: the same interpreter + imports with no featurize work,
    # measured on THIS host so the bounds are relative, not absolute.
    idle_script = script.replace(
        "feats = native_flow.featurize_flow_file(sys.argv[1], **kw)\n"
        "assert feats.num_events > 0",
        "feats = None",
    )
    assert "feats = None" in idle_script

    def idle_kb():
        out = subprocess.run(
            [sys.executable, "-c", idle_script, str(day)],
            capture_output=True, text=True, check=True, env=env,
        )
        return int(out.stdout.strip())

    idle = idle_kb()
    spill_kb = rss_kb(str(day), str(tmp_path / "s1.bin"))
    plain_kb = rss_kb(str(day))
    # The in-memory run must carry ~the whole blob over the spill run...
    assert (plain_kb - spill_kb) * 1024 > 0.6 * input_bytes
    # ...and the spill run's increment over the idle baseline must stay
    # well below input size — i.e. the blob is never resident; what
    # remains is the numeric per-event arrays and table interning.
    assert (spill_kb - idle) * 1024 < 0.6 * input_bytes
