"""Fused (device-resident chunked) EM vs the stepwise driver.

The two drivers must produce the same training trajectory: the fused loop
only changes WHERE the loop control runs (device vs host), not the math.
"""

import numpy as np
import pytest

from oni_ml_tpu.config import LDAConfig
from oni_ml_tpu.io import make_batches
from oni_ml_tpu.models import LDATrainer, train_corpus

import reference_lda as ref
from test_lda import corpus_from_docs


@pytest.fixture(scope="module")
def problem():
    docs, _ = ref.make_synthetic_corpus(
        num_docs=48, num_terms=40, num_topics=3, seed=11
    )
    return corpus_from_docs(docs, 40)


def run(corpus, **cfg_kw):
    cfg = LDAConfig(
        num_topics=4, alpha_init=2.5, batch_size=16, min_bucket_len=4,
        seed=3, **cfg_kw
    )
    return train_corpus(corpus, cfg)


def test_fused_matches_stepwise_fixed_iters(problem):
    # em_tol=0 pins the iteration count; small batch/bucket sizes force
    # multiple shape groups and multiple batches per group.
    a = run(problem, em_max_iters=6, em_tol=0.0, fused_em_chunk=0)
    b = run(problem, em_max_iters=6, em_tol=0.0, fused_em_chunk=4)
    assert a.em_iters == b.em_iters == 6
    np.testing.assert_allclose(
        [l for l, _ in a.likelihoods], [l for l, _ in b.likelihoods],
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.exp(a.log_beta), np.exp(b.log_beta), atol=1e-4
    )
    np.testing.assert_allclose(a.gamma, b.gamma, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(a.alpha, b.alpha, rtol=1e-4)


def test_fused_convergence_stop(problem):
    # A loose tolerance converges well before em_max_iters; the on-device
    # check must stop at the same iteration as the host-side check.
    a = run(problem, em_max_iters=50, em_tol=1e-3, fused_em_chunk=0)
    b = run(problem, em_max_iters=50, em_tol=1e-3, fused_em_chunk=8)
    assert a.em_iters < 50  # the tolerance actually fired
    assert b.em_iters == a.em_iters
    assert len(b.likelihoods) == b.em_iters
    assert b.likelihoods[-1][1] < 1e-3  # logged conv reflects the stop


def test_fused_chunk_boundaries_do_not_matter(problem):
    # chunk=1..3 slice the same 5 iterations differently; results agree.
    runs = [
        run(problem, em_max_iters=5, em_tol=0.0, fused_em_chunk=c)
        for c in (2, 3, 5)
    ]
    for r in runs[1:]:
        np.testing.assert_allclose(
            [l for l, _ in runs[0].likelihoods],
            [l for l, _ in r.likelihoods],
            rtol=1e-5,
        )


def test_fused_progress_and_likelihood_stream(problem, tmp_path):
    seen = []
    cfg = LDAConfig(
        num_topics=4, em_max_iters=5, em_tol=0.0, batch_size=16,
        min_bucket_len=4, fused_em_chunk=2, seed=3,
    )
    out = tmp_path / "day"
    out.mkdir()
    res = train_corpus(
        problem, cfg, out_dir=str(out),
        progress=lambda it, ll, conv: seen.append((it, ll, conv)),
    )
    assert [it for it, _, _ in seen] == [1, 2, 3, 4, 5]
    lines = (out / "likelihood.dat").read_text().strip().splitlines()
    assert len(lines) == 5
    ll0 = float(lines[0].split("\t")[0])
    np.testing.assert_allclose(ll0, res.likelihoods[0][0], rtol=1e-6)
