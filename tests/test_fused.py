"""Fused (device-resident chunked) EM vs the stepwise driver.

The two drivers must produce the same training trajectory: the fused loop
only changes WHERE the loop control runs (device vs host), not the math.
"""

import numpy as np
import pytest

from oni_ml_tpu.config import LDAConfig
from oni_ml_tpu.io import make_batches
from oni_ml_tpu.models import LDATrainer, train_corpus

import reference_lda as ref
from test_lda import corpus_from_docs


@pytest.fixture(scope="module")
def problem():
    docs, _ = ref.make_synthetic_corpus(
        num_docs=48, num_terms=40, num_topics=3, seed=11
    )
    return corpus_from_docs(docs, 40)


def run(corpus, **cfg_kw):
    cfg = LDAConfig(
        num_topics=4, alpha_init=2.5, batch_size=16, min_bucket_len=4,
        seed=3, **cfg_kw
    )
    return train_corpus(corpus, cfg)


def test_fused_matches_stepwise_fixed_iters(problem):
    # em_tol=0 pins the iteration count; small batch/bucket sizes force
    # multiple shape groups and multiple batches per group.
    a = run(problem, em_max_iters=6, em_tol=0.0, fused_em_chunk=0)
    b = run(problem, em_max_iters=6, em_tol=0.0, fused_em_chunk=4)
    assert a.em_iters == b.em_iters == 6
    np.testing.assert_allclose(
        [l for l, _ in a.likelihoods], [l for l, _ in b.likelihoods],
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.exp(a.log_beta), np.exp(b.log_beta), atol=1e-4
    )
    np.testing.assert_allclose(a.gamma, b.gamma, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(a.alpha, b.alpha, rtol=1e-4)


def test_fused_convergence_stop(problem):
    # A loose tolerance converges well before em_max_iters; the on-device
    # check must stop at the same iteration as the host-side check.
    a = run(problem, em_max_iters=50, em_tol=1e-3, fused_em_chunk=0)
    b = run(problem, em_max_iters=50, em_tol=1e-3, fused_em_chunk=8)
    assert a.em_iters < 50  # the tolerance actually fired
    assert b.em_iters == a.em_iters
    assert len(b.likelihoods) == b.em_iters
    assert b.likelihoods[-1][1] < 1e-3  # logged conv reflects the stop


def test_fused_chunk_boundaries_do_not_matter(problem):
    # chunk=1..3 slice the same 5 iterations differently; results agree.
    runs = [
        run(problem, em_max_iters=5, em_tol=0.0, fused_em_chunk=c)
        for c in (2, 3, 5)
    ]
    for r in runs[1:]:
        np.testing.assert_allclose(
            [l for l, _ in runs[0].likelihoods],
            [l for l, _ in r.likelihoods],
            rtol=1e-5,
        )


def test_fused_progress_and_likelihood_stream(problem, tmp_path):
    seen = []
    cfg = LDAConfig(
        num_topics=4, em_max_iters=5, em_tol=0.0, batch_size=16,
        min_bucket_len=4, fused_em_chunk=2, seed=3,
    )
    out = tmp_path / "day"
    out.mkdir()
    res = train_corpus(
        problem, cfg, out_dir=str(out),
        progress=lambda it, ll, conv: seen.append((it, ll, conv)),
    )
    assert [it for it, _, _ in seen] == [1, 2, 3, 4, 5]
    lines = (out / "likelihood.dat").read_text().strip().splitlines()
    assert len(lines) == 5
    ll0 = float(lines[0].split("\t")[0])
    np.testing.assert_allclose(ll0, res.likelihoods[0][0], rtol=1e-6)


def _dense_fast_problem(seed, *, k=4, v=96, b=16, l=8, mask=None,
                        wmajor=False, **runner_kw):
    """Shared scaffold for the fast-vs-stock equivalence tests:
    synthetic (log_beta, groups) plus a (fast, stock) runner pair.
    The stock (generic) impl is summoned by passing an m_step wrapper
    the fast path's `is` eligibility check cannot recognize — exactly
    how a custom m_step_fn opts out in production."""
    import jax.numpy as jnp

    from oni_ml_tpu.models import fused
    from oni_ml_tpu.ops import dense_estep, estep

    rng = np.random.default_rng(seed)
    noise = rng.uniform(size=(k, v)) + 1.0 / v
    log_beta = jnp.asarray(
        np.log(noise / noise.sum(-1, keepdims=True)), jnp.float32
    )
    widx = jnp.asarray(rng.integers(0, v, size=(b, l)), jnp.int32)
    cnts = jnp.asarray(rng.integers(1, 5, size=(b, l)), jnp.float32)
    dense = dense_estep.densify(widx, cnts, v)
    if wmajor:
        dense = jnp.transpose(dense)           # [W, B]
    m = (jnp.ones((b,), jnp.float32) if mask is None
         else jnp.asarray(mask, jnp.float32))
    groups = ((dense[None], m[None]),)
    kw = dict(
        num_topics=k, num_terms=v, var_tol=1e-6,
        em_tol=0.0, estimate_alpha=True, dense_wmajor=wmajor,
    )
    kw.update(runner_kw)
    kw.setdefault("var_max_iters", 8)
    kw.setdefault("num_docs", b)
    fast = fused.make_chunk_runner(**kw)
    stock = fused.make_chunk_runner(
        m_step_fn=lambda ss: estep.m_step(ss), **kw
    )
    return log_beta, groups, fast, stock


def test_dense_fast_path_matches_stock_chunk_runner():
    """The single-dense-group exp-space fast path (run_chunk_impl_fast)
    must match the generic impl — same likelihood trajectory, beta,
    alpha, gammas — including across a warm chunk boundary."""
    import jax.numpy as jnp

    log_beta, groups, fast, stock = _dense_fast_problem(
        5, chunk=3, warm_start=True
    )

    a0 = jnp.float32(2.5)
    nan = jnp.float32(np.nan)
    rf = fast(log_beta, a0, nan, groups, 3)
    rs = stock(log_beta, a0, nan, groups, 3)
    assert int(rf.steps_done) == int(rs.steps_done) == 3
    np.testing.assert_allclose(rf.lls, rs.lls, rtol=1e-5)
    np.testing.assert_allclose(rf.log_beta, rs.log_beta, atol=1e-4)
    np.testing.assert_allclose(rf.alpha, rs.alpha, rtol=1e-5)
    np.testing.assert_allclose(rf.gammas[0], rs.gammas[0],
                               rtol=1e-4, atol=1e-4)

    # Warm chunk boundary: feed each path its own carry, compare again.
    rf2 = fast(rf.log_beta, rf.alpha, rf.ll_prev, groups, 2,
               rf.gammas, True)
    rs2 = stock(rs.log_beta, rs.alpha, rs.ll_prev, groups, 2,
                rs.gammas, True)
    assert int(rf2.steps_done) == int(rs2.steps_done) == 2
    np.testing.assert_allclose(rf2.lls[:2], rs2.lls[:2], rtol=1e-5)
    np.testing.assert_allclose(rf2.log_beta, rs2.log_beta, atol=1e-4)
    # Warm start actually engaged: inner iterations collapsed vs cold.
    assert int(rf2.vi_iters[0]) <= int(rf.vi_iters[0])

    # Zero-step chunk returns the input beta bit-exactly (the exp/log
    # round-trip must not drift it).
    rf0 = fast(rf.log_beta, rf.alpha, rf.ll_prev, groups, 0,
               rf.gammas, True)
    assert int(rf0.steps_done) == 0
    np.testing.assert_array_equal(rf0.log_beta, rf.log_beta)


def test_dense_fast_path_matches_stock_wmajor():
    """Same equivalence under the W-major corpus layout (the production
    default on TPU)."""
    import jax.numpy as jnp

    log_beta, groups, fast, stock = _dense_fast_problem(
        9, chunk=4, warm_start=True, wmajor=True
    )
    a0, nan = jnp.float32(2.5), jnp.float32(np.nan)
    rf = fast(log_beta, a0, nan, groups, 4)
    rs = stock(log_beta, a0, nan, groups, 4)
    assert int(rf.steps_done) == int(rs.steps_done) == 4
    np.testing.assert_allclose(rf.lls, rs.lls, rtol=1e-5)
    np.testing.assert_allclose(rf.log_beta, rs.log_beta, atol=1e-4)
    np.testing.assert_allclose(rf.alpha, rs.alpha, rtol=1e-5)


def test_dense_fast_path_masked_docs_and_cold_start():
    """Edge shapes through the fast path: padded (masked-out) documents
    must not contribute to beta/likelihood, and warm_start=False must
    match the stock impl with no gamma carry."""
    import jax.numpy as jnp

    from oni_ml_tpu.ops import dense_estep

    mask = [1, 1, 1, 1, 1, 0, 0, 0]
    log_beta, groups, fast, stock = _dense_fast_problem(
        13, k=3, v=64, b=8, l=6, mask=mask, chunk=3,
        var_max_iters=6, warm_start=False, num_docs=5,
    )
    a0, nan = jnp.float32(2.5), jnp.float32(np.nan)
    rf = fast(log_beta, a0, nan, groups, 3)
    rs = stock(log_beta, a0, nan, groups, 3)
    np.testing.assert_allclose(rf.lls, rs.lls, rtol=1e-5)
    np.testing.assert_allclose(rf.log_beta, rs.log_beta, atol=1e-4)
    np.testing.assert_allclose(rf.alpha, rs.alpha, rtol=1e-5)

    # Masked docs truly inert: rerunning with the masked rows' counts
    # scrambled must not change beta or the likelihood trajectory.
    rng = np.random.default_rng(99)
    c_arr = np.asarray(
        rng.integers(1, 4, size=(8, 6)), np.float32
    )  # any counts; only rows 5+ differ between the two runs
    w_arr = np.asarray(rng.integers(0, 64, size=(8, 6)), np.int32)
    d1 = dense_estep.densify(jnp.asarray(w_arr), jnp.asarray(c_arr), 64)
    c2 = c_arr.copy()
    c2[5:] = rng.integers(10, 50, size=(3, 6))
    d2 = dense_estep.densify(jnp.asarray(w_arr), jnp.asarray(c2), 64)
    m = jnp.asarray(mask, jnp.float32)
    ra = fast(log_beta, a0, nan, ((d1[None], m[None]),), 3)
    rb = fast(log_beta, a0, nan, ((d2[None], m[None]),), 3)
    np.testing.assert_allclose(rb.lls, ra.lls, rtol=1e-6)
    np.testing.assert_allclose(rb.log_beta, ra.log_beta, atol=1e-6)


@pytest.mark.parametrize("seed,k,v,b,l,warm", [
    (21, 3, 100, 8, 5, True),    # v below the tile (pads 100 -> 128)
    (22, 2, 128, 8, 11, False),  # v exactly on the 128-lane tile
    (23, 5, 200, 16, 7, True),   # v off-tile (pads to 256), odd K
    (24, 6, 32, 32, 4, False),   # tiny model, wider batch, cold start
])
def test_dense_fast_path_fuzz_shapes(seed, k, v, b, l, warm):
    """Shape sweep through the fast-vs-stock equivalence — guards
    padding-width interactions (v on/off the 128-lane tile), odd K,
    and both warm/cold starts at shapes the fixed tests don't hit.
    (B < 8 is NOT in the sweep: the kernel's doc block needs 8
    sublanes — pinned as a clean refusal below.)"""
    import jax.numpy as jnp

    log_beta, groups, fast, stock = _dense_fast_problem(
        seed, k=k, v=v, b=b, l=l, chunk=2, warm_start=warm,
    )
    a0, nan = jnp.float32(2.5), jnp.float32(np.nan)
    rf = fast(log_beta, a0, nan, groups, 2)
    rs = stock(log_beta, a0, nan, groups, 2)
    np.testing.assert_allclose(rf.lls, rs.lls, rtol=1e-5)
    np.testing.assert_allclose(rf.log_beta, rs.log_beta, atol=1e-4)
    np.testing.assert_allclose(rf.alpha, rs.alpha, rtol=1e-5)


def test_dense_fast_path_sub8_batch_refuses_cleanly():
    """The dense kernel's doc block needs 8 sublanes, so a B=4 dense
    group must fail with the explicit no-VMEM-feasible-block error —
    not silently mis-tile.  (In production the trainer's dense gates
    check feasibility per batch and route such shapes to the sparse
    engine before any dense group exists.)"""
    import jax.numpy as jnp

    log_beta, groups, fast, _ = _dense_fast_problem(
        25, k=3, v=64, b=4, l=4, chunk=2, warm_start=False,
    )
    with pytest.raises(ValueError, match="no VMEM-feasible doc block"):
        fast(log_beta, jnp.float32(2.5), jnp.float32(np.nan), groups, 2)


def test_fast_path_engages_for_production_dense_shape(problem, monkeypatch):
    """The trainer's forced-dense single-group path must actually
    SELECT the fast impl (fused.LAST_CHUNK_PLAN) — the equivalence
    tests alone can't catch an eligibility regression that silently
    reroutes every production run to the generic impl."""
    from oni_ml_tpu.models import fused

    cfg = dict(num_topics=4, alpha_init=2.5, seed=3, em_max_iters=2,
               em_tol=0.0, fused_em_chunk=2, batch_size=64,
               min_bucket_len=64)  # batch >= docs: one dense group

    monkeypatch.setenv("ONI_ML_TPU_ESTEP", "dense")
    fused.LAST_CHUNK_PLAN = None
    train_corpus(problem, LDAConfig(**cfg))
    assert fused.LAST_CHUNK_PLAN == "fast"

    # The compact engine (3-tuple groups) must stay on the generic impl.
    monkeypatch.setenv("ONI_ML_TPU_ESTEP", "compact")
    fused.LAST_CHUNK_PLAN = None
    train_corpus(problem, LDAConfig(**cfg))
    assert fused.LAST_CHUNK_PLAN == "generic"


def test_host_sync_every_bounds_dispatch_without_changing_results(
    problem, monkeypatch
):
    """host_sync_every caps EM iterations per device dispatch
    independently of fused_em_chunk (likelihood.dat / progress stream at
    least that often — the ADVICE r05 crash-safety note), and the
    trajectory is unchanged: the chunk program just runs with a smaller
    dynamic step count."""
    from oni_ml_tpu.models import fused

    steps_seen = []
    orig = fused.make_chunk_runner

    def counting_maker(**kw):
        runner = orig(**kw)

        def counting(log_beta, alpha, ll_prev, groups, n_steps, *a, **k):
            steps_seen.append(int(n_steps))
            return runner(log_beta, alpha, ll_prev, groups, n_steps,
                          *a, **k)

        return counting

    monkeypatch.setattr(fused, "make_chunk_runner", counting_maker)
    base = run(problem, em_max_iters=6, em_tol=0.0, fused_em_chunk=64)
    assert steps_seen == [6]  # one dispatch covers the whole fit

    steps_seen.clear()
    synced = run(problem, em_max_iters=6, em_tol=0.0, fused_em_chunk=64,
                 host_sync_every=2)
    assert steps_seen == [2, 2, 2]  # bounded dispatches, same total
    assert synced.em_iters == base.em_iters == 6
    np.testing.assert_allclose(
        [ll for ll, _ in synced.likelihoods],
        [ll for ll, _ in base.likelihoods], rtol=1e-6,
    )
    np.testing.assert_allclose(synced.log_beta, base.log_beta, atol=1e-5)
