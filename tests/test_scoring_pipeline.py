"""Device scoring pipeline (scoring/pipeline.py): threshold-compaction
parity with the host `_keep_order` oracle (exact ordering, boundary
ties, all-/none-kept edges), the f32 transfer tolerance, the dispatch-
count / transfer-bytes contract the acceptance criteria name, the
calibrated host-vs-device dispatch heuristic, and 8-virtual-device
sharded scoring parity — all on the CPU backend, so this file is
tier-1."""

import numpy as np
import pytest

from oni_ml_tpu.scoring import (
    AUTO_DEVICE_MIN,
    DispatchStats,
    ScoringModel,
    batched_scores,
    chunked_scores,
    device_scores,
    dispatch_calibration,
    filtered_flow_scores,
    filtered_scores,
    use_device_path,
)
from oni_ml_tpu.scoring import score as score_mod
from oni_ml_tpu.scoring.score import _batched_scores, _keep_order


def _model(k=20, d=300, v=200, seed=0):
    rng = np.random.default_rng(seed)
    return ScoringModel(
        ip_index={}, theta=rng.random((d + 1, k)),
        word_index={}, p=rng.random((v + 1, k)),
    )


def _idx(model, n, seed=1):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, model.theta.shape[0], n).astype(np.int32),
        rng.integers(0, model.p.shape[0], n).astype(np.int32),
    )


def _gap_threshold(scores):
    """A threshold no score sits within f32 epsilon of, so the f32
    device filter and the f64 host filter must make identical keep
    decisions: the geometric midpoint of the widest relative gap
    between adjacent distinct scores near the median."""
    s = np.sort(np.unique(scores))
    mid = len(s) // 2
    i0, i1 = max(1, mid - 64), min(len(s), mid + 64)
    ratios = s[i0:i1] / s[i0 - 1: i1 - 1]
    j = int(np.argmax(ratios)) + i0
    assert s[j] / s[j - 1] > 1 + 1e-5, "no f32-safe gap in the sample"
    return float(np.sqrt(s[j] * s[j - 1]))


def _exact_model(k=4, d=32, v=16):
    """Scores exactly representable in BOTH f32 and f64: theta rows are
    one-hot powers of two and p rows are constant powers of two, so
    every score is a single product 2^-((i%5)+(j%3)) with no summation
    error — the device/host comparison becomes bit-exact and ordering
    (including stable threshold-boundary ties) must match EXACTLY."""
    theta = np.zeros((d + 1, k))
    p = np.zeros((v + 1, k))
    for i in range(d + 1):
        theta[i, i % k] = 2.0 ** -(i % 5)
    for j in range(v + 1):
        p[j] = 2.0 ** -(j % 3)
    return ScoringModel(ip_index={}, theta=theta, word_index={}, p=p)


# ---------------------------------------------------------------------------
# threshold compaction parity vs the host _keep_order oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("chunk", [7, 64, 1 << 16])
def test_filtered_matches_keep_order_exact(chunk):
    """Exact-ordering parity on f32-exact scores, across chunk sizes
    that split the day mid-stream, at a mid threshold, a threshold
    sitting EXACTLY on a score value (strict <, ties dropped on both
    paths), all-kept, and none-kept."""
    model = _exact_model()
    ia, ib = _idx(model, 500, seed=3)
    host = _batched_scores(model, ia, ib)
    assert len(np.unique(host)) < 16          # dense tie structure
    for threshold in (2.0 ** -4, 2.0 ** -3, np.inf, 0.0):
        want = _keep_order(host, threshold)
        got_order, got_scores = filtered_scores(
            model, ia, ib, threshold, chunk=chunk
        )
        np.testing.assert_array_equal(got_order, want)
        np.testing.assert_array_equal(got_scores, host[want])


def test_filtered_empty_input():
    model = _exact_model()
    order, scores = filtered_scores(
        model, np.zeros(0, np.int32), np.zeros(0, np.int32), 1.0
    )
    assert order.shape == (0,) and scores.shape == (0,)
    out = filtered_flow_scores(
        model, *(np.zeros(0, np.int32) for _ in range(4)), 1.0
    )
    assert all(a.shape == (0,) for a in out)


def test_filtered_random_parity_k20():
    """Acceptance criterion: filtered event sets identical and scores
    within 1e-6 relative of the float64 host oracle at K=20."""
    model = _model(k=20)
    ia, ib = _idx(model, 20_000)
    host = _batched_scores(model, ia, ib)
    threshold = _gap_threshold(host)
    want = _keep_order(host, threshold)
    got_order, got_scores = filtered_scores(
        model, ia, ib, threshold, chunk=4096
    )
    assert set(got_order.tolist()) == set(want.tolist())
    np.testing.assert_allclose(got_scores, host[got_order], rtol=1e-6)
    # Ascending output, like the host oracle.
    assert np.all(np.diff(got_scores) >= 0)


def test_flow_filtered_parity():
    model = _model(k=20, seed=5)
    sa, sw = _idx(model, 10_000, seed=6)
    da, dw = _idx(model, 10_000, seed=7)
    src = _batched_scores(model, sa, sw)
    dest = _batched_scores(model, da, dw)
    mn = np.minimum(src, dest)
    threshold = _gap_threshold(mn)
    want = _keep_order(mn, threshold)
    order, src_k, dest_k, mn_k = filtered_flow_scores(
        model, sa, sw, da, dw, threshold, chunk=2048
    )
    assert set(order.tolist()) == set(want.tolist())
    np.testing.assert_allclose(src_k, src[order], rtol=1e-6)
    np.testing.assert_allclose(dest_k, dest[order], rtol=1e-6)
    np.testing.assert_allclose(mn_k, mn[order], rtol=1e-6)
    assert np.all(np.diff(mn_k) >= 0)


def test_flow_filtered_exact_order():
    model = _exact_model()
    sa, sw = _idx(model, 400, seed=8)
    da, dw = _idx(model, 400, seed=9)
    mn = np.minimum(
        _batched_scores(model, sa, sw), _batched_scores(model, da, dw)
    )
    for threshold in (2.0 ** -3, np.inf, 0.0):
        want = _keep_order(mn, threshold)
        order, _, _, mn_k = filtered_flow_scores(
            model, sa, sw, da, dw, threshold, chunk=128
        )
        np.testing.assert_array_equal(order, want)
        np.testing.assert_array_equal(mn_k, mn[want])


# ---------------------------------------------------------------------------
# f32 transfer dtype: halved H2D bytes, pinned tolerance
# ---------------------------------------------------------------------------


def test_f32_transfer_tolerance():
    """theta/p ship to device as float32 (half the float64 bytes); the
    documented guarantee is ~1e-6 relative agreement with the float64
    host oracle at K=20."""
    model = _model(k=20, seed=11)
    ia, ib = _idx(model, 8192, seed=12)
    host = _batched_scores(model, ia, ib)
    dev = chunked_scores(model, ia, ib, chunk=1024)
    np.testing.assert_allclose(dev, host, rtol=1e-6)
    theta_dev, p_dev = score_mod._device_model(model)
    assert str(theta_dev.dtype) == "float32"
    assert str(p_dev.dtype) == "float32"
    # Cached on the model: a second lookup must NOT re-transfer.
    assert score_mod._device_model(model) is not (None,) and \
        score_mod._device_model(model)[0] is theta_dev


def test_chunked_scores_bitwise_equals_single_dispatch():
    """Chunking must not change a single score bit: per-event dots are
    independent, so the chunked pipeline and the one-shot padded
    dispatch agree exactly."""
    model = _model(seed=13)
    ia, ib = _idx(model, 3000, seed=14)
    one = device_scores(model, ia, ib)           # single padded dispatch
    many = chunked_scores(model, ia, ib, chunk=256)
    np.testing.assert_array_equal(one, many)


# ---------------------------------------------------------------------------
# dispatch-count / transfer-bytes contract (acceptance criteria probe)
# ---------------------------------------------------------------------------


def test_dispatch_count_and_transfer_bytes_400k_day():
    """The acceptance projection: a 400k-event day at the default-ish
    chunk runs ceil(N/chunk) index-only H2D dispatches with
    survivors-only D2H — not the old single full-result float64
    round-trip."""
    n, chunk = 400_000, 1 << 16
    model = _model(k=20, seed=15)
    ia, ib = _idx(model, n, seed=16)
    host = _batched_scores(model, ia, ib)
    threshold = float(np.quantile(host, 0.003))   # a real TOL keeps few
    stats = DispatchStats()
    order, scores = filtered_scores(
        model, ia, ib, threshold, chunk=chunk, stats=stats
    )
    n_chunks = -(-n // chunk)                     # 7
    assert stats.dispatches == n_chunks
    assert stats.chunks == n_chunks
    # H2D: exactly the two int32 index arrays per chunk, nothing else.
    assert stats.h2d_bytes == n_chunks * chunk * 4 * 2
    # D2H: one count scalar per chunk plus the survivors' (pos, score)
    # pairs — pow2-rounded per chunk so slice programs stay O(log
    # chunk) — a sliver of the old full-f64 return (8 bytes x N).
    assert stats.survivors == len(order)
    assert 4 * n_chunks + 8 * stats.survivors <= stats.d2h_bytes
    assert stats.d2h_bytes <= 4 * n_chunks + 16 * max(stats.survivors,
                                                      n_chunks)
    assert stats.d2h_bytes < 0.02 * 8 * n
    # Weights crossed once (f32 — half of float64).
    assert stats.weight_h2d_bytes == 4 * (model.theta.size + model.p.size)


def test_model_weights_transfer_once_per_swap():
    model = _model(seed=17)
    ia, ib = _idx(model, 2000, seed=18)
    stats = DispatchStats()
    filtered_scores(model, ia, ib, 1.0, chunk=512, stats=stats)
    once = stats.weight_h2d_bytes
    assert once > 0
    filtered_scores(model, ia, ib, 1.0, chunk=512, stats=stats)
    chunked_scores(model, ia, ib, chunk=512, stats=stats)
    assert stats.weight_h2d_bytes == once        # cached per model
    # A probe shared across calls accumulates coherently.
    assert stats.events == 3 * 2000
    assert stats.chunks == 3 * 4


def test_small_input_shrinks_chunk():
    """A tiny batch must not pad to the full 64k chunk: the effective
    chunk shrinks to the next power of two, keeping compiled-program
    count O(log chunk) and the H2D bytes proportional to the batch."""
    model = _model(seed=19)
    ia, ib = _idx(model, 100, seed=20)
    stats = DispatchStats()
    filtered_scores(model, ia, ib, np.inf, stats=stats)
    assert stats.chunk == 128
    assert stats.h2d_bytes == 128 * 4 * 2


# ---------------------------------------------------------------------------
# calibrated host-vs-device dispatch (the r05 "device loses" fix)
# ---------------------------------------------------------------------------


def test_use_device_path_semantics(monkeypatch):
    monkeypatch.setattr(score_mod, "_CALIBRATION",
                        {"break_even": 64, "source": "test"})
    assert not use_device_path(10, None)          # None pins host
    assert not use_device_path(0, AUTO_DEVICE_MIN)
    assert use_device_path(64, AUTO_DEVICE_MIN)   # auto: >= break-even
    assert use_device_path(64, "auto")
    assert not use_device_path(63, AUTO_DEVICE_MIN)
    assert use_device_path(5, 5) and not use_device_path(4, 5)  # legacy
    # A backend where the device can never win pins host at ANY size.
    monkeypatch.setattr(score_mod, "_CALIBRATION",
                        {"break_even": None, "source": "test"})
    assert not use_device_path(1 << 30, AUTO_DEVICE_MIN)


def test_batched_scores_auto_routes_through_calibration(monkeypatch):
    model = _model(seed=21)
    ia, ib = _idx(model, 128, seed=22)
    calls = []
    real = score_mod.device_scores
    monkeypatch.setattr(
        score_mod, "device_scores",
        lambda *a, **kw: calls.append(1) or real(*a, **kw),
    )
    monkeypatch.setattr(score_mod, "_CALIBRATION",
                        {"break_even": 64, "source": "test"})
    batched_scores(model, ia, ib, device_min=AUTO_DEVICE_MIN)
    assert calls == [1]
    monkeypatch.setattr(score_mod, "_CALIBRATION",
                        {"break_even": None, "source": "test"})
    batched_scores(model, ia, ib, device_min=AUTO_DEVICE_MIN)
    assert calls == [1]                           # host path: no device call


def test_dispatch_calibration_measures_and_caches(monkeypatch):
    monkeypatch.setattr(score_mod, "_CALIBRATION", None)
    monkeypatch.delenv("ONI_ML_TPU_SCORE_BREAK_EVEN", raising=False)
    # Isolate from the plan cache: an earlier test's measured
    # calibration persists there (by design), which would make this
    # process-level measurement test read source "plan".
    monkeypatch.setenv("ONI_ML_TPU_PLANS", "0")
    cal = dispatch_calibration()
    assert cal["source"] == "measured"
    assert cal["dispatch_s"] > 0 and cal["host_event_s"] > 0
    assert cal["break_even"] is None or cal["break_even"] >= 1
    assert dispatch_calibration() is cal          # cached
    monkeypatch.setenv("ONI_ML_TPU_SCORE_BREAK_EVEN", "123")
    env_cal = dispatch_calibration(force=True)
    assert env_cal == {**env_cal, "break_even": 123, "source": "env"}
    monkeypatch.setenv("ONI_ML_TPU_SCORE_BREAK_EVEN", "0")
    assert dispatch_calibration(force=True)["break_even"] is None
    monkeypatch.setattr(score_mod, "_CALIBRATION", None)


# ---------------------------------------------------------------------------
# sharded multi-device scoring (8-device virtual mesh, MULTICHIP pattern)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh8():
    from oni_ml_tpu.parallel import make_mesh

    return make_mesh(data=8, model=1)


def test_sharded_scores_equal_single_device(mesh8):
    model = _model(k=20, seed=23)
    ia, ib = _idx(model, 5000, seed=24)
    single = chunked_scores(model, ia, ib, chunk=1024)
    stats = DispatchStats()
    sharded = chunked_scores(
        model, ia, ib, chunk=1024, mesh=mesh8, stats=stats
    )
    np.testing.assert_array_equal(sharded, single)
    np.testing.assert_allclose(
        sharded, _batched_scores(model, ia, ib), rtol=1e-6
    )
    assert stats.chunk % 8 == 0                  # divisible by data axis


def test_sharded_filtered_equal_single_device(mesh8):
    model = _model(k=20, seed=25)
    ia, ib = _idx(model, 5000, seed=26)
    host = _batched_scores(model, ia, ib)
    threshold = _gap_threshold(host)
    o1, s1 = filtered_scores(model, ia, ib, threshold, chunk=1024)
    o2, s2 = filtered_scores(
        model, ia, ib, threshold, chunk=1024, mesh=mesh8
    )
    np.testing.assert_array_equal(o1, o2)
    np.testing.assert_array_equal(s1, s2)
    f1 = filtered_flow_scores(model, ia, ib, ia, ib, threshold, chunk=1024)
    f2 = filtered_flow_scores(
        model, ia, ib, ia, ib, threshold, chunk=1024, mesh=mesh8
    )
    for a, b in zip(f1, f2):
        np.testing.assert_array_equal(a, b)


def test_sharded_model_replicates_once(mesh8):
    model = _model(seed=27)
    ia, ib = _idx(model, 2000, seed=28)
    stats = DispatchStats()
    chunked_scores(model, ia, ib, chunk=512, mesh=mesh8, stats=stats)
    once = stats.weight_h2d_bytes
    assert once == 4 * (model.theta.size + model.p.size)
    chunked_scores(model, ia, ib, chunk=512, mesh=mesh8, stats=stats)
    assert stats.weight_h2d_bytes == once


# ---------------------------------------------------------------------------
# device engine through the public score_* wrappers
# ---------------------------------------------------------------------------


def _dns_day(n=300):
    from test_features import dns_row

    from oni_ml_tpu.features import featurize_dns

    rng = np.random.default_rng(31)
    rows = [
        dns_row(ip=f"10.0.0.{int(rng.integers(0, 40))}")
        for _ in range(n)
    ]
    feats = featurize_dns(rows)
    uniq_ips = sorted({feats.client_ip(i) for i in range(n)})
    vocab = sorted(set(feats.word))
    # Power-of-two theta/p (same construction as _exact_model): every
    # score is exact in f32 AND f64, so host and device must agree on
    # ordering bit-for-bit even under threshold=inf (all kept).
    k = 4
    theta = np.zeros((len(uniq_ips), k))
    for i in range(len(uniq_ips)):
        theta[i, i % k] = 2.0 ** -(i % 5)
    p = np.full((len(vocab), k), 0.0)
    for j in range(len(vocab)):
        p[j] = 2.0 ** -(j % 3)
    model = ScoringModel.from_results(uniq_ips, theta, vocab, p,
                                      fallback=0.1)
    return feats, model


def test_score_dns_device_engine_matches_host():
    feats, model = _dns_day()
    from oni_ml_tpu.scoring import score_dns

    host_rows, host_scores = score_dns(feats, model, threshold=np.inf)
    dev_rows, dev_scores = score_dns(
        feats, model, threshold=np.inf, engine="device"
    )
    assert len(dev_rows) == len(host_rows)
    np.testing.assert_allclose(dev_scores, host_scores, rtol=1e-5)
    # Featurized columns identical; only the trailing score column may
    # drift in its float repr (f32-derived).
    for hr, dr in zip(host_rows, dev_rows):
        assert hr.rsplit(",", 1)[0] == dr.rsplit(",", 1)[0]
        np.testing.assert_allclose(
            float(dr.rsplit(",", 1)[1]), float(hr.rsplit(",", 1)[1]),
            rtol=1e-5,
        )


def test_score_engine_rejects_unknown():
    feats, model = _dns_day(10)
    from oni_ml_tpu.scoring import score_dns

    with pytest.raises(ValueError, match="engine"):
        score_dns(feats, model, threshold=1.0, engine="gpu")


def test_default_engine_bytes_unchanged(monkeypatch):
    """The golden-bytes contract: with no engine override the host
    float64 path runs and the CSV bytes are what the oracle emits."""
    monkeypatch.delenv("ONI_ML_TPU_SCORE", raising=False)
    feats, model = _dns_day(50)
    from oni_ml_tpu.scoring import score_dns_csv

    blob_default, _ = score_dns_csv(feats, model, threshold=np.inf)
    blob_host, _ = score_dns_csv(
        feats, model, threshold=np.inf, engine="host"
    )
    assert blob_default == blob_host


# ---------------------------------------------------------------------------
# bench phase smoke
# ---------------------------------------------------------------------------


def test_bench_scoring_e2e_smoke():
    import bench

    rec = bench.bench_scoring_e2e(n_events=1500, reps=1)
    assert rec["n_events"] == 1500
    assert rec["host_events_per_sec"] > 0
    assert rec["device_events_per_sec"] > 0
    assert rec["value"] > 0
    assert rec["dispatch"]["dispatches"] >= 1
    assert "calibration" in rec
    # The dispatch-count projection the acceptance criteria name.
    n, chunk = 400_000, rec["chunk"]
    assert rec["projected_dispatches_400k"] == -(-n // chunk)
