"""Dense-corpus E-step (ops/dense_estep.py) vs the sparse reference path.

The dense kernel must reproduce estep.e_step exactly up to float32
reassociation: same fixed point, same convergence rule, same ELBO and
suff-stats semantics.  Runs in Pallas interpret mode on the CPU backend
(tests/conftest.py), mirroring how test_pallas_estep.py validates the
sparse kernel.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from oni_ml_tpu.config import LDAConfig
from oni_ml_tpu.io import Batch
from oni_ml_tpu.models import fused
from oni_ml_tpu.ops import dense_estep, estep


def _random_batch(rng, b, l, v, n_masked=0):
    word_idx = rng.integers(0, v, size=(b, l)).astype(np.int32)
    counts = rng.integers(1, 5, size=(b, l)).astype(np.float32)
    # Ragged tail: zero-count padding tokens on some docs.
    for i in range(b // 3):
        pad = rng.integers(1, l)
        word_idx[i, pad:] = 0
        counts[i, pad:] = 0.0
    doc_mask = np.ones((b,), np.float32)
    if n_masked:
        doc_mask[-n_masked:] = 0.0
        counts[-n_masked:] = 0.0
        word_idx[-n_masked:] = 0
    return (
        jnp.asarray(word_idx),
        jnp.asarray(counts),
        jnp.asarray(doc_mask),
    )


def _log_beta(rng, k, v):
    noise = rng.uniform(size=(k, v)) + 1.0 / v
    return jnp.asarray(
        np.log(noise / noise.sum(-1, keepdims=True)), jnp.float32
    )


def test_densify_matches_loop():
    rng = np.random.default_rng(0)
    b, l, v = 8, 16, 50
    word_idx, counts, _ = _random_batch(rng, b, l, v)
    dense = np.asarray(dense_estep.densify(word_idx, counts, v))
    assert dense.shape == (b, dense_estep.padded_width(v))
    expect = np.zeros((b, v), np.float32)
    for i in range(b):
        for j in range(l):
            expect[i, int(word_idx[i, j])] += float(counts[i, j])
    np.testing.assert_allclose(dense[:, :v], expect, rtol=0, atol=0)
    assert dense[:, v:].sum() == 0.0


@pytest.mark.parametrize(
    "b,l,v,k,n_masked",
    [(16, 32, 300, 4, 0), (32, 16, 130, 7, 5), (8, 8, 128, 3, 2)],
)
def test_dense_parity_vs_xla(b, l, v, k, n_masked):
    rng = np.random.default_rng(b * 1000 + v)
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked)
    log_beta = _log_beta(rng, k, v)
    alpha = jnp.float32(2.5)

    ref = estep.e_step(
        log_beta, alpha, word_idx, counts, doc_mask,
        var_max_iters=20, var_tol=1e-6, backend="xla",
    )
    dense = dense_estep.densify(word_idx, counts, v)
    got = dense_estep.e_step_dense(
        log_beta, alpha, dense, doc_mask,
        var_max_iters=20, var_tol=1e-6, interpret=True,
    )

    np.testing.assert_allclose(
        np.asarray(got.gamma), np.asarray(ref.gamma), rtol=2e-3, atol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(got.suff_stats), np.asarray(ref.suff_stats),
        rtol=2e-3, atol=1e-4,
    )
    np.testing.assert_allclose(
        float(got.likelihood), float(ref.likelihood), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(got.alpha_ss), float(ref.alpha_ss), rtol=1e-4
    )


@pytest.mark.parametrize(
    "b,l,v,k,n_masked",
    [(16, 32, 300, 4, 0), (32, 16, 130, 7, 5), (8, 8, 128, 3, 2)],
)
def test_wmajor_parity_vs_xla(b, l, v, k, n_masked):
    """The W-major (transposed-corpus) kernel — the production default —
    must match the sparse XLA reference exactly like the row-major one."""
    rng = np.random.default_rng(b * 1000 + v + 1)
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked)
    log_beta = _log_beta(rng, k, v)
    alpha = jnp.float32(2.5)

    ref = estep.e_step(
        log_beta, alpha, word_idx, counts, doc_mask,
        var_max_iters=20, var_tol=1e-6, backend="xla",
    )
    dense_t = dense_estep.densify(word_idx, counts, v).T
    got = dense_estep.e_step_dense(
        log_beta, alpha, dense_t, doc_mask,
        var_max_iters=20, var_tol=1e-6, interpret=True, wmajor=True,
    )
    np.testing.assert_allclose(
        np.asarray(got.gamma), np.asarray(ref.gamma), rtol=2e-3, atol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(got.suff_stats), np.asarray(ref.suff_stats),
        rtol=2e-3, atol=1e-4,
    )
    np.testing.assert_allclose(
        float(got.likelihood), float(ref.likelihood), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(got.alpha_ss), float(ref.alpha_ss), rtol=1e-4
    )


def test_masked_docs_are_inert():
    """A masked doc must contribute nothing to suff stats / likelihood and
    converge to gamma = alpha (its dense row is all zeros)."""
    rng = np.random.default_rng(3)
    b, l, v, k = 8, 8, 140, 3
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked=3)
    log_beta = _log_beta(rng, k, v)
    dense = dense_estep.densify(word_idx, counts, v)
    got = dense_estep.e_step_dense(
        log_beta, jnp.float32(1.5), dense, doc_mask,
        var_max_iters=10, var_tol=1e-6, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got.gamma)[-3:], 1.5, rtol=1e-6
    )


def test_backend_dispatch():
    rng = np.random.default_rng(7)
    b, l, v, k = 8, 8, 140, 3
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v)
    log_beta = _log_beta(rng, k, v)
    ref = estep.e_step(
        log_beta, 2.5, word_idx, counts, doc_mask,
        var_max_iters=10, var_tol=1e-6, backend="xla",
    )
    got = estep.e_step(
        log_beta, 2.5, word_idx, counts, doc_mask,
        var_max_iters=10, var_tol=1e-6, backend="dense",
    )
    np.testing.assert_allclose(
        np.asarray(got.gamma), np.asarray(ref.gamma), rtol=2e-3, atol=1e-3
    )

    with pytest.raises(ValueError, match="unknown E-step backend"):
        estep.e_step(
            log_beta, 2.5, word_idx, counts, doc_mask,
            var_max_iters=10, var_tol=1e-6, backend="palas",
        )
    # Forced dense on an infeasible shape names the problem.
    with pytest.raises(ValueError, match="dense E-step forced"):
        estep.e_step(
            _log_beta(rng, 3, 7), 2.5,
            word_idx[:5], counts[:5], doc_mask[:5],
            var_max_iters=10, var_tol=1e-6, backend="dense",
        )


def test_fused_runner_dense_groups_match_sparse():
    """The fused chunk runner must produce the same EM trajectory from
    densified groups as from sparse groups."""
    rng = np.random.default_rng(11)
    b, l, v, k = 16, 16, 260, 4
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked=2)
    log_beta = _log_beta(rng, k, v)
    alpha = jnp.float32(2.5)

    sparse_groups = (
        (word_idx[None], counts[None], doc_mask[None]),
    )
    dense = dense_estep.densify(word_idx, counts, v)
    dense_groups = ((dense[None], doc_mask[None]),)

    run = fused.make_chunk_runner(
        num_docs=b - 2, num_topics=k, num_terms=v, chunk=4,
        var_max_iters=20, var_tol=1e-6, em_tol=0.0, estimate_alpha=True,
    )
    run_w = fused.make_chunk_runner(
        num_docs=b - 2, num_topics=k, num_terms=v, chunk=4,
        var_max_iters=20, var_tol=1e-6, em_tol=0.0, estimate_alpha=True,
        dense_wmajor=True,
    )
    wmajor_groups = ((dense.T[None], doc_mask[None]),)
    r_sparse = run(log_beta, alpha, jnp.float32(np.nan), sparse_groups, 4)
    r_dense = run(log_beta, alpha, jnp.float32(np.nan), dense_groups, 4)
    r_wmajor = run_w(log_beta, alpha, jnp.float32(np.nan), wmajor_groups, 4)

    for r in (r_dense, r_wmajor):
        np.testing.assert_allclose(
            np.asarray(r.lls), np.asarray(r_sparse.lls), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(r.log_beta), np.asarray(r_sparse.log_beta),
            rtol=5e-3, atol=5e-3,
        )
        np.testing.assert_allclose(
            float(r.alpha), float(r_sparse.alpha), rtol=1e-3
        )
    # gammas come back doc-major from both dense layouts
    np.testing.assert_allclose(
        np.asarray(r_wmajor.gammas[0]), np.asarray(r_dense.gammas[0]),
        rtol=2e-3, atol=1e-3,
    )


def test_trainer_dense_mode_matches_sparse():
    """LDATrainer end-to-end with dense_em='on' vs 'off' on a tiny corpus."""
    from oni_ml_tpu.models.lda import LDATrainer

    rng = np.random.default_rng(5)
    b, l, v = 16, 16, 200
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked=2)
    batch = Batch(
        word_idx=np.asarray(word_idx),
        counts=np.asarray(counts),
        doc_mask=np.asarray(doc_mask),
        doc_index=np.arange(b),
    )
    results = {}
    for mode in ("on", "off"):
        cfg = LDAConfig(
            num_topics=4, em_max_iters=6, em_tol=0.0,
            var_max_iters=20, fused_em_chunk=3, seed=1, dense_em=mode,
            # This test pins dense-vs-sparse NUMERICS; warm start (dense
            # only) would make the trajectories differ by design.
            warm_start_gamma=False,
        )
        trainer = LDATrainer(cfg, num_terms=v)
        results[mode] = trainer.fit([batch], num_docs=b - 2)

    on, off = results["on"], results["off"]
    np.testing.assert_allclose(on.log_beta, off.log_beta, rtol=5e-3, atol=5e-3)
    np.testing.assert_allclose(
        [ll for ll, _ in on.likelihoods],
        [ll for ll, _ in off.likelihoods],
        rtol=1e-4,
    )


def test_explicit_block_must_divide_batch():
    rng = np.random.default_rng(2)
    b, l, v, k = 16, 8, 140, 3
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v)
    dense = dense_estep.densify(word_idx, counts, v)
    with pytest.raises(ValueError, match="does not divide"):
        dense_estep.e_step_dense(
            _log_beta(rng, k, v), 2.5, dense, doc_mask,
            var_max_iters=5, var_tol=1e-6, block=12, interpret=True,
        )


def test_dense_em_typo_raises():
    from oni_ml_tpu.models.lda import LDATrainer

    trainer = LDATrainer(
        LDAConfig(num_topics=4, dense_em="true"), num_terms=200
    )
    batch = Batch(
        word_idx=np.zeros((16, 8), np.int32),
        counts=np.zeros((16, 8), np.float32),
        doc_mask=np.ones((16,), np.float32),
        doc_index=np.arange(16),
    )
    with pytest.raises(ValueError, match="dense_em"):
        trainer._use_dense([batch])


def test_forced_dense_with_vocab_sharding_raises():
    """Dense mode composes with a data mesh but needs the full
    vocabulary; a vocab-sharded trainer must reject it loudly."""
    from oni_ml_tpu.models.lda import LDATrainer
    from oni_ml_tpu.parallel import make_mesh

    with pytest.warns(UserWarning, match="left idle"):
        mesh = make_mesh(data=2, model=2)   # 2x2 of the 8 virtual devices
    trainer = LDATrainer(
        LDAConfig(num_topics=4, dense_em="on"), num_terms=200, mesh=mesh,
        vocab_sharded=True,
    )
    batch = Batch(
        word_idx=np.zeros((16, 8), np.int32),
        counts=np.zeros((16, 8), np.float32),
        doc_mask=np.ones((16,), np.float32),
        doc_index=np.arange(16),
    )
    with pytest.raises(ValueError, match="vocabulary is sharded"):
        trainer._use_dense([batch])


def test_dense_sharded_matches_single_device():
    """The shard_map'd dense E-step (data-parallel mesh) must reproduce
    the single-device dense result: psum'd suff-stats/likelihood equal,
    gamma identical per document."""
    import jax

    from oni_ml_tpu.parallel import make_mesh, sharded

    rng = np.random.default_rng(31)
    b, l, v, k = 32, 16, 260, 4
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked=3)
    log_beta = _log_beta(rng, k, v)
    alpha = jnp.float32(2.5)
    dense = dense_estep.densify(word_idx, counts, v)

    single = dense_estep.e_step_dense(
        log_beta, alpha, dense, doc_mask,
        var_max_iters=15, var_tol=1e-6, interpret=True,
    )
    mesh = make_mesh(data=4, model=1, devices=jax.devices()[:4])
    fn = sharded.make_data_parallel_dense_e_step(mesh, wmajor=False)
    got = fn(
        log_beta, alpha, dense, doc_mask,
        jnp.zeros((b, k), jnp.float32), jnp.asarray(0, jnp.int32),
        var_max_iters=15, var_tol=1e-6, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got.gamma), np.asarray(single.gamma),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(got.suff_stats), np.asarray(single.suff_stats),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        float(got.likelihood), float(single.likelihood), rtol=1e-6
    )

    # W-major layout through the same wrapper
    fn_w = sharded.make_data_parallel_dense_e_step(mesh, wmajor=True)
    got_w = fn_w(
        log_beta, alpha, dense.T, doc_mask,
        jnp.zeros((b, k), jnp.float32), jnp.asarray(0, jnp.int32),
        var_max_iters=15, var_tol=1e-6, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got_w.gamma), np.asarray(single.gamma),
        rtol=2e-3, atol=1e-3,
    )
    np.testing.assert_allclose(
        float(got_w.likelihood), float(single.likelihood), rtol=1e-5
    )


def test_trainer_dense_data_mesh_matches_unsharded():
    """End-to-end: train_corpus with dense forced on a data mesh must
    match the unsharded dense run's trajectory."""
    import jax

    from oni_ml_tpu.models import train_corpus
    from oni_ml_tpu.parallel import make_mesh

    import reference_lda as ref
    from test_lda import corpus_from_docs

    docs, _ = ref.make_synthetic_corpus(num_docs=64, num_terms=200,
                                        num_topics=3, seed=8)
    corpus = corpus_from_docs(docs, 200)
    base = LDAConfig(num_topics=3, em_max_iters=6, em_tol=0.0,
                     batch_size=32, min_bucket_len=64, seed=4,
                     fused_em_chunk=3, dense_em="on")
    mesh = make_mesh(data=4, model=1, devices=jax.devices()[:4])
    res_mesh = train_corpus(corpus, base, mesh=mesh)
    res_single = train_corpus(corpus, base)
    np.testing.assert_allclose(
        [ll for ll, _ in res_mesh.likelihoods],
        [ll for ll, _ in res_single.likelihoods],
        rtol=1e-4,
    )
    np.testing.assert_allclose(
        res_mesh.log_beta, res_single.log_beta, rtol=5e-3, atol=5e-3
    )


def test_env_dense_does_not_leak_into_auto_dispatch(monkeypatch):
    """ONI_ML_TPU_ESTEP=dense is a driver hint; per-call e_step auto must
    not densify inline (that would re-scatter every EM iteration) and must
    not raise on shapes the dense path can't block."""
    monkeypatch.setenv("ONI_ML_TPU_ESTEP", "dense")
    rng = np.random.default_rng(9)
    # B=5 has no feasible dense block (not divisible by 8): auto dispatch
    # must still succeed via the sparse paths.
    word_idx, counts, doc_mask = _random_batch(rng, 5, 8, 60, 0)
    log_beta = _log_beta(rng, 3, 60)
    res = estep.e_step(
        log_beta, 2.5, word_idx, counts, doc_mask,
        var_max_iters=5, var_tol=1e-6,
    )
    ref = estep.e_step(
        log_beta, 2.5, word_idx, counts, doc_mask,
        var_max_iters=5, var_tol=1e-6, backend="xla",
    )
    np.testing.assert_allclose(
        np.asarray(res.gamma), np.asarray(ref.gamma), rtol=1e-5
    )


def test_scoped_vmem_kib_and_multibatch_groups():
    """The scoped-VMEM compiler option must be computable for feasible
    shapes (XLA drops the kernel's own limit inside stacked-group scans)
    and the fused runner must accept stacked NB>=2 dense groups."""
    kib = dense_estep.scoped_vmem_kib(1024, 13530, 20)
    assert kib is not None and kib >= 32 * 1024
    assert dense_estep.scoped_vmem_kib(5, 100, 4) is None  # infeasible

    rng = np.random.default_rng(21)
    b, l, v, k = 16, 8, 140, 3
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v)
    dense = dense_estep.densify(word_idx, counts, v)
    groups = ((jnp.stack([dense, dense]), jnp.stack([doc_mask, doc_mask])),)
    # The xla_tpu_* option itself only exists on the TPU compiler; on the
    # CPU test backend exercise the plumbing with a portable no-op option.
    run = fused.make_chunk_runner(
        num_docs=2 * b, num_topics=k, num_terms=v, chunk=2,
        var_max_iters=5, var_tol=1e-6, em_tol=0.0, estimate_alpha=True,
        compiler_options={},
    )
    res = run(_log_beta(rng, k, v), jnp.float32(2.5), jnp.float32(np.nan),
              groups, 2)
    assert np.isfinite(float(res.lls[-1]))


def test_use_dense_auto_is_off_on_cpu():
    from oni_ml_tpu.models.lda import LDATrainer

    cfg = LDAConfig(num_topics=4, dense_em="auto")
    trainer = LDATrainer(cfg, num_terms=200)
    batch = Batch(
        word_idx=np.zeros((16, 8), np.int32),
        counts=np.zeros((16, 8), np.float32),
        doc_mask=np.ones((16,), np.float32),
        doc_index=np.arange(16),
    )
    assert trainer._use_dense([batch]) is False  # CPU backend in tests


@pytest.mark.parametrize("wmajor", [False, True])
def test_warm_start_converges_faster_to_same_point(wmajor):
    """Seeding the fixed point with the converged gamma must finish in
    fewer inner iterations and land on the same posterior (the update
    operator is unchanged; only the start moves)."""
    rng = np.random.default_rng(42)
    b, l, v, k = 16, 32, 300, 4
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v)
    log_beta = _log_beta(rng, k, v)
    alpha = jnp.float32(2.5)
    dense = dense_estep.densify(word_idx, counts, v)
    if wmajor:
        dense = dense.T

    fresh = dense_estep.e_step_dense(
        log_beta, alpha, dense, doc_mask,
        var_max_iters=50, var_tol=1e-6, interpret=True, wmajor=wmajor,
    )
    warm = dense_estep.e_step_dense(
        log_beta, alpha, dense, doc_mask,
        var_max_iters=50, var_tol=1e-6, interpret=True, wmajor=wmajor,
        gamma_prev=fresh.gamma, warm=1,
    )
    assert int(warm.vi_iters) < int(fresh.vi_iters), (
        int(warm.vi_iters), int(fresh.vi_iters)
    )
    np.testing.assert_allclose(
        np.asarray(warm.gamma), np.asarray(fresh.gamma), rtol=1e-3, atol=1e-3
    )
    np.testing.assert_allclose(
        float(warm.likelihood), float(fresh.likelihood), rtol=1e-5
    )

    # warm=0 with a garbage gamma_prev must reproduce the fresh run
    # exactly (the flag, not the buffer, decides).
    gated = dense_estep.e_step_dense(
        log_beta, alpha, dense, doc_mask,
        var_max_iters=50, var_tol=1e-6, interpret=True, wmajor=wmajor,
        gamma_prev=jnp.full_like(fresh.gamma, 7.0), warm=0,
    )
    np.testing.assert_array_equal(
        np.asarray(gated.gamma), np.asarray(fresh.gamma)
    )


def test_fused_warm_start_matches_fresh_trajectory():
    """warm_start=True reaches the same EM optimum; likelihoods track the
    fresh-start run closely at every iteration."""
    rng = np.random.default_rng(17)
    b, l, v, k = 16, 16, 260, 4
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked=2)
    log_beta = _log_beta(rng, k, v)
    alpha = jnp.float32(2.5)
    dense = dense_estep.densify(word_idx, counts, v)
    groups = ((dense[None], doc_mask[None]),)

    runs = {}
    for warm in (False, True):
        run = fused.make_chunk_runner(
            num_docs=b - 2, num_topics=k, num_terms=v, chunk=8,
            var_max_iters=20, var_tol=1e-6, em_tol=0.0,
            estimate_alpha=True, warm_start=warm,
        )
        runs[warm] = run(log_beta, alpha, jnp.float32(np.nan), groups, 8)

    # Mid-trajectory values differ by O(var_tol effects) — the fixed
    # point is reached from a different start — but must track closely
    # and agree tightly once converged.
    np.testing.assert_allclose(
        np.asarray(runs[True].lls), np.asarray(runs[False].lls), rtol=1e-3
    )
    np.testing.assert_allclose(
        float(runs[True].lls[-1]), float(runs[False].lls[-1]), rtol=1e-5
    )
    # Compare topics in probability space: log-space values of ~e^-55
    # mass words are numerically meaningless between equally-converged
    # runs.
    np.testing.assert_allclose(
        np.exp(np.asarray(runs[True].log_beta)),
        np.exp(np.asarray(runs[False].log_beta)),
        rtol=1e-2, atol=1e-5,
    )


@pytest.mark.parametrize("wmajor", [False, True])
def test_bf16_precision_close_and_validated(wmajor):
    """dense_precision="bf16" stores the fixed-point matmul operands in
    bfloat16.  On TPU that is bit-identical to the default (XLA's
    DEFAULT matmul precision already truncates f32 MXU inputs to bf16);
    on the CPU test backend it emulates that truncation, so the result
    must track the exact-f32 path within bf16 input-rounding error
    while the f32 tail keeps the likelihood tight."""
    rng = np.random.default_rng(5)
    b, l, v, k = 16, 32, 260, 5
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v)
    log_beta = _log_beta(rng, k, v)
    dense = dense_estep.densify(word_idx, counts, v)
    dense = dense.T if wmajor else dense

    kw = dict(var_max_iters=20, var_tol=1e-6, interpret=True,
              wmajor=wmajor)
    exact = dense_estep.e_step_dense(
        log_beta, jnp.float32(2.5), dense, doc_mask, **kw
    )
    half = dense_estep.e_step_dense(
        log_beta, jnp.float32(2.5), dense, doc_mask,
        precision="bf16", **kw
    )
    np.testing.assert_allclose(
        np.asarray(half.gamma), np.asarray(exact.gamma),
        rtol=5e-2, atol=5e-2,
    )
    np.testing.assert_allclose(
        float(half.likelihood), float(exact.likelihood), rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(half.suff_stats), np.asarray(exact.suff_stats),
        rtol=0.1, atol=5e-3,
    )

    with pytest.raises(ValueError, match="dense E-step precision"):
        dense_estep.e_step_dense(
            log_beta, jnp.float32(2.5), dense, doc_mask,
            precision="fp8", **kw
        )


def test_bf16_refused_under_matmul_precision_override():
    """The 'bf16 changes no results' promise only holds under XLA's
    DEFAULT matmul precision; a process-wide "highest"/"float32"
    override must be refused, not silently degraded (ADVICE r2)."""
    import jax

    jax.config.update("jax_default_matmul_precision", "float32")
    try:
        with pytest.raises(ValueError, match="DEFAULT matmul precision"):
            dense_estep._check_precision("bf16")
    finally:
        jax.config.update("jax_default_matmul_precision", None)
    dense_estep._check_precision("bf16")  # back to DEFAULT: accepted


def test_trainer_dense_precision_bf16_tracks_f32():
    """LDAConfig.dense_precision='bf16' through the full batch trainer:
    on the CPU test backend it emulates the TPU's MXU input truncation,
    so the trained model must track the f32 run within bf16 rounding
    while the EM structure (iteration count, finite lls) is identical."""
    from oni_ml_tpu.models.lda import LDATrainer

    rng = np.random.default_rng(6)
    b, l, v = 16, 16, 200
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v)
    batch = Batch(
        word_idx=np.asarray(word_idx),
        counts=np.asarray(counts),
        doc_mask=np.asarray(doc_mask),
        doc_index=np.arange(b),
    )
    results = {}
    for prec in ("f32", "bf16"):
        cfg = LDAConfig(
            num_topics=4, em_max_iters=5, em_tol=0.0,
            var_max_iters=20, fused_em_chunk=3, seed=1,
            dense_em="on", dense_precision=prec,
            # This test pins bf16-vs-f32 NUMERICS; warm start compounds
            # start-point differences across EM iterations by design.
            warm_start_gamma=False,
        )
        results[prec] = LDATrainer(cfg, num_terms=v).fit([batch], num_docs=b)

    f32, bf16 = results["f32"], results["bf16"]
    assert len(f32.likelihoods) == len(bf16.likelihoods)
    np.testing.assert_allclose(
        bf16.log_beta, f32.log_beta, rtol=5e-2, atol=5e-2
    )
    np.testing.assert_allclose(
        [ll for ll, _ in bf16.likelihoods],
        [ll for ll, _ in f32.likelihoods],
        rtol=1e-2,
    )


@pytest.mark.parametrize("wmajor", [False, True])
@pytest.mark.parametrize("precision", ["f32", "bf16"])
def test_bf16_corpus_storage_is_bit_identical(wmajor, precision):
    """A bf16-STORED corpus (counts <= 256: exact in bf16) must produce
    bitwise-identical results to the f32-stored corpus under either
    operand precision — the storage dtype only changes HBM traffic."""
    rng = np.random.default_rng(23)
    b, l, v, k = 16, 16, 260, 4
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked=2)
    log_beta = _log_beta(rng, k, v)
    kw = dict(var_max_iters=20, var_tol=1e-6, interpret=True,
              wmajor=wmajor, precision=precision)

    d32 = dense_estep.densify(word_idx, counts, v)
    d16 = dense_estep.densify(word_idx, counts, v, dtype=jnp.bfloat16)
    assert d16.dtype == jnp.bfloat16
    # Exactness precondition: every stored count round-trips.
    np.testing.assert_array_equal(
        np.asarray(d16, np.float32), np.asarray(d32)
    )
    if wmajor:
        d32, d16 = d32.T, d16.T

    r32 = dense_estep.e_step_dense(log_beta, jnp.float32(2.5), d32,
                                   doc_mask, **kw)
    r16 = dense_estep.e_step_dense(log_beta, jnp.float32(2.5), d16,
                                   doc_mask, **kw)
    assert r16.gamma.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(r16.gamma),
                                  np.asarray(r32.gamma))
    np.testing.assert_array_equal(np.asarray(r16.suff_stats),
                                  np.asarray(r32.suff_stats))
    assert float(r16.likelihood) == float(r32.likelihood)


def test_corpus_dtype_decision():
    assert dense_estep.corpus_dtype(4.0, "bf16") == jnp.bfloat16
    assert dense_estep.corpus_dtype(256.0, "bf16") == jnp.bfloat16
    assert dense_estep.corpus_dtype(257.0, "bf16") == jnp.float32
    assert dense_estep.corpus_dtype(4.0, "f32") == jnp.float32


def test_max_dense_cell_sums_duplicates():
    """The bf16 gate must bound DENSIFIED cells: duplicate (doc, word)
    tokens sum in densify — the DUPFACTOR=1000 feedback path makes a
    ~1000-count cell out of count-1 tokens, which a max over raw counts
    never sees."""
    word_idx = np.zeros((2, 300), np.int32)
    counts = np.ones((2, 300), np.float32)
    word_idx[1] = np.arange(300) % 297      # doc 1: mostly distinct
    cell_max = dense_estep.max_dense_cell(word_idx, counts)
    assert cell_max == 300.0                # doc 0: one word, 300 tokens
    assert float(np.max(counts)) == 1.0     # the raw-count max is blind
    # ...and the decision falls back to exact f32 storage.
    assert dense_estep.corpus_dtype(cell_max, "bf16") == jnp.float32
    # Consistency with the real scatter:
    dense = np.asarray(
        dense_estep.densify(jnp.asarray(word_idx), jnp.asarray(counts), 300),
        np.float32,
    )
    assert dense.max() == cell_max


def test_vocab_sharded_dense_bf16_corpus_matches():
    """The XLA-level vocab-sharded dense plan with a bf16-stored corpus
    must match its f32-stored run bitwise (f32-promoting consumers)."""
    import jax

    from oni_ml_tpu.parallel import make_mesh, make_vocab_sharded_dense_e_step

    rng = np.random.default_rng(29)
    b, l, v, k = 16, 16, 256, 4
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked=2)
    log_beta = _log_beta(rng, k, v)
    mesh = make_mesh(data=2, model=4)
    fn = make_vocab_sharded_dense_e_step(mesh)
    kw = dict(var_max_iters=15, var_tol=1e-6)
    g0 = jnp.zeros((b, k), jnp.float32)
    res = {}
    for dt in (None, jnp.bfloat16):
        dense = dense_estep.densify(word_idx, counts, v, width=v, dtype=dt)
        res[dt] = jax.jit(
            lambda lb, a, d, m: fn(lb, a, d, m, g0,
                                   jnp.asarray(0, jnp.int32), **kw)
        )(log_beta, jnp.float32(2.5), dense, doc_mask)
    np.testing.assert_array_equal(np.asarray(res[None].gamma),
                                  np.asarray(res[jnp.bfloat16].gamma))
    assert float(res[None].likelihood) == float(res[jnp.bfloat16].likelihood)


def _compact_groups_for(word_idx, counts, doc_mask, wmajor=False):
    """Hand-build a one-batch compact-dense group the way
    fused.compact_stack_batches does: sorted unique vocab, 128-lane
    padded width, sentinel word-0 padding in the vocab map."""
    u = np.unique(np.asarray(word_idx))
    wc = -(-len(u) // 128) * 128
    vmap_ = np.zeros(wc, np.int32)
    vmap_[: len(u)] = u
    local = np.searchsorted(u, np.asarray(word_idx)).astype(np.int32)
    dense_local = dense_estep.densify(
        jnp.asarray(local), counts, wc, width=wc
    )
    if wmajor:
        dense_local = dense_local.T
    return (
        (dense_local[None], doc_mask[None], jnp.asarray(vmap_)[None]),
    ), wc, len(u)


def test_fused_runner_compact_groups_match_sparse():
    """Compact-vocab dense groups (per-batch vocabulary remap +
    suff-stats scatter-back) must reproduce the sparse EM trajectory —
    both layouts, with real sentinel padding in the vocab map."""
    rng = np.random.default_rng(13)
    b, l, v, k = 16, 16, 700, 4
    word_idx, counts, doc_mask = _random_batch(rng, b, l, v, n_masked=2)
    log_beta = _log_beta(rng, k, v)
    alpha = jnp.float32(2.5)

    sparse_groups = ((word_idx[None], counts[None], doc_mask[None]),)
    compact_groups, wc, n_unique = _compact_groups_for(
        word_idx, counts, doc_mask
    )
    assert wc < v            # actually compacted
    assert wc > n_unique     # sentinel-padded columns exist

    run = fused.make_chunk_runner(
        num_docs=b - 2, num_topics=k, num_terms=v, chunk=4,
        var_max_iters=20, var_tol=1e-6, em_tol=0.0, estimate_alpha=True,
    )
    r_sparse = run(log_beta, alpha, jnp.float32(np.nan), sparse_groups, 4)
    r_compact = run(log_beta, alpha, jnp.float32(np.nan), compact_groups, 4)

    compact_groups_w, _, _ = _compact_groups_for(
        word_idx, counts, doc_mask, wmajor=True
    )
    run_w = fused.make_chunk_runner(
        num_docs=b - 2, num_topics=k, num_terms=v, chunk=4,
        var_max_iters=20, var_tol=1e-6, em_tol=0.0, estimate_alpha=True,
        dense_wmajor=True,
    )
    r_wmajor = run_w(
        log_beta, alpha, jnp.float32(np.nan), compact_groups_w, 4
    )

    for r in (r_compact, r_wmajor):
        np.testing.assert_allclose(
            np.asarray(r.lls), np.asarray(r_sparse.lls), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(r.log_beta), np.asarray(r_sparse.log_beta),
            rtol=5e-3, atol=5e-3,
        )
        np.testing.assert_allclose(
            float(r.alpha), float(r_sparse.alpha), rtol=1e-3
        )
    # Words absent from the batch got no suff-stats: their beta rows
    # come out of the M-step exactly like the sparse run's.
    absent = np.setdiff1d(np.arange(v), np.unique(np.asarray(word_idx)))
    assert absent.size
    np.testing.assert_allclose(
        np.asarray(r_compact.log_beta)[:, absent],
        np.asarray(r_sparse.log_beta)[:, absent],
        rtol=1e-5,
    )


def test_plan_compact_widths_and_grouping():
    """plan_compact: per-group widths are 128-lane multiples covering
    the widest batch; infeasible compact widths return None."""
    rng = np.random.default_rng(7)

    def batch(b, l, v):
        w, c, m = _random_batch(rng, b, l, v)
        return Batch(
            word_idx=np.asarray(w), counts=np.asarray(c),
            doc_mask=np.asarray(m), doc_index=np.arange(b),
        )

    batches = [batch(16, 16, 5000), batch(16, 16, 5000), batch(8, 8, 5000)]
    plan = fused.plan_compact(batches, num_topics=4)
    assert plan is not None
    assert len(plan.widths) == 2  # (8,8) and (16,16) shape groups
    for g, us in enumerate(plan.uniques):
        wmax = max(len(u) for u in us)
        assert plan.widths[g] % 128 == 0
        assert plan.widths[g] >= wmax
        assert plan.widths[g] - wmax < 128
    # corpus bytes: sum over groups of NB * B * Wc * itemsize
    shapes = sorted({b.word_idx.shape for b in batches})
    expect = 0
    for (shape, wc) in zip(shapes, plan.widths):
        nb = sum(1 for b in batches if b.word_idx.shape == shape)
        expect += nb * shape[0] * wc * 4
    assert plan.corpus_bytes == expect


def test_trainer_compact_mode_matches_sparse(monkeypatch):
    """LDATrainer end-to-end: ONI_ML_TPU_ESTEP=compact (forced compact-
    vocab dense) vs dense_em='off' on a tiny corpus with two batch
    shapes."""
    from oni_ml_tpu.models.lda import LDATrainer

    rng = np.random.default_rng(23)
    v = 900

    def batch(b, l, start):
        w, c, m = _random_batch(rng, b, l, v)
        return Batch(
            word_idx=np.asarray(w), counts=np.asarray(c),
            doc_mask=np.asarray(m), doc_index=start + np.arange(b),
        )

    batches = [batch(16, 16, 0), batch(16, 16, 16), batch(8, 8, 32)]
    results = {}
    for force in ("compact", ""):
        if force:
            monkeypatch.setenv("ONI_ML_TPU_ESTEP", force)
        else:
            monkeypatch.delenv("ONI_ML_TPU_ESTEP", raising=False)
        cfg = LDAConfig(
            num_topics=4, em_max_iters=6, em_tol=0.0,
            var_max_iters=20, fused_em_chunk=3, seed=1,
            dense_em="off" if not force else "auto",
            warm_start_gamma=False,
        )
        trainer = LDATrainer(cfg, num_terms=v)
        results[force] = trainer.fit(batches, num_docs=40)

    on, off = results["compact"], results[""]
    np.testing.assert_allclose(on.log_beta, off.log_beta, rtol=5e-3,
                               atol=5e-3)
    np.testing.assert_allclose(
        [ll for ll, _ in on.likelihoods],
        [ll for ll, _ in off.likelihoods],
        rtol=1e-4,
    )
    # gamma rows come back to the same per-doc slots either way
    np.testing.assert_allclose(on.gamma, off.gamma, rtol=5e-3, atol=5e-3)


def test_trainer_compact_warm_start_trajectory(monkeypatch):
    """Warm start through the compact path: same optimum as the fresh
    compact run within tolerance (mirrors the dense warm-start pin)."""
    from oni_ml_tpu.models.lda import LDATrainer

    rng = np.random.default_rng(31)
    v = 600
    w, c, m = _random_batch(rng, 16, 16, v)
    batch = Batch(
        word_idx=np.asarray(w), counts=np.asarray(c),
        doc_mask=np.asarray(m), doc_index=np.arange(16),
    )
    monkeypatch.setenv("ONI_ML_TPU_ESTEP", "compact")
    res = {}
    for warm in (True, False):
        cfg = LDAConfig(
            num_topics=4, em_max_iters=8, em_tol=0.0, var_max_iters=20,
            fused_em_chunk=3, seed=1, warm_start_gamma=warm,
        )
        res[warm] = LDATrainer(cfg, num_terms=v).fit([batch], num_docs=16)
    np.testing.assert_allclose(
        res[True].likelihoods[-1][0], res[False].likelihoods[-1][0],
        rtol=1e-3,
    )
    np.testing.assert_allclose(
        res[True].log_beta, res[False].log_beta, rtol=5e-2, atol=5e-2
    )


def test_forced_dense_infeasible_rescues_to_compact():
    """dense_em='on' with a full-V-infeasible vocabulary but feasible
    per-batch compact widths must route to the compact plan instead of
    raising."""
    from oni_ml_tpu.models.lda import LDATrainer

    rng = np.random.default_rng(3)
    v = 4_000_000  # no VMEM-feasible full-V doc block at any batch size
    assert dense_estep.pick_block(16, v, 4) is None
    w, c, m = _random_batch(rng, 16, 16, v)
    batch = Batch(
        word_idx=np.asarray(w), counts=np.asarray(c),
        doc_mask=np.asarray(m), doc_index=np.arange(16),
    )
    trainer = LDATrainer(
        LDAConfig(num_topics=4, dense_em="on"), num_terms=v
    )
    assert trainer._use_dense([batch]) is False
    plan = trainer._plan_compact([batch])
    assert plan is not None
    assert plan.widths[0] <= 512  # 16x16 tokens -> tiny compact width


def test_forced_compact_with_mesh_raises(monkeypatch):
    """ONI_ML_TPU_ESTEP=compact on a meshed trainer must fail loudly
    (like every other forced-engine misconfiguration), not silently run
    sparse."""
    from oni_ml_tpu.models.lda import LDATrainer
    from oni_ml_tpu.parallel import make_mesh

    monkeypatch.setenv("ONI_ML_TPU_ESTEP", "compact")
    trainer = LDATrainer(
        LDAConfig(num_topics=4), num_terms=200,
        mesh=make_mesh(data=8, model=1),
    )
    batch = Batch(
        word_idx=np.zeros((16, 8), np.int32),
        counts=np.zeros((16, 8), np.float32),
        doc_mask=np.ones((16,), np.float32),
        doc_index=np.arange(16),
    )
    with pytest.raises(ValueError, match="compact dense E-step forced"):
        trainer._plan_compact([batch])


def test_env_dense_infeasible_consumes_rescue(monkeypatch):
    """ONI_ML_TPU_ESTEP=dense with an infeasible full-V shape must
    route through the compact rescue — and must not leak the rescue
    into a later decision for different batches."""
    from oni_ml_tpu.models.lda import LDATrainer

    rng = np.random.default_rng(3)
    v = 4_000_000
    w, c, m = _random_batch(rng, 16, 16, v)
    batch = Batch(
        word_idx=np.asarray(w), counts=np.asarray(c),
        doc_mask=np.asarray(m), doc_index=np.arange(16),
    )
    monkeypatch.setenv("ONI_ML_TPU_ESTEP", "dense")
    trainer = LDATrainer(LDAConfig(num_topics=4), num_terms=v)
    assert trainer._use_dense([batch]) is False  # rescue cached
    plan = trainer._plan_compact([batch])
    assert plan is not None                      # rescue consumed
    assert trainer._plan_compact([batch]) is None  # not served twice
