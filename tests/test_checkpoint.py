"""EM-iteration checkpoint/resume (SURVEY §5.4): an interrupted training
run resumes from (beta, alpha, iter) and reproduces the uninterrupted
likelihood trajectory exactly."""

import os

import numpy as np

from oni_ml_tpu.config import LDAConfig
from oni_ml_tpu.io import make_batches
from oni_ml_tpu.models import LDATrainer, train_corpus
from oni_ml_tpu.models.lda import load_checkpoint, save_checkpoint

import reference_lda as ref
from test_lda import corpus_from_docs


def _problem():
    docs, _ = ref.make_synthetic_corpus(num_docs=30, num_terms=25,
                                        num_topics=3, seed=13)
    return corpus_from_docs(docs, 25), 25


def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ck.npz")
    lb = np.random.default_rng(0).normal(size=(3, 7))
    save_checkpoint(path, lb, 1.25, 4, [(-10.0, 1.0), (-9.0, 0.1)])
    ck = load_checkpoint(path)
    np.testing.assert_array_equal(ck["log_beta"], lb)
    assert ck["alpha"] == 1.25 and ck["em_iter"] == 4
    assert ck["likelihoods"] == [(-10.0, 1.0), (-9.0, 0.1)]


def test_batch_load_rejects_stream_checkpoints(tmp_path):
    """Both streaming layouts sharing out_dir/checkpoint.npz must be
    rejected by the batch loader, not trained from as garbage topics."""
    import pytest

    from oni_ml_tpu.models.online_lda import save_stream_checkpoint

    lam = np.random.default_rng(1).gamma(100.0, 0.01, (3, 7))
    new_fmt = str(tmp_path / "new.npz")
    save_stream_checkpoint(new_fmt, lam, 2.5, 3, [(-5.0, 0.5)])
    with pytest.raises(ValueError, match="streaming-LDA checkpoint"):
        load_checkpoint(new_fmt)

    legacy = str(tmp_path / "legacy.npz")
    np.savez(legacy, log_beta=lam, alpha=np.float64(2.5),
             em_iter=np.int64(3), likelihoods=np.array([[-5.0, 0.5]]))
    with pytest.raises(ValueError, match="strictly positive"):
        load_checkpoint(legacy)


def test_resume_matches_uninterrupted(tmp_path):
    corpus, V = _problem()
    K = 3
    # Fresh-start pinned: resume bit-parity is a fresh-init guarantee.
    # Under warm_start_gamma (default) the resumed run's first iteration
    # has no previous gamma to seed from, so it re-warms from a fresh
    # fixed point — same optimum (each doc's fixed point converges to
    # the same posterior given beta), but trajectories differ in late
    # decimals from an uninterrupted warm run.
    mk = lambda iters: LDAConfig(  # noqa: E731
        num_topics=K, em_max_iters=iters, em_tol=0.0, batch_size=16,
        min_bucket_len=32, seed=7, checkpoint_every=1,
        warm_start_gamma=False)
    batches = make_batches(corpus, 16, 32)
    ckpt = str(tmp_path / "checkpoint.npz")

    # Uninterrupted 6-iteration run (no checkpoint file in play).
    full = LDATrainer(mk(6), num_terms=V).fit(batches, corpus.num_docs)

    # "Crashed" after 3 iterations.  fit() deletes its checkpoint on
    # success, so simulate the crash by re-saving iteration-3 state from a
    # completed partial run.
    partial = LDATrainer(mk(3), num_terms=V).fit(batches, corpus.num_docs)
    save_checkpoint(ckpt, partial.log_beta, partial.alpha, 3,
                    partial.likelihoods)

    # ...resume to 6 total.
    resumed = LDATrainer(mk(6), num_terms=V).fit(
        batches, corpus.num_docs, checkpoint_path=ckpt)

    np.testing.assert_allclose(
        [l for l, _ in resumed.likelihoods],
        [l for l, _ in full.likelihoods], rtol=1e-6)
    np.testing.assert_allclose(resumed.log_beta, full.log_beta, atol=1e-5)
    np.testing.assert_allclose(resumed.gamma, full.gamma, rtol=1e-4)
    assert not os.path.exists(ckpt)  # removed on successful completion


def test_train_corpus_checkpointing(tmp_path):
    corpus, V = _problem()
    cfg = LDAConfig(num_topics=3, em_max_iters=4, em_tol=0.0, batch_size=16,
                    min_bucket_len=32, checkpoint_every=2)
    seen = []

    orig = save_checkpoint

    def spy(path, *a, **kw):
        seen.append(os.path.basename(path))
        orig(path, *a, **kw)

    import oni_ml_tpu.models.lda as lda_mod
    lda_mod.save_checkpoint, saved = spy, lda_mod.save_checkpoint
    try:
        train_corpus(corpus, cfg, out_dir=str(tmp_path))
    finally:
        lda_mod.save_checkpoint = saved
    assert seen == ["checkpoint.npz", "checkpoint.npz"]  # iters 2 and 4
    assert not (tmp_path / "checkpoint.npz").exists()
    # likelihood.dat covers all 4 iterations despite checkpoint churn
    from oni_ml_tpu.io import formats
    assert formats.read_likelihood(str(tmp_path / "likelihood.dat")).shape[0] == 4


def test_online_checkpoint_resume(tmp_path):
    """A crashed streaming run resumes mid-stream and matches the
    uninterrupted lambda exactly (deterministic shuffled order)."""
    from oni_ml_tpu.config import OnlineLDAConfig
    from oni_ml_tpu.models import OnlineLDATrainer
    from oni_ml_tpu.models.online_lda import train_corpus_online

    corpus, V = _problem()
    cfg = OnlineLDAConfig(num_topics=3, batch_size=16, min_bucket_len=32,
                          tau0=8.0, seed=4, checkpoint_every=1)
    batches = make_batches(corpus, cfg.batch_size, cfg.min_bucket_len)
    order = np.random.default_rng(cfg.seed).permutation(len(batches))

    # Uninterrupted single-epoch stream.
    full = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)
    for i in order:
        full.step(batches[i])

    # Crash after 2 steps: run a partial stream that checkpoints each step.
    ckpt = str(tmp_path / "checkpoint.npz")
    part = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs,
                            checkpoint_path=ckpt)
    for i in order[:2]:
        part.step(batches[i])
    assert os.path.exists(ckpt)

    # train_corpus_online picks the checkpoint up and fast-forwards.
    out = str(tmp_path / "out")
    os.makedirs(out)
    os.replace(ckpt, os.path.join(out, "checkpoint.npz"))
    result = train_corpus_online(corpus, cfg, out_dir=out, epochs=1)
    np.testing.assert_allclose(
        np.exp(result.log_beta),
        np.exp(OnlineLDATrainer.log_beta(full)), rtol=1e-5, atol=1e-7)
    assert not os.path.exists(os.path.join(out, "checkpoint.npz"))
    # likelihood.dat column 2 is a relative change, not the learning rate
    from oni_ml_tpu.io import formats
    ll = formats.read_likelihood(os.path.join(out, "likelihood.dat"))
    assert ll[0, 1] == 1.0 and (ll[:, 1] >= 0).all()


def test_resume_rejects_shape_mismatch(tmp_path):
    corpus, V = _problem()
    ckpt = str(tmp_path / "checkpoint.npz")
    save_checkpoint(ckpt, np.zeros((5, 99)), 1.0, 2, [(-1.0, 1.0)])
    cfg = LDAConfig(num_topics=3, em_max_iters=4, batch_size=16,
                    min_bucket_len=32)
    import pytest
    with pytest.raises(ValueError, match="shape"):
        LDATrainer(cfg, num_terms=V).fit(
            make_batches(corpus, 16, 32), corpus.num_docs,
            checkpoint_path=ckpt)
