"""native_build shared helpers (no compiled library needed).

bytes_at exists because CPython's ctypes.string_at truncates its size
argument to a C int: the realistic-cardinality 30-day word_counts emit
(~3 GB in one buffer) crashed with "Negative size passed to
PyBytes_FromStringAndSize" mid-run (round 5).  The >2 GiB case is
pinned directly — it costs ~4 GB of transient RAM, which this
build host has.
"""

import ctypes

import pytest

from oni_ml_tpu.native_build import bytes_at


def test_bytes_at_small_and_empty():
    buf = ctypes.create_string_buffer(b"abcdef")
    assert bytes_at(ctypes.addressof(buf), 6) == b"abcdef"
    assert bytes_at(ctypes.addressof(buf), 0) == b""
    assert bytes_at(None, 0) == b""
    with pytest.raises(MemoryError):
        bytes_at(None, 4)


def _mem_available_gb() -> float:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) / 1e6
    except OSError:
        pass
    return 0.0


def test_bytes_at_over_2gib():
    # ~4.3 GiB transient (buffer + copy): skip cleanly on small hosts
    # instead of inviting the OOM killer to SIGKILL the whole worker.
    if _mem_available_gb() < 6.0:
        pytest.skip("needs ~5 GB free RAM for the >2 GiB pin")
    size = (1 << 31) + 16
    buf = ctypes.create_string_buffer(size)
    buf[size - 1] = b"\x7f"
    out = bytes_at(ctypes.addressof(buf), size)
    assert len(out) == size
    assert out[-1] == 0x7F
    del out, buf
    # ctypes.string_at at this size is exactly the crash this helper
    # replaces; no need to demonstrate it here.
