"""Test environment: force an 8-device virtual CPU mesh before jax imports.

Multi-device tests (sharded suff-stats psum parity etc.) run on the CPU
backend with 8 virtual devices, mirroring how the driver's
``dryrun_multichip`` validates the sharded path without real chips.
"""

import os

# Hard override: the session environment pins JAX_PLATFORMS to the real
# TPU tunnel and a sitecustomize module imports jax at interpreter start,
# so plain env-var edits here are too late.  jax.config.update works as
# long as no device backend has been instantiated yet (nothing queries
# devices during sitecustomize), so flip the platform through the config
# API instead.
#
# ONI_ML_TPU_TESTS_ON_TPU=1 skips the pin so the TPU-gated checks
# (tests/test_tpu_smoke.py) can reach the real chip:
#     ONI_ML_TPU_TESTS_ON_TPU=1 python -m pytest tests/test_tpu_smoke.py
# Only run single tests that way — the full suite assumes 8 devices.
if os.environ.get("ONI_ML_TPU_TESTS_ON_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    # Keep test numerics deterministic and f32-stable on CPU.
    os.environ.setdefault("JAX_ENABLE_X64", "0")

    import jax

    jax.config.update("jax_platforms", "cpu")
