"""Test environment: force an 8-device virtual CPU mesh before jax imports.

Multi-device tests (sharded suff-stats psum parity etc.) run on the CPU
backend with 8 virtual devices, mirroring how the driver's
``dryrun_multichip`` validates the sharded path without real chips.
"""

import os
import tempfile

# Hermetic measured-plan + compilation caches (oni_ml_tpu/plans): the
# suite must neither read a developer's ~/.cache plan/compile state nor
# write test measurements into it — a CPU-measured calibration leaking
# into the user cache would "tune" real runs from test synthetics.
# Guarded (not setdefault) so an operator pinning either path doesn't
# still pay an eagerly-created throwaway tempdir.
if "ONI_ML_TPU_PLAN_CACHE" not in os.environ:
    os.environ["ONI_ML_TPU_PLAN_CACHE"] = os.path.join(
        tempfile.mkdtemp(prefix="oni_plans_test_"), "plans.jsonl"
    )
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    os.environ["JAX_COMPILATION_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="oni_jaxcache_test_"
    )

# bench.main()'s lint preflight re-lints the whole repo (~2s per call,
# and in-process bench tests call main() repeatedly).  The suite
# already enforces that exact gate ONCE via test_analysis's live-repo
# self-run, so bench tests skip it — both faster and decoupled (a lint
# finding fails the one test that owns the gate, not every bench
# test).  Guarded so the preflight test can force it back on.
if "BENCH_LINT" not in os.environ:
    os.environ["BENCH_LINT"] = "0"

# Hard override: the session environment pins JAX_PLATFORMS to the real
# TPU tunnel and a sitecustomize module imports jax at interpreter start,
# so plain env-var edits here are too late.  jax.config.update works as
# long as no device backend has been instantiated yet (nothing queries
# devices during sitecustomize), so flip the platform through the config
# API instead.
#
# ONI_ML_TPU_TESTS_ON_TPU=1 skips the pin so the TPU-gated checks
# (tests/test_tpu_smoke.py) can reach the real chip:
#     ONI_ML_TPU_TESTS_ON_TPU=1 python -m pytest tests/test_tpu_smoke.py
# Only run single tests that way — the full suite assumes 8 devices.
if os.environ.get("ONI_ML_TPU_TESTS_ON_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    # Keep test numerics deterministic and f32-stable on CPU.
    os.environ.setdefault("JAX_ENABLE_X64", "0")

    import jax

    jax.config.update("jax_platforms", "cpu")
