"""Measured execution plans (oni_ml_tpu/plans): store durability and
invalidation, resolution precedence, the bounded autotune harness, the
compile-cache warmup counters, and the runner e2e contract — a second
run re-sweeps nothing and re-traces nothing, and a plans-on run's
artifacts are byte-identical to a plans-off run."""

import json
import os
import subprocess
import sys

import pytest

from oni_ml_tpu import plans
from oni_ml_tpu.plans import (
    KNOBS,
    NullStore,
    PlanStore,
    autotune,
    resolve,
    use_store,
)
from oni_ml_tpu.plans.store import SCHEMA_VERSION

from test_features import flow_row


def _store(tmp_path, name="plans.jsonl", seeds=False) -> PlanStore:
    return PlanStore(str(tmp_path / name), seeds=seeds)


def _fp(knob="fused_em_chunk"):
    return plans.fingerprint(KNOBS[knob].scope)


# ---------------------------------------------------------------------------
# store: durability, invalidation, layering
# ---------------------------------------------------------------------------


def test_store_roundtrip_and_latest_wins(tmp_path):
    st = _store(tmp_path)
    st.record("fused_em_chunk", "tpu:x:1", "*", 32, source="probe")
    st.record("fused_em_chunk", "tpu:x:1", "*", 64, source="autotune",
              measurements={"32": 1.0, "64": 2.0})
    # In-memory view and a fresh replay both see the LATEST entry.
    assert st.lookup("fused_em_chunk", "tpu:x:1").value == 64
    st.close()
    st2 = _store(tmp_path)
    e = st2.lookup("fused_em_chunk", "tpu:x:1")
    assert e.value == 64 and e.source == "autotune"
    assert e.measurements == {"32": 1.0, "64": 2.0}


def test_store_exact_shape_beats_wildcard(tmp_path):
    st = _store(tmp_path)
    st.record("fused_em_chunk", "tpu:x:1", "*", 128)
    st.record("fused_em_chunk", "tpu:x:1", "k20.v8192", 64)
    assert st.lookup("fused_em_chunk", "tpu:x:1", "k20.v8192").value == 64
    assert st.lookup("fused_em_chunk", "tpu:x:1", "k50.v50000").value == 128
    assert st.lookup("fused_em_chunk", "tpu:x:1").value == 128


def test_store_corrupt_tail_tolerated(tmp_path):
    """A SIGKILL mid-append truncates the final line; replay drops it
    silently and keeps every earlier entry (the telemetry journal's
    contract, inherited)."""
    st = _store(tmp_path)
    st.record("fused_em_chunk", "tpu:x:1", "*", 64)
    st.close()
    path = tmp_path / "plans.jsonl"
    with open(path, "ab") as f:
        f.write(b'{"schema": 1, "knob": "fused_em_chunk", "backe')
    st2 = _store(tmp_path)
    assert st2.lookup("fused_em_chunk", "tpu:x:1").value == 64
    assert st2.dropped_records == 0      # clean tail truncation


def test_store_garbage_lines_dropped_and_counted(tmp_path):
    path = tmp_path / "plans.jsonl"
    good = {"schema": SCHEMA_VERSION, "knob": "fused_em_chunk",
            "backend": "tpu:x:1", "shape": "*", "value": 64}
    with open(path, "w") as f:
        f.write("not json at all\n")
        f.write(json.dumps(good) + "\n")
    st = _store(tmp_path)
    assert st.lookup("fused_em_chunk", "tpu:x:1").value == 64
    assert st.dropped_records == 1       # mid-file damage is COUNTED


def test_schema_version_mismatch_invalidates(tmp_path):
    path = tmp_path / "plans.jsonl"
    rec = {"schema": SCHEMA_VERSION + 1, "knob": "fused_em_chunk",
           "backend": "tpu:x:1", "shape": "*", "value": 7}
    with open(path, "w") as f:
        f.write(json.dumps(rec) + "\n")
    st = _store(tmp_path)
    assert st.lookup("fused_em_chunk", "tpu:x:1") is None
    with use_store(st):
        value, source = resolve("fused_em_chunk", KNOBS["fused_em_chunk"].default)
    assert (value, source) == (KNOBS["fused_em_chunk"].default, "default")


def test_backend_fingerprint_mismatch_invalidates(tmp_path):
    """An entry measured on another backend (the v5e seeds, on this CPU
    suite) never resolves — falls back to the default, never crashes."""
    st = _store(tmp_path)
    st.record("fused_em_chunk", "tpu:tpu_v5_lite:1", "*", 999)
    with use_store(st):
        value, source = resolve("fused_em_chunk", KNOBS["fused_em_chunk"].default)
    assert source == "default"
    assert value == KNOBS["fused_em_chunk"].default != 999


def test_seed_plans_load_and_live_entries_override(tmp_path):
    st = _store(tmp_path, seeds=True)
    # The checked-in v5e seed is present under its (non-matching here)
    # fingerprint, marked as a seed.
    e = st.lookup("fused_em_chunk", "tpu:tpu_v5_lite:1",
                  "k20.v8192.b4096.l128")
    assert e is not None and e.value == 128 and e.source == "seed"
    assert e.measurements["128"] == 2898000
    # A live measurement on the same key beats the seed.
    st.record("fused_em_chunk", "tpu:tpu_v5_lite:1",
              "k20.v8192.b4096.l128", 256, source="autotune")
    assert st.lookup("fused_em_chunk", "tpu:tpu_v5_lite:1",
                     "k20.v8192.b4096.l128").value == 256


def test_invalid_cached_value_rejected(tmp_path):
    """A hand-edited/garbage value fails the knob's validator and
    resolution falls through to the default."""
    st = _store(tmp_path)
    st.record("fused_em_chunk", _fp(), "*", "not-an-int")
    st.record("host_sync_every", _fp("host_sync_every"), "*", -5)
    with use_store(st):
        assert resolve("fused_em_chunk", None)[1] == "default"
        assert resolve("host_sync_every", None)[1] == "default"


# ---------------------------------------------------------------------------
# resolution precedence
# ---------------------------------------------------------------------------


def test_explicit_config_override_wins(tmp_path):
    st = _store(tmp_path)
    st.record("fused_em_chunk", _fp(), "*", 64)
    with use_store(st):
        # Explicit (non-default) config beats the plan...
        assert resolve("fused_em_chunk", 32) == (32, "config")
        # ...a default-valued config yields to the plan...
        assert resolve("fused_em_chunk",
                       KNOBS["fused_em_chunk"].default) == (64, "plan")
        # ...and no entry at all means the default, by value.
        assert resolve("host_sync_every", KNOBS["host_sync_every"].default) \
            == (KNOBS["host_sync_every"].default, "default")


def test_disabled_plans_env_kills_lookups(tmp_path, monkeypatch):
    st = _store(tmp_path)
    st.record("fused_em_chunk", _fp(), "*", 64)
    monkeypatch.setenv("ONI_ML_TPU_PLANS", "0")
    # current_store() is None: resolve falls through even inside a
    # use_store scope.
    with use_store(st):
        assert plans.current_store() is None
        assert resolve("fused_em_chunk", 128)[1] == "default"
        assert plans.lookup_value("fused_em_chunk") is None


def test_null_store_disables_in_scope(tmp_path):
    st = _store(tmp_path)
    st.record("fused_em_chunk", _fp(), "*", 64)
    with use_store(NullStore()):
        assert plans.current_store() is None
    with use_store(st):
        assert resolve("fused_em_chunk", None) == (64, "plan")


def test_pre_workers_resolution(tmp_path):
    from oni_ml_tpu.features.shards import resolve_pre_workers

    st = _store(tmp_path)
    with use_store(st):
        # Explicit count is "config"; auto with no plan is cpu-count.
        assert resolve_pre_workers(3, with_source=True) == (3, "config")
        n, src = resolve_pre_workers(0, with_source=True)
        assert src == "default" and n >= 1
        st.record("pre_workers", plans.host_fingerprint(), "*", 2,
                  source="probe")
        assert resolve_pre_workers(0, with_source=True) == (2, "plan")
        # Tuple and scalar forms agree.
        assert resolve_pre_workers(0) == 2
        # An absurd operator-edited entry degrades to untuned — it must
        # not plan a million shards.
        st.record("pre_workers", plans.host_fingerprint(), "*",
                  1_000_000, source="probe")
        assert resolve_pre_workers(0, with_source=True)[1] == "default"
    with pytest.raises(ValueError):
        resolve_pre_workers(-1)


def test_dispatch_calibration_persists_across_processes(
    tmp_path, monkeypatch
):
    """The one inline autotune sweep: a fresh 'process' (cleared module
    cache) loads the recorded calibration (source 'plan') instead of
    re-measuring."""
    from oni_ml_tpu.scoring import score as score_mod

    monkeypatch.delenv("ONI_ML_TPU_SCORE_BREAK_EVEN", raising=False)
    monkeypatch.setenv("ONI_ML_TPU_PLAN_CACHE",
                       str(tmp_path / "cal.jsonl"))
    monkeypatch.setattr(score_mod, "_CALIBRATION", None)
    sweeps0 = plans.counters["autotune_sweeps"]
    cal = score_mod.dispatch_calibration()
    assert cal["source"] == "measured"
    assert plans.counters["autotune_sweeps"] == sweeps0 + 1
    # "New process": only the in-memory cache is cleared.
    monkeypatch.setattr(score_mod, "_CALIBRATION", None)
    cal2 = score_mod.dispatch_calibration()
    assert cal2["source"] == "plan"
    assert cal2["break_even"] == cal["break_even"]
    assert plans.counters["autotune_sweeps"] == sweeps0 + 1  # no re-sweep


def test_serving_batcher_resolves_plan_knobs(tmp_path):
    """BatchScorer picks plan-recorded max_batch/max_wait_ms when the
    config sits at defaults, and reports the source per knob."""
    from oni_ml_tpu.config import ServingConfig
    from oni_ml_tpu.serving import BatchScorer, ModelRegistry
    from oni_ml_tpu.runner.serve import _synthetic_day
    from oni_ml_tpu.serving.events import DnsEventFeaturizer

    rows, model, cuts = _synthetic_day(n_events=8)
    st = _store(tmp_path)
    st.record("serve_max_batch", _fp("serve_max_batch"), "*", 16,
              source="probe")
    with use_store(st):
        registry = ModelRegistry()
        registry.publish(model, source="test")
        scorer = BatchScorer(
            registry, DnsEventFeaturizer(cuts),
            ServingConfig(device_score_min=None),
        )
        try:
            assert scorer.max_batch == 16
            assert scorer.plan["max_batch"] == {
                "value": 16, "source": "plan"
            }
            assert scorer.plan["max_wait_ms"]["source"] == "default"
            # Explicit config still wins.
            scorer2 = BatchScorer(
                registry, DnsEventFeaturizer(cuts),
                ServingConfig(max_batch=4, device_score_min=None),
            )
            try:
                assert scorer2.max_batch == 4
                assert scorer2.plan["max_batch"]["source"] == "config"
            finally:
                scorer2.close()
            # A plan flush size past the backpressure bound would make
            # the max_batch trigger unreachable — it degrades to the
            # shipped default instead.
            st.record("serve_max_batch", _fp("serve_max_batch"), "*",
                      1 << 20, source="probe")
            scorer3 = BatchScorer(
                registry, DnsEventFeaturizer(cuts),
                ServingConfig(device_score_min=None),
            )
            try:
                assert scorer3.max_batch == ServingConfig.max_batch
                assert scorer3.plan["max_batch"]["source"] == "default"
            finally:
                scorer3.close()
        finally:
            scorer.close()


def test_serving_knob_resolution_is_host_scoped(tmp_path):
    """The serving flush triggers fingerprint the HOST, never the
    device: a host-pinned BatchScorer (device_score_min=None) must not
    initialize a jax backend at construction — against a wedged grant
    that init is a startup hang, not an error."""
    import subprocess
    import sys as _sys

    code = (
        "from oni_ml_tpu.config import ServingConfig\n"
        "from oni_ml_tpu.serving import BatchScorer, ModelRegistry\n"
        "from oni_ml_tpu.runner.serve import _synthetic_day\n"
        "from oni_ml_tpu.serving.events import DnsEventFeaturizer\n"
        "rows, model, cuts = _synthetic_day(n_events=8)\n"
        "reg = ModelRegistry(); reg.publish(model, source='t')\n"
        "s = BatchScorer(reg, DnsEventFeaturizer(cuts),\n"
        "                ServingConfig(device_score_min=None))\n"
        "import jax._src.xla_bridge as xb\n"
        "assert not xb._backends, list(xb._backends)\n"
        "s.close()\n"
        "print('ok')\n"
    )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["ONI_ML_TPU_PLAN_CACHE"] = str(tmp_path / "p.jsonl")
    proc = subprocess.run(
        [_sys.executable, "-c", code], capture_output=True, text=True,
        env=env, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ok" in proc.stdout


def test_dense_block_plan_validated(tmp_path):
    """A planned doc block is only a candidate: it must divide the
    batch and fit the VMEM model, else the analytic pick stands."""
    from oni_ml_tpu.ops import dense_estep

    b, v, k = 256, 1024, 20
    analytic = dense_estep.pick_block(b, v, k)
    analytic_w = dense_estep.pick_block_w(b, v, k)
    st = _store(tmp_path)
    shape = f"b{b}.v{v}.k{k}.f32"
    with use_store(st):
        st.record("dense_estep_block", _fp("dense_estep_block"),
                  shape, 32, source="probe")
        assert dense_estep.pick_block(b, v, k) == 32
        # Non-dividing block: rejected, analytic prior wins.
        st.record("dense_estep_block", _fp("dense_estep_block"),
                  shape, 100, source="probe")
        assert dense_estep.pick_block(b, v, k) == analytic
        # W-major: a non-multiple-of-128 planned block (other than the
        # full batch) is illegal for the lane layout — rejected.
        st.record("dense_estep_block_w", _fp("dense_estep_block_w"),
                  shape, 64, source="probe")
        assert dense_estep.pick_block_w(b, v, k) == analytic_w
        # A legal W-major plan (the full batch) is honored.
        st.record("dense_estep_block_w", _fp("dense_estep_block_w"),
                  shape, b, source="probe")
        assert dense_estep.pick_block_w(b, v, k) == b


def test_seed_plan_resolves_on_matching_backend(monkeypatch, tmp_path):
    """The bench acceptance: on a backend whose fingerprint matches the
    checked-in v5e seed, the headline chunk loads from the plan (the
    r05 sweep's winner) instead of re-deriving — and on this CPU suite
    it does NOT."""
    monkeypatch.setenv("ONI_ML_TPU_PLAN_CACHE",
                       str(tmp_path / "p.jsonl"))
    import bench

    chunk, src = bench._headline_chunk()
    assert (chunk, src) == (KNOBS["fused_em_chunk"].default, "default")
    # Pretend to be the v5e (the fingerprint the seed carries).
    monkeypatch.setattr(plans, "_DEVICE_FP", "tpu:tpu_v5_lite:1")
    chunk, src = bench._headline_chunk()
    assert (chunk, src) == (128, "plan")
    payload = bench.bench_plans_payload()
    assert payload["knobs"]["fused_em_chunk"]["source"] == "plan"
    entries = payload["knobs"]["fused_em_chunk"]["entries"]
    assert any(
        e["entry_source"] == "seed"
        and e.get("measurements", {}).get("128") == 2898000
        for e in entries
    )


# ---------------------------------------------------------------------------
# autotune harness
# ---------------------------------------------------------------------------


def test_autotune_budget_respected_under_fake_clock(tmp_path):
    """Budget is wall-clock: with a 10s-per-measure fake clock and a
    25s budget, exactly three candidates run (the first always does),
    the result is marked truncated, and the winner + measurements are
    recorded with provenance."""
    st = _store(tmp_path)
    t = [0.0]

    def clock():
        t[0] += 10.0
        return t[0]

    measured = []

    def measure(c):
        measured.append(c)
        return float(c)       # bigger candidate, better rate

    with use_store(st):
        res = autotune("score_device_chunk", measure, budget_s=25.0,
                       clock=clock, mode="max")
    assert measured == [8192, 16384, 32768]
    assert res.truncated and res.value == 32768
    e = st.lookup("score_device_chunk", _fp("score_device_chunk"))
    assert e.value == 32768
    assert e.record["truncated"] is True
    assert e.record["budget_s"] == 25.0
    assert e.measurements == {"8192": 8192.0, "16384": 16384.0,
                              "32768": 32768.0}


def test_autotune_unbounded_sweeps_whole_space(tmp_path):
    st = _store(tmp_path)
    with use_store(st):
        res = autotune("serve_max_batch", lambda c: -float(c),
                       mode="min", record=False)
    assert res.value == max(KNOBS["serve_max_batch"].candidates)
    assert not res.truncated
    assert st.lookup("serve_max_batch", _fp("serve_max_batch")) is None


def test_autotune_first_candidate_always_completes(tmp_path):
    """A zero budget still measures one candidate — a plan with no
    measurements is not a plan."""
    st = _store(tmp_path)
    t = [0.0]

    def clock():
        t[0] += 100.0
        return t[0]

    with use_store(st):
        res = autotune("score_device_chunk", float, budget_s=0.0,
                       clock=clock)
    assert len(res.measurements) == 1 and res.truncated


def test_autotune_counts_sweeps():
    before = plans.counters["autotune_sweeps"]
    with use_store(NullStore()):
        autotune("serve_max_batch", float, candidates=(1, 2),
                 record=False)
    assert plans.counters["autotune_sweeps"] == before + 1


# ---------------------------------------------------------------------------
# compile-cache warmup + counters
# ---------------------------------------------------------------------------


def test_warmup_scoring_hits_cache_second_time(tmp_path):
    """AOT warmup populates the persistent compilation cache; once
    warm, no path re-traces: an in-process re-warm is served from
    memory (zero requests), and a FRESH jit wrapper of the same
    program (the cross-process shape) is a persistent-cache hit — the
    counter contract the runner's plans record relies on."""
    import jax
    import numpy as np

    from oni_ml_tpu.plans import warmup

    cc = warmup.setup_compilation_cache(cache_dir=str(tmp_path / "cc"))
    assert cc["enabled"] and cc["counting"]
    w1 = warmup.warmup_scoring(101, 51, 5, 256, dsource="flow")
    assert w1["compiled"] == 1
    assert w1["compile_requests"] >= w1["compiled"]
    w2 = warmup.warmup_scoring(101, 51, 5, 256, dsource="flow")
    assert w2["traces"] == 0
    assert warmup.cache_entries(cc["dir"]) > 0

    # Cross-process shape: a fresh jit wrapper (new trace, same
    # program) must be served by the persistent cache, not recompiled.
    def mk():
        def plans_probe_fn(a, b):
            return (a * b).sum(-1)

        return jax.jit(plans_probe_fn)

    x = jax.ShapeDtypeStruct((16, 3), np.float32)
    mk().lower(x, x).compile()            # first: trace + serialize
    before = warmup.compile_counts()
    mk().lower(x, x).compile()            # fresh wrapper: cache HIT
    delta = warmup.counts_delta(before)
    assert delta["compile_requests"] >= 1
    assert delta["traces"] == 0
    assert delta["cache_hits"] == delta["compile_requests"]


def test_warmup_serving_respects_host_pin(monkeypatch):
    """When the calibration pins the host path the serving warmup
    compiles nothing (there is no device program the stream could
    reach)."""
    from oni_ml_tpu.plans import warmup

    out = warmup.warmup_serving(101, 51, 5, 1024, None)
    assert out == {"compiled": 0, "reason": "host path pinned"}
    from oni_ml_tpu.scoring import score as score_mod

    monkeypatch.setattr(score_mod, "_CALIBRATION",
                        {"break_even": 64, "source": "test"})
    out = warmup.warmup_serving(101, 51, 5, 256, 0)
    # pow2 shapes 64, 128, 256
    assert out["compiled"] == 3


# ---------------------------------------------------------------------------
# runner e2e: second run re-sweeps and re-traces nothing; plans on/off
# byte-identical
# ---------------------------------------------------------------------------


def _flow_day_file(tmp_path) -> str:
    import numpy as np

    rng = np.random.default_rng(7)
    lines = []
    for _ in range(60):
        lines.append(flow_row(
            hour=int(rng.integers(0, 24)),
            minute=int(rng.integers(0, 60)),
            second=int(rng.integers(0, 60)),
            sip=f"10.0.0.{rng.integers(1, 9)}",
            dip=f"172.16.0.{rng.integers(1, 9)}",
            col10=str(rng.choice([80, 443, 55000])),
            col11=str(rng.choice([80, 6000])),
            ipkt=str(rng.integers(1, 100)),
            ibyt=str(rng.integers(40, 10000)),
        ))
    raw = tmp_path / "flow.csv"
    raw.write_text("\n".join(lines) + "\n")
    return str(raw)


def _run_day(raw, data_dir, plan_cache, jax_cache, extra=()):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "ONI_ML_TPU_PLAN_CACHE": plan_cache,
        "JAX_COMPILATION_CACHE_DIR": jax_cache,
    })
    cmd = [
        sys.executable, "-m", "oni_ml_tpu.runner.ml_ops",
        "20160122", "flow", "1.1",
        "--flow-path", raw, "--data-dir", str(data_dir),
        "--em-max-iters", "2", "--batch-size", "64",
        "--pre-workers", "1", "--force", *extra,
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, env=env, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    with open(os.path.join(data_dir, "20160122", "metrics.json")) as f:
        return json.load(f)


def test_second_run_zero_sweeps_zero_retraces_and_plans_off_parity(
    tmp_path,
):
    """The acceptance contract, end to end in real processes:

    - run 1 (plans on, cold compile cache) populates the caches;
    - run 2 (same backend+shapes, fresh process) performs ZERO autotune
      sweeps and ZERO re-traces — every XLA compile request is a
      persistent-cache hit, asserted via the runner's plans record;
    - run 3 (--no-plans --no-compilation-cache) produces byte-identical
      word_counts.dat / flow_results.csv to run 2: measured plans are a
      throughput layer, never a semantics layer.
    """
    raw = _flow_day_file(tmp_path)
    plan_cache = str(tmp_path / "plans.jsonl")
    jax_cache = str(tmp_path / "jax_cache")
    d1, d2, d3 = (tmp_path / n for n in ("run1", "run2", "run3"))

    m1 = _run_day(raw, d1, plan_cache, jax_cache)
    rec1 = next(m for m in m1 if m.get("stage") == "plans")
    assert rec1["compilation_cache"]["enabled"]

    m2 = _run_day(raw, d2, plan_cache, jax_cache)
    rec2 = next(m for m in m2 if m.get("stage") == "plans")
    assert rec2["autotune_sweeps"] == 0
    if rec2["compilation_cache"].get("counting"):
        assert rec2["compile_requests"] > 0
        assert rec2["traces"] == 0          # zero re-traces
        assert rec2["cache_hits"] == rec2["compile_requests"]
    # Knob sources are named per stage.
    lda2 = next(m for m in m2 if m.get("stage") == "lda")
    assert lda2["plans"]["fused_em_chunk"]["source"] in (
        "default", "plan"
    )
    pre2 = next(m for m in m2 if m.get("stage") == "pre")
    assert pre2["plans"]["pre_workers"] == {
        "value": 1, "source": "config"
    }

    m3 = _run_day(raw, d3, plan_cache, jax_cache,
                  extra=("--no-plans", "--no-compilation-cache"))
    rec3 = next(m for m in m3 if m.get("stage") == "plans")
    assert rec3["enabled"] is False
    assert rec3["compilation_cache"] == {"enabled": False}

    for name in ("word_counts.dat", "flow_results.csv",
                 "doc_results.csv", "word_results.csv"):
        a = (d2 / "20160122" / name).read_bytes()
        b = (d3 / "20160122" / name).read_bytes()
        assert a == b, f"{name} differs between plans-on and plans-off"


def test_lda_stage_records_plan_sources(tmp_path):
    """In-process: a plan entry for fused_em_chunk is picked up by the
    trainer (source 'plan'), and an explicit config override beats it
    (source 'config') — surfaced through the lda stage record."""
    from oni_ml_tpu.config import LDAConfig, PipelineConfig, PlansConfig
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    raw = _flow_day_file(tmp_path)
    plan_path = str(tmp_path / "plans.jsonl")
    st = PlanStore(plan_path, seeds=False)
    st.record("fused_em_chunk", _fp(), "*", 2, source="probe")
    st.close()

    def run(data_dir, lda):
        cfg = PipelineConfig(
            data_dir=str(data_dir), flow_path=raw, lda=lda,
            pre_workers=1,
            plans=PlansConfig(cache_path=plan_path,
                              compilation_cache=False),
        )
        metrics = run_pipeline(cfg, "20160122", "flow", force=True)
        return next(m for m in metrics if m.get("stage") == "lda")

    lda_rec = run(tmp_path / "p",
                  LDAConfig(em_max_iters=2, batch_size=64))
    assert lda_rec["plans"]["fused_em_chunk"] == {
        "value": 2, "source": "plan"
    }
    lda_rec = run(tmp_path / "c",
                  LDAConfig(em_max_iters=2, batch_size=64,
                            fused_em_chunk=4))
    assert lda_rec["plans"]["fused_em_chunk"] == {
        "value": 4, "source": "config"
    }
    assert lda_rec["plans"]["host_sync_every"]["source"] == "default"
