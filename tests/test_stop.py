"""The shared fixed-point stop rule (oni_ml_tpu/ops/stop.py).

The rule is exercised end-to-end through every engine by the oracle
parity tests; these pin its branch semantics directly so a future tune
cannot silently change one exit without the table below failing.
"""

import numpy as np

from oni_ml_tpu.ops.stop import STALL_GATE, fp_continue


def cont(it, delta, prev, cap=20, tol=1e-6):
    return bool(fp_continue(it, delta, prev, cap, tol))


def test_first_iteration_always_runs():
    # it == 0 short-circuits: even a degenerate inf/inf state runs once.
    assert cont(0, np.inf, np.inf)


def test_cap_stops():
    assert not cont(20, 1.0, 2.0)


def test_var_tol_stops():
    assert not cont(5, 1e-7, 1e-5)
    assert cont(5, 1e-5, 1e-3)


def test_transient_increase_above_gate_continues():
    # Far from the fixed point the delta is not monotone (warm start
    # whose beta moved, saddle escape): a growing delta above the gate
    # must NOT abort the loop.
    assert STALL_GATE <= 2e-2
    assert cont(2, 0.3, 0.1)
    assert cont(2, STALL_GATE, STALL_GATE / 2)


def test_stagnation_below_gate_stops():
    # At the noise floor (below the gate) a non-shrinking delta ends
    # the loop: further iterations only jitter.
    d = STALL_GATE / 4
    assert not cont(5, d, d)          # equal -> stop
    assert not cont(5, d, d * 0.9)    # grew  -> stop
    assert cont(5, d * 0.9, d)        # still shrinking -> continue


def test_oracle_uses_same_constants():
    # reference_lda imports STALL_GATE from the package, so the oracle
    # cannot drift from the engines.
    import inspect

    from tests import reference_lda

    src = inspect.getsource(reference_lda.e_step_doc)
    assert "STALL_GATE" in src
