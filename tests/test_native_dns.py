"""Native (C++) DNS featurizer vs the pure-Python path."""

import pickle

import numpy as np
import pytest

from oni_ml_tpu.features import dns as pydns
from oni_ml_tpu.features import native_dns

from test_features import dns_row

pytestmark = pytest.mark.skipif(
    not native_dns.available(), reason="native dns featurizer unavailable"
)

QNAMES = [
    "www.google.com", "a.b.co.uk", "x.in-addr.arpa", "5.4.3.2.in-addr.arpa",
    "justtld", "two.parts", "deep.sub.domain.example.org", "None.foo.com",
    "dga-x7f3k9q2.evil.biz", "a.b.c.d.e.f.g.h.i.jp", "trailing.dot.net.",
    ".leading.empty.com", "intel", "www.intel.com", "a..b.example.com", "",
]


def make_day(tmp_path, n=400, seed=3):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        rows.append(
            dns_row(
                tstamp=str(int(rng.integers(1, 100000))),
                flen=str(int(rng.integers(40, 1500))),
                ip=f"10.1.{rng.integers(0, 4)}.{rng.integers(1, 60)}",
                qname=QNAMES[rng.integers(0, len(QNAMES))],
                qtype=str(rng.integers(1, 5)),
                rcode=str(rng.integers(0, 3)),
            )
        )
    rows.append(["only", "three", "fields"])     # wrong width -> dropped
    rows.append(dns_row(flen="##"))              # NaN numeric
    path = tmp_path / "dns.csv"
    path.write_text("\n".join(",".join(r) for r in rows) + "\n")
    return path, rows


TOP = frozenset({"google", "intel-ignored", "example"})


def featurize_both(tmp_path, feedback_rows=(), **kw):
    path, rows = make_day(tmp_path, **kw)
    py_rows = [r for r in rows if len(r) == pydns.NUM_DNS_COLUMNS]
    py = pydns.featurize_dns(
        py_rows, top_domains=TOP, feedback_rows=feedback_rows
    )
    nat = native_dns.featurize_dns_sources(
        [str(path)], top_domains=TOP, feedback_rows=feedback_rows
    )
    assert isinstance(nat, native_dns.NativeDnsFeatures)
    return py, nat


def assert_parity(py, nat):
    assert nat.num_events == py.num_events
    assert nat.num_raw_events == py.num_raw_events
    for name in ("time_cuts", "frame_length_cuts", "subdomain_length_cuts",
                 "entropy_cuts", "numperiods_cuts"):
        np.testing.assert_array_equal(getattr(nat, name), getattr(py, name))
    assert nat.domain == py.domain
    assert nat.subdomain == py.subdomain
    np.testing.assert_array_equal(nat.subdomain_length, py.subdomain_length)
    np.testing.assert_array_equal(nat.num_periods, py.num_periods)
    # Entropy must be bit-identical (same summation order, same libm).
    np.testing.assert_array_equal(nat.subdomain_entropy, py.subdomain_entropy)
    np.testing.assert_array_equal(nat.top_domain, py.top_domain)
    assert nat.word == py.word
    assert nat.rows == py.rows
    assert nat.word_counts() == py.word_counts()
    for i in range(0, py.num_events, max(1, py.num_events // 9)):
        assert nat.featurized_row(i) == py.featurized_row(i)
        assert nat.client_ip(i) == py.client_ip(i)


def test_parity_random_day(tmp_path):
    py, nat = featurize_both(tmp_path)
    assert_parity(py, nat)


def test_parity_with_feedback(tmp_path):
    fb = [dns_row(ip="9.9.9.9", qname="fb.example.com")] * 5
    py, nat = featurize_both(tmp_path, feedback_rows=fb)
    assert_parity(py, nat)
    assert nat.num_events == nat.num_raw_events + 5


def test_parquet_style_rows_with_commas(tmp_path):
    # Fields containing commas (frame_time!) survive the \x1f transport,
    # and sources featurize in LISTED order (first-seen id contract).
    path, rows = make_day(tmp_path, n=20)
    extra = [
        ["Mar 10, 2016 10:12:13", "12345", "99", "10.9.9.9",
         "comma.example.com", "1", "1", "0"],
    ]
    py_rows = [r for r in rows if len(r) == 8] + extra
    py = pydns.featurize_dns(py_rows, top_domains=TOP)
    nat = native_dns.featurize_dns_sources(
        [str(path), extra], top_domains=TOP
    )
    assert_parity(py, nat)
    assert nat.row(nat.num_events - 1)[0] == "Mar 10, 2016 10:12:13"


def test_pickle_roundtrip(tmp_path):
    _, nat = featurize_both(tmp_path, n=30)
    again = pickle.loads(pickle.dumps(nat))
    assert again.word_counts() == nat.word_counts()
    assert again.featurized_row(2) == nat.featurized_row(2)


def test_scoring_identical(tmp_path):
    from oni_ml_tpu.scoring import ScoringModel, score_dns

    py, nat = featurize_both(tmp_path)
    k = 4
    rng = np.random.default_rng(0)
    ips = sorted({ip for ip, _, _ in py.word_counts()})
    words = sorted({w for _, w, _ in py.word_counts()})
    model = ScoringModel.from_results(
        doc_names=ips,
        doc_topic=rng.dirichlet(np.ones(k), size=len(ips)),
        vocab=words,
        word_topic=rng.dirichlet(np.ones(k), size=len(words)),
        fallback=0.1,
    )
    rows_py, s_py = score_dns(py, model, threshold=1.1)
    rows_nat, s_nat = score_dns(nat, model, threshold=1.1)
    assert rows_py == rows_nat
    np.testing.assert_array_equal(s_py, s_nat)


def test_directory_path_errors(tmp_path):
    with pytest.raises(OSError):
        native_dns.featurize_dns_sources([str(tmp_path)])


def test_rows_with_transport_bytes_fall_back(tmp_path):
    # Fields embedding '\n' or '\x1f' can't ride the native blob; the
    # whole run must take the Python path, not silently drop events.
    weird = [["t", "1454000000", "60", "10.9.9.1",
              "evil\nname.example.com", "1", "1", "0"]]
    feats = native_dns.featurize_dns_sources([weird])
    assert isinstance(feats, pydns.DnsFeatures)
    assert feats.num_events == 1
    assert feats.rows[0][4] == "evil\nname.example.com"


def test_rows_with_carriage_return_fall_back():
    # Native ingest's CRLF handling strips a field-final '\r' from the
    # blob; such rows must route through the Python path unaltered.
    weird = [["t", "1454000000", "60", "10.9.9.1",
              "evil\rname.example.com\r", "1", "1", "0"]]
    feats = native_dns.featurize_dns_sources([weird])
    assert isinstance(feats, pydns.DnsFeatures)
    assert feats.num_events == 1
    assert feats.rows[0][4] == "evil\rname.example.com\r"


def test_csv_with_embedded_carriage_return_parity(tmp_path):
    # An embedded lone '\r' in a CSV field is a legal hostile-qname byte.
    # Both engines must keep the row intact with the '\r' in the field —
    # universal-newline reading would split it into dropped fragments.
    qname = "evil\rname.example.com"
    line1 = f"t,1454000000,60,10.9.9.1,{qname},1,1,0"
    line2 = "t,1454000060,70,10.9.9.2,ok.example.com,1,1,0"
    path = tmp_path / "dns.csv"
    path.write_bytes((line1 + "\n" + line2 + "\r\n").encode())
    nat = native_dns.featurize_dns_sources([str(path)], top_domains=TOP)
    assert isinstance(nat, native_dns.NativeDnsFeatures)
    assert nat.num_events == 2
    assert nat.rows[0][4] == qname
    assert nat.rows[1][4] == "ok.example.com"  # CRLF tail stripped

    # Python fallback (native unavailable) reads identically.
    orig = native_dns._LIB.load
    native_dns._LIB.load = lambda: None
    try:
        py = native_dns.featurize_dns_sources([str(path)], top_domains=TOP)
    finally:
        native_dns._LIB.load = orig
    assert isinstance(py, pydns.DnsFeatures)
    assert py.rows == nat.rows
    assert py.word == nat.word


def test_csv_with_separator_byte_falls_back(tmp_path):
    # A CSV field embedding the '\x1f' transport separator would split
    # into extra columns when the native rows blob is re-split; ingest
    # flags it and the whole run re-runs through the Python path.
    qname = "evil\x1fname.example.com"
    rows = [
        ["t", "1454000000", "60", "10.9.9.1", qname, "1", "1", "0"],
        ["t", "1454000060", "70", "10.9.9.2", "ok.example.com", "1", "1",
         "0"],
    ]
    path = tmp_path / "dns.csv"
    path.write_text("\n".join(",".join(r) for r in rows) + "\n")
    feats = native_dns.featurize_dns_sources([str(path)], top_domains=TOP)
    assert isinstance(feats, pydns.DnsFeatures)
    assert feats.num_events == 2
    assert feats.rows[0][4] == qname
    # The clean-file path still takes the native engine.
    clean = tmp_path / "clean.csv"
    clean.write_text(",".join(rows[1]) + "\n")
    feats2 = native_dns.featurize_dns_sources([str(clean)], top_domains=TOP)
    assert isinstance(feats2, native_dns.NativeDnsFeatures)


def test_duplicate_in_memory_sources_ingest_twice():
    """The same row-list object passed as two sources (or as source and
    feedback) must ingest once per occurrence — a regression guard for
    blob bookkeeping keyed on object identity."""
    rows = [
        ["t", str(1454000000 + i), "100", f"10.0.0.{i % 5}",
         f"s{i}.example.com", "1", "1", "0"]
        for i in range(20)
    ]
    feats = native_dns.featurize_dns_sources([rows, rows])
    assert feats.num_raw_events == 40
    fb = native_dns.featurize_dns_sources([rows], feedback_rows=rows)
    assert fb.num_raw_events == 20          # feedback rows are not raw
    assert fb.num_events == 40


def test_spill_parity_and_scoring(tmp_path):
    """spill_path streams the rows blob to disk at ingest: identical
    surface (rows/featurized_row/word_counts), native emit reads rows
    through the mmap bit-identically, and the pickle references the
    path instead of embedding the bytes."""
    import pickle as pkl

    from oni_ml_tpu.features.blob import MmapBlob
    from oni_ml_tpu.scoring import ScoringModel, score_dns_csv

    path, _ = make_day(tmp_path)
    fb = [dns_row(ip="9.9.9.9")] * 4
    nat = native_dns.featurize_dns_sources(
        [str(path)], top_domains=TOP, feedback_rows=fb
    )
    spill = native_dns.featurize_dns_sources(
        [str(path)], top_domains=TOP, feedback_rows=fb,
        spill_path=str(tmp_path / "rows.bin"),
    )
    assert isinstance(spill.rows_blob, MmapBlob)
    assert len(spill.rows_blob) == len(nat.rows_blob)
    assert spill.rows == nat.rows
    assert spill.word_counts() == nat.word_counts()
    assert spill.num_raw_events == nat.num_raw_events

    probe = bytes(nat.rows_blob[:64])
    assert probe not in pkl.dumps(spill)
    again = pkl.loads(pkl.dumps(spill))
    assert again.rows == nat.rows

    k = 4
    rng = np.random.default_rng(0)
    ips = sorted({ip for ip, _, _ in nat.word_counts()})
    words = sorted({w for _, w, _ in nat.word_counts()})
    model = ScoringModel.from_results(
        doc_names=ips,
        doc_topic=rng.dirichlet(np.ones(k), size=len(ips)),
        vocab=words,
        word_topic=rng.dirichlet(np.ones(k), size=len(words)),
        fallback=0.1,
    )
    blob_nat, s_nat = score_dns_csv(nat, model, threshold=1.1)
    blob_spill, s_spill = score_dns_csv(spill, model, threshold=1.1)
    assert blob_nat == blob_spill
    np.testing.assert_array_equal(s_nat, s_spill)


def test_spill_with_mixed_inmemory_sources(tmp_path):
    """Pre-projected (parquet-style) row sources spill too; a run that
    falls back over transport bytes leaves the partial spill file
    unreferenced and returns the Python container."""
    path, _ = make_day(tmp_path, n=50)
    mem_rows = [dns_row(ip="10.9.0.1", qname="a.b.example.com")] * 5
    spill = native_dns.featurize_dns_sources(
        [mem_rows, str(path)], top_domains=TOP,
        spill_path=str(tmp_path / "rows.bin"),
    )
    nat = native_dns.featurize_dns_sources(
        [mem_rows, str(path)], top_domains=TOP
    )
    assert spill.rows == nat.rows

    bad = [dns_row(ip="10.9.0.2", qname="evil\x1fname.com")] * 2
    fell_back = native_dns.featurize_dns_sources(
        [bad, str(path)], top_domains=TOP,
        spill_path=str(tmp_path / "rows2.bin"),
    )
    assert not isinstance(fell_back, native_dns.NativeDnsFeatures)
