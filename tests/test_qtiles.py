"""Precomputed quantile cuts side-tool (SURVEY §2.7): file format
roundtrip, exactness vs the in-run ECDF, and runner integration."""

import numpy as np

from oni_ml_tpu.features import featurize_flow
from oni_ml_tpu.features.qtiles import (
    compute_flow_qtiles,
    main as qtiles_main,
    read_flow_qtiles,
    write_flow_qtiles,
)

from test_features import flow_row


def _day_lines(n=50, seed=3):
    rng = np.random.default_rng(seed)
    lines = ["header,line"]
    for _ in range(n):
        lines.append(
            flow_row(
                hour=int(rng.integers(0, 24)),
                minute=int(rng.integers(0, 60)),
                second=int(rng.integers(0, 60)),
                sip=f"10.0.0.{rng.integers(1, 9)}",
                dip=f"172.16.0.{rng.integers(1, 9)}",
                col10=str(rng.choice([80, 443, 55000])),
                col11=str(rng.choice([80, 6000, 70000])),
                ipkt=str(rng.integers(1, 100)),
                ibyt=str(rng.integers(40, 10000)),
            )
        )
    return lines


def test_roundtrip(tmp_path):
    t = np.array([0.0, 1.5, 2.25, 7.416666666666667])
    b = np.array([0.0, 52.0, 3569.0])
    p = np.array([0.0, 1.0, 14.0])
    path = str(tmp_path / "flow_qtiles")
    write_flow_qtiles(path, t, b, p)
    t2, b2, p2 = read_flow_qtiles(path)
    np.testing.assert_array_equal(t, t2)
    np.testing.assert_array_equal(b, b2)
    np.testing.assert_array_equal(p, p2)


def test_parses_reference_shaped_line(tmp_path):
    # Same shape as the reference's checked-in flow_qtiles: three
    # space-separated lists (ibyt, ipkt, time) joined by commas.
    line = "0 52 76 104,0 1 1 1 1 2,0 2.3 4.783333333333333\n"
    path = tmp_path / "q"
    path.write_text(line)
    time, ibyt, ipkt = read_flow_qtiles(str(path))
    assert ibyt.tolist() == [0, 52, 76, 104]
    assert ipkt.tolist() == [0, 1, 1, 1, 1, 2]
    assert time[1] == 2.3


def test_precomputed_cuts_reproduce_inline_words():
    lines = _day_lines()
    inline = featurize_flow(lines)
    cuts = compute_flow_qtiles(lines)
    np.testing.assert_array_equal(cuts[0], inline.time_cuts)
    np.testing.assert_array_equal(cuts[1], inline.ibyt_cuts)
    np.testing.assert_array_equal(cuts[2], inline.ipkt_cuts)
    pre = featurize_flow(lines, precomputed_cuts=cuts)
    assert pre.src_word == inline.src_word
    assert pre.dest_word == inline.dest_word


def test_cli_and_runner_integration(tmp_path):
    lines = _day_lines()
    raw = tmp_path / "flow.csv"
    raw.write_text("\n".join(lines) + "\n")
    qfile = str(tmp_path / "flow_qtiles")
    assert qtiles_main([str(raw), qfile]) == 0

    from oni_ml_tpu.config import LDAConfig, PipelineConfig, ScoringConfig
    from oni_ml_tpu.runner import run_pipeline

    base = dict(
        data_dir=str(tmp_path),
        flow_path=str(raw),
        lda=LDAConfig(num_topics=3, em_max_iters=3, batch_size=32,
                      min_bucket_len=16, seed=1),
        scoring=ScoringConfig(threshold=1.1),
    )
    run_pipeline(PipelineConfig(**base), "20160122", "flow")
    run_pipeline(PipelineConfig(**base, qtiles_path=qfile), "20160123", "flow")
    wc1 = (tmp_path / "20160122" / "word_counts.dat").read_text()
    wc2 = (tmp_path / "20160123" / "word_counts.dat").read_text()
    assert wc1 == wc2  # cuts from the same day -> identical corpus
