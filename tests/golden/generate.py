"""Golden-day fixture generator (VERDICT r1 item 6).

Writes the committed inputs (a tiny synthetic flow day, DNS day, and
whitelist) and the expected outputs for every stage-boundary file
contract (SURVEY.md §1: the layer interfaces ARE files).  The test
(tests/test_golden.py) recomputes the outputs from the committed inputs
and compares BYTES — any drift in featurization, corpus id assignment,
result formatting, or scoring emit fails loudly.

Run only to intentionally re-pin the contract after a deliberate
format change:  python tests/golden/generate.py
Then review the git diff of tests/golden/expected/ like any contract
change.

Training is deliberately NOT part of the fixture: float EM results vary
across backends, so the model (final.beta/final.gamma) is a committed
pseudo-random input, which also pins the beta/gamma file formats.
"""

import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, "..", ".."))

from oni_ml_tpu.features import featurize_flow_file
from oni_ml_tpu.features.native_dns import featurize_dns_sources
from oni_ml_tpu.io import Corpus, formats
from oni_ml_tpu.scoring import ScoringModel, score_dns, score_flow

# Single source of truth for the fixture's scoring knobs: the test
# (tests/test_golden.py) imports these, so re-pinning with a changed
# threshold cannot desync generator and test.
FLOW_TOL = 0.005    # ~30th pct of the day: keeps a strict subset
DNS_TOL = 0.015
FLOW_FALLBACK = 0.05   # reference's unseen-IP rows (SURVEY §2.6)
DNS_FALLBACK = 0.1
K = 5


def write_inputs() -> None:
    rng = np.random.default_rng(1234)
    inp = os.path.join(HERE, "inputs")

    # -- flow day: 60 rows + header + edge cases ------------------------
    lines = ["tstart,year,month,day,hour,min,sec,tdur,sip,dip,sport,dport,"
             "proto,flag,fwd,stos,ipkt,ibyt,opkt,obyt,in,out,sas,das,dtos,"
             "dir,rip"]
    for i in range(60):
        c = ["x"] * 27
        c[1], c[2], c[3] = "2016", "1", "22"
        c[4] = str(int(rng.integers(0, 24)))
        c[5] = str(int(rng.integers(0, 60)))
        c[6] = str(int(rng.integers(0, 60)))
        c[8] = f"10.0.0.{int(rng.integers(1, 9))}"
        c[9] = f"192.168.1.{int(rng.integers(1, 7))}"
        # port mix hits every adjust_port case incl. str(float) edges
        c[10] = ["80", "443", "39999", "0", "1e15"][i % 5]
        c[11] = ["52100", "1024", "45000", "7777", "0.0001"][(i // 5) % 5]
        c[16] = str(int(rng.integers(1, 200)))
        c[17] = str(int(rng.integers(40, 9000)))
        lines.append(",".join(c))
    lines.append(",".join(["##"] * 27))          # garbage row (NaN numerics)
    lines.append("only,three,fields")            # wrong width -> dropped
    body = "\r\n".join(lines[:30]) + "\r\n" + "\n".join(lines[30:]) + "\n"
    with open(os.path.join(inp, "flow.csv"), "w", newline="") as f:
        f.write(body)

    # -- dns day: 40 rows + edge-case names -----------------------------
    qnames = [
        "www.google.com", "a.b.co.uk", "5.4.3.2.in-addr.arpa", "intel",
        "www.intel.com", "dga-x7f3k9q2.evil.biz", "deep.sub.example.org",
        "justtld", "two.parts", "trailing.dot.net.", "a..b.example.com",
    ]
    rows = []
    for i in range(40):
        rows.append(",".join([
            "frame", str(1454000000 + int(rng.integers(0, 86400))),
            str(int(rng.integers(40, 1500))),
            f"172.16.0.{int(rng.integers(1, 9))}",
            qnames[i % len(qnames)],
            "1", str(int(rng.integers(1, 17))), str(int(rng.integers(0, 4))),
        ]))
    with open(os.path.join(inp, "dns.csv"), "w", newline="") as f:
        f.write("\n".join(rows) + "\n")

    with open(os.path.join(inp, "top1m.csv"), "w") as f:
        f.write("1,google.com\n2,example.org\n3,intel.com\n")


def pinned_model(num_docs: int, vocab_size: int, seed: int):
    """Deterministic pseudo-random LDA posterior standing in for a
    trained model (training floats vary across backends)."""
    rng = np.random.default_rng(seed)
    gamma = rng.gamma(2.0, 1.0, (num_docs, K)) + 0.01
    beta = rng.dirichlet(np.ones(vocab_size) * 0.5, size=K)
    return gamma, np.log(np.maximum(beta, 1e-300))


def load_flow_feats():
    return featurize_flow_file(os.path.join(HERE, "inputs", "flow.csv"))


def load_dns_feats():
    from oni_ml_tpu.features import load_top_domains

    top = load_top_domains(os.path.join(HERE, "inputs", "top1m.csv"))
    return featurize_dns_sources(
        [os.path.join(HERE, "inputs", "dns.csv")], top_domains=top
    )


def generate(sub: str) -> None:
    """One pin recipe for both dsources: featurize -> corpus files ->
    pinned model -> (text-roundtripped) result CSVs -> scored output."""
    feats, seed, fallback, tol, score = {
        "flow": (load_flow_feats(), 77, FLOW_FALLBACK, FLOW_TOL, score_flow),
        "dns": (load_dns_feats(), 99, DNS_FALLBACK, DNS_TOL, score_dns),
    }[sub]
    exp = os.path.join(HERE, "expected", sub)
    formats.write_word_counts(
        os.path.join(exp, "word_counts.dat"), feats.word_counts()
    )
    corpus = Corpus.from_word_counts_file(os.path.join(exp, "word_counts.dat"))
    corpus.save(exp)                       # words.dat, doc.dat, model.dat

    gamma, log_beta = pinned_model(corpus.num_docs, corpus.num_terms, seed)
    formats.write_gamma(os.path.join(exp, "final.gamma"), gamma)
    formats.write_beta(os.path.join(exp, "final.beta"), log_beta)
    # downstream files derive from the COMMITTED (text-roundtripped)
    # model, exactly as the test recomputes them
    gamma = formats.read_gamma(os.path.join(exp, "final.gamma"))
    log_beta = formats.read_beta(os.path.join(exp, "final.beta"))
    norm = gamma / gamma.sum(-1, keepdims=True)
    formats.write_doc_results(
        os.path.join(exp, "doc_results.csv"), corpus.doc_names, norm
    )
    formats.write_word_results(
        os.path.join(exp, "word_results.csv"), corpus.vocab, log_beta
    )
    model = ScoringModel.from_files(
        os.path.join(exp, "doc_results.csv"),
        os.path.join(exp, "word_results.csv"),
        fallback=fallback,
    )
    rows, _ = score(feats, model, threshold=tol)
    with open(os.path.join(exp, f"{sub}_results.csv"), "w") as f:
        f.write("\n".join(rows) + ("\n" if rows else ""))


if __name__ == "__main__":
    write_inputs()
    generate("flow")
    generate("dns")
    print("golden fixture regenerated under", HERE)
