"""tools/glue_amortization.py mechanics at toy shapes.

The tool's value is the measured table in docs/architecture.md (full
shapes, quiet machine); here we pin that the harness runs the
production chunk runner over the sharded E-step on the virtual mesh
and produces a well-formed record.
"""

import math
import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)

import glue_amortization


def test_measure_structure_at_toy_shapes():
    rec = glue_amortization.measure(
        k=4, v=256, b=32, l=16, n_batches=(1, 2), chunk=2,
        var_max_iters=3, rounds=1,
    )
    assert rec["metric"] == "glue_amortization_cpu_mesh"
    assert [r["n_batches"] for r in rec["rows"]] == [1, 2]
    for r in rec["rows"]:
        assert r["t_iter_ms"] > 0
        assert math.isclose(r["t_iter_per_batch_ms"],
                            r["t_iter_ms"] / r["n_batches"], rel_tol=0.02)
    assert math.isfinite(rec["fit_glue_ms"])
    assert math.isfinite(rec["fit_per_batch_ms"])
