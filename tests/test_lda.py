"""LDA engine validation: math invariants + agreement with the slow NumPy
oracle (tests/reference_lda.py) on small synthetic corpora."""

import numpy as np
import jax.numpy as jnp
import pytest

from oni_ml_tpu.config import LDAConfig
from oni_ml_tpu.io import Corpus, make_batches
from oni_ml_tpu.models import LDATrainer, train_corpus
from oni_ml_tpu.models.lda import init_log_beta, update_alpha
from oni_ml_tpu.ops import estep

import reference_lda as ref


def corpus_from_docs(docs, num_terms):
    """Identity-vocab corpus so ids line up with the NumPy oracle."""
    ptr = [0]
    widx, cnts = [], []
    for words, counts in docs:
        widx.extend(words.tolist())
        cnts.extend(counts.tolist())
        ptr.append(len(widx))
    return Corpus(
        doc_names=[f"ip{d}" for d in range(len(docs))],
        vocab=[f"w{i}" for i in range(num_terms)],
        doc_ptr=np.asarray(ptr, np.int64),
        word_idx=np.asarray(widx, np.int32),
        counts=np.asarray(cnts, np.int32),
    )


@pytest.fixture(scope="module")
def small_problem():
    docs, _ = ref.make_synthetic_corpus(num_docs=24, num_terms=30, num_topics=3,
                                        seed=7)
    V, K = 30, 4
    rng = np.random.default_rng(3)
    noise = rng.uniform(size=(K, V)) + 1.0 / V
    log_beta = np.log(noise / noise.sum(-1, keepdims=True))
    return docs, V, K, log_beta


def test_e_step_matches_numpy(small_problem):
    docs, V, K, log_beta = small_problem
    alpha = 2.5
    corpus = corpus_from_docs(docs, V)
    batches = make_batches(corpus, batch_size=32, min_bucket_len=64)
    assert len(batches) == 1
    b = batches[0]
    res = estep.e_step(
        jnp.asarray(log_beta, jnp.float32),
        jnp.float32(alpha),
        jnp.asarray(b.word_idx),
        jnp.asarray(b.counts),
        jnp.asarray(b.doc_mask),
        var_max_iters=50,
        var_tol=1e-8,
    )
    # oracle, doc by doc
    ss_ref = np.zeros((K, V))
    ll_ref = 0.0
    gamma_ref = np.zeros((len(docs), K))
    for d, (w, c) in enumerate(docs):
        g, phi, ll = ref.e_step_doc(log_beta, alpha, w, c.astype(np.float64),
                                    var_max_iters=50, var_tol=1e-8)
        gamma_ref[d] = g
        ll_ref += ll
        np.add.at(ss_ref.T, w, phi * c[:, None])

    gamma = np.asarray(res.gamma)[np.asarray(b.doc_mask) == 1]
    order = np.argsort(b.doc_index[b.doc_mask == 1])
    np.testing.assert_allclose(gamma[order], gamma_ref, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(res.suff_stats).T, ss_ref,
                               rtol=5e-3, atol=5e-3)
    np.testing.assert_allclose(float(res.likelihood), ll_ref, rtol=1e-4)


def test_em_matches_numpy(small_problem):
    docs, V, K, log_beta0 = small_problem
    corpus = corpus_from_docs(docs, V)
    cfg = LDAConfig(num_topics=K, alpha_init=2.5, estimate_alpha=False,
                    em_max_iters=6, em_tol=0.0, var_max_iters=30, var_tol=1e-7,
                    batch_size=32, min_bucket_len=64)
    batches = make_batches(corpus, cfg.batch_size, cfg.min_bucket_len)

    trainer = LDATrainer(cfg, num_terms=V)
    result = trainer.fit(batches, corpus.num_docs, initial_log_beta=log_beta0)

    oracle = ref.em(docs, V, K, alpha=2.5, em_max_iters=6, em_tol=0.0,
                    var_max_iters=30, var_tol=1e-7, init_log_beta=log_beta0)
    # likelihood trajectories agree
    np.testing.assert_allclose(
        [l for l, _ in result.likelihoods], oracle["likelihoods"], rtol=1e-3)
    # final topics agree (compare in probability space; log space is
    # dominated by the -100 floor of untouched words)
    np.testing.assert_allclose(
        np.exp(result.log_beta), np.exp(oracle["log_beta"]), atol=2e-3)
    np.testing.assert_allclose(result.gamma, oracle["gamma"], rtol=5e-3,
                               atol=5e-3)


def test_invariants(small_problem):
    docs, V, K, _ = small_problem
    corpus = corpus_from_docs(docs, V)
    cfg = LDAConfig(num_topics=K, em_max_iters=12, em_tol=0.0,
                    batch_size=16, min_bucket_len=16, seed=5)
    result = train_corpus(corpus, cfg)
    # rows of exp(beta) sum to 1
    np.testing.assert_allclose(np.exp(result.log_beta).sum(-1), np.ones(K),
                               rtol=1e-4)
    # gamma strictly positive, every doc row written
    assert (result.gamma > 0).all()
    # likelihood non-decreasing (tiny f32 wiggle allowed)
    lls = np.array([l for l, _ in result.likelihoods])
    assert (np.diff(lls) > -abs(lls[0]) * 1e-5).all(), lls
    # alpha stayed positive and finite under Newton updates
    assert np.isfinite(result.alpha) and result.alpha > 0


def test_alpha_newton_finds_maximum():
    # compare against brute-force maximization of the objective
    from scipy.special import gammaln as g
    D, K = 100, 20
    rng = np.random.default_rng(0)
    # plausible ss: sum over docs of sum_k E[log theta].  Must satisfy
    # ss < -D*K*log(K) (~ -5991 here) for a finite maximizer to exist;
    # a symmetric-Dirichlet corpus gives ss ~ D*K*(digamma(a)-digamma(Ka)).
    ss = -float(rng.uniform(6500, 9000))

    def obj(a):
        return D * (g(K * a) - K * g(a)) + a * ss

    grid = np.linspace(0.01, 10, 20000)
    best = grid[np.argmax([obj(a) for a in grid])]
    a_hat = float(update_alpha(jnp.float32(ss), jnp.float32(1.0), D, K))
    assert abs(a_hat - best) < 5e-3, (a_hat, best)


def test_train_corpus_writes_reference_files(tmp_path, small_problem):
    docs, V, K, _ = small_problem
    corpus = corpus_from_docs(docs, V)
    cfg = LDAConfig(num_topics=K, em_max_iters=3, em_tol=0.0, batch_size=16,
                    min_bucket_len=16)
    result = train_corpus(corpus, cfg, out_dir=str(tmp_path))
    from oni_ml_tpu.io import formats
    lb = formats.read_beta(str(tmp_path / "final.beta"))
    gm = formats.read_gamma(str(tmp_path / "final.gamma"))
    other = formats.read_other(str(tmp_path / "final.other"))
    ll = formats.read_likelihood(str(tmp_path / "likelihood.dat"))
    assert lb.shape == (K, V) and gm.shape == (corpus.num_docs, K)
    assert other["num_topics"] == K and other["num_terms"] == V
    assert ll.shape == (3, 2)
    np.testing.assert_allclose(lb, result.log_beta, atol=1e-9)


def test_alpha_max_iters_knob():
    """LDAConfig.alpha_max_iters bounds the alpha-Newton loop: a cap of
    1 from a far-off init must land elsewhere than the full 100-trip
    optimization, while the default exactly reproduces lda-c's cap."""
    # ss chosen so the optimum is interior (~1.0 at d=100, k=20:
    # df = d*k*(digamma(20a) - digamma(a)) + ss = 0 around a=1).
    ss = jnp.float32(-7094.0)
    far = jnp.float32(50.0)
    full = float(update_alpha(ss, far, 100, 20))
    one = float(update_alpha(ss, far, 100, 20, max_iters=1))
    default = float(update_alpha(ss, far, 100, 20, max_iters=100))
    assert default == full
    assert 0.5 < full < 2.0         # interior optimum reached
    assert one != full              # the cap genuinely binds
    # Warm start: re-optimizing FROM the optimum converges immediately,
    # so even a tiny cap reproduces it — the premise behind lowering
    # the cap mid-EM.
    warm = float(update_alpha(ss, jnp.float32(full), 100, 20, max_iters=2))
    assert abs(warm - full) < 1e-4 * abs(full)


def test_alpha_unrolled_matches_while_loop_lowering():
    """max_iters <= 16 takes the unrolled convergence-masked lowering
    (r05: the dynamic-trip scalar while_loop charged ~0.5 ms/EM-iter
    on chip).  The mask must replicate the while_loop exit exactly, so
    for every cap the two lowerings agree to float tolerance, across
    far-off, moderate, and already-converged inits."""
    from oni_ml_tpu.models import lda as lda_mod

    ss_vals = [-7094.0, -6600.0, -8800.0]
    inits = [0.1, 1.0, 2.5, 50.0]
    for ss_v in ss_vals:
        ss = jnp.float32(ss_v)
        for a0 in inits:
            init = jnp.float32(a0)
            for cap in (1, 2, 8, 16):
                unrolled = float(update_alpha(ss, init, 100, 20,
                                              max_iters=cap))
                # Summon the while_loop lowering at the same cap by
                # calling above the unroll threshold ceiling: wrap via
                # a cap>16 equivalent is impossible for cap<=16, so
                # re-derive it with the module's while_loop directly.
                def body(state):
                    log_a, _, it = state
                    a, df, d2f = lda_mod._alpha_objective_grads(
                        log_a, ss, 100, 20)
                    return (log_a - df / (d2f * a + df), jnp.abs(df),
                            it + 1)

                def cond(state):
                    log_a, df_abs, it = state
                    return jnp.logical_and(it < cap, df_abs > 1e-5)

                import jax

                log_a, _, _ = jax.lax.while_loop(
                    cond, body,
                    (jnp.log(init), jnp.asarray(jnp.inf, jnp.float32),
                     jnp.asarray(0, jnp.int32)),
                )
                a = float(jnp.exp(log_a))
                ref = a if np.isfinite(a) and a > 0 else float(init)
                assert abs(unrolled - ref) <= 1e-5 * max(1.0, abs(ref)), (
                    ss_v, a0, cap, unrolled, ref)


def test_alpha_cap8_training_equivalent_to_cap100(small_problem):
    """bench passes alpha_max_iters=8 (the unrolled lowering); a full
    training run at cap=8 must reach the same optimum as lda-c's
    cap=100 — warm per-EM-iteration Newton converges in <8 trips, so
    the same |df| exit fires on both paths."""
    docs, V, K, _ = small_problem
    corpus = corpus_from_docs(docs, V)

    def run(cap):
        cfg = LDAConfig(num_topics=K, em_max_iters=40, em_tol=1e-4,
                        batch_size=16, min_bucket_len=16,
                        alpha_max_iters=cap, seed=0)
        return train_corpus(corpus, cfg)

    r8, r100 = run(8), run(100)
    assert abs(r8.alpha - r100.alpha) <= 1e-3 * abs(r100.alpha)
    ll8 = float(r8.likelihoods[-1][0])
    ll100 = float(r100.likelihoods[-1][0])
    assert abs(ll8 - ll100) <= 1e-5 * abs(ll100)
