"""Golden-fixture tests for every stage-boundary file contract (SURVEY §1).

The reference has zero tests; these pin the formats its stages exchange:
word_counts triples, words.dat/doc.dat/model.dat (lda_pre.py), final.*
(lda-c outputs), doc_results.csv/word_results.csv (lda_post.py).
"""

import numpy as np
import pytest

from oni_ml_tpu.io import Corpus, formats, make_batches

TRIPLES = [
    # first-seen order fixture: words w0..w3, docs ip1..ip3
    ("10.0.0.1", "80.0_1.0_2.0_3.0", 5),
    ("10.0.0.1", "333333.0_0.0_1.0_1.0", 2),
    ("10.0.0.2", "80.0_1.0_2.0_3.0", 1),
    ("10.0.0.2", "-1_443.0_5.0_5.0_2.0", 7),
    ("10.0.0.3", "80.0_1.0_2.0_3.0", 3),
    ("10.0.0.1", "53.0_9.0_9.0_4.0", 1),
]


def test_word_counts_roundtrip(tmp_path):
    p = str(tmp_path / "doc_wc.dat")
    formats.write_word_counts(p, TRIPLES)
    assert list(formats.read_word_counts(p)) == TRIPLES


def test_corpus_first_seen_order():
    c = Corpus.from_word_counts(TRIPLES)
    # words in first-seen order (lda_pre.py:38-41)
    assert c.vocab == [
        "80.0_1.0_2.0_3.0",
        "333333.0_0.0_1.0_1.0",
        "-1_443.0_5.0_5.0_2.0",
        "53.0_9.0_9.0_4.0",
    ]
    # docs in first-seen order (lda_pre.py:66-73)
    assert c.doc_names == ["10.0.0.1", "10.0.0.2", "10.0.0.3"]
    assert c.num_docs == 3 and c.num_terms == 4
    # doc 0 holds tokens (0,5) (1,2) (3,1) — appended across the stream
    lo, hi = c.doc_ptr[0], c.doc_ptr[1]
    assert list(c.word_idx[lo:hi]) == [0, 1, 3]
    assert list(c.counts[lo:hi]) == [5, 2, 1]
    assert c.num_tokens == 19


def test_model_dat_golden(tmp_path):
    c = Corpus.from_word_counts(TRIPLES)
    c.save(str(tmp_path))
    # exact LDA-C lines (lda_pre.py:84-94 writes "<N> w:c w:c ...")
    assert (tmp_path / "model.dat").read_text() == (
        "3 0:5 1:2 3:1\n" "2 0:1 2:7\n" "1 0:3\n"
    )
    assert (tmp_path / "words.dat").read_text().splitlines()[0] == "0,80.0_1.0_2.0_3.0"
    # doc.dat is 1-based (lda_pre.py:60)
    assert (tmp_path / "doc.dat").read_text().splitlines() == [
        "1,10.0.0.1",
        "2,10.0.0.2",
        "3,10.0.0.3",
    ]


def test_model_dat_roundtrip(tmp_path):
    c = Corpus.from_word_counts(TRIPLES)
    c.save(str(tmp_path))
    c2 = Corpus.from_model_dat(
        str(tmp_path / "model.dat"),
        str(tmp_path / "words.dat"),
        str(tmp_path / "doc.dat"),
    )
    assert c2.vocab == c.vocab
    assert c2.doc_names == c.doc_names
    np.testing.assert_array_equal(c2.doc_ptr, c.doc_ptr)
    np.testing.assert_array_equal(c2.word_idx, c.word_idx)
    np.testing.assert_array_equal(c2.counts, c.counts)


def test_beta_gamma_other_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    log_beta = np.log(rng.dirichlet(np.ones(7), size=3))  # K=3, V=7
    gamma = rng.gamma(2.0, 1.0, size=(5, 3))
    formats.write_beta(str(tmp_path / "final.beta"), log_beta)
    formats.write_gamma(str(tmp_path / "final.gamma"), gamma)
    formats.write_other(str(tmp_path / "final.other"), 3, 7, 2.5)
    np.testing.assert_allclose(
        formats.read_beta(str(tmp_path / "final.beta")), log_beta, atol=1e-9
    )
    np.testing.assert_allclose(
        formats.read_gamma(str(tmp_path / "final.gamma")), gamma, atol=1e-9
    )
    other = formats.read_other(str(tmp_path / "final.other"))
    assert other == {"num_topics": 3, "num_terms": 7, "alpha": 2.5}


def test_likelihood_dat(tmp_path):
    p = str(tmp_path / "likelihood.dat")
    with open(p, "w") as f:
        formats.append_likelihood(f, -1234.5678, 1.0)
        formats.append_likelihood(f, -1200.0, 0.028)
    arr = formats.read_likelihood(p)
    assert arr.shape == (2, 2)
    np.testing.assert_allclose(arr[:, 0], [-1234.5678, -1200.0])


def test_doc_results_contract(tmp_path):
    # normalized rows + the literal all-zeros row (lda_post.py:48-56)
    gamma = np.array([[2.0, 2.0], [0.0, 0.0], [3.0, 1.0]])
    p = str(tmp_path / "doc_results.csv")
    formats.write_doc_results(p, ["a", "b", "c"], gamma)
    lines = open(p).read().splitlines()
    assert lines[0] == "a,0.5 0.5"
    assert lines[1] == "b,0.0 0.0"
    assert lines[2] == "c,0.75 0.25"
    names, arr = formats.read_doc_results(p)
    assert names == ["a", "b", "c"]
    np.testing.assert_allclose(arr, [[0.5, 0.5], [0.0, 0.0], [0.75, 0.25]])


def test_word_results_contract(tmp_path):
    # per-topic exp+normalize then transpose to V x K (lda_post.py:87-96)
    log_beta = np.log(
        np.array([[0.5, 0.25, 0.25], [0.2, 0.2, 0.6]])
    )  # K=2, V=3, rows already normalized
    p = str(tmp_path / "word_results.csv")
    formats.write_word_results(p, ["w0", "w1", "w2"], log_beta)
    words, arr = formats.read_word_results(p)
    assert words == ["w0", "w1", "w2"]
    assert arr.shape == (3, 2)
    np.testing.assert_allclose(arr, [[0.5, 0.2], [0.25, 0.2], [0.25, 0.6]], atol=1e-12)


def test_make_batches_covers_all_docs():
    rng = np.random.default_rng(1)
    triples = []
    for d in range(37):
        n = int(rng.integers(1, 60))
        for w in rng.choice(100, size=n, replace=False):
            triples.append((f"ip{d}", f"w{w}", int(rng.integers(1, 5))))
    c = Corpus.from_word_counts(triples)
    batches = make_batches(c, batch_size=8, min_bucket_len=16)
    seen = []
    for b in batches:
        assert b.word_idx.shape == (8, b.bucket_len)
        assert b.bucket_len in (16, 32, 64)
        seen.extend(b.doc_index[b.doc_mask == 1].tolist())
        # padded rows are inert
        assert (b.counts[b.doc_mask == 0] == 0).all()
        # real rows keep their full token mass
        for i in np.nonzero(b.doc_mask)[0]:
            d = int(b.doc_index[i])
            lo, hi = c.doc_ptr[d], c.doc_ptr[d + 1]
            assert b.counts[i].sum() == c.counts[lo:hi].sum()
    assert sorted(seen) == list(range(c.num_docs))


def test_make_batches_underfull_bucket_not_padded_to_batch_size():
    """A bucket holding far fewer docs than batch_size pads its batch
    axis to the next pad_multiple, not the full batch_size: under a
    power-law doc-length distribution (realistic config-3 corpora) the
    huge-doc tail buckets hold a handful of docs each, and full padding
    would multiply their E-step compute and memory by
    batch_size/len(bucket) for nothing (round 5)."""
    triples = []
    for d in range(3):                      # 3 huge docs -> bucket 128
        for w in range(100):
            triples.append((f"big{d}", f"w{w}", 1))
    for d in range(2000):                   # 2000 small docs -> bucket 16
        triples.append((f"s{d}", f"w{d % 100}", 1))
        triples.append((f"s{d}", f"w{(d + 1) % 100}", 1))
    c = Corpus.from_word_counts(triples)
    batches = make_batches(c, batch_size=1024, min_bucket_len=16,
                           pad_multiple=8)
    by_len = {}
    for b in batches:
        by_len.setdefault(b.bucket_len, []).append(b)
    (big,) = by_len[128]
    assert big.word_idx.shape == (8, 128)   # NOT (1024, 128)
    small = by_len[16]
    assert small[0].word_idx.shape == (1024, 16)  # full bucket: reuse
    for b in batches:                       # stays data-axis shardable
        assert b.word_idx.shape[0] % 8 == 0
    seen = sorted(
        i for b in batches for i in b.doc_index[b.doc_mask == 1].tolist()
    )
    assert seen == list(range(c.num_docs))  # coverage invariant holds

    # Without pad_multiple the old full-batch_size padding stands:
    # direct callers that shard over meshes this module can't see
    # (e.g. a 16-wide data axis) must not regress to B=8 batches.
    default = make_batches(c, batch_size=1024, min_bucket_len=16)
    assert all(b.word_idx.shape[0] == 1024 for b in default)


def test_non_utf8_round_trips_python_reader(tmp_path):
    """Hostile raw wire bytes must survive the word_counts -> corpus ->
    words.dat round trip via surrogateescape in the pure-Python reader
    (the native reader's twin assertion lives in test_native_ingest.py,
    which is skipped without g++)."""
    import os

    from oni_ml_tpu.io.corpus import Corpus

    path = str(tmp_path / "wc.dat")
    with open(path, "wb") as f:
        f.write(b"1.2.3.4,w\xe9rd,5\n")
    os.environ["ONI_ML_TPU_NO_NATIVE"] = "1"
    try:
        c = Corpus.from_word_counts_file(path)
    finally:
        del os.environ["ONI_ML_TPU_NO_NATIVE"]
    assert c.vocab == ["w\udce9rd"]
    out = str(tmp_path / "out")
    os.makedirs(out)
    c.save(out)
    with open(os.path.join(out, "words.dat"), "rb") as f:
        assert f.read() == b"0,w\xe9rd\n"


def test_native_model_emit_matches_python(tmp_path):
    """model_emit parity: the C++ model.dat buffer is byte-identical to
    the Python CSR line loop, including empty docs and float counts
    (int()-truncated)."""
    import numpy as np

    from oni_ml_tpu.io import formats
    from oni_ml_tpu import native_emit

    rng = np.random.default_rng(9)
    lens = [0, 1, 5, 0, 37, 2, 0]                # empty docs included
    ptr = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    nnz = int(ptr[-1])
    widx = rng.integers(0, 5000, nnz).astype(np.int32)
    cnts = rng.integers(1, 2000, nnz).astype(np.float64)  # float CSR
    blob = native_emit.model_emit(ptr, widx, cnts)
    p = tmp_path / "model.dat"
    if blob is None:
        import pytest

        pytest.skip("native emit unavailable")
    # Force the Python loop by writing through the fallback body.
    import oni_ml_tpu.native_emit as ne
    real = ne.model_emit
    ne.model_emit = lambda *a: None
    try:
        formats.write_model_dat(str(p), ptr, widx, cnts)
    finally:
        ne.model_emit = real
    assert blob == p.read_bytes()
    # Round-trips through the reader.
    ptr2, widx2, cnts2 = formats.read_model_dat(str(p))
    assert np.array_equal(ptr2, ptr)
    assert np.array_equal(widx2, widx)
    assert np.array_equal(cnts2, cnts.astype(np.int64))


def test_keyed_matrix_reader_ragged_raises(tmp_path):
    """The batched doc/word-results reader must reject ragged rows
    loudly (the old per-row reader silently built an object array)."""
    p = tmp_path / "d.csv"
    p.write_text("a,0.5 0.5\nb,0.2 0.3 0.5\n")
    with pytest.raises(ValueError, match="ragged"):
        formats.read_doc_results(str(p))
    # Multi-space separation stays accepted (split() semantics).
    p.write_text("a,0.5  0.5\nb,0.25 0.75\n")
    names, mat = formats.read_doc_results(str(p))
    assert names == ["a", "b"] and mat.shape == (2, 2)
