"""Randomized native-vs-Python parity fuzzing.

The targeted parity tests (test_native_flow / test_native_dns) pin known
edge cases; these sweep randomized structure — field garbage, weird
ports, hostile query names, random widths — across several seeds so a
future change that breaks parity off the happy path fails loudly.
Every assertion is exact equality: the native paths are speedups, never
approximations.
"""

import numpy as np
import pytest

from oni_ml_tpu.features import dns as pydns
from oni_ml_tpu.features import flow as pyflow
from oni_ml_tpu.features import native_dns, native_flow


def _rand_token(rng) -> str:
    kind = rng.integers(0, 8)
    if kind == 0:
        return ""
    if kind == 1:
        return str(rng.integers(-100, 70000))
    if kind == 2:
        return f"{rng.uniform(-1e4, 1e4):.3f}"
    if kind == 3:
        return "##"
    if kind == 4:
        return rng.choice(["nan", "inf", "-inf", "1e999", "1e-999", "+5"])
    if kind == 5:
        return "x" * int(rng.integers(1, 8))
    if kind == 6:
        # str(float) fixed/scientific boundary magnitudes (exponent in
        # [-4, 16) prints fixed; outside prints "1e+16"-style).
        return rng.choice([
            "1e15", "1e16", "-1e16", "9999999999999998", "1e-4", "0.0001",
            "0.00001", "2.5e-5", "123456789012345678", "1e100",
        ])
    return " " + str(rng.integers(0, 99)) + " "


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_flow_fuzz_parity(tmp_path, seed):
    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    rng = np.random.default_rng(seed)
    path = tmp_path / "flow.csv"
    _write_fuzz_flow_csv(rng, path)

    with open(path) as f:
        py = pyflow.featurize_flow(line.rstrip("\n") for line in f)
    nat = native_flow.featurize_flow_file(str(path))
    assert nat.num_events == py.num_events
    np.testing.assert_array_equal(nat.num_time, py.num_time)
    np.testing.assert_array_equal(nat.time_cuts, py.time_cuts)
    np.testing.assert_array_equal(nat.ibyt_bin, py.ibyt_bin)
    np.testing.assert_array_equal(nat.ipkt_bin, py.ipkt_bin)
    assert nat.src_word == py.src_word
    assert nat.dest_word == py.dest_word
    assert nat.word_counts() == py.word_counts()
    assert nat.rows == py.rows


def _rand_qname(rng) -> str:
    parts = []
    for _ in range(int(rng.integers(0, 7))):
        n = int(rng.integers(0, 12))
        parts.append(
            "".join(rng.choice(list("abcdef0123456789-"), size=n))
        )
    name = ".".join(parts)
    suffix = rng.integers(0, 6)
    if suffix == 0:
        name += ".in-addr.arpa"
    elif suffix == 1:
        name += ".co.uk"
    elif suffix == 2:
        name += "."
    elif suffix == 3:
        name += ".com"
    return name


def _write_fuzz_flow_csv(rng, path):
    lines = ["hdr,line"]
    for _ in range(300):
        width = int(rng.choice([27, 27, 27, 26, 28, 5]))
        lines.append(",".join(_rand_token(rng) for _ in range(width)))
    path.write_text("\n".join(lines) + "\n")


def _write_fuzz_dns_csv(rng, path):
    lines = []
    for _ in range(300):
        width = int(rng.choice([8, 8, 8, 7, 9]))
        fields = [_rand_token(rng) for _ in range(width)]
        # Mostly structured qnames, but leave ~25% as raw fuzz tokens so
        # extract_subdomain also sees garbage (##, padded numbers, ...).
        if width == 8 and rng.random() < 0.75:
            fields[4] = _rand_qname(rng)
        lines.append(",".join(fields))
    path.write_text("\n".join(lines) + "\n")


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_dns_fuzz_parity(tmp_path, seed):
    if not native_dns.available():
        pytest.skip("native dns featurizer unavailable")
    rng = np.random.default_rng(100 + seed)
    path = tmp_path / "dns.csv"
    _write_fuzz_dns_csv(rng, path)

    rows = [
        line.split(",")
        for line in (path.read_text().rstrip("\n")).split("\n")
        if line
    ]
    py = pydns.featurize_dns(rows, top_domains=frozenset({"abc", "google"}))
    nat = native_dns.featurize_dns_sources(
        [str(path)], top_domains=frozenset({"abc", "google"})
    )
    assert nat.num_events == py.num_events
    assert nat.domain == py.domain
    assert nat.subdomain == py.subdomain
    np.testing.assert_array_equal(nat.subdomain_entropy, py.subdomain_entropy)
    np.testing.assert_array_equal(nat.num_periods, py.num_periods)
    for name in ("time_cuts", "frame_length_cuts", "subdomain_length_cuts",
                 "entropy_cuts", "numperiods_cuts"):
        np.testing.assert_array_equal(getattr(nat, name), getattr(py, name))
    assert nat.word == py.word
    assert nat.word_counts() == py.word_counts()


@pytest.mark.parametrize("seed", [0, 1])
def test_flow_fuzz_spill_parity(tmp_path, seed):
    """Spilled-vs-in-memory raw-line storage under fuzzed inputs: the
    stored rows (and everything derived) must be identical whichever
    store the bytes live in."""
    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    rng = np.random.default_rng(500 + seed)
    path = tmp_path / "flow.csv"
    _write_fuzz_flow_csv(rng, path)

    nat = native_flow.featurize_flow_file(str(path))
    spill = native_flow.featurize_flow_file(
        str(path), spill_path=str(tmp_path / "raw.bin")
    )
    assert spill.rows == nat.rows
    assert spill.word_counts() == nat.word_counts()
    assert len(spill.lines_blob) == len(nat.lines_blob)


@pytest.mark.parametrize("seed", [0, 1])
def test_dns_fuzz_spill_parity(tmp_path, seed):
    if not native_dns.available():
        pytest.skip("native dns featurizer unavailable")
    rng = np.random.default_rng(700 + seed)
    path = tmp_path / "dns.csv"
    _write_fuzz_dns_csv(rng, path)

    nat = native_dns.featurize_dns_sources([str(path)])
    # The generators never emit transport bytes, so fallback to the
    # Python container would mean a regression — fail loudly instead of
    # skipping the parity check.
    assert isinstance(nat, native_dns.NativeDnsFeatures)
    spill = native_dns.featurize_dns_sources(
        [str(path)], spill_path=str(tmp_path / "rows.bin")
    )
    assert isinstance(spill, native_dns.NativeDnsFeatures)
    assert spill.rows == nat.rows
    assert spill.word_counts() == nat.word_counts()


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_emit_kernels_parity(seed, tmp_path):
    """Randomized parity for the round-3 native emitters: model_emit
    (CSR -> LDA-C lines), wc_emit over a native container built from
    randomized DNS rows, and score_dot vs the sequential-fold numpy
    path — exact equality on randomized shapes including empty docs,
    zero-nnz corpora, and single-row models."""
    from oni_ml_tpu import native_emit
    from oni_ml_tpu.io import formats

    if not native_emit.available():
        pytest.skip("native emit unavailable")
    rng = np.random.default_rng(1000 + seed)

    # wc_emit: word_counts buffer over a native container from random
    # rows, vs formats.write_word_counts over the Python triples.
    if native_dns.available():
        rows = [
            ["t", str(1454000000 + int(rng.integers(0, 9999))),
             str(int(rng.integers(1, 2000))),
             f"10.{rng.integers(0, 5)}.{rng.integers(0, 5)}.{rng.integers(0, 9)}",
             f"s{rng.integers(0, 6)}.d{rng.integers(0, 9)}.com", "1",
             str(int(rng.integers(1, 17))), str(int(rng.integers(0, 4)))]
            for _ in range(int(rng.integers(1, 300)))
        ]
        feats = native_dns.featurize_dns_sources([rows])
        wc_blob = native_emit.word_counts_emit(feats)
        wp = tmp_path / f"wc{seed}.dat"
        formats.write_word_counts(str(wp), feats.word_counts())
        assert wc_blob == wp.read_bytes()

    # model_emit: random ragged CSR with empty docs and big counts.
    n_docs = int(rng.integers(0, 40))
    lens = rng.integers(0, 12, n_docs)
    ptr = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    nnz = int(ptr[-1]) if n_docs else 0
    widx = rng.integers(0, 1 << 20, nnz).astype(np.int32)
    cnts = rng.integers(1, 1 << 40, nnz).astype(np.int64)
    blob = native_emit.model_emit(ptr, widx, cnts)
    p = tmp_path / f"m{seed}.dat"
    import oni_ml_tpu.native_emit as ne
    real = ne.model_emit
    ne.model_emit = lambda *a: None
    try:
        formats.write_model_dat(str(p), ptr, widx, cnts)
    finally:
        ne.model_emit = real
    assert blob == p.read_bytes()

    # score_dot: random K incl. 1; values spanning magnitudes.
    k = int(rng.integers(1, 33))
    theta = rng.random((int(rng.integers(1, 50)), k)) * 10.0 ** rng.integers(-8, 8)
    pm = rng.random((int(rng.integers(1, 50)), k))
    n = int(rng.integers(0, 500))
    ia = rng.integers(0, len(theta), n).astype(np.int32)
    ib = rng.integers(0, len(pm), n).astype(np.int32)
    a, b = theta[ia], pm[ib]
    if n:
        want = a[:, 0] * b[:, 0]
        for j in range(1, k):
            want = want + a[:, j] * b[:, j]
    else:
        want = np.zeros(0)
    got = native_emit.score_dot(theta, pm, ia, ib)
    assert np.array_equal(got, want)


def test_narrow_i32_guards_overflow():
    """wc_count narrowing must raise, not wrap (round-3 advisor
    finding: astype(int32) silently corrupts counts >= 2^31)."""
    import pytest

    from oni_ml_tpu.features import native_dns, native_flow

    for mod in (native_flow, native_dns):
        ok = mod._narrow_i32(np.array([0, 5, 2**31 - 1], dtype=np.int64))
        assert ok.dtype == np.int32 and ok.tolist() == [0, 5, 2**31 - 1]
        assert mod._narrow_i32(np.zeros(0, dtype=np.int64)).dtype == np.int32
        with pytest.raises(OverflowError):
            mod._narrow_i32(np.array([1, 2**31], dtype=np.int64))
