"""Tiered model residency (oni_ml_tpu/serving/residency.py + the
capacity-tiered stacks in serving/fleet.py): tier transitions under
admission-driven LRU/LFU paging, warm→hot promotion bit-identity,
cold-tier checkpoint round trips at preserved versions, capacity-tier
shape stability with compile-trace proof (one new program family per
power-of-two census crossing, zero retraces for churn within a tier,
plans-on AND plans-off), the eviction-storm isolation the acceptance
criteria name, the device-buffer-bound regression test for the
stack-rebuild path, bf16 stacked storage at its documented tolerance,
the Zipf load_gen mix + paged fleet SLO harness, bench_diff's paged
direction keys, and the residency journal/trace vocabulary.  All CPU,
no markers — tier-1.
"""

import gc
import json
import os
import threading
import time

import numpy as np
import pytest

from oni_ml_tpu import plans
from oni_ml_tpu.config import ServingConfig
from oni_ml_tpu.plans import KNOBS, NullStore, PlanStore, use_store
from oni_ml_tpu.runner.serve import _synthetic_day
from oni_ml_tpu.scoring import ScoringModel
from oni_ml_tpu.serving import (
    TIER_COLD,
    TIER_HOT,
    TIER_WARM,
    DnsEventFeaturizer,
    FleetRegistry,
    FleetScorer,
    MetricsEmitter,
    ModelRegistry,
    ResidencyManager,
    TenantSpec,
    load_spill,
    resolve_hot_capacity,
    score_features,
    spill_model,
)
from oni_ml_tpu.telemetry.spans import Recorder


@pytest.fixture(scope="module")
def days():
    """Six distinct synthetic DNS days (distinct seeds -> distinct
    models; same K -> one pack group) shared by the residency tests."""
    return {f"t{i}": _synthetic_day(seed=42 + i) for i in range(6)}


def _tiered_fleet(days, tenants, *, hot=2, warm=0, policy="lru",
                  spill_dir="", stack_precision="f32",
                  device_score_min=None, journal=None, recorder=None,
                  fleet_max_batch=64):
    """Capacity-tiered FleetRegistry + ResidencyManager + FleetScorer
    over `tenants` (everything starts host-warm; admissions fill the
    hot tier)."""
    rec = recorder or Recorder()
    fleet = FleetRegistry(journal=journal, recorder=rec,
                          capacity_tiers=True,
                          stack_precision=stack_precision)
    mgr = ResidencyManager(
        fleet, hot_capacity=hot, warm_capacity=warm, policy=policy,
        spill_dir=spill_dir, journal=journal, recorder=rec,
    )
    featurizers = {}
    for t in tenants:
        rows, model, cuts = days[t]
        fleet.add_tenant(TenantSpec(tenant=t, dsource="dns"), hot=False)
        fleet.publish(t, model, source=f"day-{t}")
        mgr.register(t)
        featurizers[t] = DnsEventFeaturizer(cuts)
    cfg = ServingConfig(device_score_min=device_score_min,
                        fleet_max_batch=fleet_max_batch)
    metrics = MetricsEmitter(to_stdout=False, recorder=rec)
    scorer = FleetScorer(fleet, featurizers, cfg, metrics=metrics,
                         residency=mgr)
    mgr.set_pending_probe(lambda t: len(scorer._lanes[t].pending) > 0)
    return fleet, mgr, featurizers, metrics, scorer


def _score(scorer, days, tenant, n=8, timeout=30.0):
    futs = [scorer.submit(tenant, r) for r in days[tenant][0][:n]]
    scorer.flush()
    return np.array([f.result(timeout=timeout)[0] for f in futs]), \
        sorted({v for f in futs for v in [f.result(timeout)[1]]})


def _expected(days, featurizers, tenant, n=8):
    fz = featurizers[tenant]
    feats = fz([fz.validate(r) for r in days[tenant][0][:n]])
    return score_features(days[tenant][1], feats, "dns",
                          device_min=None)


# ---------------------------------------------------------------------------
# checkpoint round trips + registry unload/restore
# ---------------------------------------------------------------------------


def test_spill_round_trip_bit_identical(days, tmp_path):
    _, model, _ = days["t0"]
    path = str(tmp_path / "t0.npz")
    size = spill_model(path, model)
    assert size > 0 and os.path.exists(path)
    back = load_spill(path)
    np.testing.assert_array_equal(back.theta, model.theta)
    np.testing.assert_array_equal(back.p, model.p)
    assert back.ip_index == model.ip_index
    assert back.word_index == model.word_index


def test_registry_unload_restore_preserves_version(days):
    _, model, _ = days["t0"]
    reg = ModelRegistry()
    reg.publish(model, "day")
    reg.publish(model, "day2")
    assert reg.version == 2
    snap = reg.unload()
    assert snap.version == 2 and not reg.loaded
    with pytest.raises(RuntimeError, match="no model published"):
        reg.active()
    # Version rewind and double-restore both refuse.
    with pytest.raises(ValueError, match="restore version"):
        reg.restore(model, "ckpt", 1)
    reg.restore(model, "ckpt", 2)
    assert reg.loaded and reg.active().version == 2
    with pytest.raises(RuntimeError, match="unload first"):
        reg.restore(model, "ckpt", 2)


def test_unload_requires_eviction_first(days):
    fleet = FleetRegistry(capacity_tiers=True)
    _, model, _ = days["t0"]
    fleet.add_tenant(TenantSpec(tenant="t0", dsource="dns"))
    fleet.publish("t0", model, "day")
    with pytest.raises(RuntimeError, match="stack-resident"):
        fleet.unload_tenant("t0")
    fleet.set_hot("t0", False)
    snap = fleet.unload_tenant("t0")
    assert snap.version == 1


# ---------------------------------------------------------------------------
# tier transitions + promotion bit-identity
# ---------------------------------------------------------------------------


def test_full_tier_cycle_scores_bit_identical(days, tmp_path):
    """The tentpole invariant: a tenant scored after warm→hot (and
    after cold→warm→hot) promotion produces BIT-IDENTICAL results to
    one that was always hot — paging changes where the model lives,
    never its arithmetic."""
    tenants = tuple(f"t{i}" for i in range(6))
    fleet, mgr, featurizers, _, scorer = _tiered_fleet(
        days, tenants, hot=2, warm=2, spill_dir=str(tmp_path))
    try:
        for t in tenants:                       # warm -> hot churn
            got, versions = _score(scorer, days, t)
            np.testing.assert_array_equal(
                got, _expected(days, featurizers, t))
            assert versions == [1]              # paging never bumps
        tiers = mgr.tiers()
        assert tiers[TIER_HOT] == 2
        assert tiers[TIER_COLD] >= 1            # warm bound forced spills
        # Round 2: every tenant has paged at least once by now; the
        # cold ones reload from their spill checkpoints.
        for t in tenants:
            got, versions = _score(scorer, days, t)
            np.testing.assert_array_equal(
                got, _expected(days, featurizers, t))
            assert versions == [1]
        stats = mgr.stats_snapshot()
        assert stats["promotions"] >= len(tenants)
        assert stats["evictions"] >= 1
        assert stats["cold_loads"] >= 1
        assert stats["failures"] == 0
        assert stats["promotion_stall_s"] >= 0
    finally:
        scorer.close()
        mgr.close()


def test_lru_vs_lfu_victim_selection(days):
    """LRU evicts the least recently admitted hot tenant; LFU evicts
    the least admitted overall."""
    for policy, expect_victim in (("lru", "t0"), ("lfu", "t1")):
        fleet, mgr, featurizers, _, scorer = _tiered_fleet(
            days, ("t0", "t1", "t2"), hot=2, policy=policy)
        try:
            # t0 touched 3x (oldest last touch), t1 touched once
            # (most recent of the two before t2 arrives).
            _score(scorer, days, "t0")
            _score(scorer, days, "t0")
            _score(scorer, days, "t0")
            _score(scorer, days, "t1")
            _score(scorer, days, "t2")   # forces one eviction
            assert mgr.tier_of("t2") == TIER_HOT
            assert mgr.tier_of(expect_victim) == TIER_WARM, policy
        finally:
            scorer.close()
            mgr.close()


def test_promotion_failure_is_tenant_scoped(days, tmp_path):
    """A cold tenant whose checkpoint vanished fails ITS futures with
    the promotion error; other tenants keep scoring."""
    tenants = ("t0", "t1", "t2")
    fleet, mgr, featurizers, _, scorer = _tiered_fleet(
        days, tenants, hot=1, warm=1, spill_dir=str(tmp_path))
    try:
        _score(scorer, days, "t0")
        _score(scorer, days, "t1")
        _score(scorer, days, "t2")
        # The warm-capacity sweep runs async on the pager; wait for
        # t0's warm->cold demotion to land.
        deadline = time.monotonic() + 10.0
        while mgr.tier_of("t0") != TIER_COLD \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert mgr.tier_of("t0") == TIER_COLD
        for f in os.listdir(str(tmp_path)):
            os.remove(os.path.join(str(tmp_path), f))
        futs = [scorer.submit("t0", r) for r in days["t0"][0][:4]]
        scorer.flush()
        for f in futs:
            with pytest.raises(Exception):
                f.result(timeout=30.0)
        # The fleet survives: another tenant still scores correctly.
        got, _ = _score(scorer, days, "t2")
        np.testing.assert_array_equal(
            got, _expected(days, featurizers, "t2"))
        assert mgr.stats_snapshot()["failures"] >= 1
    finally:
        scorer.close()
        mgr.close()


def test_eviction_storm_isolation(days, tmp_path, monkeypatch):
    """The acceptance test: hot resident tenants' futures NEVER fail
    and their scores stay bit-identical while another tenant pages
    through a slow cold load — paging a tenant in must not stall or
    corrupt a resident one."""
    from oni_ml_tpu.serving import residency as residency_mod

    real_load = residency_mod.load_spill

    def slow_load(path):
        time.sleep(0.05)               # a deliberately slow checkpoint
        return real_load(path)

    monkeypatch.setattr(residency_mod, "load_spill", slow_load)
    tenants = ("t0", "t1", "t2")
    fleet, mgr, featurizers, _, scorer = _tiered_fleet(
        days, tenants, hot=2, warm=0, spill_dir=str(tmp_path),
        fleet_max_batch=8)
    try:
        _score(scorer, days, "t0")
        _score(scorer, days, "t1")
        # Force t2 cold so its every promotion pays the slow load.
        fleet.set_hot("t2", False)
        fleet.unload_tenant("t2")
        spill_model(os.path.join(str(tmp_path), "t2.npz"),
                    days["t2"][1])
        with mgr._lock:
            st = mgr._state["t2"]
            st.tier = TIER_COLD
            st.spill_path = os.path.join(str(tmp_path), "t2.npz")
            st.cold_version = 1
            st.cold_source = "day-t2"
            mgr._refresh_drainable_locked()
        expected_t1 = _expected(days, featurizers, "t1")
        errors: list = []
        results: list = []
        stop = threading.Event()

        def resident_load():
            while not stop.is_set():
                futs = [scorer.submit("t1", r)
                        for r in days["t1"][0][:8]]
                scorer.flush()
                try:
                    results.append(np.array(
                        [f.result(timeout=30.0)[0] for f in futs]))
                except Exception as e:      # pragma: no cover
                    errors.append(e)

        th = threading.Thread(target=resident_load, daemon=True)
        th.start()

        def force_cold():
            """Push t2 back to checkpoint-cold for the next cycle
            (t0 refills the tier; eviction victim choice is policy's,
            so demote explicitly once t2 has left the stack)."""
            deadline = time.monotonic() + 15.0
            while mgr.tier_of("t2") == TIER_HOT \
                    and time.monotonic() < deadline:
                _score(scorer, days, "t0")
                time.sleep(0.01)
            if mgr.tier_of("t2") == TIER_WARM:
                fleet.unload_tenant("t2")
                with mgr._lock:
                    st = mgr._state["t2"]
                    st.tier = TIER_COLD
                    st.spill_path = os.path.join(
                        str(tmp_path), "t2.npz")
                    st.cold_version = 1
                    st.cold_source = "day-t2"
                    mgr._refresh_drainable_locked()

        # Page t2 in repeatedly while t1 scores: each cycle pays the
        # slow cold load on the pager thread.
        for _ in range(3):
            got, _ = _score(scorer, days, "t2", timeout=60.0)
            np.testing.assert_array_equal(
                got, _expected(days, featurizers, "t2"))
            force_cold()
        stop.set()
        th.join(timeout=30.0)
        assert not errors                  # zero failed resident futures
        assert len(results) >= 3
        for got in results:                # bit-identical throughout
            np.testing.assert_array_equal(got, expected_t1)
        assert mgr.stats_snapshot()["cold_loads"] >= 2
    finally:
        stop.set()
        scorer.close()
        mgr.close()


# ---------------------------------------------------------------------------
# capacity tiers: shape stability + zero retraces
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("plans_on", [True, False])
def test_capacity_tier_shape_stability_and_retraces(plans_on, tmp_path):
    """Census grows 3→4 (inside capacity 4: zero new programs) →5
    (crosses the power-of-two boundary: exactly one new program
    family), then promote/evict churn within the tier retraces
    NOTHING.  Device path pinned so every flush dispatches the
    compiled gather-dot; holds with the plan cache on and off.

    Each parametrization builds days with its OWN population sizes —
    distinct stacked shapes, so the second run cannot ride the first
    run's in-process jit cache and the trace deltas stay meaningful."""
    from oni_ml_tpu.plans import warmup as plans_warmup

    # Distinct populations per param (crossing different pow2 slot
    # boundaries), so the second run cannot ride the first run's
    # compiled programs.
    n_clients, n_doms = (9, 6) if plans_on else (20, 9)
    local_days = {
        f"t{i}": _synthetic_day(seed=42 + i, n_clients=n_clients,
                                n_doms=n_doms)
        for i in range(8)
    }
    store = (PlanStore(str(tmp_path / "plans.jsonl"), seeds=False)
             if plans_on else NullStore())
    with use_store(store):
        tenants = tuple(f"t{i}" for i in range(8))
        fleet, mgr, featurizers, _, scorer = _tiered_fleet(
            local_days, tenants, hot=5, warm=0, device_score_min=1)
        try:
            # The trace counters are monitoring events off the
            # persistent compilation cache — wire it (hermetic dir via
            # conftest's JAX_COMPILATION_CACHE_DIR) so they count.
            plans_warmup.setup_compilation_cache()
            plans_warmup._ensure_listener()
            for t in tenants[:3]:
                _score(scorer, local_days, t)
            k = local_days["t0"][1].num_topics
            assert fleet.tier(k)["capacity"] == 4   # pow2 ceiling of 3
            shape3 = fleet.stack_for("t0").model.theta.shape
            # Assert on compile REQUESTS: an in-memory jit hit makes
            # no request at all, so a zero delta is immune to whatever
            # the persistent disk cache happens to hold.
            base = plans_warmup.compile_counts()["compile_requests"]
            # 3 -> 4: same tier, same shape, ZERO new programs.
            _score(scorer, local_days, tenants[3])
            assert fleet.stack_for("t0").model.theta.shape == shape3
            c4 = plans_warmup.compile_counts()["compile_requests"]
            assert c4 - base == 0
            # 4 -> 5: crosses the boundary -> capacity 8: the stacked
            # shape changes exactly ONCE, minting one new program
            # family (the gather-dot program plus its per-shape weight
            # uploads — a handful of traces from the single shape
            # change, never per-tenant).
            _score(scorer, local_days, tenants[4])
            assert fleet.tier(k)["capacity"] == 8
            shape5 = fleet.stack_for("t0").model.theta.shape
            assert shape5 != shape3
            c5 = plans_warmup.compile_counts()["compile_requests"]
            assert 1 <= c5 - c4 <= 3, (c4, c5)
            # Churn within the tier: the hot capacity is 5, so every
            # further promotion EVICTS a policy victim — census stays
            # 5, the capacity tier stays 8, and the shape (and with it
            # the compiled family, keyed by capacity, not by which
            # tenants are resident) never changes: zero retraces,
            # exactly, across the whole promote/evict storm.
            for t in (tenants[5], tenants[6], tenants[7], tenants[0],
                      tenants[2], tenants[4], tenants[6], tenants[1]):
                _score(scorer, local_days, t)   # promotes, evicting
                assert fleet.stack_for(t).model.theta.shape == shape5
            assert mgr.stats_snapshot()["evictions"] >= 5
            c_churn = plans_warmup.compile_counts()["compile_requests"]
            assert c_churn - c5 == 0
        finally:
            scorer.close()
            mgr.close()


def test_capacity_padding_never_changes_scores(days):
    """Pad rows are dead weight by construction: the padded stack's
    packed scores equal the unpadded fleet's bit-for-bit."""
    plain = FleetRegistry()
    tiered = FleetRegistry(capacity_tiers=True)
    for reg in (plain, tiered):
        for t in ("t0", "t1", "t2"):
            reg.add_tenant(TenantSpec(tenant=t, dsource="dns"))
            reg.publish(t, days[t][1], t)
    s_plain = plain.stack_for("t0")
    s_tiered = tiered.stack_for("t0")
    assert s_tiered.model.theta.shape[0] > s_plain.model.theta.shape[0]
    assert s_tiered.capacity == 4
    for t in ("t0", "t1", "t2"):
        m = days[t][1]
        i0, w0 = s_tiered.ip_base[t], s_tiered.word_base[t]
        np.testing.assert_array_equal(
            s_tiered.model.theta[i0:i0 + m.theta.shape[0]], m.theta)
        np.testing.assert_array_equal(
            s_tiered.model.p[w0:w0 + m.p.shape[0]], m.p)


# ---------------------------------------------------------------------------
# device-buffer bound across a promote/evict storm (stack-rebuild audit)
# ---------------------------------------------------------------------------


def test_device_buffer_count_bounded_across_storm(days):
    """The stack-rebuild audit's regression pin: old stacked device
    buffers must become collectible after every swap — a storm of
    promote/evict cycles (each rebuilding the stack and re-uploading
    it on first device dispatch) must not grow the live device-buffer
    census."""
    import jax

    fleet, mgr, featurizers, _, scorer = _tiered_fleet(
        days, ("t0", "t1", "t2"), hot=2, device_score_min=1)
    try:
        first: dict = {}
        for t in ("t0", "t1", "t2"):     # settle: all shapes compiled
            first[t], _ = _score(scorer, days, t)
        gc.collect()
        baseline = len(jax.live_arrays())
        for i in range(12):              # the storm
            t = f"t{i % 3}"
            got, _ = _score(scorer, days, t)
            # Same (device f32) path before and after paging: the
            # promoted tenant's scores stay BIT-identical through the
            # whole storm.
            np.testing.assert_array_equal(got, first[t])
        gc.collect()
        after = len(jax.live_arrays())
        # Exactly one stack (2 device arrays) may be live per K-group
        # plus transient slack; 12 rebuild cycles must NOT have pinned
        # 12 retired stacks (24+ arrays).
        assert after <= baseline + 8, (baseline, after)
    finally:
        scorer.close()
        mgr.close()


# ---------------------------------------------------------------------------
# bf16 stacked storage
# ---------------------------------------------------------------------------


def test_bf16_stack_halves_device_bytes_and_meets_tolerance(days):
    """ServingConfig.stack_precision="bf16": the stacked snapshot's
    device cache stores bfloat16 (half the HBM bytes -> double the
    hot-tier residency per byte), accumulation stays f32, and packed
    scores agree with the f32 stack within the DOCUMENTED tolerance
    (2^-7 relative — bf16's 8 significand bits through a K-term dot).
    The host f64 path is untouched."""
    import jax.numpy as jnp

    from oni_ml_tpu.scoring.score import _device_model

    got = {}
    for precision in ("f32", "bf16"):
        fleet, mgr, featurizers, _, scorer = _tiered_fleet(
            days, ("t0", "t1"), hot=2, stack_precision=precision,
            device_score_min=1)
        try:
            got[precision], _ = _score(scorer, days, "t0", n=16)
            stack = fleet.stack_for("t0")
            theta_dev, p_dev = _device_model(stack.model)
            want = (jnp.bfloat16 if precision == "bf16"
                    else jnp.float32)
            assert theta_dev.dtype == want and p_dev.dtype == want
        finally:
            scorer.close()
            mgr.close()
    f32, bf16 = got["f32"], got["bf16"]
    assert np.all(np.isfinite(bf16))
    rel = np.abs(bf16 - f32) / np.maximum(np.abs(f32), 1e-300)
    assert rel.max() <= 2 ** -7, rel.max()
    # And the host path (device_min=None) ignores the marker entirely:
    fleet, mgr, featurizers, _, scorer = _tiered_fleet(
        days, ("t0",), hot=1, stack_precision="bf16",
        device_score_min=None)
    try:
        host, _ = _score(scorer, days, "t0", n=16)
        np.testing.assert_array_equal(
            host, _expected(days, featurizers, "t0", n=16))
    finally:
        scorer.close()
        mgr.close()


def test_stack_precision_validation():
    with pytest.raises(ValueError, match="stack_precision"):
        FleetRegistry(stack_precision="f16")


# ---------------------------------------------------------------------------
# plans resolution
# ---------------------------------------------------------------------------


def test_resolve_hot_capacity_precedence(tmp_path):
    cfg_off = ServingConfig()
    cfg_on = ServingConfig(fleet_hot_tenants=5)
    with use_store(NullStore()):
        assert resolve_hot_capacity(cfg_off) == (0, "default")
        assert resolve_hot_capacity(cfg_on) == (5, "config")
    st = PlanStore(str(tmp_path / "plans.jsonl"), seeds=False)
    fp = plans.fingerprint(KNOBS["fleet_hot_tenants"].scope)
    st.record("fleet_hot_tenants", fp, "*", 16, source="probe")
    with use_store(st):
        assert resolve_hot_capacity(cfg_off) == (16, "plan")
        assert resolve_hot_capacity(cfg_on) == (5, "config")


def test_residency_manager_validation(days):
    fleet = FleetRegistry(capacity_tiers=True)
    with pytest.raises(ValueError, match="policy"):
        ResidencyManager(fleet, hot_capacity=2, policy="fifo")
    with pytest.raises(ValueError, match="capacities"):
        ResidencyManager(fleet, hot_capacity=-1)
    mgr = ResidencyManager(fleet, hot_capacity=2)
    try:
        fleet.add_tenant(TenantSpec(tenant="t0", dsource="dns"),
                         hot=False)
        fleet.publish("t0", days["t0"][1], "d")
        mgr.register("t0")
        with pytest.raises(ValueError, match="already registered"):
            mgr.register("t0")
    finally:
        mgr.close()


# ---------------------------------------------------------------------------
# journal vocabulary + trace lanes
# ---------------------------------------------------------------------------


def test_residency_journal_records_and_trace_lanes(days, tmp_path):
    from oni_ml_tpu.telemetry import Journal

    jpath = str(tmp_path / "residency.jsonl")
    journal = Journal(jpath)
    rec = Recorder()
    fleet, mgr, featurizers, _, scorer = _tiered_fleet(
        days, ("t0", "t1", "t2"), hot=1, warm=1,
        spill_dir=str(tmp_path / "spill"), journal=journal,
        recorder=rec)
    try:
        for t in ("t0", "t1", "t2", "t0"):
            _score(scorer, days, t)
    finally:
        scorer.close()
        mgr.close()
        journal.close()
    records = list(Journal.replay(jpath))
    promotes = [r for r in records
                if r["kind"] == "residency_promote" and r.get("ok")]
    evicts = [r for r in records if r["kind"] == "residency_evict"]
    assert promotes and evicts
    hot_legs = [r for r in promotes if r.get("tier_from") in
                (TIER_WARM, TIER_COLD) and "stall_s" in r]
    assert hot_legs
    for r in hot_legs:
        assert r["stall_s"] >= 0 and r["capacity"] >= r["census"]
    assert any(r.get("tier_to") == TIER_COLD and
               isinstance(r.get("spill_bytes"), int) for r in evicts)
    # Occupancy gauges live on the recorder.
    assert "residency.hot" in rec.gauges
    assert rec.gauges["residency.hot"] <= 1
    assert rec.counters["residency.promotions"].value >= 4
    # trace_view renders lanes + the per-tenant paging table.
    import sys as _sys

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import trace_view

    trace = trace_view.journal_to_trace(records)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "residency hot occupancy" in names
    assert any(n.startswith("residency evict ->") for n in names)
    table = trace_view.residency_table(records)
    assert {r["tenant"] for r in table} == {"t0", "t1", "t2"}
    assert sum(r["promotions"] for r in table) >= 4
    # Journal kinds are in the committed schema (the lint gate pins
    # the full contract; this is the fast tier-1 cross-check).
    schema_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "oni_ml_tpu", "analysis", "schema", "journal_schema.json")
    with open(schema_path) as f:
        schema = json.load(f)
    assert "residency_promote" in schema["kinds"]
    assert "residency_evict" in schema["kinds"]


# ---------------------------------------------------------------------------
# load_gen: zipf mix + the paged fleet SLO harness
# ---------------------------------------------------------------------------


def _load_gen():
    import sys as _sys

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import load_gen

    return load_gen


def test_fleet_mix_zipf():
    lg = _load_gen()
    mix = lg.fleet_mix(4, "poisson:1", 1000.0, zipf_s=1.0)
    weights = [tm["weight"] for tm in mix]
    assert weights == pytest.approx([1.0, 0.5, 1 / 3, 0.25])
    rates = [tm["rate_eps"] for tm in mix]
    assert sum(rates) == pytest.approx(1000.0)
    assert rates[0] > rates[-1]
    with pytest.raises(ValueError, match="zipf_s"):
        lg.fleet_mix(4, "poisson:1", 1000.0, zipf_s=-1)
    # zipf off keeps the cycled mix weights.
    plain = lg.fleet_mix(4, "poisson:3,bursty:1", 1000.0)
    assert [tm["weight"] for tm in plain] == [3.0, 1.0, 3.0, 1.0]


def test_run_fleet_slo_paged_small():
    """The serving_slo_fleet_paged harness at toy scale: working set
    (12 tenants) exceeds the hot capacity (3), all three tiers
    populated, per-tenant latency includes promotion misses, zero
    post-warmup retraces, and the payload carries the residency
    ledger + the truncation-honest tenant summary."""
    lg = _load_gen()
    res = lg.run_fleet_slo(
        12, "poisson:1,bursty:1", n_events=360, rate_eps=3000.0,
        zipf_s=1.1, hot_tenants=3, warm_tenants=4,
        device_score_min=None, timeout_s=60.0, per_tenant_detail=4,
    )
    agg = res["aggregate"]
    assert agg["errors"] == 0
    assert agg["resolved"] == res["n_events"]
    assert agg["p99_ms"] is not None
    resd = res["residency"]
    assert resd["hot_capacity"] == 3
    assert resd["tiers"][TIER_HOT] <= 3
    assert resd["tiers"][TIER_COLD] >= 1
    assert resd["promotions"] >= 3
    assert resd["failures"] == 0
    assert resd["promotion_stall_s"] >= 0
    assert res["plans"]["retraces_after_warmup"] == 0
    assert res["zipf_s"] == 1.1
    assert res["tenants_truncated"] is True
    assert len(res["tenants"]) == 4
    summary = res["tenant_summary"]
    assert summary["p99_ms"]["max"] >= summary["p99_ms"]["min"]
    # Zipf head got more events than the tail.
    events = [v["events"] for v in res["tenants"].values()]
    assert events[0] >= events[-1]


def test_bench_diff_paged_directions(tmp_path):
    """serving_slo_fleet_paged gates like the other serving phases
    (per-group directions) PLUS the residency promotion stall
    (lower-better)."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import bench_diff

    def payload(p99, stall, eps=2000.0):
        return {
            "metric": "m", "value": 1.0, "unit": "x",
            "secondary": {"serving_slo_fleet_paged": {
                "value": eps, "unit": "events/sec",
                "aggregate": {"sustained_eps": eps, "p50_ms": 50.0,
                              "p99_ms": p99, "p999_ms": p99 * 1.1},
                "residency": {"promotion_stall_s": stall,
                              "promotions": 350},
            }},
        }

    a = str(tmp_path / "a.json")
    with open(a, "w") as f:
        json.dump(payload(1000.0, 200.0), f)
    # p99 blowup -> regression.
    b = str(tmp_path / "b.json")
    with open(b, "w") as f:
        json.dump(payload(2000.0, 200.0), f)
    rows = bench_diff.diff_payloads(
        bench_diff.load_payload(a), bench_diff.load_payload(b))
    reg = [r["name"] for r in rows if r["regression"]]
    assert ("phase:serving_slo_fleet_paged:aggregate.p99_ms" in reg)
    # Promotion stall blowup -> regression; stall DROP is not.
    c = str(tmp_path / "c.json")
    with open(c, "w") as f:
        json.dump(payload(1000.0, 400.0), f)
    rows = bench_diff.diff_payloads(
        bench_diff.load_payload(a), bench_diff.load_payload(c))
    reg = [r["name"] for r in rows if r["regression"]]
    assert reg == [
        "phase:serving_slo_fleet_paged:residency.promotion_stall_s"]
    d = str(tmp_path / "d.json")
    with open(d, "w") as f:
        json.dump(payload(1000.0, 50.0), f)
    rows = bench_diff.diff_payloads(
        bench_diff.load_payload(a), bench_diff.load_payload(d))
    assert not [r for r in rows if r["regression"]]


def test_legacy_fleet_unaffected_without_residency(days):
    """A FleetScorer with no residency manager keeps the exact PR 10
    behavior: every published tenant is stack-resident, no capacity
    padding, solo fallback never engages."""
    fleet = FleetRegistry()
    featurizers = {}
    for t in ("t0", "t1"):
        fleet.add_tenant(TenantSpec(tenant=t, dsource="dns"))
        fleet.publish(t, days[t][1], t)
        featurizers[t] = DnsEventFeaturizer(days[t][2])
    stack = fleet.stack_for("t0")
    assert stack.capacity == 0
    assert stack.model.theta.shape[0] == sum(
        days[t][1].theta.shape[0] for t in ("t0", "t1"))
    metrics = MetricsEmitter(to_stdout=False)
    scorer = FleetScorer(fleet, featurizers,
                         ServingConfig(device_score_min=None),
                         metrics=metrics)
    try:
        futs = [scorer.submit("t0", r) for r in days["t0"][0][:8]]
        scorer.flush()
        got = np.array([f.result(timeout=30.0)[0] for f in futs])
        np.testing.assert_array_equal(
            got, _expected(days, featurizers, "t0"))
        solo = [r for r in metrics.records
                if isinstance(r.get("stack_version"), type(None))
                and "tenant" in r and "events" in r]
        assert not solo
    finally:
        scorer.close()


# ---------------------------------------------------------------------------
# CLI: paged fleet serve over real day directories
# ---------------------------------------------------------------------------


def test_paged_fleet_live_stream_from_manifest(tmp_path, capsys):
    """`ml_ops serve --fleet m.json --hot-tenants 1 --warm-tenants 1`
    end to end: 3 day-dir tenants through ONE hot slot, so serving the
    tagged stream forces warm→hot promotions and (with the warm tier
    bounded) day-dir cold reloads — every event still scores
    exactly-once at version 1, and the stream_end record carries the
    residency ledger."""
    import pickle

    from oni_ml_tpu.features.dns import featurize_dns
    from oni_ml_tpu.io import formats
    from oni_ml_tpu.runner import ml_ops

    def write_day(path, rows, model):
        os.makedirs(path, exist_ok=True)
        ips = sorted(model.ip_index, key=model.ip_index.get)
        vocab = sorted(model.word_index, key=model.word_index.get)
        formats.write_doc_results(
            os.path.join(path, "doc_results.csv"), ips,
            model.theta[:-1])
        formats.write_word_results(
            os.path.join(path, "word_results.csv"), vocab,
            np.log(np.asarray(model.p[:-1], np.float64)).T)
        feats = featurize_dns(rows)
        with open(os.path.join(path, "features.pkl"), "wb") as f:
            pickle.dump(feats, f)

    manifest = {"tenants": []}
    input_lines = []
    for i, t in enumerate(("alpha", "beta", "gamma")):
        rows, model, _ = _synthetic_day(seed=70 + i)
        day = str(tmp_path / t)
        write_day(day, rows, model)
        manifest["tenants"].append(
            {"tenant": t, "day_dir": day, "dsource": "dns"})
        # Two visits per tenant, interleaved: the second visit pages
        # the tenant back IN after later tenants evicted it.
        input_lines.append([f"{t}\t" + ",".join(r) for r in rows[:12]])
    mpath = str(tmp_path / "fleet.json")
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    ipath = str(tmp_path / "events.csv")
    with open(ipath, "w") as f:
        for visit in range(2):
            for lines in input_lines:
                f.write("\n".join(lines[visit * 6:(visit + 1) * 6]))
                f.write("\n")
    rc = ml_ops.main([
        "serve", "--fleet", mpath, "--input", ipath, "--no-plans",
        "--no-compilation-cache", "--device-score-min", "0",
        "--max-batch", "6", "--hot-tenants", "1",
        "--warm-tenants", "1",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    end = next(json.loads(ln) for ln in out.splitlines()
               if '"stream_end"' in ln)
    assert end["submitted"] == 36
    assert end["events_scored"] == 36
    per_tenant = {s["tenant"]: s for s in end["tenant_stats"]}
    assert all(per_tenant[t]["scored"] == 12
               for t in ("alpha", "beta", "gamma"))
    # Paging never bumped a version.
    assert end["final_versions"] == {
        "alpha": 1, "beta": 1, "gamma": 1}
    resd = end["residency"]
    assert resd["hot_capacity"] == 1
    assert resd["promotions"] >= 3
    assert resd["failures"] == 0
    # The warm bound forced at least one tenant through checkpoint-
    # cold, reloaded from its DAY DIR (no spill: day_source set).
    assert resd["cold_loads"] >= 1
    assert resd["tiers"][TIER_HOT] == 1
    # The plans record names the resolved residency capacity.
    plans_rec = next(json.loads(ln) for ln in out.splitlines()
                     if '"event": "plans"' in ln)
    assert plans_rec["knobs"]["hot_tenants"]["value"] == 1
    assert plans_rec["knobs"]["hot_tenants"]["source"] == "config"


# ---------------------------------------------------------------------------
# review regressions: refresh-vs-cold staleness, publish-while-cold,
# never-published warm victims, unmanaged tenants
# ---------------------------------------------------------------------------


def _force_tenant_cold(fleet, mgr, tenant):
    """Drive one warm tenant to checkpoint-cold through the manager's
    own demotion path."""
    assert mgr.tier_of(tenant) == TIER_WARM
    mgr._demote_cold(tenant)
    assert mgr.tier_of(tenant) == TIER_COLD


def test_refreshed_model_survives_cold_demotion(days, tmp_path):
    """A day-dir tenant republished (refresh) and then paged cold must
    come back as the REFRESHED model at the refreshed version — never
    the stale day artifacts under the new version number (the silent
    wrong-score mode the review caught)."""
    from test_residency import _tiered_fleet  # self-import for clarity

    tenants = ("t0", "t1")
    fleet, mgr, featurizers, _, scorer = _tiered_fleet(
        days, tenants, hot=1, warm=4, spill_dir=str(tmp_path))
    try:
        # Re-register t0 as a day-dir tenant at its published version.
        with mgr._lock:
            st = mgr._state["t0"]
            st.day_source = (str(tmp_path / "nonexistent_day"), 0.1)
            st.day_version = fleet.version("t0")
        _score(scorer, days, "t0")
        # Refresh publish: version 2, different values.
        refreshed = ScoringModel(
            ip_index=days["t0"][1].ip_index,
            theta=days["t0"][1].theta.copy(),
            word_index=days["t0"][1].word_index,
            p=days["t0"][1].p.copy(),
        )
        rng = np.random.default_rng(9)
        refreshed.theta = refreshed.theta * rng.uniform(
            0.5, 1.5, refreshed.theta.shape)
        refreshed.theta[:-1] /= refreshed.theta[:-1].sum(
            1, keepdims=True)
        fleet.publish("t0", refreshed, "refresh")
        expected = None
        # Evict t0 (t1 takes the slot), then demote it cold: because
        # version 2 != day_version 1, the LIVE model must spill — the
        # stale day dir (which here doesn't even exist) is not
        # consulted.
        _score(scorer, days, "t1")
        assert mgr.tier_of("t0") == TIER_WARM
        fz = featurizers["t0"]
        feats = fz([fz.validate(r) for r in days["t0"][0][:8]])
        expected = score_features(refreshed, feats, "dns",
                                  device_min=None)
        _force_tenant_cold(fleet, mgr, "t0")
        with mgr._lock:
            assert mgr._state["t0"].cold_spilled is True
        got, versions = _score(scorer, days, "t0")
        np.testing.assert_array_equal(got, expected)
        assert versions == [2]
        assert mgr.stats_snapshot()["failures"] == 0
    finally:
        scorer.close()
        mgr.close()


def test_publish_while_cold_is_adopted(days, tmp_path):
    """A RefreshLoop publish landing while the tenant is checkpoint-
    cold must not wedge promotion: the pager adopts the newer
    published model instead of restoring over it."""
    tenants = ("t0", "t1")
    fleet, mgr, featurizers, _, scorer = _tiered_fleet(
        days, tenants, hot=1, warm=4, spill_dir=str(tmp_path))
    try:
        _score(scorer, days, "t0")
        _score(scorer, days, "t1")          # t0 -> warm
        _force_tenant_cold(fleet, mgr, "t0")
        # Publish while cold (registry version bumps, model loaded).
        fleet.publish("t0", days["t0"][1], "refresh-while-cold")
        got, versions = _score(scorer, days, "t0")
        np.testing.assert_array_equal(
            got, _expected(days, featurizers, "t0"))
        assert versions == [2]
        assert mgr.tier_of("t0") == TIER_HOT
        assert mgr.stats_snapshot()["failures"] == 0
    finally:
        scorer.close()
        mgr.close()


def test_never_published_warm_tenant_does_not_wedge_pager(days):
    """A registered-but-never-published tenant over the warm bound has
    nothing to unload: the enforcement sweep must skip it and return
    (the review caught an infinite pager spin here), and the pager
    stays live for real promotions."""
    fleet, mgr, featurizers, _, scorer = _tiered_fleet(
        days, ("t0", "t1"), hot=1, warm=1)
    try:
        fleet.add_tenant(TenantSpec(tenant="ghost", dsource="dns"),
                         hot=False)
        mgr.register("ghost")               # never published
        mgr._post_enforce()                 # would previously spin
        time.sleep(0.1)
        # Pager still processes promotions afterwards.
        got, _ = _score(scorer, days, "t0")
        np.testing.assert_array_equal(
            got, _expected(days, featurizers, "t0"))
        assert mgr.tier_of("ghost") == TIER_WARM
        assert mgr._pager.is_alive()
    finally:
        scorer.close()
        mgr.close()


def test_unmanaged_tenant_drains_promptly_with_residency(days):
    """A fleet tenant never registered with the residency manager
    keeps legacy always-drainable behavior — its events must resolve
    without waiting for shutdown."""
    rec = Recorder()
    fleet = FleetRegistry(recorder=rec, capacity_tiers=True)
    mgr = ResidencyManager(fleet, hot_capacity=1, recorder=rec)
    featurizers = {}
    # t0 managed (starts warm), t1 unmanaged and stack-resident.
    fleet.add_tenant(TenantSpec(tenant="t0", dsource="dns"), hot=False)
    fleet.publish("t0", days["t0"][1], "t0")
    mgr.register("t0")
    featurizers["t0"] = DnsEventFeaturizer(days["t0"][2])
    fleet.add_tenant(TenantSpec(tenant="t1", dsource="dns"))
    fleet.publish("t1", days["t1"][1], "t1")
    featurizers["t1"] = DnsEventFeaturizer(days["t1"][2])
    metrics = MetricsEmitter(to_stdout=False, recorder=rec)
    scorer = FleetScorer(fleet, featurizers,
                         ServingConfig(device_score_min=None),
                         metrics=metrics, residency=mgr)
    try:
        futs = [scorer.submit("t1", r) for r in days["t1"][0][:8]]
        scorer.flush()
        got = np.array([f.result(timeout=5.0)[0] for f in futs])
        fz = featurizers["t1"]
        feats = fz([fz.validate(r) for r in days["t1"][0][:8]])
        np.testing.assert_array_equal(
            got, score_features(days["t1"][1], feats, "dns",
                                device_min=None))
    finally:
        scorer.close()
        mgr.close()
