"""Continuous ingestion: the ring-buffered corpus window, warm-start
EM, the drift-gated publish path, and the day-replay/bench plumbing.

The two contracts this file pins hardest:

* window id discipline — word ids are window-global, first-seen, and
  survive eviction, which is what makes warm-started beta rows mean
  the same words refresh-over-refresh;
* the publish gate — a deliberately drifted window produces
  `publish_gate: vetoed`, the fleet keeps serving the PRIOR version
  with bit-identical scores through the vetoed refresh, and a
  recovered window (drifted chunks evicted) publishes again.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

from oni_ml_tpu.config import (  # noqa: E402
    ContinuousConfig,
    LDAConfig,
    OnlineLDAConfig,
    PipelineConfig,
)
from oni_ml_tpu.dataplane import CorpusWindow, pow2_capacity  # noqa: E402
from oni_ml_tpu.io import Corpus  # noqa: E402
from oni_ml_tpu.models import (  # noqa: E402
    DriftDetector,
    OnlineLDATrainer,
    WindowTrainer,
    warm_start_log_beta,
)
from oni_ml_tpu.runner.continuous import (  # noqa: E402
    ContinuousService,
    IngestSlice,
    paced_slices,
    slice_events,
)


# ---------------------------------------------------------------------------
# CorpusWindow
# ---------------------------------------------------------------------------


def _triples(rng, ips, words, n):
    return [
        (str(rng.choice(ips)), str(rng.choice(words)),
         int(rng.integers(1, 5)))
        for _ in range(n)
    ]


def test_window_snapshot_matches_batch_corpus():
    """A window that evicted nothing assembles the same corpus the
    batch path would build from the concatenated triples (modulo the
    pow2 vocab padding tail)."""
    rng = np.random.default_rng(0)
    ips = [f"ip{i}" for i in range(12)]
    words = [f"w{i}" for i in range(20)]
    # Unique (ip, word) pairs per stream so aggregation can't differ.
    all_trips = []
    seen = set()
    for t in _triples(rng, ips, words, 400):
        if (t[0], t[1]) in seen:
            continue
        seen.add((t[0], t[1]))
        all_trips.append(t)
    w = CorpusWindow(1e9, vocab_floor=4)
    third = len(all_trips) // 3
    w.ingest_triples(all_trips[:third], 0, 100)
    w.ingest_triples(all_trips[third:2 * third], 100, 200)
    w.ingest_triples(all_trips[2 * third:], 200, 300)
    snap = w.snapshot()
    batch = Corpus.from_word_counts(all_trips)
    assert snap.real_vocab == batch.num_terms
    assert snap.corpus.vocab[:snap.real_vocab] == batch.vocab
    assert snap.corpus.doc_names == batch.doc_names
    np.testing.assert_array_equal(snap.corpus.doc_ptr, batch.doc_ptr)
    np.testing.assert_array_equal(snap.corpus.word_idx, batch.word_idx)
    np.testing.assert_array_equal(snap.corpus.counts, batch.counts)
    # pow2 capacity tier with inert pad words.
    assert snap.vocab_capacity == pow2_capacity(batch.num_terms, 4)
    assert snap.corpus.num_terms == snap.vocab_capacity
    assert all(v.startswith("__pad")
               for v in snap.corpus.vocab[snap.real_vocab:])


def test_window_duplicate_pairs_aggregate_across_chunks():
    w = CorpusWindow(1e9, vocab_floor=4)
    w.ingest_triples([("a", "x", 2), ("a", "y", 1)], 0, 10)
    w.ingest_triples([("a", "x", 3), ("b", "x", 1)], 10, 20)
    snap = w.snapshot()
    c = snap.corpus
    assert c.doc_names == ["a", "b"]
    # Doc a: x summed 2+3, y once.
    a_words = {
        c.vocab[int(c.word_idx[j])]: int(c.counts[j])
        for j in range(int(c.doc_ptr[0]), int(c.doc_ptr[1]))
    }
    assert a_words == {"x": 5, "y": 1}


def test_window_vocab_ids_survive_eviction():
    """First-seen word ids are window-global: eviction retires counts,
    never ids — the warm-start contract."""
    w = CorpusWindow(100.0, vocab_floor=4)
    w.ingest_triples([("a", f"w{i}", 1) for i in range(10)], 0, 50)
    ids_before = dict(w._words.ids)
    rec = w.advance(200.0)        # horizon 100 evicts the chunk
    assert rec["evicted_chunks"] == 1 and w.live_chunks == 0
    w.ingest_triples([("b", f"w{i}", 1) for i in range(5, 15)], 150, 200)
    for i in range(5, 10):
        assert w._words.ids[f"w{i}"] == ids_before[f"w{i}"]
    assert w.vocab_size == 15     # grew, never shrank
    snap = w.snapshot()
    # Evicted words keep their (zero-count) vocab slots.
    assert snap.real_vocab == 15
    assert snap.corpus.doc_names == ["b"]


def test_window_advance_is_o_evicted_and_journaled():
    records = []

    class _J:
        def append(self, rec):
            records.append(rec)

    w = CorpusWindow(100.0, vocab_floor=4, journal=_J())
    for i in range(5):
        w.ingest_triples([("a", "x", 1)], i * 50, i * 50 + 50)
    rec = w.advance(300.0)        # horizon 200: evicts spans ending <= 200
    assert rec["kind"] == "window_advance"
    assert rec["evicted_chunks"] == 4 and rec["chunks"] == 1
    assert w.evicted_rows == 4
    assert records and records[-1]["kind"] == "window_advance"
    assert "advance_s" in records[-1]


def test_window_rejects_stream_disorder():
    w = CorpusWindow(100.0)
    w.ingest_triples([("a", "x", 1)], 0, 50)
    with pytest.raises(ValueError, match="stream order"):
        w.ingest_triples([("a", "y", 1)], 0, 40)
    with pytest.raises(ValueError, match="inverted"):
        w.ingest_triples([("a", "y", 1)], 90, 80)


def test_pow2_capacity_tiers():
    assert pow2_capacity(3, 8) == 8
    assert pow2_capacity(9, 8) == 16
    assert pow2_capacity(16, 8) == 16
    assert pow2_capacity(1000, 8) == 1024


# ---------------------------------------------------------------------------
# Warm-start seeding (batch EM + online SVI vocab growth)
# ---------------------------------------------------------------------------


def test_warm_start_log_beta_pads_from_symmetric_prior():
    rng = np.random.default_rng(1)
    k, v0, v1 = 4, 10, 16
    p = rng.dirichlet(np.ones(v0), size=k).T       # [V0, K]
    lb = warm_start_log_beta(p, v1)
    assert lb.shape == (k, v1)
    beta = np.exp(lb)
    np.testing.assert_allclose(beta.sum(axis=1), 1.0, rtol=1e-12)
    # Old words keep their relative proportions; new words carry small
    # but trainable mass (never the LOG_ZERO floor).
    ratio = beta[:, :v0] / p.T
    np.testing.assert_allclose(
        ratio, np.broadcast_to(ratio[:, :1], ratio.shape), rtol=1e-9
    )
    assert (beta[:, v0:] > 0).all()
    assert (lb[:, v0:] > -200).all()


def test_warm_start_log_beta_refuses_shrink():
    p = np.full((8, 4), 1.0 / 8)
    with pytest.raises(ValueError, match="shrink"):
        warm_start_log_beta(p, 4)


def test_from_topic_probs_accepts_grown_vocabulary():
    """Satellite regression: day N words absent from day N−1 pad new
    lambda rows from the symmetric prior instead of erroring."""
    rng = np.random.default_rng(2)
    cfg = OnlineLDAConfig(num_topics=3)
    v0, v1 = 12, 20
    p = rng.dirichlet(np.ones(v0), size=3).T
    tr = OnlineLDATrainer.from_topic_probs(
        cfg, p, total_docs=100, num_terms=v1
    )
    lam = np.asarray(tr.lam)
    assert lam.shape == (3, v1)
    # Grown rows: prior-only lambda (p contributed nothing).
    np.testing.assert_allclose(lam[:, v0:], cfg.eta, rtol=1e-5)
    # Old rows still encode the seeded topics.
    assert (lam[:, :v0] > cfg.eta).any()
    with pytest.raises(ValueError, match="SHRINK"):
        OnlineLDATrainer.from_topic_probs(
            cfg, p, total_docs=100, num_terms=v0 - 1
        )


def _structured_corpus(rng, docs=60, v=64, k=3, tokens=30):
    """Topic-structured synthetic corpus: documents draw words from one
    of k disjoint vocabulary blocks (plus noise), so EM has real
    structure to find and warm starts have something to preserve."""
    trips = []
    block = v // k
    for d in range(docs):
        t = d % k
        for _ in range(tokens):
            if rng.random() < 0.9:
                wid = t * block + int(rng.integers(0, block))
            else:
                wid = int(rng.integers(0, v))
            trips.append((f"ip{d}", f"w{wid}", 1))
    return Corpus.from_word_counts(trips)


def test_window_trainer_warm_start_saves_iterations():
    """Warm-started EM early-exits on the existing f64 convergence
    check in a fraction of the fresh fit's iterations, at matched
    held-out likelihood — the streaming_freshness bench's claim, at
    test scale."""
    rng = np.random.default_rng(3)
    corpus = _structured_corpus(rng)
    cfg = LDAConfig(num_topics=3, batch_size=64, fused_em_chunk=1,
                    em_max_iters=60, seed=1)
    tr = WindowTrainer(cfg, corpus.num_terms)
    fresh = tr.fit(corpus)
    assert fresh.plan["warm_start"]["value"] is False
    probs = np.exp(fresh.log_beta).T       # [V, K]
    warm = tr.fit(corpus, topic_probs=probs, alpha=fresh.alpha)
    assert warm.plan["warm_start"]["value"] is True
    assert warm.em_iters < fresh.em_iters / 2
    det = DriftDetector(tol_nats=0.5)
    ll_fresh, _ = det.evaluate(fresh.log_beta, fresh.alpha, corpus,
                               holdout_frac=0.3)
    ll_warm, _ = det.evaluate(warm.log_beta, warm.alpha, corpus,
                              holdout_frac=0.3)
    assert abs(ll_warm - ll_fresh) < 0.5   # matched within drift tol


def test_window_trainer_rejects_wrong_tier():
    cfg = LDAConfig(num_topics=3, fused_em_chunk=1)
    tr = WindowTrainer(cfg, 64)
    corpus = _structured_corpus(np.random.default_rng(4), docs=10, v=32)
    with pytest.raises(ValueError, match="capacity tier"):
        tr.fit(corpus)


# ---------------------------------------------------------------------------
# DriftDetector
# ---------------------------------------------------------------------------


def test_drift_detector_flags_regression_and_recovers():
    records = []

    class _J:
        def append(self, rec):
            records.append(rec)

    d = DriftDetector(tol_nats=0.3, min_history=2, journal=_J())
    for ll in (-4.0, -4.05, -3.95):
        dec = d.check(ll)
        assert not dec.drifted
    assert d.baseline is not None
    bad = d.check(-5.0)
    assert bad.drifted and bad.delta < -0.3
    assert d.mode == "fresh"               # next refresh trains fresh
    # The drifted value did NOT poison the baseline.
    good = d.check(-4.02)
    assert not good.drifted
    assert d.mode == "warm"
    checks = [r for r in records if r["kind"] == "drift_check"]
    assert len(checks) == 5
    assert checks[3]["drifted"] is True
    assert checks[3]["baseline_ll"] is not None
    # Gate: veto on drift, publish otherwise — both journaled.
    assert d.gate(bad, version=7, tenant="t") is False
    assert d.gate(good, version=7, tenant="t") is True
    gates = [r for r in records if r["kind"] == "publish_gate"]
    assert [g["action"] for g in gates] == ["vetoed", "published"]
    assert d.vetoes == 1 and d.publishes == 1


def test_drift_detector_primes_from_journal():
    d = DriftDetector(tol_nats=0.3, min_history=2)
    replayed = [
        {"kind": "drift_check", "ll": -4.0, "drifted": False},
        {"kind": "drift_check", "ll": -4.1, "drifted": False},
        {"kind": "drift_check", "ll": -9.0, "drifted": True},
        {"kind": "other"},
    ]
    assert d.prime(replayed) == 2          # drifted checks excluded
    assert d.baseline == pytest.approx(-4.05)


# ---------------------------------------------------------------------------
# Slicing / pacing
# ---------------------------------------------------------------------------


def _flow_line(rng, sip, dip, dport, h=None):
    h = int(rng.integers(0, 24)) if h is None else h
    return (
        "2016-01-22 00:00:00,2016,1,22,"
        f"{h},{int(rng.integers(0, 60))},{int(rng.integers(0, 60))},0.0,"
        f"{sip},{dip},{int(rng.integers(1024, 60000))},{dport},TCP,,0,0,"
        f"{int(rng.integers(1, 100))},{int(rng.integers(40, 100000))},"
        "0,0,0,0,0,0,0,0,0"
    )


def test_slice_events_orders_and_buckets():
    def line(h):
        return ("2016-01-22 00:00:00,2016,1,22,"
                f"{h},0,0,0.0,10.0.0.1,10.1.0.1,4000,80,TCP,,0,0,"
                "5,400,0,0,0,0,0,0,0,0,0")

    lines = [line(h) for h in (3, 0, 2, 1, 0, 3)]
    slices = slice_events(lines, "flow", 3600.0)
    assert [s.events for s in slices] == [2, 1, 1, 2]
    assert all(s.t1 - s.t0 == 3600.0 for s in slices)
    lasts = [max(int(ln.split(",")[4]) for ln in s.lines)
             for s in slices]
    assert lasts == sorted(lasts)


def test_slice_events_skips_header_and_garbage():
    """Real reference day files carry a header row (featurize_flow
    strips it); the slicer must skip unparsable lines, not crash."""
    def line(h):
        return ("2016-01-22 00:00:00,2016,1,22,"
                f"{h},0,0,0.0,10.0.0.1,10.1.0.1,4000,80,TCP,,0,0,"
                "5,400,0,0,0,0,0,0,0,0,0")

    lines = ["treceived,unix_tsecs,year,month,hour\n",  # header
             line(0), "garbage,row\n", line(1), "\n"]
    slices = slice_events(lines, "flow", 3600.0)
    assert sum(s.events for s in slices) == 2


def test_paced_slices_stamps_arrivals():
    slices = [IngestSlice(lines=["x"], t0=0, t1=600, index=0),
              IngestSlice(lines=["y"], t0=600, t1=1200, index=1)]
    out = list(paced_slices(slices, float("inf")))
    assert all(s.arrival_wall > 0 for s in out)
    assert out[1].arrival_wall >= out[0].arrival_wall


# ---------------------------------------------------------------------------
# The drift-gated continuous service (the pinned veto regression)
# ---------------------------------------------------------------------------


def _service(tmp_path, **cc_kw):
    import dataclasses

    config = PipelineConfig(
        data_dir=str(tmp_path),
        continuous=ContinuousConfig(
            window_s=1800.0, refresh_every_s=1200.0,
            min_refresh_docs=8, drift_tol_nats=0.8,
            drift_min_history=2, vocab_floor=512, batch_size=64,
            holdout_frac=0.3, **cc_kw,
        ),
    )
    config = dataclasses.replace(
        config,
        lda=dataclasses.replace(config.lda, num_topics=4,
                                em_max_iters=30),
    )
    return ContinuousService(
        config, "flow", out_dir=str(tmp_path / "cont"),
        warmup_refreshes=2,
    )


def _normal_slice(rng, idx, n=220):
    ports = (80, 443, 22, 53)
    lines = [
        _flow_line(rng, f"10.0.0.{int(rng.integers(0, 24))}",
                   f"10.1.0.{int(rng.integers(0, 12))}",
                   ports[int(rng.integers(0, len(ports)))])
        for _ in range(n)
    ]
    return IngestSlice(lines=lines, t0=idx * 600.0,
                       t1=(idx + 1) * 600.0, index=idx)


def _drifted_slice(rng, idx, n=220):
    """High-entropy word space: uniform-random service ports explode
    the vocabulary, so the window's model scores its own held-out docs
    nats worse than the healthy baseline."""
    lines = [
        _flow_line(rng, f"10.0.0.{int(rng.integers(0, 24))}",
                   f"10.1.0.{int(rng.integers(0, 12))}",
                   int(rng.integers(1, 60000)))
        for _ in range(n)
    ]
    return IngestSlice(lines=lines, t0=idx * 600.0,
                       t1=(idx + 1) * 600.0, index=idx)


def test_drift_gate_vetoes_and_fleet_serves_prior_bits(tmp_path):
    """THE acceptance pin: a deliberately drifted window produces
    `publish_gate: vetoed`, the fleet keeps scoring BIT-IDENTICALLY on
    the prior version through the vetoed refresh, and a recovered
    window (drifted chunks evicted) publishes again."""
    from oni_ml_tpu.serving.events import score_features

    rng = np.random.default_rng(7)
    svc = _service(tmp_path)
    try:
        idx = 0
        # Healthy stream: slices 0..5, refreshes at slice 1, 3, 5.
        for _ in range(6):
            svc.ingest_slice(_normal_slice(rng, idx))
            svc.maybe_refresh(idx * 600.0 + 600.0)
            idx += 1
        assert svc.drift.publishes >= 2
        assert svc.drift.vetoes == 0
        v_before = svc.fleet.version(svc.tenant)
        snap_before = svc.fleet.active(svc.tenant)

        # Fixed probe set scored under the serving model, both through
        # the model directly (f64 host path) and the FleetScorer.
        probe = [_flow_line(rng, "10.0.0.1", "10.1.0.2", 80)
                 for _ in range(16)]
        feats = svc.scorer._lanes[svc.tenant].featurizer(probe)
        scores_before = score_features(snap_before.model, feats, "flow")
        futs = [svc.scorer.submit(svc.tenant, ln) for ln in probe]
        svc.scorer.flush()
        resolved = [f.result(timeout=30.0) for f in futs]
        served_before = np.asarray([s for s, _ in resolved], np.float64)
        assert all(v == v_before for _, v in resolved)

        # Drifted window: two high-entropy slices fill the 3-slice
        # window past the next refresh boundary.
        for _ in range(2):
            svc.ingest_slice(_drifted_slice(rng, idx))
            svc.maybe_refresh(idx * 600.0 + 600.0)
            idx += 1
        assert svc.drift.vetoes >= 1
        # The fleet never saw the drifted model: same version, same
        # snapshot object, bit-identical scores on the probe set.
        assert svc.fleet.version(svc.tenant) == v_before
        snap_after = svc.fleet.active(svc.tenant)
        assert snap_after.version == snap_before.version
        scores_after = score_features(snap_after.model, feats, "flow")
        np.testing.assert_array_equal(scores_before, scores_after)
        futs = [svc.scorer.submit(svc.tenant, ln) for ln in probe]
        svc.scorer.flush()
        resolved = [f.result(timeout=30.0) for f in futs]
        served_after = np.asarray([s for s, _ in resolved], np.float64)
        # Still the PRIOR version, bit-identical scores, through the
        # serving path itself.
        assert all(v == v_before for _, v in resolved)
        np.testing.assert_array_equal(served_before, served_after)

        # Recovery: healthy slices age the drifted chunks out of the
        # 1800 s window; the next refresh publishes.
        for _ in range(6):
            svc.ingest_slice(_normal_slice(rng, idx))
            svc.maybe_refresh(idx * 600.0 + 600.0)
            idx += 1
        assert svc.fleet.version(svc.tenant) > v_before
        # Journal carries the full verdict trail.
        payload = svc.close()
        jpath = tmp_path / "cont" / "run_journal.jsonl"
        records = [json.loads(ln) for ln in open(jpath)]
        gates = [r for r in records if r.get("kind") == "publish_gate"]
        assert any(g["action"] == "vetoed" for g in gates)
        assert any(g["action"] == "published" for g in gates)
        # Recovered: the LAST gate decision, on a clean window, is a
        # publish (vetoes during the transition — drifted chunks still
        # in-window — are the detector doing its job).
        assert gates[-1]["action"] == "published"
        checks = [r for r in records if r.get("kind") == "drift_check"]
        assert any(c["drifted"] for c in checks)
        assert any(r.get("kind") == "window_advance" for r in records)
        assert payload["vetoes"] >= 1
        assert payload["publishes"] >= 3
        assert payload["freshness_samples"] > 0
    finally:
        if svc.scorer is not None:
            svc.close()


def test_continuous_run_end_to_end_payload(tmp_path):
    """Smoke the run()/close() path: a short healthy stream produces a
    payload with freshness quantiles, warm/fresh fit stats, and the
    metrics file + journal on disk."""
    rng = np.random.default_rng(11)
    svc = _service(tmp_path)
    slices = [_normal_slice(rng, i, n=180) for i in range(5)]
    # A malformed event in a post-publish slice must be SHED (counted),
    # never kill the standing service.
    slices[4].lines.append("not,a,flow,line")
    payload = svc.run(paced_slices(slices, float("inf")))
    assert payload["slices"] == 5
    assert payload["publishes"] >= 1
    assert payload["events_rejected"] == 1
    # The scored product sink exists: flagged events stream to disk.
    assert os.path.exists(
        os.path.join(str(tmp_path / "cont"), "flagged_events.jsonl")
    )
    assert "flagged" in payload
    assert payload["freshness_p50_s"] is not None
    assert payload["freshness_event_p50_min"] > 0
    assert payload["warm"]["fits"] + payload["fresh"]["fits"] \
        == payload["refreshes"]
    assert os.path.exists(
        os.path.join(str(tmp_path / "cont"), "continuous_metrics.json")
    )
    assert os.path.exists(
        os.path.join(str(tmp_path / "cont"), "run_journal.jsonl")
    )


def test_day_replay_cli_smoke(tmp_path):
    """tools/day_replay.py end-to-end on a tiny synthetic day."""
    import day_replay

    rng = np.random.default_rng(13)
    day = tmp_path / "day.csv"
    with open(day, "w") as f:
        for h in range(4):
            for _ in range(160):
                f.write(_flow_line(rng, f"10.0.0.{int(rng.integers(0, 32))}",
                                   f"10.1.0.{int(rng.integers(0, 16))}",
                                   80, h=h) + "\n")
    rc = day_replay.main([
        str(day), "--dsource", "flow", "--slice-s", "3600",
        "--no-sleep", "--window-s", "7200", "--refresh-s", "3600",
        "--out-dir", str(tmp_path / "cont"),
    ])
    assert rc == 0
    payload = json.load(open(tmp_path / "cont"
                             / "continuous_metrics.json"))
    assert payload["slices"] == 4
    assert payload["refreshes"] >= 2
    assert payload["events"] == 640


# ---------------------------------------------------------------------------
# bench_diff direction keys
# ---------------------------------------------------------------------------


def _stream_payload(**over):
    base = {
        "freshness_p50_s": 1.0, "freshness_p99_s": 5.0,
        "freshness_event_p50_min": 15.0, "freshness_event_p99_min": 30.0,
        "warm_start_speedup": 4.0, "held_out_ll": -5.0,
    }
    base.update(over)
    return base


def test_bench_diff_streaming_direction_keys():
    import bench_diff

    old = {"metric": "m", "value": 1.0, "unit": "x",
           "secondary": {"streaming_freshness": _stream_payload()}}

    def rows_for(**over):
        new = {"metric": "m", "value": 1.0, "unit": "x",
               "secondary": {
                   "streaming_freshness": _stream_payload(**over)}}
        return bench_diff.diff_payloads(old, new)

    # Freshness latency UP -> regression (lower-better).
    rows = rows_for(freshness_p99_s=10.0)
    assert any(r["regression"] and "freshness_p99_s" in r["name"]
               for r in rows)
    # Event-time freshness UP -> regression.
    rows = rows_for(freshness_event_p50_min=40.0)
    assert any(r["regression"]
               and "freshness_event_p50_min" in r["name"] for r in rows)
    # Warm-start speedup DOWN -> regression (higher-better).
    rows = rows_for(warm_start_speedup=1.1)
    assert any(r["regression"] and "warm_start_speedup" in r["name"]
               for r in rows)
    # Held-out LL: absolute drop beyond the nats budget -> regression;
    # small wobble -> clean.
    rows = rows_for(held_out_ll=-5.6)
    assert any(r["regression"] and "held_out_ll" in r["name"]
               for r in rows)
    rows = rows_for(held_out_ll=-5.1)
    assert not any(r["regression"] and "held_out_ll" in r["name"]
                   for r in rows)
    # Improvements in every direction -> no streaming regressions.
    rows = rows_for(freshness_p50_s=0.5, freshness_p99_s=2.0,
                    freshness_event_p50_min=10.0,
                    freshness_event_p99_min=20.0,
                    warm_start_speedup=6.0, held_out_ll=-4.8)
    assert not any(r["regression"] for r in rows
                   if "streaming" in r["name"])


def test_bench_diff_streaming_headline_form():
    import bench_diff

    old = _stream_payload()
    new = _stream_payload(freshness_p50_s=3.0)
    rows = bench_diff.diff_payloads(old, new)
    assert any(r["regression"] and r["name"] == "headline.freshness_p50_s"
               for r in rows)


# ---------------------------------------------------------------------------
# trace_view lanes
# ---------------------------------------------------------------------------


def test_trace_view_renders_continuous_records():
    import trace_view

    records = [
        {"kind": "window_advance", "mono_ns": 1000, "chunks": 3,
         "rows": 500, "vocab": 200, "evicted_chunks": 1,
         "evicted_rows": 50},
        {"kind": "drift_check", "mono_ns": 2000, "ll": -5.0,
         "baseline_ll": -4.8, "delta": -0.2, "drifted": False},
        {"kind": "drift_check", "mono_ns": 3000, "ll": -7.0,
         "baseline_ll": -4.8, "delta": -2.2, "drifted": True},
        {"kind": "publish_gate", "mono_ns": 4000, "action": "vetoed",
         "version": 3, "ll": -7.0},
        {"kind": "publish_gate", "mono_ns": 5000,
         "action": "published", "version": 4, "ll": -4.9},
        {"kind": "freshness", "mono_ns": 6000, "slices": 2,
         "wall_max_s": 1.5, "event_max_s": 900.0},
    ]
    trace = trace_view.journal_to_trace(records)
    names = [e["name"] for e in trace["traceEvents"]]
    assert "window occupancy" in names
    assert "window evict" in names
    assert "drift held-out ll" in names
    assert "DRIFT" in names
    assert "publish VETOED" in names
    assert "publish gate: published" in names
    assert "freshness max" in names
    table = trace_view.continuous_table(records)
    assert table["published"] == 1 and table["vetoed"] == 1
    assert table["drifts"] == 1
    assert table["worst_freshness_s"] == 1.5
