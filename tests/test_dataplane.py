"""Streaming dataplane (oni_ml_tpu/dataplane/): channel semantics,
columnar hand-off typing, streaming corpus assembly, EM-overlapped
scoring prep, checkpoint demotion — and the contract the whole package
exists to keep: every artifact byte-identical to the serial
file-contract path, with resume/fail-fast semantics preserved.
"""

import hashlib
import json
import os
import pickle
import sys
import threading
import time

import numpy as np
import pytest

from oni_ml_tpu.config import (
    DataplaneConfig,
    FeedbackConfig,
    LDAConfig,
    PipelineConfig,
    ScoringConfig,
)
from oni_ml_tpu.dataplane import (
    Channel,
    ChannelClosed,
    ChannelError,
    Column,
    ColumnSet,
    StreamingCorpusBuilder,
    atomic_write_bytes,
    build_scoring_prep,
    clear_stale,
    intern_word_counts,
    stream_word_counts,
    word_count_columns,
)
from oni_ml_tpu.dataplane.sinks import CheckpointSinks, Task
from oni_ml_tpu.io import Corpus
from oni_ml_tpu.runner import MissingArtifactError, Stage, run_pipeline
from oni_ml_tpu.telemetry import Journal

from test_features import flow_row

HERE = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# Channel
# ---------------------------------------------------------------------------


def test_channel_fifo_and_close():
    ch = Channel("t", capacity=8)
    for i in range(5):
        ch.put(i)
    ch.close()
    assert list(ch) == [0, 1, 2, 3, 4]
    with pytest.raises(ChannelClosed):
        ch.get()
    with pytest.raises(ValueError):
        ch.put(99)  # put after close is a producer bug


def test_channel_bounded_backpressure():
    """A producer can run at most `capacity` items ahead; its put()
    blocks (and the stall is accounted) until the consumer drains."""
    ch = Channel("t", capacity=2)
    done = threading.Event()

    def producer():
        for i in range(6):
            ch.put(i)
        ch.close()
        done.set()

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.05)  # let the producer hit the bound
    assert not done.is_set()  # blocked at capacity, not buffering all 6
    assert list(ch) == list(range(6))
    t.join()
    st = ch.stats()
    assert st["puts"] == 6 and st["gets"] == 6
    assert st["max_depth"] <= 2
    assert st["put_stall_s"] > 0  # the blocked window was priced


def test_channel_producer_failure_poisons_consumer():
    ch = Channel("t", capacity=4)
    ch.put(1)
    ch.fail(RuntimeError("boom"))
    assert ch.get() == 1  # buffered items drain first
    with pytest.raises(ChannelError) as ei:
        ch.get()
    assert "producer failed" in str(ei.value)
    assert isinstance(ei.value.__cause__, RuntimeError)


def test_channel_consumer_failure_unblocks_producer():
    ch = Channel("t", capacity=1)
    ch.put(0)  # fill to capacity
    errs = []

    def producer():
        try:
            ch.put(1)  # blocks on the bound
        except ChannelError as e:
            errs.append(e)

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.05)
    ch.fail(RuntimeError("consumer died"))
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert len(errs) == 1 and "consumer failed" in str(errs[0])


def test_channel_journals_depth_and_stalls(tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    with Journal(jpath) as j:
        ch = Channel("pre.wc->corpus", capacity=4, journal=j)
        ch.put("a")
        ch.put("b")
        ch.get()
        ch.close()
    recs = [r for r in Journal.replay(jpath) if r["kind"] == "dataplane"]
    assert [r["event"] for r in recs] == ["depth"] * 3
    assert [(r["side"], r["depth"]) for r in recs] == [
        ("put", 1), ("put", 2), ("get", 1)
    ]
    assert all(r["edge"] == "pre.wc->corpus" for r in recs)


# ---------------------------------------------------------------------------
# Columns
# ---------------------------------------------------------------------------


def test_column_dtype_kind_enforced():
    with pytest.raises(TypeError, match="declared dtype kind"):
        Column("doc_id", np.zeros(3, np.float64), "i")
    with pytest.raises(TypeError, match="1-D numpy array"):
        Column("doc_id", np.zeros((3, 2), np.int32), "i")


def test_columnset_validation_and_chunks():
    with pytest.raises(ValueError, match="rows"):
        ColumnSet([Column("a", np.zeros(3, np.int32)),
                   Column("b", np.zeros(4, np.int32))])
    with pytest.raises(ValueError, match="duplicate"):
        ColumnSet([Column("a", np.zeros(3, np.int32)),
                   Column("a", np.zeros(3, np.int32))])
    cs = ColumnSet([Column("a", np.arange(10, dtype=np.int32))])
    chunks = list(cs.chunks(4))
    assert [c.num_rows for c in chunks] == [4, 4, 2]
    np.testing.assert_array_equal(
        np.concatenate([c["a"] for c in chunks]), cs["a"]
    )
    # Row windows are views, not copies: chunking a day allocates nothing.
    assert chunks[0]["a"].base is not None


def test_intern_word_counts_matches_from_word_counts():
    triples = [("10.0.0.2", "w_b", 3), ("10.0.0.1", "w_a", 1),
               ("10.0.0.2", "w_a", 2), ("10.0.0.3", "w_c", 5),
               ("10.0.0.1", "w_b", 4)]
    wc = intern_word_counts(triples)
    ref = Corpus.from_word_counts(triples)
    built = StreamingCorpusBuilder()
    built.add_arrays(wc.ids["doc_id"], wc.ids["word_id"], wc.ids["count"])
    got = built.finish(wc.ip_table, wc.word_table)
    assert got.doc_names == ref.doc_names
    assert got.vocab == ref.vocab
    np.testing.assert_array_equal(got.doc_ptr, ref.doc_ptr)
    np.testing.assert_array_equal(got.word_idx, ref.word_idx)
    np.testing.assert_array_equal(got.counts, ref.counts)


# ---------------------------------------------------------------------------
# Streaming corpus assembly == batch assembly, every chunking
# ---------------------------------------------------------------------------


def _flow_features(tmp_path, n=80, seed=11):
    from oni_ml_tpu.features.native_flow import featurize_flow_file

    rng = np.random.default_rng(seed)
    lines = ["dummy,header"]
    for _ in range(n):
        lines.append(flow_row(
            hour=int(rng.integers(0, 24)), minute=int(rng.integers(0, 60)),
            second=int(rng.integers(0, 60)),
            sip=f"10.0.0.{rng.integers(1, 12)}",
            dip=f"172.16.0.{rng.integers(1, 12)}",
            col10=str(rng.choice([80, 443, 55000, 0])),
            col11=str(rng.choice([80, 6000, 70000])),
            ipkt=str(rng.integers(1, 100)),
            ibyt=str(rng.integers(40, 10000)),
        ))
    raw = tmp_path / "flow.csv"
    raw.write_text("\n".join(lines) + "\n")
    return featurize_flow_file(str(raw))


def _corpora_equal(a: Corpus, b: Corpus):
    assert a.doc_names == b.doc_names
    assert a.vocab == b.vocab
    np.testing.assert_array_equal(a.doc_ptr, b.doc_ptr)
    np.testing.assert_array_equal(a.word_idx, b.word_idx)
    np.testing.assert_array_equal(a.counts, b.counts)


@pytest.mark.parametrize("chunk_rows", [1, 7, 64, 1 << 18])
def test_streamed_corpus_identical_to_batch(tmp_path, chunk_rows):
    """First-seen order over a sequentially consumed chunk stream is
    first-seen order over the concatenation: the streamed corpus equals
    Corpus.from_features whatever the chunking."""
    features = _flow_features(tmp_path)
    ref = Corpus.from_features(features)
    wc = word_count_columns(features)
    ch = Channel("e", capacity=2)
    t = threading.Thread(
        target=stream_word_counts, args=(wc, ch, chunk_rows)
    )
    t.start()
    builder = StreamingCorpusBuilder()
    for chunk in ch:
        builder.add(chunk)
    t.join()
    got = builder.finish(wc.ip_table, wc.word_table)
    _corpora_equal(got, ref)
    assert builder.rows == len(wc.ids["doc_id"])


def test_streamed_corpus_matches_word_counts_file(tmp_path):
    """...and equals parsing the emitted word_counts.dat — the streamed
    path reproduces the file contract's ids exactly."""
    from oni_ml_tpu.io import formats

    features = _flow_features(tmp_path)
    wc_path = str(tmp_path / "wc.dat")
    formats.write_word_counts(wc_path, features.word_counts())
    ref = Corpus.from_word_counts_file(wc_path)
    wc = word_count_columns(features)
    builder = StreamingCorpusBuilder()
    for chunk in wc.ids.chunks(13):
        builder.add(chunk)
    _corpora_equal(builder.finish(wc.ip_table, wc.word_table), ref)


def test_pure_python_container_columns(tmp_path):
    """The pure-Python fallback containers intern through
    intern_word_counts; the columnar hand-off must agree with their
    word_counts() triples."""
    from oni_ml_tpu.features.flow import featurize_flow

    features = _flow_features(tmp_path)
    rows = [features.row(i) for i in range(features.num_events)]
    lines = ["h"] + [",".join(r) for r in rows]
    pyf = featurize_flow(lines)
    wc = word_count_columns(pyf)
    builder = StreamingCorpusBuilder()
    builder.add_arrays(wc.ids["doc_id"], wc.ids["word_id"],
                       wc.ids["count"])
    got = builder.finish(wc.ip_table, wc.word_table)
    _corpora_equal(got, Corpus.from_word_counts(pyf.word_counts()))


def test_consume_corpus_failure_poisons_channel():
    """A consumer-side failure must poison the channel so a producer
    blocked in put() backpressure unblocks with the consumer's error
    instead of deadlocking the plane's drain join."""
    from oni_ml_tpu.dataplane import consume_corpus

    ch = Channel("e", capacity=1)
    errs = []

    def producer():
        try:
            for i in range(10):
                ch.put(ColumnSet([
                    Column("doc_id", np.zeros(2, np.int32)),
                    Column("word_id", np.zeros(2, np.int32)),
                    Column("count", np.ones(2, np.int64)),
                ]))
            ch.close()
        except ChannelError as e:
            errs.append(e)

    t = threading.Thread(target=producer)
    t.start()

    class _Boom(Exception):
        pass

    orig_add = StreamingCorpusBuilder.add
    try:
        StreamingCorpusBuilder.add = lambda self, chunk: (
            (_ for _ in ()).throw(_Boom("consumer died"))
        )
        with pytest.raises(_Boom):
            consume_corpus(ch, [], [])
    finally:
        StreamingCorpusBuilder.add = orig_add
    t.join(timeout=5.0)
    assert not t.is_alive()  # producer unblocked, not deadlocked
    assert errs and "consumer failed" in str(errs[0])


def test_task_stall_excluded_from_work_accounting():
    """A producer task's channel-backpressure stall is idle, not work:
    it rides the completion row as stall_s and is subtracted from the
    plane's background wall and bench's critical-path sum."""
    import bench
    from oni_ml_tpu.config import DataplaneConfig
    from oni_ml_tpu.dataplane import Dataplane

    plane = Dataplane(DataplaneConfig())
    task = plane.spawn("producer", lambda: time.sleep(0.2),
                       stall=lambda: 0.15)
    task.join_quiet()
    rec = plane.drain()
    row = rec["tasks"]["producer"]
    assert row["stall_s"] == pytest.approx(0.15)
    assert row["wall_s"] >= 0.2
    assert rec["background_wall_s"] == pytest.approx(
        row["wall_s"] - 0.15, abs=0.02
    )
    metrics = [
        {"stage": "pre", "wall_s": 1.0},
        {"stage": "corpus", "wall_s": 1.0},
        {"stage": "lda", "wall_s": 1.0},
        {"stage": "score", "wall_s": 1.0},
        {"stage": "dataplane", "tasks": {
            "wc_stream": {"stage": "corpus", "wall_s": 0.9,
                          "stall_s": 0.8, "ok": True},
        }, "edges": {}},
    ]
    crit = bench.critical_path_summary(metrics, total_s=4.0)
    # Only the 0.1s of real work counts toward the serial-equivalent.
    assert crit["sum_of_stage_walls_s"] == pytest.approx(4.1)
    assert crit["per_stage_wall_s"]["corpus"] == pytest.approx(1.1)
    assert crit["background_wall_s"] == pytest.approx(0.1)


def test_builder_rejects_ragged_chunk():
    b = StreamingCorpusBuilder()
    with pytest.raises(ValueError, match="ragged"):
        b.add_arrays(np.zeros(3, np.int32), np.zeros(2, np.int32),
                     np.zeros(3, np.int64))


def test_corpus_save_atomic_same_bytes(tmp_path):
    features = _flow_features(tmp_path)
    corpus = Corpus.from_features(features)
    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(), b.mkdir()
    corpus.save(str(a))
    corpus.save_atomic(str(b))
    for name in ("words.dat", "doc.dat", "model.dat"):
        assert (a / name).read_bytes() == (b / name).read_bytes(), name
        assert not (b / (name + ".tmp")).exists()


# ---------------------------------------------------------------------------
# ScoringModel.from_lda / scoring prep
# ---------------------------------------------------------------------------


def _tiny_lda(tmp_path):
    from oni_ml_tpu.models.lda import train_corpus

    features = _flow_features(tmp_path)
    corpus = Corpus.from_features(features)
    cfg = LDAConfig(num_topics=3, em_max_iters=4, batch_size=32,
                    min_bucket_len=16, seed=5)
    return features, corpus, train_corpus(corpus, cfg)


def test_scoring_model_from_lda_equals_file_roundtrip(tmp_path):
    """The lda→score hand-off: ScoringModel.from_lda equals writing
    doc/word_results.csv and loading them back, to the double — the
    writers' str(float64) shortest-repr round trip is exact."""
    from oni_ml_tpu.io import formats
    from oni_ml_tpu.scoring.score import ScoringModel

    _, corpus, result = _tiny_lda(tmp_path)
    dpath = str(tmp_path / "doc_results.csv")
    wpath = str(tmp_path / "word_results.csv")
    formats.write_doc_results(dpath, corpus.doc_names, result.gamma)
    formats.write_word_results(wpath, corpus.vocab, result.log_beta)
    via_files = ScoringModel.from_files(dpath, wpath, 1e-4)
    in_mem = ScoringModel.from_lda(
        corpus.doc_names, result.gamma, corpus.vocab, result.log_beta,
        1e-4,
    )
    assert via_files.ip_index == in_mem.ip_index
    assert via_files.word_index == in_mem.word_index
    # Bit-for-bit: the file round trip (str(float64) shortest repr)
    # must not perturb a single double.
    np.testing.assert_array_equal(via_files.theta, in_mem.theta)
    np.testing.assert_array_equal(via_files.p, in_mem.p)


def test_scoring_prep_matches_inline_indices(tmp_path):
    from oni_ml_tpu.scoring.score import flow_event_indices

    features, corpus, _ = _tiny_lda(tmp_path)
    prep = build_scoring_prep(features, corpus.doc_names, corpus.vocab,
                              "flow")
    ip_index = {ip: i for i, ip in enumerate(corpus.doc_names)}
    word_index = {w: i for i, w in enumerate(corpus.vocab)}
    inline = flow_event_indices(features, ip_index, word_index)
    assert prep.num_raw_events == features.num_raw_events
    for got, want in zip(prep.indices, inline):
        np.testing.assert_array_equal(got, want)


def test_scoring_prep_model_mismatch_fails_loudly(tmp_path):
    """Prep built against a different corpus than the model was trained
    on must fail, not silently rescore with wrong rows."""
    from oni_ml_tpu.scoring.score import score_flow_csv

    features, corpus, result = _tiny_lda(tmp_path)
    from oni_ml_tpu.scoring.score import ScoringModel

    model = ScoringModel.from_lda(
        corpus.doc_names, result.gamma, corpus.vocab, result.log_beta,
        1e-4,
    )
    prep = build_scoring_prep(
        features, corpus.doc_names[:-1], corpus.vocab, "flow"
    )
    with pytest.raises(ValueError, match="different corpora"):
        score_flow_csv(features, model, 1.1, prep=prep)
    bad_src = build_scoring_prep(features, corpus.doc_names, corpus.vocab,
                                 "flow")
    bad_src.dsource = "dns"
    with pytest.raises(ValueError, match="dsource"):
        score_flow_csv(features, model, 1.1, prep=bad_src)


def test_scoring_with_prep_identical_csv(tmp_path):
    from oni_ml_tpu.scoring.score import ScoringModel, score_flow_csv

    features, corpus, result = _tiny_lda(tmp_path)
    model = ScoringModel.from_lda(
        corpus.doc_names, result.gamma, corpus.vocab, result.log_beta,
        1e-4,
    )
    prep = build_scoring_prep(features, corpus.doc_names, corpus.vocab,
                              "flow")
    blob_prep, _ = score_flow_csv(features, model, 1.1, prep=prep)
    blob_inline, _ = score_flow_csv(features, model, 1.1)
    assert blob_prep == blob_inline


# ---------------------------------------------------------------------------
# Sinks / tasks
# ---------------------------------------------------------------------------


def test_atomic_write_and_clear_stale(tmp_path):
    p = str(tmp_path / "art.bin")
    atomic_write_bytes(p, b"hello")
    assert open(p, "rb").read() == b"hello"
    assert not os.path.exists(p + ".tmp")
    open(p + ".tmp", "wb").write(b"junk")
    clear_stale(p)
    assert not os.path.exists(p) and not os.path.exists(p + ".tmp")
    clear_stale(p)  # idempotent on missing files


def test_checkpoint_sinks_report_errors(tmp_path):
    sinks = CheckpointSinks(workers=2)
    ok_path = str(tmp_path / "ok.bin")
    sinks.submit("good", lambda: atomic_write_bytes(ok_path, b"x"),
                 stage="pre")

    def _bad():
        raise OSError("disk gone")

    sinks.submit("bad", _bad, stage="lda")
    rows, errors = sinks.drain()
    sinks.close()
    assert rows["good"]["ok"] and rows["good"]["stage"] == "pre"
    assert not rows["bad"]["ok"] and "disk gone" in rows["bad"]["error"]
    assert len(errors) == 1 and errors[0][0] == "bad"
    assert os.path.exists(ok_path)


def test_task_result_reraises_and_marks_consumed():
    t = Task("boom", lambda: (_ for _ in ()).throw(RuntimeError("x")))
    with pytest.raises(RuntimeError):
        t.result()
    assert t.consumed  # drain must not double-report
    ok = Task("fine", lambda: 42)
    assert ok.result() == 42
    assert ok.completion.ok and ok.completion.wall_s >= 0


# ---------------------------------------------------------------------------
# Pipeline: byte-identity, checkpoints off, fail-fast, resume refusal
# ---------------------------------------------------------------------------

_ARTIFACTS = [
    "word_counts.dat", "words.dat", "doc.dat", "model.dat",
    "final.beta", "final.gamma", "final.other", "likelihood.dat",
    "doc_results.csv", "word_results.csv", "flow_results.csv",
]


def _day_cfg(root, **dp):
    rng = np.random.default_rng(7)
    lines = ["dummy,header"]
    for _ in range(60):
        lines.append(flow_row(
            hour=int(rng.integers(0, 24)), minute=int(rng.integers(0, 60)),
            second=int(rng.integers(0, 60)),
            sip=f"10.0.0.{rng.integers(1, 9)}",
            dip=f"172.16.0.{rng.integers(1, 9)}",
            col10=str(rng.choice([80, 443, 55000, 0])),
            col11=str(rng.choice([80, 6000, 70000])),
            ipkt=str(rng.integers(1, 100)),
            ibyt=str(rng.integers(40, 10000)),
        ))
    raw = os.path.join(str(root), "flow.csv")
    with open(raw, "w") as f:
        f.write("\n".join(lines) + "\n")
    return PipelineConfig(
        data_dir=str(root), flow_path=raw,
        lda=LDAConfig(num_topics=4, em_max_iters=6, batch_size=32,
                      min_bucket_len=16, seed=3),
        feedback=FeedbackConfig(dup_factor=5),
        scoring=ScoringConfig(threshold=1.1),
        dataplane=DataplaneConfig(**dp),
    )


def _sha(path):
    return hashlib.sha256(open(path, "rb").read()).hexdigest()


def test_dataplane_artifacts_byte_identical_to_serial(tmp_path):
    """THE acceptance pin: streaming on vs --no-dataplane produce the
    same bytes for every artifact.  (features.pkl embeds its day-dir
    path — spill bookkeeping — so it is compared semantically.)"""
    plane_dir, serial_dir = tmp_path / "plane", tmp_path / "serial"
    plane_dir.mkdir(), serial_dir.mkdir()
    m_plane = run_pipeline(_day_cfg(plane_dir), "20160122", "flow")
    m_serial = run_pipeline(
        _day_cfg(serial_dir, enabled=False), "20160122", "flow"
    )
    p_day = plane_dir / "20160122"
    s_day = serial_dir / "20160122"
    for name in _ARTIFACTS:
        assert _sha(str(p_day / name)) == _sha(str(s_day / name)), name
    with open(p_day / "features.pkl", "rb") as f:
        pf = pickle.load(f)
    with open(s_day / "features.pkl", "rb") as f:
        sf = pickle.load(f)
    assert pf.num_events == sf.num_events
    assert pf.word_counts() == sf.word_counts()
    # Stage provenance: the streaming run handed everything off in
    # memory; the serial run round-tripped the file contract.
    by_stage = {m["stage"]: m for m in m_plane}
    assert by_stage["score"]["features"] == "handoff"
    assert by_stage["score"]["model"] == "handoff"
    assert by_stage["score"]["prep"] == "overlapped"
    assert by_stage["lda"]["corpus"] == "handoff"
    assert by_stage["corpus"]["stream"]["chunks"] >= 1
    s_stage = {m["stage"]: m for m in m_serial}
    assert s_stage["score"].get("features", "file") == "file"
    assert "dataplane" not in s_stage
    # The dataplane record carries every demoted write + overlap task.
    dp = by_stage["dataplane"]
    assert set(dp["tasks"]) == {
        "features_pkl", "word_counts", "corpus_dat", "final_model",
        "doc_results", "word_results", "results_csv", "wc_stream",
        "score_prep",
    }
    assert all(t["ok"] for t in dp["tasks"].values())
    assert "pre.wc->corpus" in dp["edges"]


def test_no_checkpoints_products_only_and_resume_refused(tmp_path):
    """--no-checkpoints: only products land (results CSV, metrics.json,
    journal); a later --stages resume is refused with the artifact name
    AND the provenance note."""
    cfg = _day_cfg(tmp_path, checkpoints=False)
    run_pipeline(cfg, "20160122", "flow")
    day = tmp_path / "20160122"
    assert (day / "flow_results.csv").exists()
    assert (day / "metrics.json").exists()
    assert (day / "run_journal.jsonl").exists()
    for name in _ARTIFACTS[:-1]:  # every contract file skipped
        assert not (day / name).exists(), name
    # Same scored bytes as a full serial day on the same input.
    serial_root = tmp_path / "serial"
    serial_root.mkdir()
    run_pipeline(_day_cfg(serial_root, enabled=False), "20160122", "flow")
    assert _sha(str(day / "flow_results.csv")) == _sha(
        str(serial_root / "20160122" / "flow_results.csv")
    )
    # run_start carries checkpoints: false.
    recs = Journal.replay(str(day / "run_journal.jsonl"))
    assert recs[0]["kind"] == "run_start"
    assert recs[0]["checkpoints"] is False
    # Resume against the file-less day (a normal, checkpoints-on
    # invocation — the --no-checkpoints provenance comes from the
    # journal): refused, naming the artifact.
    resume_cfg = PipelineConfig(
        data_dir=cfg.data_dir, flow_path=cfg.flow_path,
        lda=cfg.lda, feedback=cfg.feedback, scoring=cfg.scoring,
    )
    with pytest.raises(MissingArtifactError) as ei:
        run_pipeline(resume_cfg, "20160122", "flow", stages=[Stage.LDA])
    msg = str(ei.value)
    assert "model.dat" in msg
    assert "--no-checkpoints" in msg and "refused" in msg


def test_no_checkpoints_validation():
    cfg = PipelineConfig(dataplane=DataplaneConfig(checkpoints=False,
                                                   enabled=False))
    with pytest.raises(ValueError, match="no-dataplane"):
        run_pipeline(cfg, "20160122", "flow")
    cfg2 = PipelineConfig(dataplane=DataplaneConfig(checkpoints=False))
    with pytest.raises(ValueError, match="stages"):
        run_pipeline(cfg2, "20160122", "flow", stages=[Stage.PRE])
    with pytest.raises(ValueError, match="online"):
        run_pipeline(cfg2, "20160122", "flow", online=True)


@pytest.mark.parametrize("stage,artifact", [
    (Stage.CORPUS, "word_counts.dat"),
    (Stage.LDA, "model.dat"),
    (Stage.SCORE, "features.pkl"),
])
def test_stages_fail_fast_names_missing_artifact(tmp_path, stage,
                                                 artifact):
    """A --stages invocation whose upstream checkpoint is missing fails
    fast with the artifact name and the regenerating flag — not a
    loader stack trace."""
    cfg = _day_cfg(tmp_path)
    with pytest.raises(MissingArtifactError) as ei:
        run_pipeline(cfg, "20160122", "flow", stages=[stage])
    msg = str(ei.value)
    assert artifact in msg
    assert "--stages" in msg and "--force" in msg


def test_stages_resume_over_file_contract_unchanged(tmp_path):
    """A resumed --stages run falls back to the file contract exactly
    as before the dataplane: same bytes as the uninterrupted chain."""
    full_root, staged_root = tmp_path / "full", tmp_path / "staged"
    full_root.mkdir(), staged_root.mkdir()
    run_pipeline(_day_cfg(full_root), "20160122", "flow")
    cfg = _day_cfg(staged_root)
    run_pipeline(cfg, "20160122", "flow", stages=[Stage.PRE])
    run_pipeline(cfg, "20160122", "flow", stages=[Stage.CORPUS])
    m_lda = run_pipeline(cfg, "20160122", "flow", stages=[Stage.LDA])
    m_score = run_pipeline(cfg, "20160122", "flow", stages=[Stage.SCORE])
    by = {m["stage"]: m for m in m_lda}
    assert by["lda"]["corpus"] == "file"
    by = {m["stage"]: m for m in m_score}
    assert by["score"]["features"] == "file"
    assert by["score"]["model"] == "file"
    assert by["score"]["prep"] == "inline"
    for name in _ARTIFACTS:
        assert _sha(str(staged_root / "20160122" / name)) == _sha(
            str(full_root / "20160122" / name)
        ), name


def test_checkpoint_write_failure_fails_run(tmp_path, monkeypatch):
    """A failed background checkpoint write fails the RUN (rc path:
    RuntimeError naming the sink) — a day dir missing its contract
    files must never report ok."""
    cfg = _day_cfg(tmp_path)
    import oni_ml_tpu.runner.ml_ops as ml_ops

    def _boom(path, triples):
        raise OSError("disk full")

    monkeypatch.setattr(ml_ops.formats, "write_word_counts", _boom)
    # Native containers take the word_counts_emit path; break that too.
    import oni_ml_tpu.native_emit as native_emit

    monkeypatch.setattr(
        native_emit, "word_counts_emit",
        lambda features: (_ for _ in ()).throw(OSError("disk full")),
    )
    with pytest.raises(RuntimeError, match="word_counts"):
        run_pipeline(cfg, "20160122", "flow")
    # The journal's run_end must agree the run failed.
    recs = Journal.replay(
        str(tmp_path / "20160122" / "run_journal.jsonl")
    )
    ends = [r for r in recs if r["kind"] == "run_end"]
    assert len(ends) == 1 and not ends[0]["ok"]


# ---------------------------------------------------------------------------
# Journal records + trace_view lanes / stall table
# ---------------------------------------------------------------------------


def test_journal_dataplane_records_and_trace_lanes(tmp_path):
    """The journal carries the dataplane vocabulary (depth/task/edge
    events) and trace_view renders queue-depth counter lanes + the
    per-edge stall table."""
    cfg = _day_cfg(tmp_path)
    run_pipeline(cfg, "20160122", "flow")
    jpath = str(tmp_path / "20160122" / "run_journal.jsonl")
    records = Journal.replay(jpath)
    dp = [r for r in records if r.get("kind") == "dataplane"]
    events = {r["event"] for r in dp}
    assert events == {"depth", "task", "edge"}
    tasks = {r["name"] for r in dp if r["event"] == "task"}
    assert {"wc_stream", "score_prep", "features_pkl",
            "corpus_dat"} <= tasks
    edges = [r for r in dp if r["event"] == "edge"]
    assert len(edges) == 1 and edges[0]["edge"] == "pre.wc->corpus"
    assert {"capacity", "puts", "gets", "put_stall_s", "get_stall_s",
            "max_depth"} <= set(edges[0])
    # The overlap spans the ISSUE names: checkpoint sinks, overlap
    # tasks, and the score stage's (near-zero) prep join.
    span_names = {r["name"] for r in records if r.get("kind") == "span"}
    assert "dataplane.task.score_prep" in span_names
    assert "dataplane.task.wc_stream" in span_names
    assert "dataplane.checkpoint.features_pkl" in span_names
    assert "dataplane.prep_join" in span_names

    sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
    import trace_view

    trace = trace_view.journal_to_trace(records)
    names = [e["name"] for e in trace["traceEvents"]]
    # Queue-depth counter lane per edge, next to the stage spans.
    assert "dataplane pre.wc->corpus depth" in names
    assert "dataplane.task.score_prep" in names
    json.dumps(trace)
    table = trace_view.dataplane_edge_table(records)
    assert len(table) == 1
    assert table[0]["edge"] == "pre.wc->corpus"
    import io

    buf = io.StringIO()
    trace_view.print_summary(records, 0, out=buf)
    out = buf.getvalue()
    assert "dataplane edges" in out
    assert "pre.wc->corpus" in out
    assert "background tasks" in out


def test_channel_stall_lane_rendered():
    """A blocked get (starved consumer) renders as a stall counter lane
    so starvation is visually obvious in the trace."""
    sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
    import trace_view

    records = [
        {"kind": "dataplane", "event": "depth", "edge": "e", "side": "get",
         "depth": 0, "wait_s": 0.25, "mono_ns": 1000},
        {"kind": "dataplane", "event": "depth", "edge": "e", "side": "put",
         "depth": 1, "mono_ns": 2000},
    ]
    trace = trace_view.journal_to_trace(records)
    by_name = {}
    for e in trace["traceEvents"]:
        by_name.setdefault(e["name"], []).append(e)
    assert "dataplane e depth" in by_name
    stall = by_name["dataplane e get_stall_ms"]
    assert stall[0]["args"]["stall_ms"] == pytest.approx(250.0)
