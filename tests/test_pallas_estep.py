"""Pallas E-step kernel vs the XLA path.

The kernel must agree with estep.e_step to fixed-point tolerance: same
converged gammas, suff-stats, ELBO.  Also covers the in-kernel digamma
(jax.scipy's is not a Mosaic primitive) and block-size selection.

Kernel math runs under interpret mode on EVERY CPU suite run (the
``interpret`` parametrization below); the compiled Mosaic variant of
each parity test is TPU-marked, so a chip-attached run
(ONI_ML_TPU_TESTS_ON_TPU=1) exercises the real lowering with the same
assertions instead of a separate smoke file.
"""

import numpy as np
import jax.numpy as jnp
import pytest
from jax.scipy.special import digamma

from oni_ml_tpu.ops import estep, pallas_estep


def _on_tpu() -> bool:
    import jax

    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


INTERPRET = [
    pytest.param(True, id="interpret"),
    pytest.param(
        False, id="compiled",
        marks=pytest.mark.skipif(
            not _on_tpu(), reason="compiled Pallas needs a TPU backend"
        ),
    ),
]


@pytest.fixture(scope="module")
def problem():
    K, V, B, L = 4, 50, 32, 16
    rng = np.random.default_rng(0)
    noise = rng.uniform(size=(K, V)) + 1.0 / V
    lb = jnp.asarray(np.log(noise / noise.sum(-1, keepdims=True)), jnp.float32)
    w = jnp.asarray(rng.integers(0, V, size=(B, L)), jnp.int32)
    c = jnp.asarray(rng.integers(1, 5, size=(B, L)), jnp.float32)
    m = jnp.asarray((rng.uniform(size=B) > 0.2).astype(np.float32))
    return lb, jnp.float32(2.5), w, c, m


def test_digamma_matches_scipy():
    # Positive reals across the regimes the recurrence + series cover:
    # tiny (gamma can be ~alpha ~ 1e-3), mid, and large.
    x = jnp.asarray(
        np.concatenate(
            [np.linspace(1e-4, 0.1, 57), np.linspace(0.1, 6, 100),
             np.linspace(6, 500, 100)]
        ),
        jnp.float32,
    )
    ours = np.asarray(pallas_estep.digamma_pos(x))
    ref = np.asarray(digamma(x))
    np.testing.assert_allclose(
        ours, ref, rtol=2e-6, atol=2e-6 * np.maximum(np.abs(ref), 1.0).max()
    )


def test_gammaln_matches_scipy():
    # Same regimes as digamma: the dense kernel evaluates gammaln at
    # gamma entries (>= alpha, can be ~1e-3 after alpha Newton steps)
    # and at row sums (up to ~alpha*K + N_d).
    from jax.scipy.special import gammaln

    # Per-range asserts: a global atol scaled by gammaln(5000) ~ 3.8e4
    # would swamp the small-x regime entirely.
    for lo, hi, n in [(1e-4, 0.1, 57), (0.1, 6.0, 100), (6.0, 5000.0, 100)]:
        x = jnp.asarray(np.linspace(lo, hi, n), jnp.float32)
        ours = np.asarray(pallas_estep.gammaln_pos(x))
        ref = np.asarray(gammaln(x))
        np.testing.assert_allclose(
            ours, ref,
            rtol=4e-6, atol=4e-6 * np.maximum(np.abs(ref), 1.0).max(),
        )


@pytest.mark.parametrize("interpret", INTERPRET)
def test_e_step_parity(problem, interpret):
    lb, a, w, c, m = problem
    ref = estep.e_step(lb, a, w, c, m, var_max_iters=50, var_tol=1e-7,
                       backend="xla")
    pal = pallas_estep.e_step(lb, a, w, c, m, var_max_iters=50, var_tol=1e-7,
                              interpret=interpret)
    sel = np.asarray(m) == 1
    np.testing.assert_allclose(
        np.asarray(pal.gamma)[sel], np.asarray(ref.gamma)[sel],
        rtol=5e-4, atol=5e-4,
    )
    np.testing.assert_allclose(
        np.asarray(pal.suff_stats), np.asarray(ref.suff_stats),
        rtol=2e-3, atol=2e-4,
    )
    np.testing.assert_allclose(
        float(pal.likelihood), float(ref.likelihood), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(pal.alpha_ss), float(ref.alpha_ss), rtol=1e-4
    )


@pytest.mark.parametrize("interpret", INTERPRET)
def test_iteration_cap_respected(problem, interpret):
    lb, a, w, c, m = problem
    pal = pallas_estep.e_step(lb, a, w, c, m, var_max_iters=3, var_tol=0.0,
                              interpret=interpret)
    assert int(pal.vi_iters) == 3


def test_pick_block():
    # Power-of-two batches pick the largest VMEM-feasible block.
    assert pallas_estep.pick_block(4096, 128, 20) == 128
    assert pallas_estep.pick_block(16, 16, 4) == 16
    # Non-8-divisible batch: no feasible block -> caller falls back.
    assert pallas_estep.pick_block(12, 16, 4) is None
    # Huge L shrinks the block instead of blowing VMEM.
    bb = pallas_estep.pick_block(4096, 2048, 20)
    assert bb is not None
    assert pallas_estep._vmem_estimate(bb, 2048, 20) <= pallas_estep._VMEM_BUDGET
    # Large K also shrinks the block (the column temporaries scale with
    # K): the (K=50, L=16) case that OOM'd at bb=256 must stay under.
    bb = pallas_estep.pick_block(4096, 16, 50)
    assert bb is not None
    assert pallas_estep._vmem_estimate(bb, 16, 50) <= pallas_estep._VMEM_BUDGET


def test_vmem_estimate_takes_precision():
    """A bf16-stored slab halves the dominant VMEM term, so bf16 block
    picks must size against the real footprint — before _vmem_estimate
    took a precision, bf16 picks sized VMEM as f32 and halved the
    feasible block space (ISSUE 9 satellite)."""
    f32 = pallas_estep._vmem_estimate(64, 2048, 20, "f32")
    b16 = pallas_estep._vmem_estimate(64, 2048, 20, "bf16")
    assert b16 < f32
    # At a slab-dominated shape the bf16 pick reaches a strictly larger
    # block than the f32 pick.
    bb_f32 = pallas_estep.pick_block(4096, 4096, 20, "f32")
    bb_b16 = pallas_estep.pick_block(4096, 4096, 20, "bf16")
    assert bb_b16 is not None
    assert bb_f32 is None or bb_b16 >= bb_f32
    # bf16 blocks sit on the 16-sublane tile.
    assert pallas_estep.pick_block(64, 128, 4, "bf16") % 16 == 0


def test_auto_backend_on_cpu_uses_xla(problem):
    # On the CPU test backend, auto must not take the Pallas path.
    lb, a, w, c, m = problem
    assert not pallas_estep.available(32, 16, 4)
    res = estep.e_step(lb, a, w, c, m, var_max_iters=5, var_tol=1e-6)
    assert np.isfinite(float(res.likelihood))


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_warm_start_sparse_paths(problem, backend):
    """gamma_prev/warm through the sparse engines (XLA fixed point and
    the Pallas kernel): warm from the converged gamma must reach the
    same point in fewer iterations, and warm=0 with garbage gamma_prev
    must reproduce the fresh run exactly."""
    lb, alpha, w, c, m = problem
    # var_tol must be reachable in f32 (gamma ~ 10, eps ~ 1e-6 relative)
    # or both runs just hit the cap and the warm speedup is invisible.
    kw = dict(var_max_iters=40, var_tol=1e-5, backend=backend)
    if backend == "pallas":
        # interpret-mode dispatch: call the module directly.
        def run(**extra):
            return pallas_estep.e_step(lb, alpha, w, c, m, 40, 1e-5,
                                       interpret=True, **extra)
    else:
        def run(**extra):
            return estep.e_step(lb, alpha, w, c, m, **kw, **extra)

    fresh = run()
    warm = run(gamma_prev=fresh.gamma, warm=1)
    assert int(warm.vi_iters) < int(fresh.vi_iters)
    np.testing.assert_allclose(np.asarray(warm.gamma),
                               np.asarray(fresh.gamma),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(float(warm.likelihood),
                               float(fresh.likelihood), rtol=1e-5)

    cold = run(gamma_prev=jnp.full_like(fresh.gamma, 7.0), warm=0)
    np.testing.assert_array_equal(np.asarray(cold.gamma),
                                  np.asarray(fresh.gamma))


@pytest.mark.parametrize("backend", ["xla", "pallas", "sparse", "dense"])
def test_gamma_prev_without_warm_raises(problem, backend):
    """gamma_prev alone must error identically on every backend — never
    silently warm-start on one and crash on another."""
    lb, alpha, w, c, m = problem
    fresh = estep.e_step(lb, alpha, w, c, m, 10, 1e-5, backend="xla")
    with pytest.raises(ValueError, match="warm"):
        estep.e_step(lb, alpha, w, c, m, 10, 1e-5, backend=backend,
                     gamma_prev=fresh.gamma)
