"""Multi-device parity: sharded E/M steps must reproduce the single-device
engine on the 8-device virtual CPU mesh (SURVEY §4: "asserting sharded-vs-
single-device ... equality of suff-stats psums")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oni_ml_tpu.config import LDAConfig
from oni_ml_tpu.io import make_batches
from oni_ml_tpu.models import LDATrainer, train_corpus
from oni_ml_tpu.parallel import (
    make_data_parallel_e_step,
    make_mesh,
    make_vocab_sharded_fns,
    pad_vocab,
)
from oni_ml_tpu.ops import estep

import reference_lda as ref
from test_lda import corpus_from_docs


@pytest.fixture(scope="module")
def problem():
    docs, _ = ref.make_synthetic_corpus(num_docs=48, num_terms=37, num_topics=3,
                                        seed=11)
    corpus = corpus_from_docs(docs, 37)
    rng = np.random.default_rng(5)
    K = 4
    noise = rng.uniform(size=(K, 37)) + 1 / 37
    log_beta = np.log(noise / noise.sum(-1, keepdims=True))
    return corpus, K, log_beta


def test_devices_available():
    assert len(jax.devices()) == 8, jax.devices()


def test_data_parallel_e_step_parity(problem):
    corpus, K, log_beta = problem
    mesh = make_mesh(data=8, model=1)
    batches = make_batches(corpus, batch_size=64, min_bucket_len=64)
    assert len(batches) == 1
    b = batches[0]
    args = (
        jnp.asarray(log_beta, jnp.float32),
        jnp.float32(2.5),
        jnp.asarray(b.word_idx),
        jnp.asarray(b.counts),
        jnp.asarray(b.doc_mask),
    )
    single = estep.e_step(*args, var_max_iters=30, var_tol=1e-7)
    fn = make_data_parallel_e_step(mesh)
    sharded = jax.jit(
        lambda *a: fn(*a, var_max_iters=30, var_tol=1e-7)
    )(*args)
    np.testing.assert_allclose(np.asarray(sharded.gamma), np.asarray(single.gamma),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(sharded.suff_stats), np.asarray(single.suff_stats),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(sharded.likelihood), float(single.likelihood),
                               rtol=1e-5)
    np.testing.assert_allclose(float(sharded.alpha_ss), float(single.alpha_ss),
                               rtol=1e-5)


def test_vocab_sharded_e_step_parity(problem):
    corpus, K, log_beta = problem
    mesh = make_mesh(data=2, model=4)
    V = corpus.num_terms
    v_pad = pad_vocab(V, 4)
    lb_pad = np.pad(log_beta, ((0, 0), (0, v_pad - V)),
                    constant_values=estep.LOG_ZERO)
    batches = make_batches(corpus, batch_size=64, min_bucket_len=64)
    b = batches[0]
    args = (
        jnp.asarray(lb_pad, jnp.float32),
        jnp.float32(2.5),
        jnp.asarray(b.word_idx),
        jnp.asarray(b.counts),
        jnp.asarray(b.doc_mask),
    )
    single = estep.e_step(*args, var_max_iters=30, var_tol=1e-7)
    e_fn, m_fn = make_vocab_sharded_fns(mesh)
    sharded = jax.jit(
        lambda *a: e_fn(*a, var_max_iters=30, var_tol=1e-7)
    )(*args)
    np.testing.assert_allclose(np.asarray(sharded.gamma), np.asarray(single.gamma),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(sharded.suff_stats), np.asarray(single.suff_stats),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(sharded.likelihood), float(single.likelihood),
                               rtol=1e-5)
    # sharded m_step matches the dense one
    lb_single = estep.m_step(single.suff_stats)
    lb_sharded = jax.jit(m_fn)(sharded.suff_stats)
    np.testing.assert_allclose(np.asarray(lb_sharded), np.asarray(lb_single),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mesh_shape,vocab_sharded", [
    ((8, 1), False),
    ((2, 4), True),
])
def test_full_training_parity(problem, mesh_shape, vocab_sharded):
    corpus, K, log_beta = problem
    cfg = LDAConfig(num_topics=K, em_max_iters=5, em_tol=0.0, batch_size=64,
                    min_bucket_len=64, estimate_alpha=True, seed=9)
    single = train_corpus(corpus, cfg)
    mesh = make_mesh(data=mesh_shape[0], model=mesh_shape[1])
    multi = train_corpus(corpus, cfg, mesh=mesh, vocab_sharded=vocab_sharded)
    np.testing.assert_allclose(
        [l for l, _ in multi.likelihoods], [l for l, _ in single.likelihoods],
        rtol=1e-4)
    np.testing.assert_allclose(np.exp(multi.log_beta), np.exp(single.log_beta),
                               atol=1e-4)
    np.testing.assert_allclose(multi.gamma, single.gamma, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(multi.alpha, single.alpha, rtol=1e-4)


def test_full_training_parity_vocab_sharded_dense(problem):
    """End-to-end config-4 plan: train_corpus with vocab_sharded=True and
    dense_em='on' must route through make_vocab_sharded_dense_e_step and
    reproduce the single-device trajectory (fresh start pinned: the
    single-device CPU run stays sparse, and warm start would change
    trajectories by design)."""
    corpus, K, log_beta = problem
    cfg = LDAConfig(num_topics=K, em_max_iters=5, em_tol=0.0, batch_size=64,
                    min_bucket_len=64, estimate_alpha=True, seed=9,
                    warm_start_gamma=False)
    single = train_corpus(corpus, cfg)
    mesh = make_mesh(data=2, model=4)
    import dataclasses

    multi = train_corpus(corpus, dataclasses.replace(cfg, dense_em="on"),
                         mesh=mesh, vocab_sharded=True)
    np.testing.assert_allclose(
        [l for l, _ in multi.likelihoods], [l for l, _ in single.likelihoods],
        rtol=1e-4)
    np.testing.assert_allclose(np.exp(multi.log_beta), np.exp(single.log_beta),
                               atol=1e-4)
    np.testing.assert_allclose(multi.gamma, single.gamma, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(multi.alpha, single.alpha, rtol=1e-4)


@pytest.mark.parametrize("mesh_shape", [(2, 4), (1, 8), (4, 2)])
def test_vocab_sharded_DENSE_e_step_parity(problem, mesh_shape):
    """Config-4 plan: the dense MXU E-step with the vocabulary sharded
    over `model` must reproduce the unsharded dense kernel — gamma,
    suff-stats, likelihood, and the warm-start path."""
    from oni_ml_tpu.ops import dense_estep
    from oni_ml_tpu.parallel import make_vocab_sharded_dense_e_step

    corpus, K, log_beta = problem
    d, m = mesh_shape
    mesh = make_mesh(data=d, model=m)
    V = corpus.num_terms
    batches = make_batches(corpus, batch_size=64, min_bucket_len=64)
    b = batches[0]
    dense = np.asarray(dense_estep.densify(
        jnp.asarray(b.word_idx), jnp.asarray(b.counts), V
    ))
    w = dense.shape[1]
    # densify pads W to the 128-lane tile; pad further to the model axis
    # (here 128 % m == 0 already) and pad log_beta to match.
    assert w % m == 0
    lb_pad = np.pad(log_beta, ((0, 0), (0, w - V)),
                    constant_values=estep.LOG_ZERO)
    args = (
        jnp.asarray(lb_pad, jnp.float32),
        jnp.float32(2.5),
        jnp.asarray(dense),
        jnp.asarray(b.doc_mask),
    )
    kw = dict(var_max_iters=30, var_tol=1e-7)
    single = dense_estep.e_step_dense(*args, interpret=True, **kw)

    fn = make_vocab_sharded_dense_e_step(mesh)
    zeros_g = jnp.zeros((dense.shape[0], K), jnp.float32)
    sharded = jax.jit(
        lambda *a: fn(*a, zeros_g, jnp.asarray(0, jnp.int32), **kw)
    )(*args)
    np.testing.assert_allclose(np.asarray(sharded.gamma),
                               np.asarray(single.gamma),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(sharded.suff_stats), np.asarray(single.suff_stats),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(sharded.likelihood),
                               float(single.likelihood), rtol=1e-5)
    np.testing.assert_allclose(float(sharded.alpha_ss),
                               float(single.alpha_ss), rtol=1e-5)

    # Warm start from the converged gamma: same fixed point, fewer
    # iterations, matching the unsharded kernel's warm path.
    warm_single = dense_estep.e_step_dense(
        *args, interpret=True, gamma_prev=single.gamma, warm=1, **kw
    )
    warm_sharded = jax.jit(
        lambda *a: fn(*a, sharded.gamma, jnp.asarray(1, jnp.int32), **kw)
    )(*args)
    assert int(warm_sharded.vi_iters) <= int(sharded.vi_iters)
    np.testing.assert_allclose(np.asarray(warm_sharded.gamma),
                               np.asarray(warm_single.gamma),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(warm_sharded.likelihood),
                               float(warm_single.likelihood), rtol=1e-5)


def test_vocab_sharded_dense_guards(problem):
    from oni_ml_tpu.parallel import make_vocab_sharded_dense_e_step

    corpus, K, log_beta = problem
    mesh = make_mesh(data=2, model=4)
    fn = make_vocab_sharded_dense_e_step(mesh)
    kw = dict(var_max_iters=5, var_tol=1e-6)
    g0 = jnp.zeros((5, K), jnp.float32)
    with pytest.raises(ValueError, match="not divisible by data"):
        fn(jnp.zeros((K, 128)), 2.5, jnp.zeros((5, 128)), jnp.ones((5,)),
           g0, 0, **kw)
    with pytest.raises(ValueError, match="not divisible by model"):
        fn(jnp.zeros((K, 130)), 2.5, jnp.zeros((8, 130)), jnp.ones((8,)),
           g0, 0, **kw)
    with pytest.raises(ValueError, match="width"):
        fn(jnp.zeros((K, 256)), 2.5, jnp.zeros((8, 128)), jnp.ones((8,)),
           g0, 0, **kw)


@pytest.mark.parametrize("wmajor", [False, True])
def test_data_parallel_dense_one_one_mesh_parity(problem, wmajor):
    """Interpret-mode variant of tools/tpu_smoke.py check 1: the
    shard_map'd dense kernel under a degenerate (1,1) mesh must equal
    the unwrapped kernel (on the real chip the same comparison runs
    Mosaic-compiled — the suite is CPU-pinned, so that half lives in
    tools/tpu_smoke.py)."""
    from oni_ml_tpu.ops import dense_estep
    from oni_ml_tpu.parallel.sharded import make_data_parallel_dense_e_step

    corpus, K, log_beta = problem
    mesh = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    batches = make_batches(corpus, batch_size=64, min_bucket_len=64)
    b = batches[0]
    V = corpus.num_terms
    dense = dense_estep.densify(
        jnp.asarray(b.word_idx), jnp.asarray(b.counts), V
    )
    if wmajor:
        dense = jnp.transpose(dense)
    lb = jnp.asarray(log_beta, jnp.float32)
    kw = dict(var_max_iters=20, var_tol=1e-6)
    plain = dense_estep.e_step_dense(
        lb, jnp.float32(2.5), dense, jnp.asarray(b.doc_mask),
        interpret=True, wmajor=wmajor, **kw
    )
    fn = make_data_parallel_dense_e_step(mesh, wmajor=wmajor)
    zeros_g = jnp.zeros((dense.shape[1 if wmajor else 0], K), jnp.float32)
    sharded = jax.jit(
        lambda *a: fn(*a, interpret=True, **kw)
    )(lb, jnp.float32(2.5), dense, jnp.asarray(b.doc_mask), zeros_g,
      jnp.asarray(0, jnp.int32))
    np.testing.assert_allclose(np.asarray(sharded.gamma),
                               np.asarray(plain.gamma),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(sharded.suff_stats),
                               np.asarray(plain.suff_stats),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(sharded.likelihood),
                               float(plain.likelihood), rtol=1e-6)


def test_batch_size_divisibility_guard(problem):
    corpus, K, _ = problem
    mesh = make_mesh(data=8, model=1)
    cfg = LDAConfig(num_topics=K, batch_size=12)  # 12 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        train_corpus(corpus, cfg, mesh=mesh)
