"""Multi-device parity: sharded E/M steps must reproduce the single-device
engine on the 8-device virtual CPU mesh (SURVEY §4: "asserting sharded-vs-
single-device ... equality of suff-stats psums")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oni_ml_tpu.config import LDAConfig
from oni_ml_tpu.io import make_batches
from oni_ml_tpu.models import LDATrainer, train_corpus
from oni_ml_tpu.parallel import (
    make_data_parallel_e_step,
    make_mesh,
    make_vocab_sharded_fns,
    pad_vocab,
)
from oni_ml_tpu.ops import estep

import reference_lda as ref
from test_lda import corpus_from_docs


@pytest.fixture(scope="module")
def problem():
    docs, _ = ref.make_synthetic_corpus(num_docs=48, num_terms=37, num_topics=3,
                                        seed=11)
    corpus = corpus_from_docs(docs, 37)
    rng = np.random.default_rng(5)
    K = 4
    noise = rng.uniform(size=(K, 37)) + 1 / 37
    log_beta = np.log(noise / noise.sum(-1, keepdims=True))
    return corpus, K, log_beta


def test_devices_available():
    assert len(jax.devices()) == 8, jax.devices()


def test_data_parallel_e_step_parity(problem):
    corpus, K, log_beta = problem
    mesh = make_mesh(data=8, model=1)
    batches = make_batches(corpus, batch_size=64, min_bucket_len=64)
    assert len(batches) == 1
    b = batches[0]
    args = (
        jnp.asarray(log_beta, jnp.float32),
        jnp.float32(2.5),
        jnp.asarray(b.word_idx),
        jnp.asarray(b.counts),
        jnp.asarray(b.doc_mask),
    )
    single = estep.e_step(*args, var_max_iters=30, var_tol=1e-7)
    fn = make_data_parallel_e_step(mesh)
    sharded = jax.jit(
        lambda *a: fn(*a, var_max_iters=30, var_tol=1e-7)
    )(*args)
    np.testing.assert_allclose(np.asarray(sharded.gamma), np.asarray(single.gamma),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(sharded.suff_stats), np.asarray(single.suff_stats),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(sharded.likelihood), float(single.likelihood),
                               rtol=1e-5)
    np.testing.assert_allclose(float(sharded.alpha_ss), float(single.alpha_ss),
                               rtol=1e-5)


def test_vocab_sharded_e_step_parity(problem):
    corpus, K, log_beta = problem
    mesh = make_mesh(data=2, model=4)
    V = corpus.num_terms
    v_pad = pad_vocab(V, 4)
    lb_pad = np.pad(log_beta, ((0, 0), (0, v_pad - V)),
                    constant_values=estep.LOG_ZERO)
    batches = make_batches(corpus, batch_size=64, min_bucket_len=64)
    b = batches[0]
    args = (
        jnp.asarray(lb_pad, jnp.float32),
        jnp.float32(2.5),
        jnp.asarray(b.word_idx),
        jnp.asarray(b.counts),
        jnp.asarray(b.doc_mask),
    )
    single = estep.e_step(*args, var_max_iters=30, var_tol=1e-7)
    e_fn, m_fn = make_vocab_sharded_fns(mesh)
    sharded = jax.jit(
        lambda *a: e_fn(*a, var_max_iters=30, var_tol=1e-7)
    )(*args)
    np.testing.assert_allclose(np.asarray(sharded.gamma), np.asarray(single.gamma),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(sharded.suff_stats), np.asarray(single.suff_stats),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(sharded.likelihood), float(single.likelihood),
                               rtol=1e-5)
    # sharded m_step matches the dense one
    lb_single = estep.m_step(single.suff_stats)
    lb_sharded = jax.jit(m_fn)(sharded.suff_stats)
    np.testing.assert_allclose(np.asarray(lb_sharded), np.asarray(lb_single),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mesh_shape,vocab_sharded", [
    ((8, 1), False),
    ((2, 4), True),
])
def test_full_training_parity(problem, mesh_shape, vocab_sharded):
    corpus, K, log_beta = problem
    cfg = LDAConfig(num_topics=K, em_max_iters=5, em_tol=0.0, batch_size=64,
                    min_bucket_len=64, estimate_alpha=True, seed=9)
    single = train_corpus(corpus, cfg)
    mesh = make_mesh(data=mesh_shape[0], model=mesh_shape[1])
    multi = train_corpus(corpus, cfg, mesh=mesh, vocab_sharded=vocab_sharded)
    np.testing.assert_allclose(
        [l for l, _ in multi.likelihoods], [l for l, _ in single.likelihoods],
        rtol=1e-4)
    np.testing.assert_allclose(np.exp(multi.log_beta), np.exp(single.log_beta),
                               atol=1e-4)
    np.testing.assert_allclose(multi.gamma, single.gamma, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(multi.alpha, single.alpha, rtol=1e-4)


def test_batch_size_divisibility_guard(problem):
    corpus, K, _ = problem
    mesh = make_mesh(data=8, model=1)
    cfg = LDAConfig(num_topics=K, batch_size=12)  # 12 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        train_corpus(corpus, cfg, mesh=mesh)
