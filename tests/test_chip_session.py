"""tools/chip_session.py orchestration mechanics (no TPU needed).

The capture runbook must preserve step output — including on nonzero
exit and timeout — and extract the bench record from the LAST parseable
JSON line (success payload or bench's structured failure record).
Steps are stubbed with tiny shell commands; the real TPU sequence is
exercised by the runbook itself on a healthy grant.
"""

import json
import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)

import chip_session


def _steps(*specs):
    return [
        (name, [sys.executable, "-c", code], timeout)
        for name, code, timeout in specs
    ]


def test_partial_output_survives_failure_and_timeout(tmp_path, monkeypatch):
    out = tmp_path / "cap.json"
    monkeypatch.setattr(chip_session, "STEPS", _steps(
        ("ok", "print('line1'); print('line2')", 30),
        ("fails", "print('partial result'); raise SystemExit(2)", 30),
        ("hangs", "import time; print('before hang', flush=True); "
                  "time.sleep(60)", 2),
        ("bench", "print('noise'); "
                  "print('{\"metric\": \"m\", \"value\": 1.5}')", 30),
    ))
    monkeypatch.setattr(sys, "argv", ["chip_session", "--out", str(out)])
    rc = chip_session.main()
    assert rc == 1  # fails/hangs steps were not green
    log = (tmp_path / "cap.json.log").read_text()
    assert "line1" in log and "line2" in log
    assert "partial result" in log          # nonzero exit keeps output
    assert "before hang" in log             # timeout keeps output
    rec = json.loads(out.read_text())
    assert rec == {"metric": "m", "value": 1.5}


def test_bench_failure_record_is_captured(tmp_path, monkeypatch):
    """bench exiting 1 with a structured failure line must still
    produce the capture file (round-4 review finding: the failure
    record was discarded one layer up)."""
    out = tmp_path / "cap.json"
    fail = json.dumps({"metric": "lda_em_throughput", "value": None,
                       "error": "backend unavailable"})
    monkeypatch.setattr(chip_session, "STEPS", _steps(
        ("bench", f"print('{fail}'); raise SystemExit(1)", 30),
    ))
    monkeypatch.setattr(sys, "argv", ["chip_session", "--out", str(out)])
    assert chip_session.main() == 1
    rec = json.loads(out.read_text())
    assert rec["value"] is None and "backend unavailable" in rec["error"]
