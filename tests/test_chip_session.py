"""tools/chip_session.py orchestration mechanics (no TPU needed).

The capture runbook must preserve step output — including on nonzero
exit and timeout — and extract the bench record from the LAST parseable
JSON line (success payload or bench's structured failure record).
Steps are stubbed with tiny shell commands; the real TPU sequence is
exercised by the runbook itself on a healthy grant.

Timing note: a `python -c` child in this image takes ~5 s to boot
(sitecustomize imports jax), which made fixed-short-timeout steps flaky
under load (round-4 verdict item 2).  run_step now gives every step a
boot grace before its own timeout clock starts; these tests rely on
that rather than on a quiet machine.
"""

import json
import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)

import chip_session


def _steps(*specs):
    return [
        (name, [sys.executable, "-c", code], timeout)
        for name, code, timeout in specs
    ]


def test_partial_output_survives_failure_and_timeout(tmp_path, monkeypatch):
    out = tmp_path / "cap.json"
    monkeypatch.setattr(chip_session, "STEPS", _steps(
        ("ok", "print('line1'); print('line2')", 30),
        ("fails", "print('partial result'); raise SystemExit(2)", 30),
        # 2 s timeout counts from the FIRST output line, not from
        # spawn — interpreter boot (~5 s under load) is covered by the
        # boot grace, so this is deterministic on a busy machine.
        ("hangs", "import time; print('before hang', flush=True); "
                  "time.sleep(60)", 2),
        ("bench", "print('noise'); "
                  "print('{\"metric\": \"m\", \"value\": 1.5}')", 30),
    ))
    monkeypatch.setattr(sys, "argv", ["chip_session", "--out", str(out)])
    rc = chip_session.main()
    assert rc == 2  # the hangs step WEDGED -> watcher backs off longer
    log = (tmp_path / "cap.json.log").read_text()
    assert "line1" in log and "line2" in log
    assert "partial result" in log          # nonzero exit keeps output
    assert "before hang" in log             # timeout keeps output
    rec = json.loads(out.read_text())
    assert rec == {"metric": "m", "value": 1.5}


def test_boot_grace_covers_slow_interpreter_start(tmp_path, monkeypatch):
    """A step whose timeout is SHORTER than interpreter boot must still
    complete: the timeout clock starts at first output (or grace
    expiry), not at spawn."""
    out = tmp_path / "cap.json"
    monkeypatch.setattr(chip_session, "STEPS", _steps(
        ("slowboot", "import time; time.sleep(3); "
                     "print('{\"metric\": \"m\", \"value\": 2.0}')", 1),
    ))
    monkeypatch.setattr(sys, "argv", ["chip_session", "--out", str(out)])
    assert chip_session.main() == 0
    log = (tmp_path / "cap.json.log").read_text()
    assert '"value": 2.0' in log


def test_all_green_is_rc0(tmp_path, monkeypatch):
    out = tmp_path / "cap.json"
    monkeypatch.setattr(chip_session, "STEPS", _steps(
        ("ok", "print('fine')", 30),
        ("bench", "print('{\"metric\": \"m\", \"value\": 3.0}')", 30),
    ))
    monkeypatch.setattr(sys, "argv", ["chip_session", "--out", str(out)])
    assert chip_session.main() == 0
    assert json.loads(out.read_text())["value"] == 3.0


def test_bench_failure_record_is_captured(tmp_path, monkeypatch):
    """bench exiting 1 with a structured failure line must still
    produce the capture file (round-4 review finding: the failure
    record was discarded one layer up) — and a run where every step
    COMPLETED but one was red is rc=1, not rc=2 (the watcher re-arms
    at normal cadence)."""
    out = tmp_path / "cap.json"
    fail = json.dumps({"metric": "lda_em_throughput", "value": None,
                       "error": "backend unavailable"})
    monkeypatch.setattr(chip_session, "STEPS", _steps(
        ("bench", f"print('{fail}'); raise SystemExit(1)", 30),
    ))
    monkeypatch.setattr(sys, "argv", ["chip_session", "--out", str(out)])
    assert chip_session.main() == 1
    rec = json.loads(out.read_text())
    assert rec["value"] is None and "backend unavailable" in rec["error"]


def test_bench_timeout_derived_from_bench_worst_case(monkeypatch):
    """The outer bench timeout must track bench's own worst-case
    budget: raising BENCH_GATE_S (or pinning BENCH_BUDGET_S) must grow
    the outer clock with it, preserving 'the inner watchdog loses to
    nothing' (round-4 advisor finding against the hard-coded 16000)."""
    import bench

    monkeypatch.delenv("BENCH_GATE_S", raising=False)
    monkeypatch.delenv("BENCH_BUDGET_S", raising=False)
    base = chip_session._bench_timeout_s()
    assert base == bench.worst_case_budget_s() + chip_session.BENCH_TIMEOUT_MARGIN_S

    monkeypatch.setenv("BENCH_GATE_S", "9000")
    grown = chip_session._bench_timeout_s()
    assert grown > base
    assert grown == bench.worst_case_budget_s() + chip_session.BENCH_TIMEOUT_MARGIN_S

    monkeypatch.setenv("BENCH_BUDGET_S", "123")
    assert chip_session._bench_timeout_s() == 123 + chip_session.BENCH_TIMEOUT_MARGIN_S
