"""Online (streaming SVI) LDA: invariants, learning progress, agreement
with the batch engine, and reference file contracts."""

import numpy as np
import jax.numpy as jnp

from oni_ml_tpu.config import LDAConfig, OnlineLDAConfig
from oni_ml_tpu.io import make_batches
from oni_ml_tpu.models import (
    OnlineLDATrainer,
    train_corpus,
    train_corpus_online,
)
from oni_ml_tpu.ops import estep

import reference_lda as ref
from test_lda import corpus_from_docs


def _full_corpus_ll(corpus, log_beta, alpha=2.5):
    """ELBO of the whole corpus under frozen topics (one batch E-step)."""
    batches = make_batches(corpus, batch_size=256, min_bucket_len=64)
    total = 0.0
    for b in batches:
        res = estep.e_step(
            jnp.asarray(log_beta, jnp.float32),
            jnp.float32(alpha),
            jnp.asarray(b.word_idx),
            jnp.asarray(b.counts),
            jnp.asarray(b.doc_mask),
            var_max_iters=30,
            var_tol=1e-7,
        )
        total += float(res.likelihood)
    return total


def test_online_learns_topics():
    docs, _ = ref.make_synthetic_corpus(num_docs=120, num_terms=40,
                                        num_topics=3, seed=11)
    V, K = 40, 4
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0, kappa=0.7, seed=1)

    trainer = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)
    ll_init = _full_corpus_ll(corpus, trainer.log_beta())
    batches = make_batches(corpus, cfg.batch_size, cfg.min_bucket_len)
    rng = np.random.default_rng(0)
    for _ in range(5):
        for i in rng.permutation(len(batches)):
            trainer.step(batches[i])
    ll_final = _full_corpus_ll(corpus, trainer.log_beta())
    assert ll_final > ll_init + 0.05 * abs(ll_init), (ll_init, ll_final)

    # topics normalized in probability space
    np.testing.assert_allclose(
        np.exp(trainer.log_beta()).sum(-1), np.ones(K), rtol=1e-6)
    # learning rate follows the Robbins-Monro schedule, strictly decreasing
    rhos = [h.rho for h in trainer.history]
    assert all(a > b for a, b in zip(rhos, rhos[1:]))


def test_online_approaches_batch_quality():
    docs, _ = ref.make_synthetic_corpus(num_docs=150, num_terms=30,
                                        num_topics=3, seed=5)
    V, K = 30, 3
    corpus = corpus_from_docs(docs, V)

    batch_cfg = LDAConfig(num_topics=K, em_max_iters=30, em_tol=1e-6,
                          batch_size=256, min_bucket_len=64, seed=2)
    batch_res = train_corpus(corpus, batch_cfg)
    ll_batch = _full_corpus_ll(corpus, batch_res.log_beta,
                               alpha=batch_res.alpha)

    online_cfg = OnlineLDAConfig(num_topics=K, batch_size=16,
                                 min_bucket_len=64, tau0=8.0, seed=2)
    online_res = train_corpus_online(corpus, online_cfg, epochs=8)
    ll_online = _full_corpus_ll(corpus, online_res.log_beta)

    # online should land within a few percent of the batch optimum
    assert ll_online > ll_batch - 0.05 * abs(ll_batch), (ll_batch, ll_online)


def test_online_writes_reference_files(tmp_path):
    docs, _ = ref.make_synthetic_corpus(num_docs=40, num_terms=25,
                                        num_topics=2, seed=3)
    V, K = 25, 3
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=32)
    result = train_corpus_online(corpus, cfg, out_dir=str(tmp_path), epochs=2)

    from oni_ml_tpu.io import formats
    lb = formats.read_beta(str(tmp_path / "final.beta"))
    gm = formats.read_gamma(str(tmp_path / "final.gamma"))
    other = formats.read_other(str(tmp_path / "final.other"))
    assert lb.shape == (K, V)
    assert gm.shape == (corpus.num_docs, K)
    assert other["num_topics"] == K and other["num_terms"] == V
    # gamma rows cover every document and stay positive
    assert (gm > 0).all()
    np.testing.assert_allclose(lb, result.log_beta, atol=1e-9)


def test_online_sharded_matches_single_device():
    """Data-parallel online steps (suff-stats psum over the mesh) produce
    the same lambda as a single device."""
    import jax
    from oni_ml_tpu.parallel import make_mesh

    docs, _ = ref.make_synthetic_corpus(num_docs=64, num_terms=20,
                                        num_topics=2, seed=4)
    V, K = 20, 3
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0, seed=6)
    batches = make_batches(corpus, cfg.batch_size, cfg.min_bucket_len)

    single = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)
    mesh = make_mesh(data=4, model=1, devices=jax.devices()[:4])
    sharded = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs,
                               mesh=mesh)
    for b in batches:
        single.step(b)
        sharded.step(b)
    np.testing.assert_allclose(np.asarray(single.lam),
                               np.asarray(sharded.lam), rtol=2e-4, atol=2e-4)
    # vocab sharding is explicitly rejected for online mode
    bad_mesh = make_mesh(data=2, model=2, devices=jax.devices()[:4])
    import pytest
    with pytest.raises(ValueError, match="data-parallel"):
        OnlineLDATrainer(cfg, num_terms=V, total_docs=10, mesh=bad_mesh)


def test_stream_extends_without_restart():
    """New micro-batches keep refining the same model state — the streaming
    property the batch reference lacks (retrain-from-scratch per day)."""
    docs, _ = ref.make_synthetic_corpus(num_docs=80, num_terms=30,
                                        num_topics=3, seed=9)
    V, K = 30, 3
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0)
    batches = make_batches(corpus, cfg.batch_size, cfg.min_bucket_len)
    trainer = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)

    # "hour 1": first half of the stream
    half = len(batches) // 2
    for b in batches[:half]:
        trainer.step(b)
    steps_after_h1 = trainer.step_count
    lam_h1 = np.asarray(trainer.lam).copy()

    # "hour 2" arrives: continues from the same state
    for b in batches[half:]:
        trainer.step(b)
    assert trainer.step_count == steps_after_h1 + (len(batches) - half)
    assert not np.allclose(np.asarray(trainer.lam), lam_h1)
