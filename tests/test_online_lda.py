"""Online (streaming SVI) LDA: invariants, learning progress, agreement
with the batch engine, and reference file contracts."""

import numpy as np
import jax.numpy as jnp
import pytest

from oni_ml_tpu.config import LDAConfig, OnlineLDAConfig
from oni_ml_tpu.io import make_batches
from oni_ml_tpu.models import (
    OnlineLDATrainer,
    train_corpus,
    train_corpus_online,
)
from oni_ml_tpu.ops import estep

import reference_lda as ref
from test_lda import corpus_from_docs


def _full_corpus_ll(corpus, log_beta, alpha=2.5):
    """ELBO of the whole corpus under frozen topics (one batch E-step)."""
    batches = make_batches(corpus, batch_size=256, min_bucket_len=64)
    total = 0.0
    for b in batches:
        res = estep.e_step(
            jnp.asarray(log_beta, jnp.float32),
            jnp.float32(alpha),
            jnp.asarray(b.word_idx),
            jnp.asarray(b.counts),
            jnp.asarray(b.doc_mask),
            var_max_iters=30,
            var_tol=1e-7,
        )
        total += float(res.likelihood)
    return total


def test_online_learns_topics():
    docs, _ = ref.make_synthetic_corpus(num_docs=120, num_terms=40,
                                        num_topics=3, seed=11)
    V, K = 40, 4
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0, kappa=0.7, seed=1)

    trainer = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)
    ll_init = _full_corpus_ll(corpus, trainer.log_beta())
    batches = make_batches(corpus, cfg.batch_size, cfg.min_bucket_len)
    rng = np.random.default_rng(0)
    for _ in range(5):
        for i in rng.permutation(len(batches)):
            trainer.step(batches[i])
    ll_final = _full_corpus_ll(corpus, trainer.log_beta())
    assert ll_final > ll_init + 0.05 * abs(ll_init), (ll_init, ll_final)

    # topics normalized in probability space
    np.testing.assert_allclose(
        np.exp(trainer.log_beta()).sum(-1), np.ones(K), rtol=1e-6)
    # learning rate follows the Robbins-Monro schedule, strictly decreasing
    rhos = [h.rho for h in trainer.history]
    assert all(a > b for a, b in zip(rhos, rhos[1:]))


def test_online_approaches_batch_quality():
    docs, _ = ref.make_synthetic_corpus(num_docs=150, num_terms=30,
                                        num_topics=3, seed=5)
    V, K = 30, 3
    corpus = corpus_from_docs(docs, V)

    batch_cfg = LDAConfig(num_topics=K, em_max_iters=30, em_tol=1e-6,
                          batch_size=256, min_bucket_len=64, seed=2)
    batch_res = train_corpus(corpus, batch_cfg)
    ll_batch = _full_corpus_ll(corpus, batch_res.log_beta,
                               alpha=batch_res.alpha)

    online_cfg = OnlineLDAConfig(num_topics=K, batch_size=16,
                                 min_bucket_len=64, tau0=8.0, seed=2)
    online_res = train_corpus_online(corpus, online_cfg, epochs=8)
    ll_online = _full_corpus_ll(corpus, online_res.log_beta)

    # online should land within a few percent of the batch optimum
    assert ll_online > ll_batch - 0.05 * abs(ll_batch), (ll_batch, ll_online)


def test_held_out_ll_improves_with_training():
    """Document-completion held-out per-token LL (models/evaluate.py):
    a trained model must predict unseen docs' held-out halves better
    than the random init, and the batch optimum must score at least
    comparably to it on the same held-out split."""
    docs, _ = ref.make_synthetic_corpus(num_docs=200, num_terms=30,
                                        num_topics=3, seed=7)
    V, K = 30, 3
    train_corpus_docs = corpus_from_docs(docs[:150], V)
    heldout = corpus_from_docs(docs[150:], V)
    ho_batches = make_batches(heldout, batch_size=64, min_bucket_len=64)

    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0, kappa=0.7, seed=1)
    trainer = OnlineLDATrainer(cfg, num_terms=V,
                               total_docs=train_corpus_docs.num_docs)
    ll_init = trainer.held_out_per_token_ll(ho_batches)
    batches = make_batches(train_corpus_docs, cfg.batch_size,
                           cfg.min_bucket_len)
    rng = np.random.default_rng(0)
    for _ in range(5):
        for i in rng.permutation(len(batches)):
            trainer.step(batches[i])
    ll_trained = trainer.held_out_per_token_ll(ho_batches)
    assert ll_trained > ll_init + 0.1, (ll_init, ll_trained)
    # per-token log-prob is bounded above by 0
    assert ll_trained < 0.0

    from oni_ml_tpu.models.evaluate import held_out_per_token_ll
    batch_res = train_corpus(
        train_corpus_docs,
        LDAConfig(num_topics=K, em_max_iters=30, em_tol=1e-6,
                  batch_size=256, min_bucket_len=64, seed=2),
    )
    ll_batch = held_out_per_token_ll(batch_res.log_beta, batch_res.alpha,
                                     ho_batches)
    assert ll_batch > ll_init + 0.1, (ll_init, ll_batch)
    # the two engines should land in the same quality neighborhood
    assert abs(ll_batch - ll_trained) < 0.5, (ll_batch, ll_trained)


def test_online_writes_reference_files(tmp_path):
    docs, _ = ref.make_synthetic_corpus(num_docs=40, num_terms=25,
                                        num_topics=2, seed=3)
    V, K = 25, 3
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=32)
    result = train_corpus_online(corpus, cfg, out_dir=str(tmp_path), epochs=2)

    from oni_ml_tpu.io import formats
    lb = formats.read_beta(str(tmp_path / "final.beta"))
    gm = formats.read_gamma(str(tmp_path / "final.gamma"))
    other = formats.read_other(str(tmp_path / "final.other"))
    assert lb.shape == (K, V)
    assert gm.shape == (corpus.num_docs, K)
    assert other["num_topics"] == K and other["num_terms"] == V
    # gamma rows cover every document and stay positive
    assert (gm > 0).all()
    np.testing.assert_allclose(lb, result.log_beta, atol=1e-9)


def test_online_sharded_matches_single_device():
    """Data-parallel online steps (suff-stats psum over the mesh) produce
    the same lambda as a single device."""
    import jax
    from oni_ml_tpu.parallel import make_mesh

    docs, _ = ref.make_synthetic_corpus(num_docs=64, num_terms=20,
                                        num_topics=2, seed=4)
    V, K = 20, 3
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0, seed=6)
    batches = make_batches(corpus, cfg.batch_size, cfg.min_bucket_len)

    single = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)
    mesh = make_mesh(data=4, model=1, devices=jax.devices()[:4])
    sharded = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs,
                               mesh=mesh)
    for b in batches:
        single.step(b)
        sharded.step(b)
    np.testing.assert_allclose(np.asarray(single.lam),
                               np.asarray(sharded.lam), rtol=2e-4, atol=2e-4)
    # vocab sharding is explicitly rejected for online mode
    bad_mesh = make_mesh(data=2, model=2, devices=jax.devices()[:4])
    import pytest
    with pytest.raises(ValueError, match="data-parallel"):
        OnlineLDATrainer(cfg, num_terms=V, total_docs=10, mesh=bad_mesh)


def test_stream_checkpoint_roundtrip_and_resume(tmp_path):
    """The streaming checkpoint writes SVI-native fields (lam/step/
    history) and a fresh trainer resumes from it bit-for-bit."""
    from oni_ml_tpu.models.online_lda import load_stream_checkpoint

    docs, _ = ref.make_synthetic_corpus(num_docs=60, num_terms=25,
                                        num_topics=2, seed=12)
    V, K = 25, 3
    corpus = corpus_from_docs(docs, V)
    ck = str(tmp_path / "stream.npz")
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          checkpoint_every=2, seed=5)
    tr = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs,
                          checkpoint_path=ck)
    for b in make_batches(corpus, cfg.batch_size, cfg.min_bucket_len):
        tr.step(b)

    z = load_stream_checkpoint(ck)
    assert set(z) == {"lam", "alpha", "step", "history"}
    assert z["step"] % cfg.checkpoint_every == 0 and z["step"] > 0
    assert all(0 < rho <= 1 for _, rho in z["history"])

    resumed = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs,
                               checkpoint_path=ck)
    assert resumed.step_count == z["step"]
    np.testing.assert_array_equal(np.asarray(resumed.lam), z["lam"])
    assert [h.rho for h in resumed.history] == [r for _, r in z["history"]]


def test_stream_checkpoint_reads_legacy_layout(tmp_path):
    """Checkpoints written by early revisions (batch-checkpoint field
    names smuggling lambda through log_beta) still load."""
    from oni_ml_tpu.models.online_lda import load_stream_checkpoint

    lam = np.random.default_rng(0).gamma(100.0, 0.01, (3, 25))
    legacy = str(tmp_path / "legacy.npz")
    np.savez(legacy, log_beta=lam, alpha=np.float64(2.5),
             em_iter=np.int64(7),
             likelihoods=np.array([[-100.0, 0.5], [-90.0, 0.4]]))
    z = load_stream_checkpoint(legacy)
    assert z["step"] == 7 and z["alpha"] == 2.5
    np.testing.assert_array_equal(z["lam"], lam)
    assert z["history"] == [(-100.0, 0.5), (-90.0, 0.4)]

    tr = OnlineLDATrainer(
        OnlineLDAConfig(num_topics=3, batch_size=16, min_bucket_len=64),
        num_terms=25, total_docs=10, checkpoint_path=legacy,
    )
    assert tr.step_count == 7 and len(tr.history) == 2

    # A genuine batch EM checkpoint (log-probabilities, all <= 0) shares
    # the legacy field names and shape but must be rejected, not fed to
    # digamma as a "lambda".
    batch_ck = str(tmp_path / "batch.npz")
    np.savez(batch_ck, log_beta=np.log(lam / lam.sum(-1, keepdims=True)),
             alpha=np.float64(2.5), em_iter=np.int64(3),
             likelihoods=np.array([[-50.0, 1.0]]))
    with pytest.raises(ValueError, match="batch EM checkpoint"):
        load_stream_checkpoint(batch_ck)


def test_stream_extends_without_restart():
    """New micro-batches keep refining the same model state — the streaming
    property the batch reference lacks (retrain-from-scratch per day)."""
    docs, _ = ref.make_synthetic_corpus(num_docs=80, num_terms=30,
                                        num_topics=3, seed=9)
    V, K = 30, 3
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0)
    batches = make_batches(corpus, cfg.batch_size, cfg.min_bucket_len)
    trainer = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)

    # "hour 1": first half of the stream
    half = len(batches) // 2
    for b in batches[:half]:
        trainer.step(b)
    steps_after_h1 = trainer.step_count
    lam_h1 = np.asarray(trainer.lam).copy()

    # "hour 2" arrives: continues from the same state
    for b in batches[half:]:
        trainer.step(b)
    assert trainer.step_count == steps_after_h1 + (len(batches) - half)
    assert not np.allclose(np.asarray(trainer.lam), lam_h1)


def test_dense_micro_batch_matches_sparse():
    """dense_em="on" must track the default sparse E-step trajectory:
    same natural-gradient updates up to dense-vs-sparse float
    reassociation (the dense path is the TPU fast path; CPU runs it in
    interpret mode)."""
    docs, _ = ref.make_synthetic_corpus(num_docs=48, num_terms=40,
                                        num_topics=3, seed=5)
    corpus = corpus_from_docs(docs, 40)
    runs = {}
    for mode in ("off", "on"):
        cfg = OnlineLDAConfig(num_topics=4, batch_size=16,
                              min_bucket_len=64, seed=3, dense_em=mode)
        tr = OnlineLDATrainer(cfg, corpus.num_terms, total_docs=48)
        for b in make_batches(corpus, cfg.batch_size, cfg.min_bucket_len):
            tr.step(b)
        runs[mode] = np.asarray(tr.lam)
    np.testing.assert_allclose(runs["on"], runs["off"], rtol=2e-3, atol=2e-3)


def test_dense_em_validation():
    with pytest.raises(ValueError, match="dense_em"):
        OnlineLDATrainer(
            OnlineLDAConfig(num_topics=4, dense_em="dense"),
            num_terms=50, total_docs=10,
        )
    # forced dense + custom e_step_fn is contradictory — and must fail
    # at construction, not at the first step() (ADVICE r2)
    with pytest.raises(ValueError, match="dense_em='on'"):
        OnlineLDATrainer(
            OnlineLDAConfig(num_topics=4, dense_em="on"),
            num_terms=50, total_docs=10,
            e_step_fn=lambda *a, **k: None,
        )


def test_update_cache_is_bounded():
    """The per-(B, L) jitted-update cache must not grow without bound
    when fed un-bucketed ragged micro-batch shapes (ADVICE r2)."""
    tr = OnlineLDATrainer(
        OnlineLDAConfig(num_topics=4, dense_em="off"),
        num_terms=50, total_docs=10_000,
    )
    cap = tr._UPDATE_CACHE_MAX
    for l in range(1, cap + 10):
        tr._get_update(8, l)
    assert len(tr._updates) == cap
    # LRU: a hit refreshes recency, so the hit survives the next insert.
    first_kept = (8, 10)
    tr._get_update(*first_kept)
    tr._get_update(8, cap + 10)
    assert first_kept in tr._updates


def test_step_many_matches_sequential_steps():
    """The chunked scan path (step_many) is step() applied in sequence:
    same lambda, same step/rho bookkeeping, same likelihood history —
    modulo the rho schedule's f32 in-scan evaluation."""
    docs, _ = ref.make_synthetic_corpus(num_docs=96, num_terms=30,
                                        num_topics=3, seed=9)
    V, K = 30, 4
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0, seed=2)
    batches = list(make_batches(corpus, cfg.batch_size, cfg.min_bucket_len))
    stream = (batches * 3)[:12]

    seq = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)
    for b in stream:
        seq.step(b)
    chunked = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)
    infos = chunked.step_many(stream, chunk=4)

    np.testing.assert_allclose(np.asarray(seq.lam), np.asarray(chunked.lam),
                               rtol=1e-4, atol=1e-5)
    assert chunked.step_count == seq.step_count == 12
    assert [i.step for i in infos] == list(range(1, 13))
    np.testing.assert_allclose([i.rho for i in infos],
                               [h.rho for h in seq.history], rtol=1e-6)
    np.testing.assert_allclose(
        [float(i.likelihood) for i in infos],
        [float(h.likelihood) for h in seq.history], rtol=1e-4)
    # sub-chunk remainders and shape changes take the per-step path
    assert chunked.step_many(stream[:3], chunk=4)


def test_step_many_mixed_shapes_preserves_order():
    """Shape changes split runs; order and results still match step()."""
    rng = np.random.default_rng(5)
    from oni_ml_tpu.io import Batch

    V, K, B = 25, 3, 8

    def mk(l, seed):
        r = np.random.default_rng(seed)
        return Batch(
            word_idx=r.integers(0, V, size=(B, l)).astype(np.int32),
            counts=r.integers(1, 4, size=(B, l)).astype(np.float32),
            doc_index=np.arange(B, dtype=np.int32),
            doc_mask=np.ones((B,), np.float32),
        )

    stream = ([mk(16, i) for i in range(5)] + [mk(32, 10 + i) for i in range(2)]
              + [mk(16, 20 + i) for i in range(2)])
    cfg = OnlineLDAConfig(num_topics=K, batch_size=B, tau0=8.0, seed=3)
    seq = OnlineLDATrainer(cfg, num_terms=V, total_docs=64)
    for b in stream:
        seq.step(b)
    chunked = OnlineLDATrainer(cfg, num_terms=V, total_docs=64)
    chunked.step_many(stream, chunk=4)
    np.testing.assert_allclose(np.asarray(seq.lam), np.asarray(chunked.lam),
                               rtol=1e-4, atol=1e-5)


def test_step_many_sharded_matches_single_device():
    """The stacked [N, B, L] chunk shards docs (axis 1) over `data` and
    scans the shard_map'd E-step — same lambda as the unsharded chunk."""
    import jax
    from oni_ml_tpu.parallel import make_mesh

    docs, _ = ref.make_synthetic_corpus(num_docs=64, num_terms=20,
                                        num_topics=2, seed=4)
    V, K = 20, 3
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0, seed=6)
    batches = list(make_batches(corpus, cfg.batch_size, cfg.min_bucket_len))
    stream = (batches * 3)[:8]

    single = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs)
    single.step_many(stream, chunk=4)
    mesh = make_mesh(data=4, model=1, devices=jax.devices()[:4])
    sharded = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs,
                               mesh=mesh)
    sharded.step_many(stream, chunk=4)
    np.testing.assert_allclose(np.asarray(single.lam),
                               np.asarray(sharded.lam), rtol=2e-4, atol=2e-4)


def test_chunked_checkpoint_lands_after_boundary(tmp_path):
    """A checkpoint_every boundary crossed mid-chunk checkpoints at the
    chunk end (the only materialized lambda), not silently never."""
    docs, _ = ref.make_synthetic_corpus(num_docs=64, num_terms=20,
                                        num_topics=2, seed=8)
    V, K = 20, 3
    corpus = corpus_from_docs(docs, V)
    cfg = OnlineLDAConfig(num_topics=K, batch_size=16, min_bucket_len=64,
                          tau0=8.0, seed=7, checkpoint_every=3)
    batches = list(make_batches(corpus, cfg.batch_size, cfg.min_bucket_len))
    path = str(tmp_path / "stream.npz")
    tr = OnlineLDATrainer(cfg, num_terms=V, total_docs=corpus.num_docs,
                          checkpoint_path=path)
    tr.step_many((batches * 2)[:4], chunk=4)   # crosses step 3 mid-chunk
    from oni_ml_tpu.models.online_lda import load_stream_checkpoint

    ck = load_stream_checkpoint(path)
    assert ck["step"] == 4                     # end-of-chunk state
    np.testing.assert_allclose(ck["lam"], np.asarray(tr.lam))
