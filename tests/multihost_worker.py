"""Worker process for tests/test_multihost.py.

Runs one rank of a 2-process jax.distributed CPU cluster (2 virtual
devices per process -> a 4-device global (data, model) mesh spanning
both) and trains the small synthetic corpus through the REAL multi-host
code paths the single-process suite cannot reach:

- `jax.device_put` onto shardings spanning non-addressable devices,
- `to_host`'s `process_allgather` branch (models/lda.py) — the arrays
  are genuinely not fully addressable here,
- `_is_coordinator` gating of likelihood.dat / final.* / checkpoint
  writes against a shared day directory,
- the `initialize_distributed` bootstrap (parallel/mesh.py) that
  `ml_ops --multihost` calls.

Each rank dumps its LDAResult to proc<pid>.npz; the launcher asserts
rank parity and compares against a plain single-process run.

Usage: multihost_worker.py <port> <pid> <num_procs> <outdir>
"""

import os
import sys


def main() -> int:
    port, pid, nprocs, outdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    )
    # Backend setup must precede any jax import side effects: CPU-only,
    # two virtual local devices per process.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    from oni_ml_tpu.parallel import initialize_distributed, make_mesh

    initialize_distributed(f"localhost:{port}", nprocs, pid)

    import jax
    import numpy as np

    assert jax.process_count() == nprocs
    assert len(jax.devices()) == 2 * nprocs
    assert len(jax.local_devices()) == 2

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import reference_lda as ref
    from test_lda import corpus_from_docs

    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.models import train_corpus

    docs, _ = ref.make_synthetic_corpus(
        num_docs=80, num_terms=25, num_topics=3, seed=21
    )
    corpus = corpus_from_docs(docs, 25)
    cfg = LDAConfig(
        num_topics=3, em_max_iters=6, em_tol=0.0, batch_size=32,
        min_bucket_len=64, seed=4, checkpoint_every=2, fused_em_chunk=4,
    )
    mesh = make_mesh(data=2 * nprocs, model=1)
    day_dir = os.path.join(outdir, "day")
    os.makedirs(day_dir, exist_ok=True)
    res = train_corpus(corpus, cfg, out_dir=day_dir, mesh=mesh)

    # Vocab-sharded DENSE plan on a (2, 2) mesh spanning both processes:
    # the model-axis [B, K] psum inside the fixed point and the
    # column-sharded beta/suff-stats now genuinely cross hosts
    # (config 4's multi-chip path, parallel.make_vocab_sharded_dense_e_step).
    import dataclasses

    vs_mesh = make_mesh(data=nprocs, model=2)
    vs_res = train_corpus(
        corpus,
        # warm start off: the launcher pins this trajectory against the
        # (fresh-start) sparse data-parallel run above.
        dataclasses.replace(cfg, dense_em="on", checkpoint_every=0,
                            warm_start_gamma=False),
        mesh=vs_mesh,
        vocab_sharded=True,
    )

    # Streaming trainer through the same mesh: its checkpoint path calls
    # the collective _to_host BEFORE the coordinator gate — the old
    # gate-first ordering deadlocks exactly here (ADVICE r2 finding).
    from oni_ml_tpu.config import OnlineLDAConfig
    from oni_ml_tpu.io import make_batches
    from oni_ml_tpu.models import OnlineLDATrainer

    stream_ck = os.path.join(outdir, "day", "stream.npz")
    ocfg = OnlineLDAConfig(num_topics=3, batch_size=32, min_bucket_len=64,
                           checkpoint_every=1, seed=4)
    trainer = OnlineLDATrainer(ocfg, num_terms=25, total_docs=corpus.num_docs,
                               mesh=mesh, checkpoint_path=stream_ck)
    for b in make_batches(corpus, ocfg.batch_size, ocfg.min_bucket_len):
        trainer.step(b)
    lam = np.asarray(trainer._to_host(trainer.lam))

    # Full runner pipeline against the shared day dir: host-only stages
    # and all writes are coordinator-only, stage decisions broadcast, and
    # every rank joins stage_lda's collectives (runner/ml_ops.py
    # run_pipeline's multi-host contract).
    from oni_ml_tpu.config import PipelineConfig, ScoringConfig
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    flow_csv = os.path.join(outdir, "flow.csv")
    if pid == 0:  # shared dir: one writer is the point
        rows = ["hdr"]
        rng = np.random.default_rng(3)
        for i in range(200):
            c = ["0"] * 27
            c[4], c[5], c[6] = "3", "14", "9"
            c[8] = f"10.0.0.{i % 11}"
            c[9] = f"10.0.1.{i % 7}"
            c[10], c[11] = "443", str(1025 + int(rng.integers(0, 500)))
            c[16], c[17] = "9", str(int(rng.integers(40, 1500)))
            rows.append(",".join(c))
        with open(flow_csv, "w") as f:
            f.write("\n".join(rows) + "\n")
    pipe_cfg = PipelineConfig(
        data_dir=outdir, flow_path=flow_csv,
        lda=LDAConfig(num_topics=3, em_max_iters=4, em_tol=0.0,
                      batch_size=32, min_bucket_len=64, seed=4),
        scoring=ScoringConfig(threshold=0.5),
    )
    metrics = run_pipeline(pipe_cfg, "20260101", "flow", mesh=mesh)

    np.savez(
        os.path.join(outdir, f"proc{pid}.npz"),
        log_beta=res.log_beta,
        gamma=res.gamma,
        alpha=np.float64(res.alpha),
        lls=np.asarray([ll for ll, _ in res.likelihoods], np.float64),
        vs_log_beta=vs_res.log_beta,
        vs_lls=np.asarray([ll for ll, _ in vs_res.likelihoods], np.float64),
        stream_lam=lam,
        stream_steps=np.int64(trainer.step_count),
        pipeline_stages=np.int64(len(metrics)),
    )
    print(f"WORKER_OK {pid}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
