"""Worker process for tests/test_multihost.py.

Runs one rank of an N-process jax.distributed CPU cluster through the
REAL distributed-EM code paths (host-local E-step shards + explicit
suff-stats allreduce — parallel/allreduce.py) that the single-process
suite cannot reach:

- the KV-ring allgather over the coordination client's store (the
  portable transport: cross-process XLA collectives do not exist on the
  CPU backend),
- the corpus-derived shard plan (parallel/shard_plan.py) and the
  per-shard partial-stats programs (fused.make_partial_runner),
- the sparse Pallas engine over PER-SHARD bucketed layouts under
  distribution (estep_engine="sparse", interpret mode on CPU),
- the distributed streaming trainer (row-split micro-batches, lambda
  blended from reduced stats on every rank),
- `_is_coordinator` gating of likelihood.dat / final.* / checkpoint
  writes against a shared day directory,
- run_pipeline's multi-host contract (KV stage-decision broadcasts,
  coordinator-only writes, every rank joining stage_lda's reduce).

Each rank dumps its results to proc<pid>.npz; the launcher asserts
bitwise rank parity, compares against plain single-process training,
and byte-compares the coordinator artifacts of a 1-process run of THIS
SAME script against the 2-process run's (the shard plan is derived from
the corpus, not the rank count, so the bytes must match exactly).

Usage: multihost_worker.py <port> <pid> <num_procs> <outdir>
"""

import os
import sys


def main() -> int:
    port, pid, nprocs, outdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    )
    # Backend setup must precede any jax import side effects: CPU-only,
    # two virtual local devices per process (so the host-local mesh
    # path is exercised, not just mesh=None).
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    from oni_ml_tpu.parallel import initialize_distributed, local_mesh

    if nprocs > 1:
        # The 1-process baseline run needs no cluster at all — the
        # distributed path degenerates to the local transport, which is
        # exactly the byte-identity contract under test.
        initialize_distributed(f"localhost:{port}", nprocs, pid)

    import dataclasses

    import jax
    import numpy as np

    assert jax.process_count() == nprocs
    assert len(jax.local_devices()) == 2

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import reference_lda as ref
    from test_lda import corpus_from_docs

    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.models import train_corpus

    docs, _ = ref.make_synthetic_corpus(
        num_docs=80, num_terms=25, num_topics=3, seed=21
    )
    corpus = corpus_from_docs(docs, 25)
    cfg = LDAConfig(
        num_topics=3, em_max_iters=6, em_tol=0.0, batch_size=32,
        min_bucket_len=64, seed=4, checkpoint_every=2,
    )

    # Run 1 — dense family (XLA on CPU) over a HOST-LOCAL data mesh:
    # each rank's shards run shard_map'd over its own 2 devices, the
    # cross-process reduce is the explicit allreduce.  Shared day dir:
    # the coordinator alone writes final.* / likelihood.dat /
    # checkpoint.npz (checkpoint_every=2 exercises the mid-run path).
    day_dir = os.path.join(outdir, "day")
    os.makedirs(day_dir, exist_ok=True)
    res = train_corpus(
        corpus, cfg, out_dir=day_dir, mesh=local_mesh(data=2, model=1),
        distributed=True,
    )

    # Run 2 — the SPARSE Pallas engine under distribution (the PR 9
    # engine was single-process; per-shard bucketed layouts + the
    # allreduce are what let it survive scale-out).  min_bucket_len
    # floors at the lane tile via sparse_min_bucket_len; interpret
    # kernels on CPU.  Fresh-start config so the launcher can pin the
    # trajectory against a 1-process dense run.
    sparse_dir = os.path.join(outdir, "day_sparse")
    os.makedirs(sparse_dir, exist_ok=True)
    sp = train_corpus(
        corpus,
        dataclasses.replace(cfg, estep_engine="sparse",
                            checkpoint_every=0),
        out_dir=sparse_dir,
        distributed=True,
    )
    assert sp.plan["estep_engine"]["value"] == "sparse", sp.plan

    # Run 3 — distributed streaming trainer: every micro-batch
    # row-splits across ranks, lambda blends from the reduced stats on
    # every rank; the coordinator owns the stream checkpoint.
    from oni_ml_tpu.config import OnlineLDAConfig
    from oni_ml_tpu.io import make_batches
    from oni_ml_tpu.models import OnlineLDATrainer

    stream_ck = os.path.join(day_dir, "stream.npz")
    ocfg = OnlineLDAConfig(num_topics=3, batch_size=32, min_bucket_len=64,
                           checkpoint_every=1, seed=4)
    trainer = OnlineLDATrainer(
        ocfg, num_terms=25, total_docs=corpus.num_docs,
        checkpoint_path=stream_ck, distributed=True,
    )
    # Same pad rule as train_corpus_online: the batch axis must divide
    # by the process count AFTER padding (8 * nprocs, not max(8, n) —
    # 8 is not a multiple of 3).
    for b in make_batches(corpus, ocfg.batch_size, ocfg.min_bucket_len,
                          pad_multiple=8 * max(nprocs, 1)):
        trainer.step(b)
    lam = np.asarray(trainer._to_host(trainer.lam))

    # Run 4 — full runner pipeline against the shared day dir:
    # host-only stages and all writes are coordinator-only, stage
    # decisions broadcast over the KV store, and every rank joins
    # stage_lda's suff-stats reduce (runner/ml_ops.py multi-host
    # contract).
    from oni_ml_tpu.config import PipelineConfig, ScoringConfig
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    flow_csv = os.path.join(outdir, "flow.csv")
    if pid == 0:  # shared dir: one writer is the point
        rows = ["hdr"]
        rng = np.random.default_rng(3)
        for i in range(200):
            c = ["0"] * 27
            c[4], c[5], c[6] = "3", "14", "9"
            c[8] = f"10.0.0.{i % 11}"
            c[9] = f"10.0.1.{i % 7}"
            c[10], c[11] = "443", str(1025 + int(rng.integers(0, 500)))
            c[16], c[17] = "9", str(int(rng.integers(40, 1500)))
            rows.append(",".join(c))
        with open(flow_csv, "w") as f:
            f.write("\n".join(rows) + "\n")
    pipe_cfg = PipelineConfig(
        data_dir=outdir, flow_path=flow_csv,
        lda=LDAConfig(num_topics=3, em_max_iters=4, em_tol=0.0,
                      batch_size=32, min_bucket_len=64, seed=4),
        scoring=ScoringConfig(threshold=0.5),
    )
    metrics = run_pipeline(pipe_cfg, "20260101", "flow",
                           mesh=local_mesh(data=2, model=1))
    stage_records = [m for m in metrics
                     if m.get("stage") in ("pre", "corpus", "lda", "score")]

    np.savez(
        os.path.join(outdir, f"proc{pid}.npz"),
        log_beta=res.log_beta,
        gamma=res.gamma,
        alpha=np.float64(res.alpha),
        lls=np.asarray([ll for ll, _ in res.likelihoods], np.float64),
        sp_log_beta=sp.log_beta,
        sp_gamma=sp.gamma,
        sp_lls=np.asarray([ll for ll, _ in sp.likelihoods], np.float64),
        stream_lam=lam,
        stream_steps=np.int64(trainer.step_count),
        pipeline_stages=np.int64(len(stage_records)),
    )
    print(f"WORKER_OK {pid}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
