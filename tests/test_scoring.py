"""Scoring tests: dot-product semantics, the reference's fallback quirks
(0.05 flow / 0.1 dns, SURVEY §2.6), threshold filter + ascending sort."""

import numpy as np

from oni_ml_tpu.features import featurize_dns, featurize_flow
from oni_ml_tpu.io import formats
from oni_ml_tpu.scoring import ScoringModel, score_dns, score_flow

from test_features import ZERO_CUTS, dns_row, flow_row


def make_model(doc_names, vocab, k=4, fallback=0.05, seed=0):
    rng = np.random.default_rng(seed)
    theta = rng.dirichlet(np.ones(k), size=len(doc_names))
    p = rng.dirichlet(np.ones(len(vocab)), size=k).T if vocab else np.zeros((0, k))
    return ScoringModel.from_results(doc_names, theta, vocab, p, fallback)


def test_unseen_flow_event_scores_fallback_squared():
    # Fully-unseen IP and word: score = K * 0.05 * 0.05 = 0.05 at K=20 —
    # the reference's "unseen traffic is NOT maximally suspicious" quirk.
    model = ScoringModel.from_results([], np.zeros((0, 20)), [], np.zeros((0, 20)), 0.05)
    f = featurize_flow(["h", flow_row()], precomputed_cuts=ZERO_CUTS)
    rows, scores = score_flow(f, model, threshold=1.0)
    assert len(rows) == 1
    np.testing.assert_allclose(scores[0], 20 * 0.05 * 0.05, rtol=1e-6)


def test_flow_score_is_min_of_src_dest():
    f = featurize_flow(["h", flow_row(sip="a", dip="b")], precomputed_cuts=ZERO_CUTS)
    k = 3
    theta_a = np.array([1.0, 0.0, 0.0])
    theta_b = np.array([0.0, 1.0, 0.0])
    p_src = np.array([0.9, 0.1, 0.0])   # <theta_a, p_src> = 0.9
    p_dst = np.array([0.2, 0.3, 0.5])   # <theta_b, p_dst> = 0.3
    model = ScoringModel.from_results(
        ["a", "b"], np.stack([theta_a, theta_b]),
        [f.src_word[0], f.dest_word[0]], np.stack([p_src, p_dst]), 0.05,
    )
    rows, scores = score_flow(f, model, threshold=1.0)
    np.testing.assert_allclose(scores[0], 0.3)
    cols = rows[0].split(",")
    # row = 35 featurized cols + src_score + dest_score
    assert len(cols) == 37
    np.testing.assert_allclose(float(cols[-2]), 0.9)
    np.testing.assert_allclose(float(cols[-1]), 0.3)


def test_threshold_filters_and_sorts_ascending():
    events = [flow_row(sip=f"ip{i}", dip="d") for i in range(5)]
    f = featurize_flow(["h"] + events, precomputed_cuts=ZERO_CUTS)
    k = 2
    # Give each sip a distinct score via theta[0]; word prob fixed.
    theta = np.array([[0.5, 0.5], [0.1, 0.9], [0.9, 0.1], [0.3, 0.7], [0.7, 0.3]])
    vocab = [f.src_word[0], f.dest_word[0]]
    p = np.array([[1.0, 0.0], [1.0, 0.0]])  # score = theta[0]
    model = ScoringModel.from_results(
        [f"ip{i}" for i in range(5)] + ["d"],
        np.concatenate([theta, [[1.0, 0.0]]]), vocab, p, 0.05,
    )
    rows, scores = score_flow(f, model, threshold=0.6)
    # dest score = <theta_d, p_dest> = 1.0 -> min = src score
    assert list(scores) == sorted(scores)
    assert all(s < 0.6 for s in scores)
    assert len(rows) == 3  # 0.5, 0.1, 0.3 survive


def test_dns_scoring_fallback_and_row_shape():
    f = featurize_dns([dns_row(ip="known"), dns_row(ip="unknown")])
    theta = np.full((1, 20), 1 / 20)
    p = np.full((1, 20), 1 / 20)
    model = ScoringModel.from_results(["known"], theta, [f.word[0]], p, 0.1)
    rows, scores = score_dns(f, model, threshold=1.0)
    assert len(rows) == 2
    # unknown ip: 20 * 0.1 * (1/20) = 0.1; known: 20 * (1/20)^2 = 0.05
    np.testing.assert_allclose(sorted(scores), [0.05, 0.1], rtol=1e-6)
    assert all(len(r.split(",")) == 16 for r in rows)


def test_model_roundtrip_through_result_files(tmp_path):
    rng = np.random.default_rng(3)
    gamma = rng.uniform(size=(4, 5))
    log_beta = np.log(rng.dirichlet(np.ones(7), size=5))
    doc_names = [f"10.0.0.{i}" for i in range(4)]
    vocab = [f"w{i}" for i in range(7)]
    dpath, wpath = str(tmp_path / "d.csv"), str(tmp_path / "w.csv")
    formats.write_doc_results(dpath, doc_names, gamma)
    formats.write_word_results(wpath, vocab, log_beta)
    model = ScoringModel.from_files(dpath, wpath, fallback=0.1)
    # theta rows normalized; p columns come from exp-normalized beta.
    np.testing.assert_allclose(model.theta[:4].sum(axis=1), 1.0, rtol=1e-12)
    np.testing.assert_allclose(
        model.p[:7], np.exp(log_beta).T, rtol=1e-10, atol=1e-12
    )
    assert model.ip_index["10.0.0.2"] == 2
    assert model.word_index["w6"] == 6
    np.testing.assert_allclose(model.theta[4], 0.1)  # fallback row


def _weird_flow_day(tmp_path, n=400):
    """Native-backed flow day with str(float) boundary ports, NaN rows,
    and CRLF — the emit seams that must stay byte-identical."""
    from oni_ml_tpu.features import native_flow

    rng = np.random.default_rng(5)
    lines = ["hdr"]
    ports = ["80", "443", "0", "1e15", "1e16", "0.0001", "52100", "##"]
    for i in range(n):
        c = ["x"] * 27
        c[4], c[5], c[6] = str(int(rng.integers(0, 24))), "30", "15"
        c[8], c[9] = f"10.0.0.{i % 17}", f"192.168.9.{i % 13}"
        c[10], c[11] = ports[i % len(ports)], ports[(i * 3 + 1) % len(ports)]
        c[16], c[17] = str(int(rng.integers(1, 300))), "##" if i % 37 == 0 else str(int(rng.integers(40, 5000)))
        lines.append(",".join(c))
    p = tmp_path / "flow.csv"
    p.write_bytes(("\r\n".join(lines) + "\n").encode())
    return native_flow.featurize_flow_file(str(p))


def test_native_flow_emit_matches_python_bytes(tmp_path):
    from oni_ml_tpu import native_emit
    from oni_ml_tpu.scoring import score_flow_csv
    from oni_ml_tpu.scoring.score import _batched_scores, _keep_order

    if not native_emit.available():
        import pytest

        pytest.skip("native emit unavailable")
    feats = _weird_flow_day(tmp_path)
    rng = np.random.default_rng(0)
    ips = sorted(set(feats.ip_table))[: len(feats.ip_table) // 2]
    vocab = sorted(set(feats.word_table))[: max(1, len(feats.word_table) // 2)]
    k = 6
    model = ScoringModel.from_results(
        ips, rng.dirichlet(np.ones(k), size=len(ips)),
        vocab, rng.dirichlet(np.ones(len(vocab)), size=k).T, fallback=0.05,
    )
    blob, scores = score_flow_csv(feats, model, threshold=np.inf)
    assert len(scores) == feats.num_raw_events  # all kept

    # Python reference loop over the same order
    n = feats.num_raw_events
    ip_map = model.ip_rows(feats.ip_table)
    word_map = model.word_rows(feats.word_table)
    src = _batched_scores(model, ip_map[feats.sip_id[:n]], word_map[feats.sw_id[:n]])
    dest = _batched_scores(model, ip_map[feats.dip_id[:n]], word_map[feats.dw_id[:n]])
    order = _keep_order(np.minimum(src, dest), np.inf)
    want = "".join(
        ",".join(feats.featurized_row(i) + [str(src[i]), str(dest[i])]) + "\n"
        for i in order
    ).encode("utf-8")
    assert blob == want


def test_native_dns_emit_matches_python_bytes():
    from oni_ml_tpu.features import native_dns
    from oni_ml_tpu import native_emit
    from oni_ml_tpu.scoring import score_dns_csv
    from oni_ml_tpu.scoring.score import _batched_scores, _keep_order

    if not (native_emit.available() and native_dns.available()):
        import pytest

        pytest.skip("native libs unavailable")
    rng = np.random.default_rng(2)
    qnames = ["www.google.com", "a.b.co.uk", "4.3.2.1.in-addr.arpa", "x",
              "dga-9x.evil.biz", "deep.sub.example.org", "comma,in.field.com"]
    rows = [
        ["t", str(1454000000 + int(rng.integers(0, 9999))),
         str(int(rng.integers(40, 1500))), f"172.16.0.{i % 9}",
         qnames[i % len(qnames)], "1", str(int(rng.integers(1, 17))),
         str(int(rng.integers(0, 4)))]
        for i in range(300)
    ]
    feats = native_dns.featurize_dns_sources([rows])
    k = 5
    ips = sorted(set(feats.ip_table))[:5]
    vocab = sorted(set(feats.word_table))[: max(1, len(feats.word_table) - 3)]
    model = ScoringModel.from_results(
        ips, rng.dirichlet(np.ones(k), size=len(ips)),
        vocab, rng.dirichlet(np.ones(len(vocab)), size=k).T, fallback=0.1,
    )
    blob, scores = score_dns_csv(feats, model, threshold=np.inf)

    n = feats.num_raw_events
    ip_map = model.ip_rows(feats.ip_table)
    word_map = model.word_rows(feats.word_table)
    s = _batched_scores(model, ip_map[feats.ip_id[:n]], word_map[feats.word_id[:n]])
    order = _keep_order(s, np.inf)
    want = "".join(
        ",".join(feats.featurized_row(i) + [str(s[i])]) + "\n" for i in order
    ).encode("utf-8")
    assert blob == want


def test_model_row_lookup_matches_dict_semantics():
    """The vectorized searchsorted LUT must reproduce dict.get exactly,
    including hostile keys: numpy's U dtype strips TRAILING NULs on
    conversion, so 'foo\\x00' and 'foo' would otherwise collide (raw
    DNS names are legal inputs here)."""
    k = 3
    long_name = "x" * 250 + ".evil"   # past the vector-path width cap
    names = ["foo", "foo\x00", "a\x00b", "zz", "", "Ⴆ.example", long_name]
    theta = np.arange(len(names) * k, dtype=np.float64).reshape(-1, k)
    model = ScoringModel.from_results(
        names, theta, ["w"], np.ones((1, k)), fallback=0.1
    )
    queries = names + ["foo\x00\x00", "miss", "a", "a\x00", "\x00",
                       long_name + "!", "y" * 300]
    fb = len(model.ip_index)
    want = [model.ip_index.get(q, fb) for q in queries]
    got = list(model.ip_rows(queries))
    assert got == want

    empty = ScoringModel.from_results([], np.zeros((0, k)), [],
                                      np.zeros((0, k)), fallback=0.1)
    assert list(empty.ip_rows(["x", "y\x00"])) == [0, 0]


def test_surrogate_bytes_flow_through_scoring():
    """A DNS day containing a non-UTF-8 raw name (surrogateescape
    str) must score without crashing, and the emitted CSV must carry
    the ORIGINAL raw bytes."""
    from oni_ml_tpu.features.native_dns import featurize_dns_sources
    from oni_ml_tpu.scoring import score_dns_csv

    rows = [
        ["t", str(1454000000 + i), "100", f"10.0.0.{i % 4}",
         "evil\udce9\udc80.bad" if i == 3 else f"s{i % 5}.ok.com",
         "1", "1", "0"]
        for i in range(40)
    ]
    feats = featurize_dns_sources([rows])   # falls back to Python path
    vocab = sorted(set(feats.word))
    ips = sorted({feats.client_ip(i) for i in range(feats.num_events)})
    model = ScoringModel.from_results(
        ips, np.full((len(ips), 4), 0.25), vocab,
        np.full((len(vocab), 4), 0.25), fallback=0.1,
    )
    blob, scores = score_dns_csv(feats, model, threshold=np.inf)
    assert len(scores) == 40
    assert b"evil\xe9\x80.bad" in blob


def test_index_rows_hostile_keys_exact_dict_semantics():
    """Model-row resolution must match dict.get exactly for hostile
    keys — NULs anywhere, over-long strings, empty — with misses on
    the fallback row.  (The former searchsorted LUT needed an oddball
    side path for these; the dict path is exact by construction, and
    this pins it stays so.)"""
    from oni_ml_tpu.scoring.score import _index_rows

    cases = [
        "", "a", "a" * 48, "a" * 49, "a" * 300,
        "x\x00", "x\x00y", "\x00", "a" * 48 + "\x00",
    ]
    index = {s: i for i, s in enumerate(cases)}
    assert _index_rows(index, cases, -1).tolist() == list(range(len(cases)))
    assert _index_rows(index, ["missing", "y\x00"], -1).tolist() == [-1, -1]
    assert _index_rows({}, ["a"], 7).tolist() == [7]
    assert _index_rows(index, [], -1).tolist() == []


def _wc_parity(feats, tmp_path):
    from oni_ml_tpu.io import formats
    from oni_ml_tpu import native_emit

    blob = native_emit.word_counts_emit(feats)
    if blob is None:  # no toolchain: nothing to compare
        return
    path = tmp_path / "wc.dat"
    formats.write_word_counts(str(path), feats.word_counts())
    assert blob == path.read_bytes()


def test_native_word_counts_emit_flow(tmp_path):
    """wc_emit parity: the C++ word_counts buffer is byte-identical to
    formats.write_word_counts over the container's Python triples.
    The day comes from bench._write_flow_day (schema-correct since the
    round-3 column-shift fix), so the parity runs over realistic
    multi-word/multi-ip tables, not a degenerate single-source day."""
    import bench
    from oni_ml_tpu.features.native_flow import featurize_flow_file

    p = tmp_path / "day.csv"
    with open(p, "w") as f:
        bench._write_flow_day(f, 400, n_src=40, n_dst=20)
    _wc_parity(featurize_flow_file(str(p)), tmp_path)


def test_native_word_counts_emit_dns(tmp_path):
    import numpy as np

    from oni_ml_tpu.features.native_dns import featurize_dns_sources

    rng = np.random.default_rng(6)
    rows = [
        ["t", str(1454000000 + i), str(int(rng.integers(40, 1500))),
         f"10.0.{i % 7}.{i % 11}", f"s{i % 9}.dom{i % 13}.com", "1",
         str(int(rng.integers(1, 17))), str(int(rng.integers(0, 4)))]
        for i in range(500)
    ]
    _wc_parity(featurize_dns_sources([rows]), tmp_path)


def test_native_lib_missing_symbol_degrades(tmp_path):
    """A prebuilt .so predating a newly added export (no compiler to
    rebuild) must degrade to the Python fallback (load() -> None), not
    crash the caller with AttributeError at symbol-configure time."""
    from oni_ml_tpu.native_build import NativeLib

    src = tmp_path / "t.cpp"
    src.write_text('extern "C" int foo() { return 1; }\n')

    import shutil

    import pytest

    if not shutil.which("g++") or __import__("os").environ.get(
        "ONI_ML_TPU_NO_NATIVE"
    ):
        pytest.skip("no C++ toolchain: the build-failure path returns "
                    "None before configure ever runs")

    def configure(lib):
        lib.no_such_symbol.restype = None   # AttributeError on lookup

    nl = NativeLib(str(src), str(tmp_path / "t.so"), configure)
    with pytest.warns(UserWarning, match="native symbol configuration"):
        assert nl.load() is None
    assert not nl.available()


def test_score_dot_native_matches_numpy():
    """The C gather-dot must be BIT-identical to the einsum path (same
    k-order accumulation, fp-contract off): scored CSVs embed
    str(score), so even one ulp moves golden bytes."""
    from oni_ml_tpu import native_emit

    if not native_emit.available():
        import pytest

        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(11)
    for k in (3, 20, 50):
        theta = rng.random((500, k))
        p = rng.random((70, k))
        ia = rng.integers(0, 500, 20_000).astype(np.int32)
        ib = rng.integers(0, 70, 20_000).astype(np.int32)
        # Reference accumulation: strict sequential fold over k (the
        # reference's zip/map/sum, flow_post_lda.scala:231).
        a, b = theta[ia], p[ib]
        want = a[:, 0] * b[:, 0]
        for j in range(1, k):
            want = want + a[:, j] * b[:, j]
        got = native_emit.score_dot(theta, p, ia, ib)
        assert got.dtype == np.float64
        assert np.array_equal(got, want)   # bitwise, not allclose


def test_batched_scores_rejects_out_of_range_ids_both_engines():
    """Both scoring engines must RAISE on out-of-range/negative model
    rows — numpy fancy indexing would silently wrap -1 into the
    fallback row, masking a caller bug, and the C loop would read
    arbitrary memory."""
    import pytest

    import oni_ml_tpu.native_emit as ne
    from oni_ml_tpu.scoring.score import _batched_scores

    class M:
        theta = np.ones((4, 3))
        p = np.ones((5, 3))

    bad = [
        (np.array([-1, 0], np.int32), np.array([0, 1], np.int32)),
        (np.array([0, 4], np.int32), np.array([0, 1], np.int32)),
        (np.array([0, 1], np.int32), np.array([0, 5], np.int32)),
    ]
    real = ne.score_dot
    for engines in ("native", "fallback"):
        if engines == "fallback":
            ne.score_dot = lambda *a, **k: None
        try:
            for ia, ib in bad:
                with pytest.raises(IndexError):
                    _batched_scores(M(), ia, ib)
            ok = _batched_scores(
                M(), np.array([0, 3], np.int32), np.array([4, 0], np.int32)
            )
            assert np.allclose(ok, 3.0)
        finally:
            ne.score_dot = real


def test_score_dot_rejects_pre_cast_overflow_ids():
    """Range validation must run BEFORE the int32 cast: an int64 id of
    2**32 wraps to 0 post-cast and would silently score row 0."""
    import pytest

    from oni_ml_tpu import native_emit

    if not native_emit.available():
        pytest.skip("native lib unavailable")
    theta = np.ones((4, 3))
    p = np.ones((5, 3))
    with pytest.raises(IndexError):
        native_emit.score_dot(
            theta, p,
            np.array([2 ** 32, 0], np.int64), np.array([0, 1], np.int64),
        )
