"""Crash recovery through the telemetry flight recorder: SIGKILL a
pipeline run mid-EM and prove the journal replays to a consistent
state, tolerates the half-written tail, streams sub-run likelihood
points, and drives `--stages` resume past the completed stages.

This is the r05 loss mode end-to-end: a multi-hour fit dying mid-run
used to take every observability record with it; now the day dir's
run_journal.jsonl carries the completed stages, the EM likelihood
trajectory up to the kill, and enough structure for the next run to
pick up where the dead one stopped.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from oni_ml_tpu.telemetry import Journal, RunJournal

HERE = os.path.dirname(os.path.abspath(__file__))


def _write_flow_day(path: str, n: int = 60) -> None:
    """Tiny 27-column flow day (test_features.flow_row's layout,
    inlined so the kill subprocess needs no test imports)."""
    rng = np.random.default_rng(7)
    lines = ["dummy,header"]
    for _ in range(n):
        row = ["##"] * 27
        row[4] = str(int(rng.integers(0, 24)))
        row[5] = str(int(rng.integers(0, 60)))
        row[6] = str(int(rng.integers(0, 60)))
        row[8] = f"10.0.0.{rng.integers(1, 9)}"
        row[9] = f"172.16.0.{rng.integers(1, 9)}"
        row[10] = str(rng.choice([80, 443, 55000, 0]))
        row[11] = str(rng.choice([80, 6000, 70000]))
        row[16] = str(int(rng.integers(1, 100)))
        row[17] = str(int(rng.integers(40, 10000)))
        lines.append(",".join(row))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


_CHILD_SCRIPT = """
import sys
data_dir, raw = sys.argv[1], sys.argv[2]
from oni_ml_tpu.config import (FeedbackConfig, LDAConfig, PipelineConfig,
                               ScoringConfig)
from oni_ml_tpu.runner import run_pipeline

cfg = PipelineConfig(
    data_dir=data_dir, flow_path=raw,
    # em_tol=0 never converges and em_max_iters is effectively
    # unbounded: the child runs EM until the parent kills it.
    # host_sync_every=1 streams one journal em_ll point per iteration.
    lda=LDAConfig(num_topics=4, em_max_iters=1000000, em_tol=0.0,
                  batch_size=32, min_bucket_len=16, seed=3,
                  fused_em_chunk=4, host_sync_every=1),
    feedback=FeedbackConfig(dup_factor=5),
    scoring=ScoringConfig(threshold=1.1),
)
run_pipeline(cfg, "20160122", "flow", force=True)
"""


@pytest.fixture()
def killed_run(tmp_path):
    """Launch the pipeline in a subprocess, wait for EM likelihood
    points to appear in the journal (pre + corpus complete, LDA in
    flight), then SIGKILL it mid-EM."""
    raw = str(tmp_path / "flow.csv")
    _write_flow_day(raw)
    jpath = str(tmp_path / "20160122" / "run_journal.jsonl")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("ONI_ML_TPU_TESTS_ON_TPU", None)
    log = open(str(tmp_path / "child.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD_SCRIPT, str(tmp_path), raw],
        stdout=log, stderr=subprocess.STDOUT,
        cwd=os.path.dirname(HERE), env=env,
    )
    try:
        deadline = time.monotonic() + 240.0
        n_ll = 0
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                log.close()
                pytest.fail(
                    "pipeline child exited before the kill (rc="
                    f"{proc.returncode}):\n"
                    + open(str(tmp_path / "child.log")).read()[-2000:]
                )
            if os.path.exists(jpath):
                n_ll = sum(
                    1 for r in Journal.replay(jpath)
                    if r.get("kind") == "em_ll"
                )
                if n_ll >= 5:
                    break
            time.sleep(0.05)
        else:
            pytest.fail(
                f"journal never reached 5 em_ll points (saw {n_ll}); "
                "child log:\n"
                + open(str(tmp_path / "child.log")).read()[-2000:]
            )
        os.kill(proc.pid, signal.SIGKILL)  # hard kill mid-EM
        proc.wait(timeout=30.0)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30.0)
        log.close()
    return tmp_path, raw, jpath


def test_sigkill_mid_em_journal_replays_and_resumes(killed_run):
    tmp_path, raw, jpath = killed_run
    from oni_ml_tpu.config import (
        FeedbackConfig,
        LDAConfig,
        PipelineConfig,
        ScoringConfig,
    )
    from oni_ml_tpu.runner import run_pipeline

    # -- replay to a consistent state ----------------------------------
    records, dropped = Journal.replay_report(jpath)
    assert dropped == 0  # a truncated tail is tolerated, not "damage"
    assert records, "journal empty after kill"
    # The dead run's shape: run_start(force) then pre/corpus completed,
    # LDA began but never ended, no run_end.
    assert records[0]["kind"] == "run_start" and records[0]["force"]
    done = RunJournal.completed_stages(records)
    assert done == {"pre", "corpus"}
    lda_recs = [r for r in records
                if r.get("kind") == "stage" and r.get("stage") == "lda"]
    assert [r["status"] for r in lda_recs] == ["begin"]  # mid-flight
    assert not any(r.get("kind") == "run_end" for r in records)

    # -- sub-run likelihood stream (host_sync_every=1 cadence) ---------
    lls = [r for r in records if r.get("kind") == "em_ll"]
    assert len(lls) >= 5
    iters = [r["iter"] for r in lls]
    assert iters == list(range(1, len(lls) + 1))
    assert all(np.isfinite(r["ll"]) for r in lls)

    # -- deterministic truncated-tail tolerance ------------------------
    maimed = str(tmp_path / "maimed.jsonl")
    with open(jpath, "rb") as f:
        data = f.read()
    with open(maimed, "wb") as f:
        f.write(data + b'{"kind":"em_ll","iter":999,"ll":-1')
    r2, d2 = Journal.replay_report(maimed)
    assert len(r2) == len(records) and d2 == 0

    # -- resume from the journal ---------------------------------------
    cfg = PipelineConfig(
        data_dir=str(tmp_path), flow_path=raw,
        lda=LDAConfig(num_topics=4, em_max_iters=6, batch_size=32,
                      min_bucket_len=16, seed=3),
        feedback=FeedbackConfig(dup_factor=5),
        scoring=ScoringConfig(threshold=1.1),
    )
    metrics = run_pipeline(cfg, "20160122", "flow")
    by_stage = {m["stage"]: m for m in metrics}
    # Completed stages skip WITHOUT re-running, attributed to the
    # journal; the interrupted LDA (and score) run to completion.
    assert "journal" in by_stage["pre"]["skipped"]
    assert "journal" in by_stage["corpus"]["skipped"]
    assert "skipped" not in by_stage["lda"]
    assert by_stage["lda"]["em_iters"] >= 1
    assert "skipped" not in by_stage["score"]
    day = tmp_path / "20160122"
    for name in ("final.beta", "doc_results.csv", "word_results.csv",
                 "flow_results.csv"):
        assert (day / name).exists(), name

    # The resumed run appended behind the dead run's records: one
    # journal, full history, run_end ok at the tail.
    records3 = Journal.replay(jpath)
    assert len(records3) > len(records)
    starts = [r for r in records3 if r["kind"] == "run_start"]
    assert len(starts) == 2 and not starts[1]["force"]
    assert starts[1]["journal_done"] == ["corpus", "pre"]
    ends = [r for r in records3 if r["kind"] == "run_end"]
    assert len(ends) == 1 and ends[0]["ok"]
    done3 = RunJournal.completed_stages(records3)
    assert done3 == {"pre", "corpus", "lda", "score"}

    # A third run now skips EVERY stage off the journal (the run-level
    # `plans` accounting record is not a stage and never skips).
    metrics3 = run_pipeline(cfg, "20160122", "flow")
    assert all("journal" in m.get("skipped", "") for m in metrics3
               if m["stage"] != "plans")

    # -- crash/resume byte-identity across the dataplane ---------------
    # The resumed day's artifacts must equal an UNINTERRUPTED run of the
    # same config on the same input: the kill landed mid-EM with the
    # pre/corpus checkpoints already demoted to (completed, atomic)
    # background writes, and the resumed LDA/score fell back to the
    # file contract — same corpus, same training, same bytes.
    ref_dir = tmp_path / "uninterrupted"
    ref_dir.mkdir()
    ref_raw = str(ref_dir / "flow.csv")
    _write_flow_day(ref_raw)
    ref_cfg = PipelineConfig(
        data_dir=str(ref_dir), flow_path=ref_raw,
        lda=LDAConfig(num_topics=4, em_max_iters=6, batch_size=32,
                      min_bucket_len=16, seed=3),
        feedback=FeedbackConfig(dup_factor=5),
        scoring=ScoringConfig(threshold=1.1),
    )
    run_pipeline(ref_cfg, "20160122", "flow")
    for name in ("word_counts.dat", "words.dat", "doc.dat", "model.dat",
                 "final.beta", "final.gamma", "final.other",
                 "likelihood.dat", "doc_results.csv", "word_results.csv",
                 "flow_results.csv"):
        killed = (day / name).read_bytes()
        ref = (ref_dir / "20160122" / name).read_bytes()
        assert killed == ref, f"{name} diverged after crash/resume"
    # No half-written temporaries survived the kill: every demoted
    # write publishes via tmp+rename.
    leftovers = [p for p in os.listdir(day) if p.endswith(".tmp")]
    assert not leftovers, leftovers


_NOCKPT_CHILD_SCRIPT = _CHILD_SCRIPT.replace(
    "from oni_ml_tpu.config import (FeedbackConfig, LDAConfig, "
    "PipelineConfig,\n                               ScoringConfig)",
    "from oni_ml_tpu.config import (DataplaneConfig, FeedbackConfig, "
    "LDAConfig,\n                               PipelineConfig, "
    "ScoringConfig)",
).replace(
    "    scoring=ScoringConfig(threshold=1.1),\n)",
    "    scoring=ScoringConfig(threshold=1.1),\n"
    "    dataplane=DataplaneConfig(checkpoints=False),\n)",
)


def test_sigkill_no_checkpoints_resume_refused(tmp_path):
    """Kill a --no-checkpoints (pure streaming) run mid-EM: the day dir
    holds no file contract, so a --stages resume is REFUSED with the
    missing artifact named and the --no-checkpoints provenance — and a
    full re-run recomputes the day from scratch."""
    assert "DataplaneConfig" in _NOCKPT_CHILD_SCRIPT
    assert "checkpoints=False" in _NOCKPT_CHILD_SCRIPT
    raw = str(tmp_path / "flow.csv")
    _write_flow_day(raw)
    jpath = str(tmp_path / "20160122" / "run_journal.jsonl")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("ONI_ML_TPU_TESTS_ON_TPU", None)
    log = open(str(tmp_path / "child.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-c", _NOCKPT_CHILD_SCRIPT, str(tmp_path), raw],
        stdout=log, stderr=subprocess.STDOUT,
        cwd=os.path.dirname(HERE), env=env,
    )
    try:
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                log.close()
                pytest.fail(
                    "child exited before the kill (rc="
                    f"{proc.returncode}):\n"
                    + open(str(tmp_path / "child.log")).read()[-2000:]
                )
            if os.path.exists(jpath) and sum(
                1 for r in Journal.replay(jpath)
                if r.get("kind") == "em_ll"
            ) >= 2:
                break
            time.sleep(0.05)
        else:
            pytest.fail("journal never showed EM in flight")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30.0)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30.0)
        log.close()

    day = tmp_path / "20160122"
    # Pure streaming: the kill left no contract files (and no temps).
    for name in ("features.pkl", "word_counts.dat", "words.dat",
                 "doc.dat", "model.dat", "final.beta",
                 "doc_results.csv", "word_results.csv"):
        assert not (day / name).exists(), name
    records = Journal.replay(jpath)
    assert records[0]["kind"] == "run_start"
    assert records[0]["checkpoints"] is False

    from oni_ml_tpu.config import (
        FeedbackConfig,
        LDAConfig,
        PipelineConfig,
        ScoringConfig,
    )
    from oni_ml_tpu.runner import (
        MissingArtifactError,
        Stage,
        run_pipeline,
    )

    cfg = PipelineConfig(
        data_dir=str(tmp_path), flow_path=raw,
        lda=LDAConfig(num_topics=4, em_max_iters=6, batch_size=32,
                      min_bucket_len=16, seed=3),
        feedback=FeedbackConfig(dup_factor=5),
        scoring=ScoringConfig(threshold=1.1),
    )
    with pytest.raises(MissingArtifactError) as ei:
        run_pipeline(cfg, "20160122", "flow", stages=[Stage.LDA])
    msg = str(ei.value)
    assert "model.dat" in msg
    assert "--no-checkpoints" in msg and "refused" in msg

    # A full (checkpoints-on) re-run recomputes everything: no stage
    # can skip against the missing contract, whatever the journal says.
    metrics = run_pipeline(cfg, "20160122", "flow")
    by_stage = {m["stage"]: m for m in metrics}
    for stage in ("pre", "corpus", "lda", "score"):
        assert "skipped" not in by_stage[stage], stage
    assert (day / "flow_results.csv").exists()
    assert (day / "model.dat").exists()


def test_journal_written_by_normal_run_and_traceable(tmp_path):
    """A healthy run's journal: stage spans for every stage, em_ll
    points at the host-sync cadence, run_end ok — and it converts to a
    valid Chrome trace via tools/trace_view."""
    from oni_ml_tpu.config import (
        FeedbackConfig,
        LDAConfig,
        PipelineConfig,
        ScoringConfig,
    )
    from oni_ml_tpu.runner import run_pipeline

    raw = str(tmp_path / "flow.csv")
    _write_flow_day(raw)
    cfg = PipelineConfig(
        data_dir=str(tmp_path), flow_path=raw,
        lda=LDAConfig(num_topics=4, em_max_iters=6, em_tol=0.0,
                      batch_size=32, min_bucket_len=16, seed=3,
                      fused_em_chunk=4, host_sync_every=2),
        feedback=FeedbackConfig(dup_factor=5),
        scoring=ScoringConfig(threshold=1.1),
    )
    run_pipeline(cfg, "20160122", "flow")
    jpath = str(tmp_path / "20160122" / "run_journal.jsonl")
    records = Journal.replay(jpath)
    done = RunJournal.completed_stages(records)
    assert done == {"pre", "corpus", "lda", "score"}
    # em_max_iters=6, em_tol=0: exactly 6 journaled points.
    lls = [r for r in records if r["kind"] == "em_ll"]
    assert [r["iter"] for r in lls] == [1, 2, 3, 4, 5, 6]
    # spans recorded through the shared recorder (stage spans at least)
    span_names = {r["name"] for r in records if r["kind"] == "span"}
    assert any(n.startswith("stage.") for n in span_names)
    # em.run_chunk dispatch spans from the fused driver's wrapper, and
    # the driver's blocking host-sync spans: 6 iters / sync cadence 2
    # = 3 dispatches.
    assert "em.run_chunk" in span_names
    assert "em.host_sync" in span_names
    ends = [r for r in records if r["kind"] == "run_end"]
    assert len(ends) == 1 and ends[0]["ok"]

    sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
    import trace_view

    trace = trace_view.journal_to_trace(records)
    names = [e["name"] for e in trace["traceEvents"]]
    for stage in ("stage.pre", "stage.corpus", "stage.lda", "stage.score"):
        assert stage in names, stage
    json.dumps(trace)
    rows = trace_view.stage_summary(records)
    assert {r["stage"] for r in rows} == {"pre", "corpus", "lda", "score"}


def test_no_journal_flag_disables_recorder(tmp_path):
    from oni_ml_tpu.config import (
        FeedbackConfig,
        LDAConfig,
        PipelineConfig,
        ScoringConfig,
        TelemetryConfig,
    )
    from oni_ml_tpu.runner import run_pipeline

    raw = str(tmp_path / "flow.csv")
    _write_flow_day(raw)
    cfg = PipelineConfig(
        data_dir=str(tmp_path), flow_path=raw,
        lda=LDAConfig(num_topics=4, em_max_iters=3, batch_size=32,
                      min_bucket_len=16, seed=3),
        feedback=FeedbackConfig(dup_factor=5),
        scoring=ScoringConfig(threshold=1.1),
        telemetry=TelemetryConfig(journal=False),
    )
    run_pipeline(cfg, "20160122", "flow")
    assert not (tmp_path / "20160122" / "run_journal.jsonl").exists()
