"""Featurizer unit/property tests (SURVEY §4: quantile semantics vs a
brute-force oracle, adjust_port port cases, extract_subdomain, entropy)."""

import numpy as np
import pytest

from oni_ml_tpu.features import (
    bin_values,
    ecdf_cuts,
    extract_subdomain,
    featurize_dns,
    featurize_flow,
    load_top_domains,
    read_dns_feedback_rows,
    read_flow_feedback_rows,
    shannon_entropy,
)
from oni_ml_tpu.features.quantiles import DECILES, QUINTILES


# ---------------------------------------------------------------------------
# quantiles
# ---------------------------------------------------------------------------


def brute_force_cuts(values, quantiles):
    """Literal transcription of the reference rule: cdf over the multiset,
    cut = max({v : cdf(v) < q} ∪ {0})."""
    values = np.asarray(values, dtype=np.float64)
    uniq = np.unique(values)
    cdf = {v: np.mean(values <= v) for v in uniq}
    out = []
    for q in quantiles:
        best = 0.0
        for v in uniq:
            if cdf[v] < q:
                best = max(best, v)
        out.append(best)
    return np.array(out)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("quant", [DECILES, QUINTILES])
def test_ecdf_cuts_match_brute_force(seed, quant):
    rng = np.random.default_rng(seed)
    # Heavy ties, like binned network data.
    values = rng.integers(0, 40, size=500).astype(float)
    np.testing.assert_array_equal(ecdf_cuts(values, quant), brute_force_cuts(values, quant))


def test_ecdf_cuts_empty_and_constant():
    assert ecdf_cuts(np.array([]), DECILES).tolist() == [0.0] * 10
    # Constant data: cdf(v) = 1.0, never < q, all cuts 0.
    assert ecdf_cuts(np.full(10, 7.0), DECILES).tolist() == [0.0] * 10


def test_ecdf_cuts_negative_floor_at_zero():
    # The reference's zero-initialised aggregate floors cuts at 0.
    values = np.array([-5.0, -1.0, 3.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0])
    cuts = ecdf_cuts(values, DECILES)
    assert (cuts >= 0).all()
    np.testing.assert_array_equal(cuts, brute_force_cuts(values, DECILES))


def test_bin_values_counts_cuts_strictly_below():
    cuts = np.array([0.0, 2.0, 4.0])
    np.testing.assert_array_equal(
        bin_values(np.array([-1.0, 0.0, 1.0, 2.0, 5.0]), cuts),
        [0, 0, 1, 1, 3],
    )


# ---------------------------------------------------------------------------
# flow words
# ---------------------------------------------------------------------------


def flow_row(hour=1, minute=30, second=0, sip="10.0.0.1", dip="10.0.0.2",
             col10="80", col11="55000", ipkt="5", ibyt="500"):
    row = ["##"] * 27
    row[4], row[5], row[6] = str(hour), str(minute), str(second)
    row[8], row[9] = sip, dip
    row[10], row[11] = col10, col11
    row[16], row[17] = ipkt, ibyt
    return ",".join(row)


def featurize_rows(*rows, cuts=None):
    lines = ["header"] + list(rows)
    return featurize_flow(lines, precomputed_cuts=cuts)


ZERO_CUTS = (np.zeros(10), np.zeros(10), np.zeros(5))


def test_flow_port_case_2_service_port():
    # col10=80 (reference's "dport"), col11=55000 ("sport"): p_case 2,
    # word_port = min = 80, dport < sport => dest_word gets -1_ prefix.
    f = featurize_rows(flow_row(col10="80", col11="55000"), cuts=ZERO_CUTS)
    assert f.word_port[0] == "80.0"
    assert f.src_word[0] == "80.0_10.0_10.0_5.0"
    assert f.dest_word[0] == "-1_80.0_10.0_10.0_5.0"


def test_flow_port_case_2_other_direction():
    f = featurize_rows(flow_row(col10="55000", col11="80"), cuts=ZERO_CUTS)
    assert f.src_word[0].startswith("-1_80.0")
    assert not f.dest_word[0].startswith("-1_")


def test_flow_port_case_3_both_high():
    f = featurize_rows(flow_row(col10="50000", col11="60000"), cuts=ZERO_CUTS)
    assert f.word_port[0] == "333333.0"
    assert f.src_word[0] == f.dest_word[0] == "333333.0_10.0_10.0_5.0"


def test_flow_port_case_4_zero_port():
    # col10 ("dport") == 0, col11 ("sport") != 0: word_port = sport,
    # src_word marked.
    f = featurize_rows(flow_row(col10="0", col11="7000"), cuts=ZERO_CUTS)
    assert f.word_port[0] == "7000.0"
    assert f.src_word[0].startswith("-1_")
    assert not f.dest_word[0].startswith("-1_")
    # Mirrored.
    f = featurize_rows(flow_row(col10="7000", col11="0"), cuts=ZERO_CUTS)
    assert f.word_port[0] == "7000.0"
    assert f.dest_word[0].startswith("-1_")


def test_flow_port_case_1_both_low():
    f = featurize_rows(flow_row(col10="80", col11="443"), cuts=ZERO_CUTS)
    assert f.word_port[0] == "111111.0"
    # Both zero -> max(0,0)=0.
    f = featurize_rows(flow_row(col10="0", col11="0"), cuts=ZERO_CUTS)
    assert f.word_port[0] == "0.0"


def test_flow_time_and_binning():
    # 1:30:00 -> 1.5 fractional hours; cuts at deciles of a known set.
    f = featurize_rows(
        flow_row(hour=1, minute=30, second=0),
        flow_row(hour=2, minute=0, second=0, ibyt="1000", ipkt="10"),
    )
    np.testing.assert_allclose(f.num_time, [1.5, 2.0])
    # Two distinct values: cdf(1.5)=0.5, cdf(2.0)=1.0.  Deciles 0.6..0.9
    # pick 1.5; time_bin(1.5)=#{cuts<1.5}=... cuts=[0,0,0,0,0,0,1.5,1.5,1.5,1.5]
    np.testing.assert_array_equal(f.time_cuts, [0, 0, 0, 0, 0, 0, 1.5, 1.5, 1.5, 1.5])
    assert f.time_bin.tolist() == [6, 10]


def test_flow_header_and_bad_rows_dropped():
    lines = ["h,e,a,d", "h,e,a,d", "bad,row", flow_row()]
    f = featurize_flow(lines)
    assert f.num_events == 1


def test_flow_word_counts_two_documents_per_event():
    f = featurize_rows(
        flow_row(sip="a", dip="b"), flow_row(sip="a", dip="b"), cuts=ZERO_CUTS
    )
    wc = f.word_counts()
    assert ("a", f.src_word[0], 2) in wc
    assert ("b", f.dest_word[0], 2) in wc
    assert len(wc) == 2


def test_flow_ip_pair_lexicographic():
    f = featurize_rows(flow_row(sip="10.9.9.9", dip="10.10.10.10"), cuts=ZERO_CUTS)
    # "10.10.10.10" < "10.9.9.9" lexicographically is False -> sip<dip False
    assert f.ip_pair[0] == "10.10.10.10 10.9.9.9"


def test_flow_featurized_row_width():
    f = featurize_rows(flow_row(), cuts=ZERO_CUTS)
    assert len(f.featurized_row(0)) == 35


# ---------------------------------------------------------------------------
# flow feedback
# ---------------------------------------------------------------------------


def test_flow_feedback_roundtrip(tmp_path):
    fb = tmp_path / "flow_scores.csv"
    header = ",".join(f"c{i}" for i in range(22))
    sev3 = ["3", "2016-04-21 03:58:13", "1.2.3.4", "5.6.7.8", "80", "55000",
            "TCP", ".AP.", "5", "500"] + ["x"] * 12
    sev1 = list(sev3)
    sev1[0] = "1"
    fb.write_text("\n".join([header, ",".join(sev3), ",".join(sev1)]) + "\n")
    rows = read_flow_feedback_rows(str(fb), dup_factor=3)
    assert len(rows) == 3  # only the sev-3 row, duplicated
    parts = rows[0].split(",")
    assert len(parts) == 27
    assert parts[4:7] == ["03", "58", "13"]
    assert parts[8] == "1.2.3.4" and parts[11] == "55000"
    # Injected rows must survive featurization (unlike the reference,
    # whose comma-less converter gets them filtered out).
    f = featurize_flow(["header"], feedback_rows=rows)
    assert f.num_events == 3


def test_flow_feedback_missing_file():
    assert read_flow_feedback_rows("/nonexistent/x.csv", 1000) == []


def test_flow_feedback_malformed_tstart_skipped(tmp_path):
    fb = tmp_path / "flow_scores.csv"
    header = ",".join(f"c{i}" for i in range(22))
    good = ["3", "2016-04-21 03:58:13", "1.2.3.4", "5.6.7.8", "80", "55000",
            "TCP", ".AP.", "5", "500"] + ["x"] * 12
    bad = list(good)
    bad[1] = "2016-04-21"  # no time part -> must be skipped, not crash
    fb.write_text("\n".join([header, ",".join(bad), ",".join(good)]) + "\n")
    rows = read_flow_feedback_rows(str(fb), dup_factor=2)
    assert len(rows) == 2  # only the well-formed row


def test_feedback_events_train_but_are_not_scored():
    rows = read_flow_feedback_rows("/nonexistent/x.csv", 1)
    fb = [flow_row(sip="fb", dip="fb2")] * 4
    f = featurize_flow(["h", flow_row()], feedback_rows=fb)
    assert f.num_events == 5
    assert f.num_raw_events == 1
    # word_counts (training corpus) still sees every event.
    assert sum(c for _, _, c in f.word_counts()) == 10  # 5 events x 2 docs


# ---------------------------------------------------------------------------
# dns
# ---------------------------------------------------------------------------


def test_extract_subdomain_basic():
    assert extract_subdomain("mail.google.com") == ("google", "mail", 4, 3)
    assert extract_subdomain("a.b.mail.google.com") == ("google", "a.b.mail", 8, 5)


def test_extract_subdomain_two_parts_unknown():
    assert extract_subdomain("google.com") == ("None", "None", 0, 2)
    assert extract_subdomain("localhost") == ("None", "None", 0, 1)


def test_extract_subdomain_country_code():
    # cc TLD shifts domain one label left; 3 parts -> no subdomain.
    assert extract_subdomain("foo.co.uk") == ("foo", "None", 0, 3)
    assert extract_subdomain("www.foo.co.uk") == ("foo", "www", 3, 4)


def test_extract_subdomain_reverse_dns():
    d, s, sl, n = extract_subdomain("4.3.2.1.in-addr.arpa")
    assert (d, s, sl) == ("None", "None", 0)
    assert n == 6


def test_shannon_entropy():
    assert shannon_entropy("") == 0.0
    assert shannon_entropy("aaaa") == 0.0
    assert shannon_entropy("ab") == 1.0
    # The reference's 'None' placeholder quirk: 4 distinct chars -> 2 bits.
    assert shannon_entropy("None") == 2.0


def test_load_top_domains(tmp_path):
    p = tmp_path / "top-1m.csv"
    p.write_text("1,google.com\n2,facebook.com\n3,baidu.cn\n")
    top = load_top_domains(str(p))
    assert top == {"google", "facebook", "baidu"}


def dns_row(tstamp="1454000000", flen="60", ip="10.0.0.9",
            qname="mail.google.com", qtype="1", rcode="0"):
    return ["t", tstamp, flen, ip, qname, "1", qtype, rcode]


def test_dns_word_structure():
    f = featurize_dns([dns_row()], top_domains=frozenset({"google"}))
    # Single event: every cdf is 1.0 -> all cuts 0 -> every positive value
    # bins to the full cut count.
    # top=1, frame_len 60>0 ten cuts -> 10; tstamp -> 10; sub_len 4>0 -> 5;
    # entropy>0 -> 5; num_periods 3>0 -> 5; qtype 1; rcode 0
    assert f.word[0] == "1_10_10_5_5_5_1_0"


def test_dns_intel_whitelist():
    f = featurize_dns([dns_row(qname="x.intel.com")])
    assert f.top_domain[0] == 2
    assert f.word[0].startswith("2_")


def test_dns_none_subdomain_entropy_quirk():
    # domain-only query: subdomain 'None' -> entropy 2.0 feeds the ECDF.
    f = featurize_dns([dns_row(qname="google.com")])
    assert f.subdomain[0] == "None"
    assert f.subdomain_entropy[0] == 2.0
    assert (f.entropy_cuts >= 0).all()


def test_dns_word_counts_by_client():
    rows = [dns_row(ip="a"), dns_row(ip="a"), dns_row(ip="b")]
    f = featurize_dns(rows)
    wc = f.word_counts()
    assert ("a", f.word[0], 2) in wc
    assert ("b", f.word[2], 1) in wc


def test_dns_featurized_row_width():
    f = featurize_dns([dns_row()])
    assert len(f.featurized_row(0)) == 15


def test_dns_feedback_roundtrip(tmp_path):
    fb = tmp_path / "dns_scores.csv"
    header = ",".join(f"c{i}" for i in range(24))
    row = ["ft", "60", "9.9.9.9", "evil.example.com", "1", "1", "0"] + \
        ["x"] * 11 + ["3"] + ["x"] * 4 + ["1454000000"]
    bad = list(row)
    bad[18] = "1"
    fb.write_text("\n".join([header, ",".join(row), ",".join(bad)]) + "\n")
    rows = read_dns_feedback_rows(str(fb), dup_factor=2)
    assert len(rows) == 2
    assert rows[0] == ["ft", "1454000000", "60", "9.9.9.9",
                      "evil.example.com", "1", "1", "0"]
    f = featurize_dns([], feedback_rows=rows)
    assert f.num_events == 2
