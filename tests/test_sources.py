"""The declarative source subsystem: registry resolution, byte-parity
of the registered flow/dns specs against the legacy featurizers on the
golden day, the proxy TableSourceSpec round-trip, labeled-injection
determinism, and the detection-quality publish gate.

The two contracts this file pins hardest:

* byte-parity — `sources.get("flow"/"dns").featurize(...)` produces
  the SAME words and word_counts as the legacy featurize paths on the
  committed golden inputs, so routing every layer through the registry
  changed nothing about what models see;
* the quality veto — a publish candidate whose injection-suite
  recall@k regresses is vetoed like an LL drift: `quality_gate:
  vetoed` in the journal, fleet version unchanged, bit-identical
  scores on the prior model.
"""

import json
import os
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
GOLDEN = os.path.join(HERE, "golden")
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tools"))
sys.path.insert(0, GOLDEN)

from oni_ml_tpu import sources  # noqa: E402
from oni_ml_tpu.models.drift import QualityGate  # noqa: E402
from oni_ml_tpu.sources import inject  # noqa: E402
from oni_ml_tpu.sources.quality import (  # noqa: E402
    QualitySuite,
    detection_metrics,
    scenario_metrics,
)


# ---------------------------------------------------------------------------
# Registry resolution
# ---------------------------------------------------------------------------


def test_registry_names_and_lookup():
    names = sources.names()
    assert names[:2] == ("flow", "dns")   # CLI/choices order contract
    assert "proxy" in names
    assert sources.get("flow").pairs_per_event == 2
    assert sources.get("dns").pairs_per_event == 1
    assert sources.get("proxy").pairs_per_event == 1
    with pytest.raises(ValueError, match="flow"):
        sources.get("netcat")            # error lists registered names


def test_registry_rejects_duplicate_and_unnamed():
    with pytest.raises(ValueError, match="already registered"):
        sources.register(sources.FlowSource())
    # replace=True is the sanctioned override path
    sources.register(sources.FlowSource(), replace=True)


def test_spec_for_features_resolves_each_container():
    flow = sources.get("flow").featurize(
        sources.get("flow").synth_benign(40, seed=1)
    )
    dns = sources.get("dns").featurize(
        sources.get("dns").synth_benign(40, seed=1)
    )
    proxy = sources.get("proxy").featurize(
        sources.get("proxy").synth_benign(40, seed=1)
    )
    assert sources.spec_for_features(flow).name == "flow"
    assert sources.spec_for_features(dns).name == "dns"
    assert sources.spec_for_features(proxy).name == "proxy"
    with pytest.raises(TypeError, match="no registered source"):
        sources.spec_for_features(object())


# ---------------------------------------------------------------------------
# Byte-parity pins against the golden day (the tentpole's no-op proof)
# ---------------------------------------------------------------------------


def test_flow_spec_matches_legacy_featurizer_on_golden_day():
    """Registry-resolved flow featurization == the legacy native path
    on the committed golden day: same words, same word_counts, same
    cut arrays."""
    from generate import load_flow_feats

    legacy = load_flow_feats()
    with open(os.path.join(GOLDEN, "inputs", "flow.csv")) as f:
        lines = f.read().splitlines()
    spec = sources.get("flow")
    feats = spec.featurize(lines, skip_header=True)
    n = legacy.num_raw_events
    assert feats.num_raw_events == n
    assert list(feats.src_word[:n]) == list(legacy.src_word[:n])
    assert list(feats.dest_word[:n]) == list(legacy.dest_word[:n])
    assert feats.word_counts() == legacy.word_counts()
    for a, b in zip(spec.cuts_of(feats), spec.cuts_of(legacy)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dns_spec_matches_legacy_featurizer_on_golden_day():
    from generate import load_dns_feats

    from oni_ml_tpu.features import load_top_domains

    legacy = load_dns_feats()
    top = load_top_domains(os.path.join(GOLDEN, "inputs", "top1m.csv"))
    with open(os.path.join(GOLDEN, "inputs", "dns.csv")) as f:
        lines = f.read().splitlines()
    spec = sources.get("dns")
    feats = spec.featurize(lines, top_domains=top)
    n = legacy.num_raw_events
    assert feats.num_raw_events == n
    assert list(feats.word[:n]) == list(legacy.word[:n])
    assert feats.word_counts() == legacy.word_counts()


def test_flow_event_documents_match_corpus_contract():
    """`event_documents` (src block then dest block) agrees with the
    word_counts aggregation: every (doc, word) pair it emits is
    accounted for in the triples."""
    spec = sources.get("flow")
    feats = spec.featurize(spec.synth_benign(50, seed=3))
    ips, words = spec.event_documents(feats)
    assert len(ips) == len(words) == 2 * feats.num_raw_events
    from collections import Counter

    pair_counts = Counter(zip(ips, words))
    triples = {(ip, w): c for ip, w, c in feats.word_counts()}
    assert pair_counts == Counter(triples)


# ---------------------------------------------------------------------------
# Proxy: declarative spec round-trip
# ---------------------------------------------------------------------------


def test_proxy_spec_dict_roundtrip_preserves_words():
    spec = sources.get("proxy")
    d = spec.to_dict()
    clone = sources.TableSourceSpec.from_dict(d)
    assert clone.name == "proxy"
    assert clone.pairs_per_event == 1
    assert clone.to_dict() == d
    lines = spec.synth_benign(200, seed=5)
    f1 = spec.featurize(lines)
    f2 = clone.featurize(lines)
    assert f1.word == f2.word
    assert f1.word_counts() == f2.word_counts()
    # Pinned cuts reproduce the same words through the clone too (the
    # serving rule: judge a candidate on the word space it will serve).
    f3 = clone.featurize(lines, precomputed_cuts=spec.cuts_of(f1))
    assert f3.word == f1.word


def test_proxy_header_probe_and_column_discipline():
    spec = sources.get("proxy")
    header = ",".join(c for c in spec.to_dict()["columns"])
    lines = spec.synth_benign(30, seed=2)
    feats = spec.featurize([header] + lines, skip_header=True)
    assert feats.num_raw_events == 30          # header dropped
    feats2 = spec.featurize(lines + ["short,row"], skip_header=False)
    assert feats2.num_raw_events == 30         # wrong width dropped
    # event_time_s parses the HH:MM:SS column (day_replay's slicer).
    t = spec.event_time_s(lines[0])
    assert 8 * 3600 <= t < 18 * 3600


def test_proxy_event_featurizer_matches_batch_words():
    """The serving-lane featurizer (GenericEventFeaturizer) reproduces
    batch words under the batch cuts — the same pin flow/dns have."""
    spec = sources.get("proxy")
    lines = spec.synth_benign(120, seed=9)
    batch = spec.featurize(lines)
    lane = spec.event_featurizer(spec.cuts_of(batch))
    served = lane(lines)
    assert served.word == batch.word


# ---------------------------------------------------------------------------
# Labeled injection: determinism and ground-truth alignment
# ---------------------------------------------------------------------------


def test_inject_scenarios_deterministic_under_seed():
    kw = dict(n_events=300, seed=11, attack_events=6)
    d1 = inject.inject_scenarios("flow", **kw)
    d2 = inject.inject_scenarios("flow", **kw)
    assert d1.lines == d2.lines
    assert d1.labels == d2.labels
    assert d1.manifest == d2.manifest
    d3 = inject.inject_scenarios("flow", n_events=300, seed=12,
                                 attack_events=6)
    assert d3.lines != d1.lines


def test_inject_scenarios_labels_align_and_manifest():
    for source in sources.names():
        day = inject.inject_scenarios(source, n_events=200, seed=7,
                                      attack_events=5)
        scen = inject.scenarios_for(source)
        assert day.manifest["kind"] == "injection"
        assert day.manifest["source"] == source
        assert tuple(day.manifest["scenarios"]) == scen
        assert day.n_attacks == 5 * len(scen)
        assert len(day.lines) == len(day.labels) == 200 + day.n_attacks
        spec = sources.get(source)
        # Day is event-time ordered (the slicer contract) and every
        # labeled index is an attack line of the right scenario.
        times = [spec.event_time_s(ln) for ln in day.lines]
        assert times == sorted(times)
        for row in day.label_rows():
            assert day.labels[row["index"]]["scenario"] == row["scenario"]
            assert row["scenario"] in scen
            assert row["entity"] in day.lines[row["index"]]


def test_inject_scenarios_rejects_unknown():
    with pytest.raises(ValueError):
        inject.inject_scenarios("flow", scenarios=("nope",))


def test_attack_gen_cli_writes_deterministic_labeled_day(tmp_path):
    import attack_gen

    args = ["dns", "--events", "150", "--attack-events", "4",
            "--seed", "3"]
    a, b = tmp_path / "a", tmp_path / "b"
    assert attack_gen.main(args + ["--out-dir", str(a)]) == 0
    assert attack_gen.main(args + ["--out-dir", str(b)]) == 0
    for fname in ("day.csv", "labels.jsonl", "manifest.json"):
        assert (a / fname).read_bytes() == (b / fname).read_bytes()
    manifest = json.loads((a / "manifest.json").read_text())
    assert manifest["kind"] == "injection"
    day_lines = (a / "day.csv").read_text().splitlines()
    labels = [json.loads(ln) for ln in open(a / "labels.jsonl")]
    assert len(day_lines) == manifest["events"]
    assert len(labels) == manifest["attacks"]
    for row in labels:
        assert row["entity"] in day_lines[row["index"]]
    # Unknown scenario -> exit 2, no files.
    rc = attack_gen.main(["dns", "--out-dir", str(tmp_path / "c"),
                          "--scenarios", "nope"])
    assert rc == 2


# ---------------------------------------------------------------------------
# Detection metrics
# ---------------------------------------------------------------------------


def test_detection_metrics_rank_contract():
    # 2 attacks hiding at the BOTTOM of the score range (low =
    # suspicious, the pipeline invariant).
    scores = np.array([0.5, 1e-9, 0.4, 0.6, 2e-9, 0.7])
    mask = np.array([False, True, False, False, True, False])
    m = detection_metrics(scores, mask)
    assert m["k"] == 2 and m["attacks"] == 2
    assert m["precision_at_k"] == 1.0
    assert m["recall_at_k"] == 1.0
    assert m["score_separation"] > 10.0     # nats; ~log(0.5/1.5e-9)
    # Attacks scored HIGH -> complete miss.
    m2 = detection_metrics(1.0 - scores, mask)
    assert m2["recall_at_k"] == 0.0
    assert m2["score_separation"] < 0.0


def test_scenario_metrics_judge_against_global_topk():
    scores = np.array([1e-9, 0.5, 0.6, 0.7, 2e-9, 0.8])
    labels = [{"scenario": "a", "entity": "x"}, None, None, None,
              {"scenario": "b", "entity": "y"}, None]
    per = scenario_metrics(scores, labels)
    assert per["a"]["recall_at_k"] == 1.0
    assert per["b"]["recall_at_k"] == 1.0
    # Push scenario b's event out of the global top-k: its recall
    # drops while a's holds — one ranked list, not one per scenario.
    scores2 = scores.copy()
    scores2[4] = 0.9
    per2 = scenario_metrics(scores2, labels)
    assert per2["a"]["recall_at_k"] == 1.0
    assert per2["b"]["recall_at_k"] == 0.0


# ---------------------------------------------------------------------------
# Proxy end-to-end: featurize -> corpus -> EM -> score, registry only
# ---------------------------------------------------------------------------


def test_proxy_end_to_end_detection_quality():
    """The third source's full path with ZERO source-specific branches:
    injected day -> registry featurize -> Corpus -> EM -> ScoringModel
    -> QualitySuite metrics.  Only the registry knows what a proxy
    event looks like."""
    from oni_ml_tpu.config import LDAConfig, ScoringConfig
    from oni_ml_tpu.io import Corpus
    from oni_ml_tpu.models.lda import train_corpus
    from oni_ml_tpu.scoring import ScoringModel

    spec = sources.get("proxy")
    day = inject.inject_scenarios("proxy", n_events=2000, seed=7,
                                  attack_events=6)
    feats = spec.featurize(day.lines)
    corpus = Corpus.from_features(feats)
    res = train_corpus(
        corpus, LDAConfig(num_topics=2, em_max_iters=10),
        out_dir=None, save_final=False,
    )
    model = ScoringModel.from_lda(
        corpus.doc_names, res.gamma, corpus.vocab, res.log_beta,
        spec.fallback(ScoringConfig()),
    )
    suite = QualitySuite("proxy", spec.cuts_of(feats), n_events=2000,
                         seed=7, attack_events=6)
    met = suite.evaluate(model)
    assert set(met) >= {"recall_at_k", "precision_at_k",
                        "score_separation", "per_scenario"}
    assert "proxy_c2_polling" in met["per_scenario"]
    # The C2 word is novel against a modal benign day: it must rank in
    # the suspicious tail, not blend in.
    assert met["recall_at_k"] >= 0.5
    assert met["score_separation"] > 0.0
    # score_csv through the registry hook emits one row per kept event.
    blob, kept = spec.score_csv(feats, model, threshold=1.0)
    rows = blob.decode().splitlines()
    assert len(rows) == feats.num_raw_events == len(day.lines)
    assert len(kept) == len(rows)


def test_day_replay_cli_accepts_proxy(tmp_path):
    """tools/day_replay.py ingests a proxy day purely via --dsource:
    header detection, time parsing and slicing all resolve through the
    registry (the satellite fixing the flow/dns framing assumption)."""
    import attack_gen
    import day_replay

    out = tmp_path / "day"
    assert attack_gen.main(["proxy", "--out-dir", str(out),
                            "--events", "400", "--attack-events", "4",
                            "--seed", "3"]) == 0
    rc = day_replay.main([
        str(out / "day.csv"), "--dsource", "proxy",
        "--slice-s", "3600", "--no-sleep",
        "--window-s", "7200", "--refresh-s", "3600",
        "--out-dir", str(tmp_path / "cont"),
    ])
    assert rc == 0
    payload = json.load(open(tmp_path / "cont"
                             / "continuous_metrics.json"))
    manifest = json.loads((out / "manifest.json").read_text())
    assert payload["events"] == manifest["events"]
    assert payload["slices"] >= 8      # 08:00-18:00 at 3600 s slices


# ---------------------------------------------------------------------------
# QualityGate: scripted-suite unit behavior
# ---------------------------------------------------------------------------


class _ScriptedSuite:
    """Test double for sources/quality.QualitySuite: evaluate() pops a
    scripted metric dict per call."""

    def __init__(self, *recalls):
        self.queue = [
            {"recall_at_k": r, "precision_at_k": r,
             "score_separation": 2.0,
             "per_scenario": {"beaconing": {"events": 4,
                                            "hits_at_k": int(4 * r),
                                            "recall_at_k": r}}}
            for r in recalls
        ]

    def evaluate(self, model):
        return self.queue.pop(0)


def test_quality_gate_vetoes_regression_and_journals():
    journal = []
    gate = QualityGate(_ScriptedSuite(1.0, 0.9, 0.25, 0.95),
                       tol=0.25, min_history=2, journal=journal)
    d1 = gate.check(None)
    assert not d1.regressed and d1.baseline_recall is None
    assert gate.gate(d1, version=1)
    d2 = gate.check(None)
    assert not d2.regressed                   # baseline just formed
    assert gate.gate(d2, version=2)
    d3 = gate.check(None)                     # 0.25 vs median 0.95
    assert d3.regressed and d3.delta < -0.25
    assert not gate.gate(d3, version=2)
    d4 = gate.check(None)                     # recovered candidate
    assert not d4.regressed                   # veto never entered baseline
    assert gate.gate(d4, version=3)
    assert gate.checks == 4
    assert gate.publishes == 3 and gate.vetoes == 1
    kinds = [(r["kind"], r["action"]) for r in journal]
    assert kinds == [("quality_gate", "published")] * 2 + [
        ("quality_gate", "vetoed"), ("quality_gate", "published")]
    vetoed = journal[2]
    assert vetoed["recall_at_k"] == 0.25
    assert vetoed["baseline_recall"] == pytest.approx(0.95)
    assert vetoed["delta"] == pytest.approx(-0.7)
    assert vetoed["per_scenario"] == {"beaconing": 0.25}


def test_quality_gate_primes_from_journal():
    journal = []
    gate = QualityGate(_ScriptedSuite(1.0, 0.9, 0.2), tol=0.25,
                       min_history=2, journal=journal)
    for v in (1, 2, 2):
        gate.gate(gate.check(None), version=v)
    # A restarted service resumes the baseline from published records
    # only (the vetoed 0.2 must not re-enter).
    g2 = QualityGate(_ScriptedSuite(), tol=0.25, min_history=2)
    assert g2.prime(journal) == 2
    assert g2.baseline == pytest.approx(0.95)


def test_quality_gate_validates_knobs():
    with pytest.raises(ValueError, match="tol"):
        QualityGate(_ScriptedSuite(), tol=0.0)
    with pytest.raises(ValueError, match="min_history"):
        QualityGate(_ScriptedSuite(), min_history=0)


# ---------------------------------------------------------------------------
# The quality-veto pin: continuous service keeps serving prior bits
# ---------------------------------------------------------------------------


def _flow_line(rng, sip, dip, dport, h=None):
    h = int(rng.integers(0, 24)) if h is None else h
    return (
        "2016-01-22 00:00:00,2016,1,22,"
        f"{h},{int(rng.integers(0, 60))},{int(rng.integers(0, 60))},0.0,"
        f"{sip},{dip},{int(rng.integers(1024, 60000))},{dport},TCP,,0,0,"
        f"{int(rng.integers(1, 100))},{int(rng.integers(40, 100000))},"
        "0,0,0,0,0,0,0,0,0"
    )


def _normal_slice(rng, idx, n=220):
    from oni_ml_tpu.runner.continuous import IngestSlice

    ports = (80, 443, 22, 53)
    lines = [
        _flow_line(rng, f"10.0.0.{int(rng.integers(0, 24))}",
                   f"10.1.0.{int(rng.integers(0, 12))}",
                   ports[int(rng.integers(0, len(ports)))])
        for _ in range(n)
    ]
    return IngestSlice(lines=lines, t0=idx * 600.0,
                       t1=(idx + 1) * 600.0, index=idx)


def _quality_service(tmp_path, **cc_kw):
    import dataclasses

    from oni_ml_tpu.config import ContinuousConfig, PipelineConfig
    from oni_ml_tpu.runner.continuous import ContinuousService

    # drift_tol_nats is set far out of reach: this harness pins the
    # QUALITY gate, so the LL gate must never steal the veto.
    kw = dict(
        window_s=1800.0, refresh_every_s=1200.0,
        min_refresh_docs=8, drift_tol_nats=50.0,
        drift_min_history=2, vocab_floor=512, batch_size=64,
        holdout_frac=0.3, quality_gate=True, quality_events=200,
        quality_attack_events=4, quality_min_history=1,
    )
    kw.update(cc_kw)
    config = PipelineConfig(
        data_dir=str(tmp_path),
        continuous=ContinuousConfig(**kw),
    )
    config = dataclasses.replace(
        config,
        lda=dataclasses.replace(config.lda, num_topics=4,
                                em_max_iters=30),
    )
    return ContinuousService(
        config, "flow", out_dir=str(tmp_path / "cont"),
        warmup_refreshes=2,
    )


def test_quality_gate_vetoes_and_fleet_serves_prior_bits(tmp_path,
                                                         monkeypatch):
    """THE quality-plane acceptance pin: a candidate whose injection-
    suite recall regresses is vetoed — `quality_gate: vetoed` in the
    journal, fleet version unchanged, BIT-identical scores on the
    prior model — and a recovered candidate publishes again."""
    from oni_ml_tpu.serving.events import score_features

    # Script the suite verdict while keeping the REAL suite object
    # (its manifest still journals, its cuts still pin): healthy
    # candidates score 1.0 until the dial is turned.
    dial = {"recall": 1.0}

    def scripted_evaluate(self, model):
        r = dial["recall"]
        return {"recall_at_k": r, "precision_at_k": r,
                "score_separation": 2.0,
                "per_scenario": {"beaconing": {"events": 4,
                                               "hits_at_k": int(4 * r),
                                               "recall_at_k": r}}}

    monkeypatch.setattr(QualitySuite, "evaluate", scripted_evaluate)

    rng = np.random.default_rng(7)
    svc = _quality_service(tmp_path)
    try:
        idx = 0
        for _ in range(6):
            svc.ingest_slice(_normal_slice(rng, idx))
            svc.maybe_refresh(idx * 600.0 + 600.0)
            idx += 1
        assert svc.drift.publishes >= 2
        qgate = svc._quality_gate()
        assert qgate is not None and qgate.publishes >= 2
        assert qgate.vetoes == 0
        v_before = svc.fleet.version(svc.tenant)
        snap_before = svc.fleet.active(svc.tenant)
        probe = [_flow_line(rng, "10.0.0.1", "10.1.0.2", 80)
                 for _ in range(16)]
        feats = svc.scorer._lanes[svc.tenant].featurizer(probe)
        scores_before = score_features(snap_before.model, feats, "flow")

        # Healthy stream continues, but the candidate's DETECTION
        # quality collapses: drift gate passes, quality gate must veto.
        dial["recall"] = 0.0
        for _ in range(2):
            svc.ingest_slice(_normal_slice(rng, idx))
            svc.maybe_refresh(idx * 600.0 + 600.0)
            idx += 1
        assert qgate.vetoes >= 1
        assert svc.drift.vetoes == 0           # drift never fired
        assert svc.fleet.version(svc.tenant) == v_before
        snap_after = svc.fleet.active(svc.tenant)
        assert snap_after.version == snap_before.version
        scores_after = score_features(snap_after.model, feats, "flow")
        np.testing.assert_array_equal(scores_before, scores_after)

        # Recovery: quality back up -> next refresh publishes.
        dial["recall"] = 1.0
        for _ in range(2):
            svc.ingest_slice(_normal_slice(rng, idx))
            svc.maybe_refresh(idx * 600.0 + 600.0)
            idx += 1
        assert svc.fleet.version(svc.tenant) > v_before

        payload = svc.close()
        assert payload["quality_checks"] >= 3
        assert payload["quality_vetoes"] >= 1
        jpath = tmp_path / "cont" / "run_journal.jsonl"
        records = [json.loads(ln) for ln in open(jpath)]
        injected = [r for r in records if r.get("kind") == "injection"]
        assert injected and injected[0]["source"] == "flow"
        gates = [r for r in records if r.get("kind") == "quality_gate"]
        assert any(g["action"] == "vetoed" for g in gates)
        assert gates[-1]["action"] == "published"
        vetoed = next(g for g in gates if g["action"] == "vetoed")
        assert vetoed["recall_at_k"] == 0.0
        assert vetoed["delta"] is not None and vetoed["delta"] < -0.25
        assert vetoed["tenant"] == svc.tenant
    finally:
        if svc.scorer is not None:
            svc.close()


# ---------------------------------------------------------------------------
# bench_diff direction keys (satellite: quality metrics gate CI)
# ---------------------------------------------------------------------------


def _quality_payload(**over):
    base = {
        "recall_at_k": 1.0, "precision_at_k": 1.0,
        "score_separation": 2.0,
        "sources": {
            "flow": {"recall_at_k": 1.0, "precision_at_k": 1.0,
                     "score_separation": 1.7},
            "proxy": {"recall_at_k": 1.0, "precision_at_k": 1.0,
                      "score_separation": 3.7},
        },
    }
    srcs = {k: dict(v) for k, v in base["sources"].items()}
    for key, val in list(over.items()):
        if ":" in key:
            src, sub = key.split(":", 1)
            srcs[src][sub] = val
            del over[key]
    base.update(over)
    base["sources"] = srcs
    return base


def test_bench_diff_quality_direction_keys():
    import bench_diff

    old = {"metric": "m", "value": 1.0, "unit": "x",
           "secondary": {"detection_quality": _quality_payload()}}

    def rows_for(**over):
        new = {"metric": "m", "value": 1.0, "unit": "x",
               "secondary": {
                   "detection_quality": _quality_payload(**over)}}
        return bench_diff.diff_payloads(old, new)

    # Recall DOWN -> regression (higher-better).
    rows = rows_for(recall_at_k=0.5)
    assert any(r["regression"] and r["name"].endswith(".recall_at_k")
               for r in rows)
    # Precision DOWN -> regression.
    rows = rows_for(precision_at_k=0.4)
    assert any(r["regression"] and "precision_at_k" in r["name"]
               for r in rows)
    # Separation DOWN -> regression.
    rows = rows_for(score_separation=0.5)
    assert any(r["regression"] and "score_separation" in r["name"]
               for r in rows)
    # One SOURCE regressing cannot hide behind a steady mean.
    rows = rows_for(**{"proxy:recall_at_k": 0.25})
    assert any(r["regression"]
               and r["name"] == "phase:detection_quality:proxy.recall_at_k"
               for r in rows)
    # Improvements in every direction -> clean.
    rows = rows_for(recall_at_k=1.0, precision_at_k=1.0,
                    score_separation=3.0,
                    **{"flow:score_separation": 2.0})
    assert not any(r["regression"] for r in rows
                   if "detection_quality" in r["name"])


def test_bench_diff_quality_headline_form():
    import bench_diff

    old = _quality_payload()
    new = _quality_payload(recall_at_k=0.5)
    rows = bench_diff.diff_payloads(old, new)
    assert any(r["regression"] and r["name"] == "headline.recall_at_k"
               for r in rows)


# ---------------------------------------------------------------------------
# trace_view quality lanes (satellite: observability)
# ---------------------------------------------------------------------------


def test_trace_view_renders_quality_records():
    import trace_view

    records = [
        {"kind": "injection", "mono_ns": 500, "source": "flow",
         "scenarios": ["beaconing"], "events": 212, "attacks": 12,
         "seed": 7},
        {"kind": "quality_gate", "mono_ns": 1000, "action": "published",
         "version": 1, "recall_at_k": 1.0, "precision_at_k": 1.0,
         "score_separation": 2.0, "baseline_recall": None,
         "per_scenario": {"beaconing": 1.0}},
        {"kind": "quality_gate", "mono_ns": 2000, "action": "vetoed",
         "version": 2, "recall_at_k": 0.1, "precision_at_k": 0.1,
         "score_separation": 0.2, "baseline_recall": 1.0,
         "delta": -0.9, "per_scenario": {"beaconing": 0.1}},
    ]
    trace = trace_view.journal_to_trace(records)
    names = [e["name"] for e in trace["traceEvents"]]
    assert "injection suite: flow" in names
    assert names.count("quality recall@k") == 2
    assert "quality gate: published" in names
    assert "quality VETOED" in names
    table = trace_view.quality_table(records)
    assert table["checks"] == 2
    assert table["published"] == 1 and table["vetoed"] == 1
    assert table["last_recall"] == 0.1
    assert table["per_scenario"] == {"beaconing": 0.1}
    assert table["suites"][0]["source"] == "flow"
    assert trace_view.quality_table([{"kind": "other"}]) is None
