"""Sparse bucketed Pallas E-step (ops/sparse_estep.py) + the corpus
layout pass (Corpus.bucketed_layout) + the measured dense-vs-sparse
crossover.

The fused kernel must agree with estep.e_step's XLA path to fixed-point
tolerance on gamma, suff-stats, ELBO, and alpha suff-stats — it is a
full E-step, not just the fixed point — and the layout pass must
restore document order bit-exactly.  Kernel math runs under interpret
mode on every CPU run; the compiled variant is TPU-marked.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from oni_ml_tpu.io import Corpus
from oni_ml_tpu.ops import estep, sparse_estep


def _on_tpu() -> bool:
    import jax

    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


# Kernel-math parametrization (ISSUE 9 satellite): interpret=True runs
# on every CPU suite run; interpret=False is the real Mosaic compile,
# exercised only when a TPU backend is attached
# (ONI_ML_TPU_TESTS_ON_TPU=1, like tests/test_tpu_smoke.py).
INTERPRET = [
    pytest.param(True, id="interpret"),
    pytest.param(
        False, id="compiled",
        marks=pytest.mark.skipif(
            not _on_tpu(), reason="compiled Pallas needs a TPU backend"
        ),
    ),
]


@pytest.fixture(scope="module")
def problem():
    K, V, B, L = 4, 50, 32, 16
    rng = np.random.default_rng(0)
    noise = rng.uniform(size=(K, V)) + 1.0 / V
    lb = jnp.asarray(np.log(noise / noise.sum(-1, keepdims=True)), jnp.float32)
    w = jnp.asarray(rng.integers(0, V, size=(B, L)), jnp.int32)
    c = jnp.asarray(rng.integers(1, 5, size=(B, L)), jnp.float32)
    m = jnp.asarray((rng.uniform(size=B) > 0.2).astype(np.float32))
    return lb, jnp.float32(2.5), w, c, m


@pytest.fixture()
def plan_cache(tmp_path, monkeypatch):
    """Hermetic plan cache for tests that record/lookup entries."""
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv("ONI_ML_TPU_PLAN_CACHE", path)
    sparse_estep._CROSSOVER_CACHE.clear()
    yield path
    sparse_estep._CROSSOVER_CACHE.clear()


# ---------------------------------------------------------------------------
# Kernel parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("interpret", INTERPRET)
def test_full_e_step_parity(problem, interpret):
    """The fused kernel's gamma, suff-stats, ELBO, and alpha suff-stats
    all match the XLA reference — the tail runs in-kernel here, so this
    pins much more than the old fixed-point-only parity."""
    lb, a, w, c, m = problem
    ref = estep.e_step(lb, a, w, c, m, var_max_iters=50, var_tol=1e-7,
                       backend="xla")
    sp = sparse_estep.e_step(lb, a, w, c, m, 50, 1e-7,
                             interpret=interpret)
    sel = np.asarray(m) == 1
    np.testing.assert_allclose(
        np.asarray(sp.gamma)[sel], np.asarray(ref.gamma)[sel],
        rtol=5e-4, atol=5e-4,
    )
    np.testing.assert_allclose(
        np.asarray(sp.suff_stats), np.asarray(ref.suff_stats),
        rtol=2e-3, atol=2e-4,
    )
    np.testing.assert_allclose(
        float(sp.likelihood), float(ref.likelihood), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(sp.alpha_ss), float(ref.alpha_ss), rtol=1e-4
    )


@pytest.mark.parametrize("interpret", INTERPRET)
def test_bf16_slab_within_tolerance(problem, interpret):
    """bf16 slab storage rounds exp(log beta) to 8 significand bits —
    results agree with f32 to bf16 tolerance (NOT bit-exactly, unlike
    the dense engine's operand-truncation bf16 mode)."""
    lb, a, w, c, m = problem
    f32 = sparse_estep.e_step(lb, a, w, c, m, 50, 1e-7,
                              interpret=interpret)
    b16 = sparse_estep.e_step(lb, a, w, c, m, 50, 1e-7,
                              interpret=interpret, precision="bf16")
    np.testing.assert_allclose(
        float(b16.likelihood), float(f32.likelihood), rtol=5e-3
    )
    sel = np.asarray(m) == 1
    np.testing.assert_allclose(
        np.asarray(b16.gamma)[sel], np.asarray(f32.gamma)[sel],
        rtol=2e-2, atol=2e-2,
    )


def test_unknown_precision_rejected(problem):
    lb, a, w, c, m = problem
    with pytest.raises(ValueError, match="precision"):
        sparse_estep.e_step(lb, a, w, c, m, 5, 1e-6, interpret=True,
                            precision="fp8")


def test_iteration_cap_respected(problem):
    lb, a, w, c, m = problem
    sp = sparse_estep.e_step(lb, a, w, c, m, 3, 0.0, interpret=True)
    assert int(sp.vi_iters) == 3


def test_warm_start(problem):
    lb, a, w, c, m = problem
    fresh = sparse_estep.e_step(lb, a, w, c, m, 40, 1e-5, interpret=True)
    warm = sparse_estep.e_step(lb, a, w, c, m, 40, 1e-5, interpret=True,
                               gamma_prev=fresh.gamma, warm=1)
    assert int(warm.vi_iters) < int(fresh.vi_iters)
    np.testing.assert_allclose(float(warm.likelihood),
                               float(fresh.likelihood), rtol=1e-5)
    cold = sparse_estep.e_step(lb, a, w, c, m, 40, 1e-5, interpret=True,
                               gamma_prev=jnp.full_like(fresh.gamma, 7.0),
                               warm=0)
    np.testing.assert_array_equal(np.asarray(cold.gamma),
                                  np.asarray(fresh.gamma))


def test_forced_backend_through_estep(problem):
    """estep.e_step(backend='sparse') routes here; infeasible shapes
    fail loudly instead of silently falling back."""
    lb, a, w, c, m = problem
    ref = estep.e_step(lb, a, w, c, m, 20, 1e-6, backend="xla")
    sp = estep.e_step(lb, a, w, c, m, 20, 1e-6, backend="sparse")
    np.testing.assert_allclose(float(sp.likelihood),
                               float(ref.likelihood), rtol=1e-5)
    # B=12 divides by neither 8 nor 16: no feasible block.
    w12 = w[:12]
    c12 = c[:12]
    m12 = m[:12]
    with pytest.raises(ValueError, match="sparse E-step forced"):
        estep.e_step(lb, a, w12, c12, m12, 20, 1e-6, backend="sparse")


def test_make_e_step_fn_is_warm_capable(problem):
    lb, a, w, c, m = problem
    fn = sparse_estep.make_e_step_fn(precision="f32", interpret=True)
    assert fn._oni_warm_capable and fn._oni_sparse_engine
    res = fn(lb, a, w, c, m, 10, 1e-6)
    assert np.isfinite(float(res.likelihood))
    warm = fn(lb, a, w, c, m, 10, 1e-6, gamma_prev=res.gamma,
              warm=jnp.asarray(1, jnp.int32))
    assert int(warm.vi_iters) <= int(res.vi_iters)


# ---------------------------------------------------------------------------
# Block picking + plans
# ---------------------------------------------------------------------------


def test_pick_block_analytic():
    assert sparse_estep.pick_block(4096, 128, 20) in (128, 256)
    assert sparse_estep.pick_block(32, 16, 4) == 32
    # Non-8-divisible batch: no feasible block.
    assert sparse_estep.pick_block(12, 16, 4) is None
    # bf16 blocks sit on the 16-sublane tile.
    bb = sparse_estep.pick_block(64, 128, 4, "bf16")
    assert bb is not None and bb % 16 == 0
    # Huge L shrinks the block instead of blowing VMEM.
    bb = sparse_estep.pick_block(4096, 8192, 20)
    if bb is not None:
        assert sparse_estep._vmem_estimate(bb, 8192, 20) \
            <= sparse_estep._VMEM_CEILING


def test_vmem_estimate_pads_lanes_and_halves_bf16_slab():
    # L=16 occupies 128 lanes in VMEM tiles: the estimate must not
    # pretend a short bucket is 8x cheaper than it is.
    assert sparse_estep._vmem_estimate(8, 16, 4) == \
        sparse_estep._vmem_estimate(8, 128, 4)
    f32 = sparse_estep._vmem_estimate(16, 256, 4, "f32")
    b16 = sparse_estep._vmem_estimate(16, 256, 4, "bf16")
    assert b16 < f32


def test_planned_block_override_and_validation(plan_cache):
    from oni_ml_tpu import plans

    # A measured winner for this exact shape wins over the analytic
    # pick; garbage entries (wrong divisibility) are rejected.
    analytic = sparse_estep.pick_block(64, 128, 4)
    assert analytic == 64
    plans.record_value("sparse_estep_bb", 32, shape="b64.l128.k4.f32",
                       source="probe")
    assert sparse_estep.pick_block(64, 128, 4) == 32
    plans.record_value("sparse_estep_bb", 24, shape="b64.l128.k4.f32",
                       source="probe")     # not a multiple of 8
    assert sparse_estep.pick_block(64, 128, 4) == analytic
    plans.record_value("sparse_estep_bb", 48, shape="b64.l128.k4.f32",
                       source="probe")     # does not divide 64
    assert sparse_estep.pick_block(64, 128, 4) == analytic


def test_resolve_layout_len_sources(plan_cache):
    from oni_ml_tpu import plans
    from oni_ml_tpu.config import LDAConfig

    val, src = sparse_estep.resolve_layout_len(
        LDAConfig.sparse_min_bucket_len
    )
    assert (val, src) == (LDAConfig.sparse_min_bucket_len, "default")
    # Explicit config value wins as "config".
    val, src = sparse_estep.resolve_layout_len(64)
    assert (val, src) == (64, "config")
    # A plan entry beats the default.
    plans.record_value("sparse_estep_l", 256, source="probe")
    val, src = sparse_estep.resolve_layout_len(
        LDAConfig.sparse_min_bucket_len
    )
    assert (val, src) == (256, "plan")


def test_effective_vs_dense_equiv_flops():
    eff = sparse_estep.effective_flops(4096, 128, 20, 19)
    deq = sparse_estep.dense_equiv_flops(4096, 8192, 20, 19)
    assert eff == 4.0 * 4096 * 20 * 128 * 20
    # The 1.6%-dense bench shape wastes ~64x (8192 pads to itself).
    assert deq / eff == pytest.approx(8192 / 128)


# ---------------------------------------------------------------------------
# Corpus layout pass
# ---------------------------------------------------------------------------


def _toy_corpus(n_docs=60, v=120, seed=5):
    rng = np.random.default_rng(seed)
    triples = []
    for d in range(n_docs):
        n = int(rng.integers(1, 30))
        for word in rng.integers(0, v, n):
            triples.append((f"ip{d}", f"w{word}", int(rng.integers(1, 4))))
    return Corpus.from_word_counts(triples)


def test_bucketed_layout_packs_and_restores():
    corpus = _toy_corpus()
    lay = corpus.bucketed_layout(min_len=8, batch_cap=16)
    # Every real doc appears exactly once, rows match the CSR exactly.
    seen = []
    for b in lay.batches:
        assert b.word_idx.shape[0] % 8 == 0       # pad_multiple
        L = b.word_idx.shape[1]
        assert L >= 8 and (L & (L - 1)) == 0      # power-of-two bucket
        for i in range(b.word_idx.shape[0]):
            if b.doc_mask[i] == 0:
                assert (b.counts[i] == 0).all()
                continue
            d = int(b.doc_index[i])
            seen.append(d)
            lo, hi = int(corpus.doc_ptr[d]), int(corpus.doc_ptr[d + 1])
            n = hi - lo
            assert n <= L
            assert (b.word_idx[i, :n] == corpus.word_idx[lo:hi]).all()
            assert (b.counts[i, :n] == corpus.counts[lo:hi]).all()
            assert (b.counts[i, n:] == 0).all()
    assert sorted(seen) == list(range(corpus.num_docs))
    # perm is the packed order; restore() inverts it bit-exactly.
    assert list(lay.perm) == seen
    packed = np.asarray(lay.perm, np.float64) * 3.5
    restored = lay.restore(packed)
    np.testing.assert_array_equal(
        restored, np.arange(corpus.num_docs) * 3.5
    )
    with pytest.raises(ValueError, match="packed rows"):
        lay.restore(packed[:-1])


def test_bucketed_layout_sorted_by_length_and_cached():
    corpus = _toy_corpus()
    lay = corpus.bucketed_layout(min_len=8, batch_cap=16)
    lengths = corpus.doc_lengths()[lay.perm]
    assert (np.diff(lengths) >= 0).all()          # sorted by token count
    # One-time: the same parameters return the cached object.
    assert corpus.bucketed_layout(min_len=8, batch_cap=16) is lay
    assert corpus.bucketed_layout(min_len=16, batch_cap=16) is not lay


def test_bucket_shapes_match_layout():
    """bucket_shapes is the no-packing twin of bucketed_layout: the
    engine gates feasibility-check through it, so the two must agree
    shape-for-shape at every parameterization."""
    corpus = _toy_corpus()
    for min_len, cap, pad in [(8, 16, 8), (16, 32, 16), (128, 4096, 8)]:
        shapes = corpus.bucket_shapes(min_len=min_len, batch_cap=cap,
                                      pad_multiple=pad)
        lay = corpus.bucketed_layout(min_len=min_len, batch_cap=cap,
                                     pad_multiple=pad)
        assert [(s[0], s[1]) for s in shapes] == \
            [b.word_idx.shape for b in lay.batches]
        assert [s[2] for s in shapes] == \
            [int(b.doc_mask.sum()) for b in lay.batches]


def test_bucketed_layout_deterministic():
    a = _toy_corpus().bucketed_layout(min_len=8, batch_cap=16)
    b = _toy_corpus().bucketed_layout(min_len=8, batch_cap=16)
    assert len(a.batches) == len(b.batches)
    for x, y in zip(a.batches, b.batches):
        np.testing.assert_array_equal(x.word_idx, y.word_idx)
        np.testing.assert_array_equal(x.counts, y.counts)
        np.testing.assert_array_equal(x.doc_index, y.doc_index)
    np.testing.assert_array_equal(a.perm, b.perm)


# ---------------------------------------------------------------------------
# Crossover
# ---------------------------------------------------------------------------


def test_crossover_measures_then_resolves_from_plan(plan_cache):
    from oni_ml_tpu import plans

    before = plans.counters_snapshot()["autotune_sweeps"]
    rec = sparse_estep.engine_crossover(4, 512, 32, 16)
    assert rec["source"] == "measured"
    assert rec["engine"] in ("dense", "sparse")
    assert plans.counters_snapshot()["autotune_sweeps"] == before + 1
    # A fresh process (memo cleared) resolves from the persisted plan —
    # zero re-sweeps on run 2.
    sparse_estep._CROSSOVER_CACHE.clear()
    rec2 = sparse_estep.engine_crossover(4, 512, 32, 16)
    assert rec2["source"] == "plan"
    assert rec2["engine"] == rec["engine"]
    assert plans.counters_snapshot()["autotune_sweeps"] == before + 1
    # The density band generalizes to a neighbouring exact shape.
    sparse_estep._CROSSOVER_CACHE.clear()
    rec3 = sparse_estep.engine_crossover(4, 1024, 64, 32)
    assert rec3["source"] == "plan"
    assert rec3["shape"].startswith("dlog")


def test_crossover_env_pin(plan_cache, monkeypatch):
    monkeypatch.setenv("ONI_ML_TPU_ESTEP_ENGINE", "dense")
    rec = sparse_estep.engine_crossover(4, 512, 32, 16)
    assert rec == {"engine": "dense", "dense_s": None, "sparse_s": None,
                   "source": "env", "shape": rec["shape"]}
    monkeypatch.setenv("ONI_ML_TPU_ESTEP_ENGINE", "fastest")
    with pytest.raises(ValueError, match="ONI_ML_TPU_ESTEP_ENGINE"):
        sparse_estep.engine_crossover(4, 512, 32, 16)


def test_crossover_journals_record(plan_cache, tmp_path):
    from oni_ml_tpu.telemetry import Journal, Recorder, use_recorder

    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    with use_recorder(Recorder(journal=j)):
        sparse_estep.engine_crossover(4, 512, 32, 16)
    j.close()
    recs = [r for r in Journal.replay(path)
            if r.get("kind") == "estep_crossover"]
    assert len(recs) == 1
    assert recs[0]["engine"] in ("dense", "sparse")
    assert recs[0]["source"] == "measured"
    assert recs[0]["shape"] == "k4.v512.b32.l16.f32"


def test_density_bands():
    assert sparse_estep.crossover_shapes(20, 8192, 4096, 128, "bf16") == (
        "k20.v8192.b4096.l128.bf16", "dlog1.k20.bf16"
    )
    # 1.6% density lands in band 1; 0.8% in band 0 — neighbours get
    # separate evidence.
    assert sparse_estep._density_band(1.6) == 1
    assert sparse_estep._density_band(0.8) == 0
    assert sparse_estep._density_band(100.0) == 7   # clamped


# ---------------------------------------------------------------------------
# Trainer integration
# ---------------------------------------------------------------------------


def test_resolve_engine_cpu_auto_stays_dense_family(plan_cache):
    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.models.lda import resolve_estep_engine

    corpus = _toy_corpus()
    engine, src = resolve_estep_engine(corpus, LDAConfig(num_topics=4))
    assert (engine, src) == ("dense", "default")


def test_resolve_engine_forced_and_conflicts(plan_cache, monkeypatch):
    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.models.lda import resolve_estep_engine

    corpus = _toy_corpus()
    cfg = LDAConfig(num_topics=4, estep_engine="sparse")
    assert resolve_estep_engine(corpus, cfg) == ("sparse", "config")
    monkeypatch.setenv("ONI_ML_TPU_ESTEP", "sparse")
    assert resolve_estep_engine(corpus, LDAConfig(num_topics=4)) == \
        ("sparse", "env")
    # env beats config.
    assert resolve_estep_engine(
        corpus, LDAConfig(num_topics=4, estep_engine="dense")
    ) == ("sparse", "env")
    monkeypatch.delenv("ONI_ML_TPU_ESTEP")
    with pytest.raises(ValueError, match="dense_em"):
        resolve_estep_engine(
            corpus,
            LDAConfig(num_topics=4, estep_engine="sparse", dense_em="on"),
        )
    with pytest.raises(ValueError, match="estep_engine"):
        resolve_estep_engine(
            corpus, LDAConfig(num_topics=4, estep_engine="fastest")
        )


def test_sparse_engine_rejected_on_mesh(plan_cache):
    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.models.lda import resolve_estep_engine
    from oni_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(data=2, model=1)
    corpus = _toy_corpus()
    cfg = LDAConfig(num_topics=4, estep_engine="sparse")
    with pytest.raises(ValueError, match="single-process"):
        resolve_estep_engine(corpus, cfg, mesh=mesh)
    # Auto on a mesh quietly stays with the dense family.
    assert resolve_estep_engine(
        corpus, LDAConfig(num_topics=4), mesh=mesh
    ) == ("dense", "default")


def _train(cfg, corpus, out_dir=None):
    from oni_ml_tpu.models.lda import train_corpus

    return train_corpus(corpus, cfg, out_dir=out_dir)


def test_train_sparse_engine_matches_dense_family(plan_cache, tmp_path):
    """engine='sparse' vs engine='dense' (pinned) on the same corpus:
    final likelihood agrees within the bf16-class tolerance, gamma
    rows land in document order, and the plan record names the
    engine."""
    from oni_ml_tpu.config import LDAConfig

    corpus = _toy_corpus(n_docs=80, v=150)
    base = dict(num_topics=4, em_max_iters=5, batch_size=32,
                fused_em_chunk=4, host_sync_every=0, seed=0)
    out = tmp_path / "sparse"
    out.mkdir()
    res_s = _train(
        LDAConfig(estep_engine="sparse", sparse_min_bucket_len=16, **base),
        corpus, out_dir=str(out),
    )
    res_d = _train(LDAConfig(estep_engine="dense", **base), corpus)
    assert res_s.plan["estep_engine"] == {"value": "sparse",
                                          "source": "config"}
    assert res_s.plan["sparse_estep_l"]["value"] == 16
    assert res_d.plan["estep_engine"] == {"value": "dense",
                                          "source": "config"}
    ll_s = res_s.likelihoods[-1][0]
    ll_d = res_d.likelihoods[-1][0]
    np.testing.assert_allclose(ll_s, ll_d, rtol=1e-4)
    # Document order restored: per-doc posteriors agree row-for-row
    # despite the layout permutation reordering the device batches.
    assert res_s.gamma.shape == res_d.gamma.shape
    np.testing.assert_allclose(res_s.gamma, res_d.gamma,
                               rtol=2e-3, atol=2e-3)


def test_train_sparse_engine_pinned_is_byte_deterministic(
    plan_cache, tmp_path
):
    """Two runs with the engine pinned produce byte-identical
    artifacts — the acceptance contract for pinned-engine runs."""
    from oni_ml_tpu.config import LDAConfig

    cfg = LDAConfig(num_topics=4, em_max_iters=4, batch_size=32,
                    fused_em_chunk=4, host_sync_every=0, seed=0,
                    estep_engine="sparse", sparse_min_bucket_len=16)
    outs = []
    for name in ("a", "b"):
        d = tmp_path / name
        d.mkdir()
        _train(cfg, _toy_corpus(n_docs=50, v=100), out_dir=str(d))
        outs.append({
            f: (d / f).read_bytes()
            for f in ("final.beta", "final.gamma", "likelihood.dat")
        })
    assert outs[0] == outs[1]


def test_train_sparse_engine_via_env(plan_cache, tmp_path, monkeypatch):
    from oni_ml_tpu.config import LDAConfig

    monkeypatch.setenv("ONI_ML_TPU_ESTEP", "sparse")
    corpus = _toy_corpus(n_docs=40, v=80)
    res = _train(
        LDAConfig(num_topics=4, em_max_iters=3, batch_size=32,
                  sparse_min_bucket_len=16, fused_em_chunk=2,
                  host_sync_every=0),
        corpus,
    )
    assert res.plan["estep_engine"] == {"value": "sparse", "source": "env"}
    assert np.isfinite(res.likelihoods[-1][0])


def test_train_sparse_bf16_pads_batch_axis_to_sublane_tile(plan_cache):
    """A bucket packing to 24 docs has no bf16-feasible block when the
    layout pads to 8 (24 % 16 != 0): the layout must pad the batch axis
    to the engine precision's sublane tile instead of crashing
    mid-training (code-review finding on this PR)."""
    from oni_ml_tpu.config import LDAConfig

    corpus = _toy_corpus(n_docs=24, v=60)
    assert sparse_estep.pad_multiple_for("bf16") == 16
    res = _train(
        LDAConfig(num_topics=4, em_max_iters=2, batch_size=32,
                  estep_engine="sparse", sparse_min_bucket_len=16,
                  dense_precision="bf16", fused_em_chunk=2,
                  host_sync_every=0),
        corpus,
    )
    assert np.isfinite(res.likelihoods[-1][0])
    assert res.gamma.shape == (24, 4)


def test_train_sparse_infeasible_bucket_fails_fast(plan_cache):
    """A huge-L bucket that admits no VMEM block fails at engine setup
    with the shapes named — not deep inside the chunk program (the
    small-B/huge-L bucket is the VMEM-worst shape, invisible to a
    largest-batch-only gate)."""
    from oni_ml_tpu.config import LDAConfig

    rng = np.random.default_rng(1)
    triples = [("fat", f"w{w}", 1) for w in range(17_000)]
    for d in range(8):
        for w in rng.integers(0, 500, 10):
            triples.append((f"ip{d}", f"w{w}", 1))
    corpus = Corpus.from_word_counts(triples)
    assert max(corpus.doc_lengths()) == 17_000   # bucket L = 32768
    with pytest.raises(ValueError, match="32768"):
        _train(
            LDAConfig(num_topics=20, em_max_iters=2, batch_size=32,
                      estep_engine="sparse", sparse_min_bucket_len=16,
                      fused_em_chunk=2, host_sync_every=0),
            corpus,
        )


def test_train_sparse_engine_stepwise_driver(plan_cache):
    """fused_em_chunk<=1 routes the sparse engine through the stepwise
    loop — the numerical cross-check driver must accept it too."""
    from oni_ml_tpu.config import LDAConfig

    corpus = _toy_corpus(n_docs=40, v=80)
    res = _train(
        LDAConfig(num_topics=4, em_max_iters=3, batch_size=32,
                  estep_engine="sparse", sparse_min_bucket_len=16,
                  fused_em_chunk=1),
        corpus,
    )
    assert res.em_iters == 3
    assert np.isfinite(res.likelihoods[-1][0])
