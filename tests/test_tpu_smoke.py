"""TPU-gated Mosaic-under-shard_map check (VERDICT r3 weak-item 3).

The CPU-pinned suite (conftest) runs every multi-chip dense path with
`interpret=True`; only a real TPU exercises the shard_map + Pallas +
Mosaic compilation the production trainer uses.  This test is the
durable in-tree artifact for that check: it SKIPS on the CPU suite and
asserts `tools/tpu_smoke.run_checks()`'s compiled-vs-unwrapped equality
when a TPU backend is attached (run with the device-tunnel env intact
and the conftest CPU pin bypassed:
`ONI_ML_TPU_TESTS_ON_TPU=1 python -m pytest tests/test_tpu_smoke.py`).
bench.py's `mosaic_smoke` phase carries the same check into every
driver-captured BENCH record.
"""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
)


def _on_tpu() -> bool:
    import jax

    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def test_mosaic_shard_map_equality_on_tpu():
    if not _on_tpu():
        pytest.skip("no TPU backend attached (interpret path covered by "
                    "tests/test_sharded.py)")
    import tpu_smoke

    res = tpu_smoke.run_checks()
    assert res["backend"] in ("tpu", "axon")
    assert set(res["likelihoods"]) == {"wmajor=False", "wmajor=True"}
