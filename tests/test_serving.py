"""Streaming scoring service (oni_ml_tpu/serving): registry hot-swap
under concurrent scoring, micro-batch flush triggers, device-vs-host
scorer agreement, refresh-loop republish, and the end-to-end golden-day
smoke the acceptance criteria name.  All CPU, no markers — this file IS
the tier-1 serving smoke.
"""

import threading
import time

import numpy as np
import pytest

from oni_ml_tpu.config import OnlineLDAConfig, ServingConfig
from oni_ml_tpu.runner.serve import _synthetic_day
from oni_ml_tpu.scoring import ScoringModel, batched_scores, device_scores
from oni_ml_tpu.scoring.score import _batched_scores
from oni_ml_tpu.serving import (
    BatchScorer,
    DnsEventFeaturizer,
    MetricsEmitter,
    ModelRegistry,
    RefreshLoop,
    event_documents,
    validate_model,
)


@pytest.fixture(scope="module")
def day():
    """(raw dns rows, trained model, day cuts) — one synthetic day
    shared by the serving tests (runner/serve.py's dry-run fixture)."""
    return _synthetic_day()


def _perturbed(model: ScoringModel, seed: int = 7) -> ScoringModel:
    """A validly-normalized variant of `model` — a stand-in refresh."""
    rng = np.random.default_rng(seed)
    theta = model.theta * rng.uniform(0.5, 1.5, model.theta.shape)
    theta[:-1] /= theta[:-1].sum(1, keepdims=True)
    p = model.p * rng.uniform(0.5, 1.5, model.p.shape)
    p[:-1] /= p[:-1].sum(0, keepdims=True)
    return ScoringModel(
        ip_index=model.ip_index, theta=theta,
        word_index=model.word_index, p=p,
    )


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_publish_and_hot_swap(day):
    _, model, _ = day
    reg = ModelRegistry()
    with pytest.raises(RuntimeError):
        reg.active()
    s1 = reg.publish(model, source="t1")
    assert (s1.version, reg.version) == (1, 1)
    s2 = reg.publish(_perturbed(model), source="t2")
    assert s2.version == 2
    # Double-buffered: the retired snapshot stays pinned as previous.
    assert reg.previous() is s1
    assert reg.active() is s2


def test_registry_rejects_invalid_models(day):
    _, model, _ = day
    reg = ModelRegistry()
    reg.publish(model, source="good")
    bad_k = ScoringModel(model.ip_index, model.theta,
                         model.word_index, model.p[:, :-1])
    bad_rows = ScoringModel(model.ip_index, model.theta[:-1],
                            model.word_index, model.p)
    bad_neg = ScoringModel(model.ip_index, -model.theta,
                           model.word_index, model.p)
    bad_nan = ScoringModel(model.ip_index,
                           np.full_like(model.theta, np.nan),
                           model.word_index, model.p)
    for bad in (bad_k, bad_rows, bad_neg, bad_nan):
        with pytest.raises(ValueError):
            reg.publish(bad, source="bad")
    # A rejected publish must leave the active snapshot untouched.
    assert reg.active().model is model
    assert reg.version == 1
    assert validate_model(model) is model


def test_registry_load_day_from_golden(tmp_path):
    import os

    golden = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "golden", "expected", "dns")
    reg = ModelRegistry()
    snap = reg.load_day(golden, fallback=0.1)
    assert snap.version == 1 and snap.source == golden
    assert snap.model.theta.shape[0] == len(snap.model.ip_index) + 1
    with pytest.raises(FileNotFoundError):
        reg.load_day(str(tmp_path), fallback=0.1)


# ---------------------------------------------------------------------------
# device scorer vs host scorer
# ---------------------------------------------------------------------------


def test_device_scorer_matches_host(day):
    _, model, _ = day
    rng = np.random.default_rng(3)
    n = 1000
    ip_idx = rng.integers(0, model.theta.shape[0], n).astype(np.int32)
    w_idx = rng.integers(0, model.p.shape[0], n).astype(np.int32)
    host = _batched_scores(model, ip_idx, w_idx)
    dev = device_scores(model, ip_idx, w_idx)
    assert dev.dtype == np.float64
    # f32 gather+accumulate vs float64 host path: well inside 1e-5
    # relative at K=5..20 (the suspicion threshold cuts orders of
    # magnitude, not ulps).
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-12)


def test_device_scorer_pads_and_range_checks(day):
    _, model, _ = day
    # Non-power-of-two sizes pad internally; results must slice back.
    for n in (1, 3, 17):
        idx = np.arange(n, dtype=np.int32) % (model.theta.shape[0])
        widx = np.arange(n, dtype=np.int32) % (model.p.shape[0])
        out = device_scores(model, idx, widx)
        assert out.shape == (n,)
    assert device_scores(model, np.zeros(0, np.int32),
                         np.zeros(0, np.int32)).shape == (0,)
    with pytest.raises(IndexError):
        device_scores(model, np.asarray([-1], np.int32),
                      np.asarray([0], np.int32))
    with pytest.raises(IndexError):
        device_scores(model, np.asarray([0], np.int32),
                      np.asarray([model.p.shape[0]], np.int32))


def test_batched_scores_size_dispatch(day):
    _, model, _ = day
    rng = np.random.default_rng(5)
    n = 64
    ip_idx = rng.integers(0, model.theta.shape[0], n).astype(np.int32)
    w_idx = rng.integers(0, model.p.shape[0], n).astype(np.int32)
    host = batched_scores(model, ip_idx, w_idx)                 # no device
    below = batched_scores(model, ip_idx, w_idx, device_min=n + 1)
    at = batched_scores(model, ip_idx, w_idx, device_min=n)     # device
    np.testing.assert_array_equal(host, below)  # same host path
    np.testing.assert_allclose(at, host, rtol=1e-5, atol=1e-12)


# ---------------------------------------------------------------------------
# micro-batch flush triggers
# ---------------------------------------------------------------------------


def _scorer(day, registry=None, **cfg_kw):
    rows, model, cuts = day
    reg = registry or ModelRegistry()
    if reg.version == 0:
        reg.publish(model, source="test")
    metrics = MetricsEmitter(to_stdout=False)
    scorer = BatchScorer(
        reg, DnsEventFeaturizer(cuts), ServingConfig(**cfg_kw),
        metrics=metrics,
    )
    return rows, reg, metrics, scorer


def test_flush_on_max_batch(day):
    rows, _, metrics, scorer = _scorer(
        day, max_batch=8, max_wait_ms=60_000.0
    )
    try:
        futures = scorer.submit_many(rows[:16])
        # Two full batches must flush on size alone — well before the
        # 60 s wait trigger could fire.
        results = [f.result(timeout=30.0) for f in futures]
        assert len(results) == 16
        assert {r["trigger"] for r in metrics.records} == {"max_batch"}
        assert all(r["events"] == 8 for r in metrics.records)
    finally:
        scorer.close()


def test_flush_on_max_wait(day):
    rows, _, metrics, scorer = _scorer(
        day, max_batch=10_000, max_wait_ms=40.0
    )
    try:
        futures = scorer.submit_many(rows[:3])
        results = [f.result(timeout=30.0) for f in futures]
        assert len(results) == 3
        assert [r["trigger"] for r in metrics.records] == ["max_wait"]
        assert metrics.records[0]["events"] == 3
        # The latency counter reflects the enforced wait.
        assert metrics.records[0]["latency_ms"] >= 40.0
    finally:
        scorer.close()


def test_metrics_lines_shape(day):
    rows, _, metrics, scorer = _scorer(day, max_batch=16, max_wait_ms=20.0)
    try:
        for f in scorer.submit_many(rows[:32]):
            f.result(timeout=30.0)
    finally:
        scorer.close()
    assert len(metrics.records) >= 2
    for rec in metrics.records:
        for key in ("stage", "batch", "events", "latency_ms", "score_ms",
                    "events_per_sec", "queue_depth", "model_version",
                    "scorer", "flagged", "trigger"):
            assert key in rec, key
        assert rec["stage"] == "serve"


def test_submit_rejects_malformed_events(day):
    rows, _, _, scorer = _scorer(day, max_batch=4, max_wait_ms=20.0)
    try:
        with pytest.raises(ValueError):
            scorer.submit("not,enough,columns")
        futures = scorer.submit_many(rows[:4])
        assert len([f.result(timeout=30.0) for f in futures]) == 4
        assert scorer.events_scored == 4  # the malformed one never entered
    finally:
        scorer.close()


def test_close_drains_queue(day):
    rows, _, _, scorer = _scorer(day, max_batch=7, max_wait_ms=60_000.0)
    futures = scorer.submit_many(rows)  # 96 events, non-multiple of 7
    scorer.close()                      # drains everything, all triggers
    assert scorer.events_scored == len(rows)
    assert all(f.done() for f in futures)
    with pytest.raises(RuntimeError):
        scorer.submit(rows[0])


# ---------------------------------------------------------------------------
# hot-swap under concurrent scoring
# ---------------------------------------------------------------------------


def test_hot_swap_under_concurrent_scoring(day):
    rows, model, cuts = day
    reg = ModelRegistry()
    reg.publish(model, source="v1")
    metrics = MetricsEmitter(to_stdout=False)
    scorer = BatchScorer(
        reg, DnsEventFeaturizer(cuts),
        ServingConfig(max_batch=8, max_wait_ms=10.0), metrics=metrics,
    )
    stop = threading.Event()
    published = []

    def swapper():
        # Keep republishing while the stream is in flight so swaps land
        # between (and concurrently with) batch flushes.
        i = 0
        while not stop.is_set():
            published.append(
                reg.publish(_perturbed(model, seed=i), f"swap{i}").version
            )
            i += 1
            time.sleep(0.005)

    t = threading.Thread(target=swapper)
    t.start()
    try:
        futures = []
        for r in rows * 3:  # 288 events while swaps happen
            futures.append(scorer.submit(r))
        results = [f.result(timeout=60.0) for f in futures]
    finally:
        stop.set()
        t.join()
        scorer.close()
    # Exactly-once: every submitted event resolved, none double-counted.
    assert len(results) == len(rows) * 3
    assert scorer.events_scored == len(rows) * 3
    assert all(np.isfinite(s) for s, _ in results)
    # Each batch scored on ONE coherent snapshot, and the swaps were
    # actually observed by traffic.
    versions = {v for _, v in results}
    assert versions <= {1, *published}
    assert len(published) >= 1
    per_batch = {r["batch"]: r["model_version"] for r in metrics.records}
    assert len(per_batch) == scorer.batches_flushed


# ---------------------------------------------------------------------------
# refresh loop
# ---------------------------------------------------------------------------


def test_refresh_loop_republishes(day):
    rows, model, cuts = day
    reg = ModelRegistry()
    snap = reg.publish(model, source="day0")
    loop = RefreshLoop(
        reg, OnlineLDAConfig(num_topics=model.num_topics), every=2
    )
    feats = DnsEventFeaturizer(cuts)(rows[:48])
    ips, words = event_documents(feats, "dns")
    assert loop.observe(snap, ips, words) is None      # batch 1 of 2
    new = loop.observe(snap, ips, words)               # cadence crossed
    assert new is not None and new.version == 2
    assert reg.active() is new
    assert loop.refreshes == 1
    m = new.model
    # Same populations (vocab/IP identity is pinned at load)...
    assert m.ip_index is model.ip_index
    assert m.word_index is model.word_index
    # ...updated topics, still a valid model: per-topic word columns
    # re-normalize and refreshed theta rows stay distributions.
    validate_model(m)
    np.testing.assert_allclose(m.p[:-1].sum(0), 1.0, rtol=1e-8)
    touched = sorted({m.ip_index[ip] for ip in ips if ip in m.ip_index})
    np.testing.assert_allclose(
        m.theta[touched].sum(1), 1.0, rtol=1e-8
    )
    assert not np.allclose(m.p[:-1], model.p[:-1])     # actually moved
    # Fallback rows are config constants, never trained.
    np.testing.assert_array_equal(m.p[-1], model.p[-1])


def test_refresh_skips_out_of_vocab_evidence(day):
    rows, model, cuts = day
    reg = ModelRegistry()
    snap = reg.publish(model, source="day0")
    loop = RefreshLoop(
        reg, OnlineLDAConfig(num_topics=model.num_topics), every=1
    )
    ip = next(iter(model.ip_index))
    word = next(iter(model.word_index))
    new = loop.observe(
        snap,
        [ip, "10.99.99.99", ip],          # unknown IP skipped
        [word, word, "not_a_word"],        # OOV word skipped
    )
    assert new is not None and new.version == 2
    # Only the known (ip, word) pair trained; unknown IP rows untouched.
    fallback_row = len(model.ip_index)
    np.testing.assert_array_equal(
        new.model.theta[fallback_row], model.theta[fallback_row]
    )


def test_refresh_with_no_evidence_does_not_publish(day):
    _, model, _ = day
    reg = ModelRegistry()
    snap = reg.publish(model, source="day0")
    loop = RefreshLoop(
        reg, OnlineLDAConfig(num_topics=model.num_topics), every=1
    )
    assert loop.observe(snap, [], []) is None
    assert reg.version == 1


def test_from_topic_probs_seeds_near_input(day):
    from oni_ml_tpu.models.online_lda import OnlineLDATrainer

    _, model, _ = day
    p = np.asarray(model.p[:-1], np.float64)       # [V, K]
    cfg = OnlineLDAConfig(num_topics=model.num_topics)
    tr = OnlineLDATrainer.from_topic_probs(cfg, p, total_docs=100)
    lam = np.asarray(tr.lam, np.float64)
    beta = lam / lam.sum(-1, keepdims=True)        # E[beta] [K, V]
    np.testing.assert_allclose(beta.T, p, atol=2e-3)
    with pytest.raises(ValueError):
        OnlineLDATrainer.from_topic_probs(cfg, p[:, :-1], total_docs=100)


# ---------------------------------------------------------------------------
# end-to-end: golden-day model, >= 3 micro-batches, mid-stream hot-swap
# ---------------------------------------------------------------------------


def test_e2e_golden_day_serving():
    """The acceptance smoke: load the golden day's model, stream its raw
    events through >= 3 micro-batches, hot-swap to a refreshed model
    mid-stream via the refresh loop, and verify zero dropped / zero
    double-scored events plus per-batch metrics lines."""
    import os
    import sys

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "golden"))
    from generate import DNS_FALLBACK, load_dns_feats

    reg = ModelRegistry()
    snap = reg.load_day(os.path.join(here, "golden", "expected", "dns"),
                        fallback=DNS_FALLBACK)
    day_feats = load_dns_feats()
    from oni_ml_tpu.serving import featurizer_from_features

    featurizer = featurizer_from_features(day_feats)
    assert featurizer.dsource == "dns"

    loop = RefreshLoop(
        reg, OnlineLDAConfig(num_topics=snap.model.num_topics), every=2
    )
    swaps = []

    def on_batch(snapshot, feats, scores):
        ips, words = event_documents(feats, "dns")
        new = loop.observe(snapshot, ips, words)
        if new is not None:
            swaps.append(new.version)

    metrics = MetricsEmitter(to_stdout=False)
    scorer = BatchScorer(
        reg, featurizer,
        ServingConfig(max_batch=12, max_wait_ms=50.0), metrics=metrics,
        on_batch=on_batch,
    )
    with open(os.path.join(here, "golden", "inputs", "dns.csv")) as f:
        lines = [ln for ln in f if ln.strip()]
    assert len(lines) == 40
    futures = [scorer.submit(ln) for ln in lines]
    results = [f.result(timeout=60.0) for f in futures]
    scorer.close()

    # >= 3 micro-batches; zero dropped; zero double-scored.
    assert scorer.batches_flushed >= 3
    assert len(results) == 40 and scorer.events_scored == 40
    assert all(f.done() for f in futures)
    # Mid-stream hot-swap: a refresh published and later batches served
    # on the refreshed model.
    assert len(swaps) >= 1
    assert {v for _, v in results} >= {1, swaps[0]}
    # Scores on the day model are real probabilities of this day.
    assert all(0.0 <= s <= 1.0 for s, _ in results)
    # Per-batch latency/throughput metrics lines were emitted.
    assert len(metrics.records) == scorer.batches_flushed
    assert all("latency_ms" in r and "events_per_sec" in r
               for r in metrics.records)


def test_serve_dry_run_cli(capsys):
    """`ml_ops serve --dry-run` — the tools/serve_smoke.py path — runs
    the whole stack in-process and reports ok."""
    import json

    from oni_ml_tpu.runner import ml_ops

    assert ml_ops.main(["serve", "--dry-run"]) == 0
    last = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(last)
    assert summary["serve_dry_run"] == "ok"
    assert summary["batches"] >= 3
    assert summary["refresh_swaps"] >= 1


def test_on_batch_error_does_not_kill_worker(day):
    """A consumer failure (refresh publish rejected, broken sink) must
    be recorded and survived — a dead worker would hang every
    subsequent submit's future."""
    rows, model, cuts = day
    reg = ModelRegistry()
    reg.publish(model, source="v1")
    metrics = MetricsEmitter(to_stdout=False)
    calls = []

    def exploding(snapshot, feats, scores):
        calls.append(len(scores))
        raise ValueError("consumer bug")

    scorer = BatchScorer(
        reg, DnsEventFeaturizer(cuts),
        ServingConfig(max_batch=8, max_wait_ms=20.0), metrics=metrics,
        on_batch=exploding,
    )
    try:
        first = scorer.submit_many(rows[:8])
        [f.result(timeout=30.0) for f in first]      # scores delivered
        second = scorer.submit_many(rows[8:16])      # worker still alive
        [f.result(timeout=30.0) for f in second]
    finally:
        scorer.close()
    assert len(calls) == 2
    assert scorer.events_scored == 16
    assert any("on_batch_error" in r for r in metrics.records)


def test_submit_backpressure_blocks_not_grows(day):
    """With queue_max=4 a fast producer must still stream everything
    through (submit blocks until the worker drains) and the queue can
    never exceed the bound."""
    raw_rows, model, cuts = day
    reg = ModelRegistry()
    reg.publish(model, source="v1")
    scorer = BatchScorer(
        reg, DnsEventFeaturizer(cuts),
        ServingConfig(max_batch=2, max_wait_ms=5.0, queue_max=4),
    )
    try:
        futures = scorer.submit_many(raw_rows[:24])
        results = [f.result(timeout=60.0) for f in futures]
        assert len(results) == 24
        assert scorer.events_scored == 24
    finally:
        scorer.close()


def test_flush_on_empty_queue_is_noop(day):
    """flush() with nothing queued must not arm a flag that flushes the
    NEXT event as a premature batch of one."""
    rows, _, metrics, scorer = _scorer(
        day, max_batch=4, max_wait_ms=60_000.0
    )
    try:
        scorer.flush()                       # empty: must not arm
        time.sleep(0.05)
        futures = scorer.submit_many(rows[:4])
        [f.result(timeout=30.0) for f in futures]
        assert [r["trigger"] for r in metrics.records] == ["max_batch"]
    finally:
        scorer.close()


def test_validate_rejects_denormalized_model(day):
    _, model, _ = day
    reg = ModelRegistry()
    bad_theta = ScoringModel(model.ip_index, model.theta * 37.0,
                             model.word_index, model.p)
    bad_p = ScoringModel(model.ip_index, model.theta,
                         model.word_index, model.p * 37.0)
    for bad in (bad_theta, bad_p):
        with pytest.raises(ValueError):
            reg.publish(bad, source="denormalized")


def test_from_topic_probs_resume_wins_over_seed(day, tmp_path):
    """A checkpoint_path restoring an in-progress stream must keep the
    checkpoint's lambda — half-applying the seed over the checkpoint's
    step_count would desync topics from the rho schedule."""
    from oni_ml_tpu.models.online_lda import (
        OnlineLDATrainer,
        save_stream_checkpoint,
    )

    _, model, _ = day
    p = np.asarray(model.p[:-1], np.float64)
    cfg = OnlineLDAConfig(num_topics=model.num_topics)
    ckpt = str(tmp_path / "stream.npz")
    lam_ckpt = np.full((cfg.num_topics, p.shape[0]), 3.25)
    save_stream_checkpoint(ckpt, lam_ckpt, cfg.alpha, step=5,
                           history=[(-1.0, 0.1)] * 5)
    tr = OnlineLDATrainer.from_topic_probs(
        cfg, p, total_docs=100, checkpoint_path=ckpt
    )
    assert tr.step_count == 5
    np.testing.assert_allclose(np.asarray(tr.lam), lam_ckpt, rtol=1e-6)


def test_host_sync_every_negative_rejected():
    import reference_lda as ref
    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.models import train_corpus
    from test_lda import corpus_from_docs

    docs, _ = ref.make_synthetic_corpus(
        num_docs=16, num_terms=20, num_topics=2, seed=0
    )
    corpus = corpus_from_docs(docs, 20)
    with pytest.raises(ValueError, match="host_sync_every"):
        train_corpus(corpus, LDAConfig(
            num_topics=2, em_max_iters=2, batch_size=16,
            host_sync_every=-1,
        ))


def test_dry_run_honors_flags(capsys):
    import json

    from oni_ml_tpu.runner import ml_ops

    assert ml_ops.main(["serve", "--dry-run", "--max-batch", "8",
                        "--refresh-every", "1"]) == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["serve_dry_run"] == "ok"
    assert summary["batches"] == 12        # 96 events / max_batch 8
    assert summary["refresh_swaps"] >= 2   # refresh every batch


def test_batch_scorer_rejects_degenerate_config(day):
    """max_batch=0 would silently kill the worker (empty flush reads as
    shutdown) and queue_max=0 deadlocks the first submit — both must
    fail construction instead."""
    rows, model, cuts = day
    reg = ModelRegistry()
    reg.publish(model, source="v1")
    for bad in (dict(max_batch=0), dict(queue_max=0),
                dict(max_wait_ms=0.0)):
        with pytest.raises(ValueError):
            BatchScorer(reg, DnsEventFeaturizer(cuts),
                        ServingConfig(**bad))


def test_serve_stream_header_detection():
    """The serving ingress matches the batch pre stage's removeHeader:
    a leading column-name line is a header, data and garbage rows are
    not (garbage keeps the batch path's NaN-score semantics)."""
    from oni_ml_tpu.runner.serve import _looks_like_header

    flow_header = ("tstart,year,month,day,hour,min,sec,tdur,sip,dip,"
                   "sport,dport,proto,flag,fwd,stos,ipkt,ibyt,opkt,obyt,"
                   "in,out,sas,das,dtos,dir,rip")
    flow_row = ("2016-01-22 00:00:00,2016,1,22,5,38,2,0.0,10.0.0.2,"
                "10.1.0.3,46720,53,TCP,,0,0,69,26614,0,0,0,0,0,0,0,0,0")
    assert _looks_like_header(flow_header, "flow")
    assert not _looks_like_header(flow_row, "flow")
    dns_header = ("frame_time,unix_tstamp,frame_len,ip_dst,dns_qry_name,"
                  "dns_qry_class,dns_qry_type,dns_qry_rcode")
    dns_row = "t,1454000000,100,10.0.0.1,a.example.com,1,1,0"
    assert _looks_like_header(dns_header, "dns")
    assert not _looks_like_header(dns_row, "dns")
    assert not _looks_like_header("short", "flow")
