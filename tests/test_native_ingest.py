"""Native (C++) corpus ingest: exact parity with the Python builder,
multi-file concatenation, malformed-input errors, and fallback."""

import os

import numpy as np
import pytest

from oni_ml_tpu.io import Corpus, formats
from oni_ml_tpu.io import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native ingest not built and no g++"
)


def _random_triples(n, seed, n_ips=37, n_words=211):
    rng = np.random.default_rng(seed)
    return [
        (
            f"10.{rng.integers(0, 4)}.0.{rng.integers(1, n_ips)}",
            f"{rng.integers(0, 70000)}_{rng.integers(0, 10)}"
            f"_{rng.integers(0, 10)}_{rng.integers(0, 5)}"[:n_words],
            int(rng.integers(1, 1000)),
        )
        for _ in range(n)
    ]


def _assert_same(a: Corpus, b: Corpus):
    assert a.doc_names == b.doc_names
    assert a.vocab == b.vocab
    np.testing.assert_array_equal(a.doc_ptr, b.doc_ptr)
    np.testing.assert_array_equal(a.word_idx, b.word_idx)
    np.testing.assert_array_equal(a.counts, b.counts)


def test_parity_with_python(tmp_path):
    triples = _random_triples(5000, seed=1)
    path = str(tmp_path / "wc.dat")
    formats.write_word_counts(path, triples)
    nat = native.load_corpus(path)
    py = Corpus.from_word_counts(formats.read_word_counts(path))
    _assert_same(nat, py)
    assert nat.num_tokens == sum(c for _, _, c in triples)


def test_parity_edge_cases(tmp_path):
    path = str(tmp_path / "wc.dat")
    with open(path, "w") as f:
        f.write("1.2.3.4,80.0_1.0_2.0_3.0,5\n")
        f.write("\n")  # empty line skipped
        f.write("1.2.3.4,80.0_1.0_2.0_3.0,7\n")  # duplicate pair kept
        f.write("a,b ip,-1_80.0_1.0,3\n")  # comma inside ip: rsplit wins
        f.write("5.6.7.8,w,1")  # no trailing newline
    nat = native.load_corpus(path)
    py = Corpus.from_word_counts(formats.read_word_counts(path))
    _assert_same(nat, py)
    assert nat.doc_names == ["1.2.3.4", "a,b ip", "5.6.7.8"]
    assert nat.doc_ptr.tolist() == [0, 2, 3, 4]


def test_multi_file_concat(tmp_path):
    t1 = _random_triples(300, seed=2)
    t2 = _random_triples(300, seed=3)
    p1, p2, pall = (str(tmp_path / n) for n in ["a.dat", "b.dat", "all.dat"])
    formats.write_word_counts(p1, t1)
    formats.write_word_counts(p2, t2)
    formats.write_word_counts(pall, t1 + t2)
    _assert_same(native.load_corpus([p1, p2]), native.load_corpus(pall))


def test_universal_newlines(tmp_path):
    """CRLF and lone-CR files parse like Python's text-mode reader."""
    body = "1.2.3.4,w1,5{sep}1.2.3.4,w2,3{sep}5.6.7.8,w1,2{sep}"
    expect = Corpus.from_word_counts(
        [("1.2.3.4", "w1", 5), ("1.2.3.4", "w2", 3), ("5.6.7.8", "w1", 2)]
    )
    for sep in ["\r\n", "\r"]:
        path = str(tmp_path / "wc.dat")
        with open(path, "w", newline="") as f:
            f.write(body.format(sep=sep))
        _assert_same(native.load_corpus(path), expect)


def test_count_overflow_raises(tmp_path):
    path = str(tmp_path / "wc.dat")
    with open(path, "w") as f:
        f.write("1.2.3.4,w,4294967297\n")
    with pytest.raises(ValueError, match="out of range"):
        native.load_corpus(path)


def test_non_utf8_round_trips(tmp_path):
    """Raw wire bytes that are not valid UTF-8 (hostile DNS names, odd
    IP field contents) must flow through the corpus stage byte-for-byte
    via surrogateescape — in BOTH readers — not crash it."""
    path = str(tmp_path / "wc.dat")
    payload = b"1.2.3.4,w\xe9rd,5\n"
    with open(path, "wb") as f:
        f.write(payload)
    c = native.load_corpus(path)
    assert c.vocab == ["w\udce9rd"]
    out = str(tmp_path / "out")
    os.makedirs(out, exist_ok=True)
    c.save(out)
    with open(os.path.join(out, "words.dat"), "rb") as f:
        assert f.read() == b"0,w\xe9rd\n"
    # (Python-reader parity for the same bytes lives in test_formats.py,
    # which runs even without the native build.)


def test_malformed_line_raises(tmp_path):
    path = str(tmp_path / "bad.dat")
    with open(path, "w") as f:
        f.write("1.2.3.4,w,3\n")
        f.write("no-commas-here\n")
    with pytest.raises(ValueError, match="line 2"):
        native.load_corpus(path)
    with open(path, "w") as f:
        f.write("1.2.3.4,w,notanumber\n")
    with pytest.raises(ValueError, match="count"):
        native.load_corpus(path)


def test_from_word_counts_file_uses_native_and_env_disables(tmp_path):
    triples = _random_triples(100, seed=5)
    path = str(tmp_path / "wc.dat")
    formats.write_word_counts(path, triples)
    via_file = Corpus.from_word_counts_file(path)
    _assert_same(via_file, Corpus.from_word_counts(triples))
    # Env kill-switch forces the Python path (checked at load; simulate by
    # stubbing available()).
    orig = native.available
    native.available = lambda: False
    try:
        via_py = Corpus.from_word_counts_file(path)
    finally:
        native.available = orig
    _assert_same(via_file, via_py)


def test_native_is_faster_smoke(tmp_path):
    """Not a strict benchmark, but the native path must not be slower on a
    corpus big enough to matter (it's ~10-40x faster in practice)."""
    import time

    triples = _random_triples(200_000, seed=7)
    path = str(tmp_path / "big.dat")
    formats.write_word_counts(path, triples)
    t0 = time.perf_counter()
    nat = native.load_corpus(path)
    t_nat = time.perf_counter() - t0
    t0 = time.perf_counter()
    py = Corpus.from_word_counts(formats.read_word_counts(path))
    t_py = time.perf_counter() - t0
    _assert_same(nat, py)
    assert t_nat < t_py, (t_nat, t_py)
