"""Streaming raw-line reader (features/lineio.py): native-ingest line
semantics independent of chunk boundaries."""

import pytest

from oni_ml_tpu.features.lineio import iter_raw_lines

CASES = [
    (b"a,b\nc,d\n", ["a,b", "c,d"]),
    (b"a,b\r\nc,d\r\n", ["a,b", "c,d"]),          # CRLF stripped
    (b"a\rb\nc\n", ["a\rb", "c"]),                 # lone \r stays in field
    (b"a\r\r\nb\n", ["a\r", "b"]),                 # only ONE \r stripped
    (b"last-no-newline", ["last-no-newline"]),
    (b"tail\r", ["tail"]),                         # CR-final unterminated
    (b"\n\nx\n", ["", "", "x"]),                   # empties preserved
    (b"", []),
]


@pytest.mark.parametrize("data,want", CASES)
@pytest.mark.parametrize("chunk", [1, 2, 3, 1 << 22])
def test_line_semantics_all_chunk_sizes(tmp_path, data, want, chunk):
    p = tmp_path / "f.csv"
    p.write_bytes(data)
    assert list(iter_raw_lines(str(p), chunk_size=chunk)) == want
