"""tools/grant_watcher.py trigger logic (no TPU, no real time).

The watcher's value is its DECISIONS — when to fire chip_session, what
each exit code means for re-arming, how capture files are named — so
those are tested pure, with probe/capture/sleep injected.
"""

import fnmatch
import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)

import grant_watcher


def test_next_action_contract():
    # rc 0: mission complete, stop regardless of budget.
    assert grant_watcher.next_action(0, 1, 3)[0] == "stop"
    # rc 1 under budget: re-arm at normal cadence.
    assert grant_watcher.next_action(1, 1, 3) == ("rearm", 1.0)
    # rc 2 under budget: re-arm gentler (doubled interval).
    assert grant_watcher.next_action(2, 1, 3) == ("rearm", 2.0)
    # Budget exhausted: stop even on red/wedged.
    assert grant_watcher.next_action(1, 3, 3)[0] == "stop"
    assert grant_watcher.next_action(2, 3, 3)[0] == "stop"
    # A capture runner that itself died — signal-killed (negative rc,
    # e.g. OOM's -9) or no code at all — re-arms at the GENTLE cadence:
    # the grant is likely sick, and rapid retries re-wedge it.
    assert grant_watcher.next_action(None, 1, 3) == ("rearm", 2.0)
    assert grant_watcher.next_action(-9, 1, 3) == ("rearm", 2.0)
    assert grant_watcher.next_action(-15, 2, 3) == ("rearm", 2.0)


def test_capture_paths_unique_and_glob_compatible(tmp_path):
    p1 = grant_watcher.capture_out_path("r05", 1, str(tmp_path))
    p2 = grant_watcher.capture_out_path("r05", 2, str(tmp_path))
    assert p1 != p2
    # Both must match the glob bench._last_good_record() reads, so any
    # attempt's record is visible to later failure records.
    for p in (p1, p2):
        assert fnmatch.fnmatch(os.path.basename(p),
                               "r*_session_capture.json")
    assert os.path.basename(p1) == "r05_session_capture.json"
    assert os.path.basename(p2) == "r05a2_session_capture.json"


def test_capture_path_never_reuses_existing_files(tmp_path):
    """The no-overwrite invariant is on the FILES, not the watcher's
    own attempt counter: a manual chip_session run may already hold
    this round's canonical name (r05 did — the structured-failure
    capture), and the watcher's first firing must not clobber it."""
    cap_dir = tmp_path / "docs" / "bench_captures"
    cap_dir.mkdir(parents=True)
    (cap_dir / "r05_session_capture.json").write_text("{}")
    p = grant_watcher.capture_out_path("r05", 1, str(tmp_path))
    assert os.path.basename(p) == "r05a2_session_capture.json"
    # And with a2 also taken, attempt 1 walks to a3.
    (cap_dir / "r05a2_session_capture.json").write_text("{}")
    p = grant_watcher.capture_out_path("r05", 1, str(tmp_path))
    assert os.path.basename(p) == "r05a3_session_capture.json"


def test_round_tag_derived_from_bench_records(tmp_path):
    assert grant_watcher.current_round_tag(str(tmp_path)) == "r01"
    (tmp_path / "BENCH_r04.json").write_text("{}")
    assert grant_watcher.current_round_tag(str(tmp_path)) == "r05"


def _run_watch(probe_results, capture_rcs, **kw):
    """Drive watch() with scripted probe/capture outcomes; record the
    capture paths and sleep intervals it chooses."""
    probes = iter(probe_results)
    rcs = iter(capture_rcs)
    events = {"captures": [], "sleeps": []}

    def probe(timeout):
        return next(probes)

    def capture(out):
        events["captures"].append(os.path.basename(out))
        return next(rcs)

    def sleep(s):
        events["sleeps"].append(s)

    rc = grant_watcher.watch(
        interval_s=100.0, max_captures=3, round_tag="r05",
        probe=probe, capture=capture, sleep=sleep, log=lambda m: None,
        **kw)
    return rc, events


def test_watch_fires_on_first_alive_probe_and_stops_on_green(tmp_path):
    rc, ev = _run_watch([None, None, 1], [0], base_dir=str(tmp_path))
    assert rc == 0
    assert ev["captures"] == ["r05_session_capture.json"]
    # Two dead probes slept at the base interval before the grant came.
    assert ev["sleeps"] == [100.0, 100.0]


def test_watch_rearms_after_wedge_with_longer_backoff(tmp_path):
    # Wedged capture (rc 2) -> doubled interval; next alive probe fires
    # attempt 2 under its own name; green stops the loop.
    rc, ev = _run_watch([1, 1], [2, 0], base_dir=str(tmp_path))
    assert rc == 0
    assert ev["captures"] == ["r05_session_capture.json",
                              "r05a2_session_capture.json"]
    assert ev["sleeps"] == [200.0]      # the post-wedge sleep doubled


def test_watch_budget_exhaustion_returns_nonzero(tmp_path):
    rc, ev = _run_watch([1, 1, 1], [1, 1, 1], base_dir=str(tmp_path))
    assert rc == 1
    assert len(ev["captures"]) == 3     # budget respected, then stop
    # The injected capture writes no files, so each attempt claims its
    # own counter-derived name in the clean base_dir.
    assert ev["captures"] == ["r05_session_capture.json",
                              "r05a2_session_capture.json",
                              "r05a3_session_capture.json"]


def test_watch_once_mode_single_decision():
    rc, ev = _run_watch([None], [], once=True)
    assert rc == 1 and ev["captures"] == [] and ev["sleeps"] == []
