"""Parallel sharded pre-stage: workers=N must be BYTE-identical to
workers=1 on every artifact (word_counts.dat, the features.pkl numeric
arrays, interned tables), on both dsources, on hostile inputs — and the
direct featurizer→corpus handoff must equal the word_counts.dat parse
it replaces, with `--stages corpus` resume-from-file intact."""

import os
import pickle

import numpy as np
import pytest

from oni_ml_tpu.features import native_dns, native_flow
from oni_ml_tpu.features.shards import (
    iter_lines_sharded,
    plan_file_shards,
    read_shard_lines,
    resolve_pre_workers,
)
from oni_ml_tpu.io import Corpus, formats

from test_native_fuzz import _write_fuzz_dns_csv, _write_fuzz_flow_csv

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "inputs")


def _wc_bytes(features, tmp_path, tag):
    """word_counts.dat bytes exactly as stage_pre would emit them."""
    from oni_ml_tpu import native_emit

    if hasattr(features, "wc_ip") and native_emit.available():
        blob = native_emit.word_counts_emit(features)
        if blob is not None:
            return blob
    p = tmp_path / f"wc_{tag}.dat"
    formats.write_word_counts(str(p), features.word_counts())
    return p.read_bytes()


def _assert_flow_identical(a, b):
    assert a.num_events == b.num_events
    np.testing.assert_array_equal(a.num_time, b.num_time)
    np.testing.assert_array_equal(a.ibyt_bin, b.ibyt_bin)
    np.testing.assert_array_equal(a.ipkt_bin, b.ipkt_bin)
    np.testing.assert_array_equal(a.time_bin, b.time_bin)
    np.testing.assert_array_equal(a.time_cuts, b.time_cuts)
    assert a.src_word == b.src_word
    assert a.dest_word == b.dest_word
    assert a.word_counts() == b.word_counts()
    assert a.rows == b.rows


# ---------------------------------------------------------------------------
# Shard plan invariants
# ---------------------------------------------------------------------------


def test_shard_plan_covers_input_once(tmp_path):
    p = tmp_path / "d.csv"
    p.write_bytes(b"".join(b"line%d,x\n" % i for i in range(1000)))
    size = os.path.getsize(p)
    data = p.read_bytes()
    for w in (1, 2, 3, 7, 16):
        shards = plan_file_shards(str(p), w)
        assert len(shards) == w
        assert shards[0][0] == 0 and shards[-1][1] == size
        # Contiguous, and every boundary lands right after a '\n'.
        for (b0, e0), (b1, _) in zip(shards, shards[1:]):
            assert e0 == b1
            if 0 < b1 < size:
                assert data[b1 - 1:b1] == b"\n"
        # Concatenated shard lines == the sequential read.
        got = []
        for b, e in shards:
            got.extend(read_shard_lines(str(p), b, e))
        from oni_ml_tpu.features.lineio import iter_raw_lines

        assert got == list(iter_raw_lines(str(p)))


def test_iter_lines_sharded_matches_sequential(tmp_path):
    """The bounded-buffer ordered stream must equal the sequential read
    across multiple files, including a final unterminated line."""
    from oni_ml_tpu.features.lineio import iter_raw_lines

    paths = []
    for d in range(2):
        p = tmp_path / f"f{d}.csv"
        body = b"".join(b"%d,row%d\n" % (d, i) for i in range(500))
        if d == 1:
            body += b"tail,without,newline"
        p.write_bytes(body)
        paths.append(str(p))
    want = [ln for p in paths for ln in iter_raw_lines(p)]
    for w in (1, 2, 5):
        assert list(iter_lines_sharded(paths, w)) == want


def test_shard_plan_huge_line_collapses_ranges(tmp_path):
    """One line bigger than every raw split: all shards but the first
    collapse to empty rather than tearing the line."""
    p = tmp_path / "one.csv"
    p.write_bytes(b"a" * (1 << 20) + b"\nshort,line\n")
    shards = plan_file_shards(str(p), 8)
    nonempty = [s for s in shards if s[0] < s[1]]
    got = []
    for b, e in shards:
        got.extend(read_shard_lines(str(p), b, e))
    assert len(got) == 2 and got[1] == "short,line"
    assert len(nonempty) <= 2


def test_resolve_pre_workers():
    assert resolve_pre_workers(1) == 1
    assert resolve_pre_workers(5) == 5
    assert resolve_pre_workers(0) == max(1, os.cpu_count() or 1)
    with pytest.raises(ValueError):
        resolve_pre_workers(-1)


# ---------------------------------------------------------------------------
# workers=N byte-parity, native path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("workers", [2, 3, 8])
def test_flow_golden_day_parity(tmp_path, workers):
    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    path = os.path.join(GOLDEN, "flow.csv")
    seq = native_flow.featurize_flow_file(path, workers=1)
    par = native_flow.featurize_flow_file(path, workers=workers)
    _assert_flow_identical(par, seq)
    assert par.ip_table == seq.ip_table
    assert par.word_table == seq.word_table
    assert _wc_bytes(par, tmp_path, "p") == _wc_bytes(seq, tmp_path, "s")


@pytest.mark.parametrize("workers", [2, 4])
def test_dns_golden_day_parity(tmp_path, workers):
    if not native_dns.available():
        pytest.skip("native dns featurizer unavailable")
    path = os.path.join(GOLDEN, "dns.csv")
    seq = native_dns.featurize_dns_sources([path], workers=1)
    par = native_dns.featurize_dns_sources([path], workers=workers)
    assert isinstance(par, native_dns.NativeDnsFeatures)
    assert par.ip_table == seq.ip_table
    assert par.word_table == seq.word_table
    assert par.word_counts() == seq.word_counts()
    assert par.rows == seq.rows
    np.testing.assert_array_equal(par.subdomain_entropy,
                                  seq.subdomain_entropy)
    assert _wc_bytes(par, tmp_path, "p") == _wc_bytes(seq, tmp_path, "s")


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_flow_fuzz_parallel_parity(tmp_path, seed):
    """Hostile inputs (test_native_fuzz generators) through the shard
    fan-out: garbage fields, weird widths, str(float) boundary
    magnitudes — byte-identical to sequential whatever the shard
    boundaries cut through."""
    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    rng = np.random.default_rng(900 + seed)
    path = tmp_path / "flow.csv"
    _write_fuzz_flow_csv(rng, path)
    seq = native_flow.featurize_flow_file(str(path), workers=1)
    for w in (2, 5):
        par = native_flow.featurize_flow_file(str(path), workers=w)
        _assert_flow_identical(par, seq)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_dns_fuzz_parallel_parity(tmp_path, seed):
    if not native_dns.available():
        pytest.skip("native dns featurizer unavailable")
    rng = np.random.default_rng(950 + seed)
    path = tmp_path / "dns.csv"
    _write_fuzz_dns_csv(rng, path)
    seq = native_dns.featurize_dns_sources([str(path)], workers=1)
    for w in (2, 5):
        par = native_dns.featurize_dns_sources([str(path)], workers=w)
        assert par.word_counts() == seq.word_counts()
        assert par.rows == seq.rows
        assert par.word_table == seq.word_table


def test_crlf_line_split_across_shard_boundary(tmp_path):
    """CRLF terminators with line sizes arranged so raw byte splits land
    mid-line and mid-CRLF: the line-aligned plan must never tear a
    \\r\\n pair, and output must equal the sequential pass."""
    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    rows = ["header,row"]
    for i in range(101):
        # Varying widths so boundaries drift across \r\n pairs.
        rows.append(",".join([f"f{i}_{j}" * (1 + (i + j) % 3)
                              for j in range(5)] + ["x"] * 22))
    path = tmp_path / "crlf.csv"
    path.write_bytes(("\r\n".join(rows) + "\r\n").encode())
    seq = native_flow.featurize_flow_file(str(path), workers=1)
    for w in (2, 3, 7):
        par = native_flow.featurize_flow_file(str(path), workers=w)
        _assert_flow_identical(par, seq)


def test_header_row_lands_mid_shard(tmp_path):
    """removeHeader semantics under sharding: the first line of the
    first file is the header, and EVERY later duplicate — including
    ones that land in the middle of some other worker's shard — is
    dropped, exactly like the sequential pass."""
    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    header = ",".join(f"h{j}" for j in range(27))
    data = [",".join([f"r{i}"] + ["1"] * 26) for i in range(60)]
    # Sprinkle header duplicates everywhere, including shard interiors.
    lines = [header]
    for i, row in enumerate(data):
        lines.append(row)
        if i % 7 == 0:
            lines.append(header)
    path = tmp_path / "hdr.csv"
    path.write_text("\n".join(lines) + "\n")
    seq = native_flow.featurize_flow_file(str(path), workers=1)
    assert seq.num_events == 60  # every header copy dropped
    for w in (2, 4, 9):
        par = native_flow.featurize_flow_file(str(path), workers=w)
        _assert_flow_identical(par, seq)


def test_feedback_rows_append_after_merge(tmp_path):
    """Feedback rows ingest AFTER the sharded merge, so they take the
    last event slots and the last first-seen ids in both modes."""
    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    rng = np.random.default_rng(321)
    path = tmp_path / "flow.csv"
    _write_fuzz_flow_csv(rng, path)
    fb = [",".join([f"fb{i}"] + ["2"] * 26) for i in range(5)] * 3
    seq = native_flow.featurize_flow_file(str(path), feedback_rows=fb,
                                          workers=1)
    par = native_flow.featurize_flow_file(str(path), feedback_rows=fb,
                                          workers=4)
    assert par.num_raw_events == seq.num_raw_events
    assert par.num_events == seq.num_events > par.num_raw_events
    _assert_flow_identical(par, seq)


def test_parallel_with_spill_parity(tmp_path):
    """Sharded ingest with an active spill file: kept rows buffer per
    shard and append at merge time — the spill bytes and offsets must
    equal the sequential spill's."""
    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    rng = np.random.default_rng(77)
    path = tmp_path / "flow.csv"
    _write_fuzz_flow_csv(rng, path)
    seq = native_flow.featurize_flow_file(
        str(path), workers=1, spill_path=str(tmp_path / "s1.bin")
    )
    par = native_flow.featurize_flow_file(
        str(path), workers=3, spill_path=str(tmp_path / "sN.bin")
    )
    assert (tmp_path / "sN.bin").read_bytes() == \
        (tmp_path / "s1.bin").read_bytes()
    np.testing.assert_array_equal(par.line_off, seq.line_off)
    assert par.word_counts() == seq.word_counts()

    if native_dns.available():
        dpath = tmp_path / "dns.csv"
        _write_fuzz_dns_csv(rng, dpath)
        dseq = native_dns.featurize_dns_sources(
            [str(dpath)], workers=1, spill_path=str(tmp_path / "d1.bin")
        )
        dpar = native_dns.featurize_dns_sources(
            [str(dpath)], workers=3, spill_path=str(tmp_path / "dN.bin")
        )
        assert (tmp_path / "dN.bin").read_bytes() == \
            (tmp_path / "d1.bin").read_bytes()
        assert dpar.word_counts() == dseq.word_counts()


def test_multi_file_parallel_parity(tmp_path):
    """Each file shards independently; file order (and so the id
    contract) is preserved — the multi-file config-3 ingest shape."""
    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    rng = np.random.default_rng(55)
    paths = []
    for d in range(3):
        p = tmp_path / f"day{d}.csv"
        _write_fuzz_flow_csv(rng, p)
        paths.append(str(p))
    spec = ",".join(paths)
    seq = native_flow.featurize_flow_file(spec, workers=1)
    par = native_flow.featurize_flow_file(spec, workers=4)
    _assert_flow_identical(par, seq)
    assert par.ip_table == seq.ip_table
    assert par.word_table == seq.word_table


# ---------------------------------------------------------------------------
# Pure-Python fallback: same shard plan, same bytes
# ---------------------------------------------------------------------------


class _NoNative:
    def load(self):
        return None

    def available(self):
        return False


@pytest.mark.parametrize("dsource", ["flow", "dns"])
def test_fallback_parallel_parity(tmp_path, monkeypatch, dsource):
    rng = np.random.default_rng(400)
    if dsource == "flow":
        mod, writer = native_flow, _write_fuzz_flow_csv
        run = lambda p, w: native_flow.featurize_flow_file(p, workers=w)
    else:
        mod, writer = native_dns, _write_fuzz_dns_csv
        run = lambda p, w: native_dns.featurize_dns_sources([p], workers=w)
    path = tmp_path / f"{dsource}.csv"
    writer(rng, path)
    monkeypatch.setattr(mod, "_LIB", _NoNative())
    seq = run(str(path), 1)
    par = run(str(path), 4)
    assert type(par) is type(seq)   # both pure-Python containers
    assert par.rows == seq.rows
    assert par.word_counts() == seq.word_counts()


# ---------------------------------------------------------------------------
# Direct featurizer→corpus handoff
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dsource", ["flow", "dns"])
def test_from_features_equals_file_parse(tmp_path, dsource):
    rng = np.random.default_rng(600)
    if dsource == "flow":
        if not native_flow.available():
            pytest.skip("native flow featurizer unavailable")
        path = tmp_path / "f.csv"
        _write_fuzz_flow_csv(rng, path)
        feats = native_flow.featurize_flow_file(str(path), workers=2)
    else:
        if not native_dns.available():
            pytest.skip("native dns featurizer unavailable")
        path = tmp_path / "d.csv"
        _write_fuzz_dns_csv(rng, path)
        feats = native_dns.featurize_dns_sources([str(path)], workers=2)
    wc = tmp_path / "wc.dat"
    formats.write_word_counts(str(wc), feats.word_counts())
    via_file = Corpus.from_word_counts_file(str(wc))
    direct = Corpus.from_features(feats)
    assert direct.doc_names == via_file.doc_names
    assert direct.vocab == via_file.vocab
    np.testing.assert_array_equal(direct.doc_ptr, via_file.doc_ptr)
    np.testing.assert_array_equal(direct.word_idx, via_file.word_idx)
    np.testing.assert_array_equal(direct.counts, via_file.counts)


def test_from_features_python_container_routes_through_triples():
    from oni_ml_tpu.features.flow import featurize_flow

    lines = ["h"] + [
        ",".join([f"r{i}", "1", "1", str(i % 24), "0", "0"] + ["1"] * 21)
        for i in range(20)
    ]
    feats = featurize_flow(iter(lines))
    direct = Corpus.from_features(feats)
    ref = Corpus.from_word_counts(feats.word_counts())
    assert direct.doc_names == ref.doc_names and direct.vocab == ref.vocab
    np.testing.assert_array_equal(direct.word_idx, ref.word_idx)


def test_from_features_empty():
    class Empty:
        wc_ip = np.zeros(0, np.int32)
        wc_word = np.zeros(0, np.int32)
        wc_count = np.zeros(0, np.int32)
        ip_table: list = []
        word_table: list = []

    c = Corpus.from_features(Empty())
    assert c.num_docs == 0 and c.num_terms == 0 and c.num_tokens == 0


# ---------------------------------------------------------------------------
# Runner integration: handoff + resume + stage metrics
# ---------------------------------------------------------------------------


@pytest.fixture()
def flow_day(tmp_path):
    from test_features import flow_row

    rng = np.random.default_rng(9)
    lines = ["dummy,header"]
    for _ in range(80):
        lines.append(flow_row(
            hour=int(rng.integers(0, 24)), minute=int(rng.integers(0, 60)),
            second=int(rng.integers(0, 60)),
            sip=f"10.0.0.{rng.integers(1, 9)}",
            dip=f"172.16.0.{rng.integers(1, 9)}",
            col10=str(rng.choice([80, 443, 55000, 0])),
            col11=str(rng.choice([80, 6000, 70000])),
            ipkt=str(rng.integers(1, 100)),
            ibyt=str(rng.integers(40, 10000)),
        ))
    raw = tmp_path / "flow.csv"
    raw.write_text("\n".join(lines) + "\n")
    from oni_ml_tpu.config import LDAConfig, PipelineConfig, ScoringConfig

    return PipelineConfig(
        data_dir=str(tmp_path), flow_path=str(raw),
        lda=LDAConfig(num_topics=4, em_max_iters=4, batch_size=32, seed=3),
        scoring=ScoringConfig(threshold=1.1),
        pre_workers=2,
    ), tmp_path


def test_run_pipeline_direct_handoff_and_resume(flow_day):
    from oni_ml_tpu.runner import Stage, run_pipeline

    cfg, tmp_path = flow_day
    metrics = run_pipeline(cfg, "20160122", "flow")
    day = tmp_path / "20160122"
    pre = next(m for m in metrics if m.get("stage") == "pre")
    corpus_rec = next(m for m in metrics if m.get("stage") == "corpus")
    # Stage-record contract: worker count + per-pass walls on pre,
    # handoff mode on corpus.
    assert pre["pre_workers"] == 2
    assert "wall" in pre and "wc_write" in pre["wall"]
    assert corpus_rec["handoff"] == "direct"
    # The background writer finished before run_pipeline returned: the
    # resume/audit contract file exists and parses to the same corpus.
    wc_path = day / "word_counts.dat"
    assert wc_path.exists()
    via_file = Corpus.from_word_counts_file(str(wc_path))
    saved_words = (day / "words.dat").read_bytes()
    saved_docs = (day / "doc.dat").read_bytes()
    assert via_file.num_docs == len(saved_docs.decode().splitlines())

    # Resume-from-file: wipe the corpus outputs, re-run ONLY the corpus
    # stage in a fresh run (no live features) — it must parse
    # word_counts.dat and reproduce byte-identical artifacts.
    for name in ("words.dat", "doc.dat", "model.dat"):
        (day / name).unlink()
    metrics2 = run_pipeline(cfg, "20160122", "flow",
                            stages=[Stage.CORPUS])
    rec2 = next(m for m in metrics2 if m.get("stage") == "corpus")
    assert rec2["handoff"] == "file"
    assert (day / "words.dat").read_bytes() == saved_words
    assert (day / "doc.dat").read_bytes() == saved_docs


def test_run_pipeline_workers_byte_identical_artifacts(flow_day):
    """The full-pipeline contract: every pre/corpus artifact byte-equal
    between a workers=2 run and a workers=1 run of the same day."""
    from oni_ml_tpu.runner import Stage, run_pipeline

    cfg, tmp_path = flow_day
    run_pipeline(cfg, "20160122", "flow", stages=[Stage.PRE, Stage.CORPUS])
    cfg1 = cfg.replace(data_dir=str(tmp_path / "w1"), pre_workers=1)
    run_pipeline(cfg1, "20160122", "flow",
                 stages=[Stage.PRE, Stage.CORPUS])
    day2 = tmp_path / "20160122"
    day1 = tmp_path / "w1" / "20160122"
    for name in ("word_counts.dat", "words.dat", "doc.dat", "model.dat",
                 "raw_lines.bin"):
        if name == "raw_lines.bin" and not (day2 / name).exists():
            # Pure-Python fallback keeps rows in memory (no spill file);
            # both runs must then agree on its absence.
            assert not (day1 / name).exists()
            continue
        assert (day2 / name).read_bytes() == (day1 / name).read_bytes(), name
    # features.pkl numeric arrays (pickles embed the spill PATH, which
    # differs by directory — compare contents, not bytes).
    with open(day2 / "features.pkl", "rb") as f:
        f2 = pickle.load(f)
    with open(day1 / "features.pkl", "rb") as f:
        f1 = pickle.load(f)
    for attr in ("num_time", "ibyt_bin", "ipkt_bin", "time_bin",
                 "wc_ip", "wc_word", "wc_count", "line_off"):
        if hasattr(f1, attr):
            np.testing.assert_array_equal(
                getattr(f2, attr), getattr(f1, attr), err_msg=attr
            )
    assert f2.word_counts() == f1.word_counts()
