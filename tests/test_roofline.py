"""Roofline accounting + SLO metrics plane: peak-spec registry, XLA
cost-analysis harvest (with CPU/older-jax degradation), the
fixed-boundary log-bucket histogram's quantile estimates, the
OpenMetrics exporter (text + HTTP endpoint), the load generator's
arrival patterns and SLO harness, the batcher's per-stage latency
decomposition, heartbeat latency routing, and the trace_view
utilization lanes.  All CPU tier-1.
"""

import json
import math
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from oni_ml_tpu.telemetry import (
    HeartbeatMonitor,
    Journal,
    Recorder,
    RunJournal,
    render_openmetrics,
)
from oni_ml_tpu.telemetry import roofline
from oni_ml_tpu.telemetry.spans import Histogram

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _clean_registry():
    roofline.reset()
    yield
    roofline.reset()


# ---------------------------------------------------------------------------
# peak-spec registry
# ---------------------------------------------------------------------------


def test_peaks_for_v5e_fingerprints():
    # The plans-layer fingerprint shapes this registry must match:
    # "backend:device_kind:count", normalized lowercase/underscores.
    for fp in ("tpu:tpu_v5_lite:1", "axon:tpu_v5e:4", "tpu:v5litepod-8:8"):
        spec = roofline.peaks_for(fp)
        assert spec is not None, fp
        assert spec.flops_per_s == 197e12
        assert spec.hbm_bytes_per_s == 819e9
        assert "r03" in spec.provenance  # provenance rides every spec


def test_peaks_for_cpu_and_unknown_are_none():
    for fp in ("cpu:cpu:1", "nodevice", "host:x86_64:2", None, "",
               "tpu:tpu_v9_hypothetical:1"):
        assert roofline.peaks_for(fp) is None


# ---------------------------------------------------------------------------
# cost harvest
# ---------------------------------------------------------------------------


def test_harvest_jitted_on_cpu_registers_cost():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a, b: a @ b)
    x = jnp.ones((64, 64), jnp.float32)
    entry = roofline.harvest_jitted("t.matmul", f, x, x, shape="64x64")
    assert entry is not None
    assert entry["source"] in ("cost_analysis", "unavailable")
    assert roofline.cost_for("t.matmul") == entry
    if entry["source"] == "cost_analysis":
        # 64x64x64 matmul: 2*N^3 flops.
        assert entry["flops"] == pytest.approx(2 * 64**3, rel=0.5)


def test_harvest_compiled_tolerates_missing_cost_analysis():
    class NoCost:
        def cost_analysis(self):
            raise NotImplementedError("older jax / odd backend")

    entry = roofline.harvest_compiled("t.nocost", NoCost())
    assert entry["source"] == "unavailable"
    assert entry["flops"] is None and entry["bytes"] is None
    # The record built on top is wall-time-only, not an exception.
    rec = roofline.roofline_record("t.nocost", wall_s=1.0)
    assert rec["flops_per_s"] is None and rec["utilization"] is None
    assert rec["wall_s"] == 1.0


def test_ensure_harvested_is_once_per_name():
    calls = []

    class Fn:
        def lower(self, *a, **kw):
            calls.append(1)
            return self

        def compile(self):
            return self

        def cost_analysis(self):
            return {"flops": 10.0, "bytes accessed": 20.0}

    roofline.ensure_harvested("t.once", Fn())
    roofline.ensure_harvested("t.once", Fn())
    assert len(calls) == 1
    assert roofline.cost_for("t.once")["flops"] == 10.0


def test_ensure_harvested_reharvests_on_shape_change():
    """A later dispatch of the same entry at a different shape must not
    be priced with the stale shape's cost — the registry re-harvests
    when the shape key changes (and is still free on repeats)."""
    calls = []

    class Fn:
        def __init__(self, flops):
            self.flops = flops

        def lower(self, *a, **kw):
            calls.append(1)
            return self

        def compile(self):
            return self

        def cost_analysis(self):
            return {"flops": self.flops, "bytes accessed": 1.0}

    roofline.ensure_harvested("t.shape", Fn(10.0), shape="c8192")
    roofline.ensure_harvested("t.shape", Fn(10.0), shape="c8192")
    assert len(calls) == 1
    roofline.ensure_harvested("t.shape", Fn(2.0), shape="c1024")
    assert len(calls) == 2
    cost = roofline.cost_for("t.shape")
    assert cost["flops"] == 2.0 and cost["shape"] == "c1024"


def test_harvest_failure_invalidates_stale_shape_entry():
    """When re-lowering at a NEW shape fails, the old shape's cost must
    not survive to mis-price the new dispatches: the entry degrades to
    unavailable (wall-time-only records) for the current shape."""
    class Good:
        def lower(self, *a, **kw):
            return self

        def compile(self):
            return self

        def cost_analysis(self):
            return {"flops": 10.0, "bytes accessed": 20.0}

    class Broken:
        def lower(self, *a, **kw):
            raise RuntimeError("lowering failed")

    roofline.ensure_harvested("t.inval", Good(), shape="c8192")
    assert roofline.cost_for("t.inval")["flops"] == 10.0
    roofline.ensure_harvested("t.inval", Broken(), shape="c1024")
    cost = roofline.cost_for("t.inval")
    assert cost["source"] == "unavailable" and cost["shape"] == "c1024"
    assert cost["flops"] is None


# ---------------------------------------------------------------------------
# record math + emission
# ---------------------------------------------------------------------------


def _fake_cost(name, flops, nbytes, backend):
    class C:
        def cost_analysis(self):
            return {"flops": flops, "bytes accessed": nbytes}

    entry = roofline.harvest_compiled(name, C())
    entry["backend"] = backend
    with roofline._LOCK:
        roofline._COSTS[name] = entry


def test_roofline_record_math_against_peaks():
    _fake_cost("t.em", 197e10, 819e7, "tpu:tpu_v5_lite:1")  # 1% peaks @1s
    rec = roofline.roofline_record("t.em", wall_s=1.0, dispatches=2)
    assert rec["flops"] == 2 * 197e10
    assert rec["flops_per_s"] == pytest.approx(2 * 197e10)
    assert rec["utilization"]["mxu_pct"] == pytest.approx(2.0)
    assert rec["utilization"]["hbm_pct"] == pytest.approx(2.0)
    assert rec["peaks"]["flops_per_s"] == 197e12
    assert rec["kind"] == "roofline" and rec["dispatches"] == 2


def test_roofline_record_cpu_degrades_to_no_utilization():
    _fake_cost("t.cpu", 1e9, 1e6, "cpu:cpu:1")
    rec = roofline.roofline_record("t.cpu", wall_s=0.5)
    assert rec["flops_per_s"] == pytest.approx(2e9)  # achieved still real
    assert rec["peaks"] is None and rec["utilization"] is None


def test_emit_journals_record_and_sets_gauges(tmp_path):
    _fake_cost("t.phase", 197e10, 819e7, "tpu:tpu_v5_lite:1")
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    rec = Recorder(journal=j)
    before = roofline.emit_count()
    out = roofline.emit("t.phase", 1.0, recorder=rec, extra_field=7)
    j.close()
    assert out["extra_field"] == 7
    lines = [r for r in Journal.replay(path) if r.get("kind") == "roofline"]
    assert len(lines) == 1
    assert lines[0]["phase"] == "t.phase"
    assert lines[0]["utilization"]["mxu_pct"] == pytest.approx(1.0)
    assert rec.gauges["roofline.t.phase.mxu_pct"] == pytest.approx(1.0)
    assert rec.gauges["roofline.t.phase.flops_per_s"] > 0
    assert roofline.emitted_records(since=before)[0]["phase"] == "t.phase"


def test_emit_accepts_run_journal_and_never_raises(tmp_path):
    path = str(tmp_path / "j.jsonl")
    rj = RunJournal(Journal(path))
    roofline.emit("t.unharvested", 0.25, journal=rj)  # no cost: fine
    rj.close()
    recs = [r for r in Journal.replay(path) if r.get("kind") == "roofline"]
    assert len(recs) == 1
    assert recs[0]["cost_source"] == "unharvested"
    assert recs[0]["utilization"] is None
    roofline.emit("t.nothing", 0.1)  # no recorder, no journal: no raise


def test_subprocess_cpu_journal_carries_wall_time_only_record(tmp_path):
    """Satellite acceptance: under JAX_PLATFORMS=cpu the journal still
    carries the roofline record — no peaks, no exceptions,
    `utilization: null` — whatever cost_analysis does on this backend."""
    jpath = str(tmp_path / "run_journal.jsonl")
    script = """
import jax, jax.numpy as jnp
from oni_ml_tpu.telemetry import Journal, RunJournal
from oni_ml_tpu.telemetry import roofline

f = jax.jit(lambda a, b: a @ b)
x = jnp.ones((32, 32), jnp.float32)
roofline.harvest_jitted("em.run_chunk", f, x, x)
rj = RunJournal(Journal({jpath!r}))
roofline.emit("em.run_chunk", 0.5, dispatches=3, journal=rj)
rj.close()
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-c", script.format(jpath=jpath)],
        capture_output=True, text=True, env=env, timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [r for r in Journal.replay(jpath)
            if r.get("kind") == "roofline"]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["phase"] == "em.run_chunk" and rec["dispatches"] == 3
    assert rec["utilization"] is None and rec["peaks"] is None
    assert rec["wall_s"] == 0.5


# ---------------------------------------------------------------------------
# fixed-boundary log-bucket histogram quantiles
# ---------------------------------------------------------------------------


def test_histogram_quantiles_accurate_within_bucket_width():
    import threading

    h = Histogram("t", threading.RLock())
    vals = np.linspace(1.0, 1000.0, 5000)
    for v in vals:
        h.observe(float(v))
    # 2^(1/4) buckets: estimates within ~±10% of the true quantile.
    for q in (0.5, 0.9, 0.99, 0.999):
        true = float(np.quantile(vals, q))
        est = h.quantile(q)
        assert abs(est - true) / true < 0.10, (q, est, true)
    s = h.summary()
    assert s["p50"] == h.quantile(0.5)
    assert s["p999"] is not None and s["p999"] <= s["max"]
    assert s["count"] == 5000


def test_histogram_quantile_edge_cases():
    import threading

    h = Histogram("t", threading.RLock())
    assert h.quantile(0.5) is None           # empty
    h.observe(3.0)
    assert h.quantile(0.5) == pytest.approx(3.0)   # single value clamps
    assert h.quantile(0.999) == pytest.approx(3.0)
    # q=0 on an all-positive histogram clamps to the observed min — the
    # empty zero bucket must not fabricate a 0.
    assert h.quantile(0.0) == pytest.approx(3.0)
    z = Histogram("z", threading.RLock())
    for _ in range(10):
        z.observe(0.0)                        # zero bucket only
    assert z.quantile(0.5) == 0.0
    neg = Histogram("n", threading.RLock())
    neg.observe(-2.0)
    assert neg.quantile(0.5) == -2.0


def test_histogram_boundary_values_respect_le_semantics():
    import threading

    h = Histogram("t", threading.RLock())
    h.observe(2.0)   # exactly on a bucket boundary (2^(4/4))
    buckets = h.openmetrics_buckets()
    le2 = [c for le, c in buckets if le == 2.0]
    assert le2 == [1]  # counted at le=2, not pushed into the next bucket


def test_histogram_drops_non_finite_observations():
    """A NaN must not poison sum/mean for the life of the process, and
    +/-inf has no bucket: non-finite observations are dropped entirely,
    so `_count` stays equal to the +Inf bucket and the exposition stays
    valid OpenMetrics."""
    import threading

    h = Histogram("t", threading.RLock())
    h.observe(1.0)
    h.observe(float("nan"))
    h.observe(float("inf"))
    h.observe(float("-inf"))
    h.observe(3.0)
    s = h.summary()
    assert s["count"] == 2
    assert s["sum"] == pytest.approx(4.0)
    assert s["min"] == 1.0 and s["max"] == 3.0
    assert h.zero_count == 0                  # inf never misfiled there
    assert h.openmetrics_buckets()[-1] == (math.inf, 2)


def test_histogram_openmetrics_snapshot_consistent():
    """summary and buckets come back from ONE lock acquisition, and the
    +Inf bucket equals the count — the invariant the exporter's
    exposition must hold under concurrent observes."""
    import threading

    h = Histogram("t", threading.RLock())
    for v in (0.5, 1.0, 4.0):
        h.observe(v)
    s, buckets = h.openmetrics_snapshot()
    assert s["count"] == 3 and buckets[-1] == (math.inf, s["count"])


def test_histogram_openmetrics_buckets_cumulative():
    import threading

    h = Histogram("t", threading.RLock())
    for v in (0.0, 1.0, 2.0, 500.0):
        h.observe(v)
    buckets = h.openmetrics_buckets()
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)          # cumulative, non-decreasing
    assert buckets[-1] == (math.inf, 4)      # +Inf carries the total
    les = [le for le, _ in buckets[:-1]]
    assert les == sorted(les)


# ---------------------------------------------------------------------------
# OpenMetrics exporter
# ---------------------------------------------------------------------------


def test_render_openmetrics_format():
    rec = Recorder()
    rec.counter("serve.events").add(56)
    rec.histogram("serve.latency_ms").observe(5.0)
    rec.histogram("serve.latency_ms").observe(7.0)
    rec.gauge("roofline.em.run_chunk.mxu_pct", 10.5)
    text = render_openmetrics(rec)
    assert text.endswith("# EOF\n")
    assert "# TYPE serve_events counter" in text
    assert "serve_events_total 56" in text
    assert "# TYPE roofline_em_run_chunk_mxu_pct gauge" in text
    assert "roofline_em_run_chunk_mxu_pct 10.5" in text
    assert "# TYPE serve_latency_ms histogram" in text
    assert 'serve_latency_ms_bucket{le="+Inf"} 2' in text
    assert "serve_latency_ms_sum 12" in text
    assert "serve_latency_ms_count 2" in text
    # every bucket line's le parses and cumulative counts ascend
    cums = []
    for line in text.splitlines():
        if line.startswith("serve_latency_ms_bucket"):
            cums.append(int(line.rsplit(" ", 1)[1]))
    assert cums == sorted(cums) and cums[-1] == 2


def test_render_openmetrics_refresh_hook_runs_and_is_isolated():
    rec = Recorder()
    calls = []

    def refresh():
        calls.append(1)
        rec.gauge("live.g", 1.0)

    text = render_openmetrics(rec, refresh=refresh)
    assert calls == [1] and "live_g 1" in text

    def broken():
        raise RuntimeError("scrape must survive this")

    assert render_openmetrics(rec, refresh=broken).endswith("# EOF\n")


def test_metrics_server_serves_live_registry():
    from oni_ml_tpu.telemetry import MetricsServer

    rec = Recorder()
    rec.counter("serve.events").add(3)
    srv = MetricsServer(rec, port=0)   # ephemeral port
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        resp = urllib.request.urlopen(url, timeout=10)
        body = resp.read().decode()
        assert resp.headers["Content-Type"].startswith(
            "application/openmetrics-text"
        )
        assert "serve_events_total 3" in body
        rec.counter("serve.events").add(4)   # live: no snapshot staleness
        body2 = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "serve_events_total 7" in body2
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=10
            )
    finally:
        srv.close()


def test_write_openmetrics_file_sink(tmp_path):
    from oni_ml_tpu.telemetry import write_openmetrics

    rec = Recorder()
    rec.counter("c").add(1)
    path = str(tmp_path / "metrics.om")
    write_openmetrics(path, rec)
    with open(path) as f:
        text = f.read()
    assert text == render_openmetrics(rec)


# ---------------------------------------------------------------------------
# load generator
# ---------------------------------------------------------------------------


def test_arrival_offsets_poisson_statistics():
    import load_gen

    offs = load_gen.arrival_offsets("poisson", 20000, 1000.0, seed=1)
    assert len(offs) == 20000
    gaps = np.diff(offs)
    assert (gaps >= 0).all()
    assert np.mean(gaps) == pytest.approx(1e-3, rel=0.05)


def test_arrival_offsets_bursty_shape():
    import load_gen

    offs = load_gen.arrival_offsets("bursty", 256, 1000.0, burst_len=64)
    # 4 bursts of 64 at 64ms spacing; zero gaps inside a burst.
    assert len(offs) == 256
    assert set(np.unique(offs).round(6)) == {0.0, 0.064, 0.128, 0.192}
    # long-run average rate is the offered rate
    assert 256 / (offs[-1] + 64 / 1000.0) == pytest.approx(1000.0)
    with pytest.raises(ValueError):
        load_gen.arrival_offsets("nope", 10, 1.0)
    with pytest.raises(ValueError):
        load_gen.arrival_offsets("poisson", 10, 0.0)


def test_run_slo_measures_both_patterns():
    import load_gen

    res = load_gen.run_slo(
        n_events=192, rate_eps=4000.0, burst_len=32, max_batch=32,
        max_wait_ms=5.0, device_score_min=None,  # host-pinned: fast CPU
    )
    for pattern in ("poisson", "bursty"):
        r = res[pattern]
        assert r["resolved"] == 192 and r["errors"] == 0
        assert r["sustained_eps"] > 0
        for q in ("p50_ms", "p99_ms", "p999_ms"):
            assert r[q] is not None and r[q] > 0
        assert r["p50_ms"] <= r["p99_ms"] <= r["p999_ms"] <= r["max_ms"]


def test_bench_serving_slo_at_test_size():
    import bench

    res = bench.bench_serving_slo(n_events=96, rate_eps=4000.0,
                                  burst_len=32, max_batch=32,
                                  max_wait_ms=5.0,
                                  device_score_min=None)  # host: fast CPU
    assert "poisson" in res and "bursty" in res
    assert res["poisson"]["p999_ms"] is not None
    assert res["bursty"]["sustained_eps"] > 0


# ---------------------------------------------------------------------------
# batcher per-stage latency decomposition
# ---------------------------------------------------------------------------


def test_batch_records_decompose_latency(tmp_path):
    from oni_ml_tpu.config import ServingConfig
    from oni_ml_tpu.runner.serve import _synthetic_day
    from oni_ml_tpu.serving import (
        BatchScorer,
        DnsEventFeaturizer,
        MetricsEmitter,
        ModelRegistry,
    )

    rows, model, cuts = _synthetic_day()
    reg = ModelRegistry()
    reg.publish(model, source="t")
    metrics = MetricsEmitter(to_stdout=False)
    scorer = BatchScorer(
        reg, DnsEventFeaturizer(cuts),
        ServingConfig(max_batch=32, max_wait_ms=10.0,
                      device_score_min=None),
        metrics=metrics,
    )
    futs = [scorer.submit(r) for r in rows]
    for f in futs:
        f.result(timeout=30.0)
    scorer.close()
    batch_recs = [r for r in metrics.records if "latency_ms" in r]
    assert batch_recs
    for r in batch_recs:
        assert {"queue_wait_ms", "score_ms", "demux_ms"} <= set(r)
        # decomposition is consistent: stages sum to no more than the
        # end-to-end latency (+ scheduling slack)
        assert r["queue_wait_ms"] <= r["latency_ms"] + 1e-6
    snap = metrics.snapshot()
    for h in ("serve.latency_ms", "serve.queue_wait_ms",
              "serve.score_ms", "serve.demux_ms"):
        assert snap["histograms"][h]["count"] == len(batch_recs)
        assert snap["histograms"][h]["p999"] is not None
    # device_score_min=None pinned the host path: the device-only
    # histogram the serve roofline joins against must stay empty.
    assert "serve.device_score_ms" not in snap["histograms"]


def test_metrics_device_score_histogram_tracks_device_flushes_only():
    from oni_ml_tpu.serving import MetricsEmitter

    m = MetricsEmitter(to_stdout=False, recorder=Recorder())
    m.emit({"stage": "serve", "scorer": "host", "score_ms": 5.0,
            "events": 8})
    m.emit({"stage": "serve", "scorer": "device", "score_ms": 2.0,
            "events": 16})
    m.emit({"stage": "serve", "scorer": "device", "score_ms": 3.0,
            "events": 32})
    snap = m.snapshot()
    assert snap["histograms"]["serve.score_ms"]["count"] == 3
    dev = snap["histograms"]["serve.device_score_ms"]
    assert dev["count"] == 2 and dev["sum"] == pytest.approx(5.0)
    assert snap["counters"]["serve.device_events"] == 48


# ---------------------------------------------------------------------------
# heartbeat latency routing
# ---------------------------------------------------------------------------


def test_heartbeat_probe_latency_feeds_shared_histogram():
    rec = Recorder()
    answers = iter([0.001, 0.004, None, 0.002])
    hb = HeartbeatMonitor(
        interval_s=0.01, timeout_s=0.1, max_misses=5,
        probe=lambda t: next(answers), deep_probe=None, recorder=rec,
    )
    assert hb.beat_once() and hb.beat_once()
    assert not hb.beat_once()      # miss
    assert hb.beat_once()
    h = rec.histograms["heartbeat.probe_latency_s"]
    assert h.count == 3
    assert h.max == pytest.approx(0.004)
    assert rec.counters["heartbeat.misses"].value == 1
    # degradation is visible on the exporter plane before any loss
    text = render_openmetrics(rec)
    assert "heartbeat_probe_latency_s_count 3" in text


def test_heartbeat_binds_ambient_recorder():
    from oni_ml_tpu.telemetry import use_recorder

    rec = Recorder()
    with use_recorder(rec):
        hb = HeartbeatMonitor(interval_s=1.0, probe=lambda t: 0.001,
                              deep_probe=None)
    assert hb.recorder is rec
    hb.beat_once()
    assert rec.histograms["heartbeat.probe_latency_s"].count == 1


# ---------------------------------------------------------------------------
# trace_view utilization + liveness lanes
# ---------------------------------------------------------------------------


def test_trace_view_renders_roofline_and_heartbeat_counter_lanes(tmp_path):
    import trace_view

    path = str(tmp_path / "run_journal.jsonl")
    rj = RunJournal(Journal(path))
    rj.stage_begin("lda")
    rj.append({
        "kind": "roofline", "phase": "em.run_chunk", "wall_s": 1.0,
        "dispatches": 4, "flops_per_s": 5.47e12, "bytes_per_s": 25.5e9,
        "utilization": {"mxu_pct": 10.5, "hbm_pct": 3.1},
    })
    rj.append({
        "kind": "roofline", "phase": "score.device.filtered",
        "wall_s": 0.5, "dispatches": 2, "flops_per_s": 2e9,
        "utilization": None,
    })
    rj.heartbeat(True, latency_s=0.002)
    rj.stage_end("lda", ok=True, wall_s=2.0)
    rj.stage_begin("score")          # unfinished marker still works
    rj.close()

    records = trace_view.Journal.replay(path)
    trace = trace_view.journal_to_trace(records)
    evs = trace["traceEvents"]
    lanes = {e["name"]: e for e in evs if e["ph"] == "C"}
    assert lanes["roofline em.run_chunk"]["args"]["mxu_pct"] == 10.5
    assert lanes["roofline em.run_chunk"]["args"]["hbm_pct"] == 3.1
    # no utilization -> achieved-GFLOPs lane, not silence
    assert lanes["roofline score.device.filtered"]["args"][
        "gflops_per_s"] == pytest.approx(2.0)
    assert lanes["heartbeat latency_ms"]["args"]["latency_ms"] == \
        pytest.approx(2.0)
    assert any(e["name"] == "stage.score (unfinished)" for e in evs)
    json.dumps(trace)
    # summary prints the roofline section without raising
    import io

    buf = io.StringIO()
    trace_view.print_summary(records, 0, out=buf)
    out = buf.getvalue()
    assert "roofline" in out and "mxu_pct=10.5" in out


# ---------------------------------------------------------------------------
# pipeline + EM integration: instrumented runs journal rooflines
# ---------------------------------------------------------------------------


def test_scoring_pipeline_emits_roofline_under_recorder(tmp_path, day_model):
    from oni_ml_tpu.scoring.pipeline import filtered_scores
    from oni_ml_tpu.telemetry import use_recorder

    model = day_model
    rng = np.random.default_rng(5)
    n = 512
    ip = rng.integers(0, model.theta.shape[0], n).astype(np.int32)
    w = rng.integers(0, model.p.shape[0], n).astype(np.int32)
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    rec = Recorder(journal=j)
    with use_recorder(rec):
        filtered_scores(model, ip, w, 0.5, chunk=256)
    j.close()
    recs = [r for r in Journal.replay(path) if r.get("kind") == "roofline"]
    assert len(recs) == 1
    r = recs[0]
    assert r["phase"] == "score.device.filtered"
    assert r["dispatches"] == 2 and r["events"] == 512
    assert r["utilization"] is None          # CPU: no peaks
    # CPU cost analysis exists here; at minimum the record never raises
    assert r["cost_source"] in ("cost_analysis", "unavailable")


@pytest.fixture
def day_model():
    from oni_ml_tpu.runner.serve import _synthetic_day

    _, model, _ = _synthetic_day()
    return model


def test_fused_em_emits_roofline_under_recorder(tmp_path):
    from oni_ml_tpu.config import LDAConfig
    from oni_ml_tpu.io import Corpus
    from oni_ml_tpu.models.lda import train_corpus
    from oni_ml_tpu.telemetry import use_recorder

    rng = np.random.default_rng(0)
    ptr = [0]
    widx: list = []
    cnts: list = []
    for _ in range(48):
        n = int(rng.integers(3, 10))
        widx.extend(rng.integers(0, 50, n).tolist())
        cnts.extend(rng.integers(1, 4, n).tolist())
        ptr.append(len(widx))
    corpus = Corpus(
        doc_names=[f"ip{d}" for d in range(48)],
        vocab=[f"w{i}" for i in range(50)],
        doc_ptr=np.asarray(ptr, np.int64),
        word_idx=np.asarray(widx, np.int32),
        counts=np.asarray(cnts, np.int32),
    )
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    rec = Recorder(journal=j)
    cfg = LDAConfig(num_topics=4, em_max_iters=4, fused_em_chunk=2,
                    host_sync_every=2, batch_size=64)
    with use_recorder(rec):
        train_corpus(corpus, cfg)
    j.close()
    recs = [r for r in Journal.replay(path)
            if r.get("kind") == "roofline" and r["phase"] == "em.run_chunk"]
    assert len(recs) == 1
    assert recs[0]["dispatches"] >= 1
    assert recs[0]["wall_s"] > 0
    assert recs[0]["utilization"] is None    # CPU tier-1 degradation


def test_pipeline_run_journals_em_roofline_and_metrics_rollup(tmp_path):
    """The acceptance path on CPU tier-1: a journaled pipeline run's
    run_journal.jsonl carries the EM roofline record (wall-time-only /
    utilization null here), and metrics.json carries the run-level
    {"stage": "roofline"} rollup."""
    from test_features import flow_row

    from oni_ml_tpu.config import (
        FeedbackConfig,
        LDAConfig,
        PipelineConfig,
        ScoringConfig,
    )
    from oni_ml_tpu.runner import run_pipeline

    rng = np.random.default_rng(7)
    lines = ["dummy,header"]
    for _ in range(60):
        lines.append(flow_row(
            hour=int(rng.integers(0, 24)),
            minute=int(rng.integers(0, 60)),
            second=int(rng.integers(0, 60)),
            sip=f"10.0.0.{rng.integers(1, 9)}",
            dip=f"172.16.0.{rng.integers(1, 9)}",
            ipkt=str(rng.integers(1, 100)),
            ibyt=str(rng.integers(40, 10000)),
        ))
    raw = tmp_path / "flow.csv"
    raw.write_text("\n".join(lines) + "\n")
    cfg = PipelineConfig(
        data_dir=str(tmp_path), flow_path=str(raw),
        lda=LDAConfig(num_topics=4, em_max_iters=4, batch_size=32,
                      min_bucket_len=16, seed=3, fused_em_chunk=2,
                      host_sync_every=2),
        feedback=FeedbackConfig(dup_factor=5),
        scoring=ScoringConfig(threshold=1.1),
    )
    metrics = run_pipeline(cfg, "20160122", "flow")
    jpath = os.path.join(str(tmp_path), "20160122", "run_journal.jsonl")
    recs = [r for r in Journal.replay(jpath)
            if r.get("kind") == "roofline"]
    em = [r for r in recs if r["phase"] == "em.run_chunk"]
    assert em, recs
    assert em[0]["dispatches"] >= 1 and em[0]["wall_s"] > 0
    assert em[0]["utilization"] is None       # CPU: no peaks
    rollup = [m for m in metrics if m.get("stage") == "roofline"]
    assert rollup and any(
        r["phase"] == "em.run_chunk" for r in rollup[0]["records"]
    )


def test_serve_stream_openmetrics_endpoint_and_sink(tmp_path, capsys):
    """`ml_ops serve --metrics-port --openmetrics --journal` over a
    real (tiny) day dir: the live endpoint serves the serve histograms
    with quantiles, the file sink lands the same format, and the
    journal carries the serve.micro_batch roofline record."""
    import pickle
    import socket

    from oni_ml_tpu.runner import ml_ops
    from oni_ml_tpu.runner.serve import _synthetic_day
    from oni_ml_tpu.scoring import ScoringModel  # noqa: F401 (day build)

    rows, model, cuts = _synthetic_day()
    day = tmp_path / "day"
    day.mkdir()
    # Write the day-dir serving contract (`key,v1 v2 ... vK` rows):
    # results CSVs + features.pkl.
    with open(day / "doc_results.csv", "w") as f:
        for ip, th in zip(model.ip_index, model.theta[:-1]):
            f.write(ip + "," + " ".join(f"{v:.8f}" for v in th) + "\n")
    with open(day / "word_results.csv", "w") as f:
        for w, pr in zip(model.word_index, model.p[:-1]):
            f.write(w + "," + " ".join(f"{v:.8f}" for v in pr) + "\n")

    from oni_ml_tpu.features.dns import featurize_dns

    feats = featurize_dns(rows)
    with open(day / "features.pkl", "wb") as f:
        pickle.dump(feats, f)
    stream = day / "events.csv"
    with open(stream, "w") as f:
        for r in rows:
            f.write(",".join(r) + "\n")
    with socket.socket() as s:                   # a free ephemeral port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    om_path = str(tmp_path / "final.om")
    jpath = str(tmp_path / "serve_journal.jsonl")
    rc = ml_ops.main([
        "serve", "--day-dir", str(day), "--dsource", "dns",
        "--input", str(stream), "--max-batch", "16",
        "--max-wait-ms", "5", "--device-score-min", "100000",
        "--metrics-port", str(port), "--openmetrics", om_path,
        "--journal", jpath, "--no-plans", "--no-compilation-cache",
    ])
    capsys.readouterr()
    assert rc == 0
    with open(om_path) as f:
        text = f.read()
    assert text.endswith("# EOF\n")
    assert "serve_latency_ms_bucket" in text
    assert "serve_queue_wait_ms_count" in text
    assert "serve_events_total" in text
    recs = [r for r in Journal.replay(jpath)
            if r.get("kind") == "roofline"]
    assert recs and recs[0]["phase"] == "serve.micro_batch"
    assert recs[0]["dispatches"] >= 1
    # --device-score-min 100000 pinned every flush to the HOST scorer:
    # the record must be wall-time-only (path "host"), never the warmed
    # device program's cost multiplied by host flushes.
    assert recs[0]["path"] == "host"
    assert recs[0]["flops"] is None


# ---------------------------------------------------------------------------
# serving metrics snapshot quantiles (the satellite fix)
# ---------------------------------------------------------------------------


def test_metrics_snapshot_reports_true_quantiles():
    from oni_ml_tpu.serving import MetricsEmitter

    m = MetricsEmitter(to_stdout=False, recorder=Recorder())
    for i in range(1000):
        m.emit({"stage": "serve", "batch": i, "events": 1,
                "latency_ms": 1.0 + i})   # 1..1000 ms
    snap = m.snapshot()
    lat = snap["histograms"]["serve.latency_ms"]
    assert lat["count"] == 1000
    assert lat["p50"] == pytest.approx(500, rel=0.10)
    assert lat["p99"] == pytest.approx(990, rel=0.10)
    assert lat["p999"] == pytest.approx(999, rel=0.10)
    # JSON-line stream schema unchanged: records still verbatim dicts
    assert m.records[0] == {"stage": "serve", "batch": 0, "events": 1,
                            "latency_ms": 1.0}
