"""Replicated elastic serving (oni_ml_tpu/serving/placement.py +
replica.py + router.py, parallel/membership.py): consistent-hash
placement properties (determinism across processes, balance, minimal
movement, primary != shadow), the file-KV membership/heartbeat/fail
relay, the framed replica protocol, router score parity against the
single-process oracle, publish fan-out freshness, the kill-a-replica
chaos contract (zero failed futures, bit-identical survivor scores),
rolling drain/join redeploy, the route CLI dry-run, the load_gen
replicated harness + shed-path regression, and bench_diff's
replicated direction keys.  All CPU, no markers — the tier-1
replicated-serving smoke."""

import json
import math
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from oni_ml_tpu.config import ServingConfig
from oni_ml_tpu.parallel.membership import (
    FileKVClient,
    HeartbeatPublisher,
    MembershipClient,
    kv_list,
)
from oni_ml_tpu.runner.route import route_main
from oni_ml_tpu.runner.serve import _synthetic_day
from oni_ml_tpu.serving import (
    DnsEventFeaturizer,
    FleetRouter,
    ReplicaServer,
    TenantSpec,
    load_by_replica,
    moved_primaries,
    place,
    score_features,
    shadow_for,
)
from oni_ml_tpu.serving.placement import preference, stable_hash
from oni_ml_tpu.serving.replica import recv_frame, send_frame
from oni_ml_tpu.serving.router import ReplicaLink

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))


def _tenants(n):
    return [f"t{i}" for i in range(n)]


def _replicas(n):
    return [f"r{i}" for i in range(n)]


# ---------------------------------------------------------------------------
# placement properties
# ---------------------------------------------------------------------------


def test_placement_deterministic_and_input_order_invariant():
    p1 = place(_tenants(64), ["a", "b", "c"])
    p2 = place(list(reversed(_tenants(64))), ["c", "a", "b"])
    for t in _tenants(64):
        assert p1[t] == p2[t]
    # stable_hash is blake2b, not the per-process-salted builtin.
    assert stable_hash("place", "t0", "a") == stable_hash(
        "place", "t0", "a")


def test_placement_deterministic_across_processes(tmp_path):
    """The satellite pin: a DIFFERENT python process (fresh hash seed)
    computes the identical placement from the same census."""
    here = place(_tenants(32), _replicas(3))
    script = (
        "import json, sys\n"
        "sys.path.insert(0, %r)\n"
        "from oni_ml_tpu.serving import place\n"
        "p = place([f't{i}' for i in range(32)],\n"
        "          [f'r{i}' for i in range(3)])\n"
        "print(json.dumps({t: [v.primary, v.shadow]\n"
        "                  for t, v in p.items()}))\n"
    ) % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONHASHSEED="99")
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-500:]
    theirs = json.loads(out.stdout.strip().splitlines()[-1])
    assert theirs == {
        t: [v.primary, v.shadow] for t, v in here.items()
    }


def test_placement_balance_and_primary_shadow_invariants():
    for n_t, n_r in ((50, 2), (256, 4), (256, 6), (100, 3)):
        pl = place(_tenants(n_t), _replicas(n_r))
        cap = math.ceil(n_t / n_r)
        loads = load_by_replica(pl)
        assert max(loads.values()) <= cap
        assert set(loads) <= set(_replicas(n_r))
        for t, p in pl.items():
            assert p.shadow is not None
            assert p.shadow != p.primary
    # Single replica: no shadow possible, surfaced as None.
    pl = place(_tenants(8), ["only"])
    assert all(p.primary == "only" and p.shadow is None
               for p in pl.values())


def test_placement_minimal_movement_join_leave():
    """<= ceil(T/N) moved primaries across join/leave in the fleet
    regime (tenants-per-replica >= ~16 — the censuses the replicated
    benches run), and zero movement on a no-op recompute."""
    for n_t in (64, 256):
        tenants = _tenants(n_t)
        for n in (1, 2, 3, 4):
            if n_t / n < 16:
                continue
            old = place(tenants, _replicas(n))
            new = place(tenants, _replicas(n + 1))
            bound = math.ceil(n_t / n)
            joined = moved_primaries(old, new)
            assert len(joined) <= bound, (n_t, n, len(joined), bound)
            # leave == the same transition reversed.
            left = moved_primaries(new, old)
            assert len(left) <= bound
            # no-op recompute moves nothing.
            assert moved_primaries(old, place(tenants,
                                              _replicas(n))) == []


def test_placement_errors_and_shadow_for():
    with pytest.raises(ValueError, match="at least one replica"):
        place(_tenants(3), [])
    with pytest.raises(ValueError, match="duplicate tenant"):
        place(["a", "a"], _replicas(2))
    pref = preference("t3", _replicas(4))
    assert shadow_for("t3", _replicas(4)) == pref[0]
    assert shadow_for("t3", _replicas(4),
                      exclude={pref[0]}) == pref[1]
    assert shadow_for("t3", ["r0"], exclude={"r0"}) is None


# ---------------------------------------------------------------------------
# file KV + membership
# ---------------------------------------------------------------------------


def test_file_kv_client(tmp_path):
    kv = FileKVClient(str(tmp_path / "kv"))
    kv.key_value_set("a/b", "one")
    assert kv.blocking_key_value_get("a/b", 10) == "one"
    with pytest.raises(RuntimeError, match="ALREADY_EXISTS"):
        kv.key_value_set("a/b", "two")
    kv.key_value_set("a/b", "two", allow_overwrite=True)
    kv.key_value_set("a/c", "three")
    kv.key_value_set("z", "zed")
    assert kv_list(kv, "a/") == {"a/b": "two", "a/c": "three"}
    kv.key_value_delete("a/b")
    kv.key_value_delete("a/b")          # idempotent
    with pytest.raises(RuntimeError, match="DEADLINE_EXCEEDED"):
        kv.blocking_key_value_get("a/b", 30)
    # A blocked get is satisfied by a concurrent writer.
    got = {}

    def reader():
        got["v"] = kv.blocking_key_value_get("late", 5000)

    th = threading.Thread(target=reader)
    th.start()
    time.sleep(0.05)
    kv.key_value_set("late", "arrived")
    th.join(timeout=10)
    assert got["v"] == "arrived"


def test_membership_roster_heartbeats_fail_relay(tmp_path):
    kv = FileKVClient(str(tmp_path / "kv"))
    m = MembershipClient(kv, "oni/testfleet")
    m.register("r0", {"port": 1})
    m.register("r1", {"port": 2})
    assert set(m.members()) == {"r0", "r1"}
    assert m.members()["r1"]["meta"]["port"] == 2
    hb = HeartbeatPublisher(m, "r0", 0.03)
    try:
        time.sleep(0.12)
        beats = m.heartbeats()
        assert beats["r0"]["seq"] >= 2
        assert "r0" in m.alive(5.0)
        assert "r1" not in m.alive(5.0)     # never beat
    finally:
        hb.stop()
    m.fail("r0", "injected wedge")
    assert m.failures()["r0"]["reason"] == "injected wedge"
    m.clear_failure("r0")
    assert m.failures() == {}
    m.deregister("r0")
    assert set(m.members()) == {"r1"}


# ---------------------------------------------------------------------------
# frame protocol
# ---------------------------------------------------------------------------


def test_frame_roundtrip_and_oversize_guard():
    import socket as socket_mod

    a, b = socket_mod.socketpair()
    try:
        send_frame(a, {"op": "ping", "payload": list(range(100))})
        assert recv_frame(b)["payload"][-1] == 99
        # EOF mid-frame surfaces as ConnectionError, not a hang.
        a.close()
        with pytest.raises((ConnectionError, OSError)):
            recv_frame(b)
    finally:
        b.close()
    # An absurd announced length fails loudly before allocating.
    c, d = socket_mod.socketpair()
    try:
        import struct

        c.sendall(struct.pack("!I", (1 << 31) - 1))
        with pytest.raises(ConnectionError, match="oversized"):
            recv_frame(d)
    finally:
        c.close()
        d.close()


# ---------------------------------------------------------------------------
# replica + router end-to-end (in-process replicas, real sockets)
# ---------------------------------------------------------------------------


_CFG = ServingConfig(fleet_max_batch=32, fleet_max_wait_ms=5.0,
                     device_score_min=None)


@pytest.fixture()
def fleet3():
    """3 in-process replicas + router + 6 synthetic tenants, started;
    yields (router, replicas, days) and tears everything down."""
    replicas = {f"r{i}": ReplicaServer(f"r{i}", _CFG)
                for i in range(3)}
    router = FleetRouter(_CFG)
    days = {}
    try:
        for rid, rep in replicas.items():
            router.connect_replica(rid, rep.host, rep.port)
        for i in range(6):
            t = f"t{i}"
            days[t] = _synthetic_day(n_events=48, seed=200 + i)
            rows, model, cuts = days[t]
            router.add_tenant(TenantSpec(tenant=t, dsource="dns"),
                              cuts, model)
        router.start(warmup=False)
        yield router, replicas, days
    finally:
        router.close()
        for rep in replicas.values():
            rep.stop()


def _wait_failovers(router, timeout_s=15.0):
    """Failover completion (promotion + journal replay + shadow
    backfill) runs on a reader thread; poll stats() until the
    recovery record lands instead of racing it."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        fos = router.stats()["failovers"]
        if fos:
            return fos
        time.sleep(0.02)
    return router.stats()["failovers"]


def _oracle(days, t, rows):
    _, model, cuts = days[t]
    feats = DnsEventFeaturizer(cuts)(rows)
    return score_features(model, feats, "dns")


def test_router_score_parity_submit_and_submit_many(fleet3):
    """Routed scores — single submits AND chunked submit_many with
    batched responses — are bit-identical to the single-process
    oracle for every tenant."""
    router, replicas, days = fleet3
    futs = {}
    for t, (rows, _, _) in days.items():
        futs[t] = [router.submit(t, r) for r in rows[:20]]
        futs[t] += router.submit_many(t, rows[20:44])
    router.flush()
    for t, fs in futs.items():
        got = np.array([f.result(timeout=30.0)[0] for f in fs])
        np.testing.assert_array_equal(
            got, _oracle(days, t, days[t][0][:44]))
    # Every tenant is placed with a live shadow distinct from primary.
    for t, p in router.placement().items():
        assert p.shadow is not None and p.shadow != p.primary
    # Route edges priced: every replica edge saw its events.
    stats = router.stats()
    assert sum(e["events"] for e in stats["edges"].values()) \
        == sum(len(fs) for fs in futs.values())


def test_router_kill_replica_zero_failed_futures(fleet3):
    """THE chaos pin (acceptance criteria): kill a replica with
    events in flight — zero failed futures (the admission journal
    replays the victims onto promoted shadows), bit-identical scores
    for tenants on surviving replicas, and the promoted primary IS
    the old shadow (warm standby, not a re-placement)."""
    router, replicas, days = fleet3
    placement = router.placement()
    victim = placement["t0"].primary
    old = {t: placement[t] for t in days}
    futs = {t: [router.submit(t, r) for r in days[t][0][:30]]
            for t in days}
    replicas[victim].kill()
    router.flush()
    time.sleep(0.1)
    router.flush()
    for t, fs in futs.items():
        got = np.array([f.result(timeout=30.0)[0] for f in fs])
        np.testing.assert_array_equal(
            got, _oracle(days, t, days[t][0][:30]))
    new = router.placement()
    for t in days:
        if old[t].primary == victim:
            # shadow promotion, in place.
            assert new[t].primary == old[t].shadow
        else:
            # tenants that never touched the dead replica do not move.
            assert new[t].primary == old[t].primary
        assert new[t].primary != victim
        assert new[t].shadow != victim
        assert new[t].shadow != new[t].primary
    fos = _wait_failovers(router)
    assert len(fos) == 1
    assert fos[0]["resend_failures"] == 0
    assert fos[0]["recovery_s"] < 10.0
    # Post-failover traffic stays bit-identical on every tenant.
    futs2 = {t: router.submit_many(t, days[t][0][:12]) for t in days}
    router.flush()
    for t, fs in futs2.items():
        got = np.array([f.result(timeout=30.0)[0] for f in fs])
        np.testing.assert_array_equal(
            got, _oracle(days, t, days[t][0][:12]))


def test_router_publish_fanout_keeps_shadow_fresh(fleet3):
    """publish() fans out to primary AND shadow, so a post-publish
    failover serves the REFRESHED model — the shadow was never
    stale."""
    router, replicas, days = fleet3
    rows, model, cuts = days["t0"]
    rng = np.random.default_rng(11)
    k = model.num_topics
    ips = sorted(model.ip_index, key=model.ip_index.get)
    vocab = sorted(model.word_index, key=model.word_index.get)
    from oni_ml_tpu.scoring import ScoringModel

    model2 = ScoringModel.from_results(
        ips, rng.dirichlet(np.ones(k), size=len(ips)),
        vocab, rng.dirichlet(np.ones(len(vocab)), size=k).T,
        fallback=0.1,
    )
    version = router.publish("t0", model2)
    assert version == 2
    victim = router.placement()["t0"].primary
    replicas[victim].kill()
    time.sleep(0.1)
    futs = router.submit_many("t0", rows[:16])
    router.flush()
    got = np.array([f.result(timeout=30.0)[0] for f in futs])
    feats = DnsEventFeaturizer(cuts)(rows[:16])
    np.testing.assert_array_equal(
        got, score_features(model2, feats, "dns"))


def test_router_drain_join_rolling_redeploy(fleet3):
    """Drain-one-replica-at-a-time: routing flips to warm shadows
    (graceful), the drained replica reports a clean drain, a
    replacement joins with bounded movement, and traffic never
    breaks."""
    router, replicas, days = fleet3
    placement = router.placement()
    target = placement["t0"].primary
    futs = {t: router.submit_many(t, days[t][0][:16]) for t in days}
    res = router.drain_replica(target)
    assert res["drained"] is True
    for t, fs in futs.items():
        got = np.array([f.result(timeout=30.0)[0] for f in fs])
        np.testing.assert_array_equal(
            got, _oracle(days, t, days[t][0][:16]))
    after_drain = router.placement()
    assert all(p.primary != target and p.shadow != target
               for p in after_drain.values())
    assert target not in router.stats()["replicas"]
    # Respawn under a fresh id and join: minimal movement, and the
    # joined replica serves its share bit-identically.
    spare = ReplicaServer("r9", _CFG)
    try:
        joined = router.join_replica("r9", spare.host, spare.port,
                                     warmup=False)
        moved = moved_primaries(
            after_drain, router.placement())
        assert len(moved) == joined["moved"]
        assert joined["moved"] <= math.ceil(len(days) / 2)
        futs2 = {t: router.submit_many(t, days[t][0][:10])
                 for t in days}
        router.flush()
        for t, fs in futs2.items():
            got = np.array([f.result(timeout=30.0)[0] for f in fs])
            np.testing.assert_array_equal(
                got, _oracle(days, t, days[t][0][:10]))
    finally:
        spare.stop()


def test_router_drain_last_replica_refused(fleet3):
    router, replicas, days = fleet3
    live = router.stats()["replicas"]
    router.drain_replica(live[0])
    router.drain_replica(live[1])
    with pytest.raises(RuntimeError, match="last replica"):
        router.drain_replica(live[2])


def test_router_fail_key_triggers_monitor_failover(tmp_path):
    """The PR 11 relay, serving-side: a replica posting its fail key
    is failed over by the router's monitor without waiting for a
    connection EOF or heartbeat timeout."""
    cfg = ServingConfig(fleet_max_batch=32, fleet_max_wait_ms=5.0,
                        device_score_min=None,
                        replica_heartbeat_s=0.05)
    kv = FileKVClient(str(tmp_path / "kv"))
    replicas = {f"r{i}": ReplicaServer(f"r{i}", cfg, kv=kv)
                for i in range(2)}
    router = FleetRouter(cfg, kv=kv)
    try:
        for rid, rep in replicas.items():
            router.connect_replica(rid, rep.host, rep.port)
        rows, model, cuts = _synthetic_day(n_events=32, seed=400)
        for i in range(4):
            router.add_tenant(TenantSpec(tenant=f"t{i}",
                                         dsource="dns"), cuts, model)
        router.start(warmup=False)
        victim = router.placement()["t0"].primary
        MembershipClient(kv).fail(victim, "backend lost")
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if router.stats()["failovers"]:
                break
            time.sleep(0.02)
        fos = router.stats()["failovers"]
        assert fos and fos[0]["replica"] == victim
        assert victim not in router.stats()["replicas"]
        futs = router.submit_many("t0", rows[:8])
        router.flush()
        for f in futs:
            f.result(timeout=30.0)
        # Respawn under the SAME id and rejoin: connect clears the
        # stale fail key, so the monitor must not re-kill the healthy
        # replacement (review regression).
        replicas[victim].stop()
        respawn = ReplicaServer(victim, cfg, kv=kv)
        replicas[victim + "_v2"] = respawn
        router.join_replica(victim, respawn.host, respawn.port,
                            warmup=False)
        time.sleep(cfg.replica_heartbeat_s * 4)
        assert victim in router.stats()["replicas"]
        assert len(router.stats()["failovers"]) == len(fos)
        futs = router.submit_many("t0", rows[:6])
        router.flush()
        for f in futs:
            f.result(timeout=30.0)
    finally:
        router.close()
        for rep in replicas.values():
            rep.stop()


def test_router_admission_window_blocks_and_prices_stall():
    """route_max_inflight bounds outstanding events per edge; a
    saturating burst stalls at the window and the stall is priced
    into the edge stats (the Little's-law bound the scaling bench
    leans on)."""
    cfg = ServingConfig(fleet_max_batch=64, fleet_max_wait_ms=20.0,
                        device_score_min=None, route_max_inflight=8)
    rep = ReplicaServer("r0", cfg)
    router = FleetRouter(cfg)
    try:
        router.connect_replica("r0", rep.host, rep.port)
        rows, model, cuts = _synthetic_day(n_events=64, seed=500)
        router.add_tenant(TenantSpec(tenant="t0", dsource="dns"),
                          cuts, model)
        router.start(warmup=False)
        futs = [router.submit("t0", rows[i % len(rows)])
                for i in range(200)]
        router.flush()
        for f in futs:
            f.result(timeout=30.0)
        edge = router.stats()["edges"]["r0"]
        assert edge["events"] == 200
        assert edge["admission_stall_s"] > 0.0
    finally:
        router.close()
        rep.stop()


def test_replica_protocol_ops_direct(tmp_path):
    """Raw protocol against one replica: ping, idempotent add_tenant
    (router_version decides news), publish version bump, stats,
    drain."""
    rep = ReplicaServer("rx", _CFG)
    events = []
    link = ReplicaLink("rx", rep.host, rep.port, op_timeout_s=30.0,
                       on_score=lambda r, m: events.append(m),
                       on_down=lambda r, m: None)
    try:
        assert link.call({"op": "ping"})["ok"] is True
        rows, model, cuts = _synthetic_day(n_events=32, seed=600)
        req = {
            "op": "add_tenant",
            "spec": {"tenant": "ta", "dsource": "dns"},
            "cuts": cuts, "model": model, "router_version": 1,
        }
        assert link.call(dict(req))["published"] is True
        # Re-push at the same router version: no stack churn.
        rsp = link.call(dict(req))
        assert rsp["published"] is False
        assert rsp["version"] == 1
        rsp = link.call({"op": "publish", "tenant": "ta",
                         "model": model, "router_version": 2})
        assert rsp["version"] == 2
        link.send_submit(101, "ta", rows[0])
        link.call({"op": "flush"})
        deadline = time.monotonic() + 10.0
        while not events and time.monotonic() < deadline:
            time.sleep(0.01)
        assert events and events[0]["id"] == 101
        assert np.isfinite(events[0]["score"])
        stats = link.call({"op": "stats"})
        assert stats["tenants"] == ["ta"]
        assert stats["events_scored"] == 1
        assert link.call({"op": "drain"})["drained"] is True
        with pytest.raises(RuntimeError, match="unknown op"):
            link.call({"op": "nope"})
    finally:
        link.close()
        rep.stop()


def test_route_cli_dry_run_acceptance(capsys):
    """`ml_ops route --dry-run synthetic:4x3`: parity, mid-stream
    kill with zero dropped events, rolling redeploy — rc 0 and an ok
    summary."""
    rc = route_main(["--dry-run", "synthetic:4x3"])
    out = capsys.readouterr().out
    summary = json.loads(out.strip().splitlines()[-1])
    assert rc == 0
    assert summary["route_dry_run"] == "ok"
    assert summary["chaos_dropped"] == 0
    assert summary["failovers"]
    assert summary["redeploy"]["drained"]["drained"] is True


# ---------------------------------------------------------------------------
# load_gen: shed-path regression + replicated harness
# ---------------------------------------------------------------------------


def test_load_gen_shed_path_releases_collectors():
    """Regression (PR 15 satellite): a mid-replay AdmissionRejected in
    paged/reject mode must SHED the event — releasing the tenant's
    collector slot — not abort the run or leak the collector thread
    spinning on a slot no future will ever fill."""
    import load_gen

    before = threading.active_count()
    res = load_gen.run_fleet_slo(
        6, "poisson:1", n_events=600, rate_eps=20000.0, zipf_s=1.2,
        hot_tenants=2, warm_tenants=2, admission="reject",
        max_batch=64, max_wait_ms=20.0, device_score_min=None,
        tenant_queue_max=4,
    )
    agg = res["aggregate"]
    assert agg["shed"] > 0
    assert agg["errors"] == 0
    assert agg["shed"] + agg["resolved"] == res["n_events"]
    # Per-tenant shed accounting rides the payload.
    assert sum(v["shed"] for v in res["tenants"].values()) \
        == agg["shed"]
    # Collector threads joined — nothing left spinning.
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline \
            and threading.active_count() > before:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_load_gen_replicated_slo_harness():
    """The serving_slo_replicated harness at toy scale (thread-mode
    replicas): scaling legs with zero errors and zero in-window
    retraces, chaos leg with zero failed futures for victims AND
    survivors, bit-identical survivor scores, measured failover p999
    and recovery, and the payload keys bench_diff gates."""
    import load_gen

    res = load_gen.run_replicated_slo(
        replica_counts=(1, 2), n_tenants=8, zipf_s=1.1,
        events_per_replica=256, chaos_events=384,
        chaos_rate_eps=2500.0, spawn="thread",
        device_score_min=None, max_wait_ms=10.0, route_window=32,
        day_events=96,
    )
    assert res["replica_counts"] == [1, 2]
    for leg in res["scaling"].values():
        assert leg["errors"] == 0
        assert leg["sustained_eps"] > 0
    assert res["replica_scaling_efficiency"] is not None
    chaos = res["chaos"]
    assert chaos["errors_surviving"] == 0
    assert chaos["errors_victim_tenants"] == 0
    assert chaos["survivor_bit_identical"] is True
    assert chaos["failover_record"]["resend_failures"] == 0
    assert res["time_to_recovery_s"] >= 0
    assert res["failover_p999_ms"] is None \
        or res["failover_p999_ms"] > 0


def test_bench_diff_replicated_directions(tmp_path):
    """Direction gates for serving_slo_replicated: efficiency and
    per-count sustained eps higher-better; failover p999 and
    time-to-recovery lower-better."""
    import bench_diff

    base = {
        "metric": "serving_slo_replicated", "value": 10000,
        "unit": "events/sec",
        "secondary": {"serving_slo_replicated": {
            "value": 10000, "unit": "events/sec",
            "replica_scaling_efficiency": 0.95,
            "failover_p999_ms": 200.0,
            "time_to_recovery_s": 0.2,
            "sustained_eps_by_count": {"1": 2800, "2": 5400,
                                       "4": 10000},
        }},
    }

    def diff(**changes):
        import copy

        new = copy.deepcopy(base)
        new["secondary"]["serving_slo_replicated"].update(changes)
        old_p = tmp_path / "old.json"
        new_p = tmp_path / "new.json"
        old_p.write_text(json.dumps(base))
        new_p.write_text(json.dumps(new))
        return bench_diff.main([str(old_p), str(new_p)])

    assert diff() == 0
    assert diff(replica_scaling_efficiency=0.6) == 1
    assert diff(failover_p999_ms=400.0) == 1
    assert diff(time_to_recovery_s=0.5) == 1
    assert diff(time_to_recovery_s=0.05) == 0          # improvement
    assert diff(sustained_eps_by_count={"1": 2800, "2": 3000,
                                        "4": 10000}) == 1
    # Headline-form capture compares too.
    old_p = tmp_path / "ho.json"
    new_p = tmp_path / "hn.json"
    old_p.write_text(json.dumps(
        base["secondary"]["serving_slo_replicated"]))
    worse = dict(base["secondary"]["serving_slo_replicated"],
                 replica_scaling_efficiency=0.5)
    new_p.write_text(json.dumps(worse))
    assert bench_diff.main([str(old_p), str(new_p)]) == 1


def test_replica_subprocess_spawn_and_shutdown(tmp_path):
    """One REAL `ml_ops replica` subprocess: port-file handshake, KV
    registration + heartbeats, protocol round trip, clean shutdown
    over the wire (rc 0)."""
    from oni_ml_tpu.runner.route import _spawn_replica

    kv_dir = str(tmp_path / "kv")
    proc, host, port = _spawn_replica("rsub", kv_dir, str(tmp_path))
    link = None
    try:
        link = ReplicaLink("rsub", host, port, op_timeout_s=60.0,
                           on_score=lambda r, m: None,
                           on_down=lambda r, m: None)
        assert link.call({"op": "ping"})["ok"] is True
        m = MembershipClient(FileKVClient(kv_dir))
        assert "rsub" in m.members()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and "rsub" not in m.alive(5.0):
            time.sleep(0.05)
        assert "rsub" in m.alive(5.0)
        link.call({"op": "shutdown"})
        assert proc.wait(timeout=60) == 0
    finally:
        if link is not None:
            link.close()
        if proc.poll() is None:
            proc.kill()


def test_trace_view_route_lanes_and_summary():
    """route/membership/failover journal records render as counter
    lanes + instants, and the terminal summary prints the per-replica
    routing table with the failover tally."""
    import io

    import trace_view

    records = [
        {"kind": "route", "edge": "r0", "events": 1024,
         "bytes": 90000, "inflight": 12, "mono_ns": 1_000},
        {"kind": "membership", "event": "join", "replica": "r2",
         "moved": 10, "reshadowed": 4, "mono_ns": 2_000},
        {"kind": "failover", "replica": "r1", "reason": "conn lost",
         "promoted": 3, "reshadowed": 2, "inflight": 5,
         "mono_ns": 3_000},
        {"kind": "failover", "replica": "r1", "event": "recovered",
         "promoted": 3, "resent": 5, "resend_failures": 0,
         "recovery_s": 0.03, "mono_ns": 4_000},
        {"kind": "route", "edge": "r0", "event": "close",
         "events": 2048, "bytes": 180000, "errors": 0, "resends": 5,
         "admission_stall_s": 0.5, "mono_ns": 5_000},
    ]
    trace = trace_view.journal_to_trace(records)
    names = [e["name"] for e in trace["traceEvents"]]
    assert "route r0" in names
    assert any(n.startswith("fleet join") for n in names)
    assert "FAILOVER: r1" in names
    assert "FAILOVER recovered: r1" in names
    rows = trace_view.route_table(records)
    assert rows == [{"edge": "r0", "events": 2048, "bytes": 180000,
                     "resends": 5, "admission_stall_s": 0.5}]
    buf = io.StringIO()
    trace_view.print_summary(records, 0, out=buf)
    out = buf.getvalue()
    assert "replicated routing" in out
    assert "failover r1: 3 promoted, 5 in-flight replayed" in out


def test_router_journals_route_membership_failover(tmp_path, fleet3):
    """A journaled router run emits the three new record kinds with
    the schema's fields (the journal-schema lint pins the vocabulary;
    this pins the live emission path)."""
    from oni_ml_tpu.telemetry.journal import Journal

    router, replicas, days = fleet3
    path = tmp_path / "router_journal.jsonl"
    journal = Journal(str(path))
    router._journal = journal
    victim = router.placement()["t0"].primary
    futs = {t: router.submit_many(t, days[t][0][:8]) for t in days}
    replicas[victim].kill()
    router.flush()
    for fs in futs.values():
        for f in fs:
            f.result(timeout=30.0)
    assert _wait_failovers(router)
    router.close()
    journal.close()
    kinds = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            kinds.setdefault(rec["kind"], []).append(rec)
    assert "failover" in kinds
    assert any(r.get("event") == "recovered" for r in kinds["failover"])
    assert "route" in kinds
    assert any(r.get("event") == "close" for r in kinds["route"])


def test_dynamic_scorer_reapplies_plan_guard(tmp_path):
    """Review regression: a dynamic FleetScorer starts with zero lanes
    (guard unreachable) — add_tenant must re-apply the plan-flush
    degradation guard at the GROWN capacity: a plan max_batch above
    total admission capacity degrades to the default, and takes
    effect once capacity covers it."""
    from oni_ml_tpu import plans
    from oni_ml_tpu.plans import KNOBS, PlanStore, use_store
    from oni_ml_tpu.serving import FleetRegistry, FleetScorer

    st = PlanStore(str(tmp_path / "plans.jsonl"), seeds=False)
    fp = plans.fingerprint(KNOBS["fleet_max_batch"].scope)
    st.record("fleet_max_batch", fp, "*", 100, source="probe")
    rows, model, cuts = _synthetic_day(n_events=24, seed=700)
    with use_store(st):
        fleet = FleetRegistry()
        scorer = FleetScorer(fleet, {},
                             ServingConfig(device_score_min=None),
                             dynamic=True)
        try:
            for i in range(3):
                t = f"t{i}"
                fleet.add_tenant(TenantSpec(tenant=t, dsource="dns",
                                            queue_max=40))
                fleet.publish(t, model, source="test")
                scorer.add_tenant(
                    TenantSpec(tenant=t, dsource="dns", queue_max=40),
                    DnsEventFeaturizer(cuts))
                if (i + 1) * 40 < 100:
                    # Capacity 40/80 cannot reach a 100-event flush.
                    assert scorer.max_batch \
                        == ServingConfig.fleet_max_batch
                    assert scorer.plan["max_batch"]["source"] \
                        == "default"
                else:
                    # Capacity 120 covers the measured plan value.
                    assert scorer.max_batch == 100
                    assert scorer.plan["max_batch"]["source"] == "plan"
        finally:
            scorer.close()


def test_replica_wedge_posts_fail_key_and_stops_beating(tmp_path):
    """Review regression: a WEDGED replica (healthy process, broken
    scoring backend) must post the membership fail key and stop
    heartbeating — the router's monitor then promotes its shadows
    instead of trusting a liveness signal decoupled from scoring."""
    kv = FileKVClient(str(tmp_path / "kv"))
    cfg = ServingConfig(fleet_max_batch=32, fleet_max_wait_ms=5.0,
                        device_score_min=None,
                        replica_heartbeat_s=0.03)
    state = {"wedged": False}

    def health():
        if state["wedged"]:
            raise RuntimeError("backend lost (injected)")

    rep = ReplicaServer("rw", cfg, kv=kv, health_check=health)
    try:
        m = MembershipClient(kv)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and "rw" not in m.alive(5.0):
            time.sleep(0.02)
        assert "rw" in m.alive(5.0)
        assert m.failures() == {}
        state["wedged"] = True
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and "rw" not in m.failures():
            time.sleep(0.02)
        fail = m.failures()["rw"]
        assert "health check failed" in fail["reason"]
        # Heartbeats stopped: the silence corroborates the fail key.
        seq = m.heartbeats()["rw"]["seq"]
        time.sleep(0.2)
        assert m.heartbeats()["rw"]["seq"] == seq
    finally:
        rep.stop()
