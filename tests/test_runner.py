"""End-to-end pipeline tests: the runner reproduces ml_ops.sh's
stage sequence and file contract on a synthetic day, with per-stage
resume (SURVEY §5.3-5.4)."""

import json
import os

import numpy as np
import pytest

from oni_ml_tpu.config import (
    FeedbackConfig,
    LDAConfig,
    PipelineConfig,
    ScoringConfig,
)
from oni_ml_tpu.io import formats
from oni_ml_tpu.runner import Stage, run_pipeline

from test_features import dns_row, flow_row


def _stages(metrics):
    """Pipeline-stage names in order, without the run-level `plans` /
    `roofline` / `dataplane` accounting records run_pipeline appends
    after the stages."""
    return [m["stage"] for m in metrics
            if m["stage"] not in ("plans", "roofline", "dataplane")]


def test_dns_parquet_source(tmp_path):
    """Mixed CSV + parquet dns_path featurizes in listed order with
    comma-bearing parquet fields intact (the reference read Hive parquet,
    dns_pre_lda.scala:142)."""
    pytest.importorskip("pyarrow")
    import pyarrow as pa
    import pyarrow.parquet as pq

    from oni_ml_tpu.sources.builtin import _dns_sources

    n = 6
    table = pa.table({
        "frame_time": ["Mar 10, 2016 01:02:03"] * n,
        "unix_tstamp": list(range(1454000000, 1454000000 + n)),
        "frame_len": [60 + i for i in range(n)],
        "ip_dst": [f"10.2.0.{1 + i}" for i in range(n)],
        "dns_qry_name": [f"h{i}.svc.example.com" for i in range(n)],
        "dns_qry_class": ["1"] * n,
        "dns_qry_type": ["1"] * n,
        "dns_qry_rcode": ["0"] * n,
    })
    pq_path = tmp_path / "day.parquet"
    pq.write_table(table, pq_path)
    csv_path = tmp_path / "day.csv"
    csv_path.write_text(",".join(dns_row(ip="10.3.0.1")) + "\n")

    sources = _dns_sources(f"{pq_path},{csv_path}")
    assert isinstance(sources[0], list) and isinstance(sources[1], str)

    from oni_ml_tpu.features.native_dns import featurize_dns_sources

    feats = featurize_dns_sources(sources)
    assert feats.num_events == n + 1
    # Parquet rows come first (listed order), commas preserved.
    assert feats.rows[0][0] == "Mar 10, 2016 01:02:03"
    assert feats.client_ip(n) == "10.3.0.1"


@pytest.fixture()
def flow_day(tmp_path):
    rng = np.random.default_rng(7)
    lines = ["dummy,header"]
    for i in range(60):
        lines.append(
            flow_row(
                hour=int(rng.integers(0, 24)),
                minute=int(rng.integers(0, 60)),
                second=int(rng.integers(0, 60)),
                sip=f"10.0.0.{rng.integers(1, 9)}",
                dip=f"172.16.0.{rng.integers(1, 9)}",
                col10=str(rng.choice([80, 443, 55000, 0])),
                col11=str(rng.choice([80, 6000, 70000])),
                ipkt=str(rng.integers(1, 100)),
                ibyt=str(rng.integers(40, 10000)),
            )
        )
    raw = tmp_path / "flow.csv"
    raw.write_text("\n".join(lines) + "\n")
    cfg = PipelineConfig(
        data_dir=str(tmp_path),
        flow_path=str(raw),
        lda=LDAConfig(num_topics=4, em_max_iters=6, batch_size=32,
                      min_bucket_len=16, seed=3),
        feedback=FeedbackConfig(dup_factor=5),
        scoring=ScoringConfig(threshold=1.1),
    )
    return cfg, tmp_path


def test_flow_pipeline_end_to_end(flow_day):
    cfg, tmp_path = flow_day
    metrics = run_pipeline(cfg, "20160122", "flow")
    day = tmp_path / "20160122"
    for name in ["features.pkl", "word_counts.dat", "words.dat", "doc.dat",
                 "model.dat", "final.beta", "final.gamma", "final.other",
                 "likelihood.dat", "doc_results.csv", "word_results.csv",
                 "flow_results.csv", "metrics.json"]:
        assert (day / name).exists(), name
    # Stage metrics observable and complete.
    assert _stages(metrics) == ["pre", "corpus", "lda", "score"]
    # likelihood.dat: monotone non-decreasing likelihood.
    ll = formats.read_likelihood(str(day / "likelihood.dat"))
    assert ll.shape[1] == 2
    lls = ll[:, 0]
    assert all(b >= a - 1e-3 * abs(a) for a, b in zip(lls, lls[1:]))
    # threshold 1.1 > any probability -> every event flagged, ascending.
    results = (day / "flow_results.csv").read_text().splitlines()
    assert len(results) == 60
    mins = [min(float(r.split(",")[-2]), float(r.split(",")[-1])) for r in results]
    assert mins == sorted(mins)
    # final.other carries the centralized config (k, V, alpha).
    other = formats.read_other(str(day / "final.other"))
    assert other["num_topics"] == 4


def test_publish_delivers_day_dir(flow_day):
    """--publish: the completed day dir lands at DEST (the reference's
    final scp to the UI node, ml_ops.sh:118-121)."""
    cfg, tmp_path = flow_day
    dest = tmp_path / "ui_node"
    dest.mkdir()
    metrics = run_pipeline(cfg, "20160122", "flow", publish=str(dest))
    pub = [m for m in metrics if m.get("stage") == "publish"]
    assert len(pub) == 1 and pub[0]["transport"] == "copy"
    for name in ("flow_results.csv", "doc_results.csv", "final.beta",
                 "metrics.json"):
        assert (dest / "20160122" / name).exists(), name
    for name in ("flow_results.csv", "doc_results.csv", "final.beta"):
        src = (tmp_path / "20160122" / name).read_bytes()
        assert (dest / "20160122" / name).read_bytes() == src
    # delivered metrics cover all four stages; the local copy also
    # records the publish step afterwards
    import json as _json

    delivered = _json.loads((dest / "20160122" / "metrics.json").read_text())
    assert _stages(delivered) == ["pre", "corpus", "lda", "score"]
    local = _json.loads((tmp_path / "20160122" / "metrics.json").read_text())
    assert local[-1]["stage"] == "publish"
    # re-publish over an existing delivery is idempotent, not an error
    run_pipeline(cfg, "20160122", "flow", publish=str(dest))


def test_publish_remote_failure_raises(flow_day, monkeypatch):
    import subprocess

    from oni_ml_tpu.runner.ml_ops import publish_day

    calls = {}

    def fake_run(argv, capture_output, text):
        calls["argv"] = argv

        class R:
            returncode = 1
            stderr = "ssh: connect refused"

        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    import pytest

    with pytest.raises(RuntimeError, match="connect refused"):
        publish_day("/data/20160122", "uinode:/var/oni")
    assert calls["argv"] == ["scp", "-r", "/data/20160122", "uinode:/var/oni"]


def test_flow_pipeline_resume_skips_done_stages(flow_day):
    cfg, tmp_path = flow_day
    run_pipeline(cfg, "20160122", "flow")
    metrics2 = run_pipeline(cfg, "20160122", "flow")
    assert all(m.get("skipped") for m in metrics2
               if m["stage"] != "plans")
    # Forcing a single stage re-runs exactly that stage.
    metrics3 = run_pipeline(cfg, "20160122", "flow", force=True,
                            stages=[Stage.SCORE])
    assert _stages(metrics3) == ["score"]
    assert not metrics3[0].get("skipped")


def test_flow_pipeline_with_feedback(flow_day):
    cfg, tmp_path = flow_day
    header = ",".join(f"c{i}" for i in range(22))
    fb_row = ["3", "2016-01-22 10:00:00", "10.0.0.1", "172.16.0.1", "80",
              "55000", "TCP", ".AP.", "5", "500"] + ["x"] * 12
    (tmp_path / "flow_scores.csv").write_text(
        header + "\n" + ",".join(fb_row) + "\n"
    )
    metrics = run_pipeline(cfg, "20160123", "flow")
    pre = metrics[0]
    assert pre["feedback_rows"] == 5  # dup_factor
    assert pre["events"] == 65
    # Feedback duplicates train the model but are NOT scored: the results
    # hold exactly the 60 raw events.
    score = next(m for m in metrics if m["stage"] == "score")
    assert score["scored_events"] == 60
    results = (tmp_path / "20160123" / "flow_results.csv").read_text().splitlines()
    assert len(results) == 60


def test_dns_pipeline_end_to_end(tmp_path):
    rng = np.random.default_rng(11)
    names = ["mail.google.com", "x.intel.com", "a.b.evil-dga-q7.biz",
             "google.com", "4.3.2.1.in-addr.arpa"]
    rows = [
        ",".join(
            dns_row(
                tstamp=str(1454000000 + int(rng.integers(0, 86400))),
                flen=str(rng.integers(40, 500)),
                ip=f"10.0.1.{rng.integers(1, 6)}",
                qname=str(rng.choice(names)),
                qtype=str(rng.choice([1, 28])),
                rcode="0",
            )
        )
        for _ in range(50)
    ]
    raw = tmp_path / "dns.csv"
    raw.write_text("\n".join(rows) + "\n")
    top = tmp_path / "top-1m.csv"
    top.write_text("1,google.com\n2,intel.com\n")
    cfg = PipelineConfig(
        data_dir=str(tmp_path),
        dns_path=str(raw),
        top_domains_path=str(top),
        lda=LDAConfig(num_topics=3, em_max_iters=5, batch_size=16,
                      min_bucket_len=16, seed=5),
        scoring=ScoringConfig(threshold=1.1),
    )
    run_pipeline(cfg, "20160122", "dns")
    day = tmp_path / "20160122"
    results = (day / "dns_results.csv").read_text().splitlines()
    assert len(results) == 50
    scores = [float(r.split(",")[-1]) for r in results]
    assert scores == sorted(scores)
    # Sanity: every result row carries the word column and the scores are
    # real probabilities.
    assert all(0 <= s <= 1 for s in scores)
    metrics_path = json.loads((day / "metrics.json").read_text())
    assert _stages(metrics_path) == ["pre", "corpus", "lda", "score"]


def test_flow_pipeline_online_lda(flow_day):
    """--online swaps the batch EM engine for streaming SVI; every file
    contract downstream (final.*, results CSV ordering) is unchanged."""
    cfg, tmp_path = flow_day
    run_pipeline(cfg, "20160124", "flow", online=True)
    day = tmp_path / "20160124"
    for name in ["final.beta", "final.gamma", "final.other",
                 "flow_results.csv"]:
        assert (day / name).exists(), name
    gm = formats.read_gamma(str(day / "final.gamma"))
    assert (gm > 0).all()
    results = (day / "flow_results.csv").read_text().splitlines()
    assert len(results) == 60
    mins = [min(float(r.split(",")[-2]), float(r.split(",")[-1]))
            for r in results]
    assert mins == sorted(mins)


def test_runner_cli_smoke(flow_day, capsys):
    cfg, tmp_path = flow_day
    from oni_ml_tpu.runner.ml_ops import main

    rc = main([
        "20160122", "flow", "1.1",
        "--data-dir", str(tmp_path),
        "--flow-path", cfg.flow_path,
        "--topics", "4", "--em-max-iters", "3", "--batch-size", "32",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    records = [json.loads(l) for l in out]
    assert _stages(records) == ["pre", "corpus", "lda", "score"]
    assert (tmp_path / "20160122" / "flow_results.csv").exists()


def test_runner_profile_flag(flow_day, tmp_path_factory):
    cfg, tmp_path = flow_day
    from oni_ml_tpu.runner.ml_ops import main

    prof_dir = str(tmp_path_factory.mktemp("prof"))
    rc = main([
        "20160125", "flow", "1.1",
        "--data-dir", str(tmp_path), "--flow-path", cfg.flow_path,
        "--topics", "3", "--em-max-iters", "2", "--batch-size", "32",
        "--profile", prof_dir,
    ])
    assert rc == 0
    import os
    captured = [
        os.path.join(r, f) for r, _, fs in os.walk(prof_dir) for f in fs
    ]
    assert captured, "profiler produced no trace files"


def test_runner_rejects_bad_date():
    from oni_ml_tpu.runner.ml_ops import main

    with pytest.raises(SystemExit):
        main(["2016", "flow"])


def test_runner_perf_flags(flow_day, capsys):
    """--[no-]warm-start / --dense-precision must reach LDAConfig and the
    run must still produce the full stage sequence (on CPU the dense path
    is gated off, so these only steer config — the semantics knobs are
    exercised by tests/test_dense_estep.py)."""
    cfg, tmp_path = flow_day
    from oni_ml_tpu.runner.ml_ops import _build_config, build_parser, main

    args = build_parser().parse_args([
        "20160122", "flow", "1.1", "--dense-precision", "bf16",
    ])
    built = _build_config(args)
    assert built.lda.warm_start_gamma is True      # default on
    assert built.lda.dense_precision == "bf16"

    fresh = _build_config(build_parser().parse_args([
        "20160122", "flow", "1.1", "--no-warm-start",
    ]))
    assert fresh.lda.warm_start_gamma is False

    rc = main([
        "20160122", "flow", "1.1",
        "--data-dir", str(tmp_path), "--flow-path", cfg.flow_path,
        "--topics", "4", "--em-max-iters", "3", "--batch-size", "32",
        "--no-warm-start", "--dense-precision", "bf16", "--force",
    ])
    assert rc == 0


def test_eval_quality_flag_records_held_out_metrics(flow_day):
    cfg, tmp_path = flow_day
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    metrics = run_pipeline(cfg, "20160122", "flow", force=True,
                           eval_quality=True)
    lda = next(m for m in metrics if m["stage"] == "lda")
    assert np.isfinite(lda["completion_per_token_ll"])
    assert lda["completion_per_token_ll"] < 0
    assert lda["completion_perplexity"] > 1

    # Resumed run (lda stage skipped): the metric still appears,
    # computed from the saved final.beta/final.other.
    metrics2 = run_pipeline(cfg, "20160122", "flow", eval_quality=True)
    lda2 = next(m for m in metrics2 if m["stage"] == "lda")
    # The journal-driven resume (telemetry flight recorder) upgrades
    # the skip evidence when the prior run journaled its completion;
    # "outputs exist" remains the file-contract fallback.
    assert lda2.get("skipped") in (
        "journal: stage completed in a prior run", "outputs exist",
    )
    np.testing.assert_allclose(
        lda2["completion_per_token_ll"], lda["completion_per_token_ll"],
        rtol=1e-6,
    )


def test_pre_stage_spills_raw_lines(flow_day):
    """stage_pre streams raw rows to raw_lines.bin (native path):
    features.pkl must reference the spill file, not embed the bytes,
    and a vanished spill file must fail the score stage with a
    recoverable message (VERDICT r2 weak-item 2)."""
    import pickle

    from oni_ml_tpu.features import native_flow
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    cfg, tmp_path = flow_day
    run_pipeline(cfg, "20160122", "flow", force=True)
    day = tmp_path / "20160122"
    spill = day / "raw_lines.bin"
    assert spill.exists() and spill.stat().st_size > 0
    with open(day / "features.pkl", "rb") as f:
        feats = pickle.load(f)
    from oni_ml_tpu.features.blob import MmapBlob

    assert isinstance(feats.lines_blob, MmapBlob)
    # The pickle references the spill path; raw row bytes must not be
    # embedded (a distinctive slice of the spilled blob is absent).
    probe = spill.read_bytes()[:64]
    assert probe not in (day / "features.pkl").read_bytes()
    # Resume with the spill file gone: the score stage must say how to
    # recover instead of crashing deep in emit.
    (day / "flow_results.csv").unlink()
    spill.unlink()
    with pytest.raises(FileNotFoundError, match="re-run the pre stage"):
        run_pipeline(cfg, "20160122", "flow", stages=["score"])


def test_moved_day_dir_rescore(flow_day):
    """features.pkl records the spill path from pre time; a published/
    moved/renamed day dir must still re-score — stage_score re-resolves
    the spill beside features.pkl instead of trusting the stale
    absolute path (round-3 advisor finding: the stale path surfaced as
    a confusing FileNotFoundError)."""
    import dataclasses
    import shutil

    from oni_ml_tpu.features import native_flow
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    cfg, tmp_path = flow_day
    run_pipeline(cfg, "20160122", "flow", force=True)
    old_day = tmp_path / "20160122"
    results = (old_day / "flow_results.csv").read_bytes()

    # Move the whole data dir (publish/rename scenario): the recorded
    # spill path now points into a directory that no longer exists.
    new_root = tmp_path.parent / (tmp_path.name + "_moved")
    shutil.move(str(tmp_path), str(new_root))
    tmp_path.mkdir()  # keep the fixture's dir alive for pytest cleanup
    cfg2 = dataclasses.replace(cfg, data_dir=str(new_root))
    (new_root / "20160122" / "flow_results.csv").unlink()
    run_pipeline(cfg2, "20160122", "flow", stages=["score"])
    assert (new_root / "20160122" / "flow_results.csv").read_bytes() \
        == results


def test_moved_day_dir_stale_spill_refused(flow_day):
    """Re-resolution adopts a same-named spill ONLY when its size
    matches the one recorded at pre time: a stale raw_lines.bin left
    behind by an earlier interrupted run in a copied day dir would
    otherwise be silently scored against mismatched row offsets —
    wrong lines, not an error (round-4 advisor finding)."""
    import dataclasses
    import shutil

    from oni_ml_tpu.features import native_flow
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    cfg, tmp_path = flow_day
    run_pipeline(cfg, "20160122", "flow", force=True)
    new_root = tmp_path.parent / (tmp_path.name + "_moved2")
    shutil.move(str(tmp_path), str(new_root))
    tmp_path.mkdir()  # keep the fixture's dir alive for pytest cleanup
    day = new_root / "20160122"
    spill = day / "raw_lines.bin"
    spill.write_bytes(spill.read_bytes() + b"stale trailing garbage\n")
    (day / "flow_results.csv").unlink()
    cfg2 = dataclasses.replace(cfg, data_dir=str(new_root))
    with pytest.raises(FileNotFoundError, match="stale or partial"):
        run_pipeline(cfg2, "20160122", "flow", stages=["score"])


def test_partial_spill_at_recorded_path_refused(flow_day):
    """The size identity check guards the RECORDED path too, not only
    the post-move re-resolution: a pre re-run interrupted mid-ingest
    leaves a partial raw_lines.bin at the recorded path while the
    complete run's features.pkl survives — scoring would silently read
    wrong lines (round-5 review finding)."""
    from oni_ml_tpu.features import native_flow
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    if not native_flow.available():
        pytest.skip("native flow featurizer unavailable")
    cfg, tmp_path = flow_day
    run_pipeline(cfg, "20160122", "flow", force=True)
    day = tmp_path / "20160122"
    spill = day / "raw_lines.bin"
    spill.write_bytes(spill.read_bytes()[: spill.stat().st_size // 2])
    (day / "flow_results.csv").unlink()
    with pytest.raises(FileNotFoundError, match="stale or partial"):
        run_pipeline(cfg, "20160122", "flow", stages=["score"])


def test_eval_holdout_true_held_out_split(flow_day):
    """--eval-holdout: beta trains on the hash-split remainder, the
    excluded docs' per-token ll is recorded, and the file contract is
    intact — doc_results/final.gamma cover EVERY document (held-out
    thetas inferred under the trained beta)."""
    cfg, tmp_path = flow_day
    from oni_ml_tpu.models.evaluate import hash_split
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    metrics = run_pipeline(cfg, "20160122", "flow", force=True,
                           eval_holdout=0.3)
    lda = next(m for m in metrics if m["stage"] == "lda")
    assert 0 < lda["held_out_docs"]
    assert lda["held_out_frac"] == 0.3
    assert np.isfinite(lda["held_out_per_token_ll"])
    assert lda["held_out_per_token_ll"] < 0
    assert lda["held_out_perplexity"] > 1

    day = tmp_path / "20160122"
    doc_rows = (day / "doc_results.csv").read_text().splitlines()
    docs = formats.read_doc_dat(str(day / "doc.dat"))
    assert len(doc_rows) == len(docs)          # every doc has a theta row
    gamma = formats.read_gamma(str(day / "final.gamma"))
    assert gamma.shape[0] == len(docs)
    # Scoring runs against the full-contract model outputs.
    assert (day / "flow_results.csv").exists()

    # The split is deterministic by doc NAME: same fraction, same docs.
    t1, h1 = hash_split(docs, 0.3)
    t2, h2 = hash_split(list(reversed(docs)), 0.3)
    assert {docs[i] for i in h1} == {docs[len(docs) - 1 - i] for i in h2}
    assert lda["held_out_docs"] == len(h1)


def test_eval_holdout_rejects_online_and_bad_frac(flow_day):
    cfg, _ = flow_day
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    with pytest.raises(ValueError, match="batch-mode only"):
        run_pipeline(cfg, "20160124", "flow", force=True, online=True,
                     eval_holdout=0.2, stages=[Stage.LDA])
    with pytest.raises(ValueError, match="mutually exclusive"):
        run_pipeline(cfg, "20160124", "flow", force=True,
                     eval_quality=True, eval_holdout=0.2,
                     stages=[Stage.LDA])
    with pytest.raises(ValueError, match="in \\(0, 1\\)"):
        from oni_ml_tpu.models.evaluate import hash_split

        hash_split(["a", "b"], 1.5)


def test_dns_sources_expand_dir_and_glob(tmp_path):
    """dns_path accepts directories and globs like FLOW_PATH; empty
    expansions raise instead of producing an empty day."""
    import pytest

    from oni_ml_tpu.sources.builtin import _dns_sources

    d = tmp_path / "dns_parts"
    d.mkdir()
    for i in range(3):
        (d / f"part-{i}.csv").write_text(
            ",".join(dns_row(ip=f"10.3.0.{i}")) + "\n"
        )
    by_dir = _dns_sources(str(d))
    by_glob = _dns_sources(str(d / "part-*.csv"))
    by_list = _dns_sources(",".join(str(d / f"part-{i}.csv")
                                    for i in range(3)))
    assert by_dir == by_glob == by_list
    assert len(by_dir) == 3
    empty = tmp_path / "empty_dir_"
    empty.mkdir()
    with pytest.raises(OSError, match="no DNS input files"):
        _dns_sources(str(empty))
