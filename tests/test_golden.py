"""Frozen golden-day byte contract (VERDICT r1 item 6).

Recomputes every stage-boundary file from the committed inputs in
tests/golden/inputs/ and compares BYTES against the committed expected
files.  SURVEY.md §1: the reference's layer interfaces are files with
fixed formats — this is the pinned artifact that makes any contract
drift (featurization, first-seen id assignment, result formatting,
scoring emit) fail loudly instead of shipping silently.

To intentionally re-pin after a deliberate contract change, run
tests/golden/generate.py and review the diff.
"""

import os
import sys

import numpy as np
import pytest

from oni_ml_tpu.io import Corpus, formats
from oni_ml_tpu.scoring import ScoringModel, score_dns, score_flow

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")
INP = os.path.join(GOLDEN, "inputs")
sys.path.insert(0, GOLDEN)
# Scoring knobs and featurize recipes come from the generator itself so
# a re-pin with changed constants cannot desync generator and test.
from generate import (  # noqa: E402
    DNS_FALLBACK,
    DNS_TOL,
    FLOW_FALLBACK,
    FLOW_TOL,
    load_dns_feats,
    load_flow_feats,
)


def _read(p: str) -> bytes:
    with open(p, "rb") as f:
        return f.read()


def _expect(sub: str, name: str) -> bytes:
    return _read(os.path.join(GOLDEN, "expected", sub, name))


def _assert_file_matches(tmp_path, sub, name, writer) -> None:
    out = str(tmp_path / name)
    writer(out)
    assert _read(out) == _expect(sub, name), (
        f"{sub}/{name} drifted from the golden contract "
        "(tests/golden/generate.py re-pins after deliberate changes)"
    )


@pytest.fixture(scope="module")
def flow_feats():
    return load_flow_feats()


@pytest.fixture(scope="module")
def dns_feats():
    return load_dns_feats()


@pytest.mark.parametrize("sub", ["flow", "dns"])
def test_corpus_files_pinned(tmp_path, sub, flow_feats, dns_feats):
    feats = flow_feats if sub == "flow" else dns_feats
    _assert_file_matches(
        tmp_path, sub, "word_counts.dat",
        lambda p: formats.write_word_counts(p, feats.word_counts()),
    )
    corpus = Corpus.from_word_counts_file(
        os.path.join(GOLDEN, "expected", sub, "word_counts.dat")
    )
    corpus.save(str(tmp_path))
    for name in ("words.dat", "doc.dat", "model.dat"):
        assert _read(str(tmp_path / name)) == _expect(sub, name), name


@pytest.mark.parametrize("sub", ["flow", "dns"])
def test_result_formatting_pinned(tmp_path, sub):
    exp = os.path.join(GOLDEN, "expected", sub)
    corpus = Corpus.from_word_counts_file(
        os.path.join(exp, "word_counts.dat")
    )
    gamma = formats.read_gamma(os.path.join(exp, "final.gamma"))
    log_beta = formats.read_beta(os.path.join(exp, "final.beta"))
    norm = gamma / gamma.sum(-1, keepdims=True)
    _assert_file_matches(
        tmp_path, sub, "doc_results.csv",
        lambda p: formats.write_doc_results(p, corpus.doc_names, norm),
    )
    _assert_file_matches(
        tmp_path, sub, "word_results.csv",
        lambda p: formats.write_word_results(p, corpus.vocab, log_beta),
    )
    # beta/gamma writers roundtrip to identical bytes as well
    _assert_file_matches(
        tmp_path, sub, "final.gamma",
        lambda p: formats.write_gamma(p, gamma),
    )
    _assert_file_matches(
        tmp_path, sub, "final.beta",
        lambda p: formats.write_beta(p, log_beta),
    )


def test_flow_scoring_pinned(tmp_path, flow_feats):
    exp = os.path.join(GOLDEN, "expected", "flow")
    model = ScoringModel.from_files(
        os.path.join(exp, "doc_results.csv"),
        os.path.join(exp, "word_results.csv"),
        fallback=FLOW_FALLBACK,
    )
    rows, scores = score_flow(flow_feats, model, threshold=FLOW_TOL)
    got = ("\n".join(rows) + ("\n" if rows else "")).encode()
    assert got == _expect("flow", "flow_results.csv")
    # non-trivial fixture: keeps some events, drops others, ascending
    assert 0 < len(rows) < flow_feats.num_raw_events
    assert np.all(np.diff(scores) >= 0)


def test_dns_scoring_pinned(tmp_path, dns_feats):
    exp = os.path.join(GOLDEN, "expected", "dns")
    model = ScoringModel.from_files(
        os.path.join(exp, "doc_results.csv"),
        os.path.join(exp, "word_results.csv"),
        fallback=DNS_FALLBACK,
    )
    rows, scores = score_dns(dns_feats, model, threshold=DNS_TOL)
    got = ("\n".join(rows) + ("\n" if rows else "")).encode()
    assert got == _expect("dns", "dns_results.csv")
    assert 0 < len(rows) < dns_feats.num_raw_events
    assert np.all(np.diff(scores) >= 0)
