"""Real-chip smoke checks that the CPU-pinned test suite cannot reach.

Run ON the TPU host (with the device tunnel env intact):

    python tools/tpu_smoke.py

Checks (VERDICT r2 weak-item 3: "the shard_map'd dense kernel is never
Mosaic-compiled"):

1. (1,1)-mesh shard_map'd dense kernel, Mosaic-compiled (interpret=False)
   — the exact `shard_map` + Pallas + `check_vma=False` combination the
   multi-chip trainer uses (parallel/sharded.py), which off-TPU only ever
   runs interpreted.  Asserts equality with the unwrapped kernel on the
   same chip.
2. Same under the W-major layout (the production default).

Exit 0 = all green.  Exits 2 with a message when no TPU is attached (the
CPU fallback would silently re-run the interpret path the test suite
already covers).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_checks() -> dict:
    """Mosaic-under-shard_map equality on the attached TPU.  Raises on
    mismatch/compile failure; returns a result payload on success.
    Importable so bench.py's `mosaic_smoke` phase and the TPU-gated
    pytest carry the same assertion (VERDICT r3 weak-item 3: the check
    existed but left no durable artifact)."""
    import jax
    import jax.numpy as jnp

    from oni_ml_tpu.ops import dense_estep
    from oni_ml_tpu.parallel import make_mesh
    from oni_ml_tpu.parallel.sharded import make_data_parallel_dense_e_step

    rng = np.random.default_rng(0)
    k, v, b, l = 20, 1024, 256, 64
    noise = rng.uniform(size=(k, v)) + 1.0 / v
    log_beta = jnp.asarray(
        np.log(noise / noise.sum(-1, keepdims=True)), jnp.float32
    )
    word_idx = jnp.asarray(rng.integers(0, v, size=(b, l)), jnp.int32)
    counts = jnp.asarray(rng.integers(1, 5, size=(b, l)), jnp.float32)
    doc_mask = jnp.ones((b,), jnp.float32)
    kw = dict(var_max_iters=20, var_tol=1e-6)

    lls = {}
    mesh = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    for wmajor in (False, True):
        dense = jax.jit(
            lambda w, c: dense_estep.densify(w, c, v)
        )(word_idx, counts)
        if wmajor:
            dense = jnp.transpose(dense)
        plain = dense_estep.e_step_dense(
            log_beta, jnp.float32(2.5), dense, doc_mask,
            wmajor=wmajor, **kw
        )
        fn = make_data_parallel_dense_e_step(mesh, wmajor=wmajor)
        zeros_g = jnp.zeros((b, k), jnp.float32)
        sharded = jax.jit(
            lambda lb, a, d, m, g, w: fn(lb, a, d, m, g, w, **kw)
        )(log_beta, jnp.float32(2.5), dense, doc_mask, zeros_g,
          jnp.asarray(0, jnp.int32))
        np.testing.assert_allclose(
            np.asarray(sharded.gamma), np.asarray(plain.gamma),
            rtol=1e-6, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(sharded.suff_stats), np.asarray(plain.suff_stats),
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            float(sharded.likelihood), float(plain.likelihood), rtol=1e-6
        )
        lls[f"wmajor={wmajor}"] = round(float(sharded.likelihood), 3)
    return {
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "likelihoods": lls,
    }


def main() -> int:
    import jax

    if jax.default_backend() not in ("tpu", "axon"):
        print(
            f"tpu_smoke: backend is {jax.default_backend()!r}, not a TPU — "
            "nothing to check (the interpret path is covered by tests/)",
            file=sys.stderr,
        )
        return 2

    res = run_checks()
    for name, ll in res["likelihoods"].items():
        print(
            f"tpu_smoke: shard_map dense kernel ({name}) Mosaic-compiled "
            f"OK on {res['device_kind']}; ll={ll:.3f}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
