"""Scoring-dispatch chunk sweep — the scoring twin of the r05 EM
chunk sweep (tools/tpu_probes.py chunk_sweep), so the next live grant
can tune `ScoringConfig.device_chunk` in one command:

    python tools/score_probe.py [n_events] [chunk [chunk ...]]

(defaults: 400k events — the bench day size — over chunks 8k..256k).
Each measurement prints one JSON line: events/sec through the fused
flow filter pipeline (scoring/pipeline.py filtered_flow_scores — two
gathers + dot + min + threshold + compaction per chunk, double-buffered
dispatch) at a threshold keeping ~half the events, plus the pipeline's
own dispatch/transfer accounting so the record shows WHAT moved, not
just how fast.  A final line reports the measured host-vs-device
break-even (scoring.dispatch_calibration) — the constant the serving
dispatch runs under on this backend.

The per-dispatch glue model from the r05 EM sweep (~65 ms/dispatch
through the tunneled backend) predicts the same hyperbola here:
t(chunk) ≈ n/chunk · glue + n · per_event — the sweep's flat point is
the chunk where glue is amortized, and that is what device_chunk
should be set to.  Runs on any backend (CPU numbers exercise the
machinery; only TPU numbers should retune the default — the record
carries the backend so they cannot be confused)."""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_CHUNKS = (8192, 16384, 32768, 65536, 131072, 262144)


def sweep(n_events: int, chunks, reps: int = 3) -> None:
    import jax

    from oni_ml_tpu.scoring import (
        DispatchStats,
        ScoringModel,
        dispatch_calibration,
        filtered_flow_scores,
    )
    from oni_ml_tpu.scoring.score import _batched_scores

    backend = jax.default_backend()
    rng = np.random.default_rng(7)
    k, n_ips, n_words = 20, 40_000, 8_000
    model = ScoringModel(
        ip_index={}, theta=rng.random((n_ips + 1, k)),
        word_index={}, p=rng.random((n_words + 1, k)),
    )
    sa, da = (rng.integers(0, n_ips, n_events).astype(np.int32)
              for _ in range(2))
    sw, dw = (rng.integers(0, n_words, n_events).astype(np.int32)
              for _ in range(2))
    # ~half the events survive: representative of a real TOL without
    # depending on the synthetic score distribution (bench convention).
    mn = np.minimum(
        _batched_scores(model, sa, sw), _batched_scores(model, da, dw)
    )
    threshold = float(np.median(mn))

    measurements = {}
    for chunk in chunks:
        # Warm the compiled program for this chunk outside the timing.
        filtered_flow_scores(model, sa, sw, da, dw, threshold, chunk=chunk)
        best, stats = float("inf"), None
        for _ in range(reps):
            st = DispatchStats()
            t0 = time.perf_counter()
            out = filtered_flow_scores(
                model, sa, sw, da, dw, threshold, chunk=chunk, stats=st
            )
            dt = time.perf_counter() - t0
            if dt < best:
                best, stats = dt, st
        assert len(out[0])
        measurements[chunk] = round(n_events / best)
        print(json.dumps({
            "probe": "score_chunk_sweep", "backend": backend,
            "chunk": chunk, "n_events": n_events,
            "events_per_sec": round(n_events / best),
            "p50_ms": round(best * 1e3, 2),
            "dispatches": stats.dispatches,
            "h2d_mb": round(stats.h2d_bytes / 1e6, 2),
            "d2h_mb": round(stats.d2h_bytes / 1e6, 2),
            "survivors": stats.survivors,
        }), flush=True)

    # The sweep's winner seeds the plan cache directly (oni_ml_tpu/
    # plans knob "score_device_chunk", keyed by this backend's
    # fingerprint): the next pipeline/serving run on this backend loads
    # the measured chunk instead of the shipped default, and
    # `tools/plan_cache.py export` turns the session into a committable
    # seed file.  Only TPU measurements should retune production — but
    # the cache is backend-keyed, so a CPU record can never leak onto a
    # chip.
    from oni_ml_tpu import plans

    best_chunk = max(measurements, key=measurements.get)
    plans.note_sweep("score_device_chunk")
    recorded = plans.record_value(
        "score_device_chunk", int(best_chunk), source="probe",
        measurements=measurements, unit="events/sec",
        n_events=n_events,
    )
    # dispatch_calibration(force=True) re-measures AND re-records its
    # own plan entry (scoring/score.py).
    print(json.dumps({
        "probe": "score_dispatch_calibration", "backend": backend,
        **dispatch_calibration(force=True),
    }), flush=True)
    print(json.dumps({
        "probe": "plan_cache_update",
        "recorded": recorded,        # False: plans disabled/unwritable
        "store": plans.default_path(),
        "backend": plans.device_fingerprint(),
        "score_device_chunk": int(best_chunk),
        "knobs_recorded": ["score_device_chunk", "dispatch_calibration"],
    }), flush=True)


def main() -> int:
    args = [int(a) for a in sys.argv[1:]]
    n_events = args[0] if args else 400_000
    chunks = tuple(args[1:]) or DEFAULT_CHUNKS
    sweep(n_events, chunks)
    return 0


if __name__ == "__main__":
    sys.exit(main())
