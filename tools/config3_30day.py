"""Config-3-at-spec demonstration: a 30-day corpus through one pre pass.

BASELINE.json config 3 is "flow LDA at scale: 30-day corpus, 50 topics,
full IP-pair vocabulary" — the shape the reference ran on a Spark
cluster (dns_pre_lda.scala:1-2 notes the cluster-scale pre stage;
SURVEY §2.2).  Round 3 demonstrated 3 days / 6M events; this tool runs
the full 30 days on one host and records the evidence the extrapolation
in docs/performance.md was standing in for:

    python tools/config3_30day.py [--events-per-day 5000000] [--days 30]
                                  [--keep] [--out JSON_PATH]

Writes 30 synthetic day files (bench._write_flow_day schema, distinct
seeds so IPs/words overlap across days the way real traffic does), runs
the runner's pre stage over the glob with the ingest-time spill, builds
the Corpus, and prints one JSON line: events, raw input bytes, peak RSS
(ru_maxrss), per-stage walls, docs/vocab of the resulting corpus.  RSS
staying a small multiple of the numeric arrays — NOT of the raw bytes —
is the claim under test (features/blob.py).

CPU-only by design: the pre stage is host code; config 3's training
number is bench.py's `lda_em_throughput_k50_v50k` phase on the chip.

Round-5 realistic-cardinality mode (VERDICT r4 item 3 — the round-4
capture proved volume, not cardinality: 150M events but only 6,000
docs / 7,127 vocab):

    python tools/config3_30day.py --ip-zipf-a 1.2 \
        --n-src 350000 --n-dst 175000 --n-svc-ports 48 --train

draws IPs from a power-law population (num_docs scales with active
IPs: >=500k documents over 30 days), widens the service-port mix
(vocab ~50k realized words), and — with --train — runs the runner's
LDA stage at K=50 over the resulting corpus, recording em_iters,
final likelihood, and the training wall alongside the pre/corpus
walls and RSS.
"""

import argparse
import json
import os
import resource
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--events-per-day", type=int, default=5_000_000)
    ap.add_argument("--days", type=int, default=30)
    ap.add_argument("--out", default=None,
                    help="also write the JSON record here")
    ap.add_argument("--keep", action="store_true",
                    help="keep the workdir (implied by --workdir)")
    ap.add_argument("--workdir", default=None,
                    help="run in this directory instead of a fresh "
                         "tempdir; NEVER deleted (the tool only "
                         "auto-deletes directories it created)")
    # Realistic-cardinality mode (VERDICT r4 item 3): a power-law IP
    # population makes num_docs scale with active IPs (the reference's
    # two-documents-per-event mapping, flow_pre_lda.scala:366-380)
    # instead of the round-3/4 fixed 6k-host pool, and a diverse
    # service-port mix scales the realized vocabulary.
    ap.add_argument("--n-src", type=int, default=4000)
    ap.add_argument("--n-dst", type=int, default=2000)
    ap.add_argument("--ip-zipf-a", type=float, default=None,
                    help="draw IPs from a rank^-a power law over the "
                         "n-src/n-dst populations (default: uniform, "
                         "round-4 behavior)")
    ap.add_argument("--n-svc-ports", type=int, default=None,
                    help="distinct low service ports (<=1024, power-"
                         "law popularity; default: the fixed 6-service "
                         "mix)")
    ap.add_argument("--train", action="store_true",
                    help="after the corpus build, run the runner's LDA "
                         "stage (K=--num-topics) and record em_iters / "
                         "final likelihood / wall")
    ap.add_argument("--num-topics", type=int, default=50)
    ap.add_argument("--em-max-iters", type=int, default=100)
    ap.add_argument("--batch-size", type=int, default=2048)
    args = ap.parse_args()
    if args.workdir:
        args.keep = True

    # CPU-only by design (see module docstring): pin the platform
    # BEFORE any backend init.  The session env points JAX at the TPU
    # tunnel — with the axon hook bypassed the plugin is unregistered
    # and the train stage crashes on backend init; with it active, a
    # dead relay hangs every JAX op.  Both knobs, like tests/conftest:
    # the env var alone does not stick when the hook already ran.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import bench
    from oni_ml_tpu.config import (
        FeedbackConfig, LDAConfig, PipelineConfig, ScoringConfig,
    )
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    work = args.workdir or tempfile.mkdtemp(prefix="oni_config3_")
    os.makedirs(work, exist_ok=True)
    rec = {"metric": "config3_30day_pre", "days": args.days,
           "events_per_day": args.events_per_day}
    try:
        # -- generate ----------------------------------------------------
        t0 = time.perf_counter()
        raw_bytes = 0
        day_files = []
        for d in range(args.days):
            path = os.path.join(work, f"flow_201601{d + 1:02d}.csv")
            with open(path, "w") as f:
                # Distinct seed per day; shared address/port space so
                # vocabulary and documents accumulate sub-linearly
                # across days (real traffic: same hosts, same services).
                bench._write_flow_day(
                    f, args.events_per_day, n_src=args.n_src,
                    n_dst=args.n_dst, seed=100 + d,
                    ip_zipf_a=args.ip_zipf_a,
                    n_svc_ports=args.n_svc_ports,
                )
            raw_bytes += os.path.getsize(path)
            day_files.append(path)
            print(f"config3: day {d + 1}/{args.days} written "
                  f"({raw_bytes / 1e9:.1f} GB total)", file=sys.stderr)
        rec["gen_wall_s"] = round(time.perf_counter() - t0, 1)
        rec["raw_gb"] = round(raw_bytes / 1e9, 2)

        # -- pre stage over the 30-file glob (ingest-time spill) ---------
        cfg = PipelineConfig(
            data_dir=work,
            flow_path=os.path.join(work, "flow_201601*.csv"),
            lda=LDAConfig(num_topics=args.num_topics,
                          em_max_iters=args.em_max_iters,
                          batch_size=args.batch_size),
            feedback=FeedbackConfig(),
            scoring=ScoringConfig(),
        )
        rec["ip_zipf_a"] = args.ip_zipf_a
        rec["n_src"], rec["n_dst"] = args.n_src, args.n_dst
        rec["n_svc_ports"] = args.n_svc_ports
        rss0_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        t1 = time.perf_counter()
        metrics = run_pipeline(cfg, "20160131", "flow", force=True,
                               stages=["pre"])
        rec["pre_wall_s"] = round(time.perf_counter() - t1, 1)
        pre = next(m for m in metrics if m.get("stage") == "pre")
        rec["events"] = pre["events"]
        rec["word_count_rows"] = pre["word_count_rows"]

        # -- corpus build (runner stage: first-seen id assignment AND
        # the words.dat/doc.dat/model.dat writes the LDA stage needs) --
        day_dir = os.path.join(work, "20160131")
        t2 = time.perf_counter()
        metrics = run_pipeline(cfg, "20160131", "flow", force=True,
                               stages=["corpus"])
        rec["corpus_wall_s"] = round(time.perf_counter() - t2, 1)
        cm = next(m for m in metrics if m.get("stage") == "corpus")
        rec["num_docs"] = cm["docs"]
        rec["vocab_size"] = cm["vocab"]
        rec["num_tokens"] = cm["tokens"]

        # -- K=num_topics training at this document cardinality --------
        if args.train:
            t3 = time.perf_counter()
            metrics = run_pipeline(cfg, "20160131", "flow", force=True,
                                   stages=["lda"])
            rec["train_wall_s"] = round(time.perf_counter() - t3, 1)
            lm = next(m for m in metrics if m.get("stage") == "lda")
            rec["num_topics"] = args.num_topics
            rec["em_iters"] = lm["em_iters"]
            rec["final_likelihood"] = lm["final_likelihood"]
            ll_path = os.path.join(day_dir, "likelihood.dat")
            if os.path.exists(ll_path):
                with open(ll_path) as f:
                    ll_lines = f.read().strip().splitlines()
                rec["likelihood_rows"] = len(ll_lines)
                rec["likelihood_last"] = ll_lines[-1] if ll_lines else None
                if args.out:
                    # The trajectory file IS the training evidence —
                    # keep it beside the record (the workdir is
                    # deleted unless --keep).
                    shutil.copyfile(
                        ll_path,
                        os.path.splitext(args.out)[0] + "_likelihood.dat",
                    )

        # ru_maxrss is KiB on Linux: binary factor, not decimal
        # (round-4 review finding: /1e6 understated the GB by 2.4%).
        peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        rec["peak_rss_gb"] = round(peak_kb * 1024 / 1e9, 2)
        rec["baseline_rss_gb"] = round(rss0_kb * 1024 / 1e9, 2)
        rec["rss_over_raw"] = round((peak_kb * 1024) / raw_bytes, 3)
        spill = os.path.join(day_dir, "raw_lines.bin")
        rec["spill_gb"] = round(os.path.getsize(spill) / 1e9, 2) \
            if os.path.exists(spill) else None
    finally:
        if not args.keep:
            shutil.rmtree(work, ignore_errors=True)

    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
