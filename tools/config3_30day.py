"""Config-3-at-spec demonstration: a 30-day corpus through one pre pass.

BASELINE.json config 3 is "flow LDA at scale: 30-day corpus, 50 topics,
full IP-pair vocabulary" — the shape the reference ran on a Spark
cluster (dns_pre_lda.scala:1-2 notes the cluster-scale pre stage;
SURVEY §2.2).  Round 3 demonstrated 3 days / 6M events; this tool runs
the full 30 days on one host and records the evidence the extrapolation
in docs/performance.md was standing in for:

    python tools/config3_30day.py [--events-per-day 5000000] [--days 30]
                                  [--keep] [--out JSON_PATH]

Writes 30 synthetic day files (bench._write_flow_day schema, distinct
seeds so IPs/words overlap across days the way real traffic does), runs
the runner's pre stage over the glob with the ingest-time spill, builds
the Corpus, and prints one JSON line: events, raw input bytes, peak RSS
(ru_maxrss), per-stage walls, docs/vocab of the resulting corpus.  RSS
staying a small multiple of the numeric arrays — NOT of the raw bytes —
is the claim under test (features/blob.py).

CPU-only by design: the pre stage is host code; config 3's training
number is bench.py's `lda_em_throughput_k50_v50k` phase on the chip.
"""

import argparse
import json
import os
import resource
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--events-per-day", type=int, default=5_000_000)
    ap.add_argument("--days", type=int, default=30)
    ap.add_argument("--out", default=None,
                    help="also write the JSON record here")
    ap.add_argument("--keep", action="store_true",
                    help="keep the workdir (implied by --workdir)")
    ap.add_argument("--workdir", default=None,
                    help="run in this directory instead of a fresh "
                         "tempdir; NEVER deleted (the tool only "
                         "auto-deletes directories it created)")
    args = ap.parse_args()
    if args.workdir:
        args.keep = True

    import bench
    from oni_ml_tpu.config import (
        FeedbackConfig, LDAConfig, PipelineConfig, ScoringConfig,
    )
    from oni_ml_tpu.io.corpus import Corpus
    from oni_ml_tpu.runner.ml_ops import run_pipeline

    work = args.workdir or tempfile.mkdtemp(prefix="oni_config3_")
    os.makedirs(work, exist_ok=True)
    rec = {"metric": "config3_30day_pre", "days": args.days,
           "events_per_day": args.events_per_day}
    try:
        # -- generate ----------------------------------------------------
        t0 = time.perf_counter()
        raw_bytes = 0
        day_files = []
        for d in range(args.days):
            path = os.path.join(work, f"flow_201601{d + 1:02d}.csv")
            with open(path, "w") as f:
                # Distinct seed per day; shared address/port space so
                # vocabulary and documents accumulate sub-linearly
                # across days (real traffic: same hosts, same services).
                bench._write_flow_day(
                    f, args.events_per_day, n_src=4000, n_dst=2000,
                    seed=100 + d,
                )
            raw_bytes += os.path.getsize(path)
            day_files.append(path)
            print(f"config3: day {d + 1}/{args.days} written "
                  f"({raw_bytes / 1e9:.1f} GB total)", file=sys.stderr)
        rec["gen_wall_s"] = round(time.perf_counter() - t0, 1)
        rec["raw_gb"] = round(raw_bytes / 1e9, 2)

        # -- pre stage over the 30-file glob (ingest-time spill) ---------
        cfg = PipelineConfig(
            data_dir=work,
            flow_path=os.path.join(work, "flow_201601*.csv"),
            lda=LDAConfig(num_topics=50),
            feedback=FeedbackConfig(),
            scoring=ScoringConfig(),
        )
        rss0_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        t1 = time.perf_counter()
        metrics = run_pipeline(cfg, "20160131", "flow", force=True,
                               stages=["pre"])
        rec["pre_wall_s"] = round(time.perf_counter() - t1, 1)
        pre = next(m for m in metrics if m.get("stage") == "pre")
        rec["events"] = pre["events"]
        rec["word_count_rows"] = pre["word_count_rows"]

        # -- corpus build ------------------------------------------------
        day_dir = os.path.join(work, "20160131")
        t2 = time.perf_counter()
        corpus = Corpus.from_word_counts_file(
            os.path.join(day_dir, "word_counts.dat")
        )
        rec["corpus_wall_s"] = round(time.perf_counter() - t2, 1)
        rec["num_docs"] = corpus.num_docs
        rec["vocab_size"] = corpus.num_terms

        # ru_maxrss is KiB on Linux: binary factor, not decimal
        # (round-4 review finding: /1e6 understated the GB by 2.4%).
        peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        rec["peak_rss_gb"] = round(peak_kb * 1024 / 1e9, 2)
        rec["baseline_rss_gb"] = round(rss0_kb * 1024 / 1e9, 2)
        rec["rss_over_raw"] = round((peak_kb * 1024) / raw_bytes, 3)
        spill = os.path.join(day_dir, "raw_lines.bin")
        rec["spill_gb"] = round(os.path.getsize(spill) / 1e9, 2) \
            if os.path.exists(spill) else None
    finally:
        if not args.keep:
            shutil.rmtree(work, ignore_errors=True)

    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
