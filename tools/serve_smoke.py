"""Fast serving sanity check: run `ml_ops serve --dry-run` (single
model) AND `ml_ops serve --dry-run --fleet synthetic` (2-tenant fleet)
in clean subprocesses (CPU pinned) and verify both summary lines.

The single dry run exercises the whole serving stack — registry
publish, micro-batch flush triggers, host scoring, mid-stream
online-LDA refresh hot-swap, per-batch metrics.  The fleet dry run
exercises the multi-tenant path end to end — FleetRegistry stacked
snapshots, cross-tenant packed flushes, per-tenant demux, and
hot-swap isolation (tenant 0 republish leaves tenant 1's versions and
futures untouched).  Both run against synthetic in-memory days, so
this is the one-command check that the streaming paths still work on a
box with no chip grant and no day data.  tests/test_serving.py /
tests/test_fleet.py carry the same paths as tier-1 tests; this wrapper
is the operator/CI front door:

    python tools/serve_smoke.py
"""

import json
import os
import subprocess
import sys

MODES = {
    "single": ["serve", "--dry-run"],
    "fleet": ["serve", "--dry-run", "--fleet", "synthetic"],
}
_OK_KEYS = {"single": "serve_dry_run", "fleet": "serve_fleet_dry_run"}


def run_smoke(mode: str = "single", timeout_s: float = 300.0) -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "oni_ml_tpu.runner.ml_ops",
         *MODES[mode]],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    summary = None
    if lines:
        try:
            summary = json.loads(lines[-1])
        except ValueError:
            pass
    return {
        "rc": proc.returncode,
        "summary": summary,
        "stderr_tail": proc.stderr.strip()[-500:],
    }


def main() -> int:
    out = {}
    ok = True
    for mode in MODES:
        res = run_smoke(mode)
        mode_ok = (
            res["rc"] == 0
            and isinstance(res["summary"], dict)
            and res["summary"].get(_OK_KEYS[mode]) == "ok"
        )
        ok = ok and mode_ok
        out[mode] = {"ok": mode_ok, **res}
    print(json.dumps({"serve_smoke": "ok" if ok else "FAILED",
                      "modes": out}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
