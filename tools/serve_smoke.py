"""Fast serving sanity check: run `ml_ops serve --dry-run` in a clean
subprocess (CPU pinned) and verify its summary line.

The dry run exercises the whole serving stack — registry publish,
micro-batch flush triggers, host scoring, mid-stream online-LDA refresh
hot-swap, per-batch metrics — against a synthetic in-memory day, so
this is the one-command check that the streaming path still works on a
box with no chip grant and no day data.  tests/test_serving.py carries
the same path as a tier-1 test; this wrapper is the operator/CI
front door:

    python tools/serve_smoke.py
"""

import json
import os
import subprocess
import sys


def run_smoke(timeout_s: float = 300.0) -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "oni_ml_tpu.runner.ml_ops",
         "serve", "--dry-run"],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    summary = None
    if lines:
        try:
            summary = json.loads(lines[-1])
        except ValueError:
            pass
    return {
        "rc": proc.returncode,
        "summary": summary,
        "stderr_tail": proc.stderr.strip()[-500:],
    }


def main() -> int:
    out = run_smoke()
    ok = (
        out["rc"] == 0
        and isinstance(out["summary"], dict)
        and out["summary"].get("serve_dry_run") == "ok"
    )
    print(json.dumps({"serve_smoke": "ok" if ok else "FAILED", **out}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
