"""Compile-only HBM feasibility probe for config 4 (VERDICT r4 item 6).

The architecture doc's config-4 claims were arithmetic: "the densified
[B, V] corpus alone is ~4 GB/chip under data parallelism (infeasible on
a 16 GB v5e), the vocab-sharded dense plan shards it to ~0.5 GB and
fits".  This tool turns that into a COMPILER-verified fact at real
width — it AOT-lowers and compiles both plans at V=512k / K=20 /
per-chip B=2048 on the 8-device virtual mesh (no execution, no
multi-GB allocation: XLA's buffer assignment is static) and records
each plan's per-device argument/output/temp/peak bytes from
`compiled.memory_analysis()`:

    python tools/config4_hbm_probe.py [--v 524288] [--b 2048] [--k 20]
                                      [--out JSON_PATH]

Anchors: BASELINE.json config 4 (huge-V DNS regime,
dns_pre_lda.scala:320-326); docs/architecture.md "Multi-chip
collective-volume model".

Fidelity notes:
- Sizes are per-device (jax reports post-sharding buffer bytes).
- The vocab-sharded plan is the production XLA path — its numbers are
  exactly what a TPU run would place in HBM, modulo layout padding.
- The data-parallel dense plan compiles the Pallas kernel in interpret
  mode off-TPU, so its TEMP bytes over-estimate the Mosaic kernel's
  scratch; its ARGUMENT bytes (the resident corpus shard — the basis
  of the infeasibility claim) are layout-exact either way.  At real
  config-4 width the kernel's VMEM feasibility gate
  (dense_estep.pick_block) refuses the plan outright before lowering —
  the probe records that refusal as the plan's verdict.
"""

import argparse
import json
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HBM_BYTES = 16e9   # TPU v5e per-chip HBM (public spec)


def _stats(compiled) -> dict:
    ma = compiled.memory_analysis()
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is None:
        # jax 0.4.x CompiledMemoryStats has no peak field; the
        # args+outputs+temps sum is the conservative residency bound
        # (aliasing can only shrink it), which is what the fits-HBM
        # verdict needs.
        peak = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                + ma.temp_size_in_bytes)
    rec = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "peak_bytes": int(peak),
    }
    rec["fits_hbm"] = bool(rec["peak_bytes"] < HBM_BYTES)
    rec["peak_gb"] = round(rec["peak_bytes"] / 1e9, 2)
    return rec


def probe(v: int, b: int, k: int, n_devices: int = 8,
          var_max_iters: int = 20) -> dict:
    """Compile both config-4 plans at width `v`, per-chip batch `b`.
    Returns the record (no execution)."""
    import __graft_entry__ as graft

    graft._ensure_devices(n_devices)

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from oni_ml_tpu.parallel import make_mesh
    from oni_ml_tpu.parallel.sharded import (
        make_data_parallel_dense_e_step,
        make_vocab_sharded_dense_e_step,
    )

    rec = {"metric": "config4_hbm_probe", "k": k, "v": v,
           "b_per_chip": b, "n_devices": n_devices,
           "hbm_bytes": int(HBM_BYTES), "plans": {}}

    def sds(shape, dtype, mesh, spec):
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=NamedSharding(mesh, spec))

    # -- Plan of record: vocab-sharded dense on (data=1, model=8) ------
    # Columns of C and beta shard over `model`; the corpus never exists
    # whole on any chip.
    vs_mesh = make_mesh(data=1, model=n_devices,
                        devices=jax.devices()[:n_devices])
    vs_fn = make_vocab_sharded_dense_e_step(vs_mesh)
    args_vs = (
        sds((k, v), jnp.float32, vs_mesh, P(None, "model")),   # log_beta
        sds((), jnp.float32, vs_mesh, P()),                    # alpha
        sds((b, v), jnp.float32, vs_mesh, P("data", "model")),  # dense C
        sds((b,), jnp.float32, vs_mesh, P("data")),            # doc_mask
        sds((b, k), jnp.float32, vs_mesh, P("data")),          # gamma_prev
        sds((), jnp.int32, vs_mesh, P()),                      # warm
    )
    compiled = jax.jit(
        partial(vs_fn, var_max_iters=var_max_iters, var_tol=1e-6)
    ).lower(*args_vs).compile()
    rec["plans"]["vocab_sharded_dense"] = _stats(compiled)

    # -- The rejected alternative: data-parallel dense, per-chip B=b ---
    # Every chip holds its FULL [b, V] document shard; B_global = b * n.
    dp_mesh = make_mesh(data=n_devices, model=1,
                        devices=jax.devices()[:n_devices])
    dp_fn = make_data_parallel_dense_e_step(dp_mesh, wmajor=False)
    bg = b * n_devices
    args_dp = (
        sds((k, v), jnp.float32, dp_mesh, P()),                # replicated
        sds((), jnp.float32, dp_mesh, P()),
        sds((bg, v), jnp.float32, dp_mesh, P("data", None)),   # dense C
        sds((bg,), jnp.float32, dp_mesh, P("data")),
        sds((bg, k), jnp.float32, dp_mesh, P("data")),
        sds((), jnp.int32, dp_mesh, P()),
    )
    try:
        compiled = jax.jit(
            partial(dp_fn, var_max_iters=var_max_iters, var_tol=1e-6,
                    interpret=jax.default_backend() != "tpu")
        ).lower(*args_dp).compile()
        rec["plans"]["data_parallel_dense"] = _stats(compiled)
        dp_ok = (rec["plans"]["data_parallel_dense"]["argument_bytes"]
                 >= b * v * 4)
    except ValueError as e:
        # At real config-4 width the kernel's own VMEM feasibility gate
        # (dense_estep.pick_block) refuses before lowering even starts —
        # a stronger infeasibility verdict than any HBM estimate, so
        # record it as the plan's result.  ONLY that specific refusal
        # counts: an unrelated ValueError (spec mismatch, divisibility)
        # must fail the probe, not masquerade as compiler-verified
        # infeasibility.
        if "no VMEM-feasible doc block" not in str(e):
            raise
        rec["plans"]["data_parallel_dense"] = {
            "infeasible": True, "reason": str(e),
        }
        dp_ok = True

    vs_peak = rec["plans"]["vocab_sharded_dense"]["peak_bytes"]
    rec["dp_corpus_resident_gb"] = round(b * v * 4 / 1e9, 2)
    rec["claim_verified"] = bool(vs_peak < HBM_BYTES and dp_ok)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--v", type=int, default=524288)
    ap.add_argument("--b", type=int, default=2048)
    ap.add_argument("--k", type=int, default=20)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    rec = probe(args.v, args.b, args.k)
    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
