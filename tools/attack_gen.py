"""Emit a labeled injected day to disk for any registered source.

The file-shaped face of sources/inject.py: one benign synthetic day
with the source's attack scenarios planted into it, written as

    <out-dir>/day.csv        the raw event CSV, event-time ordered —
                             feeds `tools/day_replay.py --dsource X`
                             or `ml_ops continuous` directly
    <out-dir>/labels.jsonl   ground truth: one {"index", "scenario",
                             "entity"} row per attack line (index into
                             day.csv)
    <out-dir>/manifest.json  the {"kind": "injection"} record — same
                             vocabulary as the journal record
                             continuous mode emits when it builds its
                             quality suite

Everything is deterministic under --seed: same arguments, byte-
identical outputs (pinned by tests/test_sources.py).

Usage:

    python tools/attack_gen.py proxy --out-dir /tmp/proxy_day \
        --events 8000 --attack-events 8 --seed 7
    python tools/day_replay.py /tmp/proxy_day/day.csv --dsource proxy
"""

from __future__ import annotations

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

from oni_ml_tpu.sources import inject, names as source_names  # noqa: E402


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        description="Generate a labeled attack-injected day for a "
        "registered source."
    )
    ap.add_argument("source", choices=list(source_names()))
    ap.add_argument("--out-dir", required=True,
                    help="output directory (created if missing)")
    ap.add_argument("--events", type=int, default=8000,
                    help="benign event count (default 8000)")
    ap.add_argument("--attack-events", type=int, default=8,
                    help="events per attack scenario (default 8)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--scenarios", default=None,
                    help="comma list of scenario names (default: every "
                    "scenario registered for the source)")
    args = ap.parse_args(argv)
    scenarios = (tuple(s for s in args.scenarios.split(",") if s)
                 if args.scenarios is not None else None)
    try:
        day = inject.inject_scenarios(
            args.source, n_events=args.events, seed=args.seed,
            scenarios=scenarios, attack_events=args.attack_events,
        )
    except ValueError as e:
        print(f"attack_gen: {e}", file=sys.stderr)
        return 2
    os.makedirs(args.out_dir, exist_ok=True)
    day_path = os.path.join(args.out_dir, "day.csv")
    with open(day_path, "w") as f:
        f.write("\n".join(day.lines) + "\n")
    with open(os.path.join(args.out_dir, "labels.jsonl"), "w") as f:
        for row in day.label_rows():
            f.write(json.dumps(row) + "\n")
    with open(os.path.join(args.out_dir, "manifest.json"), "w") as f:
        json.dump(day.manifest, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "out_dir": args.out_dir,
        "source": args.source,
        "events": len(day.lines),
        "attacks": day.n_attacks,
        "scenarios": day.manifest["scenarios"],
    }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
