"""Glue amortization at day scale on the virtual mesh (VERDICT r4 item 7).

docs/architecture.md's collective-volume model concedes the flagship
single-batch config tops out at ≈2.6× on 8 chips — Amdahl on the
per-EM-iteration fixed cost that does not shrink with the document
split (r05 decomposed the single-chip term into ~65 ms/dispatch
tunnel glue amortized by chunk + device-side fixed work like the
alpha update; docs/performance.md round-5 section) — and claims
multi-chip pays at day-scale corpora because many resident batches
amortize that fixed cost.  This tool MEASURES the amortization
structure on the 8-device virtual CPU mesh (relative shape, not TPU
absolute times): per-EM-iteration wall against resident batch count
for the production fused chunk runner with the data-parallel sharded
E-step, then the least-squares split into fixed (glue) and marginal
(per-batch compute) components:

    python tools/glue_amortization.py [--out JSON_PATH]

The fixed component is per EM ITERATION, not per batch — so its share
of the iteration falls as 1/n_batches, which is exactly the mechanism
the day-scale multi-chip claim rests on.  Results are pasted (with
provenance) into docs/architecture.md next to the arithmetic.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def measure(k=20, v=8192, b=512, l=64, n_batches=(1, 2, 4, 8, 16),
            chunk=4, var_max_iters=10, rounds=3, n_devices=8) -> dict:
    import __graft_entry__ as graft

    graft._ensure_devices(n_devices)

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from oni_ml_tpu.io.corpus import Batch
    from oni_ml_tpu.models import fused
    from oni_ml_tpu.parallel import make_mesh
    from oni_ml_tpu.parallel.mesh import DATA_AXIS
    from oni_ml_tpu.parallel.sharded import make_data_parallel_e_step

    mesh = make_mesh(data=n_devices, model=1,
                     devices=jax.devices()[:n_devices])
    put = lambda x: jax.device_put(  # noqa: E731  (doc axis = axis 1)
        x, NamedSharding(mesh, P(None, DATA_AXIS)))
    e_fn = make_data_parallel_e_step(mesh)

    rng = np.random.default_rng(0)
    noise = rng.uniform(size=(k, v)) + 1.0 / v
    log_beta0 = jnp.asarray(
        np.log(noise / noise.sum(-1, keepdims=True)), jnp.float32
    )

    rows = []
    for nb in n_batches:
        batches = [
            Batch(
                word_idx=rng.integers(0, v, size=(b, l)).astype(np.int32),
                counts=rng.integers(1, 5, size=(b, l)).astype(np.float32),
                doc_index=np.arange(i * b, (i + 1) * b, dtype=np.int32),
                doc_mask=np.ones((b,), np.float32),
            )
            for i in range(nb)
        ]
        groups = fused.stack_batches(batches, np.float32, put)
        run_chunk = fused.make_chunk_runner(
            num_docs=nb * b, num_topics=k, num_terms=v, chunk=chunk,
            var_max_iters=var_max_iters, var_tol=1e-6, em_tol=0.0,
            estimate_alpha=True, e_step_fn=e_fn,
        )
        gammas0 = tuple(
            put(g) for g in fused.initial_gammas(
                groups.arrays, k, jnp.float32)
        )
        log_beta, alpha = log_beta0, jnp.float32(2.5)
        res = run_chunk(log_beta, alpha, jnp.float32(np.nan),
                        groups.arrays, chunk, gammas0, jnp.asarray(False))
        float(res.lls[-1])          # compile + settle
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            res = run_chunk(res.log_beta, res.alpha, res.ll_prev,
                            groups.arrays, chunk, res.gammas,
                            res.steps_done > 0)
            assert np.isfinite(float(res.lls[-1]))
            best = min(best, (time.perf_counter() - t0) / chunk)
        rows.append({"n_batches": nb, "t_iter_ms": round(best * 1e3, 2),
                     "t_iter_per_batch_ms": round(best * 1e3 / nb, 2)})

    # Least-squares t(n) = glue + n * per_batch over the measured rows.
    ns = np.asarray([r["n_batches"] for r in rows], np.float64)
    ts = np.asarray([r["t_iter_ms"] for r in rows], np.float64)
    per_batch, glue = np.polyfit(ns, ts, 1)
    rec = {
        "metric": "glue_amortization_cpu_mesh",
        "k": k, "v": v, "b_per_batch": b, "l": l,
        "n_devices": n_devices, "chunk": chunk,
        "rows": rows,
        "fit_glue_ms": round(float(glue), 2),
        "fit_per_batch_ms": round(float(per_batch), 2),
        "glue_share_1_batch": round(float(glue / ts[0]), 3),
        "glue_share_max_batches": round(float(glue / ts[-1]), 3),
    }
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--rounds", type=int, default=3)
    args = ap.parse_args()
    rec = measure(rounds=args.rounds)
    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
