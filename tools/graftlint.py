"""graftlint launcher for source checkouts:

    python tools/graftlint.py [--json] [--rule ID] [--list-rules]
    python tools/graftlint.py --update-schema | --update-baseline

Thin wrapper over oni_ml_tpu.analysis.cli (the same code behind
`oni-ml-ops lint` and the `oni-graftlint` console script).  Nothing
here imports jax or numpy — the lint runs on any CI box in well under
a second.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from oni_ml_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
