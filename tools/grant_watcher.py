"""Unattended grant-return watcher (VERDICT r4 items 1 and 9).

Three consecutive rounds of TPU evidence died because a human had to be
present at the moment the chip grant returned.  This loop probes the
device backend GENTLY — one subprocess-isolated probe every ~10 min;
rapid retries have been observed to RE-wedge a recovering grant — and
the moment a probe answers, it fires the capture runbook
(tools/chip_session.py), streaming everything under
docs/bench_captures/ as it is produced.

Re-arm policy (keyed on chip_session's documented exit-code contract):
  rc 0  all steps green      -> STOP: the mission is complete.
  rc 1  completed, >=1 red   -> the record was captured but the chip
                                did not survive the full sequence;
                                RE-ARM at normal cadence, bounded by
                                --max-captures.
  rc 2  a step wedged        -> the grant likely died mid-step; RE-ARM
                                with a doubled probe interval (gentler
                                still), bounded by --max-captures.

Each capture attempt writes its own file (the canonical
rNN_session_capture.json if free, else the first unclaimed
rNNa{k}_session_capture.json — EXISTING files are never reused, even
ones written by a manual chip_session run before this watcher
started) so a later, worse capture can never overwrite an earlier,
better one.  Both shapes match the r*_session_capture.json glob
bench._last_good_record() reads.

Usage (start-of-session, background):

    nohup python tools/grant_watcher.py \
        >> docs/bench_captures/watcher.log 2>&1 &

    python tools/grant_watcher.py --once   # single probe+decision
"""

import argparse
import glob
import os
import re
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

DEFAULT_INTERVAL_S = 600.0        # gentle: ~10 min between probes
DEFAULT_PROBE_TIMEOUT_S = 120.0   # one backend-init probe attempt
DEFAULT_MAX_CAPTURES = 3          # chip_session firings per watch


def current_round_tag(base_dir: str = HERE) -> str:
    """rNN for the round in progress: one past the newest driver
    record (BENCH_r*.json) at the repo root."""
    rounds = [0]
    for path in glob.glob(os.path.join(base_dir, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            rounds.append(int(m.group(1)))
    return f"r{max(rounds) + 1:02d}"


def capture_out_path(round_tag: str, attempt: int,
                     base_dir: str = HERE) -> str:
    """Never reuse an EXISTING capture file: the watcher counts its
    own firings, but a manual chip_session run (or a previous watcher
    process) may already have claimed this round's canonical name —
    the no-overwrite invariant is on the FILES, not on this process's
    attempt counter, so walk forward to the first free name."""
    def path_for(n: int) -> str:
        tag = round_tag if n == 1 else f"{round_tag}a{n}"
        return os.path.join(base_dir, "docs", "bench_captures",
                            f"{tag}_session_capture.json")

    n = attempt
    while os.path.exists(path_for(n)):
        n += 1
    return path_for(n)


def next_action(rc: "int | None", captures_done: int,
                max_captures: int):
    """Pure re-arm policy over chip_session's exit-code contract.
    Returns ("stop", reason) or ("rearm", interval_factor)."""
    if rc == 0:
        return ("stop", "capture complete (all steps green)")
    if captures_done >= max_captures:
        return ("stop",
                f"capture budget exhausted ({captures_done} attempts)")
    if rc is None or rc < 0 or rc == 2:
        # rc 2 = a step wedged; negative = the runner itself was
        # signal-killed (OOM, SIGKILL) mid-capture; None = it never
        # returned a code.  All three say the grant is likely sick —
        # probe gentler (rapid retries re-wedge a recovering grant).
        return ("rearm", 2.0)
    return ("rearm", 1.0)       # completed but red: normal cadence


def probe_once(timeout: float = DEFAULT_PROBE_TIMEOUT_S):
    """Device count or None, via the shared subprocess-isolated probe
    (SIGTERM-grace timeout; never SIGKILL first)."""
    from __graft_entry__ import probe_device_count

    return probe_device_count(timeout)


def run_capture(out_path: str) -> int:
    return subprocess.run(
        [sys.executable, os.path.join(HERE, "tools", "chip_session.py"),
         "--out", out_path],
        cwd=HERE,
    ).returncode


def watch(*, interval_s: float = DEFAULT_INTERVAL_S,
          probe_timeout_s: float = DEFAULT_PROBE_TIMEOUT_S,
          max_captures: int = DEFAULT_MAX_CAPTURES,
          round_tag: "str | None" = None,
          once: bool = False,
          probe=probe_once, capture=run_capture, sleep=time.sleep,
          log=None, base_dir: str = HERE, journal=None) -> int:
    """The watch loop.  probe/capture/sleep are injectable so the
    trigger logic is testable without a backend or real time.
    Returns 0 when a fully-green capture landed, 1 otherwise (budget
    exhausted, or --once with no grant).

    `journal` (oni_ml_tpu.telemetry.RunJournal) mirrors every probe
    outcome and capture decision as crash-safe heartbeat/annotation
    records — the same vocabulary the pipeline heartbeat writes, so
    tools/trace_view.py shows grant liveness over a whole watch."""
    if log is None:
        def log(msg):
            print(f"grant_watcher[{time.strftime('%F %T')}]: {msg}",
                  flush=True)
    if round_tag is None:
        round_tag = current_round_tag(base_dir)
    log(f"watching for a grant: interval {interval_s:.0f}s, probe "
        f"timeout {probe_timeout_s:.0f}s, capture budget {max_captures}, "
        f"round tag {round_tag}")
    captures = 0
    factor = 1.0
    probes = 0
    while True:
        n = probe(probe_timeout_s)
        probes += 1
        if journal is not None:
            journal.heartbeat(bool(n), probe=probes,
                              devices=int(n) if n else 0)
        if n:
            captures += 1
            out = capture_out_path(round_tag, captures, base_dir)
            log(f"probe {probes}: backend ALIVE ({n} device(s)) — "
                f"firing chip_session (attempt {captures}) -> {out}")
            rc = capture(out)
            action, detail = next_action(rc, captures, max_captures)
            log(f"chip_session rc={rc} -> {action} ({detail})")
            if journal is not None:
                journal.annotation("watcher_capture", rc=rc, out=out,
                                   action=action, detail=str(detail))
            if action == "stop":
                return 0 if rc == 0 else 1
            factor = detail
        else:
            log(f"probe {probes}: backend unresponsive "
                f"(next in {interval_s * factor:.0f}s)")
        if once:
            return 1
        sleep(interval_s * factor)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Probe for a returned TPU grant; auto-run the "
                    "capture runbook when one appears.")
    ap.add_argument("--interval", type=float, default=DEFAULT_INTERVAL_S,
                    help="seconds between probes (default 600)")
    ap.add_argument("--probe-timeout", type=float,
                    default=DEFAULT_PROBE_TIMEOUT_S)
    ap.add_argument("--max-captures", type=int,
                    default=DEFAULT_MAX_CAPTURES)
    ap.add_argument("--round-tag", default=None,
                    help="rNN capture prefix (default: derived from "
                         "BENCH_r*.json)")
    ap.add_argument("--once", action="store_true",
                    help="single probe + decision, then exit")
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="mirror probe/capture outcomes into a "
                    "crash-safe telemetry journal "
                    "(oni_ml_tpu/telemetry/journal.py JSONL; "
                    "tools/trace_view.py summarizes it)")
    args = ap.parse_args()
    journal = None
    if args.journal:
        from oni_ml_tpu.telemetry import Journal, RunJournal

        journal = RunJournal(Journal(args.journal))
        journal.run_start(app="grant_watcher")
    try:
        return watch(interval_s=args.interval,
                     probe_timeout_s=args.probe_timeout,
                     max_captures=args.max_captures,
                     round_tag=args.round_tag,
                     once=args.once,
                     journal=journal)
    finally:
        if journal is not None:
            journal.close()


if __name__ == "__main__":
    sys.exit(main())
