"""Compare two bench payloads and fail loudly on regression.

The post-bench CI step (docs/performance.md "Catching regressions"):
feed it the previous round's captured payload (BENCH_rNN.json — the
driver wrapper with a "parsed" object — or a raw `python bench.py`
headline line) and the fresh one, and it diffs every comparable number:

  * the headline metric (direction inferred from the unit: rates are
    higher-better, seconds lower-better),
  * every secondary phase's `value` present in BOTH payloads,
  * roofline utilization (headline `utilization.mxu_pct` / `hbm_pct`,
    absolute percentage points),
  * the streaming dataplane's `overlap_efficiency` from the
    pipeline_e2e phases (absolute drop — the number is a fraction of
    hidden work, so relative deltas near 0 are noise).

Exit codes: 0 = no regression beyond thresholds, 1 = regression
(printed per row), 2 = unusable input.  `--json` emits the full row
set for dashboards.

Usage:

    python tools/bench_diff.py BENCH_r05.json BENCH_r06.json \
        [--threshold-pct 10] [--efficiency-drop 0.05] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys

# Phases whose payloads carry an overlap_efficiency headline.
_OVERLAP_PHASES = ("pipeline_e2e", "pipeline_e2e_dns")

# Serving SLO phases: their payloads carry nested latency quantiles and
# sustained rates whose regression DIRECTIONS differ per key —
# sustained_eps is higher-better (events/sec), the pNN_ms quantiles are
# lower-better (ms) — so each key compares under its own unit instead
# of riding the phase's single headline value.  serving_slo nests per
# arrival pattern; serving_slo_fleet nests an aggregate plus one
# summary per tenant; serving_slo_fleet_paged additionally carries the
# tiered-residency ledger (its aggregate p99 INCLUDES promotion
# misses, so a paging regression gates through the same keys).
_SERVING_PHASES = ("serving_slo", "serving_slo_fleet",
                   "serving_slo_fleet_paged")
_SERVING_KEYS = (
    ("sustained_eps", "events/sec"),     # higher-better
    ("p50_ms", "ms"),                    # lower-better
    ("p99_ms", "ms"),
    ("p999_ms", "ms"),
)

# Tiered-residency keys (serving_slo_fleet_paged "residency" section):
# the total priced promotion stall is dead time on paging tenants'
# latency paths (lower-better).  Promotion/eviction COUNTS are
# reported but not gated — they change with the Zipf draw and
# capacity config, not with performance.
_RESIDENCY_KEYS = (
    ("promotion_stall_s", "s"),          # lower-better
)

# Continuous-ingestion freshness phase: direction per key — freshness
# latencies (wall seconds AND speed-invariant event-time minutes) are
# lower-better, the warm-start speedup (fresh wall / warm wall at
# matched held-out likelihood) is higher-better.  The held-out
# likelihood itself gates on ABSOLUTE drop (nats — a relative delta on
# a negative log-likelihood is meaningless), like overlap_efficiency.
_STREAMING_PHASE = "streaming_freshness"
_STREAMING_KEYS = (
    ("freshness_p50_s", "s"),              # lower-better
    ("freshness_p99_s", "s"),
    ("freshness_event_p50_min", "min"),    # minutes; latency direction
    ("freshness_event_p99_min", "min"),
    ("warm_start_speedup", "x"),           # higher-better
)


def _streaming_rows(name: str, old: dict, new: dict,
                    threshold_pct: float, ll_drop: float) -> "list[dict]":
    rows = []
    for key, unit in _STREAMING_KEYS:
        r = _rel_row(f"{name}.{key}", old.get(key), new.get(key), unit,
                     threshold_pct)
        if r:
            rows.append(r)
    r = _abs_row(f"{name}.held_out_ll", old.get("held_out_ll"),
                 new.get("held_out_ll"), "nats", ll_drop)
    if r:
        rows.append(r)
    return rows


# Composed standing-service phase (continuous x fleet x cosched):
# direction per key — the fleet freshness latencies (wall seconds and
# speed-invariant event-time minutes) and the serve-latency tails are
# lower-better; `p99_during_refresh_ms` is the co-scheduler's
# acceptance number (the serve tail WHILE a refresh fit holds the
# process), and the yield/preempt waits are the arbitration's own
# priced cost.  `sustained_eps` is the replayed multi-tenant drain
# rate (higher-better).  The chaos bits (failed_futures == 0,
# failovers >= 1, zero retraces) are asserted by the test suite and
# reported in the payload, not trended here — they are correctness
# bits, not performance trends.
_CONTINUOUS_REPLICATED_PHASE = "continuous_replicated"
_CONTINUOUS_REPLICATED_KEYS = (
    ("freshness_p50_s", "s"),                    # lower-better
    ("freshness_p99_s", "s"),
    ("freshness_event_p50_min", "min"),          # minutes; latency
    ("freshness_event_p99_min", "min"),
    ("p99_idle_ms", "ms"),                       # lower-better
    ("p99_during_refresh_ms", "ms"),             # the cosched claim
    ("yield_wait_p99_ms", "ms"),
    ("preempt_wait_p99_ms", "ms"),
    ("sustained_eps", "events/sec"),             # higher-better
)


def _continuous_replicated_rows(name: str, old: dict, new: dict,
                                threshold_pct: float) -> "list[dict]":
    rows = []
    for key, unit in _CONTINUOUS_REPLICATED_KEYS:
        r = _rel_row(f"{name}.{key}", old.get(key), new.get(key), unit,
                     threshold_pct)
        if r:
            rows.append(r)
    return rows


# Detection-quality phase: every key is HIGHER-better —
# precision/recall@k are fractions of attacks ranked inside the top-k,
# score_separation is the median benign-vs-attack log-score gap in
# nats.  A recall drop gates exit 1 exactly like a p99 blowup; the
# per-source sections gate too, so one source regressing cannot hide
# behind the cross-source mean.
_QUALITY_PHASE = "detection_quality"
_QUALITY_KEYS = (
    ("recall_at_k", "fraction"),         # higher-better
    ("precision_at_k", "fraction"),      # higher-better
    ("score_separation", "nats"),        # higher-better
)


def _quality_rows(name: str, old: dict, new: dict,
                  threshold_pct: float) -> "list[dict]":
    rows = []
    for key, unit in _QUALITY_KEYS:
        r = _rel_row(f"{name}.{key}", old.get(key), new.get(key), unit,
                     threshold_pct)
        if r:
            rows.append(r)
    old_src = old.get("sources") or {}
    new_src = new.get("sources") or {}
    for src in sorted(set(old_src) & set(new_src)):
        o, n = old_src[src], new_src[src]
        if not isinstance(o, dict) or not isinstance(n, dict):
            continue
        for key, unit in _QUALITY_KEYS:
            r = _rel_row(f"{name}:{src}.{key}", o.get(key), n.get(key),
                         unit, threshold_pct)
            if r:
                rows.append(r)
    return rows


# Device-featurization phase: every gated key is events/sec
# (higher-better) — the per-micro-batch host/device/fused rates are
# nested {batch_size: eps} dicts, the fleet drain rates are scalars.
# The speedup ratios are derived (reported, not separately gated: a
# device-rate regression already gates through its own key).
_FEATURIZE_PHASE = "featurize_device"
_FEATURIZE_TIER_KEYS = (
    ("host_eps", "events/sec"),          # higher-better
    ("device_eps", "events/sec"),        # higher-better
    ("fused_eps", "events/sec"),         # higher-better
)
_FEATURIZE_KEYS = (
    ("fleet_host_eps", "events/sec"),    # higher-better
    ("fleet_device_eps", "events/sec"),  # higher-better
)


def _featurize_rows(name: str, old: dict, new: dict,
                    threshold_pct: float) -> "list[dict]":
    rows = []
    for key, unit in _FEATURIZE_TIER_KEYS:
        o, n = old.get(key) or {}, new.get(key) or {}
        if not isinstance(o, dict) or not isinstance(n, dict):
            continue
        for batch in sorted(set(o) & set(n), key=str):
            r = _rel_row(f"{name}.{key}@{batch}", o[batch], n[batch],
                         unit, threshold_pct)
            if r:
                rows.append(r)
    for key, unit in _FEATURIZE_KEYS:
        r = _rel_row(f"{name}.{key}", old.get(key), new.get(key), unit,
                     threshold_pct)
        if r:
            rows.append(r)
    return rows


# Replicated elastic serving phase: direction per key — aggregate
# sustained events/s per replica count and the scaling efficiency are
# higher-better; the chaos phase's p999-during-failover and
# time-to-full-recovery are lower-better (a slower promotion is a
# regression exactly like a latency blowup).  Error/retrace counts are
# asserted by the test suite, not gated here (they are correctness
# bits, not performance trends).
_REPLICATED_PHASE = "serving_slo_replicated"
_REPLICATED_KEYS = (
    ("replica_scaling_efficiency", "fraction"),  # higher-better
    ("failover_p999_ms", "ms"),                  # lower-better
    ("time_to_recovery_s", "s"),                 # lower-better
)


def _replicated_rows(name: str, old: dict, new: dict,
                     threshold_pct: float) -> "list[dict]":
    rows = []
    for key, unit in _REPLICATED_KEYS:
        r = _rel_row(f"{name}.{key}", old.get(key), new.get(key), unit,
                     threshold_pct)
        if r:
            rows.append(r)
    old_eps = old.get("sustained_eps_by_count") or {}
    new_eps = new.get("sustained_eps_by_count") or {}
    for count in sorted(set(old_eps) & set(new_eps), key=int):
        r = _rel_row(f"{name}.sustained_eps[{count}]",
                     old_eps.get(count), new_eps.get(count),
                     "events/sec", threshold_pct)
        if r:
            rows.append(r)
    return rows


# Distributed-EM scaling phase: direction per key — scaling efficiency
# is a fraction of ideal speedup (higher-better), the per-iteration
# allreduce wall is dead time on the EM critical path (lower-better).
# Bytes per iteration are reported but not gated: they change with the
# payload schema, not with performance.
_DISTRIBUTED_PHASE = "distributed_em"
_DISTRIBUTED_KEYS = (
    ("scaling_efficiency", "fraction"),  # higher-better
    ("allreduce_wall_s_per_iter", "s"),  # lower-better
)


def _distributed_rows(name: str, old: dict, new: dict,
                      threshold_pct: float) -> "list[dict]":
    rows = []
    for key, unit in _DISTRIBUTED_KEYS:
        r = _rel_row(f"{name}.{key}", old.get(key), new.get(key), unit,
                     threshold_pct)
        if r:
            rows.append(r)
    return rows


# Cross-host serving phase: direction per key — aggregate sustained
# events/s (at the winning router count) and the router scaling
# efficiency are higher-better; the columnar wire's bytes-per-event is
# overhead on every frame (lower-better — a fatter encoding IS the
# regression the zero-copy wire exists to prevent); the autoscaler's
# scale-up reaction is dead time between the band breach and the
# joined replica (lower-better).  The fanin_exceeds_single_router /
# bit_identical / zero-error bits are asserted by the test suite and
# the bench gate, not trended here.
_CROSSHOST_PHASE = "serving_crosshost"
_CROSSHOST_KEYS = (
    ("sustained_eps", "events/sec"),           # higher-better
    ("router_scaling_efficiency", "fraction"),  # higher-better
    ("wire_bytes_per_event", "bytes/event"),   # lower-better
    ("scale_up_reaction_s", "s"),              # lower-better
)


def _crosshost_rows(name: str, old: dict, new: dict,
                    threshold_pct: float) -> "list[dict]":
    rows = []
    for key, unit in _CROSSHOST_KEYS:
        r = _rel_row(f"{name}.{key}", old.get(key), new.get(key), unit,
                     threshold_pct)
        if r:
            rows.append(r)
    old_eps = (old.get("fanin") or {}).get(
        "aggregate_eps_by_routers") or {}
    new_eps = (new.get("fanin") or {}).get(
        "aggregate_eps_by_routers") or {}
    for count in sorted(set(old_eps) & set(new_eps), key=int):
        r = _rel_row(f"{name}.aggregate_eps[{count}r]",
                     old_eps.get(count), new_eps.get(count),
                     "events/sec", threshold_pct)
        if r:
            rows.append(r)
    return rows


def _serving_groups(payload: dict) -> "dict[str, dict]":
    """label -> latency-summary dict for every comparable group in a
    serving SLO payload: arrival patterns (serving_slo), the fleet
    aggregate, and each tenant (serving_slo_fleet)."""
    groups: dict = {}
    for pattern in ("poisson", "bursty"):
        g = payload.get(pattern)
        if isinstance(g, dict):
            groups[pattern] = g
    agg = payload.get("aggregate")
    if isinstance(agg, dict):
        groups["aggregate"] = agg
    tenants = payload.get("tenants")
    if isinstance(tenants, dict):
        for tid in sorted(tenants):
            if isinstance(tenants[tid], dict):
                groups[f"tenant.{tid}"] = tenants[tid]
    return groups


def _serving_rows(name: str, old: dict, new: dict,
                  threshold_pct: float) -> "list[dict]":
    """Per-group, per-key comparison rows for one serving SLO phase
    present in both payloads: a p99/p999 blowup gates exit 1 exactly
    like a throughput drop, each under its own direction.  A paged
    payload's residency ledger contributes its own direction-aware
    keys (promotion stall lower-better)."""
    rows = []
    old_groups = _serving_groups(old)
    new_groups = _serving_groups(new)
    for label in sorted(set(old_groups) & set(new_groups)):
        for key, unit in _SERVING_KEYS:
            r = _rel_row(
                f"{name}:{label}.{key}",
                old_groups[label].get(key), new_groups[label].get(key),
                unit, threshold_pct,
            )
            if r:
                rows.append(r)
    old_res, new_res = old.get("residency"), new.get("residency")
    if isinstance(old_res, dict) and isinstance(new_res, dict):
        for key, unit in _RESIDENCY_KEYS:
            r = _rel_row(f"{name}:residency.{key}", old_res.get(key),
                         new_res.get(key), unit, threshold_pct)
            if r:
                rows.append(r)
    return rows


def load_payload(path: str) -> dict:
    """A bench payload from either container: the driver's capture
    wrapper ({"parsed": {...}}), a raw headline object, or a
    failure payload (whose comparable numbers live in "last_good")."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a JSON object")
    if isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    if data.get("value") is None and isinstance(
        data.get("last_good"), dict
    ):
        data = data["last_good"]
    return data


def _higher_is_better(unit: str) -> bool:
    u = (unit or "").lower()
    if u in ("bytes", "bytes/event"):   # wire overhead: lower-better
        return False
    if "/" in u:          # docs/sec, events/sec, ...
        return True
    return u not in ("seconds", "second", "s", "ms", "milliseconds",
                     "min", "minutes")


def _rel_row(name: str, old, new, unit: str, threshold_pct: float):
    """One relative-delta comparison row; regression when the metric
    moved the WRONG direction by more than threshold_pct."""
    if not isinstance(old, (int, float)) or not isinstance(
        new, (int, float)
    ) or old == 0:
        return None
    delta_pct = 100.0 * (new - old) / abs(old)
    worse = -delta_pct if _higher_is_better(unit) else delta_pct
    return {
        "name": name, "old": old, "new": new, "unit": unit,
        "delta_pct": round(delta_pct, 2),
        "regression": worse > threshold_pct,
    }


def _abs_row(name: str, old, new, unit: str, max_drop: float):
    """Absolute-drop comparison (utilization points, overlap
    efficiency): regression when new < old - max_drop."""
    if not isinstance(old, (int, float)) or not isinstance(
        new, (int, float)
    ):
        return None
    return {
        "name": name, "old": old, "new": new, "unit": unit,
        "delta_abs": round(new - old, 4),
        "regression": (old - new) > max_drop,
    }


def diff_payloads(old: dict, new: dict, threshold_pct: float = 10.0,
                  efficiency_drop: float = 0.05,
                  util_drop_pct: float = 2.0,
                  ll_drop: float = 0.25) -> "list[dict]":
    rows = []
    # Headline.
    r = _rel_row(
        f"headline:{new.get('metric', old.get('metric', '?'))}",
        old.get("value"), new.get("value"), new.get("unit", ""),
        threshold_pct,
    )
    if r:
        rows.append(r)
    # Secondary phases present in both.
    old_sec = old.get("secondary") or {}
    new_sec = new.get("secondary") or {}
    for name in sorted(set(old_sec) & set(new_sec)):
        o, n = old_sec[name], new_sec[name]
        if not isinstance(o, dict) or not isinstance(n, dict):
            continue
        r = _rel_row(f"phase:{name}", o.get("value"), n.get("value"),
                     n.get("unit", o.get("unit", "")), threshold_pct)
        if r:
            rows.append(r)
    # Roofline utilization on the headline (absolute points — 10.5% MXU
    # dropping to 8% is a real kernel regression even though the
    # relative delta reads -24%).
    old_util = old.get("utilization") or {}
    new_util = new.get("utilization") or {}
    for key in ("mxu_pct", "hbm_pct"):
        r = _abs_row(f"utilization:{key}", old_util.get(key),
                     new_util.get(key), "pct", util_drop_pct)
        if r:
            rows.append(r)
    # Serving SLO latency/throughput keys (direction per key: rates
    # higher-better, millisecond quantiles lower-better) — from the
    # secondary phase payloads, and from the headline payload itself
    # when the compared run IS a serving phase capture.
    for name in _SERVING_PHASES:
        o, n = old_sec.get(name), new_sec.get(name)
        if isinstance(o, dict) and isinstance(n, dict):
            rows.extend(_serving_rows(f"phase:{name}", o, n,
                                      threshold_pct))
    if _serving_groups(old) and _serving_groups(new):
        rows.extend(_serving_rows("headline", old, new, threshold_pct))
    # Replicated-serving keys (per-count sustained eps + scaling
    # efficiency higher-better, failover p999 / recovery lower-better)
    # — phase payloads and replicated-headline captures.
    o, n = old_sec.get(_REPLICATED_PHASE), new_sec.get(_REPLICATED_PHASE)
    if isinstance(o, dict) and isinstance(n, dict):
        rows.extend(_replicated_rows(f"phase:{_REPLICATED_PHASE}", o, n,
                                     threshold_pct))
    if ("replica_scaling_efficiency" in old
            and "replica_scaling_efficiency" in new):
        rows.extend(_replicated_rows("headline", old, new,
                                     threshold_pct))
    # Cross-host serving keys (fan-in eps + scaling efficiency
    # higher-better; wire bytes/event + autoscale reaction
    # lower-better) — phase payloads and crosshost-headline captures.
    o, n = old_sec.get(_CROSSHOST_PHASE), new_sec.get(_CROSSHOST_PHASE)
    if isinstance(o, dict) and isinstance(n, dict):
        rows.extend(_crosshost_rows(f"phase:{_CROSSHOST_PHASE}", o, n,
                                    threshold_pct))
    if ("router_scaling_efficiency" in old
            and "router_scaling_efficiency" in new):
        rows.extend(_crosshost_rows("headline", old, new,
                                    threshold_pct))
    # Device-featurization keys (events/s per engine per micro-batch
    # tier + fleet drain rates, all higher-better) — phase payloads
    # and featurize-headline captures.
    o, n = old_sec.get(_FEATURIZE_PHASE), new_sec.get(_FEATURIZE_PHASE)
    if isinstance(o, dict) and isinstance(n, dict):
        rows.extend(_featurize_rows(f"phase:{_FEATURIZE_PHASE}", o, n,
                                    threshold_pct))
    if "fleet_device_eps" in old and "fleet_device_eps" in new:
        rows.extend(_featurize_rows("headline", old, new,
                                    threshold_pct))
    # Distributed-EM scaling keys (efficiency higher-better, allreduce
    # wall lower-better) — from the secondary phase payloads, and from
    # the headline payload when the compared run IS a distributed_em
    # capture.
    o, n = old_sec.get(_DISTRIBUTED_PHASE), new_sec.get(_DISTRIBUTED_PHASE)
    if isinstance(o, dict) and isinstance(n, dict):
        rows.extend(_distributed_rows(f"phase:{_DISTRIBUTED_PHASE}", o, n,
                                      threshold_pct))
    if "scaling_efficiency" in old and "scaling_efficiency" in new:
        rows.extend(_distributed_rows("headline", old, new, threshold_pct))
    # Streaming-freshness keys (freshness latencies lower-better,
    # warm-start speedup higher-better, held-out LL absolute-drop
    # gated) — phase payloads and freshness-headline captures.
    o, n = old_sec.get(_STREAMING_PHASE), new_sec.get(_STREAMING_PHASE)
    if isinstance(o, dict) and isinstance(n, dict):
        rows.extend(_streaming_rows(f"phase:{_STREAMING_PHASE}", o, n,
                                    threshold_pct, ll_drop))
    if ("freshness_p50_s" in old and "freshness_p50_s" in new
            and "p99_during_refresh_ms" not in new):
        # A composed continuous_replicated capture also carries
        # freshness keys; its own branch below owns them there.
        rows.extend(_streaming_rows("headline", old, new,
                                    threshold_pct, ll_drop))
    # Composed standing-service keys (freshness + serve-during-refresh
    # tails + yield/preempt waits lower-better, sustained eps
    # higher-better) — phase payloads and composed-headline captures
    # (sentinel: p99_during_refresh_ms, unique to this phase).
    o, n = (old_sec.get(_CONTINUOUS_REPLICATED_PHASE),
            new_sec.get(_CONTINUOUS_REPLICATED_PHASE))
    if isinstance(o, dict) and isinstance(n, dict):
        rows.extend(_continuous_replicated_rows(
            f"phase:{_CONTINUOUS_REPLICATED_PHASE}", o, n,
            threshold_pct))
    if ("p99_during_refresh_ms" in old
            and "p99_during_refresh_ms" in new):
        rows.extend(_continuous_replicated_rows(
            "headline", old, new, threshold_pct))
    # Detection-quality keys (all higher-better: recall/precision@k,
    # score separation; per-source sections too) — phase payloads and
    # quality-headline captures.
    o, n = old_sec.get(_QUALITY_PHASE), new_sec.get(_QUALITY_PHASE)
    if isinstance(o, dict) and isinstance(n, dict):
        rows.extend(_quality_rows(f"phase:{_QUALITY_PHASE}", o, n,
                                  threshold_pct))
    if "recall_at_k" in old and "recall_at_k" in new:
        rows.extend(_quality_rows("headline", old, new, threshold_pct))
    # Streaming-dataplane overlap efficiency (absolute fraction).
    for name in _OVERLAP_PHASES:
        o, n = old_sec.get(name), new_sec.get(name)
        if not isinstance(o, dict) or not isinstance(n, dict):
            continue
        r = _abs_row(f"overlap_efficiency:{name}",
                     o.get("overlap_efficiency"),
                     n.get("overlap_efficiency"), "fraction",
                     efficiency_drop)
        if r:
            rows.append(r)
    return rows


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two bench payloads; exit 1 on regression "
        "beyond thresholds."
    )
    ap.add_argument("old", help="baseline payload (BENCH_rNN.json or "
                    "raw bench.py output)")
    ap.add_argument("new", help="candidate payload")
    ap.add_argument("--threshold-pct", type=float, default=10.0,
                    help="relative regression tolerance for headline / "
                    "phase values (default 10%%)")
    ap.add_argument("--efficiency-drop", type=float, default=0.05,
                    help="max tolerated absolute drop in "
                    "overlap_efficiency (default 0.05)")
    ap.add_argument("--util-drop-pct", type=float, default=2.0,
                    help="max tolerated absolute drop in utilization "
                    "percentage points (default 2.0)")
    ap.add_argument("--ll-drop", type=float, default=0.25,
                    help="max tolerated absolute drop in the streaming "
                    "phase's held-out per-token log-likelihood, in "
                    "nats (default 0.25)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the comparison rows as JSON")
    args = ap.parse_args(argv)
    try:
        old = load_payload(args.old)
        new = load_payload(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    rows = diff_payloads(old, new, args.threshold_pct,
                         args.efficiency_drop, args.util_drop_pct,
                         args.ll_drop)
    if not rows:
        print("bench_diff: no comparable metrics between the two "
              "payloads", file=sys.stderr)
        return 2
    regressions = [r for r in rows if r["regression"]]
    if args.as_json:
        print(json.dumps({"rows": rows,
                          "regressions": len(regressions)}, indent=2))
    else:
        for r in rows:
            delta = (f"{r['delta_pct']:+.2f}%" if "delta_pct" in r
                     else f"{r['delta_abs']:+.4f}")
            flag = "  REGRESSION" if r["regression"] else ""
            print(f"{r['name']:<44} {r['old']:>14} -> {r['new']:>14} "
                  f"({delta}){flag}")
        verdict = (f"{len(regressions)} regression(s)" if regressions
                   else "no regressions")
        print(f"bench_diff: {len(rows)} metrics compared, {verdict}")
    return 1 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
