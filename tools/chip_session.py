"""One-shot capture sequence for a healthy TPU grant (round-4 runbook).

The device tunnel has died mid-session in rounds 2, 3, and 4 — when a
grant comes back there may be minutes, not hours.  This script runs the
whole evidence sequence in priority order, with each step's stdout
written STRAIGHT to the capture log as it is produced (no pipe
buffering, no post-hoc copy), so a mid-sequence wedge — or a step that
exits nonzero, like bench's structured-failure rc=1 — keeps every line
already measured:

    python tools/chip_session.py [--out docs/bench_captures/rNN_session_capture.json]

Sequence (each step its own subprocess; a wedge costs one step):
  1. tools/tpu_smoke.py        — shard_map+Pallas Mosaic sanity (fast)
  2. bench.py                  — the full phase record; its last JSON
                                 line (success OR the structured
                                 failure record) is saved as the
                                 session capture.  Runs BEFORE the
                                 probes since r05: the grant died
                                 mid-probes and the round lost the
                                 whole phase record.
  3. tools/tpu_probes.py       — cap_sweep / alpha_ab / fastpath_ab /
                                 chunk_sweep / batch_amort (the
                                 decomposition that says where the
                                 next factor comes from)

Per-step timing: each step gets BOOT_GRACE_S to produce its FIRST
output byte (a python child in this image takes ~5 s just to boot —
sitecustomize imports jax — which used to eat short budgets before the
step's first print; round-4 flaky-test finding), then its own timeout
counts.  The bench step's outer timeout is derived from bench.py's own
worst-case watchdog budget plus margin — the inner watchdog must lose
to nothing, so its best-known record or structured failure line is
always emitted and captured, even when an operator raises BENCH_GATE_S
(round-4 advisor finding: the old hard-coded 16000 s silently inverted
that ordering).  Timeouts SIGTERM with a grace window (never SIGKILL
first — round-3 post-mortem: a SIGKILL mid-claim likely killed the
relay).

Exit-code contract (what tools/grant_watcher.py keys its re-arm
policy on — VERDICT r4 item 9):
  0  every step green: the capture is complete; the watcher's mission
     is over and it should STOP.
  1  every step ran to COMPLETION but at least one exited nonzero
     (e.g. bench emitted its structured failure record, or a probe
     script failed): the log and capture file still hold everything
     produced.  The chip answered the trigger probe but did not
     survive the full sequence — the watcher should RE-ARM at its
     normal cadence, bounded by its capture budget.
  2  at least one step WEDGED (hit its timeout and was TERMed): the
     grant likely died mid-step.  The watcher should RE-ARM with a
     LONGER back-off — rapid retries have been observed to re-wedge a
     recovering grant.
"""

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

# Time allowed for a step's interpreter to boot and produce its first
# output byte before the step's own timeout clock starts.
BOOT_GRACE_S = 60.0
# Margin on top of bench's self-computed worst case for subprocess
# spawn/teardown and the salvage grace window.
BENCH_TIMEOUT_MARGIN_S = 1800.0


def _bench_timeout_s() -> float:
    """Outer timeout for the bench step, derived from bench's own
    worst-case watchdog budget (and BENCH_BUDGET_S, when an operator
    pins one) so the outer clock always loses to the inner watchdog."""
    import bench

    budget = float(os.environ.get("BENCH_BUDGET_S",
                                  bench.worst_case_budget_s()))
    return budget + BENCH_TIMEOUT_MARGIN_S


# bench runs SECOND, right after the fast sanity check: it is the
# highest-value artifact and the grant has died mid-session in every
# round so far — r05 lost the whole phase record because the grant
# expired during the probe step that used to run before it.  The
# probes are decomposition detail and take the tail position.
STEPS = [
    ("tpu_smoke", [sys.executable, os.path.join(HERE, "tools", "tpu_smoke.py")], 600),
    ("bench", [sys.executable, os.path.join(HERE, "bench.py")], _bench_timeout_s()),
    ("tpu_probes", [sys.executable, os.path.join(HERE, "tools", "tpu_probes.py")], 2400),
]


def run_step(name, cmd, timeout, logf, boot_grace=BOOT_GRACE_S):
    """Run one step with stdout+stderr appended to `logf` AS PRODUCED.
    `timeout` counts from the step's first output byte (or from
    boot-grace expiry, whichever comes first), so interpreter boot
    under load cannot eat a short budget.  Returns (lines, rc, wall):
    lines is whatever the step wrote to the stdout-tail of the log —
    present even on nonzero rc or timeout (partial probe output and
    bench's structured failure record must survive; round-4 review
    finding).  rc is None when the step wedged (timed out)."""
    print(f"chip_session: === {name} (timeout {timeout:.0f}s) ===", flush=True)
    logf.write(f"--- {name} @ {time.strftime('%F %T')} ---\n")
    logf.flush()
    start_pos = logf.tell()
    t0 = time.monotonic()
    proc = subprocess.Popen(cmd, stdout=logf, stderr=logf, text=True,
                            cwd=HERE)
    rc = None
    # Boot phase: wait for the first output byte (the child writes to
    # the log fd directly, so file growth == first output) or for the
    # grace to expire, whichever is first.
    boot_deadline = t0 + boot_grace
    while proc.poll() is None and time.monotonic() < boot_deadline:
        if os.path.getsize(logf.name) > start_pos:
            break
        time.sleep(0.05)
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        print(f"chip_session: {name} TIMED OUT after {timeout:.0f}s",
              flush=True)
    wall = time.monotonic() - t0
    logf.flush()
    with open(logf.name) as f:
        f.seek(start_pos)
        lines = f.read().strip().splitlines()
    status = f"rc={rc}" if rc is not None else "timeout"
    print(f"chip_session: {name} {status} ({wall:.0f}s, "
          f"{len(lines)} lines)", flush=True)
    return lines, rc, wall


def main() -> int:
    # Default capture name derives from the round in progress (one past
    # the newest BENCH_r*.json) — a hard-coded rNN literal would make a
    # next-round manual run silently overwrite THIS round's capture.
    import grant_watcher

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--out",
        default=os.path.join(
            HERE, "docs", "bench_captures",
            f"{grant_watcher.current_round_tag()}_session_capture.json"),
    )
    args = ap.parse_args()
    log_path = args.out + ".log"
    green = 0
    wedged = 0
    with open(log_path, "a+") as logf:
        logf.write(f"\n=== chip_session {time.strftime('%F %T')} ===\n")
        for name, cmd, timeout in STEPS:
            lines, rc, wall = run_step(name, cmd, timeout, logf)
            logf.write(f"[{name}] wall={wall:.0f}s rc={rc}\n")
            logf.flush()
            green += rc == 0
            wedged += rc is None
            if name == "bench":
                # The LAST parseable JSON line is the record — a
                # success payload or the structured failure line
                # (value=null + last_good); both are worth keeping.
                for line in reversed(lines):
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "metric" in rec:
                        with open(args.out, "w") as f:
                            json.dump(rec, f, indent=1)
                        print(
                            f"chip_session: bench record -> {args.out}",
                            flush=True,
                        )
                        break
    print(f"chip_session: {green}/{len(STEPS)} steps green "
          f"({wedged} wedged); log: {log_path}", flush=True)
    if green == len(STEPS):
        return 0
    return 2 if wedged else 1


if __name__ == "__main__":
    sys.exit(main())
