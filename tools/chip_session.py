"""One-shot capture sequence for a healthy TPU grant (round-4 runbook).

The device tunnel has died mid-session in rounds 2, 3, and 4 — when a
grant comes back there may be minutes, not hours.  This script runs the
whole evidence sequence in priority order, with each step's stdout
written STRAIGHT to the capture log as it is produced (no pipe
buffering, no post-hoc copy), so a mid-sequence wedge — or a step that
exits nonzero, like bench's structured-failure rc=1 — keeps every line
already measured:

    python tools/chip_session.py [--out docs/bench_captures/rNN_session_capture.json]

Sequence (each step its own subprocess; a wedge costs one step):
  1. tools/tpu_smoke.py        — shard_map+Pallas Mosaic sanity (fast)
  2. tools/tpu_probes.py       — cap_sweep / alpha_ab / fastpath_ab /
                                 chunk_sweep (the decomposition that
                                 says where the next factor comes from)
  3. bench.py                  — the full phase record; its last JSON
                                 line (success OR the structured
                                 failure record) is saved as the
                                 session capture

The bench step's outer timeout (16000 s) deliberately exceeds
bench.py's own worst-case watchdog budget (~14,200 s with every device
phase wedging) — the inner watchdog must lose to nothing, so its
best-known record or structured failure line is always emitted and
captured.  Timeouts SIGTERM with a grace window (never SIGKILL first —
round-3 post-mortem: a SIGKILL mid-claim likely killed the relay).
"""

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STEPS = [
    ("tpu_smoke", [sys.executable, os.path.join(HERE, "tools", "tpu_smoke.py")], 600),
    ("tpu_probes", [sys.executable, os.path.join(HERE, "tools", "tpu_probes.py")], 2400),
    ("bench", [sys.executable, os.path.join(HERE, "bench.py")], 16000),
]


def run_step(name, cmd, timeout, logf):
    """Run one step with stdout+stderr appended to `logf` AS PRODUCED.
    Returns (lines, rc, wall): lines is whatever the step wrote to
    stdout-tail of the log — present even on nonzero rc or timeout
    (partial probe output and bench's structured failure record must
    survive; round-4 review finding)."""
    print(f"chip_session: === {name} (timeout {timeout}s) ===", flush=True)
    logf.write(f"--- {name} @ {time.strftime('%F %T')} ---\n")
    logf.flush()
    start_pos = logf.tell()
    t0 = time.time()
    proc = subprocess.Popen(cmd, stdout=logf, stderr=logf, text=True,
                            cwd=HERE)
    rc = None
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        print(f"chip_session: {name} TIMED OUT after {timeout}s", flush=True)
    wall = time.time() - t0
    logf.flush()
    with open(logf.name) as f:
        f.seek(start_pos)
        lines = f.read().strip().splitlines()
    status = f"rc={rc}" if rc is not None else "timeout"
    print(f"chip_session: {name} {status} ({wall:.0f}s, "
          f"{len(lines)} lines)", flush=True)
    return lines, rc, wall


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--out",
        default=os.path.join(HERE, "docs", "bench_captures",
                             "r04_session_capture.json"),
    )
    args = ap.parse_args()
    log_path = args.out + ".log"
    green = 0
    with open(log_path, "a+") as logf:
        logf.write(f"\n=== chip_session {time.strftime('%F %T')} ===\n")
        for name, cmd, timeout in STEPS:
            lines, rc, wall = run_step(name, cmd, timeout, logf)
            logf.write(f"[{name}] wall={wall:.0f}s rc={rc}\n")
            logf.flush()
            green += rc == 0
            if name == "bench":
                # The LAST parseable JSON line is the record — a
                # success payload or the structured failure line
                # (value=null + last_good); both are worth keeping.
                for line in reversed(lines):
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "metric" in rec:
                        with open(args.out, "w") as f:
                            json.dump(rec, f, indent=1)
                        print(
                            f"chip_session: bench record -> {args.out}",
                            flush=True,
                        )
                        break
    print(f"chip_session: {green}/{len(STEPS)} steps green; log: "
          f"{log_path}", flush=True)
    return 0 if green == len(STEPS) else 1


if __name__ == "__main__":
    sys.exit(main())
