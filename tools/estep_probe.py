"""Density × bucket-shape sweep for the sparse E-step engine — the
EM twin of tools/score_probe.py, so the next live grant can tune the
sparse engine's block shapes and the dense-vs-sparse crossover in one
command:

    python tools/estep_probe.py [--k K] [--v V] [--b B]
        [--densities 0.5,1,2,5,10] [--precision bf16] [--reps 2]

Per density the probe synthesizes a corpus whose per-doc live-token
count L makes the densified batch exactly that dense (L = density·V,
padded to the power-of-two bucket the layout pass would pick), then:

1. **Block sweep** — times `sparse_estep.e_step` at every feasible
   power-of-two doc block and records the winner into the plan cache
   (knob `sparse_estep_bb`, shape key b{B}.l{L}.k{K}.{precision}) with
   the full measurement set as provenance, so
   `sparse_estep.pick_block` resolves it as the measured prior on the
   next run (source "plan", zero re-sweeps).
2. **Crossover** — measures dense-vs-sparse at the same shape
   (`sparse_estep.measure_crossover`: one pinned E-step each, densify
   outside the dense timing) and persists the winner under BOTH the
   exact-shape and density-band keys (knob `estep_engine`), exactly
   like the trainer's inline sweep — the dispatch_calibration pattern.

One JSON line per measurement; a final `plan_cache_update` line names
every knob recorded.  Runs on any backend (CPU numbers exercise the
machinery and pin the interpret-mode crossover; the cache is
backend-fingerprint-keyed, so a CPU record can never leak onto a
chip).  `tools/plan_cache.py export` turns a TPU session's records
into committable `plans/seeds/` entries — the shipped v5e seeds for
these knobs were produced this way (see their provenance notes).
"""

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_DENSITIES = (0.5, 1.0, 2.0, 5.0, 10.0)


def _bucket_len(n: int, min_len: int) -> int:
    b = min_len
    while b < n:
        b *= 2
    return b


def sweep_density(k: int, v: int, b: int, density_pct: float,
                  precision: str, reps: int) -> "dict | None":
    """One density point: block sweep + crossover.  Returns the summary
    record (None when no bucket shape is feasible at this density)."""
    import jax
    import jax.numpy as jnp

    from oni_ml_tpu.ops import sparse_estep

    backend = jax.default_backend()
    min_len, _ = sparse_estep.resolve_layout_len(None)
    l_raw = max(1, int(round(density_pct / 100.0 * v)))
    l = _bucket_len(l_raw, min_len)
    rng = np.random.default_rng(11)
    noise = rng.uniform(size=(k, v)) + 1.0 / v
    log_beta = jnp.asarray(
        np.log(noise / noise.sum(-1, keepdims=True)), jnp.float32
    )
    word_idx = jnp.asarray(rng.integers(0, v, size=(b, l)), jnp.int32)
    counts = jnp.asarray(rng.integers(1, 5, size=(b, l)).astype(np.float32))
    mask = jnp.ones((b,), jnp.float32)
    alpha = jnp.float32(2.5)
    interp = backend != "tpu"
    vi = 8                       # pinned trip count (crossover convention)

    # -- block sweep ------------------------------------------------------
    sub = 16 if precision == "bf16" else 8
    candidates = []
    bb = sub
    while bb <= min(b, sparse_estep._MAX_BLOCK_DOCS) and b % bb == 0:
        if sparse_estep._vmem_estimate(
            bb, l, k, precision
        ) <= sparse_estep._VMEM_CEILING:
            candidates.append(bb)
        bb *= 2
    if not candidates:
        print(json.dumps({
            "probe": "estep_block_sweep", "backend": backend,
            "density_pct": density_pct, "b": b, "l": l, "k": k,
            "skipped": "no VMEM-feasible doc block",
        }), flush=True)
        return None

    from oni_ml_tpu import plans

    measurements = {}
    for cand in candidates:
        fn = jax.jit(functools.partial(
            sparse_estep.e_step, var_max_iters=vi, var_tol=0.0,
            interpret=interp, precision=precision, block=cand,
        ))
        float(np.asarray(                      # compile + warm
            fn(log_beta, alpha, word_idx, counts, mask).likelihood
        ))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            res = fn(log_beta, alpha, word_idx, counts, mask)
            float(np.asarray(res.likelihood))  # sync
            best = min(best, time.perf_counter() - t0)
        measurements[cand] = round(b / best)
        print(json.dumps({
            "probe": "estep_block_sweep", "backend": backend,
            "density_pct": density_pct, "b": b, "l": l, "k": k,
            "precision": precision, "block": cand,
            "docs_per_sec": round(b / best), "t_estep_ms":
            round(best * 1e3, 3),
        }), flush=True)
    best_bb = max(measurements, key=measurements.get)
    shape = f"b{b}.l{l}.k{k}.{precision}"
    plans.note_sweep("sparse_estep_bb")
    recorded_bb = plans.record_value(
        "sparse_estep_bb", int(best_bb), shape=shape, source="probe",
        measurements={str(c): m for c, m in measurements.items()},
        unit="docs/sec", density_pct=density_pct,
    )

    # -- dense-vs-sparse crossover ---------------------------------------
    cross = sparse_estep.engine_crossover(
        k, v, b, l, precision=precision, force=True
    )
    print(json.dumps({
        "probe": "estep_crossover", "backend": backend,
        "density_pct": density_pct, **cross,
    }), flush=True)
    return {
        "density_pct": density_pct, "b": b, "l": l, "shape": shape,
        "sparse_estep_bb": int(best_bb), "recorded": recorded_bb,
        "engine": cross["engine"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Sparse E-step density x bucket-shape sweep; "
        "records sparse_estep_bb winners and the dense/sparse "
        "crossover into the plan cache."
    )
    ap.add_argument("--k", type=int, default=20)
    ap.add_argument("--v", type=int, default=8192)
    ap.add_argument("--b", type=int, default=1024)
    ap.add_argument("--densities", default=None,
                    help="comma list of corpus densities in percent "
                    "(default 0.5,1,2,5,10)")
    ap.add_argument("--precision", default="bf16",
                    choices=("f32", "bf16"))
    ap.add_argument("--reps", type=int, default=2)
    args = ap.parse_args(argv)
    densities = (
        tuple(float(d) for d in args.densities.split(","))
        if args.densities else DEFAULT_DENSITIES
    )
    from oni_ml_tpu import plans

    summaries = []
    for d in densities:
        s = sweep_density(args.k, args.v, args.b, d, args.precision,
                          args.reps)
        if s is not None:
            summaries.append(s)
    print(json.dumps({
        "probe": "plan_cache_update",
        "store": plans.default_path(),
        "backend": plans.device_fingerprint(),
        "knobs_recorded": ["sparse_estep_bb", "estep_engine"],
        "points": summaries,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
