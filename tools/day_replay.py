"""Pace a historical day's raw CSV into the continuous-mode ingest.

The streaming_freshness bench (and the continuous-mode tests) need
realistic arrival patterns, not a file handed over at once: this tool
replays a completed day — the exact CSV the batch pipeline would have
eaten tomorrow — as the event stream it originally was, at ×N
real-time speed, through `ml_ops continuous`'s service loop
(oni_ml_tpu/runner/continuous.py).

Slicing is event-time-ordered (`slice_events`, through the source
registry's `event_time_s` hook — flow rows by their hour/minute/second
columns, DNS rows by unix_tstamp, proxy rows by p_time) and pacing is the
load generator's open-loop discipline (tools/load_gen.py): each
slice's delivery wall-time is its event-time offset divided by the
speed factor, and a delivery that falls behind schedule is not dropped
— the backlog shows up as freshness latency, exactly like a real
overloaded ingest.  `--jitter` additionally spreads each slice's
delivery inside its span with a Poisson draw from
`load_gen.arrival_offsets`, so burst-shaped arrivals can be replayed
without editing the day file.

Usage:

    python tools/day_replay.py DAY.csv --dsource flow --speed 720 \
        --slice-s 300 --out-dir /tmp/continuous [--fresh-control]

At --speed 720 a 24-hour day replays in two wall minutes; the payload
(last stdout line) is the same summary `ml_ops continuous` prints:
freshness quantiles, warm-vs-fresh walls, publish/veto counts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

from oni_ml_tpu.runner.continuous import (  # noqa: E402
    paced_slices,
    run_continuous,
    slice_events,
)
from oni_ml_tpu.sources import names as source_names  # noqa: E402


def replay_slices(path: str, dsource: str, *, slice_s: float,
                  speed: float, limit: "int | None" = None,
                  jitter_seed: "int | None" = None,
                  sleep=time.sleep):
    """Slice a day CSV and yield paced IngestSlices at ×`speed`.

    `limit` caps the event count (bench/test budgets); `jitter_seed`
    turns on within-span Poisson delivery jitter via load_gen's
    arrival_offsets (None = deliver each slice at its span end)."""
    with open(path) as f:
        lines = f.readlines()
    if limit is not None:
        lines = lines[:limit]
    slices = slice_events(lines, dsource, slice_s)
    if jitter_seed is not None:
        import load_gen

        rng = np.random.default_rng(jitter_seed)
        for sl in slices:
            # One Poisson inter-arrival draw per slice: shift the
            # slice's effective delivery point inside its span so
            # replayed arrivals are not metronome-regular.
            off = load_gen.arrival_offsets(
                "poisson", 1, 1.0 / max(slice_s, 1e-9),
                seed=int(rng.integers(0, 2**31)),
            )[0]
            sl.t1 = min(sl.t1, sl.t0 + max(off, 1e-3)) \
                if off < slice_s else sl.t1
    return paced_slices(slices, speed, sleep=sleep)


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        description="Replay a historical day CSV into continuous-mode "
        "ingest at ×N real-time speed."
    )
    ap.add_argument("day_csv",
                    help="raw CSV of one day (any registered source)")
    ap.add_argument("--dsource", choices=list(source_names()),
                    default="flow")
    ap.add_argument("--speed", type=float, default=60.0,
                    help="replay speed multiplier (60 = 1 event-hour "
                    "per wall-minute); inf with --no-sleep")
    ap.add_argument("--slice-s", type=float, default=300.0,
                    help="ingest slice span in EVENT seconds")
    ap.add_argument("--limit", type=int, default=None,
                    help="cap replayed events (bench/test budgets)")
    ap.add_argument("--out-dir", default=None,
                    help="service output dir (default: "
                    "<day_csv dir>/continuous)")
    ap.add_argument("--window-s", type=float, default=None)
    ap.add_argument("--refresh-s", type=float, default=None)
    ap.add_argument("--jitter-seed", type=int, default=None,
                    help="Poisson within-slice delivery jitter "
                    "(load_gen arrival_offsets)")
    ap.add_argument("--fresh-control", action="store_true",
                    help="measure one fresh fit against a warm "
                    "refresh's snapshot (warm_start_speedup)")
    ap.add_argument("--no-sleep", action="store_true",
                    help="deliver as fast as consumed (tests/CI)")
    ap.add_argument("--tenant", default="stream")
    args = ap.parse_args(argv)
    if not os.path.exists(args.day_csv):
        print(f"day_replay: no such file {args.day_csv}",
              file=sys.stderr)
        return 2
    import dataclasses

    from oni_ml_tpu.config import PipelineConfig

    config = PipelineConfig(
        data_dir=os.path.dirname(os.path.abspath(args.day_csv)) or "."
    )
    overrides = {}
    if args.window_s is not None:
        overrides["window_s"] = args.window_s
    if args.refresh_s is not None:
        overrides["refresh_every_s"] = args.refresh_s
    if overrides:
        config = config.replace(continuous=dataclasses.replace(
            config.continuous, **overrides))
    out_dir = args.out_dir or os.path.join(config.data_dir, "continuous")
    speed = float("inf") if args.no_sleep else args.speed
    payload = run_continuous(
        config, args.dsource,
        replay_slices(args.day_csv, args.dsource,
                      slice_s=args.slice_s, speed=speed,
                      limit=args.limit, jitter_seed=args.jitter_seed),
        out_dir=out_dir, tenant=args.tenant,
        fresh_control=args.fresh_control,
    )
    print(json.dumps(payload), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
