"""On-chip profiling probes for the fused-EM iteration — run these the
next time a healthy TPU grant is attached (they were authored in round 3
while the session's device tunnel was down, so the numbers they produce
are the first thing round 4 should capture).

    python tools/tpu_probes.py [cap_sweep] [alpha_ab] [fastpath_ab]
                               [chunk_sweep]

(no args = all four).  Each probe prints one JSON line per
measurement.  What they answer:

cap_sweep — fixed-cost decomposition of one EM iteration.  docs/s at
  forced var_max_iters caps, warm start OFF so the cap is the actual
  trip count; regressing t_iter on the cap gives slope = per-VI-
  iteration cost and intercept = the fixed per-EM-iteration cost (XLA
  glue + corpus streaming + tail pass).  Round 3's driver-parity bench
  measured 3.13 ms/iter at mean_vi 5.37 against a ~0.9 ms historical
  glue estimate — the intercept says where the next headline factor
  must come from.

alpha_ab — attribute the alpha-Newton update's cost.  estimate_alpha
  runs an up-to-100-trip SCALAR Newton while_loop (digamma/trigamma
  per trip) inside every EM iteration — the TPU's worst-case shape.
  If the A/B shows it material, the candidate fix is a fixed-depth
  fori_loop(8) from the warm previous alpha (quadratic convergence
  makes 8 plenty mid-run), which also removes a dynamic trip count.

fastpath_ab — the round-4 exp-space single-dense-group fast path
  (fused.run_chunk_impl_fast) vs the generic chunk impl: how much of
  the fixed glue the in-loop exp/log/transpose elimination actually
  buys on chip.

chunk_sweep — host-dispatch amortization.  Round-2 data said 8->32
  chunk doubled throughput and 32->64 was flat; re-check at the
  current (much faster) iteration time, where the same absolute
  dispatch overhead is a LARGER fraction of each iteration.

batch_amort — day-scale glue amortization ON CHIP: per-EM-iteration
  wall and docs/s vs resident batch count (1/2/4 stacked B=4096
  batches through the production chunk runner's scan; capped at 4
  since r05, where the grant died in the long n=8 setup window).  The CPU-mesh
  twin (tools/glue_amortization.py; table in docs/architecture.md)
  shows the structural split 14.0 ms fixed + 10.6 ms/batch; this
  cashes the absolute single-chip numbers the 2.6x-ceiling paragraph
  and the "multi-chip pays at day scale" claim rest on.  Note the
  round-4 exp-space fast path only engages at n_batches=1 — the
  stacked runs measure the generic impl, so comparing n=1 against
  n>1 also bounds what the fast path would buy at day scale.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K, V, B, L = 20, 8192, 4096, 128          # headline shape (config 1)


def cap_sweep():
    import bench

    for cap in (1, 3, 6, 12, 20):
        em = bench.bench_em(K, V, B, L, chunk=32, rounds=3, var_max_iters=cap,
                            warm_start=False, precision="bf16")
        print(json.dumps({
            "probe": "cap_sweep", "cap": cap,
            "t_iter_ms": round(em["t_iter"] * 1e3, 3),
            "mean_vi": round(em["mean_vi"], 2),
            "docs_per_sec": round(em["docs_per_sec"]),
        }), flush=True)


def alpha_ab():
    # Three-way since r05: newton100 forces the dynamic while_loop
    # lowering (the pre-r05 production shape), newton8 is the capped
    # UNROLLED lowering bench now uses (r05 charged ~0.5 ms/EM-iter to
    # the estimate at chunk=32 — this row says how much the unroll
    # recovers), fixed is the no-estimate floor.
    import bench
    from oni_ml_tpu.models import fused

    orig = fused.make_chunk_runner

    def newton100(**kw):
        kw["alpha_max_iters"] = 100
        return orig(**kw)

    def newton8(**kw):
        # Pinned here, not inherited from bench's current tuning, so
        # the emitted label stays true if bench's cap ever moves.
        kw["alpha_max_iters"] = 8
        return orig(**kw)

    def no_alpha(**kw):
        kw["estimate_alpha"] = False
        return orig(**kw)

    try:
        for label, maker in (("newton100", newton100),
                             ("newton8", newton8),
                             ("fixed", no_alpha)):
            fused.make_chunk_runner = maker
            em = bench.bench_em(K, V, B, L, chunk=32, rounds=3,
                                warm_start=True,
                                precision="bf16")
            print(json.dumps({
                "probe": "alpha_ab", "alpha": label,
                "t_iter_ms": round(em["t_iter"] * 1e3, 3),
                "docs_per_sec": round(em["docs_per_sec"]),
            }), flush=True)
    finally:
        fused.make_chunk_runner = orig


def fastpath_ab():
    """Exp-space single-dense-group fast path (round-4
    fused.run_chunk_impl_fast) vs the generic chunk impl: measures the
    per-EM-iteration glue the fast path removes (the exp(log_beta)
    pass, m_step's log, two [V, K] transposes, EStepResult assembly).
    The generic impl is summoned by wrapping m_step so the fast path's
    `is` eligibility check cannot recognize it."""
    import bench
    from oni_ml_tpu.models import fused
    from oni_ml_tpu.ops import estep

    orig = fused.make_chunk_runner

    def stock(**kw):
        if kw.get("m_step_fn") in (None, estep.m_step):
            kw["m_step_fn"] = lambda ss: estep.m_step(ss)
        return orig(**kw)

    try:
        for label, maker in (("fast", orig), ("stock", stock)):
            fused.make_chunk_runner = maker
            em = bench.bench_em(K, V, B, L, chunk=32, rounds=3,
                                warm_start=True,
                                precision="bf16")
            print(json.dumps({
                "probe": "fastpath_ab", "path": label,
                "t_iter_ms": round(em["t_iter"] * 1e3, 3),
                "mean_vi": round(em["mean_vi"], 2),
                "docs_per_sec": round(em["docs_per_sec"]),
            }), flush=True)
    finally:
        fused.make_chunk_runner = orig


def chunk_sweep():
    import bench

    # 16 measured 821k in r05 (known-bad, dropped to save grant time);
    # the r05 curve was still improving at 128 (2.898M).  Least squares
    # over the four r05 points gives t_iter ~= 0.94 ms device floor +
    # ~65 ms per-dispatch glue / chunk, so the open question is where
    # 256/512 (predicted ~1.19 / ~1.07 ms) flatten onto that floor.
    measurements = {}
    for chunk in (32, 64, 128, 256, 512):
        em = bench.bench_em(K, V, B, L, chunk=chunk, rounds=3,
                            warm_start=True, precision="bf16")
        measurements[chunk] = round(em["docs_per_sec"])
        print(json.dumps({
            "probe": "chunk_sweep", "chunk": chunk,
            "t_iter_ms": round(em["t_iter"] * 1e3, 3),
            "docs_per_sec": round(em["docs_per_sec"]),
        }), flush=True)
    # Persist the winner as a measured plan (oni_ml_tpu/plans): the
    # exact capture→cache→seed workflow that turned the r05 sweep into
    # plans/seeds/v5e.jsonl, now automatic — the next run on this
    # backend trains at the measured chunk, and `tools/plan_cache.py
    # export` emits the committable seed.
    from oni_ml_tpu import plans

    best = max(measurements, key=measurements.get)
    plans.note_sweep("fused_em_chunk")
    recorded = plans.record_value(
        "fused_em_chunk", int(best), shape=f"k{K}.v{V}.b{B}.l{L}",
        source="probe", measurements=measurements, unit="docs/sec",
    )
    # Both records always attempted (no short-circuit): a failed
    # exact-shape write must not silently skip the wildcard one.
    recorded_wild = plans.record_value(
        "fused_em_chunk", int(best), shape="*",
        source="probe", measurements=measurements, unit="docs/sec",
        note="wildcard projection: the amortized term is per-dispatch "
             "glue, shape-independent on this backend",
    )
    print(json.dumps({
        "probe": "plan_cache_update",
        # False: plans disabled / cache unwritable for that write.
        "recorded": recorded,
        "recorded_wildcard": recorded_wild,
        "store": plans.default_path(),
        "backend": plans.device_fingerprint(),
        "fused_em_chunk": int(best),
    }), flush=True)


def batch_amort():
    import bench

    # Capped at 4: n=1/2/4 (r05: 1.354M / 2.134M / 3.193M docs/s)
    # already demonstrate the fixed-glue amortization curve, and the
    # r05 grant died in the long n=8 setup window before bench ever
    # ran — the marginal data point is not worth holding the grant.
    for nb in (1, 2, 4):
        em = bench.bench_em(K, V, B, L, chunk=32, rounds=3,
                            warm_start=True, precision="bf16",
                            n_batches=nb)
        print(json.dumps({
            "probe": "batch_amort", "n_batches": nb,
            "t_iter_ms": round(em["t_iter"] * 1e3, 3),
            "t_iter_per_batch_ms": round(em["t_iter"] * 1e3 / nb, 3),
            "docs_per_sec": round(em["docs_per_sec"]),
        }), flush=True)


def main() -> int:
    import jax

    if jax.default_backend() != "tpu":
        print("tpu_probes: backend is not TPU — these probes measure "
              "device behavior; run on the chip host", file=sys.stderr)
        return 2
    which = sys.argv[1:] or ["cap_sweep", "alpha_ab", "fastpath_ab",
                             "chunk_sweep", "batch_amort"]
    for name in which:
        fn = globals().get(name)
        if fn is None:
            print(f"tpu_probes: unknown probe {name!r}", file=sys.stderr)
            return 2
        fn()
    return 0


if __name__ == "__main__":
    sys.exit(main())
