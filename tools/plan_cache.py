"""Plan-cache operations CLI — show/clear/export for the measured
execution plans (oni_ml_tpu/plans):

    python tools/plan_cache.py show [--knob NAME] [--all-backends]
    python tools/plan_cache.py clear
    python tools/plan_cache.py export [DEST]

`show` prints the resolved view: one JSON line per entry (latest per
(knob, backend, shape), seeds included), plus a header naming the live
store path and this process's fingerprints so "why didn't my entry
match" is answerable at a glance.  By default only entries matching
THIS host/backend print; `--all-backends` shows everything, including
seed plans for hardware you are not on.

`clear` removes the LIVE cache file only — checked-in seed plans are
code, not cache, and survive.

`export` writes the current resolved entries as a standalone JSONL
stream (stdout, or DEST) in exactly the seed-file format, so a live
grant session's captured measurements can be committed under
`oni_ml_tpu/plans/seeds/` — the workflow that turned the r05 chunk
sweep into the shipped v5e seed.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _store():
    from oni_ml_tpu import plans

    return plans.default_store()


def cmd_show(args) -> int:
    from oni_ml_tpu import plans

    store = _store()
    fps = {plans.host_fingerprint()}
    header = {
        "store": store.path,
        "schema": plans.SCHEMA_VERSION,
        "host": plans.host_fingerprint(),
        "seeds": plans.seed_paths(),
        "dropped_records": store.dropped_records,
    }
    if not args.no_device:
        header["backend"] = plans.device_fingerprint()
        fps.add(header["backend"])
    print(json.dumps(header), flush=True)
    for e in sorted(store.entries(), key=lambda e: e.key):
        if args.knob and e.knob != args.knob:
            continue
        if not args.all_backends and e.backend not in fps:
            continue
        print(json.dumps({
            "knob": e.knob, "backend": e.backend, "shape": e.shape,
            "value": e.value, "source": e.source,
            **({"measurements": e.measurements} if e.measurements else {}),
        }), flush=True)
    return 0


def cmd_clear(args) -> int:
    store = _store()
    existed = os.path.exists(store.path)
    store.clear()
    print(json.dumps({
        "cleared": store.path, "existed": existed,
        "note": "seed plans under oni_ml_tpu/plans/seeds/ are code and "
                "were not touched",
    }), flush=True)
    return 0


def cmd_export(args) -> int:
    from oni_ml_tpu.plans.store import SCHEMA_VERSION

    store = _store()
    out = open(args.dest, "w") if args.dest else sys.stdout
    try:
        for e in sorted(store.entries(), key=lambda e: e.key):
            if args.knob and e.knob != args.knob:
                continue
            rec = {k: v for k, v in e.record.items()
                   if k not in ("seq", "t", "mono_ns")}
            rec["schema"] = SCHEMA_VERSION
            out.write(json.dumps(rec) + "\n")
    finally:
        if args.dest:
            out.close()
            print(json.dumps({"exported": args.dest}), flush=True)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="plan_cache",
        description="show/clear/export the measured-plan cache "
        "(oni_ml_tpu/plans)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    show = sub.add_parser("show", help="print resolved entries")
    show.add_argument("--knob", default=None)
    show.add_argument("--all-backends", action="store_true",
                      help="include entries for other fingerprints "
                      "(e.g. seed plans for hardware you are not on)")
    show.add_argument("--no-device", action="store_true",
                      help="skip the device fingerprint (does not "
                      "initialize a jax backend; host-scoped view only)")
    sub.add_parser("clear", help="remove the live cache file")
    exp = sub.add_parser("export",
                         help="write entries as a seed-able JSONL stream")
    exp.add_argument("dest", nargs="?", default=None)
    exp.add_argument("--knob", default=None)
    args = p.parse_args(argv)
    return {"show": cmd_show, "clear": cmd_clear,
            "export": cmd_export}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
