"""Pre-stage worker-count sweep — the featurization twin of
tools/score_probe.py, so a new host can size `pre_workers` in one
command:

    python tools/pre_probe.py [n_events] [workers [workers ...]]

(defaults: a synthetic 2M-event flow day AND a 1M-event DNS day, swept
over workers 1/2/4/8/auto).  Each measurement prints one JSON line:
events/sec through the full featurize path (parse + cuts + word build
+ word-count aggregation), the per-pass wall breakdown, and the
deterministic-merge overhead (`merge_s` — the sequential term the shard
fan-out pays; native_src/common.h shard_bounds design).  Output parity
across worker counts is pinned by tests/test_pre_parallel.py, so the
sweep is free to chase throughput only.

Reading the result: the flat point is where shard parallelism is
amortized against the merge — on a W-core host that is usually
workers=W (= `pre_workers=0` auto); past it, extra shards only grow
merge work.  A host where workers=1 wins (single-core containers,
heavily contended VMs) should pin `pre_workers=1` in config — that is
the exact legacy single-pass path, zero new code in the loop."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_WORKERS = (1, 2, 4, 8, 0)   # 0 = auto (host cores)


def _day_files(tmp, n_events):
    from bench import _write_dns_day, _write_flow_day

    flow = os.path.join(tmp, "flow_day.csv")
    with open(flow, "w") as f:
        _write_flow_day(f, n_events)
    dns = os.path.join(tmp, "dns_day.csv")
    with open(dns, "w") as f:
        _write_dns_day(f, max(n_events // 2, 1))
    return flow, dns


def sweep(n_events: int, workers, reps: int = 2) -> None:
    import tempfile

    from oni_ml_tpu.features import native_dns, native_flow

    native = native_flow.available()
    tmp = tempfile.mkdtemp(prefix="oni_pre_probe_")
    # Deduplicate by RESOLVED count before measuring: w=0 resolves to
    # the host core count — the same value as an explicit leg on most
    # sweep lists — and (being a sweep, not a consumer) it must resolve
    # from cpu_count, NOT from the plan cache: a plan-resolved auto leg
    # would re-measure the previous winner under a second key, double
    # its aggregate, and make the first recorded winner
    # self-reinforcing.
    auto = max(1, os.cpu_count() or 1)
    legs = []
    for w in workers:
        resolved = w if w else auto
        if all(r != resolved for _, r in legs):
            legs.append((w, resolved))
    # events/sec per resolved worker count, summed across the flow and
    # dns days — the aggregate that picks this host's plan entry.
    aggregate: dict = {}
    try:
        flow_path, dns_path = _day_files(tmp, n_events)
        days = [
            ("flow", n_events,
             lambda w, t: native_flow.featurize_flow_file(
                 flow_path, workers=w, timings=t)),
            ("dns", max(n_events // 2, 1),
             lambda w, t: native_dns.featurize_dns_sources(
                 [dns_path], workers=w, timings=t)),
        ]
        for dsource, n, fn in days:
            for w, resolved in legs:
                best, best_t = float("inf"), {}
                for _ in range(reps):
                    timings: dict = {}
                    t0 = time.perf_counter()
                    # Always pass the EXPLICIT count: w=0 would
                    # plan-resolve inside the featurizer — the sweep
                    # must measure the labeled count, not consume the
                    # cache it exists to fill.
                    feats = fn(resolved, timings)
                    dt = time.perf_counter() - t0
                    if dt < best:
                        best, best_t = dt, timings
                aggregate[resolved] = aggregate.get(resolved, 0) + round(
                    n / best
                )
                print(json.dumps({
                    "probe": "pre_worker_sweep", "dsource": dsource,
                    "native": native, "workers": w,
                    "resolved_workers": resolved, "n_events": n,
                    "events_per_sec": round(n / best),
                    "wall_s": round(best, 3),
                    "merge_s": best_t.get("merge_s", 0.0),
                    "passes": {
                        k: v for k, v in best_t.items() if k != "merge_s"
                    },
                    "word_count_rows": (
                        len(feats.wc_ip) if hasattr(feats, "wc_ip")
                        else len(feats.word_counts())
                    ),
                }), flush=True)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)

    # Seed the plan cache with this host's measured best (oni_ml_tpu/
    # plans, host-scoped knob "pre_workers"): the next run's
    # pre_workers=0 auto resolves to the measured winner instead of
    # cpu_count.  Parity across worker counts is pinned by
    # tests/test_pre_parallel.py, so this is throughput-only.
    from oni_ml_tpu import plans

    best_workers = max(aggregate, key=aggregate.get)
    plans.note_sweep("pre_workers")
    recorded = plans.record_value(
        "pre_workers", int(best_workers), source="probe",
        measurements=aggregate, unit="events/sec (flow+dns sum)",
        n_events=n_events, native=native,
    )
    print(json.dumps({
        "probe": "plan_cache_update",
        "recorded": recorded,        # False: plans disabled/unwritable
        "store": plans.default_path(),
        "host": plans.host_fingerprint(),
        "pre_workers": int(best_workers),
        "aggregate_events_per_sec": aggregate,
    }), flush=True)


def main() -> int:
    args = [int(a) for a in sys.argv[1:]]
    n_events = args[0] if args else 2_000_000
    workers = tuple(args[1:]) or DEFAULT_WORKERS
    sweep(n_events, workers)
    return 0


if __name__ == "__main__":
    sys.exit(main())
