"""Pre-stage worker-count sweep — the featurization twin of
tools/score_probe.py, so a new host can size `pre_workers` in one
command:

    python tools/pre_probe.py [n_events] [workers [workers ...]]

(defaults: a synthetic 2M-event flow day AND a 1M-event DNS day, swept
over workers 1/2/4/8/auto).  Each measurement prints one JSON line:
events/sec through the full featurize path (parse + cuts + word build
+ word-count aggregation), the per-pass wall breakdown, and the
deterministic-merge overhead (`merge_s` — the sequential term the shard
fan-out pays; native_src/common.h shard_bounds design).  Output parity
across worker counts is pinned by tests/test_pre_parallel.py, so the
sweep is free to chase throughput only.

Reading the result: the flat point is where shard parallelism is
amortized against the merge — on a W-core host that is usually
workers=W (= `pre_workers=0` auto); past it, extra shards only grow
merge work.  A host where workers=1 wins (single-core containers,
heavily contended VMs) should pin `pre_workers=1` in config — that is
the exact legacy single-pass path, zero new code in the loop."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_WORKERS = (1, 2, 4, 8, 0)   # 0 = auto (host cores)


def _day_files(tmp, n_events):
    from bench import _write_dns_day, _write_flow_day

    flow = os.path.join(tmp, "flow_day.csv")
    with open(flow, "w") as f:
        _write_flow_day(f, n_events)
    dns = os.path.join(tmp, "dns_day.csv")
    with open(dns, "w") as f:
        _write_dns_day(f, max(n_events // 2, 1))
    return flow, dns


def sweep(n_events: int, workers, reps: int = 2) -> None:
    import tempfile

    from oni_ml_tpu.features import native_dns, native_flow
    from oni_ml_tpu.features.shards import resolve_pre_workers

    native = native_flow.available()
    tmp = tempfile.mkdtemp(prefix="oni_pre_probe_")
    try:
        flow_path, dns_path = _day_files(tmp, n_events)
        days = [
            ("flow", n_events,
             lambda w, t: native_flow.featurize_flow_file(
                 flow_path, workers=w, timings=t)),
            ("dns", max(n_events // 2, 1),
             lambda w, t: native_dns.featurize_dns_sources(
                 [dns_path], workers=w, timings=t)),
        ]
        for dsource, n, fn in days:
            for w in workers:
                resolved = resolve_pre_workers(w)
                best, best_t = float("inf"), {}
                for _ in range(reps):
                    timings: dict = {}
                    t0 = time.perf_counter()
                    feats = fn(w, timings)
                    dt = time.perf_counter() - t0
                    if dt < best:
                        best, best_t = dt, timings
                print(json.dumps({
                    "probe": "pre_worker_sweep", "dsource": dsource,
                    "native": native, "workers": w,
                    "resolved_workers": resolved, "n_events": n,
                    "events_per_sec": round(n / best),
                    "wall_s": round(best, 3),
                    "merge_s": best_t.get("merge_s", 0.0),
                    "passes": {
                        k: v for k, v in best_t.items() if k != "merge_s"
                    },
                    "word_count_rows": (
                        len(feats.wc_ip) if hasattr(feats, "wc_ip")
                        else len(feats.word_counts())
                    ),
                }), flush=True)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    args = [int(a) for a in sys.argv[1:]]
    n_events = args[0] if args else 2_000_000
    workers = tuple(args[1:]) or DEFAULT_WORKERS
    sweep(n_events, workers)
    return 0


if __name__ == "__main__":
    sys.exit(main())
